"""Avro Object Container Files — self-describing export/ingest.

Parity with the reference's AvroDataFile (geomesa-feature-avro/.../
AvroDataFile.scala): features serialize to an Avro record schema derived
from the FeatureType (geometry as WKT string, date as long
``timestamp-millis``, every field nullable), wrapped in the standard Avro
container format (magic ``Obj\\x01``, metadata map with inline JSON schema,
``null`` codec, sync-marker-delimited blocks). Implemented from scratch —
``fastavro`` is not in the environment — and interoperable with any Avro
reader.
"""

from __future__ import annotations

import io
import json
import os
import struct
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

MAGIC = b"Obj\x01"

_AVRO_TYPES = {
    "int": "int", "int32": "int", "integer": "int",
    "long": "long", "int64": "long",
    "float": "float", "float32": "float",
    "double": "double", "float64": "double",
    "bool": "boolean", "boolean": "boolean",
    "string": "string",
}


# ---------------------------------------------------------------------------
# primitive codec
# ---------------------------------------------------------------------------

def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def write_long(buf: io.BytesIO, n: int):
    n = _zigzag(int(n))
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            buf.write(bytes([b | 0x80]))
        else:
            buf.write(bytes([b]))
            return


def read_long(buf) -> int:
    shift = 0
    acc = 0
    while True:
        (b,) = buf.read(1)
        acc |= (b & 0x7F) << shift
        if not (b & 0x80):
            return _unzigzag(acc)
        shift += 7


def write_bytes(buf: io.BytesIO, b: bytes):
    write_long(buf, len(b))
    buf.write(b)


def read_bytes(buf) -> bytes:
    return buf.read(read_long(buf))


def write_string(buf: io.BytesIO, s: str):
    write_bytes(buf, s.encode("utf-8"))


# ---------------------------------------------------------------------------
# schema
# ---------------------------------------------------------------------------

def avro_schema(ft, attrs: Optional[List[str]] = None) -> Dict[str, Any]:
    """FeatureType -> Avro record schema (every field nullable).

    ``attrs`` restricts the schema to a projection's attributes."""
    fields: List[Dict[str, Any]] = [
        {"name": "__fid__", "type": "string"}
    ]
    for a in ft.attributes:
        if attrs is not None and a.name not in attrs:
            continue
        if a.is_geom:
            typ: Any = "string"  # WKT
        elif a.type == "date":
            typ = {"type": "long", "logicalType": "timestamp-millis"}
        else:
            typ = _AVRO_TYPES.get(a.type, "string")
        fields.append({"name": a.name, "type": ["null", typ]})
    return {
        "type": "record",
        "name": ft.name,
        "namespace": "geomesa_tpu",
        "fields": fields,
    }


def _rows(ft, d, names) -> Iterator[Tuple[Any, ...]]:
    """Iterate already-decoded columns ``d`` in schema order over ``names``."""
    geom_names = {a.name for a in ft.attributes if a.is_geom}
    point_names = {
        a.name for a in ft.attributes if a.is_geom and a.is_point
    }
    n = len(d["__fid__"])
    for i in range(n):
        row: List[Any] = [str(d["__fid__"][i])]
        for name in names:
            v = d[name][i]
            if name in point_names and not isinstance(v, str):
                v = f"POINT ({v[0]} {v[1]})"
            elif name in geom_names:
                v = None if v is None else str(v)
            row.append(v)
        yield tuple(row)


def write_avro(path_or_buf, ft, batch, dicts, sync: Optional[bytes] = None):
    """Write a feature batch as an Avro container file. Projected batches
    (missing columns) produce a correspondingly reduced schema."""
    from geomesa_tpu.schema.columns import decode_batch

    d = decode_batch(ft, batch, dicts)
    attrs = [a.name for a in ft.attributes if a.name in d]
    schema = avro_schema(ft, attrs)
    types = [f["type"] for f in schema["fields"]]
    sync = sync or os.urandom(16)
    own = isinstance(path_or_buf, str)
    out = open(path_or_buf, "wb") if own else path_or_buf
    try:
        out.write(MAGIC)
        meta = io.BytesIO()
        write_long(meta, 2)
        write_string(meta, "avro.schema")
        write_bytes(meta, json.dumps(schema).encode())
        write_string(meta, "avro.codec")
        write_bytes(meta, b"null")
        write_long(meta, 0)
        out.write(meta.getvalue())
        out.write(sync)

        block = io.BytesIO()
        n = 0
        for row in _rows(ft, d, attrs):
            _write_row(block, row, types)
            n += 1
        if n:
            head = io.BytesIO()
            write_long(head, n)
            write_bytes(head, block.getvalue())
            out.write(head.getvalue())
            out.write(sync)
    finally:
        if own:
            out.close()


def _write_row(buf: io.BytesIO, row, types):
    for v, t in zip(row, types):
        if isinstance(t, list):  # nullable union
            if v is None or (
                isinstance(v, (float, np.floating)) and np.isnan(v)
            ):
                write_long(buf, 0)
                continue
            write_long(buf, 1)
            t = t[1]
        _write_value(buf, v, t)


def _write_value(buf: io.BytesIO, v, t):
    if isinstance(t, dict):
        t = t["type"]
    if t == "string":
        write_string(buf, str(v))
    elif t in ("int", "long"):
        if isinstance(v, np.datetime64):
            v = v.astype("datetime64[ms]").astype(np.int64)
        write_long(buf, int(v))
    elif t == "float":
        buf.write(struct.pack("<f", float(v)))
    elif t == "double":
        buf.write(struct.pack("<d", float(v)))
    elif t == "boolean":
        buf.write(b"\x01" if v else b"\x00")
    else:
        raise ValueError(f"unsupported avro type {t!r}")


def read_avro(path_or_buf) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
    """Read an Avro container file -> (schema, records). Null codec only."""
    own = isinstance(path_or_buf, str)
    f = open(path_or_buf, "rb") if own else path_or_buf
    try:
        if f.read(4) != MAGIC:
            raise ValueError("not an Avro container file")
        meta: Dict[str, bytes] = {}
        while True:
            cnt = read_long(f)
            if cnt == 0:
                break
            if cnt < 0:  # block-size-prefixed variant
                read_long(f)
                cnt = -cnt
            for _ in range(cnt):
                k = read_bytes(f).decode()
                meta[k] = read_bytes(f)
        codec = meta.get("avro.codec", b"null")
        if codec not in (b"null", b""):
            raise ValueError(f"unsupported avro codec {codec!r}")
        schema = json.loads(meta["avro.schema"])
        sync = f.read(16)
        records: List[Dict[str, Any]] = []
        fields = schema["fields"]
        rest = f.read()  # container files are block-seekable; buffer whole
        buf = io.BytesIO(rest)
        while buf.tell() < len(rest):
            n = read_long(buf)
            blen = read_long(buf)
            bbuf = io.BytesIO(buf.read(blen))
            for _ in range(n):
                rec = {}
                for fl in fields:
                    rec[fl["name"]] = _read_value(bbuf, fl["type"])
                records.append(rec)
            if buf.read(16) != sync:
                raise ValueError("sync marker mismatch")
        return schema, records
    finally:
        if own:
            f.close()


def _read_value(buf, t):
    if isinstance(t, list):
        idx = read_long(buf)
        if t[idx] == "null":
            return None
        return _read_value(buf, t[idx])
    if isinstance(t, dict):
        t = t["type"]
    if t == "string":
        return read_bytes(buf).decode("utf-8")
    if t in ("int", "long"):
        return read_long(buf)
    if t == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if t == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if t == "boolean":
        return buf.read(1) == b"\x01"
    if t == "null":
        return None
    raise ValueError(f"unsupported avro type {t!r}")
