"""BIN format: packed 16/24-byte track records.

Wire-format parity with the reference's BinaryOutputEncoder
(utils/bin/BinaryOutputEncoder.scala:36) and BinSorter
(index/utils/bin/BinSorter.scala): little-endian records of

    [track-id-hash: int32][dtg-seconds: int32][lat: f32][lon: f32]

plus an optional 8-byte label (int64) for the 24-byte variant. The track id
is the Java ``String.hashCode`` of the track attribute (feature id by
default) so files are byte-compatible with reference consumers.

Packing is a vectorized structured-array write; string hashing touches each
*distinct* dictionary value once.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np

RECORD = np.dtype(
    [("track", "<i4"), ("dtg", "<i4"), ("lat", "<f4"), ("lon", "<f4")]
)
RECORD_LABEL = np.dtype(
    [("track", "<i4"), ("dtg", "<i4"), ("lat", "<f4"), ("lon", "<f4"),
     ("label", "<i8")]
)


def java_string_hash(s: str) -> int:
    """Java String.hashCode (int32 wraparound) — reference track-id hashing.
    Iterates UTF-16 code units (surrogate pairs for astral chars) to match
    Java exactly."""
    h = 0
    b = s.encode("utf-16-be", "surrogatepass")
    for i in range(0, len(b), 2):
        unit = (b[i] << 8) | b[i + 1]
        h = (h * 31 + unit) & 0xFFFFFFFF
    return h - 0x100000000 if h >= 0x80000000 else h


def _hash_values(vals: Sequence) -> np.ndarray:
    from geomesa_tpu import native

    return native.java_hash(vals)


def label_to_i64(vals: Sequence) -> np.ndarray:
    """Labels ride as int64: numeric labels directly, strings as their first
    8 bytes little-endian (reference Convert2ViewerFunction behavior)."""
    a = np.asarray(vals)
    if a.dtype.kind in "iuf":
        return a.astype(np.int64)
    out = np.zeros(len(a), np.int64)
    for i, v in enumerate(a):
        b = str(v).encode("utf-8")[:8]
        out[i] = int.from_bytes(b.ljust(8, b"\0"), "little", signed=True)
    return out


def pack(
    track_ids: np.ndarray,
    dtg_ms: np.ndarray,
    lat: np.ndarray,
    lon: np.ndarray,
    labels: Optional[np.ndarray] = None,
    sort: bool = True,
) -> bytes:
    """Pack columns into BIN bytes (sorted by time unless ``sort=False``)."""
    n = len(track_ids)
    rec = np.empty(n, RECORD_LABEL if labels is not None else RECORD)
    rec["track"] = np.asarray(track_ids, np.int32)
    rec["dtg"] = (np.asarray(dtg_ms, np.int64) // 1000).astype(np.int32)
    rec["lat"] = np.asarray(lat, np.float32)
    rec["lon"] = np.asarray(lon, np.float32)
    if labels is not None:
        rec["label"] = labels
    if sort:
        rec = rec[np.argsort(rec["dtg"], kind="stable")]
    return rec.tobytes()


def pack_batch(ft, batch, dicts, track: Optional[str] = None,
               label: Optional[str] = None, sort: bool = True) -> bytes:
    """Pack a ColumnBatch using schema metadata (geom + dtg fields)."""
    geom, dtg = ft.geom_field, ft.dtg_field
    if geom is None or dtg is None:
        raise ValueError("BIN export requires geometry and date attributes")
    cols = batch.columns
    if track is None or track == "id":
        from geomesa_tpu.schema.columns import fid_strs

        tids = _hash_values(fid_strs(cols["__fid__"]))
    else:
        a = ft.attr(track)
        col = cols[track]
        if a.type == "string":
            vocab = dicts[track].values
            if not vocab:  # all-null column: empty dictionary
                tids = np.zeros(len(col), np.int32)
            else:
                vocab_hash = _hash_values(vocab)
                tids = np.where(
                    col >= 0, vocab_hash[np.clip(col, 0, None)], 0
                ).astype(np.int32)
        else:
            tids = col.astype(np.int32)
    labels = None
    if label is not None:
        a = ft.attr(label)
        if a.type == "string":
            vocab = dicts[label].values
            col = cols[label]
            if not vocab:  # all-null column: empty dictionary
                labels = np.zeros(len(col), np.int64)
            else:
                lab64 = label_to_i64(vocab)
                labels = np.where(
                    col >= 0, lab64[np.clip(col, 0, None)], 0
                ).astype(np.int64)
        else:
            labels = label_to_i64(cols[label])
    return pack(
        tids, cols[dtg], cols[geom + "__y"], cols[geom + "__x"], labels, sort
    )


def unpack(data: bytes, label: bool = False) -> Dict[str, np.ndarray]:
    rec = np.frombuffer(data, RECORD_LABEL if label else RECORD)
    out = {
        "track": rec["track"].copy(),
        "dtg_s": rec["dtg"].copy(),
        "lat": rec["lat"].copy(),
        "lon": rec["lon"].copy(),
    }
    if label:
        out["label"] = rec["label"].copy()
    return out


def record_size(data: bytes) -> int:
    """Infer 16 vs 24-byte records (reference BinSorter does the same)."""
    n = len(data)
    if n % 24 and n % 16 == 0:
        return 16
    if n % 16 and n % 24 == 0:
        return 24
    if n % 16 == 0 and n % 24 == 0:
        return 16  # ambiguous (multiple of 48): default
    raise ValueError(f"not a BIN payload: {n} bytes")


def merge_sorted(chunks: Iterable[bytes], label: bool = False) -> bytes:
    """Merge time-sorted BIN chunks into one time-sorted payload
    (BinSorter merge analog, vectorized k-way via mergesort)."""
    dtype = RECORD_LABEL if label else RECORD
    recs = [np.frombuffer(c, dtype) for c in chunks if c]
    if not recs:
        return b""
    allr = np.concatenate(recs)
    return allr[np.argsort(allr["dtg"], kind="stable")].tobytes()
