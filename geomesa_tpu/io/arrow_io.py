"""Arrow interchange: columnar batches <-> Arrow record batches / IPC.

Role parity with geomesa-arrow (SURVEY.md §2.6): `SimpleFeatureVector`
(schema -> vectors including geometry vectors, vector/SimpleFeatureVector.scala:42),
`ArrowDictionary` (dictionary-encoded string attributes), file/stream
reader-writers, and `DeltaWriter` (io/DeltaWriter.scala:53 — incremental
batches with inline dictionary deltas merged client-side).

Mapping (one Arrow field per attribute):

* point geometry  -> FixedSizeList<float64>[2]  (x, y)   [like the reference's
                     fixed-size point vectors in geomesa-arrow-jts]
* other geometry  -> utf8 WKT
* date            -> timestamp[ms]
* string          -> dictionary<int32, utf8>   (codes shared with the store)
* numerics/bool   -> their arrow type
* feature id      -> utf8 field "__fid__"

The in-memory dictionary codes ARE the Arrow dictionary codes — export is
zero-re-encode, and the device layout is by construction Arrow-compatible.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from geomesa_tpu.schema.columns import ColumnBatch, DictionaryEncoder, encode_batch
from geomesa_tpu.schema.feature_type import FeatureType

FID = "__fid__"


def point_type() -> pa.DataType:
    return pa.list_(pa.float64(), 2)


def arrow_field(ft: FeatureType, name: str, wkt_geoms: Sequence[str] = ()) -> pa.Field:
    """``wkt_geoms`` names non-point geometry attributes stored WITH a WKT
    column (-> utf8); non-point geometries ingested as x/y reference points
    only are FixedSizeList like points, keeping empty- and non-empty-result
    schemas identical for the same dataset."""
    a = ft.attr(name)
    if a.is_geom:
        t = pa.utf8() if (not a.is_point and name in wkt_geoms) else point_type()
    elif a.type == "date":
        t = pa.timestamp("ms")
    elif a.type == "string":
        t = pa.dictionary(pa.int32(), pa.utf8())
    elif a.type == "json":
        t = pa.utf8()  # raw document text
    elif a.type == "bool":
        t = pa.bool_()
    else:
        t = pa.from_numpy_dtype(np.dtype(a.type))
    return pa.field(name, t)


def arrow_schema(ft: FeatureType, properties: Optional[Sequence[str]] = None,
                 wkt_geoms: Sequence[str] = ()) -> pa.Schema:
    names = properties or [a.name for a in ft.attributes]
    fields = [pa.field(FID, pa.utf8())] + [
        arrow_field(ft, n, wkt_geoms) for n in names
    ]
    return pa.schema(fields, metadata={b"geomesa:spec": ft.spec().encode()})


def batch_to_arrow(
    ft: FeatureType,
    batch: ColumnBatch,
    dicts: Dict[str, DictionaryEncoder],
    properties: Optional[Sequence[str]] = None,
) -> pa.RecordBatch:
    """Encoded columns -> Arrow record batch (strings stay dictionary codes).

    Non-point geometries are emitted as utf8 WKT when the batch carries the
    ``__wkt`` column, else as their x/y reference point (FixedSizeList) —
    the field type always matches the emitted array.
    """
    names = properties or [a.name for a in ft.attributes]
    arrays: List[pa.Array] = [None]  # fid placeholder
    fields: List[pa.Field] = [pa.field(FID, pa.utf8())]
    fids = batch.columns.get(FID)
    if fids is None:
        fids = np.array([str(i) for i in range(batch.n)], dtype=object)
    else:
        from geomesa_tpu.schema.columns import fid_strs

        fids = fid_strs(fids)
    arrays[0] = pa.array([str(f) for f in fids], pa.utf8())
    for name in names:
        a = ft.attr(name)
        if a.is_geom:
            if a.is_point or name + "__wkt" not in batch.columns:
                xs = batch.columns[name + "__x"]
                ys = batch.columns[name + "__y"]
                flat = np.empty(2 * len(xs), np.float64)
                flat[0::2], flat[1::2] = xs, ys
                arrays.append(
                    pa.FixedSizeListArray.from_arrays(pa.array(flat), 2)
                )
                fields.append(pa.field(name, point_type()))
            else:
                arrays.append(
                    pa.array([str(w) for w in batch.columns[name + "__wkt"]], pa.utf8())
                )
                fields.append(pa.field(name, pa.utf8()))
        elif a.type == "date":
            arrays.append(pa.array(batch.columns[name], pa.timestamp("ms")))
            fields.append(pa.field(name, pa.timestamp("ms")))
        elif a.type == "json":
            arrays.append(pa.array(
                [None if v is None else str(v) for v in batch.columns[name]],
                pa.utf8(),
            ))
            fields.append(pa.field(name, pa.utf8()))
        elif a.type == "string":
            codes = batch.columns[name]
            vocab = dicts.get(name, DictionaryEncoder()).values
            mask = codes < 0
            arrays.append(
                pa.DictionaryArray.from_arrays(
                    pa.array(np.where(mask, 0, codes).astype(np.int32),
                             mask=mask),
                    pa.array(vocab if vocab else [""], pa.utf8()),
                )
            )
            fields.append(pa.field(name, pa.dictionary(pa.int32(), pa.utf8())))
        else:
            arr = pa.array(batch.columns[name])
            arrays.append(arr)
            fields.append(pa.field(name, arr.type))
    schema = pa.schema(fields, metadata={b"geomesa:spec": ft.spec().encode()})
    return pa.RecordBatch.from_arrays(arrays, schema=schema)


def table_to_data(ft: FeatureType, table: "pa.Table | pa.RecordBatch") -> Tuple[Dict, List[str]]:
    """Arrow -> (data dict for encode_batch, fids). Inverse of batch_to_arrow;
    also accepts 'plain' layouts (x/y columns, utf8 strings, int64 dates)."""
    if isinstance(table, pa.RecordBatch):
        table = pa.Table.from_batches([table])
    cols = {c: table.column(c) for c in table.column_names}
    data: Dict[str, object] = {}
    fids = None
    if FID in cols:
        fids = cols[FID].to_pylist()
    for a in ft.attributes:
        name = a.name
        if a.is_geom:
            if name in cols:
                col = cols[name]
            elif name + "__x" in cols:
                data[name + "__x"] = np.asarray(cols[name + "__x"].to_numpy(zero_copy_only=False))
                data[name + "__y"] = np.asarray(cols[name + "__y"].to_numpy(zero_copy_only=False))
                continue
            else:
                raise KeyError(f"arrow input missing geometry column {name!r}")
            t = col.type
            if pa.types.is_fixed_size_list(t) or pa.types.is_list(t):
                arr = col.combine_chunks()
                if isinstance(arr, pa.ChunkedArray):
                    arr = arr.chunk(0)
                flat = np.asarray(arr.flatten().to_numpy(zero_copy_only=False), np.float64)
                data[name + "__x"] = flat[0::2].copy()
                data[name + "__y"] = flat[1::2].copy()
            else:
                data[name] = col.to_pylist()  # WKT strings
        elif a.type == "date":
            col = cols[name]
            if pa.types.is_timestamp(col.type):
                data[name] = col.cast(pa.timestamp("ms")).to_numpy(zero_copy_only=False).astype("datetime64[ms]")
            else:
                data[name] = np.asarray(col.to_numpy(zero_copy_only=False), np.int64)
        elif a.type in ("string", "json"):
            col = cols[name]
            if pa.types.is_dictionary(col.type):
                col = col.cast(pa.utf8())
            data[name] = col.to_pylist()
        else:
            data[name] = np.asarray(
                cols[name].to_numpy(zero_copy_only=False)
            )
    return data, fids


# -- IPC files / streams ----------------------------------------------------

def write_ipc(path_or_buf, batches: Iterable[pa.RecordBatch], schema: pa.Schema):
    with pa.OSFile(path_or_buf, "wb") if isinstance(path_or_buf, str) else path_or_buf as sink:
        with pa.ipc.new_file(sink, schema) as writer:
            for b in batches:
                writer.write_batch(b)


def read_ipc(path_or_buf) -> pa.Table:
    src = pa.memory_map(path_or_buf) if isinstance(path_or_buf, str) else path_or_buf
    with pa.ipc.open_file(src) as reader:
        return reader.read_all()


class _ChunkSink:
    """File-like sink that lets the writer snapshot bytes appended per batch."""

    def __init__(self):
        self._buf = bytearray()

    def write(self, data) -> int:
        b = bytes(data)
        self._buf += b
        return len(b)

    def take(self) -> bytes:
        out = bytes(self._buf)
        self._buf.clear()
        return out

    def flush(self):
        pass

    @property
    def closed(self) -> bool:
        return False

    def close(self):
        pass


class DeltaWriter:
    """Incremental Arrow stream with dictionary deltas (DeltaWriter.scala:53
    analog): one long-lived IPC stream; each ``write`` returns the bytes
    appended for that batch — the first chunk carries the schema + initial
    dictionaries, later chunks carry only dictionary *deltas* (new entries)
    plus the record batch. Chunks are order-dependent; ``merge`` concatenates
    and decodes them client-side (the reference merges delta batches the same
    way, ArrowScan.scala:38-79)."""

    def __init__(self, ft: FeatureType, dicts: Dict[str, DictionaryEncoder],
                 properties: Optional[Sequence[str]] = None):
        self.ft = ft
        self.dicts = dicts
        self.properties = properties
        self._sink = _ChunkSink()
        self._writer = None

    def write(self, batch: ColumnBatch) -> bytes:
        rb = batch_to_arrow(self.ft, batch, self.dicts, self.properties)
        if self._writer is None:
            opts = pa.ipc.IpcWriteOptions(emit_dictionary_deltas=True)
            self._writer = pa.ipc.new_stream(self._sink, rb.schema, options=opts)
        self._writer.write_batch(rb)
        return self._sink.take()

    def close(self) -> bytes:
        """End the stream; returns any trailing bytes (EOS marker)."""
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        return self._sink.take()

    @staticmethod
    def merge(chunks: Sequence[bytes]) -> pa.Table:
        if not chunks:
            return pa.table({})
        with pa.ipc.open_stream(pa.BufferReader(b"".join(chunks))) as r:
            batches = []
            while True:
                try:
                    batches.append(r.read_next_batch())
                except StopIteration:
                    break
        return pa.Table.from_batches(batches).unify_dictionaries()
