"""IO: Arrow interchange, Parquet storage, BIN format, export formats."""
