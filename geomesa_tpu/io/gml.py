"""GML 3.1 export — CLI `export -F gml` parity (the reference exports GML
via GeoTools encoders, geomesa-tools/.../export/formats/GmlExporter.scala).

Emits a ``wfs:FeatureCollection`` with one ``gml:featureMember`` per feature;
geometries as gml:Point/LineString/Polygon/Multi* in EPSG:4326 (lon lat
posLists, srsDimension 2).
"""

from __future__ import annotations

from typing import Dict
from xml.sax.saxutils import escape, quoteattr

import numpy as np

from geomesa_tpu.utils import geometry as geo

_HEADER = (
    '<?xml version="1.0" encoding="UTF-8"?>\n'
    '<wfs:FeatureCollection xmlns:wfs="http://www.opengis.net/wfs" '
    'xmlns:gml="http://www.opengis.net/gml" '
    'xmlns:geomesa="http://geomesa.org">\n'
)


def _pos_list(coords) -> str:
    return " ".join(f"{x:.10g} {y:.10g}" for x, y in np.asarray(coords))


def _gml_geom(g) -> str:
    srs = ' srsName="urn:ogc:def:crs:EPSG::4326"'
    if isinstance(g, geo.Point):
        return (
            f"<gml:Point{srs}><gml:pos>{g.x:.10g} {g.y:.10g}</gml:pos>"
            "</gml:Point>"
        )
    if isinstance(g, geo.LineString):
        return (
            f"<gml:LineString{srs}><gml:posList>{_pos_list(g.coords)}"
            "</gml:posList></gml:LineString>"
        )
    if isinstance(g, geo.Polygon):
        out = [f"<gml:Polygon{srs}><gml:exterior><gml:LinearRing><gml:posList>",
               _pos_list(geo._close_ring(g.shell)),
               "</gml:posList></gml:LinearRing></gml:exterior>"]
        for h in g.holes:
            out.append(
                "<gml:interior><gml:LinearRing><gml:posList>"
                + _pos_list(geo._close_ring(h))
                + "</gml:posList></gml:LinearRing></gml:interior>"
            )
        out.append("</gml:Polygon>")
        return "".join(out)
    if isinstance(g, geo.MultiPoint):
        inner = "".join(
            f"<gml:pointMember>{_gml_geom(p)}</gml:pointMember>"
            for p in g.points
        )
        return f"<gml:MultiPoint{srs}>{inner}</gml:MultiPoint>"
    if isinstance(g, geo.MultiLineString):
        inner = "".join(
            f"<gml:lineStringMember>{_gml_geom(ls)}</gml:lineStringMember>"
            for ls in g.lines
        )
        return f"<gml:MultiLineString{srs}>{inner}</gml:MultiLineString>"
    if isinstance(g, geo.MultiPolygon):
        inner = "".join(
            f"<gml:polygonMember>{_gml_geom(p)}</gml:polygonMember>"
            for p in g.polygons
        )
        return f"<gml:MultiPolygon{srs}>{inner}</gml:MultiPolygon>"
    raise ValueError(f"unsupported geometry {type(g).__name__}")


def dumps(ft, batch, dicts: Dict) -> str:
    """Feature batch -> GML 3.1 FeatureCollection text."""
    from geomesa_tpu.schema.columns import decode_batch

    d = decode_batch(ft, batch, dicts)
    tn = ft.name
    out = [_HEADER]
    for i in range(batch.n):
        out.append("<gml:featureMember>")
        out.append(f'<geomesa:{tn} gml:id={quoteattr(str(d["__fid__"][i]))}>')
        for a in ft.attributes:
            if a.name not in d:  # projected out
                continue
            v = d[a.name][i]
            if v is None or (
                isinstance(v, (float, np.floating)) and np.isnan(v)
            ):
                continue
            if a.is_geom:
                if isinstance(v, str):
                    g = geo.parse_wkt(v)
                elif isinstance(v, geo.Geometry):
                    g = v
                else:
                    g = geo.Point(float(v[0]), float(v[1]))
                out.append(
                    f"<geomesa:{a.name}>{_gml_geom(g)}</geomesa:{a.name}>"
                )
            elif a.type == "date":
                iso = str(np.datetime64(v, "ms")) + "Z"
                out.append(f"<geomesa:{a.name}>{iso}</geomesa:{a.name}>")
            else:
                out.append(
                    f"<geomesa:{a.name}>{escape(str(v))}</geomesa:{a.name}>"
                )
        out.append(f"</geomesa:{tn}>")
        out.append("</gml:featureMember>\n")
    out.append("</wfs:FeatureCollection>\n")
    return "".join(out)
