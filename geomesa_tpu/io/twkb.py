"""TWKB (Tiny Well-Known Binary) geometry codec.

Wire-format parity with the reference's compressed geometry encoding inside
Kryo row values (geomesa-feature-common/.../serialization/TwkbSerialization.
scala): type+precision header byte, metadata byte, zigzag-varint delta-coded
coordinates. Subset: Point, LineString, Polygon, MultiPoint, MultiLineString,
MultiPolygon; optional empty flag; no bbox/size/id-list extensions (the
reference doesn't emit them either).
"""

from __future__ import annotations

import io
from typing import List, Tuple

import numpy as np

from geomesa_tpu.utils import geometry as geo

_TYPE = {
    "point": 1, "linestring": 2, "polygon": 3,
    "multipoint": 4, "multilinestring": 5, "multipolygon": 6,
}


def _zz(v: int) -> int:
    return (v << 1) ^ (v >> 63)


def _unzz(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def _wv(buf: io.BytesIO, v: int):
    v &= (1 << 64) - 1
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            buf.write(bytes([b | 0x80]))
        else:
            buf.write(bytes([b]))
            return


def _rv(buf: io.BytesIO) -> int:
    shift = acc = 0
    while True:
        (b,) = buf.read(1)
        acc |= (b & 0x7F) << shift
        if not (b & 0x80):
            if acc >= 1 << 63:
                acc -= 1 << 64
            return acc
        shift += 7


class _Writer:
    def __init__(self, precision: int):
        self.scale = 10 ** precision
        self.px = 0
        self.py = 0

    def coords(self, buf: io.BytesIO, pts) -> None:
        for x, y in pts:
            ix, iy = round(float(x) * self.scale), round(float(y) * self.scale)
            _wv(buf, _zz(ix - self.px))
            _wv(buf, _zz(iy - self.py))
            self.px, self.py = ix, iy


class _Reader:
    def __init__(self, precision: int):
        self.scale = 10 ** precision
        self.px = 0
        self.py = 0

    def coords(self, buf: io.BytesIO, n: int) -> List[Tuple[float, float]]:
        out = []
        for _ in range(n):
            self.px += _unzz(_rv(buf))
            self.py += _unzz(_rv(buf))
            out.append((self.px / self.scale, self.py / self.scale))
        return out


def encode(g: geo.Geometry, precision: int = 7) -> bytes:
    """Geometry -> TWKB bytes (default precision 7 ≈ 1 cm at the equator,
    the reference's default). Precision must fit the header's zigzag
    nibble: -8..7 (the TWKB spec range)."""
    if not -8 <= precision <= 7:
        raise ValueError(f"TWKB precision must be in [-8, 7], got {precision}")
    buf = io.BytesIO()
    t = _TYPE[g.kind]
    buf.write(bytes([(_zz_p(precision) << 4) | t]))
    buf.write(b"\x00")  # metadata: no bbox/size/ids/extended/empty
    w = _Writer(precision)
    if isinstance(g, geo.Point):
        w.coords(buf, [(g.x, g.y)])
    elif isinstance(g, geo.LineString):
        _wv(buf, len(g.coords))
        w.coords(buf, g.coords)
    elif isinstance(g, geo.Polygon):
        rings = [geo._close_ring(g.shell)] + [geo._close_ring(h) for h in g.holes]
        _wv(buf, len(rings))
        for r in rings:
            _wv(buf, len(r))
            w.coords(buf, r)
    elif isinstance(g, geo.MultiPoint):
        _wv(buf, len(g.points))
        w.coords(buf, [(p.x, p.y) for p in g.points])
    elif isinstance(g, geo.MultiLineString):
        _wv(buf, len(g.lines))
        for ls in g.lines:
            _wv(buf, len(ls.coords))
            w.coords(buf, ls.coords)
    elif isinstance(g, geo.MultiPolygon):
        _wv(buf, len(g.polygons))
        for p in g.polygons:
            rings = [geo._close_ring(p.shell)] + [
                geo._close_ring(h) for h in p.holes
            ]
            _wv(buf, len(rings))
            for r in rings:
                _wv(buf, len(r))
                w.coords(buf, r)
    else:
        raise ValueError(f"unsupported geometry {g.kind!r}")
    return buf.getvalue()


def _zz_p(p: int) -> int:
    return ((p << 1) ^ (p >> 31)) & 0xF


def _unzz_p(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def decode(data: bytes) -> geo.Geometry:
    buf = io.BytesIO(data)
    (head,) = buf.read(1)
    t = head & 0x0F
    precision = _unzz_p(head >> 4)
    (meta,) = buf.read(1)
    if meta & 0x10:  # empty flag
        raise ValueError("empty TWKB geometries are not supported")
    if meta & 0x0F:
        raise ValueError("TWKB bbox/size/id extensions are not supported")
    r = _Reader(precision)
    if t == 1:
        (xy,) = r.coords(buf, 1)
        return geo.Point(*xy)
    if t == 2:
        return geo.LineString(tuple(r.coords(buf, _rv(buf))))
    if t == 3:
        nrings = _rv(buf)
        rings = [tuple(r.coords(buf, _rv(buf))) for _ in range(nrings)]
        return geo.Polygon(rings[0], tuple(rings[1:]))
    if t == 4:
        pts = r.coords(buf, _rv(buf))
        return geo.MultiPoint(tuple(geo.Point(*xy) for xy in pts))
    if t == 5:
        n = _rv(buf)
        return geo.MultiLineString(tuple(
            geo.LineString(tuple(r.coords(buf, _rv(buf)))) for _ in range(n)
        ))
    if t == 6:
        n = _rv(buf)
        polys = []
        for _ in range(n):
            nrings = _rv(buf)
            rings = [tuple(r.coords(buf, _rv(buf))) for _ in range(nrings)]
            polys.append(geo.Polygon(rings[0], tuple(rings[1:])))
        return geo.MultiPolygon(tuple(polys))
    raise ValueError(f"unknown TWKB type {t}")
