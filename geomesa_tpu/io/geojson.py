"""GeoJSON export/import (tools export -F geojson + geomesa-geojson analog)."""

from __future__ import annotations

import json
from typing import Dict, Iterable, List, Optional

import numpy as np

from geomesa_tpu.schema.columns import ColumnBatch, DictionaryEncoder
from geomesa_tpu.schema.feature_type import FeatureType
from geomesa_tpu.utils import geometry as geo


def _geom_json(ft: FeatureType, name: str, batch: ColumnBatch, i: int):
    wkt_col = batch.columns.get(name + "__wkt")
    if wkt_col is not None:
        g = geo.parse_wkt(str(wkt_col[i]))
        return _shape_to_json(g)
    xs = batch.columns.get(name + "__x")
    if xs is None:  # geometry projected out of the result
        return None
    x = float(xs[i])
    y = float(batch.columns[name + "__y"][i])
    return {"type": "Point", "coordinates": [x, y]}


def _shape_to_json(g: geo.Geometry) -> Dict:
    if isinstance(g, geo.Point):
        return {"type": "Point", "coordinates": [g.x, g.y]}
    if isinstance(g, geo.LineString):
        return {"type": "LineString", "coordinates": [list(p) for p in g.coords]}
    if isinstance(g, geo.Polygon):
        rings = [g.shell] + list(g.holes)
        return {
            "type": "Polygon",
            "coordinates": [[list(p) for p in r] for r in rings],
        }
    if isinstance(g, geo.MultiPoint):
        return {"type": "MultiPoint", "coordinates": [[p.x, p.y] for p in g.points]}
    if isinstance(g, geo.MultiPolygon):
        return {
            "type": "MultiPolygon",
            "coordinates": [
                [[list(p) for p in ring] for ring in [poly.shell] + list(poly.holes)]
                for poly in g.polygons
            ],
        }
    raise ValueError(f"cannot encode {type(g).__name__} as GeoJSON")


def to_geojson(ft: FeatureType, batch: ColumnBatch,
               dicts: Dict[str, DictionaryEncoder]) -> Dict:
    """ColumnBatch -> GeoJSON FeatureCollection dict."""
    gname = ft.geom_field
    features: List[Dict] = []
    decoded: Dict[str, list] = {}
    for a in ft.attributes:
        if a.is_geom:
            continue
        col = batch.columns.get(a.name)
        if col is None:
            continue
        if a.type == "string":
            decoded[a.name] = dicts[a.name].decode(col)
        elif a.type == "date":
            decoded[a.name] = [
                None if v is None else str(np.datetime64(int(v), "ms")) + "Z"
                for v in col.tolist()
            ]
        else:
            decoded[a.name] = col.tolist()
    fids = batch.columns.get("__fid__")
    if fids is not None:
        from geomesa_tpu.schema.columns import fid_strs

        fids = fid_strs(fids)
    for i in range(batch.n):
        props = {k: v[i] for k, v in decoded.items()}
        features.append({
            "type": "Feature",
            "id": str(fids[i]) if fids is not None else str(i),
            "geometry": _geom_json(ft, gname, batch, i) if gname else None,
            "properties": props,
        })
    return {"type": "FeatureCollection", "features": features}


def dumps(ft: FeatureType, batch: ColumnBatch,
          dicts: Dict[str, DictionaryEncoder]) -> str:
    return json.dumps(to_geojson(ft, batch, dicts))
