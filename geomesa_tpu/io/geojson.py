"""GeoJSON export/import (tools export -F geojson + geomesa-geojson analog)."""

from __future__ import annotations

import json
from typing import Dict, Iterable, List, Optional

import numpy as np

from geomesa_tpu.schema.columns import ColumnBatch, DictionaryEncoder
from geomesa_tpu.schema.feature_type import FeatureType
from geomesa_tpu.utils import geometry as geo


def _geom_json(ft: FeatureType, name: str, batch: ColumnBatch, i: int):
    wkt_col = batch.columns.get(name + "__wkt")
    if wkt_col is not None:
        g = geo.parse_wkt(str(wkt_col[i]))
        return _shape_to_json(g)
    xs = batch.columns.get(name + "__x")
    if xs is None:  # geometry projected out of the result
        return None
    x = float(xs[i])
    y = float(batch.columns[name + "__y"][i])
    return {"type": "Point", "coordinates": [x, y]}


def _shape_to_json(g: geo.Geometry) -> Dict:
    if isinstance(g, geo.Point):
        return {"type": "Point", "coordinates": [g.x, g.y]}
    if isinstance(g, geo.LineString):
        return {"type": "LineString", "coordinates": [list(p) for p in g.coords]}
    if isinstance(g, geo.Polygon):
        rings = [g.shell] + list(g.holes)
        return {
            "type": "Polygon",
            "coordinates": [[list(p) for p in r] for r in rings],
        }
    if isinstance(g, geo.MultiPoint):
        return {"type": "MultiPoint", "coordinates": [[p.x, p.y] for p in g.points]}
    if isinstance(g, geo.MultiLineString):
        return {
            "type": "MultiLineString",
            "coordinates": [[list(p) for p in ls.coords] for ls in g.lines],
        }
    if isinstance(g, geo.MultiPolygon):
        return {
            "type": "MultiPolygon",
            "coordinates": [
                [[list(p) for p in ring] for ring in [poly.shell] + list(poly.holes)]
                for poly in g.polygons
            ],
        }
    raise ValueError(f"cannot encode {type(g).__name__} as GeoJSON")


def to_geojson(ft: FeatureType, batch: ColumnBatch,
               dicts: Dict[str, DictionaryEncoder]) -> Dict:
    """ColumnBatch -> GeoJSON FeatureCollection dict."""
    gname = ft.geom_field
    features: List[Dict] = []
    decoded: Dict[str, list] = {}
    for a in ft.attributes:
        if a.is_geom:
            continue
        col = batch.columns.get(a.name)
        if col is None:
            continue
        if a.type == "string":
            decoded[a.name] = dicts[a.name].decode(col)
        elif a.type == "date":
            decoded[a.name] = [
                None if v is None else str(np.datetime64(int(v), "ms")) + "Z"
                for v in col.tolist()
            ]
        else:
            decoded[a.name] = col.tolist()
    fids = batch.columns.get("__fid__")
    if fids is not None:
        from geomesa_tpu.schema.columns import fid_strs

        fids = fid_strs(fids)
    for i in range(batch.n):
        props = {k: v[i] for k, v in decoded.items()}
        features.append({
            "type": "Feature",
            "id": str(fids[i]) if fids is not None else str(i),
            "geometry": _geom_json(ft, gname, batch, i) if gname else None,
            "properties": props,
        })
    return {"type": "FeatureCollection", "features": features}


def dumps(ft: FeatureType, batch: ColumnBatch,
          dicts: Dict[str, DictionaryEncoder]) -> str:
    return json.dumps(to_geojson(ft, batch, dicts))


def _json_to_shape(g: Dict) -> geo.Geometry:
    t = g["type"]
    c = g["coordinates"]
    if t == "Point":
        return geo.Point(float(c[0]), float(c[1]))
    if t == "LineString":
        return geo.LineString(tuple((float(x), float(y)) for x, y in c))
    if t == "Polygon":
        rings = [tuple((float(x), float(y)) for x, y in r) for r in c]
        return geo.Polygon(rings[0], tuple(rings[1:]))
    if t == "MultiPoint":
        return geo.MultiPoint(tuple(
            geo.Point(float(x), float(y)) for x, y in c))
    if t == "MultiLineString":
        return geo.MultiLineString(tuple(
            geo.LineString(tuple((float(x), float(y)) for x, y in ls))
            for ls in c))
    if t == "MultiPolygon":
        polys = []
        for pc in c:
            rings = [tuple((float(x), float(y)) for x, y in r) for r in pc]
            polys.append(geo.Polygon(rings[0], tuple(rings[1:])))
        return geo.MultiPolygon(tuple(polys))
    raise ValueError(f"cannot decode GeoJSON geometry type {t!r}")


def from_geojson(ft: FeatureType, doc: "str | Dict"):
    """GeoJSON FeatureCollection (or single Feature) -> (columns, fids)
    shaped for ``GeoDataset.insert`` under ``ft``'s schema — the parse
    direction of :func:`to_geojson`, used by the REST ingest endpoint and
    the JVM DataStore's writer path.

    Missing properties fill with the columnar null representation
    (string -> None is not representable, so "" ; numeric -> NaN/0;
    date -> epoch 0), matching ``update_schema``'s null fill."""
    from geomesa_tpu import resilience

    if isinstance(doc, str):
        doc = json.loads(doc)
    try:
        # ingest-parser fault edge (docs/RESILIENCE.md, ``io.geojson.
        # parse``): corruption in the body is contained to a typed
        # ValueError — the REST layer answers 400, a converter pipeline
        # quarantines the record; there is nothing to retry in a
        # malformed document
        resilience.fault_point("io.geojson.parse", schema=ft.name)
        return _from_geojson(ft, doc)
    except (KeyError, IndexError, TypeError) as e:
        # structural problems in the client's body are input errors
        # (-> HTTP 400 at the REST layer), never KeyError (-> 404)
        raise ValueError(f"malformed GeoJSON: {type(e).__name__}: {e}")


def _from_geojson(ft: FeatureType, doc: Dict):
    feats = (doc["features"] if doc.get("type") == "FeatureCollection"
             else [doc])
    n = len(feats)
    data: Dict[str, np.ndarray] = {}
    fids: List[str] = []
    for i, f in enumerate(feats):
        fid = f.get("id")
        if fid is None:
            fid = (f.get("properties") or {}).get("id", f"gj-{i}")
        fids.append(str(fid))
    for a in ft.attributes:
        if a.is_geom:
            geoms = [f.get("geometry") for f in feats]
            if any(g is None for g in geoms):
                raise ValueError(
                    f"feature missing geometry for attribute {a.name!r}"
                )
            if a.type == "point":
                bad = {g["type"] for g in geoms if g.get("type") != "Point"}
                if bad:
                    raise ValueError(
                        f"attribute {a.name!r} is Point-typed but the "
                        f"body carries {sorted(bad)} geometries"
                    )
                data[a.name + "__x"] = np.array(
                    [float(g["coordinates"][0]) for g in geoms], np.float64)
                data[a.name + "__y"] = np.array(
                    [float(g["coordinates"][1]) for g in geoms], np.float64)
            else:
                data[a.name] = np.array(
                    [_json_to_shape(g).wkt() for g in geoms], dtype=object)
            continue
        vals = [(f.get("properties") or {}).get(a.name) for f in feats]
        if a.type == "string" or a.type == "json":
            data[a.name] = np.array(
                [("" if v is None else
                  (v if isinstance(v, str) else json.dumps(v)))
                 for v in vals], dtype=object)
        elif a.type == "date":
            data[a.name] = np.array(
                ["1970-01-01T00:00:00" if v is None
                 else str(v).rstrip("Z") for v in vals],
                dtype="datetime64[ms]")
        elif a.type in ("float32", "float64"):
            data[a.name] = np.array(
                [np.nan if v is None else float(v) for v in vals],
                np.float32 if a.type == "float32" else np.float64)
        elif a.type in ("int32", "int64"):
            data[a.name] = np.array(
                [0 if v is None else int(v) for v in vals],
                np.int32 if a.type == "int32" else np.int64)
        elif a.type == "bool":
            data[a.name] = np.array(
                [bool(v) for v in vals], np.bool_)
        else:  # pragma: no cover - the registry above is exhaustive
            raise ValueError(f"unsupported attribute type {a.type!r}")
    return data, np.array(fids, dtype=object) if n else np.array([], object)
