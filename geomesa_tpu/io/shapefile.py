"""ESRI Shapefile export (.shp/.shx/.dbf) — CLI `export -F shp` parity.

The reference exports shapefiles through GeoTools' shapefile datastore
(geomesa-tools/.../export/formats/ShapefileExporter.scala); here the three
files are written directly: Point (type 1), PolyLine (3), Polygon (5).
Attributes land in the DBF as C(254) strings / N(18,x) numerics / D dates —
the standard dBASE III subset every GIS reads.
"""

from __future__ import annotations

import os
import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from geomesa_tpu.utils import geometry as geo

SHP_POINT = 1
SHP_POLYLINE = 3
SHP_POLYGON = 5
SHP_MULTIPOINT = 8


def _is_null(v) -> bool:
    return v is None or (
        isinstance(v, (float, np.floating)) and np.isnan(v)
    )


def _geom_parts(g) -> Tuple[int, List[np.ndarray]]:
    """Geometry -> (shape type, list of part vertex arrays [n, 2])."""
    if isinstance(g, geo.Point):
        return SHP_POINT, [np.array([[g.x, g.y]])]
    if isinstance(g, geo.MultiPoint):
        return SHP_MULTIPOINT, [
            np.array([[p.x, p.y] for p in g.points])
        ]
    if isinstance(g, geo.LineString):
        return SHP_POLYLINE, [np.asarray(g.coords)]
    if isinstance(g, geo.MultiLineString):
        return SHP_POLYLINE, [np.asarray(ls.coords) for ls in g.lines]
    if isinstance(g, geo.Polygon):
        rings = [np.asarray(geo._close_ring(g.shell))] + [
            np.asarray(geo._close_ring(h)) for h in g.holes
        ]
        return SHP_POLYGON, rings
    if isinstance(g, geo.MultiPolygon):
        rings: List[np.ndarray] = []
        for p in g.polygons:
            rings.append(np.asarray(geo._close_ring(p.shell)))
            rings += [np.asarray(geo._close_ring(h)) for h in p.holes]
        return SHP_POLYGON, rings
    raise ValueError(f"unsupported geometry {type(g).__name__}")


def _record_bytes(shape_type: int, parts: List[np.ndarray]) -> bytes:
    if shape_type == SHP_POINT:
        x, y = float(parts[0][0, 0]), float(parts[0][0, 1])
        return struct.pack("<idd", SHP_POINT, x, y)
    pts = np.concatenate(parts)
    xmin, ymin = pts.min(axis=0)
    xmax, ymax = pts.max(axis=0)
    if shape_type == SHP_MULTIPOINT:  # no parts array in the record
        return (
            struct.pack("<i4di", SHP_MULTIPOINT, xmin, ymin, xmax, ymax, len(pts))
            + pts.astype("<f8").tobytes()
        )
    out = struct.pack(
        "<i4dii", shape_type, xmin, ymin, xmax, ymax, len(parts), len(pts)
    )
    off = 0
    for p in parts:
        out += struct.pack("<i", off)
        off += len(p)
    out += pts.astype("<f8").tobytes()
    return out


def write_shapefile(path: str, ft, batch, dicts):
    """Write ``path``(.shp/.shx/.dbf) from a feature batch."""
    from geomesa_tpu.schema.columns import decode_batch

    base = path[:-4] if path.lower().endswith(".shp") else path
    d = decode_batch(ft, batch, dicts)
    gname = ft.geom_field
    if gname is None or gname not in d:
        raise ValueError(
            "shapefile export requires the geometry attribute "
            "(include it in the projection)"
        )
    geoms = []
    for v in d[gname]:
        if isinstance(v, str):
            geoms.append(geo.parse_wkt(v))
        elif isinstance(v, geo.Geometry):
            geoms.append(v)
        else:  # (x, y) pair
            geoms.append(geo.Point(float(v[0]), float(v[1])))

    recs = [_geom_parts(g) for g in geoms]
    shape_type = recs[0][0] if recs else SHP_POINT
    if any(t != shape_type for t, _ in recs):
        raise ValueError("shapefiles hold a single geometry type")

    # .shp + .shx
    contents = [_record_bytes(t, p) for t, p in recs]
    shp_len = 100 + sum(8 + len(c) for c in contents)
    shx_len = 100 + 8 * len(contents)
    allpts = (
        np.concatenate([np.concatenate(p) for _, p in recs])
        if recs else np.zeros((0, 2))
    )
    bbox = (
        (allpts[:, 0].min(), allpts[:, 1].min(),
         allpts[:, 0].max(), allpts[:, 1].max())
        if len(allpts) else (0.0, 0.0, 0.0, 0.0)
    )

    def header(total_words: int) -> bytes:
        return (
            struct.pack(">i20x2i", 9994, total_words, 0)[:28]
            + struct.pack("<2i", 1000, shape_type)
            + struct.pack("<4d", *bbox)
            + struct.pack("<4d", 0, 0, 0, 0)
        )

    with open(base + ".shp", "wb") as f:
        f.write(header(shp_len // 2))
        for i, c in enumerate(contents):
            f.write(struct.pack(">2i", i + 1, len(c) // 2))
            f.write(c)
    with open(base + ".shx", "wb") as f:
        f.write(header(shx_len // 2))
        off = 50
        for c in contents:
            f.write(struct.pack(">2i", off, len(c) // 2))
            off += 4 + len(c) // 2

    # .dbf (projected-out attributes are skipped)
    attrs = [a for a in ft.attributes if not a.is_geom and a.name in d]
    _write_dbf(base + ".dbf", attrs, d, batch.n)
    return base


def _write_dbf(path: str, attrs, d: Dict[str, Any], n: int):
    fields = []
    for a in attrs:
        if a.type == "date":
            fields.append((a.name[:10], b"D", 8, 0))
        elif a.type in ("int32", "int64"):
            fields.append((a.name[:10], b"N", 18, 0))
        elif a.type in ("float32", "float64"):
            fields.append((a.name[:10], b"N", 18, 6))
        else:
            fields.append((a.name[:10], b"C", 254, 0))
    header_len = 32 + 32 * len(fields) + 1
    rec_len = 1 + sum(w for _, _, w, _ in fields)
    with open(path, "wb") as f:
        f.write(struct.pack("<B3BIHH20x", 3, 24, 1, 1, n, header_len, rec_len))
        for name, typ, width, dec in fields:
            f.write(struct.pack(
                "<11s1s4xBB14x", name.encode()[:11], typ, width, dec
            ))
        f.write(b"\x0d")
        for i in range(n):
            f.write(b" ")
            for (name, typ, width, dec), a in zip(fields, attrs):
                v = d[a.name][i]
                if typ == b"D":
                    s = (
                        "        " if _is_null(v)
                        else str(np.datetime64(v, "D")).replace("-", "")
                    )
                elif typ == b"N":
                    if _is_null(v):
                        s = " " * width
                    elif dec:
                        s = f"{float(v):.{dec}f}".rjust(width)
                    else:
                        s = str(int(v)).rjust(width)
                else:
                    s = ("" if v is None else str(v))[:width].ljust(width)
                f.write(s[:width].ljust(width).encode("utf-8", "replace")[:width].ljust(width, b" "))
        f.write(b"\x1a")


def read_shapefile(path: str) -> List[Tuple[int, List[np.ndarray]]]:
    """Minimal .shp reader (round-trip tests + CLI import):
    [(shape_type, parts)].

    Fault posture (docs/RESILIENCE.md, ``io.shapefile.read``): the file
    read retries in place on transient ``OSError`` (fd pressure, NFS
    blips — seeded RetryPolicy, ``geomesa.retry.*``); a file whose
    geometry records fail to parse is CORRUPTION and raises a typed
    ``ValueError`` naming the path — there is nothing to retry in broken
    bytes, the operator repairs or drops the file."""
    from geomesa_tpu import resilience

    base = path[:-4] if path.lower().endswith(".shp") else path

    def _read() -> bytes:
        resilience.fault_point("io.shapefile.read", path=base + ".shp")
        with open(base + ".shp", "rb") as f:
            return f.read()

    data = resilience.RetryPolicy.from_config(seed=0).call(
        _read, retryable=resilience.transient_os_error
    )
    try:
        return _parse_shp(data)
    except (struct.error, ValueError, IndexError) as e:
        raise ValueError(
            f"corrupt shapefile {base + '.shp'!r}: {type(e).__name__}: {e}"
        ) from e


def _parse_shp(data: bytes) -> List[Tuple[int, List[np.ndarray]]]:
    out = []
    pos = 100
    while pos < len(data):
        (_, words) = struct.unpack(">2i", data[pos:pos + 8])
        body = data[pos + 8:pos + 8 + words * 2]
        pos += 8 + words * 2
        (stype,) = struct.unpack("<i", body[:4])
        if stype == SHP_POINT:
            x, y = struct.unpack("<2d", body[4:20])
            out.append((stype, [np.array([[x, y]])]))
        elif stype == SHP_MULTIPOINT:
            (npts,) = struct.unpack("<i", body[36:40])
            pts = np.frombuffer(body[40:40 + 16 * npts], "<f8").reshape(-1, 2)
            out.append((stype, [pts.copy()]))
        else:
            nparts, npts = struct.unpack("<2i", body[36:44])
            part_idx = list(struct.unpack(f"<{nparts}i", body[44:44 + 4 * nparts]))
            pts = np.frombuffer(
                body[44 + 4 * nparts:44 + 4 * nparts + 16 * npts], "<f8"
            ).reshape(-1, 2)
            bounds = part_idx + [npts]
            parts = [
                pts[bounds[i]:bounds[i + 1]].copy() for i in range(nparts)
            ]
            out.append((stype, parts))
    return out
