"""ArrowDataStore analog: query and append GeoMesa-schema Arrow IPC files.

Reference parity: geomesa-arrow's ``ArrowDataStore``
(geomesa-arrow/geomesa-arrow-gt/src/main/scala/org/locationtech/geomesa/
arrow/data/ArrowDataStore.scala) exposes an Arrow IPC file — typically one
produced by an Arrow export — as a queryable, appendable feature store.
Here the file's batches are lazily hydrated into an in-process
:class:`~geomesa_tpu.api.dataset.GeoDataset`, so every query rides the
normal planner/executor stack (ECQL pushdown, density/stats kernels)
instead of a bespoke row loop; appends re-dictionary-encode against the
store and rewrite the file on :meth:`flush` (IPC files are immutable —
the reference's writable mode likewise rewrites/streams whole files).
"""

from __future__ import annotations

import os
import threading
import warnings
from typing import Dict, List, Optional

import numpy as np

from geomesa_tpu.schema.feature_type import FeatureType


class ArrowDataStore:
    """One Arrow IPC file as a feature store.

    >>> store = ArrowDataStore("/data/flights.arrow")
    >>> store.query("BBOX(geom, -10, 40, 5, 55)").n
    >>> store.append({...}, fids=[...]); store.flush()
    """

    def __init__(self, path: str, ft: Optional[FeatureType] = None,
                 create: bool = False):
        import pyarrow as pa  # noqa: F401  (hard dep of this module)

        self.path = path
        # reentrant: append() holds the lock across _dataset()
        self._lock = threading.RLock()
        self._ds = None
        self._dirty = False
        if not os.path.exists(path):
            if not create or ft is None:
                raise FileNotFoundError(
                    f"{path!r} does not exist (pass create=True and a "
                    "FeatureType to start a new store)"
                )
            self._ft = ft
            # a created-but-never-appended store must still flush its
            # (empty) file, or reopening it would raise FileNotFoundError
            self._dirty = True
        else:
            self._ft = ft  # None = infer from the file on first use

    # -- internals ---------------------------------------------------------
    def _read_ipc(self):
        """The store's IPC file, under the resilience contract
        (docs/RESILIENCE.md): the read is a named fault point
        (``io.arrow.read_ipc``) and transient ``OSError``s (fd pressure,
        an NFS blip) retry in place via the standard ``geomesa.retry.*``
        RetryPolicy — a missing file or real corruption raises
        immediately (retrying cannot heal either). The file's directory
        carries a circuit breaker (the remote-root treatment the lake
        tier standardized, docs/LAKE.md): a dead mount fences fast after
        repeated transient failures instead of re-walking the retry
        ladder on every open."""
        import os as _os

        from geomesa_tpu import resilience
        from geomesa_tpu.io import arrow_io

        def attempt():
            resilience.fault_point("io.arrow.read_ipc", path=self.path)
            return arrow_io.read_ipc(self.path)

        return resilience.guarded_root_io(
            _os.path.dirname(self.path),
            lambda: resilience.RetryPolicy.from_config().call(
                attempt,
                retryable=lambda e: isinstance(e, OSError)
                and not isinstance(e, FileNotFoundError),
                deadline=resilience.current_deadline(),
            ),
        )

    def _dataset(self):
        """Lazily hydrate the file into a GeoDataset (under the lock —
        an unlocked hydration racing an append could rebuild from the
        stale file and drop the appended rows on the next flush)."""
        with self._lock:
            if self._ds is not None:
                return self._ds
            from geomesa_tpu.api.dataset import GeoDataset

            ds = GeoDataset()
            if os.path.exists(self.path):
                table = self._read_ipc()
                if self._ft is None:
                    self._ft = _infer_feature_type(
                        os.path.splitext(os.path.basename(self.path))[0],
                        table,
                    )
                ds.create_schema(self._ft)
                if table.num_rows:
                    ds.ingest_arrow(self._ft.name, table)
                    ds.flush(self._ft.name)
            else:
                ds.create_schema(self._ft)
            self._ds = ds
            return ds

    @property
    def feature_type(self) -> FeatureType:
        self._dataset()
        return self._ft

    @property
    def name(self) -> str:
        return self.feature_type.name

    # -- reads (full planner/executor stack) -------------------------------
    def query(self, query="INCLUDE"):
        """``query``: ECQL text or a :class:`~geomesa_tpu.api.dataset.Query`
        (hints ride the Query object, as everywhere else)."""
        return self._dataset().query(self.name, query)

    def count(self, ecql: str = "INCLUDE") -> int:
        return self._dataset().count(self.name, ecql)

    def density(self, ecql: str = "INCLUDE", **kw):
        return self._dataset().density(self.name, ecql, **kw)

    def stats(self, stat: str, ecql: str = "INCLUDE"):
        return self._dataset().stats(self.name, stat, ecql)

    # -- writes ------------------------------------------------------------
    def append(self, data: Dict[str, np.ndarray], fids=None) -> int:
        """Buffer rows into the store (visible to queries immediately);
        :meth:`flush` persists them to the file."""
        with self._lock:
            ds = self._dataset()
            n = ds.insert(self.name, data, fids)
            ds.flush(self.name)
            self._dirty = True
            return n

    def flush(self):
        """Rewrite the IPC file with the store's current contents. The
        write is a named fault point (``io.arrow.write_ipc``); it is NOT
        retried — the tmp-then-replace sequence is not idempotent against
        a half-acknowledged rename, and a failed flush leaves the old
        complete file in place (re-flush at will: ``_dirty`` stays set)."""
        from geomesa_tpu import resilience

        with self._lock:
            if not self._dirty:
                return
            ds = self._dataset()
            tmp = self.path + ".tmp"
            resilience.fault_point("io.arrow.write_ipc", path=self.path)
            ds.export_arrow(self.name, tmp)
            os.replace(tmp, self.path)
            self._dirty = False

    def close(self):
        self.flush()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def _infer_feature_type(name: str, table) -> FeatureType:
    """Feature type of an Arrow table written by this framework: arrow_io
    embeds the exact spec string as schema metadata (``geomesa:spec``).
    Foreign Arrow files fall back to structural inference."""
    import pyarrow as pa

    md = table.schema.metadata or {}
    spec = md.get(b"geomesa:spec")
    if spec:
        return FeatureType.from_spec(name, spec.decode())
    from geomesa_tpu.io.arrow_io import FID

    parts: List[str] = []
    skipped: List[str] = []
    geom_done = False
    for field in table.schema:
        t = field.type
        if field.name == FID:
            continue
        if pa.types.is_fixed_size_list(t) and t.list_size == 2 \
                and not geom_done:
            parts.append(f"*{field.name}:Point:srid=4326")
            geom_done = True
        elif pa.types.is_timestamp(t):
            parts.append(f"{field.name}:Date")
        elif pa.types.is_dictionary(t) or pa.types.is_string(t) or \
                pa.types.is_large_string(t):
            parts.append(f"{field.name}:String")
        elif pa.types.is_integer(t):
            parts.append(
                f"{field.name}:Long" if t.bit_width == 64
                else f"{field.name}:Integer"
            )
        elif pa.types.is_floating(t):
            parts.append(
                f"{field.name}:Double" if t.bit_width == 64
                else f"{field.name}:Float"
            )
        elif pa.types.is_boolean(t):
            parts.append(f"{field.name}:Boolean")
        else:
            skipped.append(f"{field.name}:{t}")
    if skipped:
        warnings.warn(
            f"inferring a feature type for {name!r} (no geomesa:spec "
            f"metadata): skipped columns with unsupported Arrow types "
            f"{skipped}; their values will be absent from query results",
            stacklevel=2,
        )
    if not parts:
        raise ValueError(
            f"cannot infer a feature type from {name!r}: no recognized "
            "columns and no geomesa:spec metadata"
        )
    return FeatureType.from_spec(name, ",".join(parts))
