"""Radix pack-sort: the bulk-ingest sort engine.

Building a sorted index table needs an argsort of n keys per index — numpy's
``argsort`` runs at ~5-9M keys/s on one core, which caps ingest far below the
1B-point target (SURVEY.md §7(c)). But numpy's *value-only* ``np.sort`` on
uint64 is a radix sort at ~70M keys/s. This module exploits that by packing

    [ key bits (quantized) | row index bits ]

into a single uint64, value-sorting, then unpacking both the permutation and
the sorted (quantized) key column from the same array — no argsort, no
key-column gather. The stored key column is the *quantized* key; window
resolution quantizes its query bounds with the same shift, so searchsorted
windows stay supersets of the exact matches (the fine mask kernel restores
exactness — same contract as the reference's coarse row filters,
index/filters/Z3Filter.scala:18-62).

The trade: key precision is whatever fits in 64 bits after the row-index
bits (28 bits at 200M rows). A z3 key keeps ~11 bits/dim — cell occupancy at
that depth is a handful of rows, so the windows widen only at range edges.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

#: refuse to quantize a key below this many bits (fall back to argsort)
MIN_KEY_BITS = 16


def bits_for(n: int) -> int:
    """Bits needed to represent values 0..n-1 (at least 1)."""
    return max(1, int(n - 1).bit_length()) if n > 1 else 1


def to_ordered_u64(a: np.ndarray) -> Tuple[np.ndarray, int]:
    """Order-preserving map of a numeric column into uint64.

    Returns (u64 array, significant bits). int32/float32 map losslessly in
    32 bits; 64-bit types use their full width (callers quantize by
    shifting, which stays order-preserving / superset-safe)."""
    k = a.dtype.kind
    if a.dtype == np.int32:
        return (a.astype(np.int64) + 2**31).astype(np.uint64), 32
    if a.dtype == np.uint32:
        return a.astype(np.uint64), 32
    if a.dtype == np.int64:
        return (a.astype(np.uint64) + np.uint64(2**63)), 64
    if a.dtype == np.uint64:
        return a, 64
    if a.dtype == np.float32:
        b = a.view(np.uint32).astype(np.uint64)
        sign = (b >> np.uint64(31)).astype(bool)
        return np.where(sign, np.uint64(2**32 - 1) - b, b + np.uint64(2**31)), 33
    if a.dtype == np.float64:
        b = a.view(np.uint64)
        sign = (b >> np.uint64(63)).astype(bool)
        return np.where(sign, ~b, b | np.uint64(2**63)), 64
    if k == "b":
        return a.astype(np.uint64), 1
    if a.dtype == np.int16 or a.dtype == np.int8:
        return (a.astype(np.int64) + 2**15).astype(np.uint64), 16
    raise TypeError(f"no u64 ordering for dtype {a.dtype}")


def ordered_u64_scalar(v, dtype) -> int:
    """``to_ordered_u64`` for one query-bound scalar (window resolution).
    Out-of-range integer bounds clamp to the dtype's limits (still a
    superset: the fine filter applies the exact comparison)."""
    dt = np.dtype(dtype)
    if dt.kind in "iu" and not isinstance(v, float):
        info = np.iinfo(dt)
        v = min(max(int(v), info.min), info.max)
    out, _ = to_ordered_u64(np.asarray([v], dtype=dt))
    return int(out[0])


def pack_sort(
    key: np.ndarray,
    key_bits: int,
    prefix: Optional[np.ndarray] = None,
    tiebreak: Optional[np.ndarray] = None,
    tiebreak_bits: int = 0,
    force_shift: Optional[int] = None,
) -> Optional[Tuple[np.ndarray, np.ndarray, Optional[np.ndarray], int]]:
    """Sort rows by (prefix, key[, tiebreak]) via one packed radix sort.

    ``key``: uint64 with ``key_bits`` significant low bits (already
    order-mapped). ``prefix``: optional small int column (e.g. time bin)
    that sorts ahead of the key. ``tiebreak``: optional uint64 whose top
    bits order equal keys (locality only — not stored, not resolvable).
    ``force_shift`` pins the key quantization (LSM appends must match the
    existing table's stored keys); None picks the finest shift that fits.

    Returns (perm int32|int64, key_quantized_sorted uint64, prefix_sorted
    or None, key_shift) — or None when the bit budget leaves the key too
    coarse (< MIN_KEY_BITS), in which case the caller argsorts.
    """
    n = len(key)
    if n == 0:
        return None
    idx_bits = bits_for(n)
    if prefix is not None:
        pmin = int(prefix.min())
        pspan = int(prefix.max()) - pmin + 1
        prefix_bits = bits_for(pspan)
    else:
        pmin = 0
        prefix_bits = 0
    avail = 64 - idx_bits - prefix_bits
    if avail <= 0:
        return None
    shift = max(0, key_bits - avail) if force_shift is None else force_shift
    kq_bits = key_bits - shift
    if kq_bits < min(MIN_KEY_BITS, key_bits) or kq_bits > avail or kq_bits <= 0:
        return None
    spare = avail - kq_bits
    tb_bits = min(tiebreak_bits, spare) if tiebreak is not None else 0

    from geomesa_tpu import native

    L = native.lib()
    if L is not None:
        key = np.ascontiguousarray(key, np.uint64)
        packed = np.empty(n, np.uint64)
        tb = (
            np.ascontiguousarray(tiebreak, np.uint64) if tb_bits else None
        )
        pfx = (
            np.ascontiguousarray(prefix, np.int32) if prefix is not None else None
        )
        L.gm_pack_idx(
            key, n, shift, idx_bits, tb_bits,
            tb.ctypes.data if tb is not None else None,
            pfx.ctypes.data if pfx is not None else None,
            prefix_bits, pmin, packed,
        )
        # packed values are unique (row index in the low bits), so stability
        # is irrelevant. numpy's default introsort is AVX-vectorized and
        # beats scalar std::sort on one thread; the native parallel
        # mergesort wins when the host has cores to spare.
        if n > 2_000_000 and L.gm_num_threads() >= 4:
            L.gm_sort_u64(packed, n)
        else:
            packed.sort()
        small = n < 2**31
        perm = np.empty(n, np.int32 if small else np.int64)
        key_sorted = np.empty(n, np.uint64)
        prefix_sorted = (
            np.empty(n, np.int32) if prefix is not None else None
        )
        L.gm_unpack_idx(
            packed, n, kq_bits, idx_bits, tb_bits, prefix_bits, pmin,
            perm.ctypes.data if small else None,
            perm.ctypes.data if not small else None,
            key_sorted,
            prefix_sorted.ctypes.data if prefix_sorted is not None else None,
        )
        if prefix_sorted is not None:
            prefix_sorted = prefix_sorted.astype(prefix.dtype, copy=False)
        return perm, key_sorted, prefix_sorted, shift

    if prefix is not None:
        # subtract in int64 then reinterpret as u64 (values nonnegative)
        p64 = (prefix.astype(np.int64, copy=False) - np.int64(pmin)).view(np.uint64)
    else:
        p64 = None
    kq = key >> np.uint64(shift) if shift else key
    packed = kq << np.uint64(idx_bits + tb_bits)
    if tb_bits:
        packed |= (tiebreak >> np.uint64(64 - tb_bits)) << np.uint64(idx_bits)
    if p64 is not None:
        packed |= p64 << np.uint64(64 - prefix_bits)
    packed |= np.arange(n, dtype=np.uint64)
    packed.sort()
    perm = (packed & np.uint64((1 << idx_bits) - 1)).astype(
        np.int32 if n < 2**31 else np.int64
    )
    key_sorted = (packed >> np.uint64(idx_bits + tb_bits)) & np.uint64(
        (1 << kq_bits) - 1
    )
    if p64 is not None:
        prefix_sorted = (
            (packed >> np.uint64(64 - prefix_bits)).view(np.int64) + np.int64(pmin)
        ).astype(prefix.dtype, copy=False)
    else:
        prefix_sorted = None
    return perm, key_sorted, prefix_sorted, shift


_HASH_PRIMES = np.array(
    [
        0x9E3779B97F4A7C15, 0xC2B2AE3D27D4EB4F, 0x165667B19E3779F9,
        0x27D4EB2F165667C5, 0x85EBCA77C2B2AE63, 0xFF51AFD7ED558CCD,
        0xC4CEB9FE1A85EC53, 0x2545F4914F6CDD1D,
    ],
    dtype=np.uint64,
)


def fid_hash64(fids: np.ndarray) -> np.ndarray:
    """Vectorized order-free 64-bit hash of a string/bytes column.

    Character bytes are NUL-padded to 8-byte chunks and mixed as
    ``XOR_j(chunk_j * prime_j)`` + avalanche — a handful of vector passes
    regardless of string width, and width-independent (zero chunks
    contribute zero, so the same fid hashes identically from U7 and U32
    columns). Used as the id-index sort key: lookups hash the query ids the
    same way; collisions are resolved by the exact fid equality mask (IdIn)
    on the window rows."""
    a = np.asarray(fids)
    if a.dtype.kind == "O":
        a = a.astype(str)
    if a.dtype.kind == "U":
        # canonical hash layout is ALWAYS the UTF-8 byte ('S') form: the
        # store keeps fid columns as 'S' when ASCII (columns.encode_fids),
        # and a query-time hash of the same fid must land in the same
        # bucket whatever array layout it arrived in — including mixed
        # ASCII/non-ASCII batches, where content-dependent layouts would
        # make the same ASCII fid hash two different ways
        from geomesa_tpu.schema.columns import _u_to_s

        a = _u_to_s(a)
        if a.dtype.kind == "U":  # non-ASCII present: per-element UTF-8
            a = np.char.encode(a, "utf-8")
    if a.dtype.kind == "S":
        w = a.dtype.itemsize
    else:
        raise TypeError(f"fid hash needs a string column, got {a.dtype}")
    from geomesa_tpu import native

    out = native.fid_hash64(a)
    if out is not None:
        return out
    n = len(a)
    k = (w + 7) // 8
    m = np.zeros((n, k * 8), np.uint8)
    m[:, :w] = np.frombuffer(a.tobytes(), dtype=np.uint8).reshape(n, w)
    q = m.view(np.uint64)
    h = np.zeros(n, np.uint64)
    for j in range(k):
        h ^= q[:, j] * _HASH_PRIMES[j % 8]
    # avalanche so quantized top bits spread (the table stores h >> shift)
    h ^= h >> np.uint64(33)
    h *= np.uint64(0xFF51AFD7ED558CCD)
    h ^= h >> np.uint64(29)
    return h


def fid_hash64_one(fid: str) -> int:
    """Scalar counterpart of :func:`fid_hash64` (query-time lookups)."""
    return int(fid_hash64(np.asarray([fid]))[0])
