"""Key spaces: feature batch -> sort keys (ingest) and filter -> scan windows
(plan time).

Reference parity (SURVEY.md §2.4):

* ``Z3KeySpace``   ~ Z3IndexKeySpace (Z3Index): point geom + time
* ``Z2KeySpace``   ~ Z2IndexKeySpace (Z2Index): point geom
* ``XZ3KeySpace``  ~ XZ3IndexKeySpace: extent geom + time
* ``XZ2KeySpace``  ~ XZ2IndexKeySpace: extent geom
* ``IdKeySpace``   ~ IdIndex: feature id lookups
* ``AttributeKeySpace`` ~ AttributeIndex: per-attribute sorted index

The TPU translation of "byte ranges": each key space can compute, per shard
and per query, a set of **(start, end) row windows** into that shard's sorted
arrays via ``searchsorted`` — the slice-descriptor model (SURVEY.md §1). The
fine-grained z-ranges additionally drive selectivity estimation.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from geomesa_tpu import config
from geomesa_tpu.curves.binned_time import BinnedTime, TimePeriod
from geomesa_tpu.curves.cover import ZRange
from geomesa_tpu.curves.xz import XZ2SFC, XZ3SFC
from geomesa_tpu.curves.zorder import Z2SFC, Z3SFC, split_u64
from geomesa_tpu.filter import ir
from geomesa_tpu.index import packsort
from geomesa_tpu.schema.columns import ColumnBatch
from geomesa_tpu.schema.feature_type import FeatureType

MAX_WINDOW_BINS = 64  # collapse per-bin windows beyond this many time bins


@dataclass
class KeyPlan:
    """Plan-time product of a key space for one query (IndexValues+ranges
    analog). ``windows(shard_cols)`` resolves to row windows per shard."""

    keyspace: "KeySpace"
    #: provably empty (disjoint bounds)
    disjoint: bool = False
    #: full scan (no key constraint)
    full_scan: bool = False
    #: z-ranges for selectivity estimation (may be empty for full scans)
    ranges: List[ZRange] = field(default_factory=list)
    #: time bins touched (z3/xz3)
    bins: Optional[np.ndarray] = None
    #: estimated fraction of key space covered (coarse; cost input)
    coverage: float = 1.0

    def windows(self, shard_cols: Dict[str, np.ndarray], n: int) -> Tuple[np.ndarray, np.ndarray]:
        """Resolve to (starts, ends) row windows for one shard's host key
        columns (each sorted). ``n`` = row count of the shard."""
        if self.disjoint:
            return np.zeros(1, np.int64), np.zeros(1, np.int64)
        if self.full_scan:
            return np.zeros(1, np.int64), np.full(1, n, np.int64)
        return self.keyspace.resolve_windows(self, shard_cols, n)


class KeySpace:
    name: str = "base"   # unique per instance (table key)
    kind: str = "base"   # family (cost model / dispatch key)
    key_cols: Sequence[str] = ()

    def supports(self, ft: FeatureType) -> bool:
        raise NotImplementedError

    def index_keys(self, ft: FeatureType, batch: ColumnBatch) -> Dict[str, np.ndarray]:
        """Vectorized key encode for an ingest batch (toIndexKey analog)."""
        raise NotImplementedError

    def sort_order(self, cols: Dict[str, np.ndarray]) -> np.ndarray:
        """argsort for the table's global sort (primary last in lexsort)."""
        raise NotImplementedError

    def fast_build(
        self,
        cols: Dict[str, np.ndarray],
        force_shifts: Optional[Dict[str, int]] = None,
    ) -> Optional[tuple]:
        """Radix pack-sort build (packsort module): returns
        (order, sorted_key_columns, shifts) — key columns QUANTIZED by
        ``shifts`` — or None to fall back to :meth:`sort_order` + gather.
        ``force_shifts`` pins the quantization to an existing table's
        (LSM-append compatibility)."""
        return None

    def plan(self, ft: FeatureType, f: ir.Filter) -> Optional[KeyPlan]:
        """None if this key space cannot serve the filter at all."""
        raise NotImplementedError

    def resolve_windows(self, plan: KeyPlan, shard_cols, n: int):
        raise NotImplementedError

    #: False when appends must always fully rebuild (checked BEFORE any
    #: fresh-batch sorting so the probe costs nothing)
    can_insert = True

    def insert_positions(
        self,
        sorted_key_cols: Dict[str, np.ndarray],
        fresh_sorted: Dict[str, np.ndarray],
    ) -> Optional[np.ndarray]:
        """Merge positions of already-sorted fresh keys into the existing
        sorted key columns — the LSM append path (O(old + fresh) instead of a
        full re-sort). Generic: single key column -> one searchsorted;
        (bin, key) pairs -> per-bin two-level searchsorted. Returns None when
        this key space needs a full rebuild (e.g. rank vocabularies)."""
        cols = list(self.key_cols)
        if len(cols) == 1:
            k = cols[0]
            return np.searchsorted(
                sorted_key_cols[k], fresh_sorted[k], side="right"
            ).astype(np.int64)
        if len(cols) == 2:  # (bin, key): z3/xz3/s3 layouts
            bc, kc = cols
            bins_col = sorted_key_cols[bc]
            key_col = sorted_key_cols[kc]
            fb = fresh_sorted[bc]
            fk = fresh_sorted[kc]
            p = np.empty(len(fb), np.int64)
            for b in np.unique(fb):
                sel = fb == b
                s = int(np.searchsorted(bins_col, b, side="left"))
                e = int(np.searchsorted(bins_col, b, side="right"))
                p[sel] = s + np.searchsorted(
                    key_col[s:e], fk[sel], side="right"
                )
            return p
        return None


def _bin_and_offset(binned: BinnedTime, ft: FeatureType, dtg: str, batch):
    """(bin, offset_ms) for an ingest batch, reusing the ``<dtg>__bin``
    column encode_batch already computed (same period as the schema's key
    spaces) — saves a second floor-division pass over the timestamps."""
    bin_col = dtg + "__bin"
    if bin_col in batch and ft.time_period == binned.period:
        b = batch[bin_col]
        return b, binned.offset_from_bin(batch[dtg], b)
    return binned.to_bin_and_offset(batch[dtg])


#: per-shard budget for resolved scan windows (bins x z-ranges); beyond it
#: ranges gap-union down (over-cover; the fine filter restores exactness)
MAX_SHARD_WINDOWS = 256

_window_cap_tls = __import__("threading").local()


def shard_window_cap() -> int:
    """Active per-shard window budget. The compacted scan path raises it
    (``window_cap``) to resolve gap-union-free windows: scan cost there is
    per admitted ROW, not per window, so fine windows are strictly
    better — tighter chunk spatial boxes and fewer false-positive rows."""
    return getattr(_window_cap_tls, "cap", None) or MAX_SHARD_WINDOWS


class window_cap:
    """Context manager scoping a raised shard-window budget."""

    def __init__(self, cap: int):
        self.cap = cap

    def __enter__(self):
        self.prev = getattr(_window_cap_tls, "cap", None)
        _window_cap_tls.cap = self.cap
        return self

    def __exit__(self, *exc):
        _window_cap_tls.cap = self.prev


def _merge_cap(los: np.ndarray, his: np.ndarray, cap: int,
               adjacent: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """One vectorized pass shared by range- and window-capping: sort, merge
    overlapping (or within ``adjacent``) intervals, then keep only the
    ``cap-1`` LARGEST gaps as separators (equivalent to repeatedly unioning
    the smallest gap, without the quadratic loop). Over-covers; the fine
    filter restores exactness (Z3Filter.scala keeps every window; here the
    kernel's window count is a static shape, so a budget applies)."""
    if len(los) == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    order = np.argsort(los, kind="stable")
    los = np.asarray(los, np.int64)[order]
    his = np.asarray(his, np.int64)[order]
    # merge overlapping/adjacent: a new interval starts where lo exceeds
    # the running max of prior his (+adjacency)
    run_hi = np.maximum.accumulate(his)
    new = np.concatenate(([True], los[1:] > run_hi[:-1] + adjacent))
    idx = np.flatnonzero(new)
    mlo = los[idx]
    mhi = run_hi[np.concatenate((idx[1:] - 1, [len(los) - 1]))]
    if len(mlo) > cap:
        gaps = mlo[1:] - mhi[:-1]
        keep = np.sort(np.argpartition(gaps, -(cap - 1))[-(cap - 1):]) \
            if cap > 1 else np.zeros(0, np.int64)
        mlo = np.concatenate((mlo[:1], mlo[keep + 1]))
        mhi = np.concatenate((mhi[keep], mhi[-1:]))
    return mlo, mhi


def _merge_zranges(ranges: List[Tuple[int, int]], cap: int) -> List[Tuple[int, int]]:
    """Tuple-list façade over :func:`_merge_cap` (adjacency 1: integer key
    ranges touching end-to-end fuse)."""
    if not ranges:
        return []
    los = np.asarray([r[0] for r in ranges], np.int64)
    his = np.asarray([r[1] for r in ranges], np.int64)
    mlo, mhi = _merge_cap(los, his, cap, adjacent=1)
    return list(zip(mlo.tolist(), mhi.tolist()))


def _per_geom_ranges(cover_fn, bounds_list) -> List[ZRange]:
    """Cover each query geometry's bounds separately and merge — disjoint
    bboxes get disjoint covers instead of one envelope cover (reference
    FilterHelper.extractGeometries feeds per-geometry ranges the same way)."""
    all_r: List[Tuple[int, int]] = []
    for b in bounds_list:
        for r in cover_fn(b):
            all_r.append((int(r.lo), int(r.hi)))
    merged = _merge_zranges(all_r, config.SCAN_RANGES_TARGET.to_int() or 2000)
    return [ZRange(lo, hi) for lo, hi in merged]


def _shift_of(shard_cols: Dict, col: str) -> int:
    """Quantization shift of a stored key column (0 on the argsort path).
    Bounds must be shifted identically before searchsorted — floor on both
    sides keeps windows supersets (side='right' then covers the whole
    quantized cell of the upper bound)."""
    shifts = shard_cols.get("__shifts__")
    return 0 if shifts is None else shifts.get(col, 0)


def _coverage(ranges: List[ZRange], total_bits: int) -> float:
    span = sum(r.hi - r.lo + 1 for r in ranges)
    return span / float(1 << total_bits)


class Z3KeySpace(KeySpace):
    """(bin, z3) keys over point geometry + time (reference
    Z3IndexKeySpace.scala:64-233)."""

    name = "z3"
    kind = "z3"

    def __init__(self, geom: str, dtg: str, period: "str | TimePeriod" = TimePeriod.WEEK):
        self.geom = geom
        self.dtg = dtg
        self.sfc = Z3SFC(period)
        self.binned = self.sfc.binned
        self.key_cols = ("__z3_bin", "__z3")

    def supports(self, ft):
        return (
            ft.has(self.geom) and ft.attr(self.geom).is_point
            and ft.has(self.dtg) and ft.attr(self.dtg).type == "date"
        )

    def index_keys(self, ft, batch):
        xs = batch[self.geom + "__x"]
        ys = batch[self.geom + "__y"]
        b, off = _bin_and_offset(self.binned, ft, self.dtg, batch)
        z = self.sfc.index(xs, ys, off)
        return {"__z3_bin": np.asarray(b, np.int32), "__z3": z}

    def sort_order(self, cols):
        return np.lexsort((cols["__z3"], cols["__z3_bin"]))

    def fast_build(self, cols, force_shifts=None):
        fs = None if force_shifts is None else force_shifts.get("__z3")
        out = packsort.pack_sort(
            cols["__z3"], 63, prefix=cols["__z3_bin"], force_shift=fs
        )
        if out is None:
            return None
        perm, zq, bins_sorted, shift = out
        return perm, {"__z3_bin": bins_sorted, "__z3": zq}, {"__z3": shift}

    def plan(self, ft, f):
        geoms = ir.extract_geometries(f, self.geom)
        intervals = ir.extract_intervals(f, self.dtg)
        if geoms.disjoint or intervals.disjoint:
            return KeyPlan(self, disjoint=True)
        if intervals.is_empty:
            return None  # no temporal bound: z3 not applicable (reference same)
        # Clamp intervals into representable time.
        CLAMP = 2**45
        iv = [(max(lo, -CLAMP), min(hi, CLAMP)) for lo, hi in intervals.values]
        bins = np.unique(
            np.concatenate([self.binned.bins_between(lo, hi) for lo, hi in iv])
        )
        max_off = float(self.binned.max_offset_ms)
        if geoms.is_empty:
            xy = [((-180.0, -90.0, 180.0, 90.0))]
        else:
            xy = [g.bounds() for g in geoms.values]
        # Per-geometry covers over the full offset span (middle bins);
        # disjoint query boxes produce disjoint range sets (Z3Filter.scala
        # checks every window per row — here every window becomes its own
        # scan window at resolve time).
        ranges = _per_geom_ranges(
            lambda b: self.sfc.ranges(
                (b[0], b[2]), (b[1], b[3]), (0.0, max_off)
            ),
            xy,
        )
        # Edge-bin time tightening (Z3IndexKeySpace.getIndexValues:133-158:
        # per-bin offset windows): the first/last bin of each interval gets
        # its own cover restricted to the interval's offsets in that bin.
        edge: Dict[int, List[Tuple[int, int]]] = {}
        for lo, hi in iv:
            blo, olo = self.binned.to_bin_and_offset(np.asarray([lo], np.int64))
            bhi, ohi = self.binned.to_bin_and_offset(np.asarray([hi], np.int64))
            blo, olo = int(blo[0]), float(olo[0])
            bhi, ohi = int(bhi[0]), float(ohi[0])
            for b, off_lo, off_hi in (
                ((blo, olo, max_off if blo != bhi else ohi),)
                + (((bhi, 0.0, ohi),) if bhi != blo else ())
            ):
                rs = [
                    (int(r.lo), int(r.hi))
                    for box in xy
                    for r in self.sfc.ranges(
                        (box[0], box[2]), (box[1], box[3]), (off_lo, off_hi)
                    )
                ]
                edge.setdefault(b, []).extend(rs)
        cov = _coverage(ranges, 63) * min(1.0, len(bins) / max(len(bins), 1))
        plan = KeyPlan(self, ranges=ranges, bins=bins.astype(np.int32), coverage=cov)
        plan._iv = iv
        plan._edge = {
            b: _merge_zranges(rs, config.SCAN_RANGES_TARGET.to_int() or 2000)
            for b, rs in edge.items()
        }
        return plan

    def resolve_windows(self, plan, shard_cols, n):
        bins_col = shard_cols["__z3_bin"]
        z_col = shard_cols["__z3"]
        sh = _shift_of(shard_cols, "__z3")
        bins = plan.bins
        if len(bins) > MAX_WINDOW_BINS:
            # collapse: one window spanning [first bin, last bin]
            s = np.searchsorted(bins_col, bins[0], side="left")
            e = np.searchsorted(bins_col, bins[-1], side="right")
            return np.asarray([s], np.int64), np.asarray([e], np.int64)
        # Per-window pushdown (Z3Filter.scala:18-62 parity): every cover
        # range resolves to its own scan window per bin — disjoint or
        # L-shaped query geometries admit only their own candidates, not
        # the [zmin, zmax] envelope. Edge bins use their time-tightened
        # range sets from plan time. The shifted+merged range sets are
        # shard-independent: computed once per (plan, shift) and cached.
        edge = getattr(plan, "_edge", {})
        cap = shard_window_cap()
        per_bin_cap = max(1, cap // max(len(bins), 1))
        cache = plan.__dict__.setdefault("_shifted_ranges", {})
        sets = cache.get((sh, cap))
        if sets is None:
            base = _merge_zranges(
                [(r.lo >> sh, r.hi >> sh) for r in plan.ranges], per_bin_cap
            )
            esets = {
                b: _merge_zranges(
                    [(lo >> sh, hi >> sh) for lo, hi in rs], per_bin_cap
                )
                for b, rs in edge.items()
            }
            sets = cache[(sh, cap)] = (base, esets)
        base, esets = sets
        from geomesa_tpu import native

        starts: List[int] = []
        ends: List[int] = []
        plain = np.asarray(
            [b for b in bins.tolist() if b not in esets], np.int32
        )
        for lo, hi in base:
            ws, we = native.bin_windows(bins_col, z_col, plain, lo, hi)
            starts.extend(ws.tolist())
            ends.extend(we.tolist())
        for b, rs in esets.items():
            s = int(np.searchsorted(bins_col, b, side="left"))
            e = int(np.searchsorted(bins_col, b, side="right"))
            if e <= s or not rs:
                continue
            seg = z_col[s:e]
            los = np.asarray([r[0] for r in rs], seg.dtype)
            his = np.asarray([r[1] for r in rs], seg.dtype)
            ws = s + np.searchsorted(seg, los, side="left")
            we = s + np.searchsorted(seg, his, side="right")
            keep = we > ws
            starts.extend(ws[keep].tolist())
            ends.extend(we[keep].tolist())
        if not starts:
            return np.zeros(1, np.int64), np.zeros(1, np.int64)
        return _cap_windows(
            np.asarray(starts, np.int64), np.asarray(ends, np.int64),
            shard_window_cap(),
        )


class Z2KeySpace(KeySpace):
    """z2 keys over point geometry (reference Z2IndexKeySpace)."""

    name = "z2"
    kind = "z2"

    def __init__(self, geom: str):
        self.geom = geom
        self.sfc = Z2SFC()
        self.key_cols = ("__z2",)

    def supports(self, ft):
        return ft.has(self.geom) and ft.attr(self.geom).is_point

    def index_keys(self, ft, batch):
        return {"__z2": self.sfc.index(batch[self.geom + "__x"], batch[self.geom + "__y"])}

    def sort_order(self, cols):
        return np.argsort(cols["__z2"], kind="stable")

    def fast_build(self, cols, force_shifts=None):
        fs = None if force_shifts is None else force_shifts.get("__z2")
        out = packsort.pack_sort(cols["__z2"], 62, force_shift=fs)
        if out is None:
            return None
        perm, zq, _, shift = out
        return perm, {"__z2": zq}, {"__z2": shift}

    def plan(self, ft, f):
        geoms = ir.extract_geometries(f, self.geom)
        if geoms.disjoint:
            return KeyPlan(self, disjoint=True)
        if geoms.is_empty:
            return KeyPlan(self, full_scan=True)
        ranges = _per_geom_ranges(
            lambda b: self.sfc.ranges(*b),
            [g.bounds() for g in geoms.values],
        )
        return KeyPlan(self, ranges=ranges, coverage=_coverage(ranges, 62))

    def resolve_windows(self, plan, shard_cols, n):
        # per-range windows (Z2Filter parity): disjoint query boxes scan
        # only their own covers, not the [zmin, zmax] envelope
        z_col = shard_cols["__z2"]
        sh = _shift_of(shard_cols, "__z2")
        rs = _merge_zranges(
            [(r.lo >> sh, r.hi >> sh) for r in plan.ranges], shard_window_cap()
        )
        if not rs:
            return np.zeros(1, np.int64), np.zeros(1, np.int64)
        los = np.asarray([r[0] for r in rs], z_col.dtype)
        his = np.asarray([r[1] for r in rs], z_col.dtype)
        ws = np.searchsorted(z_col, los, side="left")
        we = np.searchsorted(z_col, his, side="right")
        keep = we > ws
        if not keep.any():
            return np.zeros(1, np.int64), np.zeros(1, np.int64)
        return _cap_windows(
            ws[keep].astype(np.int64), we[keep].astype(np.int64),
            shard_window_cap(),
        )


class XZ2KeySpace(KeySpace):
    """xz2 codes over extent geometries (reference XZ2IndexKeySpace)."""

    name = "xz2"
    kind = "xz2"

    def __init__(self, geom: str, g: int = 12):
        self.geom = geom
        self.sfc = XZ2SFC(g=g)
        self.key_cols = ("__xz2",)

    def supports(self, ft):
        a = ft.attr(self.geom) if ft.has(self.geom) else None
        return a is not None and a.is_geom and not a.is_point

    def index_keys(self, ft, batch):
        return {
            "__xz2": self.sfc.index(
                batch[self.geom + "__xmin"], batch[self.geom + "__ymin"],
                batch[self.geom + "__xmax"], batch[self.geom + "__ymax"],
            )
        }

    def sort_order(self, cols):
        return np.argsort(cols["__xz2"], kind="stable")

    def fast_build(self, cols, force_shifts=None):
        fs = None if force_shifts is None else force_shifts.get("__xz2")
        code = cols["__xz2"].astype(np.uint64)  # sequence codes, nonnegative
        bits = int(self.sfc.subtree_size[0]).bit_length()
        out = packsort.pack_sort(code, bits, force_shift=fs)
        if out is None:
            return None
        perm, cq, _, shift = out
        return perm, {"__xz2": cq}, {"__xz2": shift}

    def plan(self, ft, f):
        geoms = ir.extract_geometries(f, self.geom)
        if geoms.disjoint:
            return KeyPlan(self, disjoint=True)
        if geoms.is_empty:
            return KeyPlan(self, full_scan=True)
        bs = np.asarray([g.bounds() for g in geoms.values])
        bbox = (bs[:, 0].min(), bs[:, 1].min(), bs[:, 2].max(), bs[:, 3].max())
        ranges = self.sfc.ranges(*bbox)
        total = self.sfc.subtree_size[0]
        span = sum(r.hi - r.lo + 1 for r in ranges)
        return KeyPlan(self, ranges=ranges, coverage=span / total)

    def resolve_windows(self, plan, shard_cols, n):
        # XZ ranges are NOT contiguous-envelope friendly (singleton parent
        # codes interleave) — resolve each merged range to a window.
        col = shard_cols["__xz2"]
        sh = _shift_of(shard_cols, "__xz2")
        starts, ends = [], []
        for r in plan.ranges:
            s = np.searchsorted(col, r.lo >> sh, side="left")
            e = np.searchsorted(col, r.hi >> sh, side="right")
            if e > s:
                starts.append(s)
                ends.append(e)
        if not starts:
            return np.zeros(1, np.int64), np.zeros(1, np.int64)
        # cap window count: merge down to MAX_WINDOW_BINS by unioning gaps
        return _cap_windows(
            np.asarray(starts, np.int64), np.asarray(ends, np.int64), MAX_WINDOW_BINS
        )


class XZ3KeySpace(KeySpace):
    """(bin, xz3) codes over extent geometries + time (reference XZ3IndexKeySpace)."""

    name = "xz3"
    kind = "xz3"

    def __init__(self, geom: str, dtg: str, period: "str | TimePeriod" = TimePeriod.WEEK, g: int = 12):
        self.geom = geom
        self.dtg = dtg
        self.sfc = XZ3SFC(period, g=g)
        self.binned = self.sfc.binned
        self.key_cols = ("__xz3_bin", "__xz3")

    def supports(self, ft):
        a = ft.attr(self.geom) if ft.has(self.geom) else None
        return (
            a is not None and a.is_geom and not a.is_point
            and ft.has(self.dtg) and ft.attr(self.dtg).type == "date"
        )

    def index_keys(self, ft, batch):
        b, off = _bin_and_offset(self.binned, ft, self.dtg, batch)
        code = self.sfc.index(
            batch[self.geom + "__xmin"], batch[self.geom + "__ymin"], off,
            batch[self.geom + "__xmax"], batch[self.geom + "__ymax"], off,
        )
        return {"__xz3_bin": np.asarray(b, np.int32), "__xz3": code}

    def sort_order(self, cols):
        return np.lexsort((cols["__xz3"], cols["__xz3_bin"]))

    def fast_build(self, cols, force_shifts=None):
        fs = None if force_shifts is None else force_shifts.get("__xz3")
        bits = int(self.sfc.subtree_size[0]).bit_length()
        out = packsort.pack_sort(
            cols["__xz3"].astype(np.uint64), bits,
            prefix=cols["__xz3_bin"], force_shift=fs,
        )
        if out is None:
            return None
        perm, cq, bins_sorted, shift = out
        return perm, {"__xz3_bin": bins_sorted, "__xz3": cq}, {"__xz3": shift}

    def plan(self, ft, f):
        geoms = ir.extract_geometries(f, self.geom)
        intervals = ir.extract_intervals(f, self.dtg)
        if geoms.disjoint or intervals.disjoint:
            return KeyPlan(self, disjoint=True)
        if intervals.is_empty:
            return None
        CLAMP = 2**45
        iv = [(max(lo, -CLAMP), min(hi, CLAMP)) for lo, hi in intervals.values]
        bins = np.unique(
            np.concatenate([self.binned.bins_between(lo, hi) for lo, hi in iv])
        )
        if geoms.is_empty:
            bbox = (-180.0, -90.0, 180.0, 90.0)
        else:
            bs = np.asarray([g.bounds() for g in geoms.values])
            bbox = (bs[:, 0].min(), bs[:, 1].min(), bs[:, 2].max(), bs[:, 3].max())
        ranges = self.sfc.ranges(
            (bbox[0], bbox[2]), (bbox[1], bbox[3]),
            (0.0, float(self.binned.max_offset_ms)),
        )
        total = self.sfc.subtree_size[0]
        span = sum(r.hi - r.lo + 1 for r in ranges)
        return KeyPlan(self, ranges=ranges, bins=bins.astype(np.int32), coverage=span / total)

    def resolve_windows(self, plan, shard_cols, n):
        bins_col = shard_cols["__xz3_bin"]
        code_col = shard_cols["__xz3"]
        sh = _shift_of(shard_cols, "__xz3")
        bins = plan.bins
        if len(bins) > 8:  # xz windows multiply per bin; collapse earlier
            s = np.searchsorted(bins_col, bins[0], side="left")
            e = np.searchsorted(bins_col, bins[-1], side="right")
            return np.asarray([s], np.int64), np.asarray([e], np.int64)
        starts, ends = [], []
        for b in bins.tolist():
            s = np.searchsorted(bins_col, b, side="left")
            e = np.searchsorted(bins_col, b, side="right")
            if e <= s:
                continue
            seg = code_col[s:e]
            for r in plan.ranges:
                s2 = s + np.searchsorted(seg, r.lo >> sh, side="left")
                e2 = s + np.searchsorted(seg, r.hi >> sh, side="right")
                if e2 > s2:
                    starts.append(s2)
                    ends.append(e2)
        if not starts:
            return np.zeros(1, np.int64), np.zeros(1, np.int64)
        return _cap_windows(
            np.asarray(starts, np.int64), np.asarray(ends, np.int64), MAX_WINDOW_BINS
        )


class S2KeySpace(KeySpace):
    """S2 cell-id keys over point geometry (reference S2Index / S2SFC.scala:17,
    which wraps Google S2; cell math in geomesa_tpu.curves.s2)."""

    name = "s2"
    kind = "s2"

    def __init__(self, geom: str):
        self.geom = geom
        from geomesa_tpu.curves.s2 import S2SFC

        self.sfc = S2SFC(max_cells=64)
        self.key_cols = ("__s2",)

    def supports(self, ft):
        return ft.has(self.geom) and ft.attr(self.geom).is_point

    def index_keys(self, ft, batch):
        return {
            "__s2": self.sfc.index(batch[self.geom + "__x"], batch[self.geom + "__y"])
        }

    def sort_order(self, cols):
        return np.argsort(cols["__s2"], kind="stable")

    def fast_build(self, cols, force_shifts=None):
        fs = None if force_shifts is None else force_shifts.get("__s2")
        out = packsort.pack_sort(cols["__s2"], 64, force_shift=fs)
        if out is None:
            return None
        perm, cq, _, shift = out
        return perm, {"__s2": cq}, {"__s2": shift}

    def plan(self, ft, f):
        geoms = ir.extract_geometries(f, self.geom)
        if geoms.disjoint:
            return KeyPlan(self, disjoint=True)
        if geoms.is_empty:
            return KeyPlan(self, full_scan=True)
        bs = np.asarray([g.bounds() for g in geoms.values])
        bbox = (bs[:, 0].min(), bs[:, 1].min(), bs[:, 2].max(), bs[:, 3].max())
        ranges = self.sfc.ranges(*bbox)
        span = sum(r.hi - r.lo + 1 for r in ranges)
        return KeyPlan(self, ranges=ranges, coverage=span / float(6 << 60))

    def resolve_windows(self, plan, shard_cols, n):
        col = shard_cols["__s2"]
        sh = _shift_of(shard_cols, "__s2")
        starts, ends = [], []
        for r in plan.ranges:
            s = np.searchsorted(col, np.uint64(r.lo >> sh), side="left")
            e = np.searchsorted(col, np.uint64(r.hi >> sh), side="right")
            if e > s:
                starts.append(s)
                ends.append(e)
        if not starts:
            return np.zeros(1, np.int64), np.zeros(1, np.int64)
        return _cap_windows(
            np.asarray(starts, np.int64), np.asarray(ends, np.int64), MAX_WINDOW_BINS
        )


class S3KeySpace(KeySpace):
    """(time bin, S2 cell id) keys: the reference's S3Index (S2 space +
    BinnedTime period bins)."""

    name = "s3"
    kind = "s3"

    def __init__(self, geom: str, dtg: str, period: "str | TimePeriod" = TimePeriod.WEEK):
        self.geom = geom
        self.dtg = dtg
        from geomesa_tpu.curves.s2 import S2SFC

        self.sfc = S2SFC(max_cells=64)
        self.binned = BinnedTime(period)
        self.key_cols = ("__s3_bin", "__s3")

    def supports(self, ft):
        return (
            ft.has(self.geom) and ft.attr(self.geom).is_point
            and ft.has(self.dtg) and ft.attr(self.dtg).type == "date"
        )

    def index_keys(self, ft, batch):
        bin_col = self.dtg + "__bin"
        if bin_col in batch and ft.time_period == self.binned.period:
            b = batch[bin_col]
        else:
            b, _ = self.binned.to_bin_and_offset(batch[self.dtg])
        return {
            "__s3_bin": np.asarray(b, np.int32),
            "__s3": self.sfc.index(batch[self.geom + "__x"], batch[self.geom + "__y"]),
        }

    def sort_order(self, cols):
        return np.lexsort((cols["__s3"], cols["__s3_bin"]))

    def fast_build(self, cols, force_shifts=None):
        fs = None if force_shifts is None else force_shifts.get("__s3")
        out = packsort.pack_sort(
            cols["__s3"], 64, prefix=cols["__s3_bin"], force_shift=fs
        )
        if out is None:
            return None
        perm, cq, bins_sorted, shift = out
        return perm, {"__s3_bin": bins_sorted, "__s3": cq}, {"__s3": shift}

    def plan(self, ft, f):
        geoms = ir.extract_geometries(f, self.geom)
        intervals = ir.extract_intervals(f, self.dtg)
        if geoms.disjoint or intervals.disjoint:
            return KeyPlan(self, disjoint=True)
        if intervals.is_empty:
            return None
        CLAMP = 2**45
        iv = [(max(lo, -CLAMP), min(hi, CLAMP)) for lo, hi in intervals.values]
        bins = np.unique(
            np.concatenate([self.binned.bins_between(lo, hi) for lo, hi in iv])
        )
        if geoms.is_empty:
            return KeyPlan(self, ranges=[], bins=bins.astype(np.int32), coverage=1.0)
        bs = np.asarray([g.bounds() for g in geoms.values])
        bbox = (bs[:, 0].min(), bs[:, 1].min(), bs[:, 2].max(), bs[:, 3].max())
        ranges = self.sfc.ranges(*bbox)
        span = sum(r.hi - r.lo + 1 for r in ranges)
        cov = span / float(6 << 60)
        return KeyPlan(self, ranges=ranges, bins=bins.astype(np.int32), coverage=cov)

    def resolve_windows(self, plan, shard_cols, n):
        bins_col = shard_cols["__s3_bin"]
        col = shard_cols["__s3"]
        sh = _shift_of(shard_cols, "__s3")
        bins = plan.bins
        if len(bins) > 8 or not plan.ranges:
            s = np.searchsorted(bins_col, bins[0], side="left")
            e = np.searchsorted(bins_col, bins[-1], side="right")
            return np.asarray([s], np.int64), np.asarray([e], np.int64)
        starts, ends = [], []
        for b in bins.tolist():
            s = np.searchsorted(bins_col, b, side="left")
            e = np.searchsorted(bins_col, b, side="right")
            if e <= s:
                continue
            seg = col[s:e]
            for r in plan.ranges:
                s2_ = s + np.searchsorted(seg, np.uint64(r.lo >> sh), side="left")
                e2_ = s + np.searchsorted(seg, np.uint64(r.hi >> sh), side="right")
                if e2_ > s2_:
                    starts.append(s2_)
                    ends.append(e2_)
        if not starts:
            return np.zeros(1, np.int64), np.zeros(1, np.int64)
        return _cap_windows(
            np.asarray(starts, np.int64), np.asarray(ends, np.int64), MAX_WINDOW_BINS
        )


class IdKeySpace(KeySpace):
    """Feature-id index (reference IdIndex), hash-keyed: rows sort by a
    64-bit hash of the fid instead of the string bytes — string argsorts
    don't scale to bulk loads, and id lookups only need *locatable* rows:
    the window for hash(fid) is a superset (collisions included) and the
    IdIn mask applies exact fid equality on the window rows."""

    name = "id"
    kind = "id"
    key_cols = ("__idhash",)

    def supports(self, ft):
        return True

    def index_keys(self, ft, batch):
        return {"__idhash": packsort.fid_hash64(batch["__fid__"])}

    def sort_order(self, cols):
        return np.argsort(cols["__idhash"], kind="stable")

    def fast_build(self, cols, force_shifts=None):
        fs = None if force_shifts is None else force_shifts.get("__idhash")
        out = packsort.pack_sort(cols["__idhash"], 64, force_shift=fs)
        if out is None:
            return None
        perm, hq, _, shift = out
        return perm, {"__idhash": hq}, {"__idhash": shift}

    def plan(self, ft, f):
        ids = ir.extract_ids(f)
        if ids is None:
            return None
        plan = KeyPlan(self, coverage=0.0)
        plan._ids = sorted(ids)
        return plan

    def resolve_windows(self, plan, shard_cols, n):
        col = shard_cols["__idhash"]
        sh = _shift_of(shard_cols, "__idhash")
        starts, ends = [], []
        for fid in plan._ids:
            h = packsort.fid_hash64_one(fid) >> sh
            s = np.searchsorted(col, np.uint64(h), side="left")
            e = np.searchsorted(col, np.uint64(h), side="right")
            if e > s:
                starts.append(s)
                ends.append(e)
        if not starts:
            return np.zeros(1, np.int64), np.zeros(1, np.int64)
        return np.asarray(starts, np.int64), np.asarray(ends, np.int64)


class AttributeKeySpace(KeySpace):
    """Per-attribute sorted index (reference AttributeIndex + tiered keyspace;
    the z-curve tiebreak plays the reference's secondary-tier role)."""

    kind = "attr"

    #: attribute-type name -> numpy dtype of the stored column
    _NP_TYPES = {
        "int32": np.int32, "int64": np.int64, "float32": np.float32,
        "float64": np.float64, "date": np.int64, "bool": np.bool_,
    }

    def __init__(self, attr: str, geom: Optional[str] = None,
                 attr_type: Optional[str] = None):
        self.attr = attr
        self.geom = geom
        self.attr_type = attr_type
        self.name = f"attr:{attr}"
        self.key_cols = (f"__attr_{attr}",)

    @property
    def sort_col(self) -> str:
        return f"__attr_{self.attr}"

    def supports(self, ft):
        return ft.has(self.attr) and not ft.attr(self.attr).is_geom

    def index_keys(self, ft, batch):
        a = ft.attr(self.attr)
        vals = batch[self.attr]
        if a.type == "string":
            # codes are re-ranked to value order at table build (store step);
            # raw codes stored here, rank column computed on flush.
            return {self.sort_col: vals.astype(np.int64)}
        return {self.sort_col: vals}

    def sort_order(self, cols):
        if self.geom and "__z2" in cols:
            return np.lexsort((cols["__z2"], cols[self.sort_col]))
        return np.argsort(cols[self.sort_col], kind="stable")

    def fast_build(self, cols, force_shifts=None):
        col = cols[self.sort_col]
        if self.attr_type == "string":
            # rank column (small ints; -1 = null sorts first as 0)
            key = (col.astype(np.int64) + 1).astype(np.uint64)
            bits = packsort.bits_for(int(key.max()) + 1) if len(key) else 1
        else:
            try:
                key, bits = packsort.to_ordered_u64(col)
            except TypeError:
                return None
        tb, tb_bits = None, 0
        if self.geom and "__z2" in cols:
            tb = cols["__z2"].astype(np.uint64) << np.uint64(2)  # 62 bits -> top
            tb_bits = 16  # spatial-locality tiebreak, best-effort
        fs = None if force_shifts is None else force_shifts.get(self.sort_col)
        out = packsort.pack_sort(
            key, bits, tiebreak=tb, tiebreak_bits=tb_bits, force_shift=fs
        )
        if out is None:
            return None
        perm, kq, _, shift = out
        return perm, {self.sort_col: kq}, {self.sort_col: shift}

    # string attrs re-rank their dictionary on growth and the z2 tiebreak
    # is a second sort key: appends always fully rebuild
    can_insert = False

    def plan(self, ft, f):
        bounds = ir.extract_attr_bounds(f, self.attr)
        if bounds.disjoint:
            return KeyPlan(self, disjoint=True)
        if bounds.is_empty:
            return None
        plan = KeyPlan(self, coverage=0.1)  # refined by stats in the decider
        plan._bounds = bounds.values
        plan._ft = ft
        return plan

    def resolve_windows(self, plan, shard_cols, n):
        col = shard_cols[self.sort_col]
        a = plan._ft.attr(self.attr)
        shifts = shard_cols.get("__shifts__") or {}
        # fast-built tables store the ordered-u64 QUANTIZED key; bounds go
        # through the same transform (presence in shifts marks the path,
        # since shift can legitimately be 0)
        fastq = self.sort_col in shifts
        sh = shifts.get(self.sort_col, 0)
        np_type = self._NP_TYPES.get(a.type)
        starts, ends = [], []
        for lo, hi in plan._bounds:
            if a.type == "string":
                # bounds are raw strings; map through the rank dictionary
                # attached by the store at resolve time
                rank = shard_cols.get("__rank_lookup__")
                if rank is None:
                    return np.zeros(1, np.int64), np.full(1, n, np.int64)
                lo2 = rank(lo, "lo") if lo is not None else None
                hi2 = rank(hi, "hi") if hi is not None else None
                if fastq:
                    lo2 = None if lo2 is None else np.uint64((lo2 + 1) >> sh)
                    hi2 = None if hi2 is None else np.uint64((hi2 + 1) >> sh)
            elif fastq:
                lo2 = (
                    None if lo is None
                    else np.uint64(packsort.ordered_u64_scalar(lo, np_type) >> sh)
                )
                hi2 = (
                    None if hi is None
                    else np.uint64(packsort.ordered_u64_scalar(hi, np_type) >> sh)
                )
            else:
                lo2, hi2 = lo, hi
                if a.type == "date":
                    lo2 = None if lo is None else np.int64(lo)
                    hi2 = None if hi is None else np.int64(hi)
            s = 0 if lo2 is None else int(np.searchsorted(col, lo2, side="left"))
            e = n if hi2 is None else int(np.searchsorted(col, hi2, side="right"))
            if e > s:
                starts.append(s)
                ends.append(e)
        if not starts:
            return np.zeros(1, np.int64), np.zeros(1, np.int64)
        return _cap_windows(
            np.asarray(starts, np.int64), np.asarray(ends, np.int64), MAX_WINDOW_BINS
        )


def _cap_windows(starts: np.ndarray, ends: np.ndarray, cap: int):
    """Merge overlapping row windows; if more than ``cap`` remain, union the
    smallest gaps to fit (over-covering; fine filter restores exactness).
    Row windows are half-open, so only true overlap merges (adjacency 0)."""
    return _merge_cap(starts, ends, cap, adjacent=0)


def keyspaces_for_schema(ft: FeatureType) -> List[KeySpace]:
    """Pick indices from the schema shape (GeoMesaFeatureIndexFactory.indices
    analog, reference GeoMesaDataStore.preSchemaCreate:116). The
    ``geomesa.indices`` user-data key overrides the defaults with an explicit
    comma-separated list of index kinds (z3,z2,xz3,xz2,s2,s3,id,attr)."""
    geom = ft.geom_field
    dtg = ft.dtg_field
    period = ft.time_period

    explicit = ft.user_data.get("geomesa.indices")
    if explicit:
        wanted = [k.strip().lower() for k in explicit.split(",") if k.strip()]
    else:
        wanted = []
        if geom is not None:
            if ft.attr(geom).is_point:
                if dtg is not None:
                    wanted.append("z3")
                wanted.append("z2")
            else:
                if dtg is not None:
                    wanted.append("xz3")
                wanted.append("xz2")
        wanted += ["id", "attr"]

    out: List[KeySpace] = []
    for kind in wanted:
        if kind == "z3" and geom and dtg:
            out.append(Z3KeySpace(geom, dtg, period))
        elif kind == "z2" and geom:
            out.append(Z2KeySpace(geom))
        elif kind == "xz3" and geom and dtg:
            out.append(XZ3KeySpace(geom, dtg, period))
        elif kind == "xz2" and geom:
            out.append(XZ2KeySpace(geom))
        elif kind == "s2" and geom:
            out.append(S2KeySpace(geom))
        elif kind == "s3" and geom and dtg:
            out.append(S3KeySpace(geom, dtg, period))
        elif kind == "id":
            out.append(IdKeySpace())
        elif kind == "attr":
            for a in ft.attributes:
                if a.indexed and not a.is_geom and a.type != "json":
                    out.append(AttributeKeySpace(a.name, geom, a.type))
    if not any(isinstance(k, IdKeySpace) for k in out):
        out.append(IdKeySpace())
    return [k for k in out if k.supports(ft)]
