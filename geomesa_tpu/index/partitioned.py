"""Time-partitioned, out-of-core feature store.

The TPU analog of the reference's table partitioning
(geomesa-index-api/.../conf/partition/TimePartition.scala:35: one physical
table per time period derived from the default date attribute) fused with the
FSDS cold tier (ParquetFileSystemStorage streams partitions from disk under
bounded memory; AbstractBatchScan.scala:32): each time period owns a child
:class:`FeatureStore`; only a bounded number stay resident in host RAM, the
rest are spilled to an on-disk columnar snapshot (master columns + each
index's precomputed sort permutation and key columns, so reload never
re-sorts). Queries stream pruned partitions through RAM/HBM one at a time and
merge additive results — the 1B-point path on a 16 GB-HBM chip.

Partition key = the schema's time-period bin (``geomesa.partition.period``
user-data, defaulting to the Z3 interval — the same epoch bin the reference's
TimePartition uses).
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import threading
from typing import Dict, List, Optional

import numpy as np

from geomesa_tpu import config, metrics, resilience
from geomesa_tpu.curves.binned_time import BinnedTime
from geomesa_tpu.index.keyspace import AttributeKeySpace
from geomesa_tpu.index.store import FeatureStore
from geomesa_tpu.schema.columns import ColumnBatch
from geomesa_tpu.schema.feature_type import FeatureType
from geomesa_tpu.stats import sketches as sk


def is_partitioned_schema(ft: FeatureType) -> bool:
    v = ft.user_data.get("geomesa.partition", "").lower()
    return v in ("time", "true")


class _LakeLazyCols(dict):
    """Master-column mapping over a lake snapshot: a member decodes its
    (surviving) row groups on first access — the same ColumnGroups
    contract as :class:`_LazyCols`, now at row-group granularity
    (docs/LAKE.md): a projected query on a statistics-pruned partial load
    touches only the column chunks it needs."""

    def __init__(self, snap, zkeys: Dict[str, str], groups=None,
                 on_corrupt=None, cache=None):
        super().__init__()
        self._snap = snap
        self._zkeys = dict(zkeys)  # column name -> prefixed snapshot name
        self._groups = groups      # None = every row group
        self._cache = cache        # cross-chunk residency (docs/JOIN.md §11)
        #: corruption hook: a crc/decode failure during a LAZY column read
        #: surfaces mid-scan, after the load committed — the owning
        #: partitioned store quarantines the bin here so the next query
        #: fails fast instead of re-parsing a corrupt chunk
        self._on_corrupt = on_corrupt

    def __missing__(self, k):
        from geomesa_tpu.lake.format import LakeCorruptError

        zk = self._zkeys.get(k)
        if zk is None:
            raise KeyError(k)
        try:
            v = self._snap.read_column(zk, self._groups,
                                       cache=self._cache)
        except LakeCorruptError as e:
            if self._on_corrupt is not None:
                self._on_corrupt(e)
            raise
        self[k] = v
        return v

    def __contains__(self, k):
        return super().__contains__(k) or k in self._zkeys

    def get(self, k, default=None):
        try:
            return self[k]
        except KeyError:
            return default

    def __iter__(self):
        seen = dict.fromkeys(self._zkeys)
        seen.update(dict.fromkeys(super().keys()))
        return iter(seen)

    def keys(self):
        return list(iter(self))

    def items(self):
        return [(k, self[k]) for k in self]

    def values(self):
        return [self[k] for k in self]

    def __len__(self):
        return len(set(self._zkeys) | set(super().keys()))


class _LazyCols(dict):
    """Master-column mapping that loads snapshot members on first access —
    the ColumnGroups analog (reference conf/ColumnGroups.scala:28: scans
    touch only the column families they need). A reloaded cold partition
    materializes exactly the columns its queries read; a count/density
    touching 3 of 12 attributes never pays IO for the other 9."""

    def __init__(self, npz_path: str, zkeys: Dict[str, str]):
        super().__init__()
        self._path = npz_path
        self._zkeys = dict(zkeys)   # column name -> npz member
        self._zf = None

    def __missing__(self, k):
        zk = self._zkeys.get(k)
        if zk is None:
            raise KeyError(k)
        if self._zf is None:
            self._zf = np.load(self._path, allow_pickle=False)
        v = self._zf[zk]
        self[k] = v
        return v

    def __contains__(self, k):
        return super().__contains__(k) or k in self._zkeys

    def get(self, k, default=None):
        # dict.get bypasses __missing__; lazy members must still resolve
        try:
            return self[k]
        except KeyError:
            return default

    def __iter__(self):
        seen = dict.fromkeys(self._zkeys)
        seen.update(dict.fromkeys(super().keys()))
        return iter(seen)

    def keys(self):
        return list(iter(self))

    def items(self):  # materializes: snapshot writes / merges need all
        return [(k, self[k]) for k in self]

    def values(self):
        return [self[k] for k in self]

    def __len__(self):
        return len(set(self._zkeys) | set(super().keys()))


class PartitionedFeatureStore(FeatureStore):
    """FeatureStore facade over per-time-period child stores.

    Children share this store's dictionary encoders (so string codes and
    compiled predicates are valid across partitions) and the parent's
    ``version`` (bumped on any child mutation) keys cross-partition kernel
    caches. The parent's own ``tables`` stay empty — execution fans out via
    :class:`geomesa_tpu.planning.partitioned_exec.PartitionedExecutor`.
    """

    def __init__(self, ft: FeatureType, n_shards: Optional[int] = None,
                 spill_dir: Optional[str] = None,
                 max_resident: Optional[int] = None):
        super().__init__(ft, n_shards)
        if ft.dtg_field is None:
            raise ValueError(
                "time partitioning requires a date attribute "
                "(geomesa.partition=time on a schema with no dtg)"
            )
        self.partition_period = ft.user_data.get(
            "geomesa.partition.period", ft.time_period
        )
        self.binned = BinnedTime(self.partition_period)
        #: resident children, bin -> store (insertion order = LRU order)
        self.partitions: Dict[int, FeatureStore] = {}
        #: spilled children, bin -> snapshot dir
        self.spilled: Dict[int, str] = {}
        #: per-partition row counts (resident AND spilled)
        self.part_counts: Dict[int, int] = {}
        #: resident children with changes not yet on disk
        self._dirty: set = set()
        #: per-partition content sequence (bumped on every mutation) —
        #: drives incremental checkpointing without path aliasing
        self._part_seq: Dict[int, int] = {}
        self.max_resident = max(
            1,
            max_resident
            if max_resident is not None
            else (config.MAX_RESIDENT_PARTITIONS.to_int() or 4),
        )
        self._spill_dir = spill_dir or config.SPILL_DIR.get()
        self._owns_spill_dir = False
        #: guards the partition map (partitions/spilled/_dirty/_snapshot
        #: paths): the query pipeline's prefetch thread loads partition
        #: i+1 while the query thread may evict after partition i
        #: (planning/partitioned_exec.py). RLock: child() -> _load() ->
        #: evict() nests.
        self._part_lock = threading.RLock()
        #: corrupt-snapshot quarantine (docs/RESILIENCE.md): bin -> first
        #: failure repr. A quarantined bin fails fast on load (the query
        #: layer's degradation contract skips it per-query) until
        #: :meth:`clear_spill_quarantine` re-admits it. Transient OSErrors
        #: are retried in place and NEVER quarantined.
        self._spill_quarantine: Dict[int, str] = {}
        self._shard_bucket = config.SHARD_LEN_BUCKET.to_int() or 1
        self._merged_stats = None
        self._merged_stats_version = -1

    # -- partition bookkeeping --------------------------------------------
    @property
    def spill_dir(self) -> str:
        if self._spill_dir is None:
            self._spill_dir = tempfile.mkdtemp(prefix="geomesa_spill_")
            self._owns_spill_dir = True
        return self._spill_dir

    def partition_bins(self) -> List[int]:
        with self._part_lock:
            return sorted(set(self.partitions) | set(self.spilled))

    def _new_child(self) -> FeatureStore:
        child = FeatureStore(self.ft, self.n_shards)
        child.dicts = self.dicts  # shared: codes valid across partitions
        for t in child.tables.values():
            t.shard_len_multiple = self._shard_bucket
        return child

    def _touch(self, b: int):
        """Move partition ``b`` to the most-recently-used position."""
        self.partitions[b] = self.partitions.pop(b)

    def child(self, b: int, create: bool = False) -> Optional[FeatureStore]:
        """Resident child for bin ``b``, loading from disk if spilled."""
        with self._part_lock:
            st = self.partitions.get(b)
            if st is not None:
                self._touch(b)
                return st
            if b in self.spilled:
                return self._load(b)
            if not create:
                return None
            st = self._new_child()
            self.partitions[b] = st
            self._dirty.add(b)
            return st

    def evict(self, keep: Optional[int] = None):
        """Spill least-recently-used residents down to ``keep`` (default the
        store's ``max_resident``)."""
        keep = self.max_resident if keep is None else keep
        with self._part_lock:
            while len(self.partitions) > max(keep, 1):
                b = next(iter(self.partitions))  # LRU head
                self._spill(b)

    # -- spill format ------------------------------------------------------
    def _part_dir(self, b: int) -> str:
        return os.path.join(self.spill_dir, f"part_{b}")

    def _spill(self, b: int):
        """Write partition ``b``'s columnar snapshot to disk and drop it
        from RAM. Partitions that are clean since their last load/spill skip
        the write (their snapshot dir is still valid).

        Fault posture (docs/RESILIENCE.md, ``index.spill.store``): the
        write is retried in place on transient ``OSError`` (seeded
        RetryPolicy, ``geomesa.retry.*``); the partition leaves RAM only
        AFTER its snapshot is durable, so a store failure (retries
        exhausted) raises with the partition still resident — a spill can
        back off, it can never lose data."""
        st = self.partitions[b]
        st.flush()
        snaps = getattr(self, "_snapshot_paths", {})
        d = snaps.get(b, self._part_dir(b))
        if b in self._dirty or not os.path.isdir(d):
            d = self._part_dir(b)
            policy = resilience.RetryPolicy.from_config(seed=int(b))

            def attempt():
                resilience.fault_point("index.spill.store", bin=int(b),
                                       path=d)
                self._write_snapshot(st, d)

            policy.call(attempt,
                        retryable=resilience.transient_os_error)
            snaps[b] = d
            self._snapshot_paths = snaps
        self.partitions.pop(b)  # only now: the snapshot is durable
        self._dirty.discard(b)
        self.spilled[b] = d
        self.part_counts[b] = st.count

    def _write_snapshot(self, st: FeatureStore, d: str):
        tmp = d + ".tmp"
        os.makedirs(tmp, exist_ok=True)
        if config.LAKE_ENABLED.to_bool():
            # columnar lake snapshot (docs/LAKE.md): footer-indexed row
            # groups with per-group statistics; same tmp-then-replace
            # atomicity as the npz writer below
            from geomesa_tpu.lake import snapshot as lake_snapshot

            try:
                lake_snapshot.write_snapshot(st, self.ft, tmp)
            except BaseException:
                shutil.rmtree(tmp, ignore_errors=True)
                raise
            if os.path.exists(d):
                shutil.rmtree(d)
            os.replace(tmp, d)
            resilience.fsync_dir(os.path.dirname(os.path.abspath(d)))
            return
        arrs: Dict[str, np.ndarray] = {}
        if st._all is not None:
            for k, v in st._all.columns.items():
                arrs["c/" + k] = v.astype("U") if v.dtype.kind == "O" else v
        for k, v in st._key_cols.items():
            arrs["k/" + k] = v
        shifts: Dict[str, Dict[str, int]] = {}
        for name, t in st.tables.items():
            arrs[f"t/{name}/order"] = t.order
            for k, v in t.key_columns.items():
                arrs[f"t/{name}/key/{k}"] = v
            if t._rank_vocab is not None:
                arrs[f"t/{name}/vocab"] = t._rank_vocab.astype("U")
            if t.key_shifts is not None:
                shifts[name] = dict(t.key_shifts)
        np.savez(os.path.join(tmp, "data.npz"), **arrs)
        meta = {
            "n": st._all.n if st._all is not None else 0,
            "shifts": shifts,
            "stats": {k: v.to_json() for k, v in st.stats.items()},
        }
        with open(os.path.join(tmp, "meta.json"), "w") as fh:
            json.dump(meta, fh)
        if os.path.exists(d):
            shutil.rmtree(d)
        os.replace(tmp, d)
        resilience.fsync_dir(os.path.dirname(os.path.abspath(d)))

    def _load(self, b: int) -> FeatureStore:
        """Reload a spilled partition (``index.spill.load`` fault edge;
        docs/RESILIENCE.md): transient ``OSError`` retries in place via a
        seeded RetryPolicy and is never quarantined (the next query
        re-attempts); any other parse failure marks the snapshot CORRUPT —
        the bin quarantines (fail-fast on later loads, counted in
        ``index.spill.quarantined``) until :meth:`clear_spill_quarantine`
        re-admits it after repair. The ``spilled`` entry is removed only
        on success, so a failed load can always be retried."""
        q = self._spill_quarantine.get(b)
        if q is not None:
            raise ValueError(
                f"partition {b} snapshot quarantined: {q} "
                "(clear_spill_quarantine() re-admits after repair)"
            )
        d = self.spilled[b]
        policy = resilience.RetryPolicy.from_config(seed=int(b))

        def attempt():
            resilience.fault_point("index.spill.load", bin=int(b), path=d)
            return self._load_snapshot(b, d)

        try:
            st = policy.call(attempt,
                             retryable=resilience.transient_os_error)
        except OSError:
            raise  # transient: never quarantined, the next read retries
        except Exception as e:
            self._spill_quarantine[b] = repr(e)[:300]
            metrics.inc("index.spill.quarantined")
            raise ValueError(
                f"corrupt partition snapshot for bin {b}: {e!r}"
            ) from e
        self.spilled.pop(b, None)
        self.partitions[b] = st
        self.part_counts[b] = st.count
        # remember the snapshot dir: if the partition stays clean, a later
        # eviction re-uses it without rewriting (incremental checkpointing)
        self._snapshot_paths = getattr(self, "_snapshot_paths", {})
        self._snapshot_paths[b] = d
        self.evict()
        return st

    def spill_quarantine(self) -> Dict[int, str]:
        """Copy of the corrupt-snapshot quarantine map (bin -> first
        failure)."""
        with self._part_lock:
            return dict(self._spill_quarantine)

    def clear_spill_quarantine(self, b: Optional[int] = None) -> List[int]:
        """Re-admit quarantined partition snapshot(s) for loading (the
        operator repaired or restored the files). Returns the bins
        cleared; repeat failures re-quarantine."""
        with self._part_lock:
            if b is not None:
                return ([b] if self._spill_quarantine.pop(b, None)
                        is not None else [])
            cleared = list(self._spill_quarantine)
            self._spill_quarantine.clear()
            return cleared

    def _load_snapshot(self, b: int, d: str) -> FeatureStore:
        """Parse one snapshot dir into a fresh child store — pure read,
        no partition-map mutation (:meth:`_load` commits on success).
        Dispatches on the snapshot's format: lake (``part.lake``,
        docs/LAKE.md) or the legacy npz layout — either always loads,
        so a store written before the lake tier reloads unchanged."""
        from geomesa_tpu.lake.snapshot import SNAPSHOT_FILE

        if os.path.exists(os.path.join(d, SNAPSHOT_FILE)):
            return self._load_lake_snapshot(b, d)
        st = self._new_child()
        with open(os.path.join(d, "meta.json")) as fh:
            meta = json.load(fh)
        st.stats = {k: sk.Stat.from_json(v) for k, v in meta["stats"].items()}
        path = os.path.join(d, "data.npz")
        with np.load(path, allow_pickle=False) as z:
            files = list(z.files)
            # master/attribute columns load LAZILY on first access (the
            # ColumnGroups analog); the sort permutations and key columns
            # every scan touches load eagerly
            zkeys = {k[2:]: k for k in files if k.startswith(("c/", "k/"))}
            master = _LazyCols(path, zkeys)
            cols = _LazyCols(path, {k[2:]: k for k in files if k.startswith("c/")})
            st._key_cols = {k[2:]: z[k] for k in files if k.startswith("k/")}
            # seed the eagerly-loaded key-cache arrays so master accesses
            # share them instead of re-reading the npz member
            master.update(st._key_cols)
            st._all = ColumnBatch(cols, int(meta["n"]))
            for name, t in st.tables.items():
                pre = f"t/{name}/"
                if pre + "order" not in files:
                    continue
                t.order = z[pre + "order"]
                t.key_columns = {
                    k[len(pre) + 4:]: z[k]
                    for k in files if k.startswith(pre + "key/")
                }
                if pre + "vocab" in files:
                    t._rank_vocab = z[pre + "vocab"].astype(object)
                sh = meta["shifts"].get(name)
                t.key_shifts = {k: int(v) for k, v in sh.items()} if sh else None
                t._master = master
                t.n = len(t.order)
                t.shard_bounds = np.linspace(
                    0, t.n, t.n_shards + 1
                ).astype(np.int64)
        self._upgrade_loaded(st, master)
        return st

    def _load_lake_snapshot(self, b: int, d: str) -> FeatureStore:
        """Full (every-row-group) load of a lake snapshot: the lake twin
        of the npz branch above — key columns and sort permutations load
        eagerly, master/attribute columns lazily per column."""
        from geomesa_tpu.lake.snapshot import PartitionSnapshot

        snap = PartitionSnapshot(d)
        st = self._new_child()
        meta = snap.meta
        st.stats = {k: sk.Stat.from_json(v)
                    for k, v in meta["stats"].items()}
        n = int(meta["n"])
        corrupt = self._quarantiner(b)
        master = _LakeLazyCols(snap, {c[2:]: c for c in snap.columns},
                               on_corrupt=corrupt)
        cols = _LakeLazyCols(
            snap, {c[2:]: c for c in snap.columns if c.startswith("c/")},
            on_corrupt=corrupt,
        )
        st._key_cols = {
            c[2:]: snap.read_column(c)
            for c in snap.columns if c.startswith("k/")
        }
        master.update(st._key_cols)
        st._all = ColumnBatch(cols, n)
        for name, t in st.tables.items():
            ent = snap.tables.get(name)
            if ent is None:
                continue  # snapshot predates this index: rebuilt on load
            order = snap.table_order(name)
            t.order = (np.arange(n, dtype=np.int64)
                       if order is None else order)
            t.key_columns = snap.table_keys(name)
            vocab = snap.table_vocab(name)
            if vocab is not None:
                t._rank_vocab = vocab.astype(object)
            sh = meta["shifts"].get(name)
            t.key_shifts = ({k: int(v) for k, v in sh.items()}
                            if sh else None)
            t._master = master
            t.n = len(t.order)
            t.shard_bounds = np.linspace(
                0, t.n, t.n_shards + 1
            ).astype(np.int64)
        self._upgrade_loaded(st, master)
        return st

    def _quarantiner(self, b: int):
        """Corruption hook for lazily-decoded lake columns: quarantine the
        bin on the first structural failure (same contract as a corrupt
        load — :meth:`clear_spill_quarantine` re-admits after repair)."""

        def mark(e: BaseException) -> None:
            with self._part_lock:
                if b in self._spill_quarantine:
                    return
                self._spill_quarantine[b] = repr(e)[:300]
            metrics.inc("index.spill.quarantined")

        return mark

    @staticmethod
    def _pushdown_fallback(b: int, window: Optional[Dict],
                           reason: str) -> None:
        """One pushdown request this snapshot could not serve pruned
        (docs/LAKE.md §10): counted in ``lake.pushdown.fallback`` and
        recorded on the window dict so the executor folds it into the
        explain/audit ``exec_path`` — a silent full load must never read
        as "pushdown covered everything"."""
        metrics.inc("lake.pushdown.fallback")
        if isinstance(window, dict):
            window.setdefault("fallbacks", []).append((int(b), reason))

    # -- statistics-pruned partial loads (docs/LAKE.md) --------------------
    def scan_child(self, b: int,
                   window: Optional[Dict] = None) -> Optional[FeatureStore]:
        """Child for one ADDITIVE scan: residents serve as-is; a spilled
        lake partition whose footer statistics prune row groups against
        ``window`` loads an EPHEMERAL pruned child (never entered into the
        resident map — a later query must not see a partial partition),
        decoding only the surviving groups' bytes. Falls back to the
        ordinary :meth:`child` load when there is no window, the snapshot
        predates the lake format, the plan's index is not the snapshot's
        primary sort order, or nothing prunes (a full resident load is
        then strictly better — it caches).

        ``window``: ``{"index": plan index name, "boxes": [...] | None,
        "times": [...] | None}`` (see ``partitioned_exec._push_window``).
        Quarantine semantics match :meth:`_load`: transient ``OSError``
        retries and never quarantines; a corrupt footer or row group
        (crc mismatch, torn encoding) quarantines the bin until
        :meth:`clear_spill_quarantine` re-admits it."""
        from geomesa_tpu.lake.format import LakeCorruptError  # noqa: F401
        from geomesa_tpu.lake.snapshot import (
            SNAPSHOT_FILE, PartitionSnapshot,
        )

        with self._part_lock:
            st = self.partitions.get(b)
            if st is not None:
                self._touch(b)
                return st
            if b not in self.spilled:
                return None
            q = self._spill_quarantine.get(b)
            if q is not None:
                raise ValueError(
                    f"partition {b} snapshot quarantined: {q} "
                    "(clear_spill_quarantine() re-admits after repair)"
                )
            d = self.spilled[b]
        if window is None:
            return self.child(b)
        if not os.path.exists(os.path.join(d, SNAPSHOT_FILE)):
            # pre-lake npz snapshot: statistics don't exist, pushdown
            # CANNOT engage — count it so the full load never reads as
            # "pushdown covered everything" (docs/LAKE.md §10)
            self._pushdown_fallback(b, window, "legacy-snapshot")
            return self.child(b)
        requested = window.get("index")
        ks = next((k for k in self.keyspaces if k.name == requested), None)
        if ks is None:
            # exotic keyspace: the plan's index is not one this store
            # carries statistics for (docs/LAKE.md §10)
            self._pushdown_fallback(b, window, "unknown-keyspace")
            return self.child(b)
        policy = resilience.RetryPolicy.from_config(seed=int(b))
        try:
            snap = policy.call(lambda: PartitionSnapshot(d),
                               retryable=resilience.transient_os_error)
            groups = snap.prune(window.get("boxes"), window.get("times"))
            have = set(snap.columns)
            buildable = requested == snap.primary or all(
                ("k/" + kc) in have or ("c/" + kc) in have
                for kc in ks.key_cols
            )
            if snap.primary is None or snap.primary not in snap.tables:
                self._pushdown_fallback(b, window, "no-primary-order")
                return self.child(b)
            if not buildable:
                # the requested keyspace's key columns aren't in the
                # snapshot: a pruned subset couldn't rebuild its
                # permutation — the exotic-keyspace full-load fallback
                self._pushdown_fallback(b, window, "keyspace-not-buildable")
                return self.child(b)
            if len(groups) == len(snap.groups):
                # nothing prunes: the full resident load is strictly
                # better (it caches) — a DELIBERATE full load, not a
                # fallback, so it stays out of the fallback accounting
                return self.child(b)

            def attempt():
                resilience.fault_point("index.spill.load", bin=int(b),
                                       path=d)
                return self._load_pruned(b, snap, groups, ks,
                                         cache=window.get("residency"))

            return policy.call(attempt,
                               retryable=resilience.transient_os_error)
        except OSError:
            raise  # transient: never quarantined, the next read retries
        except Exception as e:
            with self._part_lock:
                self._spill_quarantine[b] = repr(e)[:300]
            metrics.inc("index.spill.quarantined")
            raise ValueError(
                f"corrupt partition snapshot for bin {b}: {e!r}"
            ) from e

    def _load_pruned(self, b: int, snap, groups: List[int],
                     ks, cache=None) -> FeatureStore:
        """Assemble the ephemeral pruned child over the surviving row
        groups. When the plan's index IS the snapshot's primary sort
        order, the groups are SFC-contiguous slices of it — order is the
        identity, key columns are the groups' chunks, nothing re-sorts.
        Any other index rebuilds its permutation from the subset's cached
        key columns (a host sort of only the LOADED rows; window
        resolution then admits a possibly-different candidate superset,
        but the compiled predicate decides matches — results stay exact).
        Only the requested index table exists on the child."""
        from geomesa_tpu.schema.columns import null_columns

        primary = snap.primary
        requested = ks.name
        st = self._new_child()
        meta = snap.meta
        st.stats = {k: sk.Stat.from_json(v)
                    for k, v in meta["stats"].items()}
        nsel = snap.group_rows(groups)
        corrupt = self._quarantiner(b)
        master = _LakeLazyCols(snap, {c[2:]: c for c in snap.columns},
                               groups, on_corrupt=corrupt, cache=cache)
        cols = _LakeLazyCols(
            snap, {c[2:]: c for c in snap.columns if c.startswith("c/")},
            groups, on_corrupt=corrupt, cache=cache,
        )
        st._key_cols = {}
        st._all = ColumnBatch(cols, nsel)
        t = st.tables[requested]
        st.tables = {requested: t}
        st.keyspaces = [k for k in st.keyspaces if k.name == requested]
        if nsel == 0:
            # everything pruned: a zero-row child — every consumer skips
            # it on ``child.count == 0`` before any window resolution, so
            # the table only needs a coherent empty shape (decoding zero
            # groups cannot recover the key columns' true dtypes)
            t.order = np.zeros(0, np.int64)
            t.n = 0
            t._master = master
            t.shard_bounds = np.zeros(t.n_shards + 1, np.int64)
        elif requested == primary:
            t.order = np.arange(nsel, dtype=np.int64)
            t.key_columns = snap.table_keys(primary, groups, cache=cache)
            vocab = snap.table_vocab(primary)
            if vocab is not None:
                t._rank_vocab = vocab.astype(object)
            sh = meta["shifts"].get(primary)
            t.key_shifts = ({k: int(v) for k, v in sh.items()}
                            if sh else None)
            t._master = master
            t.n = nsel
            t.shard_bounds = np.linspace(
                0, nsel, t.n_shards + 1
            ).astype(np.int64)
        else:
            needed: Dict[str, np.ndarray] = {}
            for kc in ks.key_cols:
                needed[kc] = master[kc]  # decodes the subset chunks
            if isinstance(ks, AttributeKeySpace):
                needed[ks.attr] = master[ks.attr]
            t.rebuild(needed, self.dicts)
            for k2, v2 in list(t._master.items()):
                if k2 not in master:
                    master[k2] = v2
            t._master = master
        # schema upgrades WITHOUT index rebuilds (the pruned child serves
        # one plan on one index): null-fill attributes the snapshot
        # predates, adopt the current feature type
        missing = [a for a in self.ft.attributes
                   if not a.is_geom and a.name not in master]
        if missing and nsel:
            cc = null_columns(self.ft, missing, nsel, self.dicts)
            master.update(cc)
            st._all.columns.update(cc)
        st.ft = self.ft
        t.ft = self.ft
        st.__dict__["_lake_note"] = snap.account(groups)
        return st

    def spill_all(self) -> List[int]:
        """Spill every resident partition to its snapshot (operators /
        benchmarks forcing a fully-cold store). Returns the bins spilled."""
        with self._part_lock:
            out = list(self.partitions)
            for b in out:
                self._spill(b)
            return out

    # -- write path --------------------------------------------------------
    def flush(self):
        """Route buffered rows to their time partitions, then flush touched
        partitions one at a time under the residency budget (ingest never
        materializes more than one partition's indexed form at once beyond
        that budget)."""
        with self._lock:
            if not self._buffer:
                return
            fresh = ColumnBatch.concat(self._buffer)
            self._buffer = []
        dtg = self.ft.dtg_field
        bins, _ = self.binned.to_bin_and_offset(
            np.asarray(fresh.columns[dtg], np.int64)
        )
        # i32 keys radix-sort ~5x faster than i64 (bin ids are epoch
        # periods, far below 2^31); one global gather per column up front
        # makes every partition's sub-batch a zero-copy slice
        order = np.argsort(bins.astype(np.int32), kind="stable")
        sb = bins[order]
        sorted_cols = {k: v[order] for k, v in fresh.columns.items()}
        cuts = np.flatnonzero(np.concatenate(([True], sb[1:] != sb[:-1])))
        bounds = np.concatenate((cuts, [len(sb)]))
        done = 0
        try:
            for i, c in enumerate(cuts):
                b = int(sb[c])
                hi = bounds[i + 1]
                # contiguous-slice COPIES (cheap memcpy, unlike the fancy
                # gather this replaced) — a view would pin the whole sorted
                # batch in every child's master columns, defeating the
                # residency-budget eviction
                sub = ColumnBatch(
                    {k: v[c:hi].copy() for k, v in sorted_cols.items()},
                    int(hi - c),
                )
                child = self.child(b, create=True)
                child._buffer.append(sub)
                # routed: the sub-batch now lives in the child's buffer —
                # even if its flush below fails, the child's NEXT flush
                # commits it, so it must not be re-buffered on error
                done = i + 1
                self._dirty.add(b)
                self._part_seq[b] = self._part_seq.get(b, 0) + 1
                child.flush()
                self.part_counts[b] = child.count
                self.evict()
        except BaseException:
            # spill backpressure must never LOSE rows (docs/RESILIENCE.md,
            # index.spill.store): a failed eviction mid-route re-buffers
            # the not-yet-routed remainder of this batch, so the very next
            # flush retries it — before this, everything past the failing
            # partition silently vanished from the ingest buffer
            rest = int(cuts[done]) if done < len(cuts) else len(sb)
            if rest < len(sb):
                with self._lock:
                    self._buffer.append(ColumnBatch(
                        {k: v[rest:].copy()
                         for k, v in sorted_cols.items()},
                        int(len(sb) - rest),
                    ))
            if done:
                self.version += 1  # some partitions did take rows
            raise
        self.version += 1

    def _upgrade_loaded(self, st: FeatureStore, master) -> None:
        """Patch a freshly-loaded child whose snapshot predates a schema
        or index change: null-fill missing attribute columns and build
        missing index permutations — touching ONLY this partition, in
        memory (the snapshot on disk is not rewritten; it upgrades for
        real the next time this partition is dirtied)."""
        from geomesa_tpu.schema.columns import null_columns

        n = st._all.n if st._all is not None else 0
        missing = [a for a in self.ft.attributes
                   if not a.is_geom and a.name not in master]
        if missing and n:
            cols = null_columns(self.ft, missing, n, self.dicts)
            master.update(cols)
            st._all.columns.update(cols)
        st.ft = self.ft
        for t in st.tables.values():
            t.ft = self.ft
            if t.n == 0 and n:
                st.build_missing_table(t)
        # write-time sketches for indexed attrs the snapshot predates
        for a in self.ft.attributes:
            if a.indexed and not a.is_geom and a.type != "json":
                st.ensure_attr_sketch(a.name)

    # -- schema / index lifecycle -----------------------------------------
    def add_columns(self, new_ft, added) -> None:
        """In-place column append, partition-aware: resident children
        upgrade immediately; spilled snapshots upgrade lazily on load
        (``_load`` null-fills missing schema columns), so no partition is
        rewritten — the O(dataset) re-flush r4 did here is gone."""
        from geomesa_tpu.schema.columns import null_columns

        self.flush()
        self.ft = new_ft
        null_columns(new_ft, added, 0, self.dicts)  # register encoders
        for child in self.partitions.values():
            child.add_columns(new_ft, added)
        self.version += 1
        self._merged_stats = None

    def add_attribute_index(self, attr: str) -> None:
        """Enable an attribute index: resident children build only the new
        permutation; spilled partitions build theirs on next load (under
        the residency budget). Snapshots are NOT dirtied — the new index
        arrays rebuild per load until the partition is next written."""
        a = self.ft.attr(attr)
        if a.is_geom or a.type == "json":
            raise ValueError(f"cannot attribute-index {attr!r} ({a.type})")
        ks = AttributeKeySpace(attr, self.ft.geom_field, a.type)
        if any(k.name == ks.name for k in self.keyspaces):
            return
        self.flush()
        self.keyspaces.append(ks)
        for child in self.partitions.values():
            child.add_attribute_index(attr)
        self.version += 1
        self._merged_stats = None

    def remove_attribute_index(self, attr: str) -> None:
        name = f"attr:{attr}"
        if not any(k.name == name for k in self.keyspaces):
            raise KeyError(f"no attribute index on {attr!r}")
        self.keyspaces = [k for k in self.keyspaces if k.name != name]
        for child in self.partitions.values():
            if name in child.tables:
                child.remove_attribute_index(attr)
        self.version += 1
        self._merged_stats = None

    def delete(self, mask_fn) -> int:
        self.flush()
        removed = 0
        for b in self.partition_bins():
            child = self.child(b)
            r = child.delete(mask_fn)
            if r:
                removed += r
                self._dirty.add(b)
                self._part_seq[b] = self._part_seq.get(b, 0) + 1
                self.part_counts[b] = child.count
            self.evict()
        if removed:
            self.version += 1
            self._merged_stats = None
        return removed

    # -- read-side surface -------------------------------------------------
    @property
    def count(self) -> int:
        resident = {b: st.count for b, st in self.partitions.items()}
        spilled = sum(
            c for b, c in self.part_counts.items()
            if b not in resident and b in self.spilled
        )
        return sum(resident.values()) + spilled + self.pending

    @property
    def stats(self) -> Dict[str, sk.Stat]:
        """Merged write-time sketches across all partitions (resident stats
        merge directly; spilled partitions merge from their snapshot JSON —
        no column data is read). Cached per store version."""
        if (
            self._merged_stats is not None
            and self._merged_stats_version == self.version
        ):
            return self._merged_stats
        merged = self._init_stats()
        for st in self.partitions.values():
            for k, v in st.stats.items():
                if k in merged:
                    merged[k].merge(v)
                else:
                    merged[k] = sk.Stat.from_json(v.to_json())
        for b, d in self.spilled.items():
            try:
                with open(os.path.join(d, "meta.json")) as fh:
                    meta = json.load(fh)
            except OSError:
                continue
            for k, s in meta["stats"].items():
                v = sk.Stat.from_json(s)
                if k in merged:
                    merged[k].merge(v)
                else:
                    merged[k] = v
        self._merged_stats = merged
        self._merged_stats_version = self.version
        return merged

    @stats.setter
    def stats(self, value):
        """Intentionally a cache-invalidating no-op: merged stats are ALWAYS
        recomputed from partition sketches (resident + snapshot metas), so
        assignments from FeatureStore.__init__ and GeoDataset.load are
        absorbed rather than stored — there is no base-stats state."""
        self._merged_stats = None

    def wkt_geoms(self) -> List[str]:
        from geomesa_tpu.lake.snapshot import SNAPSHOT_FILE, PartitionSnapshot

        for st in self.partitions.values():
            return st.wkt_geoms()
        for d in self.spilled.values():
            try:
                if os.path.exists(os.path.join(d, SNAPSHOT_FILE)):
                    # lake snapshots answer from the footer column list —
                    # no payload bytes load (docs/LAKE.md)
                    names = set(PartitionSnapshot(d).columns)
                else:
                    with np.load(os.path.join(d, "data.npz"),
                                 allow_pickle=False) as z:
                        names = set(z.files)
                return [
                    a.name for a in self.ft.attributes
                    if a.is_geom and "c/" + a.name + "__wkt" in names
                ]
            except OSError:
                continue
        return []

    # -- durable checkpoint (incremental; GeoMesaMetadata/TableBasedMetadata
    # analog at the partition granularity) --------------------------------
    def checkpoint_into(self, path: str) -> Dict[int, str]:
        """Write/refresh every partition's snapshot under ``path`` without
        evicting residents, and WITHOUT aliasing live store state into the
        checkpoint (deleting a checkpoint must never corrupt the live
        store). Incrementality comes from per-partition content sequence
        numbers: a partition unchanged since the last checkpoint to the
        same ``path`` is skipped. Returns bin -> snapshot dir."""
        os.makedirs(path, exist_ok=True)
        out: Dict[int, str] = {}
        written = self.__dict__.setdefault("_ckpt_seqs", {}).setdefault(
            os.path.abspath(path), {}
        )
        snaps = getattr(self, "_snapshot_paths", {})
        for b, st in list(self.partitions.items()):
            st.flush()
            d = os.path.join(path, f"part_{b}")
            cur = self._part_seq.get(b, 0)
            if written.get(b) == cur and os.path.isdir(d):
                out[b] = d
                continue
            if (
                b not in self._dirty
                and os.path.isdir(snaps.get(b, ""))
                and os.path.abspath(snaps[b]) != os.path.abspath(d)
            ):
                if os.path.isdir(d):
                    shutil.rmtree(d)
                shutil.copytree(snaps[b], d)
            else:
                self._write_snapshot(st, d)
            written[b] = cur
            out[b] = d
        for b, sd in list(self.spilled.items()):
            d = os.path.join(path, f"part_{b}")
            cur = self._part_seq.get(b, 0)
            if written.get(b) == cur and os.path.isdir(d):
                out[b] = d
                continue
            if os.path.abspath(sd) != os.path.abspath(d):
                if os.path.isdir(d):
                    shutil.rmtree(d)
                shutil.copytree(sd, d)
            written[b] = cur
            out[b] = d
        return out

    def attach_snapshots(self, mapping: Dict[int, str]):
        """Register on-disk partition snapshots (the load path): partitions
        stay cold until a query or write touches them."""
        for b, d in mapping.items():
            b = int(b)
            with open(os.path.join(d, "meta.json")) as fh:
                meta = json.load(fh)
            self.spilled[b] = d
            self.part_counts[b] = int(meta["n"])
        self._merged_stats = None
        self._merged_stats_version = -1

    def __del__(self):
        try:
            if getattr(self, "_owns_spill_dir", False):
                shutil.rmtree(self._spill_dir, ignore_errors=True)
        except Exception:
            pass  # interpreter shutdown: module globals may be gone
