"""Index core (L3).

Capability parity with geomesa-index-api (SURVEY.md §2.4): an index is a sort
key function + shard assignment over columnar arrays. Key spaces encode
feature batches into curve keys at ingest (vectorized) and turn filters into
scan windows at plan time (the IndexKeySpace.getIndexValues/getRanges/
getRangeBytes triple, reference index/api/IndexKeySpace.scala:23-110).
"""

from geomesa_tpu.index.keyspace import (  # noqa: F401
    KeySpace, Z3KeySpace, Z2KeySpace, XZ3KeySpace, XZ2KeySpace,
    IdKeySpace, AttributeKeySpace, keyspaces_for_schema,
)
from geomesa_tpu.index.store import FeatureStore, IndexTable  # noqa: F401
