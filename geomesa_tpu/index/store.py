"""Sharded, sorted columnar feature store — the storage substrate (L3/L4).

The TPU analog of a backend adapter (SURVEY.md §2.5): instead of rowkey tables
in Accumulo/HBase, each index is a set of **sorted columnar shards**. A shard
is a contiguous slab of the index's global sort order (so per-shard
``searchsorted`` row windows play the role of rowkey range scans), padded to a
common length so the stacked [n_shards, shard_len] arrays pjit cleanly over a
device mesh.

Write path parity (GeoMesaFeatureWriter/IndexAdapter.BaseIndexWriter,
reference IndexAdapter.scala:132-190): an ingest batch computes ALL index keys
in one vectorized pass before any table is touched; tables rebuild their sort
on flush (LSM-style delta buffers are a later optimization — the write buffer
is the memtable).

Write-time stats parity (MetadataBackedStats.scala:36-100): flush updates the
persisted sketches (count, geometry/time bounds, Z3 histogram, per-indexed-
attribute sketches) that drive the cost-based strategy decider.
"""

from __future__ import annotations

import itertools
import threading
import uuid
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from geomesa_tpu import config
from geomesa_tpu.index.keyspace import (
    AttributeKeySpace, KeyPlan, KeySpace, keyspaces_for_schema,
)
from geomesa_tpu.schema.columns import ColumnBatch, DictionaryEncoder, encode_batch
from geomesa_tpu.schema.feature_type import FeatureType
from geomesa_tpu.stats import sketches as sk

# Columns that live host-side only (string dtypes or 64-bit keys).
_HOST_ONLY_DTYPES = ("O", "U", "S")


def _device_view(a: np.ndarray) -> Optional[np.ndarray]:
    """Host column -> device-eligible array (int32/float32/bool), or None."""
    if a.dtype.kind in _HOST_ONLY_DTYPES:
        return None
    if a.dtype == np.float64:
        return a.astype(np.float32)
    if a.dtype == np.int64:
        # raw epoch-ms / z keys stay host-side; generic int64 attribute
        # columns ride as float32 (documented precision tradeoff)
        return a.astype(np.float32)
    if a.dtype == np.uint64:
        return None
    return a


class IndexTable:
    """One index = a sort permutation + sorted KEY columns over the store's
    single master column set.

    Attribute columns are NOT duplicated per index (the pre-refactor layout
    held a full sorted copy of every column in every table — 8x memory at 8
    indices); they are gathered through ``order`` on demand: once per device
    upload (cached), per-query on the host fallback path."""

    def __init__(self, keyspace: KeySpace, ft: FeatureType, n_shards: int):
        self.keyspace = keyspace
        self.ft = ft
        self.n_shards = n_shards
        #: sorted-row -> master-row permutation
        self.order = np.zeros(0, np.int64)
        #: this index's sort-key columns, already in sorted order
        self.key_columns: Dict[str, np.ndarray] = {}
        self._master: Dict[str, np.ndarray] = {}
        self.n = 0
        self.shard_bounds = np.zeros(n_shards + 1, np.int64)
        self._device_cache: Dict[tuple, dict] = {}
        #: host-side staging for the partition pipeline: stacked [S, L]
        #: arrays assembled off-thread by stage_host, consumed (and freed)
        #: by device_columns on the query thread
        self._host_stage: Dict[tuple, np.ndarray] = {}
        self._rank_vocab: Optional[np.ndarray] = None  # for string attr index
        #: key-column quantization shifts when the radix pack-sort built
        #: this table (None = argsort path, raw keys stored)
        self.key_shifts: Optional[Dict[str, int]] = None
        #: round the padded shard length up to a multiple of this, so tables
        #: of near-equal size (time partitions) share compiled kernel shapes
        self.shard_len_multiple = 1

    # -- build ------------------------------------------------------------
    def rebuild(self, columns: Dict[str, np.ndarray], dicts: Dict[str, DictionaryEncoder]):
        """Re-sort by this index's key and re-shard. ``columns`` is the
        master column dict (attributes + every index's key columns); the
        table keeps a reference plus its own sorted key columns."""
        cols = dict(columns)
        ks = self.keyspace
        if isinstance(ks, AttributeKeySpace) and self.ft.attr(ks.attr).type == "string":
            # dictionary codes are insertion-ordered; build a value-ordered
            # rank column so searchsorted windows work for string ranges
            vocab = np.array(dicts[ks.attr].values, dtype=object)
            order = np.argsort(vocab)
            rank_of_code = np.empty(len(vocab), np.int64)
            rank_of_code[order] = np.arange(len(vocab))
            codes = columns[ks.attr]
            ranks = np.where(codes >= 0, rank_of_code[np.clip(codes, 0, None)], -1)
            cols[ks.sort_col] = ranks
            self._rank_vocab = vocab[order]
        fb = ks.fast_build(cols)
        if fb is not None:
            # radix pack-sort: permutation + quantized sorted keys in one
            # value-sort, no argsort / key gather (packsort module)
            self.order, self.key_columns, self.key_shifts = fb
            self._master = cols
            self.n = len(self.order)
        else:
            order = ks.sort_order(cols)
            self.order = np.asarray(
                order, np.int32 if len(order) < 2**31 else np.int64
            )
            self._master = cols
            key_names = (set(ks.key_cols) | {getattr(ks, "sort_col", None)}) - {None}
            self.key_columns = {
                k: cols[k][order] for k in key_names if k in cols
            }
            self.key_shifts = None
            self.n = len(order)
        self.shard_bounds = np.linspace(0, self.n, self.n_shards + 1).astype(np.int64)
        self._device_cache.clear()
        self._host_stage.clear()

    def append_rows(
        self,
        columns: Dict[str, np.ndarray],
        dicts: Dict[str, DictionaryEncoder],
        fresh_cols: Dict[str, np.ndarray],
        n_fresh: int,
    ):
        """LSM append: sort the fresh rows locally and MERGE them into the
        existing order via searchsorted insertion positions — O(old + fresh)
        instead of the full O(n log n) re-sort (SURVEY.md §7 hard part (c)).
        Falls back to :meth:`rebuild` when the key space requires it."""
        ks = self.keyspace
        if self.n == 0 or not ks.can_insert:
            return self.rebuild(columns, dicts)
        key_names = list(self.key_columns)
        if any(k not in fresh_cols for k in key_names):
            return self.rebuild(columns, dicts)
        if self.key_shifts is not None:
            # quantized table: fresh keys must be quantized with the SAME
            # shifts or the merged column would not be sorted
            fb = ks.fast_build(fresh_cols, force_shifts=self.key_shifts)
            if fb is None or fb[2] != self.key_shifts:
                return self.rebuild(columns, dicts)
            fresh_order, fresh_sorted, _ = fb
            fresh_order = fresh_order.astype(np.int64, copy=False)
        else:
            fresh_order = np.asarray(ks.sort_order(fresh_cols), np.int64)
            fresh_sorted = {k: fresh_cols[k][fresh_order] for k in key_names}
        p = ks.insert_positions(self.key_columns, fresh_sorted)
        if p is None:
            return self.rebuild(columns, dicts)
        old_n = self.n
        master_base = old_n  # master rows are [old | fresh]
        total = old_n + n_fresh
        final = np.empty(total, np.int32 if total < 2**31 else np.int64)
        at = p + np.arange(n_fresh)
        is_fresh = np.zeros(total, bool)
        is_fresh[at] = True
        final[is_fresh] = master_base + fresh_order
        final[~is_fresh] = self.order
        self.order = final
        self._master = columns
        # masked scatter-merge of the sorted key columns (np.insert's
        # generality made it the per-flush hotspot)
        merged_keys = {}
        for k in key_names:
            old = self.key_columns[k]
            m = np.empty(total, old.dtype)
            m[at] = fresh_sorted[k].astype(old.dtype, copy=False)
            m[~is_fresh] = old
            merged_keys[k] = m
        self.key_columns = merged_keys
        self.n = total
        self.shard_bounds = np.linspace(0, self.n, self.n_shards + 1).astype(np.int64)
        self._device_cache.clear()
        self._host_stage.clear()

    # -- column access -----------------------------------------------------
    def has_column(self, name: str) -> bool:
        return name in self.key_columns or name in self._master

    def dtype_of(self, name: str):
        col = self.key_columns.get(name)
        if col is None:
            col = self._master.get(name)
        return None if col is None else col.dtype

    def is_host_only(self, name: str) -> bool:
        dt = self.dtype_of(name)
        return dt is None or dt.kind in _HOST_ONLY_DTYPES

    def column_names(self):
        names = dict.fromkeys(self._master)
        names.update(dict.fromkeys(self.key_columns))
        return list(names)

    def col_sorted(self, name: str) -> np.ndarray:
        """Full column in this index's sort order (key cols are stored
        sorted; attribute cols gather through the permutation)."""
        col = self.key_columns.get(name)
        if col is not None:
            return col
        return self._master[name][self.order]

    def shard_cols(self, names, s: int) -> Dict[str, np.ndarray]:
        """Selected columns for one shard, in sorted order."""
        sl = self.shard_slice(s)
        rows = self.order[sl]
        out = {}
        for k in names:
            kc = self.key_columns.get(k)
            if kc is not None:
                out[k] = kc[sl]
            elif k in self._master:
                out[k] = self._master[k][rows]
        return out

    def shard_rows_cols(self, names, s: int, idx: np.ndarray) -> Dict[str, np.ndarray]:
        """Selected columns for specific sorted-order row positions of one
        shard — gathers only ``idx`` rows (the refinement-candidate path),
        avoiding a full-shard copy."""
        sl = self.shard_slice(s)
        rows = self.order[sl.start + idx]
        out = {}
        for k in names:
            kc = self.key_columns.get(k)
            if kc is not None:
                out[k] = kc[sl.start + idx]
            elif k in self._master:
                out[k] = self._master[k][rows]
        return out

    @property
    def shard_len(self) -> int:
        """Padded per-shard length (static shape for the device).

        Partitioned children always round up to ``shard_len_multiple``
        (geomesa.partition.shard.bucket) so near-equal partitions share
        kernel shapes; under warm-path shape bucketing plain stores round
        to ``geomesa.compact.shard.bucket`` (8192) the same way, so a
        small insert never changes L — the padded scan kernel's static
        shape — and therefore never recompiles. Padding costs masked rows
        (≤ bucket/L relative overhead: 0.4% at the bench's 2.5M-row
        shards)."""
        if self.n == 0:
            return 0
        m = int(np.max(np.diff(self.shard_bounds)))
        b = self.shard_len_multiple
        if b <= 1 and config.COMPACT_BUCKETING.to_bool():
            b = config.COMPACT_SHARD_BUCKET.to_int() or 1
        return m if b <= 1 else -(-m // b) * b

    def shard_slice(self, s: int) -> slice:
        return slice(int(self.shard_bounds[s]), int(self.shard_bounds[s + 1]))

    # -- device layout ----------------------------------------------------
    def _stack_host(self, name: str, L: int) -> Optional[np.ndarray]:
        """One column's padded [n_shards, L] HOST array (the slab gather +
        pad half of a device upload) — pure numpy, no jax."""
        if not self.has_column(name):
            return None
        dv = _device_view(self.col_sorted(name))
        if dv is None:
            return None
        stacked = np.zeros((self.n_shards, L), dtype=dv.dtype)
        for s in range(self.n_shards):
            sl = self.shard_slice(s)
            stacked[s, : sl.stop - sl.start] = dv[sl]
        return stacked

    def stage_host(self, names: Sequence[str]) -> int:
        """Assemble (and cache) the stacked host arrays for ``names`` —
        the expensive host half of :meth:`device_columns`, jax-free so the
        partition pipeline's prefetch thread can overlap it with another
        partition's device execution. ``device_columns`` consumes each
        staged array (paying only the device_put) and frees it. Columns
        already device-resident are skipped: in the warm steady state
        (device cache hit) staging would be pure waste, and the pipeline's
        consumer additionally clears leftovers after each partition.
        Returns the bytes newly staged by THIS call (the per-query cost
        ledger's ``bytes_staged`` contribution — 0 in the warm state)."""
        L = self.shard_len
        resident = set()
        for cached in list(self._device_cache.values()):
            resident.update(cached)
        staged_bytes = 0
        for name in sorted(set(names)):
            if name in resident or (name, L) in self._host_stage:
                continue
            stacked = self._stack_host(name, L)
            if stacked is not None:
                self._host_stage[(name, L)] = stacked
                staged_bytes += int(stacked.nbytes)
        return staged_bytes

    def device_columns(self, names: Sequence[str], sharding=None):
        """Stacked padded [n_shards, shard_len] jnp arrays for ``names``
        (cached). With a ``NamedSharding``, columns are placed sharded over
        the mesh's 'shard' axis. Host-only columns are silently skipped —
        callers must route predicates on those through the host path."""
        import jax

        key = (tuple(sorted(set(names))), id(sharding))
        L = self.shard_len
        cached = self._device_cache.get(key)
        if cached is not None:
            # free any staged host copies a prefetcher built before this
            # hit (they would otherwise sit as dead duplicates)
            for name in key[0]:
                self._host_stage.pop((name, L), None)
            return cached
        out = {}
        for name in key[0]:
            stacked = self._host_stage.pop((name, L), None)
            if stacked is None:
                stacked = self._stack_host(name, L)
            if stacked is None:
                continue
            out[name] = (
                jax.device_put(stacked, sharding)
                if sharding is not None
                else jax.device_put(stacked)
            )
        self._device_cache[key] = out
        return out

    # -- scan windows ------------------------------------------------------
    def windows(self, plan: KeyPlan) -> Tuple[np.ndarray, np.ndarray]:
        """Resolve the key plan to per-shard row windows, padded to a common
        window count: (starts [S, K], ends [S, K]) in *local* shard rows."""
        per_shard = []
        for s in range(self.n_shards):
            sl = self.shard_slice(s)
            n = sl.stop - sl.start
            # window resolution only ever touches the sort-key columns
            shard_cols = {k: v[sl] for k, v in self.key_columns.items()}
            if self.key_shifts is not None:
                shard_cols["__shifts__"] = self.key_shifts
            if self._rank_vocab is not None:
                vocab = self._rank_vocab

                def rank_lookup(value, side):
                    if side == "lo":
                        return int(np.searchsorted(vocab, value, side="left"))
                    return int(np.searchsorted(vocab, value, side="right")) - 1

                shard_cols["__rank_lookup__"] = rank_lookup
            starts, ends = plan.windows(shard_cols, n)
            per_shard.append((starts, ends))
        K = max(len(s) for s, _ in per_shard)
        # pad the window count to its shape bucket (power of two above the
        # geomesa.compact.bucket.floor): K is a kernel static shape, and
        # bucketing keeps near-identical queries (or the same query across
        # time partitions, or distinct queries with few windows) on one
        # compiled kernel. Padded windows are (0, 0) — empty, exact.
        from geomesa_tpu.kernels.registry import bucket_count

        K = bucket_count(K)
        S = self.n_shards
        starts = np.zeros((S, K), np.int32)
        ends = np.zeros((S, K), np.int32)
        for i, (s, e) in enumerate(per_shard):
            starts[i, : len(s)] = s
            ends[i, : len(e)] = e
        return starts, ends

    def host_gather(self, global_mask: np.ndarray,
                    names: Optional[Sequence[str]] = None) -> ColumnBatch:
        """Select matching rows from the host master copy.

        ``global_mask`` is over the padded [S, L] layout (flattened).
        ``names``: optional projection — only the listed columns (plus
        their derived ``<name>__*`` companions and the feature id) gather,
        so projected queries on lazily-loaded cold partitions touch only
        the column groups they need (ColumnGroups.scala:28 analog)."""
        L = self.shard_len
        idx = []
        for s in range(self.n_shards):
            sl = self.shard_slice(s)
            local = global_mask[s * L : s * L + (sl.stop - sl.start)]
            idx.append(np.nonzero(local)[0] + sl.start)
        sel = np.concatenate(idx) if idx else np.zeros(0, np.int64)
        return self._gather_sorted(sel, names)

    def host_gather_positions(self, positions: np.ndarray,
                              names: Optional[Sequence[str]] = None) -> ColumnBatch:
        """Like :meth:`host_gather` but from padded [S*L] flat POSITIONS
        (device top-k / kNN results) — O(k), never touching a full-table
        mask. Row order follows ``positions``."""
        positions = np.asarray(positions, np.int64)
        L = self.shard_len
        s = positions // L
        sel = self.shard_bounds[s] + (positions - s * L)
        return self._gather_sorted(sel, names)

    def _gather_sorted(self, sel: np.ndarray,
                       names: Optional[Sequence[str]] = None) -> ColumnBatch:
        rows = self.order[sel]
        cols = self.column_names() if names is None else [
            k for k in self.column_names()
            if k == "__fid__" or k in names
            or any(k.startswith(n + "__") for n in names)
        ]
        out = {}
        for k in cols:
            if k in self._master:  # master wins: key copies may be quantized
                out[k] = self._master[k][rows]
            else:
                kc = self.key_columns.get(k)
                if kc is not None:
                    out[k] = kc[sel]
        return ColumnBatch(out, len(sel))


class FeatureStore:
    """All index tables + write buffer + persisted stats for one schema.

    The GeoMesaDataStore-per-type analog: schema, writer, tables, stats
    (reference GeoMesaDataStore.scala:49, MetadataBackedStats)."""

    _uids = itertools.count()

    def __init__(self, ft: FeatureType, n_shards: Optional[int] = None):
        #: process-unique id: cache keys must never collide across store
        #: objects (id() can be recycled after GC — partition children churn)
        self.uid = next(FeatureStore._uids)
        self.ft = ft
        self.n_shards = n_shards or ft.shards or config.DEFAULT_SHARDS.to_int()
        self.dicts: Dict[str, DictionaryEncoder] = {}
        self.keyspaces = keyspaces_for_schema(ft)
        self.tables: Dict[str, IndexTable] = {
            ks.name: IndexTable(ks, ft, self.n_shards) for ks in self.keyspaces
        }
        self._buffer: List[ColumnBatch] = []
        self._all: Optional[ColumnBatch] = None
        #: cached index-key columns for the current master rows
        self._key_cols: Dict[str, np.ndarray] = {}
        self._lock = threading.Lock()
        self.stats = self._init_stats()
        #: bumped on every data mutation; keys cross-query kernel caches
        self.version = 0
        #: changes whenever PERSISTED rows are rewritten (delete, column
        #: adds) rather than appended; incremental checkpoints compare it
        #: to decide between append-a-chunk and full rewrite. EVERY
        #: mutation path that rewrites existing rows must call
        #: :meth:`_bump_epoch`.
        self.mutation_epoch = uuid.uuid4().hex

    def _init_stats(self) -> Dict[str, sk.Stat]:
        ft = self.ft
        out: Dict[str, sk.Stat] = {"count": sk.CountStat()}
        if ft.geom_field:
            out["bounds"] = sk.MinMax(ft.geom_field)
        if ft.dtg_field:
            out["time-bounds"] = sk.MinMax(ft.dtg_field)
        if ft.geom_field and ft.attr(ft.geom_field).is_point:
            out["z2-histogram"] = sk.Z2HistogramStat(ft.geom_field, 1024)
        if ft.geom_field and ft.dtg_field and ft.attr(ft.geom_field).is_point:
            out["z3-histogram"] = sk.Z3HistogramStat(
                ft.geom_field, ft.dtg_field, ft.time_period, 1024
            )
        for a in ft.attributes:
            if a.indexed and not a.is_geom and a.type != "json":
                if a.type == "string":
                    out[f"enum-{a.name}"] = sk.EnumerationStat(a.name)
                else:
                    out[f"minmax-{a.name}"] = sk.MinMax(a.name)
        return out

    # -- write path --------------------------------------------------------
    def append(self, data: Dict, fids=None, visibilities=None,
               observer=None) -> int:
        """Buffer an ingest batch (encoded immediately; keys at flush).

        ``visibilities``: per-feature visibility expression(s) — one string
        for the whole batch or a sequence per feature (geomesa-security
        analog; dictionary-encoded into the ``__vis__`` code column).

        ``observer``: optional callable handed the ENCODED ColumnBatch
        after it buffers — the standing-query delta hook (docs/
        STANDING.md) reads the exact columns a window re-scan would."""
        from geomesa_tpu.security import VIS_COLUMN, parse_visibility

        batch = encode_batch(self.ft, data, self.dicts, fids)
        vd = self.dicts.get(VIS_COLUMN)
        if vd is None:
            vd = self.dicts[VIS_COLUMN] = DictionaryEncoder([""])
        if visibilities is None:
            vis = np.zeros(batch.n, np.int32)
        else:
            if isinstance(visibilities, str):
                visibilities = [visibilities] * batch.n
            exprs = [v or "" for v in visibilities]
            for v in set(exprs):
                parse_visibility(v)  # validate at write time
            vis = vd.encode(exprs)
        batch.columns[VIS_COLUMN] = vis
        with self._lock:
            self._buffer.append(batch)
        if observer is not None:
            observer(batch)
        return batch.n

    @property
    def pending(self) -> int:
        return sum(b.n for b in self._buffer)

    @property
    def count(self) -> int:
        return (self._all.n if self._all else 0) + self.pending

    def flush(self):
        """Merge buffer into tables: compute all index keys in one vectorized
        pass, then rebuild each table's sort (atomic mutation batch parity,
        reference IndexAdapter.scala:140-154)."""
        with self._lock:
            if not self._buffer:
                return
            fresh = ColumnBatch.concat(self._buffer)
            self._buffer = []
        # index keys for the FRESH rows only (per-row functions — old rows'
        # keys are cached in self._key_cols and just concatenated)
        fresh_keys: Dict[str, np.ndarray] = {}
        for ks in self.keyspaces:
            fresh_keys.update(ks.index_keys(self.ft, fresh))
        # write-time stats on the fresh rows; include the freshly-computed
        # key columns so Z-histograms reuse them instead of re-encoding
        # (the period marker tells Z3 sketches the keys match their config)
        stat_cols = {**fresh.columns, **fresh_keys}
        if "__z3" in fresh_keys:
            stat_cols["__z3_period"] = self.ft.time_period
        for st in self.stats.values():
            st.observe(stat_cols)
        if self._all is not None:
            # datasets persisted before visibility support lack __vis__
            from geomesa_tpu.security import VIS_COLUMN

            if VIS_COLUMN in fresh.columns and VIS_COLUMN not in self._all.columns:
                self._all.columns[VIS_COLUMN] = np.zeros(self._all.n, np.int32)
                # the back-fill REWRITES persisted rows (they gain a column):
                # an incremental checkpoint appending only the fresh chunk
                # would leave old chunks without __vis__, silently dropping
                # visibility labels on reload — force a full rewrite
                self._bump_epoch()
        if self._all is None:
            merged = fresh
            key_cols: Dict[str, np.ndarray] = {**fresh.columns, **fresh_keys}
        else:
            merged = ColumnBatch.concat([self._all, fresh])
            key_cols = dict(merged.columns)
            old_keys = self._key_cols
            recomputed = set()
            for k, fv in fresh_keys.items():
                ov = old_keys.get(k)
                if ov is None:  # cold cache (load()): recompute, once per ks
                    for ks in self.keyspaces:
                        if k in ks.key_cols and ks.name not in recomputed:
                            key_cols.update(ks.index_keys(self.ft, merged))
                            recomputed.add(ks.name)
                            break
                else:
                    key_cols[k] = np.concatenate([ov, fv])
        self._all = ColumnBatch(
            {k: key_cols[k] for k in merged.columns}, merged.n
        )
        self._key_cols = {
            k: v for k, v in key_cols.items() if k not in merged.columns
        }
        fresh_all = {**fresh.columns, **fresh_keys}
        for ks in self.keyspaces:
            self.tables[ks.name].append_rows(
                key_cols, self.dicts, fresh_all, fresh.n
            )
        self.version += 1

    # -- schema / index lifecycle -----------------------------------------
    def add_columns(self, new_ft: FeatureType, added) -> None:
        """Append null-filled columns for ``added`` attributes IN PLACE —
        no index key changes, so every table keeps its sort permutation
        and only learns the new master columns (the O(1)-per-index path
        GeoMesaDataStore.scala:288-336's append-only updateSchema implies;
        r4 rebuilt + re-flushed the whole store here)."""
        from geomesa_tpu.schema.columns import null_columns

        self.flush()
        self.ft = new_ft
        n = self._all.n if self._all is not None else 0
        cols = null_columns(new_ft, added, n, self.dicts)
        self._bump_epoch()
        if n:
            self._all.columns.update(cols)
        for t in self.tables.values():
            t.ft = new_ft
            if n:
                t._master.update(cols)
                t._device_cache.clear()
        self.version += 1

    def _bump_epoch(self) -> None:
        """Mark persisted rows as rewritten: the next incremental
        checkpoint must do a full rewrite, not append a chunk."""
        self.mutation_epoch = uuid.uuid4().hex

    def _attr_stat_key(self, attr: str) -> str:
        a = self.ft.attr(attr)
        return f"enum-{attr}" if a.type == "string" else f"minmax-{attr}"

    def build_missing_table(self, t: IndexTable) -> None:
        """Build an empty table's permutation from the master rows —
        used both when an index is enabled on a live store and when a
        partition snapshot predating the index is loaded. Only the
        keyspace's own input columns are touched, so lazily-loaded
        snapshots (_LazyCols) materialize one column, not the store."""
        if self._all is None or not self._all.n:
            return
        ks = t.keyspace
        fresh = ks.index_keys(self.ft, self._all)
        self._key_cols.update(fresh)
        needed = dict(fresh)
        if isinstance(ks, AttributeKeySpace):
            needed[ks.attr] = self._all.columns[ks.attr]
        t.rebuild(needed, self.dicts)
        # master lookup mapping for on-demand attribute gathers:
        # share an existing table's (possibly lazy) master
        other = next((ot for oname, ot in self.tables.items()
                      if oname != ks.name and ot.n), None)
        if other is not None:
            base = other._master
            for k, v in t._master.items():
                if k not in base:
                    base[k] = v
            t._master = base
        else:
            merged = {**self._all.columns, **self._key_cols}
            for k, v in t._master.items():
                merged.setdefault(k, v)
            t._master = merged

    def ensure_attr_sketch(self, attr: str) -> None:
        """Retroactively build the write-time sketch the cost model needs
        for an attribute index, if absent."""
        skey = self._attr_stat_key(attr)
        if skey in self.stats:
            return
        a = self.ft.attr(attr)
        stat = (sk.EnumerationStat(attr) if a.type == "string"
                else sk.MinMax(attr))
        if self._all is not None and self._all.n:
            stat.observe(self._all.columns)
        self.stats[skey] = stat

    def add_attribute_index(self, attr: str) -> None:
        """Enable an attribute index on a live schema: build ONLY the new
        sort permutation over the existing master columns (the reference
        validates such transitions in updateSchema,
        GeoMesaDataStore.scala:288-336; r4 required a full re-create)."""
        a = self.ft.attr(attr)
        if a.is_geom or a.type == "json":
            raise ValueError(f"cannot attribute-index {attr!r} ({a.type})")
        ks = AttributeKeySpace(attr, self.ft.geom_field, a.type)
        if ks.name in self.tables:
            return  # already indexed
        self.flush()
        self.keyspaces.append(ks)
        t = IndexTable(ks, self.ft, self.n_shards)
        self.tables[ks.name] = t
        self.build_missing_table(t)
        self.ensure_attr_sketch(attr)
        self.version += 1

    def remove_attribute_index(self, attr: str) -> None:
        """Drop an attribute index (permutation + key columns + sketch);
        master data is untouched."""
        name = f"attr:{attr}"
        if name not in self.tables:
            raise KeyError(f"no attribute index on {attr!r}")
        del self.tables[name]
        self.keyspaces = [k for k in self.keyspaces if k.name != name]
        self._key_cols.pop(f"__attr_{attr}", None)
        self.stats.pop(self._attr_stat_key(attr), None)
        self.version += 1

    def wkt_geoms(self) -> List[str]:
        """Non-point geometry attributes stored WITH exact WKT (drives the
        Arrow field type for extent geometries)."""
        cols = self._all.columns if self._all is not None else {}
        return [
            a.name for a in self.ft.attributes
            if a.is_geom and a.name + "__wkt" in cols
        ]

    def delete(self, mask_fn) -> int:
        """Remove rows matching ``mask_fn(columns) -> bool mask`` (host)."""
        self.flush()
        if self._all is None or self._all.n == 0:
            return 0
        mask = mask_fn(self._all.columns)
        removed = int(mask.sum())
        if removed == 0:
            return 0
        keep_mask = ~mask
        keep = self._all.select(keep_mask)
        self._all = keep
        self._bump_epoch()
        self.stats["count"] = sk.CountStat(keep.n)
        key_cols: Dict[str, np.ndarray] = dict(keep.columns)
        # filter the cached key columns with the same mask (per-row values)
        self._key_cols = {k: v[keep_mask] for k, v in self._key_cols.items()}
        key_cols.update(self._key_cols)
        for ks in self.keyspaces:
            for k in ks.key_cols:
                if k not in key_cols:
                    key_cols.update(ks.index_keys(self.ft, keep))
                    self._key_cols.update({
                        kk: vv for kk, vv in key_cols.items()
                        if kk not in keep.columns
                    })
                    break
            self.tables[ks.name].rebuild(key_cols, self.dicts)
        self.version += 1
        return removed
