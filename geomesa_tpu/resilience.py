"""Resilience layer: retry/deadline/circuit-breaker policies, deterministic
fault injection, and typed partial-result degradation.

The production posture of the reference stack is spread across several
mechanisms this module unifies for the TPU port:

* tablet-server retry semantics (Accumulo/HBase client retries under the
  datastore) -> :class:`RetryPolicy` — exponential backoff + full jitter from
  a seeded RNG, so a retry schedule is reproducible in tests;
* the ThreadManagement query killer (index/utils/ThreadManagement.scala:28-80)
  -> :class:`Deadline`, the primitive under ``planning.executor.query_deadline``
  (which remains the public scan-scope API);
* client-side connection fencing -> :class:`CircuitBreaker`, so a dead
  sidecar fails fast instead of paying the full timeout per call;
* GeoBlocks-style partial aggregation over pruned regions (PAPERS.md) ->
  :class:`PartialResult` / :class:`Skipped` — a scan over N partitions where
  K fail can return the aggregate over N−K plus a structured account of what
  was skipped and why, instead of raising or hanging.

Fault injection
---------------
Every I/O edge calls :func:`fault_point` with a dotted site name
(``sidecar.do_get``, ``fs.read_partition``, ``stream.poll.decode``,
``exec.partition.scan``). When no injector is installed the call is a single
module-global ``None`` check — fault points sit at partition/RPC/message
granularity, never inside per-row loops, so the disabled cost is unmeasurable
on the hot scan path. Installing an injector requires the
``geomesa.fault.injection`` property to be enabled, and rules are seeded, so
a chaos scenario replays identically run to run::

    with config.FAULT_INJECTION.scoped("true"):
        with inject_faults(seed=7) as inj:
            inj.fail("sidecar.do_get", errors.Unavailable("sidecar restart"), times=2)
            client.count("t")   # fails twice, retries, succeeds

Degradation contract (docs/RESILIENCE.md)
-----------------------------------------
Partition-loop call sites consult :func:`partial_allowed`. Strict mode (the
default) re-raises — behavior is unchanged from before this module existed.
Under ``with allow_partial() as partial:`` (or the ``geomesa.scan.partial``
property) a failing partition is recorded via :func:`record_skip` and the
scan continues; the aggregate over the surviving partitions is returned and
``partial.skipped`` lists what was dropped. Degraded aggregates are exact
over the partitions that survived — never an estimate.
"""

from __future__ import annotations

import fnmatch
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Generic, List, Optional, Tuple, TypeVar

from geomesa_tpu import config

T = TypeVar("T")


# ---------------------------------------------------------------------------
# Errors
# ---------------------------------------------------------------------------


class QueryTimeoutError(RuntimeError):
    """Raised when a scan exceeds its :class:`Deadline` (``geomesa.query.
    timeout`` — the reference's ThreadManagement query killer). Re-exported
    by ``planning.executor`` for compatibility."""


class DeadlineShedError(QueryTimeoutError):
    """Raised by the serving scheduler when a query is SHED — dropped at
    admission or dispatch because its deadline budget cannot be met (already
    expired while queued, or smaller than the estimated queue wait) —
    BEFORE any planning or device work ran. Crosses the sidecar wire as a
    ``[GM-SHED]`` coded Flight error (PROTOCOL §7.1). Subclasses
    :class:`QueryTimeoutError` so existing deadline-aware callers classify
    it as a timeout; ``retry_after_s`` is advisory (0 = don't retry: a
    deadline-bound request will not make it on a busy queue either)."""

    def __init__(self, msg: str, retry_after_s: float = 0.0):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class AdmissionRejectedError(RuntimeError):
    """Raised by the serving scheduler when the bounded admission queue is
    full: backpressure, not failure — the server is healthy but saturated.
    Crosses the wire as ``[GM-OVERLOADED]`` (retryable with backoff)."""

    def __init__(self, depth: int):
        super().__init__(
            f"admission queue full ({depth} queued); retry with backoff or "
            "raise geomesa.serving.queue.depth"
        )
        self.depth = depth


class DeviceDrainError(RuntimeError):
    """Raised when serving work is rejected or stranded because its
    executor slot / device was DRAINED: the slot's dispatcher died, or the
    pinned device was cordoned (operator action or an open device breaker)
    and the supervisor re-clamped the pool (docs/RESILIENCE.md §6,
    docs/SERVING.md). Crosses the sidecar wire as ``[GM-DRAINING]``
    (retryable: a respawned slot — or a re-opened stream — will serve the
    request; the device work that was in flight is NOT known to have
    committed, so streams must re-open, not resume)."""


class FleetPartialError(RuntimeError):
    """Raised by the fleet router (docs/RESILIENCE.md §7) when every ring
    owner of some cell range is down and strict mode forbids degrading:
    the message leads with the typed ``[GM-FLEET-PARTIAL]`` code and the
    error carries EXACT survivor accounting — the aggregate over the
    cell groups that DID complete (``value`` over ``ok`` of ``total``
    groups) plus the :class:`Skipped` records for the rest. Under
    ``allow_partial()`` the router returns the survivor aggregate and
    records the same skips instead of raising (the §3 degradation
    contract, generalized from partitions to replicas)."""

    def __init__(self, msg: str, value: Any = None, ok: int = 0,
                 total: int = 0, skipped: Optional[List["Skipped"]] = None):
        super().__init__(f"[GM-FLEET-PARTIAL] {msg}")
        self.value = value
        self.ok = ok
        self.total = total
        self.skipped = list(skipped or ())


class CircuitOpenError(RuntimeError):
    """Raised by :meth:`CircuitBreaker.allow` while the breaker is open:
    the callee has failed repeatedly and calls are being fenced off until
    the reset window elapses."""

    def __init__(self, name: str, retry_after_s: float):
        super().__init__(
            f"circuit {name!r} is open (retry after {retry_after_s:.1f}s)"
        )
        self.breaker_name = name
        self.retry_after_s = retry_after_s


class InjectedFault(RuntimeError):
    """Default error type raised by a fault-injection rule."""


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------


@dataclass
class RetryPolicy:
    """Exponential backoff with full jitter from a seeded RNG.

    ``attempts`` is the TOTAL number of tries (1 = no retry). Delay before
    retry ``i`` (1-based) is ``min(base_ms * 2**(i-1), max_ms)`` scaled by
    ``1 - jitter * rng.random()`` — deterministic for a given seed."""

    attempts: int = 3
    base_ms: float = 50.0
    max_ms: float = 5_000.0
    jitter: float = 0.2
    seed: Optional[int] = None
    sleep: Callable[[float], None] = time.sleep

    def __post_init__(self):
        self._rng = random.Random(self.seed)

    @staticmethod
    def from_config(seed: Optional[int] = None) -> "RetryPolicy":
        def cfg(v, default):
            # explicit 0 is a real setting (no delay / no retry): only an
            # UNSET property falls back to the default
            return default if v is None else v

        return RetryPolicy(
            attempts=cfg(config.RETRY_ATTEMPTS.to_int(), 3),
            base_ms=cfg(config.RETRY_BASE_MS.to_float(), 50.0),
            max_ms=cfg(config.RETRY_MAX_MS.to_float(), 5_000.0),
            jitter=cfg(config.RETRY_JITTER.to_float(), 0.0),
            seed=seed,
        )

    def delays_ms(self) -> List[float]:
        """The backoff schedule for this policy's remaining retries
        (consumes RNG state — one call per executed schedule)."""
        out = []
        for i in range(max(self.attempts - 1, 0)):
            d = min(self.base_ms * (2.0 ** i), self.max_ms)
            if self.jitter:
                d *= 1.0 - self.jitter * self._rng.random()
            out.append(d)
        return out

    def call(self, fn: Callable[[], T],
             retryable: Callable[[BaseException], bool] = lambda e: True,
             deadline: "Optional[Deadline]" = None,
             on_retry: Optional[Callable[[int, BaseException], None]] = None) -> T:
        """Run ``fn`` with retries. ``retryable(exc)`` gates each retry;
        a live ``deadline`` stops retrying (and trims sleeps) when the
        budget would be exceeded."""
        last: Optional[BaseException] = None
        attempts = max(self.attempts, 1)  # 0/negative still means one try
        for attempt in range(1, attempts + 1):
            try:
                return fn()
            except Exception as e:  # KeyboardInterrupt/SystemExit propagate
                last = e
                if attempt >= attempts or not retryable(e):
                    raise
                d = min(self.base_ms * (2.0 ** (attempt - 1)), self.max_ms)
                if self.jitter:
                    d *= 1.0 - self.jitter * self._rng.random()
                if deadline is not None:
                    rem = deadline.remaining_s()
                    if rem is not None:
                        if rem <= 0:
                            raise
                        d = min(d, rem * 1000.0)
                if on_retry is not None:
                    on_retry(attempt, e)
                if d > 0:
                    self.sleep(d / 1000.0)
        raise last  # pragma: no cover — loop always returns or raises


# ---------------------------------------------------------------------------
# Deadline
# ---------------------------------------------------------------------------

_deadline_local = threading.local()


@dataclass(frozen=True)
class Deadline:
    """A wall-clock budget. ``expires_at`` is ``time.monotonic()``-based;
    ``None`` means unlimited (checks are no-ops)."""

    expires_at: Optional[float]

    @staticmethod
    def after(timeout_s: Optional[float]) -> "Deadline":
        return Deadline(
            None if timeout_s is None else time.monotonic() + timeout_s
        )

    def remaining_s(self) -> Optional[float]:
        if self.expires_at is None:
            return None
        return self.expires_at - time.monotonic()

    @property
    def expired(self) -> bool:
        return self.expires_at is not None and time.monotonic() > self.expires_at

    def check(self, what: str = "query") -> None:
        if self.expired:
            raise QueryTimeoutError(
                f"{what} exceeded geomesa.query.timeout; narrow the filter "
                "or raise the timeout"
            )


UNLIMITED = Deadline(None)


def current_deadline() -> Deadline:
    """The innermost active deadline scope on this thread (UNLIMITED when
    none). Remote/IO edges use it to propagate the query budget into
    per-call timeouts."""
    d = getattr(_deadline_local, "stack", None)
    return d[-1] if d else UNLIMITED


class _DeadlineScope:
    def __init__(self, deadline: Deadline):
        self.deadline = deadline

    def __enter__(self) -> Deadline:
        stack = getattr(_deadline_local, "stack", None)
        if stack is None:
            stack = _deadline_local.stack = []
        stack.append(self.deadline)
        self._stack = stack  # enter/exit may run on different threads
        return self.deadline

    def __exit__(self, *exc):
        # generators (streamed exports) can resume on a different thread
        # than the one that opened the scope: pop from the ENTERED stack,
        # and remove this scope's own deadline even if others interleaved
        try:
            self._stack.remove(self.deadline)
        except ValueError:
            pass
        return False


def deadline_scope(timeout_s: Optional[float]) -> _DeadlineScope:
    """Scope a deadline over this thread (nests; inner scopes may be
    tighter or looser — ``check_deadline`` honors the innermost)."""
    return _DeadlineScope(Deadline.after(timeout_s))


def adopt_deadline(deadline: Deadline) -> _DeadlineScope:
    """Install an EXISTING deadline as this thread's innermost scope —
    the cross-thread half of deadline propagation (the
    ``tracing.snapshot``/``adopt`` analog): a worker fanning out on
    behalf of a query captures ``current_deadline()`` on the caller and
    re-enters it here, so the same wall-clock budget bounds every
    branch (fleet scatter dispatch uses this)."""
    return _DeadlineScope(deadline)


def check_deadline(what: str = "query") -> None:
    """Raise :class:`QueryTimeoutError` if the innermost deadline passed.
    Called between per-shard host passes, around device dispatches, and per
    partition — kernels are not interruptible, so enforcement is at phase
    granularity (the guarantee the reference's killer thread gives a
    blocking scan)."""
    current_deadline().check(what)


# ---------------------------------------------------------------------------
# CircuitBreaker
# ---------------------------------------------------------------------------


class CircuitBreaker:
    """Count-based breaker: ``threshold`` consecutive failures open the
    circuit; after ``reset_ms`` ONE trial call is admitted (half-open) —
    success closes, failure re-opens. While that single trial is in
    flight, every other caller is fenced with :class:`CircuitOpenError`:
    a half-open breaker must probe the callee with one request, not a
    thundering herd of them. ``clock`` is injectable so tests advance
    time deterministically."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"

    def __init__(self, name: str, threshold: Optional[int] = None,
                 reset_ms: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.threshold = threshold if threshold is not None else (
            config.BREAKER_THRESHOLD.to_int() or 5
        )
        self.reset_ms = reset_ms if reset_ms is not None else (
            config.BREAKER_RESET_MS.to_float() or 30_000.0
        )
        self.clock = clock
        self._lock = threading.Lock()
        self._failures = 0
        self._state = self.CLOSED
        self._opened_at = 0.0
        self._trial_in_flight = False
        self._trial_started = 0.0
        self._trial_thread: Optional[int] = None

    @property
    def state(self) -> str:
        with self._lock:
            return self._effective_state()

    def _effective_state(self) -> str:
        if self._state == self.OPEN and (
            (self.clock() - self._opened_at) * 1000.0 >= self.reset_ms
        ):
            return self.HALF_OPEN
        return self._state

    def allow(self) -> None:
        """Raise :class:`CircuitOpenError` unless a call may proceed.
        In half-open, admits ONE caller as the trial request; concurrent
        callers are fenced until the trial resolves (or, if the trial
        never reports back — caller died mid-call — until a full reset
        window has elapsed since it started, when a new trial is
        admitted so the breaker cannot wedge half-open forever)."""
        with self._lock:
            st = self._effective_state()
            if st == self.OPEN:
                rem = self.reset_ms / 1000.0 - (self.clock() - self._opened_at)
                raise CircuitOpenError(self.name, max(rem, 0.0))
            if st == self.HALF_OPEN:
                if self._trial_in_flight:
                    stale = (
                        (self.clock() - self._trial_started) * 1000.0
                        >= self.reset_ms
                    )
                    if not stale:
                        rem = (
                            self.reset_ms / 1000.0
                            - (self.clock() - self._trial_started)
                        )
                        raise CircuitOpenError(self.name, max(rem, 0.0))
                self._state = self.HALF_OPEN
                self._trial_in_flight = True
                self._trial_started = self.clock()
                self._trial_thread = threading.get_ident()

    def record_success(self) -> None:
        with self._lock:
            if (
                self._state == self.HALF_OPEN
                and self._trial_in_flight
                and self._trial_thread is not None
                and threading.get_ident() != self._trial_thread
            ):
                # a SUPERSEDED trial (slow caller outlived its staleness
                # window; a fresher trial is probing now) reporting back
                # late: its success must not close the circuit over the
                # live trial's head — the live trial's own report decides
                return
            self._failures = 0
            self._state = self.CLOSED
            self._trial_in_flight = False
            self._trial_thread = None

    def record_failure(self) -> None:
        # failures count from ANY caller, including a superseded trial —
        # a failure signal from the callee is always valid evidence
        with self._lock:
            self._failures += 1
            if self._state == self.HALF_OPEN or self._failures >= self.threshold:
                self._state = self.OPEN
                self._opened_at = self.clock()
            self._trial_in_flight = False
            self._trial_thread = None

    def trip(self) -> None:
        """Force the circuit OPEN regardless of the failure count — the
        device-health latency-outlier path (parallel/health.py): evidence
        other than a thrown exception (a consecutive-outlier streak) has
        judged the callee sick. Recovery follows the normal half-open
        trial after ``reset_ms``."""
        with self._lock:
            self._failures = max(self._failures, self.threshold)
            self._state = self.OPEN
            self._opened_at = self.clock()
            self._trial_in_flight = False
            self._trial_thread = None


_breakers: Dict[str, CircuitBreaker] = {}
_breakers_lock = threading.Lock()


def breaker(name: str, **kw) -> CircuitBreaker:
    """Process-wide named breaker registry (one breaker per sidecar
    location, shared by every client to it)."""
    with _breakers_lock:
        b = _breakers.get(name)
        if b is None:
            b = _breakers[name] = CircuitBreaker(name, **kw)
        return b


def guarded_root_io(root: str, fn):
    """Run one storage-root I/O under the root's ``fs.root:<abspath>``
    circuit breaker (docs/RESILIENCE.md; the remote-root treatment of
    the lake tier, docs/LAKE.md): an open circuit fences fast, transient
    ``OSError``s charge the breaker, success resets it. A
    ``FileNotFoundError`` is the caller's business (a missing file says
    nothing about the mount) and never charges. THE one copy of this
    sequence — fs/storage.py and io/arrow_store.py both route here."""
    import os as _os

    br = breaker("fs.root:" + _os.path.abspath(root))
    br.allow()  # raises CircuitOpenError while the root is fenced
    try:
        out = fn()
    except OSError as e:
        if not isinstance(e, FileNotFoundError):
            br.record_failure()
        raise
    br.record_success()
    return out


def reset_breakers() -> None:
    """Drop all registered breakers (test isolation)."""
    with _breakers_lock:
        _breakers.clear()


def breaker_states() -> Dict[str, str]:
    """name -> effective state for every registered breaker (the /healthz
    surface in obs.py; docs/OBSERVABILITY.md)."""
    with _breakers_lock:
        items = list(_breakers.items())
    return {name: b.state for name, b in items}


# ---------------------------------------------------------------------------
# Deterministic fault injection
# ---------------------------------------------------------------------------


@dataclass
class _FaultRule:
    pattern: str
    error: Any                      # exception instance, type, or factory
    times: Optional[int] = None     # None = every matching hit
    p: float = 1.0                  # probability per hit (seeded RNG)
    delay_s: float = 0.0            # sleep before raising/continuing
    hits: int = 0                   # matched (after p/times gating)
    #: optional context predicate: the rule matches only when
    #: ``where(ctx)`` is truthy (ctx = the fault point's keyword args —
    #: e.g. target device 3 only: ``where=lambda c: c.get("device") == 3``)
    where: Optional[Callable[[Dict[str, Any]], bool]] = None


class FaultInjector:
    """Seeded registry of fault rules matched against fault-point names
    (``fnmatch`` patterns: ``sidecar.*``, ``fs.read_partition``, ...)."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._rng = random.Random(seed)
        self._rules: List[_FaultRule] = []
        self._lock = threading.Lock()
        self.fired: List[Tuple[str, str]] = []  # (site, error repr)

    def fail(self, pattern: str, error: Any = None, times: Optional[int] = 1,
             p: float = 1.0, delay_s: float = 0.0,
             where: Optional[Callable[[Dict[str, Any]], bool]] = None,
             ) -> "_FaultRule":
        """Arm a rule. ``error`` may be an exception instance/type or a
        zero-arg factory; default :class:`InjectedFault`. ``times=None``
        fires on every match. ``where`` narrows the rule to fault-point
        hits whose context satisfies the predicate (e.g. one device of
        the mesh: ``where=lambda c: c.get("device") == 3``)."""
        rule = _FaultRule(pattern, error, times, p, delay_s, where=where)
        with self._lock:
            self._rules.append(rule)
        return rule

    def _materialize(self, rule: _FaultRule, site: str) -> BaseException:
        err = rule.error
        if err is None:
            return InjectedFault(f"injected fault at {site}")
        if isinstance(err, BaseException):
            return err
        out = err()  # type or factory
        return out if isinstance(out, BaseException) else InjectedFault(str(out))

    def fire(self, site: str, ctx: Dict[str, Any]) -> None:
        with self._lock:
            for rule in self._rules:
                if not fnmatch.fnmatch(site, rule.pattern):
                    continue
                if rule.times is not None and rule.hits >= rule.times:
                    continue
                if rule.where is not None and not rule.where(ctx):
                    continue
                if rule.p < 1.0 and self._rng.random() >= rule.p:
                    continue
                rule.hits += 1
                err = self._materialize(rule, site)
                self.fired.append((site, repr(err)))
                delay = rule.delay_s
                break
            else:
                return
        if delay:
            time.sleep(delay)
        raise err


def transient_os_error(e: BaseException) -> bool:
    """Retryable-``OSError`` classification for file edges (spill
    load/store, shapefile import): fd pressure and NFS blips retry;
    DETERMINISTIC path errors — missing file, wrong node type, denied
    permission — fail fast, because retrying them only stalls through
    the backoff schedule to the identical error."""
    return isinstance(e, OSError) and not isinstance(
        e, (FileNotFoundError, IsADirectoryError, NotADirectoryError,
            PermissionError),
    )


_injector: Optional[FaultInjector] = None


def fault_point(site: str, **ctx: Any) -> None:
    """An instrumented I/O edge. No-op (one global load + compare) unless
    an injector is installed via :func:`inject_faults`. Sites live at
    partition/RPC/message granularity — never inside per-row loops — so
    the disabled overhead is unmeasurable on the hot scan path."""
    inj = _injector
    if inj is None:
        return
    inj.fire(site, ctx)


class _InjectScope:
    def __init__(self, injector: FaultInjector):
        self.injector = injector

    def __enter__(self) -> FaultInjector:
        global _injector
        if not config.FAULT_INJECTION.to_bool():
            raise RuntimeError(
                "fault injection requires geomesa.fault.injection=true "
                "(scoped or via GEOMESA_FAULT_INJECTION)"
            )
        if _injector is not None:
            raise RuntimeError("a fault injector is already installed")
        _injector = self.injector
        return self.injector

    def __exit__(self, *exc):
        global _injector
        _injector = None
        return False


def inject_faults(seed: int = 0) -> _InjectScope:
    """Install a process-global seeded :class:`FaultInjector` for the
    scope (off by default; gated by ``geomesa.fault.injection``). The
    injector is global — faults fire on server/consumer threads too."""
    return _InjectScope(FaultInjector(seed))


# ---------------------------------------------------------------------------
# Typed partial-result degradation
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Skipped:
    """One unit of work dropped from a degraded scan."""

    source: str        # e.g. "fs.read_partition", "exec.partition.scan"
    part: str          # partition name / bin / file path
    error: str         # repr of the failure
    phase: str = ""    # optional sub-phase ("decode", "scan", ...)


@dataclass
class PartialResult(Generic[T]):
    """An aggregate over the surviving subset of a partitioned scan.

    ``value`` is exact over ``ok_parts`` partitions; ``skipped`` lists the
    dropped ones with why. ``degraded`` is False when nothing was skipped
    (then ``value`` is the complete answer)."""

    value: T
    skipped: List[Skipped] = field(default_factory=list)
    total_parts: int = 0
    ok_parts: int = 0

    @property
    def degraded(self) -> bool:
        return bool(self.skipped)

    def unwrap(self) -> T:
        """``value``, raising if anything was skipped (strict consumers)."""
        if self.skipped:
            s = self.skipped[0]
            raise RuntimeError(
                f"degraded result: {len(self.skipped)} partition(s) skipped "
                f"(first: {s.part}: {s.error})"
            )
        return self.value


class DegradationCollector:
    """Accumulates :class:`Skipped` records for one logical operation.
    Installed thread-locally by :func:`allow_partial`."""

    def __init__(self):
        self.skipped: List[Skipped] = []
        self._lock = threading.Lock()

    @property
    def degraded(self) -> bool:
        return bool(self.skipped)

    def add(self, rec: Skipped) -> None:
        with self._lock:
            self.skipped.append(rec)


_partial_local = threading.local()


def _collectors() -> List[DegradationCollector]:
    st = getattr(_partial_local, "stack", None)
    if st is None:
        st = _partial_local.stack = []
    return st


class _PartialScope:
    def __enter__(self) -> DegradationCollector:
        c = DegradationCollector()
        _collectors().append(c)
        return c

    def __exit__(self, *exc):
        _collectors().pop()
        return False


def allow_partial() -> _PartialScope:
    """``with allow_partial() as partial:`` — partition failures inside the
    scope degrade (skip + record) instead of raising; ``partial.skipped``
    holds the account. Nests; records land in the innermost collector."""
    return _PartialScope()


def partial_allowed() -> bool:
    """May the current operation degrade? True inside an
    :func:`allow_partial` scope or when ``geomesa.scan.partial`` is set."""
    if _collectors():
        return True
    return bool(config.SCAN_PARTIAL.to_bool())


def record_skip(source: str, part: str, error: BaseException,
                phase: str = "") -> Skipped:
    """Record one skipped partition: into the active collector (if any)
    and the process audit trail (``audit.degradations``). Callers decide
    whether to continue (see :func:`partial_allowed`)."""
    rec = Skipped(source=source, part=str(part), error=repr(error), phase=phase)
    st = _collectors()
    if st:
        st[-1].add(rec)
    from geomesa_tpu import audit, tracing

    audit.record_degradation(rec)
    # a degraded query is an always-keep class for trace tail sampling
    tracing.mark_degraded()
    return rec


# ---------------------------------------------------------------------------
# Durable tmp-then-rename publish (THE one copy — ISSUE 16 satellite)
# ---------------------------------------------------------------------------


def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so a rename/create inside it is durable — on many
    filesystems ``os.replace`` orders but does not persist the directory
    entry until the directory itself is synced. Filesystems that refuse
    directory fsync (some network mounts) keep the rename atomic, just
    not provably durable; the refusal is swallowed (the pre-existing
    behavior). THE one copy of this sequence — fs/storage.py metadata,
    the lake writer's publish, the fleet epoch marker, and journal
    segment creation all route here."""
    import os as _os

    try:
        dirfd = _os.open(path, _os.O_RDONLY)
    except OSError:
        return
    try:
        _os.fsync(dirfd)
    except OSError:
        pass
    finally:
        _os.close(dirfd)


def durable_replace(tmp: str, path: str) -> None:
    """``os.replace`` + parent-directory fsync: the durable half of every
    tmp-then-rename publish in the tree. The tmp file itself must already
    be written + fsynced by the caller."""
    import os as _os

    _os.replace(tmp, path)
    fsync_dir(_os.path.dirname(_os.path.abspath(path)))


def durable_write_json(path: str, obj: Any, indent: Optional[int] = None
                       ) -> None:
    """Crash-safe JSON publish: same-directory tmp, write, flush, file
    fsync, atomic replace, directory fsync — a crash at ANY point leaves
    either the old complete file or the new complete file, never torn
    JSON."""
    import json as _json
    import os as _os

    tmp = path + f".tmp.{_os.getpid()}"
    try:
        with open(tmp, "w") as fh:
            _json.dump(obj, fh, indent=indent)
            fh.flush()
            _os.fsync(fh.fileno())
        durable_replace(tmp, path)
    except BaseException:
        try:
            _os.remove(tmp)
        except OSError:
            pass
        raise


__all__ = [
    "QueryTimeoutError", "DeadlineShedError", "AdmissionRejectedError",
    "CircuitOpenError", "DeviceDrainError", "FleetPartialError",
    "InjectedFault",
    "RetryPolicy", "Deadline", "UNLIMITED", "current_deadline",
    "deadline_scope", "check_deadline",
    "CircuitBreaker", "breaker", "reset_breakers",
    "FaultInjector", "fault_point", "inject_faults",
    "Skipped", "PartialResult", "DegradationCollector", "allow_partial",
    "partial_allowed", "record_skip",
    "fsync_dir", "durable_replace", "durable_write_json",
]
