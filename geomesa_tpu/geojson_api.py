"""GeoJSON document façade — geomesa-geojson parity.

The reference's geomesa-geojson module (GeoJsonGtIndex.scala) lets users
treat a store as a JSON-document index: add GeoJSON features, query with a
tiny MongoDB-style JSON query language translated to CQL. Same surface here
over a GeoDataset:

    api = GeoJsonIndex(ds)
    api.create_index("points")
    ids = api.add("points", geojson_text)
    api.query("points", {"properties.name": "alice"})
    api.query("points", {"bbox": [-10, -10, 10, 10]})

Query language (reference README parity): equality on ``properties.*``,
``{"$lt"/"$le"/"$gt"/"$ge": v}`` comparisons, ``bbox``, ``dwithin``
(geometry + meters), ``intersects`` (inline GeoJSON geometry), and ``$or``.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence

import numpy as np


def _geom_to_wkt(g: Dict[str, Any]) -> str:
    t = g["type"].lower()
    c = g["coordinates"]
    if t == "point":
        return f"POINT ({c[0]} {c[1]})"
    if t == "linestring":
        inner = ", ".join(f"{x} {y}" for x, y in c)
        return f"LINESTRING ({inner})"
    if t == "polygon":
        rings = ", ".join(
            "(" + ", ".join(f"{x} {y}" for x, y in ring) + ")" for ring in c
        )
        return f"POLYGON ({rings})"
    if t == "multipolygon":
        polys = ", ".join(
            "(" + ", ".join(
                "(" + ", ".join(f"{x} {y}" for x, y in ring) + ")"
                for ring in p
            ) + ")"
            for p in c
        )
        return f"MULTIPOLYGON ({polys})"
    raise ValueError(f"unsupported GeoJSON geometry {g['type']!r}")


class GeoJsonIndex:
    """Store + query GeoJSON documents over a GeoDataset."""

    SPEC = "json:String,dtg:Date,*geom:Point"

    def __init__(self, dataset):
        self.ds = dataset

    def create_index(self, name: str, points: bool = True):
        # documents keep their full JSON payload; the indexed columns are the
        # geometry and an optional 'dtg' property (reference stores kryo-bson
        # with JSON-path pushdown; columnar layout plays that role here)
        self.ds.create_schema(name, self.SPEC)

    def delete_index(self, name: str):
        self.ds.delete_schema(name)

    def add(self, name: str, geojson: "str | Dict") -> List[str]:
        doc = json.loads(geojson) if isinstance(geojson, str) else geojson
        feats = (
            doc["features"] if doc.get("type") == "FeatureCollection"
            else [doc]
        )
        xs, ys, texts, fids, dtgs = [], [], [], [], []
        for i, f in enumerate(feats):
            g = f["geometry"]
            if g["type"] != "Point":
                raise ValueError("GeoJSON index stores point features")
            xs.append(float(g["coordinates"][0]))
            ys.append(float(g["coordinates"][1]))
            texts.append(json.dumps(f, sort_keys=True))
            props = f.get("properties") or {}
            fid = f.get("id") or props.get("id") or f"gj-{len(fids)}-{i}"
            fids.append(str(fid))
            dtgs.append(props.get("dtg") or props.get("date") or "1970-01-01")
        self.ds.insert(name, {
            "geom__x": np.asarray(xs),
            "geom__y": np.asarray(ys),
            "json": np.array(texts, dtype=object),
            "dtg": np.array(dtgs, dtype="datetime64[ms]"),
        }, fids=np.array(fids, dtype=object))
        self.ds.flush(name)
        return fids

    # -- query translation (JSON query -> coarse CQL + exact doc filter) ---
    #
    # CQL is only the *index acceleration*: clauses that can't be translated
    # safely (properties.*, anything under $or) coarsen to INCLUDE. The
    # exact semantics come from `_doc_match`, which is always applied to the
    # returned documents — so $or nesting and quoting in values cannot
    # change the result set, only the amount scanned.
    @classmethod
    def _to_cql(cls, q: "Dict | None") -> str:
        if not q:
            return "INCLUDE"
        clauses = []
        for k, v in q.items():
            if k == "bbox":
                xmin, ymin, xmax, ymax = (float(t) for t in v)
                clauses.append(f"BBOX(geom, {xmin}, {ymin}, {xmax}, {ymax})")
            elif k == "intersects":
                clauses.append(f"INTERSECTS(geom, {_geom_to_wkt(v)})")
            elif k == "dwithin":
                g, meters = v["geometry"], float(v["distance"])
                clauses.append(
                    f"DWITHIN(geom, {_geom_to_wkt(g)}, {meters}, meters)"
                )
            # properties.* / id / $or: host-side exact filter only
        return " AND ".join(clauses) if clauses else "INCLUDE"

    def query(self, name: str, q: "Dict | str | None" = None,
              max_features: Optional[int] = None) -> List[Dict]:
        """Run a JSON query; returns GeoJSON feature dicts."""
        from geomesa_tpu.api.dataset import Query

        if isinstance(q, str):
            q = json.loads(q) if q.strip() else None
        _validate_query(q)
        cql = self._to_cql(q)
        fc = self.ds.query(name, Query(ecql=cql, max_features=None))
        st = self.ds._store(name)
        codes = fc.batch.columns.get("json")
        if codes is None or fc.batch.n == 0:
            return []
        texts = st.dicts["json"].decode(codes)
        docs = [json.loads(t) for t in texts if t is not None]
        docs = [d for d in docs if _doc_match(d, q)]
        if max_features is not None:
            docs = docs[:max_features]
        return docs


_KNOWN_KEYS = {"bbox", "intersects", "dwithin", "id", "$or"}


def _validate_query(q: "Dict | None"):
    if not q:
        return
    for k, v in q.items():
        if k == "$or":
            for sub in v:
                _validate_query(sub)
        elif k not in _KNOWN_KEYS and not k.startswith("properties."):
            raise ValueError(f"unsupported query key {k!r}")


def _point_of(doc: Dict):
    c = (doc.get("geometry") or {}).get("coordinates") or (0.0, 0.0)
    return float(c[0]), float(c[1])


def _doc_match(doc: Dict, q: "Dict | None") -> bool:
    """Exact host-side evaluation of the JSON query against one document."""
    if not q:
        return True
    from geomesa_tpu.utils import geometry as geo
    from geomesa_tpu.utils.geometry import haversine_m, parse_wkt

    for k, v in q.items():
        if k == "$or":
            if not any(_doc_match(doc, sub) for sub in v):
                return False
        elif k == "bbox":
            x, y = _point_of(doc)
            xmin, ymin, xmax, ymax = (float(t) for t in v)
            if not (xmin <= x <= xmax and ymin <= y <= ymax):
                return False
        elif k == "intersects":
            x, y = _point_of(doc)
            g = parse_wkt(_geom_to_wkt(v))
            if not bool(np.asarray(g.contains_points([x], [y]))[0]):
                return False
        elif k == "dwithin":
            x, y = _point_of(doc)
            g = parse_wkt(_geom_to_wkt(v["geometry"]))
            if isinstance(g, geo.Point):
                d = haversine_m(x, y, g.x, g.y)
            else:  # nearest-vertex approximation for non-point targets
                b = g.bounds()
                verts = [(b[0], b[1]), (b[0], b[3]), (b[2], b[1]), (b[2], b[3])]
                d = min(haversine_m(x, y, vx, vy) for vx, vy in verts)
                if bool(np.asarray(g.contains_points([x], [y]))[0]):
                    d = 0.0
            if d > float(v["distance"]):
                return False
        elif k == "id":
            did = doc.get("id") or (doc.get("properties") or {}).get("id")
            if str(did) != str(v):
                return False
        elif k.startswith("properties."):
            if not _prop_match(doc, k[len("properties."):], v):
                return False
    return True


def _prop_match(doc: Dict, prop: str, cond: Any) -> bool:
    v: Any = doc.get("properties") or {}
    for part in prop.split("."):
        if not isinstance(v, dict):
            return False
        v = v.get(part)
    if isinstance(cond, dict):
        for op, rhs in cond.items():
            if v is None:
                return False
            if op == "$lt" and not (v < rhs):
                return False
            if op == "$le" and not (v <= rhs):
                return False
            if op == "$gt" and not (v > rhs):
                return False
            if op == "$ge" and not (v >= rhs):
                return False
        return True
    return v == cond
