"""GeoJSON document façade — geomesa-geojson parity.

The reference's geomesa-geojson module (GeoJsonGtIndex.scala) lets users
treat a store as a JSON-document index: add GeoJSON features, query with a
tiny MongoDB-style JSON query language translated to CQL. Same surface here
over a GeoDataset:

    api = GeoJsonIndex(ds)
    api.create_index("points")
    ids = api.add("points", geojson_text)
    api.query("points", {"properties.name": "alice"})
    api.query("points", {"bbox": [-10, -10, 10, 10]})

Query language (reference README parity): equality on ``properties.*``,
``{"$lt"/"$le"/"$gt"/"$ge": v}`` comparisons, ``bbox``, ``dwithin``
(geometry + meters), ``intersects`` (inline GeoJSON geometry), and ``$or``.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence

import numpy as np


def _geom_to_wkt(g: Dict[str, Any]) -> str:
    t = g["type"].lower()
    c = g["coordinates"]
    if t == "point":
        return f"POINT ({c[0]} {c[1]})"
    if t == "linestring":
        inner = ", ".join(f"{x} {y}" for x, y in c)
        return f"LINESTRING ({inner})"
    if t == "polygon":
        rings = ", ".join(
            "(" + ", ".join(f"{x} {y}" for x, y in ring) + ")" for ring in c
        )
        return f"POLYGON ({rings})"
    if t == "multipolygon":
        polys = ", ".join(
            "(" + ", ".join(
                "(" + ", ".join(f"{x} {y}" for x, y in ring) + ")"
                for ring in p
            ) + ")"
            for p in c
        )
        return f"MULTIPOLYGON ({polys})"
    raise ValueError(f"unsupported GeoJSON geometry {g['type']!r}")


class GeoJsonIndex:
    """Store + query GeoJSON documents over a GeoDataset."""

    SPEC = "json:String,dtg:Date,*geom:Point"

    def __init__(self, dataset):
        self.ds = dataset

    def create_index(self, name: str, points: bool = True):
        # documents keep their full JSON payload; the indexed columns are the
        # geometry and an optional 'dtg' property (reference stores kryo-bson
        # with JSON-path pushdown; columnar layout plays that role here)
        self.ds.create_schema(name, self.SPEC)

    def delete_index(self, name: str):
        self.ds.delete_schema(name)

    def add(self, name: str, geojson: "str | Dict") -> List[str]:
        doc = json.loads(geojson) if isinstance(geojson, str) else geojson
        feats = (
            doc["features"] if doc.get("type") == "FeatureCollection"
            else [doc]
        )
        xs, ys, texts, fids, dtgs = [], [], [], [], []
        for i, f in enumerate(feats):
            g = f["geometry"]
            if g["type"] != "Point":
                raise ValueError("GeoJSON index stores point features")
            xs.append(float(g["coordinates"][0]))
            ys.append(float(g["coordinates"][1]))
            texts.append(json.dumps(f, sort_keys=True))
            props = f.get("properties") or {}
            fid = f.get("id") or props.get("id") or f"gj-{len(fids)}-{i}"
            fids.append(str(fid))
            dtgs.append(props.get("dtg") or props.get("date") or "1970-01-01")
        self.ds.insert(name, {
            "geom__x": np.asarray(xs),
            "geom__y": np.asarray(ys),
            "json": np.array(texts, dtype=object),
            "dtg": np.array(dtgs, dtype="datetime64[ms]"),
        }, fids=np.array(fids, dtype=object))
        self.ds.flush(name)
        return fids

    # -- query translation (JSON query -> CQL) -----------------------------
    def _to_cql(self, q: "Dict | None") -> str:
        if not q:
            return "INCLUDE"
        clauses = []
        for k, v in q.items():
            if k == "$or":
                parts = [self._to_cql(sub) for sub in v]
                clauses.append("(" + " OR ".join(parts) + ")")
            elif k == "bbox":
                xmin, ymin, xmax, ymax = v
                clauses.append(f"BBOX(geom, {xmin}, {ymin}, {xmax}, {ymax})")
            elif k == "intersects":
                clauses.append(f"INTERSECTS(geom, {_geom_to_wkt(v)})")
            elif k == "dwithin":
                g, meters = v["geometry"], float(v["distance"])
                clauses.append(
                    f"DWITHIN(geom, {_geom_to_wkt(g)}, {meters}, meters)"
                )
            elif k.startswith("properties."):
                # property predicates evaluate host-side on the JSON column
                clauses.append(("__PROP__", k[len("properties."):], v))
            elif k == "id":
                clauses.append(f"IN ('{v}')")
            else:
                raise ValueError(f"unsupported query key {k!r}")
        cql_parts = [c for c in clauses if isinstance(c, str)]
        self._prop_filters = [c for c in clauses if not isinstance(c, str)]
        return " AND ".join(cql_parts) if cql_parts else "INCLUDE"

    def query(self, name: str, q: "Dict | str | None" = None,
              max_features: Optional[int] = None) -> List[Dict]:
        """Run a JSON query; returns GeoJSON feature dicts."""
        from geomesa_tpu.api.dataset import Query

        if isinstance(q, str):
            q = json.loads(q) if q.strip() else None
        self._prop_filters = []
        cql = self._to_cql(q)
        fc = self.ds.query(name, Query(ecql=cql, max_features=None))
        st = self.ds._store(name)
        codes = fc.batch.columns.get("json")
        if codes is None or fc.batch.n == 0:
            return []
        texts = st.dicts["json"].decode(codes)
        docs = [json.loads(t) for t in texts if t is not None]
        for _, prop, cond in self._prop_filters:
            docs = [d for d in docs if _prop_match(d, prop, cond)]
        if max_features is not None:
            docs = docs[:max_features]
        return docs


def _prop_match(doc: Dict, prop: str, cond: Any) -> bool:
    v: Any = doc.get("properties") or {}
    for part in prop.split("."):
        if not isinstance(v, dict):
            return False
        v = v.get(part)
    if isinstance(cond, dict):
        for op, rhs in cond.items():
            if v is None:
                return False
            if op == "$lt" and not (v < rhs):
                return False
            if op == "$le" and not (v <= rhs):
                return False
            if op == "$gt" and not (v > rhs):
                return False
            if op == "$ge" and not (v >= rhs):
                return False
        return True
    return v == cond
