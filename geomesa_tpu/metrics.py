"""Metrics registry (geomesa-metrics analog, SURVEY.md §2.8).

The reference uses a Dropwizard ``MetricRegistry`` with pluggable reporters
(GeoMesaMetrics.scala:26); consumers are the Kafka live cache and converter
``EvaluationContext`` counters. Here: a process-wide registry of counters,
gauges, and timers with a prometheus-text dump — attached to ingest, query
execution, and the streaming layer.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple


class Counter:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1):
        with self._lock:
            self.value += n


class Gauge:
    """A sampled value; either set explicitly or backed by a callable.

    ``set()``/``value`` are lock-protected, and a callable backing is only
    installed through :meth:`set_fn` — replacing an existing (different)
    callable must be explicit (``replace=True``), never the silent
    last-registration-wins the old ``MetricRegistry.gauge`` did."""

    def __init__(self, fn: Optional[Callable[[], float]] = None):
        self._lock = threading.Lock()
        self.fn = fn
        self._value = 0.0

    def set(self, v: float):
        with self._lock:
            self._value = float(v)

    def set_fn(self, fn: Callable[[], float], replace: bool = False) -> None:
        """Install (or explicitly replace) the callable backing."""
        with self._lock:
            if self.fn is not None and self.fn is not fn and not replace:
                raise ValueError(
                    "gauge is already callable-backed; pass replace=True to "
                    "swap the backing function"
                )
            self.fn = fn

    @property
    def value(self) -> float:
        with self._lock:
            fn = self.fn
            if fn is None:
                return self._value
        return float(fn())  # sample outside the lock: fn may be slow


#: Fixed histogram bucket upper bounds (seconds). Spans sub-millisecond
#: kernel dispatches through multi-second partitioned scans; the prometheus
#: rendering emits cumulative ``_bucket{le=...}`` lines so p50/p90/p99 are
#: derivable with the standard histogram_quantile arithmetic.
DEFAULT_BUCKETS_S: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)


class Histogram:
    """Fixed-bucket histogram. Defaults to the latency buckets (seconds);
    pass custom ``buckets`` plus ``unit=None`` for dimensionless
    distributions (e.g. fusion batch sizes) — the prometheus rendering then
    drops the ``_seconds`` suffix.

    **Exemplars**: ``observe(seconds, trace_id=...)`` additionally records
    the trace id against the bucket the observation landed in (last-writer
    wins per bucket), rendered in OpenMetrics exemplar syntax — so a p99
    outlier in /metrics links directly to its exported/slow-logged trace.
    The exemplar map is lazily allocated: histograms never fed a trace_id
    pay nothing."""

    __slots__ = ("buckets", "counts", "count", "sum_s", "unit", "exemplars",
                 "_lock")

    def __init__(self, buckets: Optional[Tuple[float, ...]] = None,
                 unit: Optional[str] = "s"):
        self.unit = unit
        self.buckets = tuple(buckets or DEFAULT_BUCKETS_S)
        self.counts = [0] * (len(self.buckets) + 1)  # last = +Inf overflow
        self.count = 0
        self.sum_s = 0.0
        #: bucket index -> (trace_id, value, unix_ts); None until first use
        self.exemplars: Optional[Dict[int, Tuple[str, float, float]]] = None
        self._lock = threading.Lock()

    def observe(self, seconds: float, trace_id: Optional[str] = None):
        i = bisect.bisect_left(self.buckets, seconds)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.sum_s += seconds
            if trace_id is not None:
                if self.exemplars is None:
                    self.exemplars = {}
                self.exemplars[i] = (trace_id, seconds, time.time())

    def quantile(self, q: float) -> float:
        """Approximate quantile: the upper bound of the bucket holding the
        q-th observation (the same answer prometheus derives from the text
        exposition; +Inf resolves to the largest finite bound)."""
        with self._lock:
            total = self.count
            counts = list(self.counts)
        if total == 0:
            return 0.0
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= rank:
                return self.buckets[min(i, len(self.buckets) - 1)]
        return self.buckets[-1]

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            counts = list(self.counts)
            total, s = self.count, self.sum_s
            ex = dict(self.exemplars) if self.exemplars else {}
        return {"count": total, "sum_s": s, "counts": counts,
                "buckets": list(self.buckets), "exemplars": ex}


class Timer:
    """Count + total/max duration + latency distribution. Use as a context
    manager; every existing ``timer(...)`` hot site feeds the embedded
    :class:`Histogram` with no call-site changes, so /metrics carries
    p50/p90/p99 for all of them."""

    __slots__ = ("count", "total_s", "max_s", "hist", "_lock")

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0
        self.hist = Histogram()
        self._lock = threading.Lock()

    def update(self, seconds: float):
        with self._lock:
            self.count += 1
            self.total_s += seconds
            self.max_s = max(self.max_s, seconds)
        self.hist.observe(seconds)

    def time(self):
        return _TimerContext(self)

    @property
    def mean_s(self) -> float:
        return self.total_s / self.count if self.count else 0.0


class _TimerContext:
    def __init__(self, timer: Timer):
        self.timer = timer

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.timer.update(time.perf_counter() - self._t0)
        return False


class MetricRegistry:
    def __init__(self, prefix: str = "geomesa"):
        self.prefix = prefix
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get(self, name: str, cls, *args):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(*args)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name!r} already registered as {type(m).__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str, fn: Optional[Callable[[], float]] = None,
              replace: bool = False) -> Gauge:
        """A named gauge. ``fn`` installs a callable backing; replacing an
        EXISTING different backing requires ``replace=True`` (satellite fix:
        the old path silently swapped ``fn`` under concurrent readers)."""
        g = self._get(name, Gauge)
        if fn is not None:
            g.set_fn(fn, replace=replace)
        return g

    def timer(self, name: str) -> Timer:
        return self._get(name, Timer)

    def histogram(self, name: str, buckets: Optional[Tuple[float, ...]] = None,
                  unit: Optional[str] = "s") -> Histogram:
        """A named histogram. ``buckets``/``unit`` apply only on first
        registration (a histogram's shape is fixed for its lifetime)."""
        return self._get(name, Histogram, buckets, unit)

    def report(self) -> Dict[str, object]:
        out: Dict[str, object] = {}
        with self._lock:
            items = list(self._metrics.items())
        for name, m in items:
            if isinstance(m, Counter):
                out[name] = m.value
            elif isinstance(m, Gauge):
                out[name] = m.value
            elif isinstance(m, Timer):
                out[name] = {
                    "count": m.count, "total_s": m.total_s,
                    "mean_s": m.mean_s, "max_s": m.max_s,
                    "p50_s": m.hist.quantile(0.5),
                    "p99_s": m.hist.quantile(0.99),
                }
            elif isinstance(m, Histogram):
                snap = m.snapshot()
                out[name] = {
                    "count": snap["count"], "sum_s": snap["sum_s"],
                    "p50_s": m.quantile(0.5), "p90_s": m.quantile(0.9),
                    "p99_s": m.quantile(0.99),
                }
        return out

    @staticmethod
    def _prom_hist_lines(metric: str, snap: Dict[str, object],
                         exemplars: bool = False) -> List[str]:
        """Cumulative prometheus histogram lines for one histogram
        SNAPSHOT (``Histogram.snapshot()`` shape — the fleet federation
        renders merged snapshot dicts through the same code). With
        ``exemplars`` (OpenMetrics exposition ONLY — the `#` suffix is a
        parse error under the classic text format, so callers must
        negotiate the content type first), buckets holding an exemplar
        render it in OpenMetrics exemplar syntax
        (`... # {trace_id="…"} value timestamp`), linking the bucket to a
        concrete trace (docs/OBSERVABILITY.md)."""
        ex = (snap.get("exemplars") or {}) if exemplars else {}

        def _ex(i: int) -> str:
            e = ex.get(i)
            if e is None:
                return ""
            tid, val, ts = e
            return f' # {{trace_id="{tid}"}} {val:.6f} {ts:.3f}'

        lines: List[str] = []
        cum = 0
        for i, (le, c) in enumerate(zip(snap["buckets"], snap["counts"])):
            cum += c
            lines.append(f'{metric}_bucket{{le="{le}"}} {cum}{_ex(i)}')
        cum += snap["counts"][-1]
        lines.append(
            f'{metric}_bucket{{le="+Inf"}} {cum}'
            f'{_ex(len(snap["buckets"]))}'
        )
        lines.append(f"{metric}_sum {snap['sum_s']:.6f}")
        lines.append(f"{metric}_count {snap['count']}")
        return lines

    def prometheus(self, exemplars: bool = False) -> str:
        """Prometheus text exposition of all metrics. Timers render their
        legacy count/total/max lines PLUS ``_seconds`` histogram buckets;
        standalone histograms render the standard bucket/sum/count triple
        (p50/p90/p99 derivable with histogram_quantile). ``exemplars``
        adds per-bucket exemplar suffixes — legal ONLY in the OpenMetrics
        exposition (obs.py negotiates it via the Accept header and
        appends the required ``# EOF``); the classic ``version=0.0.4``
        text format must stay exemplar-free or standard scrapers fail the
        whole scrape."""
        lines: List[str] = []
        p = self.prefix
        with self._lock:
            items = list(self._metrics.items())
        for name, m in items:
            metric = f"{p}_{name}".replace(".", "_").replace("-", "_")
            if isinstance(m, Timer):
                lines.append(f"{metric}_count {m.count}")
                lines.append(f"{metric}_seconds_total {m.total_s:.6f}")
                lines.append(f"{metric}_seconds_max {m.max_s:.6f}")
                lines.extend(self._prom_hist_lines(
                    metric + "_seconds", m.hist.snapshot(), exemplars))
            elif isinstance(m, Histogram):
                suffix = "_seconds" if m.unit == "s" else ""
                lines.extend(self._prom_hist_lines(
                    metric + suffix, m.snapshot(), exemplars))
            elif isinstance(m, (Counter, Gauge)):
                lines.append(f"{metric} {m.value}")
        return "\n".join(lines) + "\n"

    def export_snapshot(self) -> Dict[str, object]:
        """STRUCTURED export for metrics federation (docs/OBSERVABILITY.md
        §9): raw counters, sampled gauges, and full histogram bucket
        vectors — NOT the quantile summaries :meth:`report` collapses to.
        The fleet router merges these exactly: counters add, histogram
        ``counts`` add bucket-wise (ladders are compared, never assumed),
        gauges keep per-replica identity. Exemplars are deliberately
        omitted: they are per-process pointers into per-process trace
        retention and do not survive a merge."""
        counters: Dict[str, int] = {}
        gauges: Dict[str, float] = {}
        hists: Dict[str, object] = {}
        timers: Dict[str, object] = {}
        with self._lock:
            items = list(self._metrics.items())
        for name, m in items:
            if isinstance(m, Counter):
                counters[name] = m.value
            elif isinstance(m, Gauge):
                try:
                    gauges[name] = float(m.value)
                except Exception:
                    continue  # a dead callable backing must not kill export
            elif isinstance(m, Timer):
                snap = m.hist.snapshot()
                snap.pop("exemplars", None)
                snap["unit"] = m.hist.unit
                timers[name] = {"count": m.count, "total_s": m.total_s,
                                "max_s": m.max_s, "hist": snap}
            elif isinstance(m, Histogram):
                snap = m.snapshot()
                snap.pop("exemplars", None)
                snap["unit"] = m.unit
                hists[name] = snap
        return {"counters": counters, "gauges": gauges,
                "histograms": hists, "timers": timers}

    def clear(self):
        with self._lock:
            self._metrics.clear()


def _merge_hist(acc: Optional[Dict[str, object]],
                snap: Dict[str, object]) -> Tuple[Dict[str, object], bool]:
    """Merge one histogram snapshot into the accumulator. Returns
    ``(acc, ok)``; ``ok`` is False when the bucket ladders differ (custom
    ladders — FUSION_BATCH_BUCKETS, JOURNAL_*_BUCKETS, the router's merge
    buckets — only merge with themselves; a mismatched snapshot is counted
    as skew, never silently re-binned)."""
    if acc is None:
        return ({"buckets": list(snap["buckets"]),
                 "counts": list(snap["counts"]),
                 "count": int(snap["count"]),
                 "sum_s": float(snap["sum_s"]),
                 "unit": snap.get("unit", "s")}, True)
    if list(acc["buckets"]) != list(snap["buckets"]):
        return acc, False
    acc["counts"] = [a + b for a, b in zip(acc["counts"], snap["counts"])]
    acc["count"] = int(acc["count"]) + int(snap["count"])
    acc["sum_s"] = float(acc["sum_s"]) + float(snap["sum_s"])
    return acc, True


def merge_exports(exports: Dict[str, Dict[str, object]]) -> Dict[str, object]:
    """Merge per-replica :meth:`MetricRegistry.export_snapshot` payloads
    into ONE fleet view: counters and histogram bucket vectors add exactly,
    timers add (max of maxes), gauges stay per-replica keyed by replica id.
    ``bucket_skew`` counts (name -> snapshots dropped) histogram snapshots
    whose ladder disagreed with the first replica's — exactness over
    silent re-binning."""
    counters: Dict[str, int] = {}
    gauges: Dict[str, Dict[str, float]] = {}
    hists: Dict[str, Dict[str, object]] = {}
    timers: Dict[str, Dict[str, object]] = {}
    skew: Dict[str, int] = {}
    for rid in sorted(exports):
        snap = exports[rid] or {}
        for name, v in (snap.get("counters") or {}).items():
            counters[name] = counters.get(name, 0) + int(v)
        for name, v in (snap.get("gauges") or {}).items():
            gauges.setdefault(name, {})[rid] = float(v)
        for name, h in (snap.get("histograms") or {}).items():
            merged, ok = _merge_hist(hists.get(name), h)
            hists[name] = merged
            if not ok:
                skew[name] = skew.get(name, 0) + 1
        for name, t in (snap.get("timers") or {}).items():
            acc = timers.get(name)
            if acc is None:
                timers[name] = {"count": int(t["count"]),
                                "total_s": float(t["total_s"]),
                                "max_s": float(t["max_s"]),
                                "hist": dict(t["hist"])}
                timers[name]["hist"]["buckets"] = list(t["hist"]["buckets"])
                timers[name]["hist"]["counts"] = list(t["hist"]["counts"])
                continue
            acc["count"] += int(t["count"])
            acc["total_s"] += float(t["total_s"])
            acc["max_s"] = max(acc["max_s"], float(t["max_s"]))
            merged, ok = _merge_hist(acc["hist"], t["hist"])
            acc["hist"] = merged
            if not ok:
                skew[name] = skew.get(name, 0) + 1
    return {"replicas": sorted(exports), "counters": counters,
            "gauges": gauges, "histograms": hists, "timers": timers,
            "bucket_skew": skew}


def render_fleet(merged: Dict[str, object], prefix: str = "geomesa",
                 openmetrics: bool = False) -> str:
    """Prometheus text exposition of one :func:`merge_exports` result.
    Fleet-level series (summed counters, bucket-wise-merged histograms,
    added timers) render exactly like a single process's; gauges render
    one line per replica with a ``replica`` label — a gauge is a sampled
    per-process fact and summing it would lie. ``openmetrics`` changes
    nothing here (merged snapshots carry no exemplars) but is accepted so
    the caller can negotiate content types uniformly."""
    del openmetrics  # merged snapshots are exemplar-free by construction

    def mangle(name: str) -> str:
        return f"{prefix}_{name}".replace(".", "_").replace("-", "_")

    lines: List[str] = []
    for name, v in sorted((merged.get("counters") or {}).items()):
        lines.append(f"{mangle(name)} {v}")
    for name, per in sorted((merged.get("gauges") or {}).items()):
        for rid, v in sorted(per.items()):
            lines.append(f'{mangle(name)}{{replica="{rid}"}} {v}')
    for name, h in sorted((merged.get("histograms") or {}).items()):
        suffix = "_seconds" if h.get("unit") == "s" else ""
        lines.extend(MetricRegistry._prom_hist_lines(
            mangle(name) + suffix, h))
    for name, t in sorted((merged.get("timers") or {}).items()):
        metric = mangle(name)
        lines.append(f"{metric}_count {t['count']}")
        lines.append(f"{metric}_seconds_total {t['total_s']:.6f}")
        lines.append(f"{metric}_seconds_max {t['max_s']:.6f}")
        lines.extend(MetricRegistry._prom_hist_lines(
            metric + "_seconds", t["hist"]))
    return "\n".join(lines) + "\n"


_REGISTRY = MetricRegistry()


def registry() -> MetricRegistry:
    return _REGISTRY


def inc(name: str, n: int = 1) -> None:
    """Shorthand: bump a counter in the process registry (used by the
    aggregate cache and the stream quarantine path, which count from hot
    loops and shouldn't re-spell the registry plumbing)."""
    _REGISTRY.counter(name).inc(n)


def observe(name: str, seconds: float,
            trace_id: Optional[str] = None) -> None:
    """Shorthand: record one latency observation into a process-registry
    histogram (span completions in tracing.py use this path). An optional
    ``trace_id`` rides along as the bucket's exemplar."""
    _REGISTRY.histogram(name).observe(seconds, trace_id)


# Aggregate-cache metric names (cache/store.py, cache/service.py). Kept here
# so operators grepping the exposition format find the contract in one place:
#   cache.hit          whole-result hits (no scan at all)
#   cache.partial      partial-cover hits (only the residual cells scanned)
#   cache.miss         queries that found nothing reusable
#   cache.put          entries admitted
#   cache.evict        entries evicted by the size-aware LRU
#   cache.invalidate   entries dropped by a dataset epoch bump
#   cache.bytes        resident cached bytes (gauge)
#   cache.entries      resident entry count (gauge)
#   cache.hierarchy.hit      interior cells served by assembling cached
#                            child cells instead of scanning (zoom-out path)
#   cache.hierarchy.promote  coarse entries written by assembly / bottom-up
#                            sibling roll-up
#   cache.hierarchy.residual cells that fell through to a residual scan
#                            after an assembly attempt found no children
#   cache.polygon            queries decomposed into interior + boundary
#                            cells by the polygon-region path
CACHE_HIT = "cache.hit"
CACHE_HIER_HIT = "cache.hierarchy.hit"
CACHE_HIER_PROMOTE = "cache.hierarchy.promote"
CACHE_HIER_RESIDUAL = "cache.hierarchy.residual"
CACHE_POLYGON = "cache.polygon"
#   cache.curve.region    density_curve queries whose block-chunk loop
#                         split into polygon families (interior chunks
#                         residual-keyed, outside chunks unscanned —
#                         docs/CACHE.md "Polygon curve chunks")
CACHE_CURVE_REGION = "cache.curve.region"
# Warm-path executor metrics (kernels/registry.py, planning/executor.py,
# planning/partitioned_exec.py; docs/PERF.md):
#   kernel.recompiles   fresh jit traces admitted to the kernel registry
#                       (each one paid an XLA trace+compile)
#   kernel.bucket_hit   kernel registry hits — a query served by an
#                       already-compiled kernel (shape bucket + key match)
#   kernel.evict        LRU evictions from the kernel registry
#   pipeline.prefetch   partitions whose host load/column assembly was
#                       overlapped with the previous partition's execution
KERNEL_RECOMPILES = "kernel.recompiles"
KERNEL_BUCKET_HIT = "kernel.bucket_hit"
KERNEL_EVICT = "kernel.evict"
PIPELINE_PREFETCH = "pipeline.prefetch"
# Sharded partitioned scan (planning/partitioned_exec.py; docs/SCALE.md):
#   scan.sharded.queries     queries served by the multi-device fan-out
#   scan.sharded.device.<id> per-device partition dispatches (the bench's
#                            per-device dispatch counts read these)
#   pipeline.deviceput       partitions whose device upload was overlapped
#                            on the prefetch thread (geomesa.pipeline.
#                            device-put; docs/PERF.md)
SCAN_SHARDED = "scan.sharded.queries"
SCAN_SHARDED_DEVICE = "scan.sharded.device"
PIPELINE_DEVICE_PUT = "pipeline.deviceput"
# Device fault tolerance (parallel/health.py, planning/partitioned_exec.py,
# serving/scheduler.py; docs/RESILIENCE.md §6):
#   device.health.<id>        gauge: 1 = ok, 0 = cordoned, -1 = broken
#                             (breaker open / half-open awaiting trial)
#   scan.reassigned           partitions requeued onto a surviving device
#                             after a per-device dispatch failure
#   serving.slot.died         pool dispatcher deaths (per-slot suffix too)
#   serving.slot.respawn      slots respawned by the pool supervisor
#                             (per-slot suffix too)
DEVICE_HEALTH_PREFIX = "device.health"
SCAN_REASSIGNED = "scan.reassigned"
SERVING_SLOT_DIED = "serving.slot.died"
SERVING_SLOT_RESPAWN = "serving.slot.respawn"
# Observability metrics (tracing.py, kernels/registry.py, obs.py;
# docs/OBSERVABILITY.md):
#   kernel.recompiles.<site>   per-jit-site fresh traces (suffix = site)
#   kernel.recompile.alert     gauge: sites over geomesa.kernel.alert.
#                              threshold within the LAST query window
#   kernel.recompile.alerts    total alert trips (counter)
#   trace.<stage>              per-stage latency histograms (span tree)
#   trace.slow                 queries that exceeded geomesa.trace.slow.ms
KERNEL_RECOMPILE_ALERT = "kernel.recompile.alert"
KERNEL_RECOMPILE_ALERTS = "kernel.recompile.alerts"
#   kernel.evict.<site>        per-jit-site LRU evictions (suffix = site)
#   kernel.recompiles.evicted  fresh traces paid for keys the LRU had
#                              previously evicted — the registry-thrash
#                              signal (docs/PERF.md "Registry pressure";
#                              the bench eviction_recompiles key reads it)
KERNEL_RECOMPILE_EVICTED = "kernel.recompiles.evicted"
# Trace export + tail sampling (tracing_export.py; docs/OBSERVABILITY.md):
#   trace.export.exported   traces handed to a sink (after sampling)
#   trace.export.sampled    healthy traces dropped by the sample rate
#   trace.export.dropped    traces dropped on export-queue overflow (the
#                           non-blocking contract: full queue = drop+count,
#                           never a blocked query/dispatch thread)
#   trace.export.failed     sink write failures after retries/breaker
#   trace.export.batches    OTLP batches successfully written
TRACE_EXPORT_EXPORTED = "trace.export.exported"
TRACE_EXPORT_SAMPLED = "trace.export.sampled"
TRACE_EXPORT_DROPPED = "trace.export.dropped"
TRACE_EXPORT_FAILED = "trace.export.failed"
TRACE_EXPORT_BATCHES = "trace.export.batches"
# Per-device utilization + SLO burn (utilization.py, slo.py):
#   device.busy.<id>             gauge: busy fraction of device <id> over
#                                the trailing geomesa.device.busy.window
#   serving.slot.occupancy.<s>   gauge: busy fraction of pool slot <s>
#   slo.burn.<op>                gauge: fast-window burn rate for the
#                                geomesa.slo.<op>.p99.ms target
#   slo.breaker.<name>           gauge: circuit-breaker state on the SLO
#                                alert surface (1 open, 0.5 half-open,
#                                0 closed) — breaker-open transitions page
#                                through the same scrape the burn gauges do
DEVICE_BUSY_PREFIX = "device.busy"
SLOT_OCCUPANCY_PREFIX = "serving.slot.occupancy"
SLO_BURN_PREFIX = "slo.burn"
SLO_BREAKER_PREFIX = "slo.breaker"
# Serving-scheduler metrics (serving/scheduler.py, planning/executor.py;
# docs/SERVING.md):
#   serving.queue.depth     gauge: tickets currently queued (all users)
#   serving.queue.wait      histogram: admission -> dispatch latency
#   serving.admitted        tickets admitted to the queue
#   serving.completed       tickets whose execution finished (any outcome)
#   serving.shed.deadline   tickets shed with [GM-SHED] (budget unmeetable)
#   serving.shed.queue_full tickets rejected with [GM-OVERLOADED]
#   serving.fused           tickets served via a fused batch (every member,
#                           primary included — matches the ledger rollups)
#   serving.fusion.batch    histogram (dimensionless): fused batch sizes
#   exec.device.dispatch    device kernel dispatches issued by the executor
#                           (the fusion-actually-fused bench gate counts it)
SERVING_QUEUE_DEPTH = "serving.queue.depth"
SERVING_QUEUE_WAIT = "serving.queue.wait"
SERVING_ADMITTED = "serving.admitted"
SERVING_COMPLETED = "serving.completed"
SERVING_SHED_DEADLINE = "serving.shed.deadline"
SERVING_SHED_QUEUE_FULL = "serving.shed.queue_full"
#   serving.executor.dispatch.<slot>  groups executed per pool slot (the
#                           pool-actually-parallel bench/CI gate reads
#                           these; docs/SERVING.md)
#   serving.fused.distinct  members served via a DISTINCT-literal batched
#                           pass (query-axis megakernel; docs/SERVING.md
#                           "Query-axis batching")
#   serving.speculative     deadline-shed counts answered with the typed
#                           coarse estimate instead of [GM-SHED] (client
#                           opted in via speculative_ok; docs/SERVING.md)
#   serving.placement.bound fused groups that executed on their preferred
#                           (column-hot) slot after a placement deferral
#   serving.placement.defer fuse-bearing tickets deferred toward their
#                           preferred slot (docs/SERVING.md §5c)
SERVING_FUSED = "serving.fused"
SERVING_FUSED_DISTINCT = "serving.fused.distinct"
SERVING_SPECULATIVE = "serving.speculative"
SERVING_PLACEMENT_BOUND = "serving.placement.bound"
SERVING_PLACEMENT_DEFER = "serving.placement.defer"
SERVING_FUSION_BATCH = "serving.fusion.batch"
SERVING_EXECUTOR_DISPATCH = "serving.executor.dispatch"
EXEC_DEVICE_DISPATCH = "exec.device.dispatch"
#: fused batch-size histogram buckets (members per micro-batch)
FUSION_BATCH_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 24.0, 32.0)
# Stream-consumer lag (stream/live.py, stream/confluent.py;
# docs/OBSERVABILITY.md):
#   stream.lag          gauge: ms between the last applied message's event
#                       time and its apply time (poll -> apply lag)
#   stream.apply        histogram: per-poll apply-phase latency
STREAM_LAG = "stream.lag"
STREAM_APPLY = "stream.apply"
#   stream.epoch.<schema>   gauge: the live window's mutation epoch — the
#                           staleness anchor standing subscriptions and
#                           window-aggregate caches key on (stream/live.py)
#   stream.poll.batches     counter: applied (non-empty) poll batches
STREAM_EPOCH = "stream.epoch"
STREAM_POLL_BATCHES = "stream.poll.batches"
# Standing subscriptions (geomesa_tpu/subscribe/; docs/STANDING.md):
#   subscribe.groups            gauge: distinct standing groups resident
#   subscribe.subscribers       gauge: registered subscribers (all groups)
#   subscribe.update.dispatches counter: delta evaluation passes — ONE per
#                               applied ingest batch per schema, however
#                               many fused subscribers watch (the CI-gated
#                               one-dispatch contract)
#   subscribe.updates           counter: update records emitted to rings
#   subscribe.rescans           counter: dirty-scoped from-scratch rescans
#                               (deletes, age-off, guard-mismatch imports)
#   subscribe.fused             counter: registrations absorbed into an
#                               existing group (serving-fusion analog)
#   subscribe.verify            counter: delta-vs-rescan bit-identity
#                               assertions run (geomesa.subscribe.verify)
#   subscribe.handoff.exported  counter: groups exported for warm handoff
#   subscribe.handoff.imported  counter: groups adopted verbatim (guard
#                               matched) on import
#   subscribe.handoff.resync    counter: groups re-scanned on import
#                               (guard mismatch -> resync update)
SUBSCRIBE_GROUPS = "subscribe.groups"
SUBSCRIBE_SUBSCRIBERS = "subscribe.subscribers"
SUBSCRIBE_DISPATCHES = "subscribe.update.dispatches"
SUBSCRIBE_UPDATES = "subscribe.updates"
SUBSCRIBE_RESCANS = "subscribe.rescans"
SUBSCRIBE_FUSED = "subscribe.fused"
SUBSCRIBE_VERIFY = "subscribe.verify"
SUBSCRIBE_HANDOFF_EXPORTED = "subscribe.handoff.exported"
SUBSCRIBE_HANDOFF_IMPORTED = "subscribe.handoff.imported"
SUBSCRIBE_HANDOFF_RESYNC = "subscribe.handoff.resync"
CACHE_PARTIAL = "cache.partial"
CACHE_MISS = "cache.miss"
CACHE_PUT = "cache.put"
CACHE_EVICT = "cache.evict"
CACHE_INVALIDATE = "cache.invalidate"
CACHE_BYTES = "cache.bytes"
CACHE_ENTRIES = "cache.entries"
# Spatial joins (planning/join_exec.py; docs/JOIN.md):
#   join.queries          spatial joins executed (count + pair forms)
#   join.cells            co-partition cells that held rows on BOTH sides
#   join.candidate.pairs  pairwise tests actually dispatched (same-cell +
#                         boundary-strip pairs — the O(pairs-in-cell)
#                         account vs the naive N*M)
#   join.pairs            matched pairs emitted
JOIN_QUERIES = "join.queries"
JOIN_CELLS = "join.cells"
JOIN_CANDIDATE_PAIRS = "join.candidate.pairs"
JOIN_PAIRS = "join.pairs"
# Adaptive strategy decision trail (docs/JOIN.md §5): per-strategy joint-
# cell routing counts — join.cells.pairwise / .brute / .split, plus
# join.cells.interior for polygon-join cells matched wholesale with zero
# pairwise work. The prefix is the ledger contract; suffixes come from
# JoinStats.strategy_cells.
JOIN_CELLS_STRATEGY = "join.cells."
#   join.pushdown.bytes   probe-side payload bytes actually read by the
#                         window-pushdown join side scan (vs skipped)
JOIN_PUSHDOWN_BYTES = "join.pushdown.bytes"
# Columnar geo-lake tier (geomesa_tpu/lake/; docs/LAKE.md):
#   lake.bytes.read        payload + footer bytes actually read
#   lake.bytes.skipped     payload bytes statistics-pruning never touched
#   lake.rowgroups.loaded  row groups decoded for scans
#   lake.rowgroups.pruned  row groups excluded by footer statistics
#   lake.pushdown.scans    partition scans served by a pruned partial load
#   cache.persist.restored cache entries re-served from a persisted tier
LAKE_BYTES_READ = "lake.bytes.read"
LAKE_BYTES_SKIPPED = "lake.bytes.skipped"
LAKE_ROWGROUPS_LOADED = "lake.rowgroups.loaded"
LAKE_ROWGROUPS_PRUNED = "lake.rowgroups.pruned"
LAKE_PUSHDOWN_SCANS = "lake.pushdown.scans"
#   lake.pushdown.fallback  pushdown asked for, but the snapshot could not
#                           serve a pruned load (exotic/unbuildable
#                           keyspace, pre-lake snapshot) and fell back to
#                           the full resident load — docs/LAKE.md §10
LAKE_PUSHDOWN_FALLBACK = "lake.pushdown.fallback"
CACHE_PERSIST_RESTORED = "cache.persist.restored"
# Replica fleet (geomesa_tpu/fleet/; docs/RESILIENCE.md §7):
#   fleet.route.affinity   queries served by their ring-owner replica
#   fleet.route.failover   queries re-routed to a later ring owner after
#                          the preferred owner failed/was fenced
#   fleet.route.scatter    decomposable counts split across owner groups
#   fleet.route.partial    queries degraded typed [GM-FLEET-PARTIAL]
#   fleet.epoch.bump       router-stamped mutations
#   fleet.epoch.refresh    replica-side schema refreshes forced by an
#                          incoming request's newer fleet epoch
#   fleet.replica.health.<id>  1 ok / 0 cordoned|draining / -1 broken
FLEET_ROUTE_AFFINITY = "fleet.route.affinity"
FLEET_ROUTE_FAILOVER = "fleet.route.failover"
FLEET_ROUTE_SCATTER = "fleet.route.scatter"
FLEET_ROUTE_PARTIAL = "fleet.route.partial"
FLEET_EPOCH_BUMP = "fleet.epoch.bump"
FLEET_EPOCH_REFRESH = "fleet.epoch.refresh"
FLEET_REPLICA_HEALTH_PREFIX = "fleet.replica.health"
#   fleet.scatter.<kind>   scattered queries by aggregate kind (count /
#                          density / stats / curve — docs/RESILIENCE.md
#                          §7 "Scatter-gather for every mergeable
#                          aggregate")
#   fleet.scatter.merge_ms router-side fixed-order merge cost of one
#                          scattered query's partials (histogram)
#   fleet.uncordon         replicas auto-uncordoned after K consecutive
#                          successful probes (geomesa.fleet.uncordon.probes)
#   fleet.member.join      replicas registered with a router at runtime
#   fleet.member.leave     replicas deregistered at runtime
#   fleet.handoff.entries  cache entries pushed to the new ring owner by
#                          warm-handoff drains
FLEET_SCATTER_KIND_PREFIX = "fleet.scatter"
FLEET_SCATTER_MERGE_MS = "fleet.scatter.merge_ms"
FLEET_UNCORDON = "fleet.uncordon"
FLEET_MEMBER_JOIN = "fleet.member.join"
FLEET_MEMBER_LEAVE = "fleet.member.leave"
FLEET_HANDOFF_ENTRIES = "fleet.handoff.entries"
#   fleet.epoch.marker.quarantined  corrupt fleet-epochs.json markers moved
#                          aside (crc mismatch / unparsable — read as empty,
#                          the safe direction: a redundant refresh, never a
#                          stale serve; docs/RESILIENCE.md §8)
FLEET_EPOCH_MARKER_QUARANTINED = "fleet.epoch.marker.quarantined"
# Durable mutation journal (fs/journal.py; docs/RESILIENCE.md §8):
#   journal.appends         records made durable (acked appends)
#   journal.group.size      histogram: appends per group-commit fsync
#   journal.fsync_ms        histogram: group-commit write+fsync latency (ms)
#   journal.replayed        records re-applied by recovery/refresh replay
#   journal.truncated_bytes bytes reclaimed (checkpoints) or clipped
#                           (torn tails)
#   journal.torn_tails      torn segment tails truncated at open/replay
#   journal.lag             gauge: appended-but-not-yet-durable records
#                           (also the /healthz journal section)
JOURNAL_APPENDS = "journal.appends"
JOURNAL_GROUP_SIZE = "journal.group.size"
JOURNAL_FSYNC_MS = "journal.fsync_ms"
JOURNAL_REPLAYED = "journal.replayed"
JOURNAL_TRUNCATED_BYTES = "journal.truncated_bytes"
JOURNAL_TORN_TAILS = "journal.torn_tails"
JOURNAL_LAG = "journal.lag"
#: group-commit batch-width buckets (appends per fsync)
JOURNAL_GROUP_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)
#: group-commit fsync latency buckets (milliseconds)
JOURNAL_FSYNC_BUCKETS_MS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0,
                            50.0, 100.0, 250.0)
# Fleet observability plane (fleet/obs.py; docs/OBSERVABILITY.md §9):
#   fleet.federation.scrapes   metrics-export federation sweeps completed
#   fleet.federation.errors    replica snapshots a sweep failed to pull
#                              (the merge proceeds over the survivors)
#   fleet.trace.stitched       stitched cross-replica traces assembled
#   fleet.trace.stitch.failed  scattered traces the stitcher could not
#                              assemble (replica retention expired, fetch
#                              failed) — exported unstitched, counted
#   fleet.anomaly.<id>         gauge: per-replica latency anomaly factor —
#                              worst per-op recent-median ratio vs the
#                              fleet median (1.0 = at median; ≥ the
#                              geomesa.fleet.anomaly.factor threshold is
#                              flagged in /debug/fleet). Observation only.
FLEET_FEDERATION_SCRAPES = "fleet.federation.scrapes"
FLEET_FEDERATION_ERRORS = "fleet.federation.errors"
FLEET_TRACE_STITCHED = "fleet.trace.stitched"
FLEET_TRACE_STITCH_FAILED = "fleet.trace.stitch.failed"
FLEET_ANOMALY_PREFIX = "fleet.anomaly"
# Cell-heat telemetry (heat.py, cache/service.py; docs/OBSERVABILITY.md §9):
#   heat.cells        gauge: distinct (schema, cell) rows resident in the
#                     process heat table
#   heat.evicted      heat rows dropped by the table's size bound
HEAT_CELLS = "heat.cells"
HEAT_EVICTED = "heat.evicted"
#   join.pushdown.residency.hits   chunk-boundary row-group column chunks
#                                  served from the cross-chunk residency
#                                  cache instead of a re-decode
#   join.pushdown.residency.bytes  encoded payload bytes that re-decode
#                                  would have re-read (docs/JOIN.md §11)
JOIN_PUSHDOWN_RESIDENCY_HITS = "join.pushdown.residency.hits"
JOIN_PUSHDOWN_RESIDENCY_BYTES = "join.pushdown.residency.bytes"
#   compact.desc.shared   compact-scan descriptors served from the
#                         content-addressed share (a rebuild avoided:
#                         another site/query resolved the same windows —
#                         docs/PERF.md "Shared descriptors")
COMPACT_DESC_SHARED = "compact.desc.shared"
