"""Metrics registry (geomesa-metrics analog, SURVEY.md §2.8).

The reference uses a Dropwizard ``MetricRegistry`` with pluggable reporters
(GeoMesaMetrics.scala:26); consumers are the Kafka live cache and converter
``EvaluationContext`` counters. Here: a process-wide registry of counters,
gauges, and timers with a prometheus-text dump — attached to ingest, query
execution, and the streaming layer.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional


class Counter:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1):
        with self._lock:
            self.value += n


class Gauge:
    """A sampled value; either set explicitly or backed by a callable."""

    def __init__(self, fn: Optional[Callable[[], float]] = None):
        self.fn = fn
        self._value = 0.0

    def set(self, v: float):
        self._value = float(v)

    @property
    def value(self) -> float:
        return float(self.fn()) if self.fn is not None else self._value


class Timer:
    """Count + total/max duration. Use as a context manager."""

    __slots__ = ("count", "total_s", "max_s", "_lock")

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0
        self._lock = threading.Lock()

    def update(self, seconds: float):
        with self._lock:
            self.count += 1
            self.total_s += seconds
            self.max_s = max(self.max_s, seconds)

    def time(self):
        return _TimerContext(self)

    @property
    def mean_s(self) -> float:
        return self.total_s / self.count if self.count else 0.0


class _TimerContext:
    def __init__(self, timer: Timer):
        self.timer = timer

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.timer.update(time.perf_counter() - self._t0)
        return False


class MetricRegistry:
    def __init__(self, prefix: str = "geomesa"):
        self.prefix = prefix
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get(self, name: str, cls, *args):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(*args)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name!r} already registered as {type(m).__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str, fn: Optional[Callable[[], float]] = None) -> Gauge:
        g = self._get(name, Gauge, fn)
        if fn is not None:
            g.fn = fn
        return g

    def timer(self, name: str) -> Timer:
        return self._get(name, Timer)

    def report(self) -> Dict[str, object]:
        out: Dict[str, object] = {}
        with self._lock:
            items = list(self._metrics.items())
        for name, m in items:
            if isinstance(m, Counter):
                out[name] = m.value
            elif isinstance(m, Gauge):
                out[name] = m.value
            elif isinstance(m, Timer):
                out[name] = {
                    "count": m.count, "total_s": m.total_s,
                    "mean_s": m.mean_s, "max_s": m.max_s,
                }
        return out

    def prometheus(self) -> str:
        """Prometheus text exposition of all metrics."""
        lines: List[str] = []
        p = self.prefix
        for name, v in self.report().items():
            metric = f"{p}_{name}".replace(".", "_").replace("-", "_")
            if isinstance(v, dict):  # timer
                lines.append(f"{metric}_count {v['count']}")
                lines.append(f"{metric}_seconds_total {v['total_s']:.6f}")
                lines.append(f"{metric}_seconds_max {v['max_s']:.6f}")
            else:
                lines.append(f"{metric} {v}")
        return "\n".join(lines) + "\n"

    def clear(self):
        with self._lock:
            self._metrics.clear()


_REGISTRY = MetricRegistry()


def registry() -> MetricRegistry:
    return _REGISTRY


def inc(name: str, n: int = 1) -> None:
    """Shorthand: bump a counter in the process registry (used by the
    aggregate cache and the stream quarantine path, which count from hot
    loops and shouldn't re-spell the registry plumbing)."""
    _REGISTRY.counter(name).inc(n)


# Aggregate-cache metric names (cache/store.py, cache/service.py). Kept here
# so operators grepping the exposition format find the contract in one place:
#   cache.hit          whole-result hits (no scan at all)
#   cache.partial      partial-cover hits (only the residual cells scanned)
#   cache.miss         queries that found nothing reusable
#   cache.put          entries admitted
#   cache.evict        entries evicted by the size-aware LRU
#   cache.invalidate   entries dropped by a dataset epoch bump
#   cache.bytes        resident cached bytes (gauge)
#   cache.entries      resident entry count (gauge)
CACHE_HIT = "cache.hit"
# Warm-path executor metrics (kernels/registry.py, planning/executor.py,
# planning/partitioned_exec.py; docs/PERF.md):
#   kernel.recompiles   fresh jit traces admitted to the kernel registry
#                       (each one paid an XLA trace+compile)
#   kernel.bucket_hit   kernel registry hits — a query served by an
#                       already-compiled kernel (shape bucket + key match)
#   kernel.evict        LRU evictions from the kernel registry
#   pipeline.prefetch   partitions whose host load/column assembly was
#                       overlapped with the previous partition's execution
KERNEL_RECOMPILES = "kernel.recompiles"
KERNEL_BUCKET_HIT = "kernel.bucket_hit"
KERNEL_EVICT = "kernel.evict"
PIPELINE_PREFETCH = "pipeline.prefetch"
CACHE_PARTIAL = "cache.partial"
CACHE_MISS = "cache.miss"
CACHE_PUT = "cache.put"
CACHE_EVICT = "cache.evict"
CACHE_INVALIDATE = "cache.invalidate"
CACHE_BYTES = "cache.bytes"
CACHE_ENTRIES = "cache.entries"
