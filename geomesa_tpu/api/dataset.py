"""GeoDataset: the datastore API surface.

Role parity with the reference's GeoMesaDataStore + process layer
(GeoMesaDataStore.scala:49: schema CRUD, feature writer/reader, query planner
wiring, stats; geomesa-process: density/stats/unique/sampling/knn/proximity):
one Python object owning the schema catalog, per-schema FeatureStores, the
planner, and the executor.

Queries accept ECQL text plus hints. Aggregations (density, stats, knn, ...)
are first-class methods — the equivalent of GeoMesa's query-hint-driven
pushdown scans.
"""

from __future__ import annotations

import contextlib
import functools
import json
import os
import shutil
import time
import uuid
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from geomesa_tpu import config, metrics, resilience, security, tracing
from geomesa_tpu.audit import AuditWriter
from geomesa_tpu.cache import AggregateCache
from geomesa_tpu.filter import ir, parse_ecql
from geomesa_tpu.filter.compile import CompiledFilter
from geomesa_tpu.index.store import FeatureStore
from geomesa_tpu.planning.executor import Executor, query_deadline
from geomesa_tpu.planning.explain import Explainer
from geomesa_tpu.planning.planner import QueryHints, QueryPlanner
from geomesa_tpu.schema.columns import ColumnBatch, DictionaryEncoder, decode_batch
from geomesa_tpu.schema.feature_type import FeatureType
from geomesa_tpu.stats import parse_stat
from geomesa_tpu.stats import sketches as sk


@dataclass
class Query:
    """A query: ECQL + hints (the GeoTools Query analog)."""

    ecql: str = "INCLUDE"
    max_features: Optional[int] = None
    properties: Optional[List[str]] = None
    sort_by: Optional[List[Tuple[str, bool]]] = None  # (attr, descending)
    sampling: Optional[int] = None
    #: per-key sampling attribute: 1-in-``sampling`` per distinct value
    sample_by: Optional[str] = None
    index: Optional[str] = None
    #: visibility authorizations for this query (None = dataset default)
    auths: Optional[List[str]] = None
    #: EPSG code to reproject result geometries into (storage is 4326;
    #: the reference reprojects as the final post-processing step,
    #: QueryPlanner.scala:68-90). Built-in closed forms: 3857 (latitudes
    #: beyond +/-85.051 clamp to the projection edge with a
    #: RuntimeWarning), 3395, UTM 326xx/327xx, 5070, 3035; any EPSG via
    #: pyproj when installed; others pluggable via
    #: utils.reproject.register.
    srid: Optional[int] = None

    def hints(self) -> QueryHints:
        return QueryHints(
            query_index=self.index,
            sampling=self.sampling,
            sample_by=self.sample_by,
            max_features=self.max_features,
            properties=self.properties,
            sort_by=self.sort_by,
        )


class FeatureCollection:
    """Query result: host columns + decode helpers."""

    def __init__(self, ft: FeatureType, batch: ColumnBatch,
                 dicts: Dict[str, DictionaryEncoder], srid: int = 4326):
        self.ft = ft
        self.batch = batch
        self.dicts = dicts
        #: CRS of the geometry columns (4326 unless the query reprojected)
        self.srid = srid

    def __len__(self):
        return self.batch.n

    @property
    def columns(self):
        return self.batch.columns

    @property
    def fids(self):
        """Feature ids as ``str`` (the raw ``columns['__fid__']`` is a
        fixed-width bytes column at bulk scale)."""
        from geomesa_tpu.schema.columns import fid_strs

        col = self.batch.columns.get("__fid__")
        if col is None:
            return []
        return fid_strs(col).tolist()

    def to_dict(self) -> Dict[str, Any]:
        if self.batch.n == 0:
            return {}
        return decode_batch(self.ft, self.batch, self.dicts)

    def to_pandas(self):
        import pandas as pd

        d = self.to_dict()
        if not d:
            return pd.DataFrame()
        geom = self.ft.geom_field
        if geom in d and isinstance(d[geom], list) and d[geom] and isinstance(d[geom][0], tuple):
            xs, ys = zip(*d[geom])
            d[geom + "_x"], d[geom + "_y"] = list(xs), list(ys)
            del d[geom]
        return pd.DataFrame(d)


class SpatialJoinResult:
    """Result of a co-partitioned spatial join (docs/JOIN.md): the exact
    matched-pair total plus a streaming matched-pair view. ``count`` is
    exact over completed tiles (equal to the full answer unless
    ``stats.skipped`` is non-empty — the ``allow_partial()`` degradation
    account). ``batches()`` streams matched pairs as ColumnBatches of at
    most ``geomesa.join.batch.rows`` rows: left columns verbatim, right
    columns prefixed ``right.`` (the attribute equi-join's convention)."""

    def __init__(self, lst, lbatch: ColumnBatch, rst, rbatch: ColumnBatch,
                 pairs, count: int, stats):
        self._lst, self._lbatch = lst, lbatch
        self._rst, self._rbatch = rst, rbatch
        #: matched (left, right) row positions, int64 [K, 2], row-major
        self.pairs = pairs
        self.count = int(count)
        self.stats = stats

    @property
    def degraded(self) -> bool:
        return bool(self.stats.skipped)

    def batches(self, batch_rows: Optional[int] = None):
        """Yield matched-pair ColumnBatches (chunked: peak memory is one
        chunk's gathered columns, never the whole pair set)."""
        if self.pairs is None:
            raise ValueError("join_count result carries no pairs; use "
                             "join_spatial for the streaming form")
        if batch_rows is None:
            batch_rows = config.JOIN_BATCH_ROWS.to_int() or 65536
        batch_rows = max(int(batch_rows), 1)
        for lo in range(0, len(self.pairs), batch_rows):
            chunk = self.pairs[lo: lo + batch_rows]
            li, rj = chunk[:, 0], chunk[:, 1]
            cols = {k: v[li] for k, v in self._lbatch.columns.items()}
            for k, v in self._rbatch.columns.items():
                cols["right." + k] = v[rj]
            yield ColumnBatch(cols, len(chunk))

    def __iter__(self):
        return self.batches()

    def to_batch(self) -> ColumnBatch:
        """The whole pair set as one ColumnBatch (small joins / tests)."""
        out = list(self.batches(batch_rows=max(len(self.pairs), 1)))
        return out[0] if out else ColumnBatch({}, 0)


def _traced(op: str, speculative: Optional[str] = None):
    """Open one ROOT span per public query operation (docs/OBSERVABILITY.md)
    and pass it through serving admission (docs/SERVING.md): the local-path
    analog of the sidecar's admission queue — an op whose deadline budget is
    already expired (or provably unmeetable against recent service times)
    is SHED with a typed error before any planning or device work, and the
    op's wall time lands in the per-user serving ledger that backs both
    fair-share and the /debug/queries rollups. Admission is reentrant
    (nested public ops account once) and a no-op inside a scheduler-
    dispatched ticket (the ticket already accounts).

    ``speculative``: name of a method serving the SPECULATIVE degraded
    answer when admission sheds AND the caller opted in with
    ``speculative_ok=True`` — the op returns the typed coarse result
    (host-only, no device work — exactly what shedding protects) instead
    of raising ``[GM-SHED]`` (docs/SERVING.md)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, name, *args, **kw):
            from geomesa_tpu.resilience import DeadlineShedError

            spec_ok = bool(kw.pop("speculative_ok", False))
            with tracing.start(op, schema=name):
                # the fallback runs INSIDE the op's root span, so the
                # speculative audit event carries this trace id — the
                # degraded answers are exactly the ones operators need
                # to correlate back to a trace
                try:
                    with self.serving.admit(op):
                        return fn(self, name, *args, **kw)
                except DeadlineShedError:
                    # only the ADMISSION gate raises DeadlineShedError
                    # (a mid-scan expiry is a plain QueryTimeoutError),
                    # so no device work has happened yet
                    if not (spec_ok and speculative):
                        raise
                    return getattr(self, speculative)(name, *args, **kw)

        return wrapper

    return deco


class GeoDataset:
    """Schema catalog + per-schema stores + planner + executor."""

    def __init__(self, mesh=None, n_shards: Optional[int] = None,
                 prefer_device: bool = True,
                 auths: Optional[Sequence[str]] = None):
        self.mesh = mesh
        self.n_shards = n_shards
        self.prefer_device = prefer_device
        #: dataset-level authorizations (None = geomesa.security.auths or
        #: unrestricted; per-query ``Query.auths`` overrides)
        self.auths = list(auths) if auths is not None else None
        self.audit = AuditWriter()
        #: aggregate result cache (docs/CACHE.md) — shared by every query of
        #: this dataset, including all Flight queries when a sidecar serves
        #: it. Inert unless geomesa.cache.enabled=true.
        self.cache = AggregateCache()
        #: serving scheduler (docs/SERVING.md): local ops pass through its
        #: inline admission (deadline shed + per-user ledger); a Flight
        #: sidecar serving this dataset starts its dispatch thread, so
        #: Flight and local ops share ONE fair-share domain and ledger.
        from geomesa_tpu.serving import QueryScheduler

        self.serving = QueryScheduler()
        self.serving.set_residency_probe(self._residency_bytes)
        self._stores: Dict[str, FeatureStore] = {}
        self._executors: Dict[str, Executor] = {}
        self.metadata: Dict[str, Dict[str, str]] = {}
        #: durable mutation journal (fs/journal.py; docs/RESILIENCE.md §8).
        #: Attached by load()/attach_journal(); None keeps the
        #: in-memory-only semantics (acked mutations live until the next
        #: explicit save). With it attached, every mutation edge appends a
        #: typed record BEFORE applying and blocks until it is on disk.
        self._journal = None
        #: replay guard: mutations applied FROM the journal or a checkpoint
        #: attach must not re-journal themselves
        self._replaying = False
        #: per-schema high-water mark of journal records applied locally —
        #: lets a fleet replica catch up incrementally from the shared
        #: journal instead of re-attaching the whole schema snapshot
        self._applied_seq: Dict[str, int] = {}
        #: fingerprint of the manifest entry each schema was attached from —
        #: the incremental journal catch-up is only valid while the root's
        #: manifest entry is unchanged (journal-only growth); an entry
        #: rewritten out-of-band (e.g. a non-journaled save) forces the
        #: full re-attach path
        self._ckpt_fp: Dict[str, int] = {}
        #: records re-applied by the last load()/replay (CLI/bench surface)
        self._journal_replayed = 0
        #: standing-query engine (geomesa_tpu/subscribe/; docs/STANDING.md)
        #: — created lazily on the first subscribe() so datasets that never
        #: register a viewport pay nothing on the ingest path
        self.standing = None

    # -- schema CRUD (MetadataBackedDataStore analog) ----------------------
    def create_schema(self, name_or_ft, spec: Optional[str] = None) -> FeatureType:
        if isinstance(name_or_ft, FeatureType):
            ft = name_or_ft
        else:
            ft = FeatureType.from_spec(name_or_ft, spec)
        if ft.name in self._stores:
            raise ValueError(f"schema {ft.name!r} already exists")
        # schema-create records carry the spec so recovery is self-contained
        # (a schema created after the last checkpoint rebuilds from the
        # journal alone)
        self._journal_rec("schema-create", ft.name, spec=ft.spec(),
                          n_shards=self.n_shards)
        from geomesa_tpu.index.partitioned import (
            PartitionedFeatureStore, is_partitioned_schema,
        )

        if is_partitioned_schema(ft):
            self._stores[ft.name] = PartitionedFeatureStore(ft, self.n_shards)
        else:
            self._stores[ft.name] = FeatureStore(ft, self.n_shards)
        self.metadata[ft.name] = {"spec": ft.spec()}
        return ft

    def get_schema(self, name: str) -> FeatureType:
        return self._store(name).ft

    def list_schemas(self) -> List[str]:
        return sorted(self._stores)

    def delete_schema(self, name: str):
        st = self._store(name)  # raise if missing
        # tombstone FIRST: if we crash between the in-memory drop and the
        # next checkpoint, replay must not resurrect the schema from its
        # still-on-disk files
        self._journal_rec("delete-schema", name)
        if self.standing is not None:
            self.standing.drop_schema(name)
        # drop the schema's cached aggregates: its uid is never accessed
        # again, so neither epoch sync nor the per-uid LRU could reclaim them
        self.cache.store.invalidate(st.uid)
        del self._stores[name]
        del self.metadata[name]
        self._applied_seq.pop(name, None)
        self._ckpt_fp.pop(name, None)

    def describe(self, name: str) -> str:
        st = self._store(name)
        lines = [st.ft.describe(), f"  count: {st.count}"]
        lines.append(f"  indices: {[ks.name for ks in st.keyspaces]}")
        return "\n".join(lines)

    def _store(self, name: str) -> FeatureStore:
        st = self._stores.get(name)
        if st is None:
            raise KeyError(
                f"no schema {name!r} (have: {', '.join(sorted(self._stores)) or 'none'})"
            )
        return st

    # -- durable mutation journal (docs/RESILIENCE.md §8) ------------------
    def attach_journal(self, path: str):
        """Attach (or create) the write-ahead mutation journal under
        ``path``: from here on, every mutation edge appends a typed,
        crc-framed record and blocks until it is group-committed to disk —
        **ack = durable**. ``load()`` attaches automatically when the root
        has a journal; ``save()`` attaches on first checkpoint. No-op when
        ``geomesa.journal.enabled`` is false or a journal is already
        attached. Returns the journal (or None when disabled)."""
        if not config.JOURNAL_ENABLED.to_bool():
            return None
        if self._journal is None:
            from geomesa_tpu.fs.journal import MutationJournal

            self._journal = MutationJournal(path)
        return self._journal

    @contextlib.contextmanager
    def _replay_scope(self):
        prev = self._replaying
        self._replaying = True
        try:
            yield
        finally:
            self._replaying = prev

    def _journal_rec(self, kind: str, name: Optional[str],
                     blobs=None, **payload) -> None:
        """Append one typed mutation record (WAL discipline: BEFORE the
        mutation applies) and block until durable. A journal failure
        raises — the mutation is never acked non-durable. ``blobs`` is
        the raw-bytes sink filled by the caller's enc_columns pass."""
        j = self._journal
        if j is None or self._replaying:
            return
        rec = {"kind": kind, "schema": name}
        rec.update(payload)
        seq = j.append(rec, blobs=blobs)
        if name is not None:
            self._applied_seq[name] = seq

    def _apply_record(self, rec: Dict[str, Any]) -> bool:
        """Re-apply one journal record through the normal mutation edges
        (under :meth:`_replay_scope`, so nothing re-journals). Returns
        False for unknown kinds."""
        from geomesa_tpu.fs import journal as _jr

        kind, name = rec.get("kind"), rec.get("schema")
        if kind == "schema-create":
            prev = self.n_shards
            self.n_shards = rec.get("n_shards", prev)
            try:
                self.create_schema(FeatureType.from_spec(name, rec["spec"]))
            finally:
                self.n_shards = prev
        elif kind == "delete-schema":
            # tombstone: replay must never resurrect a dropped schema whose
            # files outlived the crash
            if name in self._stores:
                self.delete_schema(name)
                self._plan_cache_clear(name)
                self._drop_executors(name)
        elif kind == "insert":
            self.insert(name, _jr.dec_columns(rec["data"]),
                        _jr.dec_value(rec.get("fids")),
                        _jr.dec_value(rec.get("vis")))
        elif kind == "delete-features":
            self.delete_features(name, rec["ecql"],
                                 _jr.dec_value(rec.get("auths")))
        elif kind == "update-schema":
            self.update_schema(name, rec["add_spec"])
        elif kind == "age-off":
            self.age_off(name, int(rec["older_than_ms"]))
        elif kind == "add-index":
            self.add_attribute_index(name, rec["attr"])
        elif kind == "remove-index":
            self.remove_attribute_index(name, rec["attr"])
        elif kind == "subscribe":
            from geomesa_tpu.subscribe.spec import StandingSpec

            self._standing_engine().register(
                StandingSpec.from_dict(rec["spec"]), sub_id=rec["sub_id"])
        elif kind == "unsubscribe":
            if self.standing is not None:
                self.standing.unregister(rec["sub_id"])
        else:
            return False
        return True

    def _journal_replay(self, ckpt_seq: Dict[str, int],
                        schema: Optional[str] = None,
                        truncate: bool = False) -> int:
        """Replay journal records past each schema's checkpointed position
        (``ckpt_seq``), in global sequence order. A record that fails to
        apply is recorded through the degradation trail and skipped — a
        poisoned record must not fail the whole root. Returns #applied."""
        j = self._journal
        if j is None:
            return 0
        applied = 0
        with self._replay_scope():
            for rec in j.records(schema=schema, truncate=truncate):
                name = rec.get("schema")
                seq = int(rec.get("seq", 0))
                if seq <= ckpt_seq.get(name, 0):
                    continue
                if seq <= self._applied_seq.get(name, 0):
                    continue  # already applied live / by a prior replay
                try:
                    if not self._apply_record(rec):
                        continue
                except Exception as e:
                    resilience.record_skip(
                        "journal.replay", f"{name}@{seq}", e, phase="apply")
                    continue
                if name is not None:
                    self._applied_seq[name] = seq
                applied += 1
        if applied:
            metrics.registry().counter(metrics.JOURNAL_REPLAYED).inc(applied)
        self._journal_replayed = applied
        return applied

    # -- writes ------------------------------------------------------------
    def insert(self, name: str, data: Dict[str, Any], fids=None,
               visibilities=None) -> int:
        """Append a batch of features. Call flush() (or query) to index.

        ``visibilities``: per-feature visibility expression(s) (one string or
        a sequence), enforced at query time against ``Query.auths``."""
        st = self._store(name)
        if self._journal is not None and not self._replaying:
            from geomesa_tpu.fs import journal as _jr

            sink: list = []
            self._journal_rec(
                "insert", name, blobs=sink,
                data=_jr.enc_columns(data, sink),
                fids=None if fids is None else _jr.enc_value(fids, sink),
                vis=None if visibilities is None
                else _jr.enc_value(visibilities, sink))
        # standing-query delta hook (docs/STANDING.md): the observer sees
        # the ENCODED batch inside append — the same columns a re-scan of
        # the window reads — so delta evaluation is race-free and fires on
        # journal replay too (fleet catch-up advances standing results
        # through this same edge)
        obs = None
        if self.standing is not None and self.standing.active(name):
            obs = lambda b: self.standing.on_batch(name, b.columns, b.n)
        n = st.append(data, fids, visibilities, observer=obs)
        metrics.registry().counter("ingest.features").inc(n)
        return n

    def flush(self, name: Optional[str] = None):
        for st in ([self._store(name)] if name else self._stores.values()):
            st.flush()

    def ingest(self, name: str, source, converter_config) -> "Any":
        """Converter-driven ingest (geomesa-convert analog). ``source`` is
        text / a file object / parsed JSON; returns the EvaluationContext
        with success/failure counts."""
        from geomesa_tpu.convert import EvaluationContext, converter_for

        st = self._store(name)
        conv = converter_for(st.ft, converter_config)
        ctx = EvaluationContext()
        for data, fids in conv.convert(source, ctx):
            if data and len(next(iter(data.values()), ())) > 0:
                self.insert(name, data, fids)
        self.flush(name)
        return ctx

    def update_schema(self, name: str, add_spec: str) -> FeatureType:
        """Add attributes to an existing schema, keeping data (the reference's
        ``updateSchema`` supports append-only attribute changes; GeoMesaData
        Store.scala:288-336 validates transitions the same way).

        Existing columns — including visibility labels and derived geometry/
        time columns — are carried over verbatim IN PLACE: no index key
        changes, so sort permutations are untouched and no row is
        re-flushed (r4 rebuilt the whole store here — O(dataset) per
        added column). Added columns fill with this layout's null
        representation: string -> null code (-1), float -> NaN, int/long
        -> 0, bool -> False, date -> epoch 0 (the fixed-width columnar
        model has no validity bitmap for those). Spilled partitions
        upgrade lazily on their next load."""
        st = self._store(name)
        st.flush()
        old = st.ft
        # insert new attributes before the ';user-data' section, if any
        spec = old.spec()
        attrs_part, sep, ud_part = spec.partition(";")
        new_ft = FeatureType.from_spec(
            name, attrs_part + "," + add_spec + sep + ud_part
        )
        added = [a for a in new_ft.attributes if not old.has(a.name)]
        for a in added:
            if a.is_geom:
                raise ValueError("cannot add geometry attributes to a schema")
        self._journal_rec("update-schema", name, add_spec=add_spec)
        st.add_columns(new_ft, added)
        self._drop_executors(name)
        self._plan_cache_clear(name)
        self.metadata[name]["spec"] = new_ft.spec()
        return new_ft

    def add_attribute_index(self, name: str, attr: str) -> None:
        """Enable an attribute index on an existing schema without
        recreating it: builds ONLY the new sort permutation (per
        partition, under the residency budget, for partitioned stores;
        spilled partitions build theirs on next load). The reference
        validates exactly this transition in updateSchema
        (GeoMesaDataStore.scala:288-336)."""
        st = self._store(name)
        a = st.ft.attr(attr)
        self._journal_rec("add-index", name, attr=attr)
        st.add_attribute_index(attr)
        a.options["index"] = "true"  # so spec()/save()/load round-trips
        # an explicit geomesa.indices list overrides the option-derived
        # defaults in keyspaces_for_schema — it must name the attr kind
        # or rebuilt/loaded child stores would silently drop the index
        explicit = st.ft.user_data.get("geomesa.indices")
        if explicit is not None:
            kinds = [k.strip().lower() for k in explicit.split(",")
                     if k.strip()]
            if "attr" not in kinds:
                st.ft.user_data["geomesa.indices"] = explicit + ",attr"
        self._drop_executors(name)
        self._plan_cache_clear(name)
        self.metadata[name]["spec"] = st.ft.spec()

    def remove_attribute_index(self, name: str, attr: str) -> None:
        """Drop an attribute index (permutation + sketch); data untouched."""
        st = self._store(name)
        self._journal_rec("remove-index", name, attr=attr)
        st.remove_attribute_index(attr)
        st.ft.attr(attr).options.pop("index", None)
        self._drop_executors(name)
        self._plan_cache_clear(name)
        self.metadata[name]["spec"] = st.ft.spec()

    def age_off(self, name: str, older_than) -> int:
        """Drop features older than a cutoff (AgeOffFilter/DtgAgeOffFilter
        analog, reference index/filters/AgeOffFilter.scala). ``older_than``:
        epoch-ms int, numpy datetime64, or ISO string. Returns rows removed."""
        st = self._store(name)
        dtg = st.ft.dtg_field
        if dtg is None:
            raise ValueError(f"schema {name!r} has no date attribute")
        if isinstance(older_than, str):
            from geomesa_tpu.filter.ecql import parse_iso_ms

            cutoff = parse_iso_ms(older_than)
        elif isinstance(older_than, np.datetime64):
            cutoff = int(older_than.astype("datetime64[ms]").astype(np.int64))
        else:
            cutoff = int(older_than)
        # the RESOLVED cutoff is journaled, so replay is deterministic even
        # for callers that passed a relative/now-derived value
        self._journal_rec("age-off", name, older_than_ms=cutoff)
        st.flush()
        pred = lambda cols: cols[dtg] < cutoff
        bounds = self._standing_dirty_bounds(name, st, pred)
        n = st.delete(pred)
        if n and self.standing is not None and self.standing.active(name):
            self.standing.on_dirty(name, bounds)
        return n

    def delete_features(self, name: str, ecql: str,
                        auths: Optional[Sequence[str]] = None) -> int:
        """Delete matching features. A caller with restricted auths can only
        delete rows their auths permit them to see."""
        st = self._store(name)
        f = parse_ecql(ecql)
        self._journal_rec("delete-features", name, ecql=ecql,
                          auths=None if auths is None else list(auths))
        from geomesa_tpu.filter.compile import compile_filter

        cf = compile_filter(f, st.ft, st.dicts)
        cf = self._vis_wrap(st, cf, self._effective_auths(Query(auths=auths)))
        # exact_mask applies the extent-geometry refinement pass — deletes
        # must never act on the coarse bbox superset
        pred = lambda cols: cf.exact_mask(cols, len(cols["__fid__"]))
        bounds = self._standing_dirty_bounds(name, st, pred)
        n = st.delete(pred)
        if n and self.standing is not None and self.standing.active(name):
            # deletes are non-additive: standing groups intersecting the
            # removed rows' bounds re-scan; disjoint groups are untouched
            self.standing.on_dirty(name, bounds)
        return n

    def _standing_dirty_bounds(self, name: str, st: FeatureStore, pred):
        """BBox of the rows ``pred`` is about to remove — the dirty extent
        a non-additive mutation scopes standing re-scans to (docs/
        STANDING.md). None = no standing groups, or unknown extent."""
        if self.standing is None or not self.standing.active(name):
            return None
        st.flush()
        if st._all is None or not st._all.n:
            return None
        g = st.ft.geom_field
        cols = st._all.columns
        if g is None or g + "__x" not in cols:
            return None
        try:
            m = np.asarray(pred(cols)).astype(bool)
        except Exception:
            return None
        xs = cols[g + "__x"][m]
        ys = cols[g + "__y"][m]
        ok = np.isfinite(xs) & np.isfinite(ys)
        if not ok.any():
            return None
        xs, ys = xs[ok], ys[ok]
        return (float(xs.min()), float(ys.min()),
                float(xs.max()), float(ys.max()))

    # -- standing queries (geomesa_tpu/subscribe/; docs/STANDING.md) -------
    def _standing_engine(self):
        if self.standing is None:
            from geomesa_tpu.subscribe import (
                StandingQueryEngine, StoreWindow,
            )

            self.standing = StandingQueryEngine(
                lambda nm: StoreWindow(self, nm)
            )
        return self.standing

    def subscribe(self, name: str, aggregate: str, bbox=None, region=None,
                  width: int = 256, height: int = 256,
                  levels: Optional[int] = None,
                  stat_spec: Optional[str] = None,
                  sub_id: Optional[str] = None) -> str:
        """Register a standing viewport: every applied ingest batch then
        updates the result incrementally instead of re-scanning (docs/
        STANDING.md). Same-viewport subscribers fuse into one standing
        group. Returns the subscription id (its prefix is the fleet ring
        route key). NOTE: standing results are visibility-unrestricted —
        they aggregate every row of the window."""
        from geomesa_tpu.subscribe import spec as subspec

        sp = subspec.make_spec(
            name, aggregate, bbox=bbox, region=region, width=width,
            height=height, levels=levels, stat_spec=stat_spec,
        )
        self._store(name)  # raise on unknown schema before registering
        eng = self._standing_engine()
        if sub_id is None:
            # WAL discipline: the journal record carries the id the
            # register will use, so crash replay rebuilds the SAME
            # subscription id the caller was handed (docs/STANDING.md §7)
            sub_id = eng.make_sub_id(sp)
        self._journal_rec("subscribe", name, spec=sp.to_dict(),
                          sub_id=sub_id)
        return eng.register(sp, sub_id=sub_id)

    def unsubscribe(self, sub_id: str) -> bool:
        if self.standing is None:
            return False
        schema = self.standing.schema_of(sub_id)
        if schema is not None:
            self._journal_rec("unsubscribe", schema, sub_id=sub_id)
        return self.standing.unregister(sub_id)

    def subscription_poll(self, sub_id: str, cursor: int = 0):
        """Current standing result + update records past ``cursor``."""
        from geomesa_tpu.subscribe import UnknownSubscription

        if self.standing is None:
            raise UnknownSubscription(sub_id)
        return self.standing.poll(sub_id, cursor)

    # -- planning ----------------------------------------------------------
    def _effective_auths(self, q: Query) -> Optional[List[str]]:
        if q.auths is not None:
            return list(q.auths)
        if self.auths is not None:
            return self.auths
        return security.DefaultAuthorizationsProvider().auths()

    def _vis_wrap(self, st: FeatureStore, compiled: CompiledFilter,
                  auths) -> CompiledFilter:
        """Fuse the row-visibility check into a predicate mask
        (LocalQueryRunner.visible:133 analog, but in the scan kernel)."""
        if auths is None:
            return compiled
        vd = st.dicts.get(security.VIS_COLUMN)
        if vd is None:
            return compiled  # no feature has ever carried a visibility
        lut = security.allowed_lut(vd.values, auths)
        if lut.all():
            return compiled
        inner = compiled

        def fn(cols, xp):
            allowed = xp.asarray(lut)[cols[security.VIS_COLUMN]]
            return inner.fn(cols, xp) & allowed

        refine = inner.refine
        if refine is not None:
            # the exact tree must ALSO enforce visibility: band corrections
            # and refinement passes evaluate it directly, and a row the
            # caller's auths cannot see must never be restored by either
            inner_refine = refine

            def refine(cols, xp=np):  # noqa: F811
                allowed = np.asarray(lut)[np.asarray(cols[security.VIS_COLUMN])]
                return np.asarray(inner_refine(cols, xp)) & allowed

        rcols = list(inner.refine_columns or [])
        if refine is not None and security.VIS_COLUMN not in rcols:
            rcols.append(security.VIS_COLUMN)
        return CompiledFilter(
            fn, list(inner.columns) + [security.VIS_COLUMN], inner.ecql,
            refine=refine, refine_columns=rcols,
            band=inner.band,
            refine_only_if_band=inner.refine_only_if_band,
        )

    def _apply_visibility(self, st: FeatureStore, plan, auths) -> None:
        plan.compiled = self._vis_wrap(st, plan.compiled, auths)

    def _plan(self, name: str, query: "str | Query", explain=None):
        from geomesa_tpu.kernels import registry as kreg

        # per-query recompile window: a jit site tracing more than
        # geomesa.kernel.alert.threshold times before the next query trips
        # the kernel.recompile.alert gauge (docs/OBSERVABILITY.md)
        kreg.begin_query_window()
        with tracing.span("plan"):
            return self._plan_inner(name, query, explain)

    def _plan_inner(self, name: str, query: "str | Query", explain=None):
        st = self._store(name)
        st.flush()
        q = Query(ecql=query) if isinstance(query, str) else query
        auths = self._effective_auths(q)
        # Plan-object cache (IteratorCache.scala:30 analog: remote servers
        # cache parsed filters by spec string): a plan is pure in (query,
        # auths, schema/store version, interceptor registry), and reusing
        # the OBJECT also reuses the window/kernel caches that live on it.
        pkey = None
        if explain is None and isinstance(q.ecql, str):
            from geomesa_tpu.planning import interceptors

            pkey = (name, repr(q), None if auths is None else tuple(auths),
                    st.uid, st.version, interceptors.version())
            cache = self.__dict__.setdefault("_plan_cache", {})
            hit = cache.get(pkey)
            if hit is not None:
                # guards are config-dependent (e.g. BLOCK_FULL_TABLE_SCANS
                # may have flipped since the plan was cached): re-check
                # them on every hit — they are cheap; planning is not
                QueryPlanner(st)._guard(hit.key_plan, hit.filter, Explainer())
                interceptors.apply_guards(st.ft, hit)
                # exec_path/degraded describe ONE execution: stale notes
                # from the cached plan's previous run (device_error, sort,
                # skipped partitions, ...) must not leak into this call's
                # audit/explain
                hit.__dict__.pop("exec_path", None)
                hit.__dict__.pop("degraded", None)
                return st, q, hit
        planner = QueryPlanner(st)
        t0 = time.perf_counter()
        with metrics.registry().timer("query.plan").time():
            plan = planner.plan(q.ecql, q.hints(), explain)
        self._apply_visibility(st, plan, auths)
        if isinstance(q.ecql, str):
            # the predicate is reproducible from text + auths + the
            # EFFECTIVE filter (interceptors may rewrite it for the same
            # text — QueryInterceptor.scala:51): allow the executor to
            # reuse jitted kernels and resolved windows across API calls
            plan.__dict__["cache_token"] = (
                q.ecql,
                None if auths is None else tuple(auths),
                hash(repr(plan.filter)),
            )
        plan.__dict__["plan_time_ms"] = (time.perf_counter() - t0) * 1e3
        if pkey is not None:
            if len(cache) >= 256:
                cache.clear()
            cache[pkey] = plan
        return st, q, plan

    def _plan_cache_clear(self, name: str) -> None:
        """Drop cached plans for one schema (lifecycle changes bump the
        store version too, so stale entries could never HIT — this just
        releases them eagerly). The fusion layer's structural-template
        memo rides along: slot eligibility reads the schema's attribute
        types, which lifecycle changes can alter (docs/SERVING.md
        "Query-axis batching")."""
        cache = self.__dict__.get("_plan_cache")
        if cache:
            for k in [k for k in cache if k[0] == name]:
                del cache[k]
        tcache = self.__dict__.get("_template_key_cache")
        if tcache:
            for k in [k for k in tcache if k[0] == name]:
                del tcache[k]

    @staticmethod
    def _plan_audit_extras(plan) -> Dict[str, Any]:
        """Execution-path hints shared by every audit writer (the normal
        :meth:`_audit` and the fused batch's per-member events): exec_path,
        device timings, and the degraded-partition account. Pops
        ``degraded`` — the plan object is cached/reused across calls, and
        each execution's skip list must be reported exactly once
        (docs/RESILIENCE.md)."""
        extras: Dict[str, Any] = {}
        path = plan.__dict__.get("exec_path")
        if path:
            extras["exec_path"] = {
                k: v for k, v in path.items() if v is not None
            }
        if "device_coarse_ms" in plan.__dict__:
            extras["device_coarse_ms"] = round(
                plan.__dict__["device_coarse_ms"], 3
            )
        acct = plan.__dict__.pop("lake_acct", None)
        if acct:
            # pruned-vs-loaded row groups and bytes for THIS execution
            # (docs/LAKE.md; popped like degraded — cached plans re-run)
            extras["lake"] = dict(acct)
        degraded = plan.__dict__.pop("degraded", None)
        if degraded:
            extras["degraded"] = [
                {"part": d.part, "error": d.error, "phase": d.phase}
                for d in degraded
            ]
        return extras

    def _audit(self, name: str, q: Query, plan, t_scan0: float, hits: int,
               op: str = "query"):
        hints = {"op": op, "index": plan.index_name,
                 "max_features": q.max_features, "sampling": q.sampling}
        # the span tree and the audit event meet on this id: operators go
        # from a slow QueryEvent straight to its trace (and, for sidecar
        # queries, from the server audit back to the client's root span)
        tid = tracing.current_trace_id()
        if tid is not None:
            hints["trace_id"] = tid
        hints.update(self._plan_audit_extras(plan))
        self.audit.record(
            name, plan.ecql, hints,
            plan.__dict__.get("plan_time_ms", 0.0),
            (time.perf_counter() - t_scan0) * 1e3, hits,
            # serving identity (docs/SERVING.md): the admitted user —
            # Flight header or geomesa.user — lands on the QueryEvent, so
            # the audit log and the fair-share ledger attribute alike
            user=self.serving.current_user() or "",
            scanned=plan.__dict__.get("scanned_rows", 0),
            table_rows=plan.__dict__.get("table_rows", 0),
        )

    @_traced("explain")
    def explain(self, name: str, query: "str | Query",
                analyze: bool = False, region=None) -> str:
        """Planner explain tree. ``analyze=True`` additionally resolves the
        scan windows and runs a count so the output reports selectivity —
        candidate (scanned) rows vs matched rows — the over-scan signal.
        ``region``: optional polygon, folded in exactly as the aggregate
        entry points do (see :meth:`density`)."""
        exp = Explainer(enabled=True)
        st, q0, plan = self._plan(
            name, self._with_region(name, query, region), exp
        )
        # cache participation (docs/CACHE.md): would this query be served
        # from / populate the aggregate cache, and in what shape?
        from geomesa_tpu.cache import decompose

        exp.push("Aggregate cache")
        exp.kv("enabled", bool(config.CACHE_ENABLED.to_bool()))
        d = decompose(plan.filter, st.ft)
        if d is not None:
            exp.kv("partial-cover", f"level {d.level}, "
                   f"{len(d.cells)} interior cells, "
                   f"{len(d.strips)} boundary strips")
            exp.kv("residual filter", d.residual_key)
        else:
            from geomesa_tpu.cache import decompose_region

            dr = decompose_region(plan.filter, st.ft)
            if dr is not None:
                exp.kv("polygon cover", f"level {dr.level}, "
                       f"{len(dr.cells)} interior cells, "
                       f"{len(dr.boundary)} boundary cells")
                exp.kv("residual filter", dr.residual_key)
            else:
                exp.line("partial-cover: not decomposable "
                         "(whole-result caching only)")
        exp.pop()
        # hierarchical pre-aggregation posture (docs/CACHE.md): would this
        # query's cells be served from the quadtree, and from which levels?
        from geomesa_tpu.cache import hierarchy as _hier

        exp.push("Hierarchy")
        exp.kv("enabled", _hier.enabled())
        exp.kv("depth", _hier.depth())
        probe = (self.cache.probe_cover(self, st, q0, plan)
                 if _hier.enabled() else None)
        if probe is not None:
            served = sum(probe["levels"].values())
            exp.kv(
                "cells resident/assemblable",
                f"{served}/{probe['cells']}"
                + (f" ({probe['boundary']} boundary cells scan exactly)"
                   if probe["kind"] == "polygon" else ""),
            )
            if probe["levels"]:
                exp.kv("levels hit", ", ".join(
                    f"L{lvl}={n}" for lvl, n in sorted(probe["levels"].items())
                ))
            exp.kv("residual fraction", probe["residual_fraction"])
        else:
            exp.line("no cell cover for this query (whole-result only)")
        exp.pop()
        # warm-path posture (docs/PERF.md): shape bucketing + the shared
        # version-stable kernel registry + the partition prefetch pipeline
        exp.push("Warm path")
        floor = config.COMPACT_BUCKET_FLOOR.to_int()
        exp.kv(
            "shape bucketing",
            f"on (K floor {8 if floor is None else floor})"
            if config.COMPACT_BUCKETING.to_bool() else "off",
        )
        ex0 = self._executor(st)
        reg = (ex0.kernel_registry()
               if hasattr(ex0, "kernel_registry") else None)
        if reg is not None:
            tr = reg.traces()
            exp.kv(
                "kernel registry",
                f"{len(reg)} compiled kernels, "
                f"{sum(tr.values())} traces to date",
            )
            if tr:
                per_site = ", ".join(
                    f"{site}={n}" for site, n in sorted(
                        tr.items(), key=lambda kv: -kv[1]
                    )[:8]
                )
                exp.kv("traces by site", per_site)
        # per-site recompile alert posture (docs/OBSERVABILITY.md): the
        # same signal /metrics exposes as kernel.recompile.alert
        from geomesa_tpu.kernels import registry as kreg

        thr = kreg.alert_threshold()
        qw = kreg.query_recompiles()
        over = {s: n for s, n in qw.items() if n > thr}
        exp.kv(
            "recompile alert",
            (f"TRIPPED ({', '.join(f'{s}={n}' for s, n in sorted(over.items()))})"
             if over else f"clear (threshold {thr}/query)"),
        )
        exp.kv("prefetch pipeline",
               bool(config.PIPELINE_PREFETCH.to_bool()))
        exp.kv("persistent compile cache",
               config.COMPILE_CACHE_DIR.get() or "off")
        exp.pop()
        # observability posture. The trace_id is THIS explain call's own
        # trace (explain writes no audit event); a query's audit-greppable
        # id lives in its QueryEvent hints — this line documents the id
        # format and proves tracing is live end-to-end
        exp.push("Observability")
        exp.kv("tracing", "on" if tracing.enabled() else "off")
        tid = tracing.current_trace_id()
        if tid is not None:
            exp.kv("trace_id (this explain call)", tid)
        slow = config.TRACE_SLOW_MS.get()
        exp.kv("slow-query threshold", f"{slow} ms" if slow else "off")
        exp.pop()
        if analyze:
            ex = self._executor(st)
            matched = ex.count(plan)
            scanned = plan.__dict__.get("scanned_rows", 0)
            total = plan.__dict__.get("table_rows", 0)
            exp.push("Selectivity (analyze)")
            exp.line(f"Table rows: {total}")
            exp.line(f"Window candidates (scanned): {scanned}")
            exp.line(f"Matched: {matched}")
            if scanned:
                exp.line(f"Match ratio: {matched / scanned:.4f}")
            if "device_coarse_ms" in plan.__dict__:
                exp.line(
                    "Device coarse kernel: "
                    f"{plan.__dict__['device_coarse_ms']:.3f} ms "
                    "(host refined candidates only)"
                )
            path = plan.__dict__.get("exec_path")
            if path:
                exp.push("Execution path")
                for k, v in path.items():
                    if v is not None:
                        exp.line(f"{k}: {v}")
                # achieved scan bandwidth vs the docs/SCALE.md roofline
                # (the cost model's per-row HBM bound), when a device
                # coarse timing exists to measure against
                ms = plan.__dict__.get("device_coarse_ms")
                if ms and scanned:
                    n_cols = len(plan.compiled.columns) or 1
                    gbs = scanned * n_cols * 4 / (ms * 1e-3) / 1e9
                    exp.line(f"achieved scan bandwidth: {gbs:.1f} GB/s "
                             f"({scanned} rows x {n_cols} f32 cols)")
                exp.pop()
            exp.pop()
        # per-query cost attribution (docs/OBSERVABILITY.md): THIS explain
        # call's trace cost ledger — device ms per device, partition
        # pruning, bytes staged, cache hits — populated by analyze's count
        # (a plan-only explain shows planning-side cost only). The same
        # ledger rolls per-user into /debug/queries and rides exported
        # traces as geomesa.cost.* attributes.
        exp.push("Cost")
        cost = tracing.current_cost()
        if cost:
            for k, v in sorted(cost.items()):
                exp.kv(k, round(v, 3))
        else:
            exp.line("(none recorded — enable geomesa.trace.enabled and "
                     "analyze=True for device/partition attribution)")
        exp.pop()
        return str(exp)

    def _executor(self, st: FeatureStore) -> Executor:
        # one executor per store (per serving-pool slot): executors cache
        # sharding objects, and device_columns keys its upload cache by
        # id(sharding) — a fresh executor per query would re-upload every
        # column on meshed datasets. On a pool dispatch thread (slot > 0)
        # the executor is keyed (schema, slot) and PINNED to that slot's
        # device, so N dispatch threads drive N devices without ever
        # sharing one (docs/SERVING.md); slot 0 / inline callers keep the
        # original un-keyed, un-pinned executor byte-for-byte.
        from geomesa_tpu.index.partitioned import PartitionedFeatureStore
        from geomesa_tpu.planning.partitioned_exec import PartitionedExecutor

        slot = self.serving.current_slot()
        key = st.ft.name if not slot else (st.ft.name, slot)
        ex = self._executors.get(key)
        if ex is not None and slot and self.mesh is None \
                and self.prefer_device:
            # device-health re-pin (docs/RESILIENCE.md §6): the slot ->
            # device mapping moves when a device is cordoned or its
            # breaker opens (parallel/devices.slot_device skips fenced
            # lanes), so a cached slot executor pinned to the OLD device
            # is rebuilt on its next dispatch — the supervisor's
            # "respawn on a healthy device" lands here
            from geomesa_tpu.parallel.devices import slot_device

            if getattr(ex, "device", None) is not slot_device(slot):
                ex = None
        if ex is None or ex.store is not st:
            device = None
            if slot and self.mesh is None and self.prefer_device:
                from geomesa_tpu.parallel.devices import slot_device

                device = slot_device(slot)
            if isinstance(st, PartitionedFeatureStore):
                ex = PartitionedExecutor(st, self.mesh, self.prefer_device,
                                         device=device)
            else:
                ex = Executor(st, self.mesh, self.prefer_device,
                              device=device)
            self._executors[key] = ex
        return ex

    def _drop_executors(self, name: str) -> None:
        """Drop every slot's executor for one schema (lifecycle changes)."""
        for k in [k for k in self._executors
                  if k == name or (isinstance(k, tuple) and k[0] == name)]:
            del self._executors[k]

    def _residency_bytes(self, schema: str, slot: int) -> int:
        """One schema's device-resident column bytes on serving slot
        ``slot``'s device RIGHT NOW — the scheduler's placement-ranking
        probe (docs/SERVING.md §5c: rank candidate slots by ACTUAL
        residency, not by who dispatched last). A cheap metadata walk
        over the stores' device-column caches (no jit, no locks, no
        device sync — it runs under the scheduler lock). Meshed datasets
        shard every column across all devices, so residency is uniform
        and the probe abstains."""
        if self.mesh is not None:
            return 0
        st = self._stores.get(schema)
        if st is None:
            return 0
        try:
            from geomesa_tpu.parallel.devices import slot_device

            dev = slot_device(slot)
        except Exception:
            return 0
        total = 0
        children = (list(st.partitions.values())
                    if hasattr(st, "partitions") else [st])
        for child in children:
            for t in getattr(child, "tables", {}).values():
                for cached in list(t._device_cache.values()):
                    for arr in list(cached.values()):
                        try:
                            if dev in arr.devices():
                                total += int(arr.nbytes)
                        except Exception:
                            continue  # a mid-walk eviction never fails
        return total

    # -- reads -------------------------------------------------------------
    @staticmethod
    def _timeout_s() -> Optional[float]:
        ms = config.QUERY_TIMEOUT.to_duration_ms()
        return ms / 1000.0 if ms is not None else None

    @_traced("query")
    def query(self, name: str, query: "str | Query" = "INCLUDE") -> FeatureCollection:
        st, q, plan = self._plan(name, query)
        t0 = time.perf_counter()
        ex = self._executor(st)
        with metrics.registry().timer("query.scan").time(), \
                query_deadline(self._timeout_s()):
            batch = None
            # sort+limit pushdown: the device selects the top-k candidate
            # rows by the PRIMARY sort key (superset with boundary ties —
            # threshold select for large k / non-f32 dtypes), and the host
            # gathers + exact-sorts only those candidates instead of the
            # whole result set. Multi-key sorts are exact because every
            # primary-key boundary tie is among the candidates.
            topk_max = config.TOPK_MAX.to_int()
            if topk_max is None:
                topk_max = int(config.TOPK_MAX.default)  # 0 disables
            if (
                q.sort_by
                and q.max_features is not None
                and 0 < q.max_features <= topk_max
            ):
                attr, desc = q.sort_by[0]
                ties = len(q.sort_by) > 1
                names = None
                if plan.hints.properties:
                    names = list(plan.hints.properties) + [
                        a for a, _ in q.sort_by]
                if hasattr(ex, "top_rows"):
                    idx = ex.top_rows(plan, attr, desc, q.max_features,
                                      include_ties=ties)
                    if idx is not None:
                        table = st.tables[plan.index_name]
                        batch = table.host_gather_positions(idx, names)
                elif hasattr(ex, "top_batch"):
                    # partitioned store: per-partition candidate top-ks,
                    # exact-sorted + truncated below
                    batch = ex.top_batch(plan, attr, desc, q.max_features,
                                         names, include_ties=ties)
                if batch is not None:
                    plan.__dict__.setdefault("exec_path", {})[
                        "sort"] = f"device-topk(k={q.max_features})"
            if batch is None:
                batch = ex.features(plan)
        self._audit(name, q, plan, t0, batch.n)
        # post-processing: sort -> limit -> projection (QueryPlanner.runQuery
        # order, reference QueryPlanner.scala:68-90)
        if q.sort_by and batch.n:
            # stable multi-key sort, least-significant key first
            order = np.arange(batch.n)
            for attr, desc in reversed(q.sort_by):
                col = batch.columns[attr][order]
                if attr in st.dicts:
                    # dictionary codes are insertion-ordered: decode so
                    # ORDER BY a string is lexicographic (nulls first)
                    col = np.asarray(
                        [v if v is not None else ""
                         for v in st.dicts[attr].decode(col)],
                        dtype=object,
                    )
                if desc:
                    o2 = (batch.n - 1) - np.argsort(col[::-1], kind="stable")[::-1]
                else:
                    o2 = np.argsort(col, kind="stable")
                order = order[o2]
            batch = ColumnBatch(
                {k: v[order] for k, v in batch.columns.items()}, batch.n
            )
        if q.max_features is not None and batch.n > q.max_features:
            batch = ColumnBatch(
                {k: v[: q.max_features] for k, v in batch.columns.items()},
                q.max_features,
            )
        if q.properties:
            keep = set(q.properties) | {"__fid__"}
            pref = tuple(p + "__" for p in q.properties)
            batch = ColumnBatch(
                {
                    k: v for k, v in batch.columns.items()
                    if k in keep or k.startswith(pref)
                },
                batch.n,
            )
        if q.srid is not None and q.srid != 4326 and batch.n:
            batch = self._reproject_batch(st.ft, batch, q.srid)
        return FeatureCollection(st.ft, batch, st.dicts, srid=q.srid or 4326)

    @staticmethod
    def _reproject_batch(ft: FeatureType, batch: ColumnBatch,
                         srid: int) -> ColumnBatch:
        """Transform every geometry column to ``srid`` (last step of the
        post-processing chain, matching QueryPlanner.scala:68-90; raises
        for unregistered CRS pairs). Point x/y columns transform in one
        vectorized pass; WKT extent columns batch every vertex of every
        geometry into one transform call (nulls pass through)."""
        from geomesa_tpu.utils import reproject as rp

        fn = rp.transformer(4326, srid)
        cols = dict(batch.columns)
        for a in ft.attributes:
            if not a.is_geom:
                continue
            xc, yc = a.name + "__x", a.name + "__y"
            if xc in cols:
                x, y = fn(
                    np.asarray(cols[xc], np.float64),
                    np.asarray(cols[yc], np.float64),
                )
                cols[xc], cols[yc] = x, y
            wc = a.name + "__wkt"
            if wc in cols:
                cols[wc] = rp.reproject_wkt_array(cols[wc], fn)
        return ColumnBatch(cols, batch.n)

    def query_batches(self, name: str, query: "str | Query" = "INCLUDE",
                      batch_rows: Optional[int] = None):
        """Stream query results as ColumnBatch chunks (the ArrowScan delta-
        batch contract): a partitioned store yields partition-at-a-time so
        peak memory is one partition's matches, never the whole result.
        Sorted queries fall back to one materialized batch (a global sort
        needs all rows). Projection and CRS reprojection (Query.srid)
        apply per chunk — the stream carries the same CRS query() returns
        — and audit fires once at stream end."""
        q = Query(ecql=query) if isinstance(query, str) else query
        if q.sort_by:  # a global sort needs all rows: one materialized batch
            fc = self.query(name, q)

            def _one():
                if fc.batch.n:
                    yield fc.batch

            return _one()
        # plan EAGERLY so unknown attributes / parse errors / guard vetoes
        # (and unregistered CRS pairs) raise here, not mid-stream inside
        # the consumer's iteration. The root span is managed manually
        # (adopt + finish, never __enter__/__exit__): it must cover the
        # consumer-driven iteration, which outlives this call frame.
        root = tracing.start("query_batches", schema=name)
        traced = root is not tracing.NOOP
        prev = tracing.snapshot()
        if traced:
            root.t0 = time.perf_counter()
            tracing.adopt(root)
        try:
            # serving admission (docs/SERVING.md): shed-before-work + the
            # per-user ledger; the admitted span covers the eager planning
            # (the stream body is driven by the consumer's iteration)
            with self.serving.admit("query_batches"):
                st, q, plan = self._plan(name, q)
            if q.srid is not None and q.srid != 4326:
                from geomesa_tpu.utils import reproject as rp

                rp.transformer(4326, q.srid)  # raise now if unknown
        except BaseException:
            # the generator (whose finally owns the happy-path finish)
            # never runs when planning raises: close the root here so a
            # failed query still lands in the histogram/slow log
            if traced:
                root.finish()
            raise
        finally:
            if traced:
                tracing.adopt(prev)  # restore any enclosing span, not None
        keep_pref = None
        if q.properties:
            keep = set(q.properties) | {"__fid__"}
            keep_pref = (keep, tuple(p + "__" for p in q.properties))

        def _iter():
            t0 = time.perf_counter()
            hits = 0
            iter_prev = tracing.snapshot()  # the CONSUMER thread's context
            if traced:
                tracing.adopt(root)
            try:
                with metrics.registry().timer("query.scan").time(), \
                        query_deadline(self._timeout_s()):
                    for batch in self._executor(st).features_iter(plan, batch_rows):
                        hits += batch.n
                        if keep_pref is not None:
                            keep, pref = keep_pref
                            batch = ColumnBatch(
                                {
                                    k: v for k, v in batch.columns.items()
                                    if k in keep or k.startswith(pref)
                                },
                                batch.n,
                            )
                        if q.srid is not None and q.srid != 4326 and batch.n:
                            batch = self._reproject_batch(st.ft, batch, q.srid)
                        yield batch
                self._audit(name, q, plan, t0, hits)
            finally:
                if traced:
                    root.finish()
                    tracing.adopt(iter_prev)

        return _iter()

    def _with_region(self, name: str, query: "str | Query", region):
        """Fold a polygon ``region`` into the query as one INTERSECTS
        conjunct on the schema's geometry — the canonical aggregate-over-
        polygon shape (docs/CACHE.md): the cache decomposes it into
        interior cells (hierarchy-served) plus an exact boundary scan.
        ``region``: WKT text or a geometry object. Composed as ECQL TEXT
        when the query is textual, so the plan cache, the version-stable
        kernel tokens, and the serving fusion keys (docs/SERVING.md) all
        see the polygon — two different regions can never fuse or share a
        whole-result entry."""
        if region is None:
            return query
        from geomesa_tpu.utils import geometry as geo

        geom = self._store(name).ft.geom_field
        if geom is None:
            raise ValueError(f"schema {name!r} has no geometry field")
        wkt = region if isinstance(region, str) else region.wkt()
        geo.parse_wkt(wkt)  # validate before it reaches the planner
        conjunct = f"INTERSECTS({geom}, {wkt})"
        q = query if isinstance(query, Query) else Query(ecql=query)
        if not isinstance(q.ecql, str):
            combined: "str | ir.Filter" = ir.And(
                (q.ecql, parse_ecql(conjunct))
            )
        elif q.ecql.strip().upper() == "INCLUDE":
            combined = conjunct
        else:
            combined = f"({q.ecql}) AND {conjunct}"
        import dataclasses

        q = dataclasses.replace(q, ecql=combined)
        return q if isinstance(query, Query) or not isinstance(combined, str) \
            else combined

    @_traced("count", speculative="_speculative_count")
    def count(self, name: str, query: "str | Query" = "INCLUDE",
              exact: bool = True, region=None) -> int:
        """Exact feature count. ``speculative_ok=True`` (kw): under
        overload, a count this deadline would shed at admission returns
        the planner's coarse estimate — typed via an audit event carrying
        ``speculative: true`` — instead of failing ``[GM-SHED]``
        (docs/SERVING.md; the sidecar's ``speculative_ok`` request flag /
        ``x-geomesa-speculative-ok`` header ride the same path)."""
        st, q, plan = self._plan(name, self._with_region(name, query, region))
        if not exact:
            return int(plan.est_count)
        t0 = time.perf_counter()
        with query_deadline(self._timeout_s()):
            n = self.cache.count(self, st, q, plan)
        self._audit(name, q, plan, t0, n, op="count")
        return n

    def _speculative_count(self, name: str, query: "str | Query" = "INCLUDE",
                           exact: bool = True, region=None) -> int:
        """The speculative degraded count (see :meth:`count`): planner
        estimate only — host work, zero device time — with its own audit
        marker so operators can distinguish every coarse answer served
        under load from the exact counts around it."""
        st, q, plan = self._plan(name, self._with_region(name, query, region))
        est = int(plan.est_count)
        metrics.inc(metrics.SERVING_SPECULATIVE)
        hints = {"op": "count", "index": plan.index_name,
                 "speculative": True, "shed": True}
        tid = tracing.current_trace_id()
        if tid is not None:
            hints["trace_id"] = tid
        self.audit.record(
            name, plan.ecql, hints,
            plan.__dict__.get("plan_time_ms", 0.0), 0.0, est,
            user=self.serving.current_user() or "",
        )
        return est

    def bounds(self, name: str) -> Optional[Tuple[float, float, float, float]]:
        st = self._store(name)
        st.flush()
        mm = st.stats.get("bounds")
        if not isinstance(mm, sk.MinMax) or mm.is_empty:
            return None
        return (mm.lo[0], mm.lo[1], mm.hi[0], mm.hi[1])

    # -- analytics (geomesa-process parity) --------------------------------
    @_traced("density", speculative="_speculative_density")
    def density(self, name: str, query: "str | Query" = "INCLUDE",
                bbox=None, width: int = 256, height: int = 256,
                weight: Optional[str] = None, region=None) -> np.ndarray:
        """Heatmap grid (DensityProcess / DensityScan analog). ``region``:
        optional polygon (WKT or geometry) clipping the aggregate — folded
        in as an INTERSECTS conjunct; with the cache enabled the interior
        decomposes over hierarchy cells and only the polygon boundary
        scans (docs/CACHE.md). ``speculative_ok=True`` (kw): under
        overload, a density this deadline would shed at admission returns
        the coarse cache/hierarchy-served estimate grid — typed via an
        audit event carrying ``speculative: true`` — instead of failing
        ``[GM-SHED]`` (docs/SERVING.md)."""
        st, q, plan = self._plan(name, self._with_region(name, query, region))
        if bbox is None:
            bbox = self.bounds(name) or (-180, -90, 180, 90)
            bbox = (bbox[0], bbox[1], bbox[2], bbox[3])
        else:
            bbox = tuple(bbox)
        t0 = time.perf_counter()
        with metrics.registry().timer("query.density").time(), \
                query_deadline(self._timeout_s()):
            grid = self.cache.density(
                self, st, q, plan, bbox, width, height, weight
            )
        self._audit(name, q, plan, t0, int(np.count_nonzero(grid)), op="density")
        return grid

    def _speculative_audit(self, name: str, plan, op: str, hits: int,
                           extra: Optional[Dict[str, Any]] = None) -> None:
        """Shared audit marker for every speculative degraded answer
        (docs/SERVING.md): ``speculative: true`` + ``shed: true`` so
        operators can distinguish each coarse answer served under load."""
        metrics.inc(metrics.SERVING_SPECULATIVE)
        hints: Dict[str, Any] = {"op": op, "index": plan.index_name,
                                 "speculative": True, "shed": True}
        if extra:
            hints.update(extra)
        tid = tracing.current_trace_id()
        if tid is not None:
            hints["trace_id"] = tid
        self.audit.record(
            name, plan.ecql, hints,
            plan.__dict__.get("plan_time_ms", 0.0), 0.0, hits,
            user=self.serving.current_user() or "",
        )

    def _speculative_density(self, name: str,
                             query: "str | Query" = "INCLUDE",
                             bbox=None, width: int = 256, height: int = 256,
                             weight: Optional[str] = None,
                             region=None) -> np.ndarray:
        """The speculative degraded density (see :meth:`density`): a
        coarse estimate grid assembled from RESIDENT cache/hierarchy
        count cells — host reads only, zero device work (exactly what
        shedding protects). Resident cells splat their exact counts
        uniformly over their footprint; unresident coverage splats the
        planner-estimate remainder; a non-decomposable query splats the
        whole estimate. Typed + audited like speculative counts.
        Weighted grids never serve speculatively — the resident cells
        hold row COUNTS, and a count splatted into a weight-sum grid
        would be a silent unit change — so a weighted shed stays
        ``[GM-SHED]``."""
        if weight is not None:
            from geomesa_tpu.resilience import DeadlineShedError

            raise DeadlineShedError(
                "[GM-SHED] weighted density has no speculative form "
                "(resident cells hold counts, not weight sums)"
            )
        st, q, plan = self._plan(name, self._with_region(name, query, region))
        if bbox is None:
            bbox = self.bounds(name) or (-180, -90, 180, 90)
        bbox = tuple(float(v) for v in bbox)
        grid = np.zeros((height, width), np.float32)
        est = float(plan.est_count)
        got = self.cache.speculative_cells(self, st, q, plan)

        def splat(box, value):
            # uniform splat of `value` over box ∩ render bbox, in pixels
            x0, y0, x1, y1 = box
            sx = width / max(bbox[2] - bbox[0], 1e-12)
            sy = height / max(bbox[3] - bbox[1], 1e-12)
            c0 = int(np.clip(np.floor((x0 - bbox[0]) * sx), 0, width))
            c1 = int(np.clip(np.ceil((x1 - bbox[0]) * sx), 0, width))
            r0 = int(np.clip(np.floor((y0 - bbox[1]) * sy), 0, height))
            r1 = int(np.clip(np.ceil((y1 - bbox[1]) * sy), 0, height))
            if c1 > c0 and r1 > r0 and value > 0:
                grid[r0:r1, c0:c1] += np.float32(
                    value / ((r1 - r0) * (c1 - c0))
                )

        resident_cells = 0
        if got is not None:
            decomp, resident, missing = got
            from geomesa_tpu.cache.cells import cell_box

            resident_cells = len(resident)
            served = 0
            for cell, n in resident:
                splat(cell_box(decomp.level, *cell), float(n))
                served += n
            remainder = max(est - served, 0.0)
            uncovered = len(missing) + decomp.residual_count()
            if uncovered and remainder > 0:
                for cell in missing:
                    splat(cell_box(decomp.level, *cell),
                          remainder / uncovered)
        else:
            splat(bbox, est)
        self._speculative_audit(
            name, plan, "density", int(np.count_nonzero(grid)),
            {"resident_cells": resident_cells},
        )
        return grid

    @_traced("density_curve")
    def density_curve(self, name: str, query: "str | Query" = "INCLUDE",
                      level: int = 9, bbox=None,
                      weight: Optional[str] = None, region=None):
        """Exact density over the morton-block grid at ``level`` (a global
        2^level x 2^level partition of lon/lat — the EPSG:4326 tile pyramid
        aligns with it by construction). Returns ``(grid, snapped_bbox)``
        where the grid covers the blocks intersecting ``bbox`` (default:
        the store's bounds), row 0 at the south edge.

        This is the index-native heatmap: per-block counts are CDF
        differences over the z2-sorted scan — no scatter — so it runs at
        memory bandwidth where the per-pixel scatter path pays ~6.7 ns per
        scanned row (docs/SCALE.md). Use it for tile rendering; use
        :meth:`density` when the grid must align to an arbitrary bbox.

        ``region``: optional polygon (WKT or geometry) folded in as an
        INTERSECTS conjunct; the cache's block-chunk loop classifies each
        chunk against it — interior chunks share residual-keyed entries
        with non-region pyramids and outside chunks never scan
        (docs/CACHE.md "Polygon curve chunks")."""
        if not 0 < level <= 15:
            raise ValueError("level must be in 1..15 (grid = 4^level blocks)")
        query = self._with_region(name, query, region)
        q = Query(ecql=query) if isinstance(query, str) else query
        import dataclasses

        q = dataclasses.replace(q, index="z2")
        st, q, plan = self._plan(name, q)
        if bbox is None:
            bbox = self.bounds(name) or (-180.0, -90.0, 180.0, 90.0)
        window, snapped = self._snap_blocks(bbox, level)
        t0 = time.perf_counter()
        with metrics.registry().timer("query.density").time(), \
                query_deadline(self._timeout_s()):
            grid = self.cache.density_curve(
                self, st, q, plan, level, window, weight
            )
        self._audit(name, q, plan, t0, int(np.count_nonzero(grid)),
                    op="density_curve")
        return grid, snapped

    @staticmethod
    def _snap_blocks(bbox, level: int):
        """Snap a bbox outward to the level-``level`` morton block grid:
        ``((ix0, iy0, ix1, iy1), snapped_bbox)``. Inclusive outward snap:
        floor on BOTH edges — a bbox edge exactly on a block boundary
        includes the block CONTAINING it, matching the inclusive
        x <= xmax semantics of the equivalent BBOX filter."""
        n_blocks = 1 << level
        fx = lambda v: (v + 180.0) / 360.0 * n_blocks  # noqa: E731
        fy = lambda v: (v + 90.0) / 180.0 * n_blocks  # noqa: E731
        ix0 = int(np.clip(np.floor(fx(bbox[0])), 0, n_blocks - 1))
        ix1 = int(np.clip(np.floor(fx(bbox[2])), ix0, n_blocks - 1))
        iy0 = int(np.clip(np.floor(fy(bbox[1])), 0, n_blocks - 1))
        iy1 = int(np.clip(np.floor(fy(bbox[3])), iy0, n_blocks - 1))
        snapped = (
            ix0 * 360.0 / n_blocks - 180.0,
            iy0 * 180.0 / n_blocks - 90.0,
            (ix1 + 1) * 360.0 / n_blocks - 180.0,
            (iy1 + 1) * 180.0 / n_blocks - 90.0,
        )
        return (ix0, iy0, ix1, iy1), snapped

    def density_curve_batch(self, name: str, query: "str | Query" = "INCLUDE",
                            level: int = 9, bboxes=(), weight: Optional[str] = None,
                            members: Optional[List[Dict[str, Any]]] = None):
        """N curve-aligned density crops of ONE layer + filter in a single
        device pass (docs/SERVING.md): the cross-query fusion entry the
        serving scheduler uses when concurrent clients ask for different
        tiles of the same heatmap. Plans once, stacks the per-crop CDF
        gather positions over the query axis, and de-interleaves
        bit-identically versus calling :meth:`density_curve` per bbox.

        Returns ``[(grid, snapped_bbox), ...]`` in ``bboxes`` order (a
        ``None`` bbox uses the store bounds). ``members`` (optional, same
        length): per-member metadata dicts — ``trace_id``/``user`` land in
        that member's audit event so fused queries stay individually
        attributable. Bypasses the aggregate cache (each member is a
        fresh crop; repeats are served by fusion itself)."""
        if not 0 < level <= 15:
            raise ValueError("level must be in 1..15 (grid = 4^level blocks)")
        q = Query(ecql=query) if isinstance(query, str) else query
        import dataclasses

        q = dataclasses.replace(q, index="z2")
        bboxes = list(bboxes)
        if members is not None and len(members) != len(bboxes):
            raise ValueError("members must align with bboxes")
        with tracing.start("density_curve_batch", schema=name,
                           batch=len(bboxes)), \
                self.serving.admit("density_curve"):
            st, q, plan = self._plan(name, q)
            default_bbox = None
            windows, snaps = [], []
            for bb in bboxes:
                if bb is None:
                    if default_bbox is None:
                        default_bbox = (
                            self.bounds(name)
                            or (-180.0, -90.0, 180.0, 90.0)
                        )
                    bb = default_bbox
                w, s = self._snap_blocks(bb, level)
                windows.append(w)
                snaps.append(s)
            t0 = time.perf_counter()
            with metrics.registry().timer("query.density").time(), \
                    query_deadline(self._timeout_s()):
                ex = self._executor(st)
                if hasattr(ex, "density_curve_batch"):
                    grids = ex.density_curve_batch(plan, level, windows,
                                                   weight)
                else:  # executor without the fused entry: per-crop serial
                    grids = [
                        ex.density_curve(plan, level, w, weight)
                        for w in windows
                    ]
            # one audit event PER MEMBER via the shared fused-batch audit
            # helper (fused queries stay individually attributable; the
            # shared scan cost + extras ride member 0 so sums over events
            # never double-count). All members share ONE plan here.
            self._batch_audit(
                name, "density_curve", [plan],
                [int(np.count_nonzero(g)) for g in grids], t0, members,
                extra_hints={"level": level}, distinct=False,
            )
            return list(zip(grids, snaps))

    def density_curve_filter_batch(self, name: str, queries, level: int = 9,
                                   bboxes=None, weight: Optional[str] = None,
                                   members: Optional[List[Dict[str, Any]]] = None):
        """M curve-aligned density crops with DISTINCT filters — each
        member its own viewport literals AND its own crop window — in one
        device dispatch, or None when the members do not share a
        batchable structural template (docs/SERVING.md "Query-axis
        batching", extended to the curve path). Returns
        ``[(grid, snapped_bbox), ...]`` in member order, each grid
        bit-identical to its serial :meth:`density_curve`."""
        if not 0 < level <= 15:
            raise ValueError("level must be in 1..15 (grid = 4^level blocks)")
        if not queries:
            return []
        if members is not None and len(members) != len(queries):
            raise ValueError("members must align with queries")
        bboxes = list(bboxes) if bboxes is not None \
            else [None] * len(queries)
        if len(bboxes) != len(queries):
            raise ValueError("bboxes must align with queries")
        import dataclasses

        qs = [
            dataclasses.replace(
                Query(ecql=q) if isinstance(q, str) else q, index="z2"
            )
            for q in queries
        ]
        with tracing.start("density_curve_filter_batch", schema=name,
                           batch=len(qs)), \
                self.serving.admit("density_curve"):
            st, plans, spec = self._batch_plans(name, qs)
            if spec is None:
                return None
            ex = self._executor(st)
            if not hasattr(ex, "density_curve_filter_batch"):
                return None
            default_bbox = None
            windows, snaps = [], []
            for bb in bboxes:
                if bb is None:
                    if default_bbox is None:
                        default_bbox = (
                            self.bounds(name)
                            or (-180.0, -90.0, 180.0, 90.0)
                        )
                    bb = default_bbox
                w, s = self._snap_blocks(bb, level)
                windows.append(w)
                snaps.append(s)
            t0 = time.perf_counter()
            with metrics.registry().timer("query.density").time(), \
                    query_deadline(self._timeout_s()):
                grids = ex.density_curve_filter_batch(
                    plans, spec, level, windows, weight
                )
            if grids is None:
                return None
            metrics.inc(metrics.SERVING_FUSED_DISTINCT, len(grids))
            self._batch_audit(
                name, "density_curve", plans,
                [int(np.count_nonzero(g)) for g in grids], t0, members,
                extra_hints={"level": level},
            )
            return list(zip(grids, snaps))

    # -- query-axis batched aggregates (docs/SERVING.md "Query-axis
    # batching"): M *distinct* viewports of one structural query shape in
    # a single device dispatch. These are the fusion layer's distinct-
    # literal batch executors (serving/fuse.py) and are also directly
    # callable. Every method returns None when the batch cannot ride the
    # megakernel — the caller degrades to query-at-a-time execution, so
    # batching can change latency, never results. Bypasses the aggregate
    # cache (each member is a fresh viewport; repeats are served by
    # repeat fusion / the cache on the serial path).
    def _batch_plans(self, name: str, queries):
        """Plan every member; returns ``(st, plans, spec)`` with spec None
        when the members do not share a batchable structural template."""
        from geomesa_tpu.planning import batch as batchmod

        qs = [Query(ecql=q) if isinstance(q, str) else q for q in queries]
        auths = self._effective_auths(qs[0])
        akey = None if auths is None else tuple(auths)
        st = plans = None
        triples = []
        for q in qs:
            if (None if self._effective_auths(q) is None
                    else tuple(self._effective_auths(q))) != akey:
                return None, None, None  # mixed auths never batch
            triples.append(self._plan(name, q))
        st = triples[0][0]
        plans = [t[2] for t in triples]
        # members near an index cost boundary can split their choice
        # (say z2 vs z3 for one bbox+time template): the batch needs ONE
        # table, and any candidate index returns identical results, so
        # re-plan the minority onto the majority's index
        names = {p.index_name for p in plans}
        if len(names) > 1:
            import dataclasses
            from collections import Counter

            maj = Counter(
                p.index_name for p in plans
            ).most_common(1)[0][0]
            for i, (q, p) in enumerate(zip(qs, plans)):
                if p.index_name != maj:
                    try:
                        _, _, p2 = self._plan(
                            name, dataclasses.replace(q, index=maj)
                        )
                        plans[i] = p2
                    except Exception:
                        return st, plans, None  # index can't serve it
        spec = batchmod.build_spec(self, st, plans, auths)
        return st, plans, spec

    def _batch_audit(self, name: str, op: str, plans, hits, t0: float,
                     members, extra_hints=None,
                     distinct: bool = True) -> None:
        """One audit event PER MEMBER of a fused batch: fused queries
        stay individually attributable; the shared scan cost and
        execution-path extras ride member 0 so sums over events never
        double-count. ``plans`` is per-member, or length-1 when every
        member shares one plan (the density_curve tile batch);
        ``distinct`` marks query-axis (distinct-literal) batches."""
        scan_ms = (time.perf_counter() - t0) * 1e3
        extras = self._plan_audit_extras(plans[0])
        shared_plan = len(plans) != len(hits)
        for i in range(len(hits)):
            plan = plans[0] if shared_plan else plans[i]
            hints: Dict[str, Any] = {
                "op": op, "index": plan.index_name, "fused": True,
                "fused_batch": len(hits), "fused_member": i,
            }
            if distinct:
                hints["distinct"] = True
            if extra_hints:
                hints.update(extra_hints)
            m = members[i] if members is not None else {}
            tid = m.get("trace_id") or tracing.current_trace_id()
            if tid is not None:
                hints["trace_id"] = tid
            if m.get("user"):
                hints["user"] = m["user"]
            if i == 0:
                hints.update(extras)
            self.audit.record(
                name, plan.ecql, hints,
                plan.__dict__.get("plan_time_ms", 0.0) if i == 0 else 0.0,
                scan_ms if i == 0 else 0.0,
                int(hits[i]),
                user=m.get("user") or (self.serving.current_user() or ""),
                scanned=plan.__dict__.get("scanned_rows", 0)
                if i == 0 else 0,
                table_rows=plan.__dict__.get("table_rows", 0),
            )

    def count_batch(self, name: str, queries, exact: bool = True,
                    members: Optional[List[Dict[str, Any]]] = None):
        """M distinct exact counts in one device dispatch, or None when
        the members do not share a structural template (the caller runs
        them query-at-a-time). Each member's value equals its serial
        :meth:`count` exactly — the CI-gated contract."""
        if not queries:
            return []
        if not exact:
            return None  # estimates never scan; nothing to batch
        if members is not None and len(members) != len(queries):
            raise ValueError("members must align with queries")
        with tracing.start("count_batch", schema=name,
                           batch=len(queries)), \
                self.serving.admit("count"):
            st, plans, spec = self._batch_plans(name, queries)
            if spec is None:
                return None
            ex = self._executor(st)
            if not hasattr(ex, "count_batch"):
                return None
            t0 = time.perf_counter()
            with query_deadline(self._timeout_s()):
                res = ex.count_batch(plans, spec)
            if res is None:
                return None
            metrics.inc(metrics.SERVING_FUSED_DISTINCT, len(res))
            self._batch_audit(name, "count", plans, res, t0, members)
            return res

    def density_batch(self, name: str, queries, bboxes=None,
                      width: int = 256, height: int = 256,
                      weight: Optional[str] = None,
                      members: Optional[List[Dict[str, Any]]] = None):
        """M distinct heatmaps — each over its OWN query + grid bbox — in
        one device dispatch, or None when ineligible. ``bboxes`` aligns
        with ``queries`` (None entries use the store bounds, exactly like
        :meth:`density`)."""
        if not queries:
            return []
        if members is not None and len(members) != len(queries):
            raise ValueError("members must align with queries")
        bboxes = list(bboxes) if bboxes is not None \
            else [None] * len(queries)
        if len(bboxes) != len(queries):
            raise ValueError("bboxes must align with queries")
        with tracing.start("density_batch", schema=name,
                           batch=len(queries)), \
                self.serving.admit("density"):
            st, plans, spec = self._batch_plans(name, queries)
            if spec is None:
                return None
            ex = self._executor(st)
            if not hasattr(ex, "density_batch"):
                return None
            default_bbox = None
            boxes = []
            for bb in bboxes:
                if bb is None:
                    if default_bbox is None:
                        default_bbox = (
                            self.bounds(name) or (-180, -90, 180, 90)
                        )
                    bb = default_bbox
                boxes.append(tuple(bb))
            t0 = time.perf_counter()
            with metrics.registry().timer("query.density").time(), \
                    query_deadline(self._timeout_s()):
                grids = ex.density_batch(plans, spec, boxes, width,
                                         height, weight)
            if grids is None:
                return None
            metrics.inc(metrics.SERVING_FUSED_DISTINCT, len(grids))
            self._batch_audit(
                name, "density", plans,
                [int(np.count_nonzero(g)) for g in grids], t0, members,
            )
            return grids

    def stats_batch(self, name: str, stat_spec: str, queries,
                    members: Optional[List[Dict[str, Any]]] = None):
        """M distinct stats scans of one spec in one device dispatch, or
        None when ineligible (descriptive leaves, surviving f32 band
        rows, or a non-batchable template keep query-at-a-time
        execution). The member Stat objects are freshly parsed here and
        discarded on fallback, so a partially-absorbed batch can never
        leak into the serial rerun."""
        if not queries:
            return []
        if members is not None and len(members) != len(queries):
            raise ValueError("members must align with queries")
        with tracing.start("stats_batch", schema=name,
                           batch=len(queries)), \
                self.serving.admit("stats"):
            stats = [parse_stat(stat_spec) for _ in queries]
            st, plans, spec = self._batch_plans(name, queries)
            if spec is None:
                return None
            ex = self._executor(st)
            if not hasattr(ex, "stats_batch"):
                return None
            t0 = time.perf_counter()
            with metrics.registry().timer("query.stats").time(), \
                    query_deadline(self._timeout_s()):
                out = ex.stats_batch(plans, spec, stats)
            if out is None:
                return None
            metrics.inc(metrics.SERVING_FUSED_DISTINCT, len(out))
            self._batch_audit(name, "stats", plans, [0] * len(out), t0,
                              members, extra_hints={"stat": stat_spec})
            return out

    @_traced("stats", speculative="_speculative_stats")
    def stats(self, name: str, stat_spec: str,
              query: "str | Query" = "INCLUDE", region=None) -> sk.Stat:
        """Exact stats over matching features (StatsProcess/StatsScan
        analog). ``region``: optional polygon (WKT or geometry) — see
        :meth:`density`. ``speculative_ok=True`` (kw): under overload, a
        shed stats call returns the coarse write-time-sketch-served
        estimate — typed ``speculative: true`` in the audit —
        instead of failing ``[GM-SHED]`` (docs/SERVING.md)."""
        st, q, plan = self._plan(name, self._with_region(name, query, region))
        parse_stat(stat_spec)  # validate the spec before any timing/scan
        t0 = time.perf_counter()
        with metrics.registry().timer("query.stats").time(), \
                query_deadline(self._timeout_s()):
            out = self.cache.stats(self, st, q, plan, stat_spec)
        self._audit(name, q, plan, t0, 0, op="stats")
        return out

    def _speculative_stats(self, name: str, stat_spec: str,
                           query: "str | Query" = "INCLUDE",
                           region=None) -> sk.Stat:
        """The speculative degraded stats (see :meth:`stats`): served
        from the PERSISTED write-time sketches — host reads, zero device
        work. Leaves with a matching persisted sketch (MinMax of an
        indexed attribute or the dtg field; Count — exact unfiltered,
        planner-estimated otherwise) return its value; other leaves
        return empty. The result shape
        always matches the spec, so typed consumers need no special
        casing — only the audit marker distinguishes it."""
        st, q, plan = self._plan(name, self._with_region(name, query, region))
        stat = parse_stat(stat_spec)
        leaves = stat.stats if isinstance(stat, sk.SeqStat) else [stat]
        served = 0
        for leaf in leaves:
            if isinstance(leaf, sk.CountStat):
                # unfiltered count is exact from the store; a filtered
                # one degrades to the planner estimate
                f = plan.filter
                leaf.count = int(
                    st.count if isinstance(f, ir.Include)
                    else plan.est_count
                )
                served += 1
            elif isinstance(leaf, sk.MinMax):
                mm = st.stats.get(f"minmax-{leaf.attribute}")
                if mm is None and leaf.attribute == st.ft.dtg_field:
                    mm = st.stats.get("time-bounds")
                if isinstance(mm, sk.MinMax) and not mm.is_empty:
                    leaf.merge(mm)
                    served += 1
        self._speculative_audit(name, plan, "stats", 0,
                                {"stat": stat_spec,
                                 "served_leaves": served})
        return stat

    def unique(self, name: str, attribute: str,
               query: "str | Query" = "INCLUDE") -> List:
        """Distinct values (UniqueProcess analog)."""
        st = self._store(name)
        stat = self.stats(name, f"Enumeration({attribute})", query)
        vals = list(stat.value().keys())
        return sorted(vals, key=lambda v: (v is None, v))

    def min_max(self, name: str, attribute: str,
                query: "str | Query" = "INCLUDE", exact: bool = True):
        """MinMaxProcess / GeoMesaStats.getMinMax analog. ``exact=False``
        reads the persisted write-time sketch (no scan)."""
        if not exact:
            st = self._store(name)
            st.flush()
            mm = st.stats.get(f"minmax-{attribute}")
            if isinstance(mm, sk.MinMax) and not mm.is_empty:
                return mm.value()
            # no persisted sketch for this attribute: fall through to exact
        return self.stats(name, f"MinMax({attribute})", query).value()

    # -- stats sketch surface (GeoMesaStats.scala:39-230 parity) -----------
    def histogram(self, name: str, attribute: str, bins: int = 20,
                  bounds: Optional[Tuple[float, float]] = None,
                  query: "str | Query" = "INCLUDE") -> sk.Histogram:
        """Binned histogram (getHistogram). ``bounds`` defaults to the
        attribute's (exact or persisted) min/max."""
        if bounds is None:
            # persisted write-time sketch when available (no extra scan)
            mm = self.min_max(name, attribute, query, exact=False)
            if not mm or mm.get("min") is None:
                raise ValueError(f"no data to bound histogram on {attribute!r}")
            bounds = (float(mm["min"]), float(mm["max"]))
        lo, hi = bounds
        if hi <= lo:
            hi = lo + 1.0
        return self.stats(
            name, f"Histogram({attribute},{bins},{lo},{hi})", query
        )

    def frequency(self, name: str, attribute: str, width: int = 256,
                  query: "str | Query" = "INCLUDE") -> sk.Frequency:
        """Count-min frequency sketch (getFrequency)."""
        return self.stats(name, f"Frequency({attribute},{width})", query)

    def top_k(self, name: str, attribute: str, k: int = 10,
              query: "str | Query" = "INCLUDE") -> List:
        """Top-k values with counts (getTopK)."""
        stat = self.stats(name, f"TopK({attribute},{k})", query)
        return stat.value()

    def z3_histogram(self, name: str) -> Optional[sk.Z3HistogramStat]:
        """The persisted spatio-temporal histogram driving the cost model
        (getZ3Histogram; write-time, no scan)."""
        st = self._store(name)
        st.flush()
        z = st.stats.get("z3-histogram")
        return z if isinstance(z, sk.Z3HistogramStat) and not z.is_empty else None

    @_traced("knn")
    def knn(self, name: str, x: float, y: float, k: int = 10,
            query: "str | Query" = "INCLUDE") -> FeatureCollection:
        """K nearest neighbors via iterative expanding-radius search
        (KNearestNeighborSearchProcess.scala parity): start from a radius
        sized by the store's average point density, constrain the plan with
        that bbox so the z-index windows prune the scan, and double until
        the k-th candidate's exact distance fits inside the searched bbox's
        inscribed circle — an INCLUDE kNN no longer scans the whole table."""
        import math

        from geomesa_tpu.utils.geometry import EARTH_RADIUS_M, haversine_m

        st = self._store(name)
        st.flush()
        q = Query(ecql=query) if isinstance(query, str) else query
        ex = self._executor(st)
        empty = FeatureCollection(st.ft, ColumnBatch({}, 0), st.dicts)
        if st.count == 0 or k <= 0:
            return empty
        geom = st.ft.geom_field
        base = parse_ecql(q.ecql)
        bounds = self.bounds(name) or (-180.0, -90.0, 180.0, 90.0)
        area = max((bounds[2] - bounds[0]) * (bounds[3] - bounds[1]), 1e-9)
        full_span = max(bounds[2] - bounds[0], bounds[3] - bounds[1], 1e-6)
        # initial radius: expect ~4k points of average density inside
        r = max(
            math.sqrt(4.0 * k * area / (math.pi * max(st.count, 1))), 1e-4
        )
        deg_m = math.pi / 180.0 * EARTH_RADIUS_M
        planner = QueryPlanner(st)
        auths = self._effective_auths(q)
        from geomesa_tpu.filter.compile import compile_filter

        base_compiled = compile_filter(base, st.ft, st.dicts)
        batch, order = None, None
        prev_n = -1
        for attempt in range(16):
            # the lon half-width uses the band-EDGE cosine (smallest in the
            # band) so every point within r*deg_m meters falls inside the
            # box; pole-adjacent or extreme-latitude searches skip the
            # restriction (the inscribed-circle argument breaks there), and
            # the last attempt is always unrestricted — the search can
            # never silently return a truncated result
            pole = (y + r >= 89.99) or (y - r <= -89.99)
            cos_edge = math.cos(math.radians(min(abs(y) + r, 89.99)))
            restricted = (
                r < full_span and not pole and cos_edge >= 0.05
                and attempt < 15
            )
            if restricted:
                half_lon = r / cos_edge
                lat_lo, lat_hi = max(y - r, -90.0), min(y + r, 90.0)
                lon_lo, lon_hi = x - half_lon, x + half_lon
                if lon_hi - lon_lo >= 360.0:
                    boxes = [(-180.0, lat_lo, 180.0, lat_hi)]
                elif lon_lo < -180.0:  # antimeridian wrap (west)
                    boxes = [(-180.0, lat_lo, lon_hi, lat_hi),
                             (lon_lo + 360.0, lat_lo, 180.0, lat_hi)]
                elif lon_hi > 180.0:  # antimeridian wrap (east)
                    boxes = [(lon_lo, lat_lo, 180.0, lat_hi),
                             (-180.0, lat_lo, lon_hi - 360.0, lat_hi)]
                else:
                    boxes = [(lon_lo, lat_lo, lon_hi, lat_hi)]
                bb = tuple(ir.BBox(geom, *b) for b in boxes)
                f = ir.And((base, bb[0] if len(bb) == 1 else ir.Or(bb)))
            else:
                boxes = None
                f = base
            plan = planner.plan(f, q.hints())
            if restricted:
                # the restriction prunes via the plan's WINDOWS and via
                # traced box scalars inside the kNN aggregation — the
                # compiled predicate stays location-free, so one jitted
                # kernel serves every location and radius (a baked-in box
                # with a location-blind cache token returned stale-box
                # results — r4 review)
                plan.compiled = base_compiled
            plan.__dict__["cache_token"] = (
                "knn", q.ecql, None if auths is None else tuple(auths),
            )
            plan.__dict__["window_token"] = (
                plan.__dict__["cache_token"],
                round(x, 9), round(y, 9), restricted and round(r, 9),
            )
            self._apply_visibility(st, plan, auths)
            if hasattr(ex, "knn_features"):  # partitioned: per-partition top-k
                batch = ex.knn_features(plan, x, y, k, boxes=boxes)
            else:
                idx, _ = ex.knn(plan, x, y, k, boxes=boxes)
                table = st.tables[plan.index_name]
                batch = table.host_gather_positions(np.sort(idx))
            order = np.zeros(0, np.int64)
            kth_m = math.inf
            if batch.n:
                d = haversine_m(
                    batch.columns[geom + "__x"], batch.columns[geom + "__y"],
                    x, y,
                )
                order = np.argsort(d)[:k]
                kth_m = float(d[order[-1]])
            if not restricted:
                break
            # exact iff the k-th neighbor lies inside the searched bbox's
            # inscribed circle (domain-clamped edges hold no points beyond
            # the lon/lat domain, so clamping never loses candidates)
            if len(order) >= k and kth_m <= r * deg_m:
                break
            if batch.n == prev_n and batch.n < k:
                # a doubling added no candidates and we're still short of
                # k: the base filter is the limiting factor, not the box —
                # jump straight to the unrestricted pass
                r = full_span
            else:
                r *= 2.0
            prev_n = batch.n
        batch = ColumnBatch(
            {kk: v[order] for kk, v in batch.columns.items()}, len(order)
        )
        return FeatureCollection(st.ft, batch, st.dicts)

    def proximity(self, name: str, wkt_or_geom, distance_m: float,
                  query: "str | Query" = "INCLUDE") -> FeatureCollection:
        """ProximitySearchProcess analog: features within distance of a geometry."""
        from geomesa_tpu.utils import geometry as geo

        g = (
            geo.parse_wkt(wkt_or_geom) if isinstance(wkt_or_geom, str) else wkt_or_geom
        )
        st = self._store(name)
        base = query.ecql if isinstance(query, Query) else query
        f = ir.And((
            parse_ecql(base),
            ir.DWithin(st.ft.geom_field, g, distance_m),
        ))
        planner = QueryPlanner(st)
        st.flush()
        q = query if isinstance(query, Query) else Query()
        plan = planner.plan(f, q.hints())
        self._apply_visibility(st, plan, self._effective_auths(q))
        batch = self._executor(st).features(plan)
        return FeatureCollection(st.ft, batch, st.dicts)

    # -- process library delegates (geomesa-process parity) ----------------
    def tube_select(self, name: str, tube_xy, tube_times_ms, buffer_m: float,
                    query: "str | Query" = "INCLUDE", **kw) -> FeatureCollection:
        from geomesa_tpu import processes

        return processes.tube_select(
            self, name, tube_xy, tube_times_ms, buffer_m, query, **kw
        )

    def spatial_join(self, points: str, polygons,
                     query: "str | Query" = "INCLUDE",
                     weight: Optional[str] = None):
        from geomesa_tpu import processes

        return processes.spatial_join(self, points, polygons, query, weight)

    def join(self, left: str, right: str, left_attr: Optional[str] = None,
             right_attr: Optional[str] = None,
             left_query: "str | Query" = "INCLUDE",
             right_query: "str | Query" = "INCLUDE", *,
             predicate: Optional[str] = None, distance=None,
             dx=None, dy=None, level: Optional[int] = None):
        """Join two schemas. With ``left_attr``/``right_attr``: the
        attribute equi-join (JoinProcess analog, unchanged). With
        ``predicate``: the TPU-native SPATIAL join between two
        point-schema datasets (docs/JOIN.md) — ``"bbox"`` (envelopes of
        half-widths ``dx``/``dy`` intersect), ``"dwithin"`` (planar
        degree ``distance``), or ``"dwithin_meters"`` (haversine
        great-circle ``distance`` meters) — SFC-cell co-partitioned so
        candidate work is O(pairs-in-same-cell), returning a streaming
        :class:`SpatialJoinResult`."""
        if predicate is None:
            if left_attr is None or right_attr is None:
                raise ValueError(
                    "join needs left_attr/right_attr (equi-join) or "
                    "predicate= (spatial join)"
                )
            from geomesa_tpu import processes

            return processes.join(
                self, left, right, left_attr, right_attr,
                left_query, right_query,
            )
        return self.join_spatial(
            left, right, predicate=predicate, distance=distance, dx=dx,
            dy=dy, left_query=left_query, right_query=right_query,
            level=level,
        )

    def _join_sides(self, left: str, right: str,
                    left_query: "str | Query", right_query: "str | Query",
                    right_polygon: bool = False):
        """Plan + scan both join sides (each under its own filter /
        visibility), validating the geometry contract: both sides POINT,
        except polygon-predicate joins (``right_polygon``) where the
        right side must be a POLYGON/MULTIPOLYGON schema."""
        lst, lq, lplan = self._plan(left, left_query)
        rst, rq, rplan = self._plan(right, right_query)
        for st_, nm, poly in ((lst, left, False), (rst, right,
                                                   right_polygon)):
            g = st_.ft.geom_field
            a = None if g is None else st_.ft.attr(g)
            if poly:
                if a is None or a.type not in ("polygon", "multipolygon"):
                    raise ValueError(
                        f"[GM-ARG] polygon join requires a POLYGON "
                        f"geometry on schema {nm!r}"
                    )
            elif a is None or not a.is_point:
                raise ValueError(
                    f"[GM-ARG] spatial join requires a POINT geometry "
                    f"on schema {nm!r}"
                )
        with tracing.span("scan.join.sides"):
            lbatch = self._executor(lst).features(lplan)
            rbatch = self._executor(rst).features(rplan)
        return lst, lplan, lbatch, rst, rplan, rbatch

    @staticmethod
    def _side_xy(st: FeatureStore, batch: ColumnBatch):
        g = st.ft.geom_field
        z = np.zeros(0, np.float64)
        return (batch.columns.get(g + "__x", z),
                batch.columns.get(g + "__y", z))

    @staticmethod
    def _side_polygons(st: FeatureStore, batch: ColumnBatch):
        """The polygon side's geometries, parsed from the schema's host
        WKT column (row order == batch order, so pair indices line up)."""
        from geomesa_tpu.utils import geometry as geo

        g = st.ft.geom_field
        col = batch.columns.get(g + "__wkt")
        if col is None:
            return []
        return [geo.parse_wkt(w) for w in col]

    def _join_run(self, left: str, right: str, predicate: str, distance,
                  dx, dy, left_query, right_query, level,
                  want_pairs: bool):
        """The shared spatial-join body: sides scan -> co-partition ->
        per-cell strategy routing -> kernels over the device mesh ->
        audit. Polygon predicates route through the classify-cells
        wholesale/boundary engine; count-only joins over a partitioned
        right side stream it through window-pushdown side scans
        (docs/JOIN.md §6) instead of materializing it whole."""
        from geomesa_tpu.kernels import join as kjoin
        from geomesa_tpu.planning import join_exec

        t0 = time.perf_counter()
        metrics.inc(metrics.JOIN_QUERIES)
        prefer = self.prefer_device and self.mesh is None
        with query_deadline(self._timeout_s()):
            if predicate in kjoin.POLYGON_PREDICATES:
                lst, lplan, lbatch, rst, rplan, rbatch = self._join_sides(
                    left, right, left_query, right_query,
                    right_polygon=True,
                )
                lx, ly = self._side_xy(lst, lbatch)
                geoms = self._side_polygons(rst, rbatch)
                pairs, total, stats = join_exec.run_polygon_join(
                    lx, ly, geoms, predicate, level=level,
                    prefer_device=prefer, want_pairs=want_pairs,
                )
            elif not want_pairs and self._join_pushdown_ready(
                    right, predicate, right_query):
                (lst, lplan, lbatch, rst,
                 total, stats) = self._join_pushdown_count(
                    left, right, predicate, distance, dx, dy,
                    left_query, right_query, level, prefer,
                )
                rbatch = ColumnBatch({}, 0)
                pairs = None
            else:
                lst, lplan, lbatch, rst, rplan, rbatch = self._join_sides(
                    left, right, left_query, right_query
                )
                lx, ly = self._side_xy(lst, lbatch)
                rx, ry = self._side_xy(rst, rbatch)
                pairs, total, stats = join_exec.run_join(
                    lx, ly, rx, ry, predicate, distance=distance, dx=dx,
                    dy=dy, level=level, prefer_device=prefer,
                    want_pairs=want_pairs,
                )
        hints = {
            "op": "join", "index": lplan.index_name, "right": right,
            "predicate": predicate, "level": stats.level,
            "cells_joint": stats.cells_joint,
            "candidate_pairs": stats.candidate_pairs,
            "naive_pairs": stats.naive_pairs,
            "strip_fraction": round(stats.strip_fraction, 4),
            "adaptive": stats.adaptive,
        }
        if stats.strategy_cells:
            # the decision trail: joint cells per strategy (docs/JOIN.md §5)
            hints["strategies"] = dict(stats.strategy_cells)
        if stats.wholesale_pairs:
            hints["wholesale_pairs"] = stats.wholesale_pairs
        if stats.pushdown:
            hints["pushdown"] = dict(stats.pushdown)
        if stats.skipped:
            hints["degraded"] = list(stats.skipped)
        tid = tracing.current_trace_id()
        if tid is not None:
            hints["trace_id"] = tid
        hints.update(self._plan_audit_extras(lplan))
        self.audit.record(
            left, lplan.ecql, hints,
            lplan.__dict__.get("plan_time_ms", 0.0),
            (time.perf_counter() - t0) * 1e3, total,
            user=self.serving.current_user() or "",
            scanned=lplan.__dict__.get("scanned_rows", 0),
            table_rows=lplan.__dict__.get("table_rows", 0),
        )
        return SpatialJoinResult(
            lst, lbatch, rst, rbatch, pairs, total, stats
        )

    def _join_pushdown_ready(self, right: str, predicate: str,
                             right_query: "str | Query") -> bool:
        """Whether the count-only join can stream the right side through
        lake window-pushdown side scans (docs/JOIN.md §6): planar
        predicate (``dwithin_meters`` needs per-row latitude-dependent
        reach plus antimeridian wrap — its windows are not OR-of-bbox),
        a plain right query (row-set-dependent hints fall back), and a
        partitioned right store that can serve statistics-pruned
        children."""
        from geomesa_tpu.kernels import join as kjoin

        if predicate not in (kjoin.JOIN_BBOX, kjoin.JOIN_DWITHIN):
            return False
        on = config.JOIN_PUSHDOWN.to_bool()
        if not (True if on is None else bool(on)):
            return False
        if isinstance(right_query, Query) and (
                right_query.max_features is not None
                or right_query.sampling is not None
                or right_query.sample_by is not None
                or right_query.sort_by or right_query.properties):
            return False
        try:
            st = self._store(right)
        except KeyError:
            return False
        from geomesa_tpu.index.partitioned import PartitionedFeatureStore

        g = st.ft.geom_field
        return (isinstance(st, PartitionedFeatureStore)
                and g is not None and st.ft.attr(g).is_point)

    def _join_pushdown_count(self, left: str, right: str, predicate: str,
                             distance, dx, dy, left_query, right_query,
                             level, prefer: bool):
        """Count-only join with window-pushdown side scans: the LEFT
        side's occupied cells chunk into groups of
        ``geomesa.join.pushdown.cells``; each chunk re-plans the right
        side under ``(right_query) AND (OR of chunk cell boxes inflated
        by reach + 2 margins)`` and streams it through the partitioned
        executor's lake window (footer-pruned per-cell ranged reads) —
        the right side is never materialized whole on the host.

        Exactly-once accounting: a left row's cell lives in exactly one
        chunk, and any right row whose reach box touches a chunk cell
        lies inside that chunk's inflated window with a full
        CLASSIFY_MARGIN to spare (one margin funds the strip contract,
        the second funds the scan filter kernel's f32 edge uncertainty,
        and the window bounds round OUTWARD to fixed-point ECQL), so
        chunk counts partition the pair set."""
        from dataclasses import replace as _dc_replace

        from geomesa_tpu.cache import cells as gcells
        from geomesa_tpu.cache.cells import CLASSIFY_MARGIN
        from geomesa_tpu.kernels import join as kjoin
        from geomesa_tpu.planning import join_exec

        lst, lq, lplan = self._plan(left, left_query)
        g = lst.ft.geom_field
        if g is None or not lst.ft.attr(g).is_point:
            raise ValueError(
                f"[GM-ARG] spatial join requires a POINT geometry "
                f"on schema {left!r}"
            )
        rst = self._store(right)
        rgeom = rst.ft.geom_field
        with tracing.span("scan.join.sides"):
            lbatch = self._executor(lst).features(lplan)
        lx, ly = self._side_xy(lst, lbatch)
        lx = np.asarray(lx, np.float64)
        ly = np.asarray(ly, np.float64)
        p0, p1 = kjoin.pair_params(predicate, distance=distance, dx=dx,
                                   dy=dy)
        if predicate == kjoin.JOIN_BBOX:
            reach_x, reach_y = float(p0), float(p1)
        else:
            reach_x = reach_y = float(distance)
        if level is None:
            # level votes from the LEFT side only — the right side is
            # never whole on the host, so its density cannot vote
            bounds = None
            if len(lx):
                bounds = (float(lx.min()), float(ly.min()),
                          float(lx.max()), float(ly.max()))
            level = join_exec.choose_level(
                len(lx), len(lx), max(reach_x, reach_y), bounds
            )
        stats = join_exec.JoinStats(level=level, n_left=len(lx))
        if not len(lx):
            return lst, lplan, lbatch, rst, 0, stats
        # the WINDOW grid is finer than the join grid: the join level
        # optimizes pairwise tile occupancy (cells can span many
        # degrees), but pruning power needs boxes comparable to a row
        # group's footprint — size window cells to the reach (the pad is
        # then a fraction of the cell, not a multiple). Exactness never
        # depends on this choice: each chunk's inflated windows are a
        # provable superset of its left rows' matches at ANY level.
        wlevel = int(np.clip(int(np.floor(np.log2(
            360.0 / max(2.0 * (max(reach_x, reach_y) + CLASSIFY_MARGIN),
                        1e-9)))), level, 15))
        ix, iy = gcells.point_cells(lx, ly, wlevel)
        cell = join_exec._cell_ids(ix, iy)
        order = np.argsort(cell, kind="stable")
        ucell, starts = np.unique(cell[order], return_index=True)
        ends = np.concatenate([starts[1:], [len(order)]])
        uix = ix[order][starts]
        uiy = iy[order][starts]
        stats.cells_left = len(ucell)
        per = config.JOIN_PUSHDOWN_CELLS.to_int() or 256
        per = max(int(per), 1)
        base = right_query.ecql if isinstance(right_query, Query) \
            else right_query
        rq_base = right_query if isinstance(right_query, Query) \
            else Query(ecql=right_query)
        pad_x = reach_x + 2.0 * CLASSIFY_MARGIN
        pad_y = reach_y + 2.0 * CLASSIFY_MARGIN

        def _lo(v):
            return f"{np.floor(v * 1e9) / 1e9:.9f}"

        def _hi(v):
            return f"{np.ceil(v * 1e9) / 1e9:.9f}"

        total = 0
        bytes_loaded = groups_loaded = 0
        bytes_side = groups_side = 0
        chunks = 0
        # one residency cache spans the whole chunk loop: adjacent chunks'
        # reach-inflated windows overlap, so boundary row groups surviving
        # pruning in both chunks decode once (docs/JOIN.md §11)
        from geomesa_tpu.lake.residency import GroupResidencyCache

        residency = GroupResidencyCache.from_config()
        for clo in range(0, len(ucell), per):
            chi = min(clo + per, len(ucell))
            chunks += 1
            boxes = gcells.cell_boxes(wlevel, uix[clo:chi], uiy[clo:chi])
            clause = " OR ".join(
                f"BBOX({rgeom}, {_lo(b[0] - pad_x)}, {_lo(b[1] - pad_y)},"
                f" {_hi(b[2] + pad_x)}, {_hi(b[3] + pad_y)})"
                for b in boxes
            )
            ecql = clause if base.strip().upper() == "INCLUDE" \
                else f"({base}) AND ({clause})"
            rst2, _rq2, rplan2 = self._plan(
                right, _dc_replace(rq_base, ecql=ecql)
            )
            if residency is not None:
                rplan2.__dict__["residency"] = residency
            ex = self._executor(rst2)
            scan = getattr(ex, "features_pushdown", None) or ex.features
            with tracing.span("scan.join.side.window", chunk=chunks):
                rb = scan(rplan2)
            rx, ry = self._side_xy(rst2, rb)
            stats.n_right += len(rx)
            sel = order[starts[clo]: ends[chi - 1]]
            plan = join_exec.co_partition(
                lx[sel], ly[sel], rx, ry, predicate, reach_x, reach_y,
                level=level, p0=p0, p1=p1,
            )
            _, cnt = join_exec.execute_predicate(
                plan, lx[sel], ly[sel], rx, ry, predicate,
                prefer_device=prefer, want_pairs=False,
            )
            total += cnt
            cst = plan.stats
            stats.cells_joint += cst.cells_joint
            stats.candidate_pairs += cst.candidate_pairs
            stats.strip_entries += cst.strip_entries
            stats.tiles += cst.tiles
            stats.devices = max(stats.devices, cst.devices)
            stats.adaptive = cst.adaptive
            for k, v in cst.strategy_cells.items():
                stats.strategy_cells[k] = stats.strategy_cells.get(k, 0) + v
            for k, v in cst.est_pairs.items():
                stats.est_pairs[k] = stats.est_pairs.get(k, 0) + v
            for k, v in cst.dispatched_pairs.items():
                stats.dispatched_pairs[k] = \
                    stats.dispatched_pairs.get(k, 0) + v
            stats.skipped.extend(
                f"chunk{chunks - 1}:{s}" for s in cst.skipped
            )
            acct = rplan2.__dict__.get("lake_acct") or {}
            bytes_loaded += int(acct.get("bytes_loaded", 0))
            groups_loaded += int(acct.get("groups_loaded", 0))
            # one chunk's payload/groups_total IS the whole side (every
            # chunk scan sees every row group's footer): the honest
            # full-materialization baseline for the fraction
            bytes_side = max(bytes_side, int(acct.get("bytes_payload", 0)))
            groups_side = max(groups_side, int(acct.get("groups_total", 0)))
        stats.matched = total
        res_hits = residency.hits if residency is not None else 0
        res_saved = residency.bytes_saved if residency is not None else 0
        stats.pushdown = {
            "chunks": chunks, "cells": len(ucell),
            "bytes_loaded": bytes_loaded, "bytes_side": bytes_side,
            "groups_loaded": groups_loaded, "groups_side": groups_side,
            "residency_hits": res_hits,
            "bytes_saved_residency": res_saved,
        }
        metrics.inc(metrics.JOIN_PUSHDOWN_RESIDENCY_HITS, res_hits)
        metrics.inc(metrics.JOIN_PUSHDOWN_RESIDENCY_BYTES, res_saved)
        metrics.inc(metrics.JOIN_CELLS, stats.cells_joint)
        metrics.inc(metrics.JOIN_CANDIDATE_PAIRS, stats.candidate_pairs)
        for s, k in stats.strategy_cells.items():
            metrics.inc(metrics.JOIN_CELLS_STRATEGY + s, k)
        metrics.inc(metrics.JOIN_PAIRS, total)
        metrics.inc(metrics.JOIN_PUSHDOWN_BYTES, bytes_loaded)
        tracing.add_cost("join_pushdown_bytes", float(bytes_loaded))
        tracing.add_cost("join_cells", float(stats.cells_joint))
        tracing.add_cost("join_candidate_pairs",
                         float(stats.candidate_pairs))
        return lst, lplan, lbatch, rst, total, stats

    @_traced("join")
    def join_spatial(self, left: str, right: str, *, predicate: str,
                     distance=None, dx=None, dy=None,
                     left_query: "str | Query" = "INCLUDE",
                     right_query: "str | Query" = "INCLUDE",
                     level: Optional[int] = None) -> "SpatialJoinResult":
        """Spatial join of two point schemas (docs/JOIN.md): matched
        pairs stream as ColumnBatches (``SpatialJoinResult.batches()``,
        right columns prefixed ``right.``). Runs through serving
        admission / deadlines like every public op; under
        ``resilience.allow_partial()`` per-tile-slice failures degrade
        with exact survivor totals (``result.stats.skipped``)."""
        return self._join_run(left, right, predicate, distance, dx, dy,
                              left_query, right_query, level,
                              want_pairs=True)

    @_traced("join")
    def join_count(self, left: str, right: str, *, predicate: str,
                   distance=None, dx=None, dy=None,
                   left_query: "str | Query" = "INCLUDE",
                   right_query: "str | Query" = "INCLUDE",
                   level: Optional[int] = None) -> int:
        """The join's aggregate form: exact matched-pair count without
        materializing pairs (the [C, B, P] verdict mask never leaves the
        device — only per-tile counts transfer). Slots into the serving
        batch/fusion path as a repeat-fusable op (docs/SERVING.md)."""
        res = self._join_run(left, right, predicate, distance, dx, dy,
                             left_query, right_query, level,
                             want_pairs=False)
        return res.count

    def explain_join(self, left: str, right: str, *, predicate: str,
                     distance=None, dx=None, dy=None,
                     left_query: "str | Query" = "INCLUDE",
                     right_query: "str | Query" = "INCLUDE",
                     level: Optional[int] = None,
                     analyze: bool = False) -> str:
        """Join plan explain (docs/JOIN.md): the co-partition's pruning
        account — cells, candidate pairs vs naive N*M, boundary-strip
        fraction — plus (``analyze=True``) the executed match count."""
        from geomesa_tpu.kernels import join as kjoin
        from geomesa_tpu.planning import join_exec

        exp = Explainer(enabled=True)
        with tracing.start("explain_join", schema=left), \
                self.serving.admit("explain"):
            if predicate in kjoin.POLYGON_PREDICATES:
                lst, lplan, lbatch, rst, rplan, rbatch = self._join_sides(
                    left, right, left_query, right_query,
                    right_polygon=True,
                )
                lx, ly = self._side_xy(lst, lbatch)
                geoms = self._side_polygons(rst, rbatch)
                t0 = time.perf_counter()
                _, total, st = join_exec.run_polygon_join(
                    lx, ly, geoms, predicate, level=level,
                    prefer_device=analyze and self.prefer_device
                    and self.mesh is None,
                    want_pairs=False,
                )
                exp.push("Join")
                exp.kv("predicate", predicate)
                exp.kv("sides", f"{left} ({st.n_left} rows) x "
                       f"{right} ({st.n_right} polygons)")
                exp.kv("cell level", st.level)
                exp.kv("cells", f"{st.cells_left} occupied point cells")
                exp.pop()
                exp.push("Adaptive")
                exp.kv("cells[interior]",
                       f"{st.strategy_cells.get('interior', 0)} "
                       f"(wholesale: {st.wholesale_pairs} pairs, zero "
                       f"kernel work)")
                exp.kv("cells[boundary]",
                       f"{st.strategy_cells.get('boundary', 0)} "
                       f"(kernel: {st.candidate_pairs} candidate pairs)")
                exp.kv("statistics read",
                       "classify_cells(cell box, polygon, "
                       "CLASSIFY_MARGIN) per candidate cell")
                if analyze:
                    exp.kv("matched (analyze)", total)
                    exp.kv("kernel ms",
                           round((time.perf_counter() - t0) * 1e3, 3))
                    if st.skipped:
                        exp.kv("degraded", ", ".join(st.skipped))
                exp.pop()
                return str(exp)
            lst, lplan, lbatch, rst, rplan, rbatch = self._join_sides(
                left, right, left_query, right_query
            )
            lx, ly = self._side_xy(lst, lbatch)
            rx, ry = self._side_xy(rst, rbatch)
            p0, p1 = kjoin.pair_params(predicate, distance=distance,
                                       dx=dx, dy=dy)
            wrap_x = False
            if predicate == kjoin.JOIN_BBOX:
                reach_x, reach_y = float(p0), float(p1)
            elif predicate == kjoin.JOIN_DWITHIN_METERS:
                reach_x, reach_y = join_exec.meters_reach_deg(
                    float(distance), ry
                )
                wrap_x = True
            else:
                reach_x = reach_y = float(distance)
            plan = join_exec.co_partition(
                lx, ly, rx, ry, predicate, reach_x, reach_y, level=level,
                p0=p0, p1=p1, wrap_x=wrap_x,
            )
            st = plan.stats
            exp.push("Join")
            exp.kv("predicate", predicate)
            exp.kv("sides", f"{left} ({st.n_left} rows) x "
                   f"{right} ({st.n_right} rows)")
            exp.kv("co-partition level", st.level)
            exp.kv("cells", f"{st.cells_left} build, {st.cells_right} "
                   f"probe, {st.cells_joint} joint (dispatched)")
            exp.kv("candidate pairs",
                   f"{st.candidate_pairs} of {st.naive_pairs} naive "
                   f"({st.candidate_fraction:.4f})")
            exp.kv("boundary-strip fraction",
                   round(st.strip_fraction, 4))
            exp.kv("tiles", f"{st.tiles} ({plan.Bp} x {plan.Pp} padded, "
                   f"{len(plan.sections)} section(s))")
            exp.pop()
            # the adaptive decision trail (docs/JOIN.md §5): what each
            # joint cell's routing read and what it chose
            exp.push("Adaptive")
            exp.kv("enabled", str(bool(st.adaptive)).lower())
            for strat in ("pairwise", "brute", "split.l", "split.r"):
                if strat not in st.strategy_cells:
                    continue
                exp.kv(f"cells[{strat}]",
                       f"{st.strategy_cells[strat]} "
                       f"(est {st.est_pairs.get(strat, 0)} pairs, "
                       f"dispatched {st.dispatched_pairs.get(strat, 0)} "
                       f"slots)")
            exp.kv("statistics read",
                   "per-cell (n_build, n_probe); thresholds: brute <= "
                   f"{config.JOIN_ADAPTIVE_BRUTE_PAIRS.to_int() or 256} "
                   "pairs, skew >= "
                   f"{config.JOIN_ADAPTIVE_SKEW_RATIO.to_int() or 8}:1 "
                   "over tile")
            if analyze:
                t0 = time.perf_counter()
                _, total = join_exec.execute_predicate(
                    plan, lx, ly, rx, ry, predicate,
                    prefer_device=self.prefer_device and self.mesh is None,
                    want_pairs=False,
                )
                exp.kv("matched (analyze)", total)
                exp.kv("pairwise ms",
                       round((time.perf_counter() - t0) * 1e3, 3))
                if st.skipped:
                    exp.kv("degraded", ", ".join(st.skipped))
            exp.pop()
        return str(exp)

    def sample(self, name: str, one_in_n: int,
               query: "str | Query" = "INCLUDE") -> FeatureCollection:
        from geomesa_tpu import processes

        return processes.sample(self, name, one_in_n, query)

    def point2point(self, name: str, group_by: str,
                    query: "str | Query" = "INCLUDE", break_on_day=False):
        from geomesa_tpu import processes

        return processes.point2point(self, name, group_by, query, break_on_day)

    def track_label(self, name: str, track_attr: str,
                    query: "str | Query" = "INCLUDE") -> FeatureCollection:
        from geomesa_tpu import processes

        return processes.track_label(self, name, track_attr, query)

    def route_search(self, name: str, route, buffer_m: float,
                     query: "str | Query" = "INCLUDE", **kw) -> FeatureCollection:
        from geomesa_tpu import processes

        return processes.route_search(self, name, route, buffer_m, query, **kw)

    def export_bin(self, name: str, query: "str | Query" = "INCLUDE",
                   track: Optional[str] = None, label: Optional[str] = None,
                   sort: bool = True) -> bytes:
        """Query results as packed BIN records (BinAggregatingScan /
        BinConversionProcess analog): 16 bytes/record, 24 with a label."""
        from geomesa_tpu.io import bin_format

        fc = self.query(name, query)
        st = self._store(name)
        if fc.batch.n == 0:
            return b""
        return bin_format.pack_batch(st.ft, fc.batch, st.dicts, track, label, sort)

    # -- Arrow interchange (geomesa-arrow / ArrowScan analog) --------------
    def to_arrow(self, name: str, query: "str | Query" = "INCLUDE",
                 properties=None):
        """Query results as an Arrow table (dictionary-encoded strings)."""
        import pyarrow as pa

        from geomesa_tpu.io import arrow_io

        if isinstance(query, str):
            q = Query(ecql=query)
        else:
            import dataclasses

            q = dataclasses.replace(query)
        if properties is not None:
            q.properties = list(properties)
        fc = self.query(name, q)
        st = self._store(name)
        if fc.batch.n == 0:
            # schema of the empty table must match non-empty results: a
            # non-point geometry is utf8 WKT iff the store carries __wkt
            return arrow_io.arrow_schema(
                st.ft, q.properties, st.wkt_geoms()
            ).empty_table()
        rb = arrow_io.batch_to_arrow(st.ft, fc.batch, st.dicts, q.properties)
        return pa.Table.from_batches([rb])

    def export_arrow(self, name: str, path: str,
                     query: "str | Query" = "INCLUDE", properties=None):
        """Write query results to an Arrow IPC file."""
        from geomesa_tpu.io import arrow_io

        table = self.to_arrow(name, query, properties)
        arrow_io.write_ipc(path, table.to_batches(), table.schema)

    def ingest_arrow(self, name: str, source) -> int:
        """Ingest an Arrow table / record batch / IPC file path."""
        import pyarrow as pa

        from geomesa_tpu.io import arrow_io

        if isinstance(source, str):
            source = arrow_io.read_ipc(source)
        st = self._store(name)
        data, fids = arrow_io.table_to_data(st.ft, source)
        return self.insert(name, data, fids)

    # -- persistence (shard-manifest checkpoint, SURVEY.md §5) -------------
    def _save_flat_chunks(self, path: str, name: str, st,
                          prev_entry: Optional[dict]) -> dict:
        """Incremental flat-store checkpoint (TableBasedMetadata
        incrementality analog): the master batch is append-only between
        non-append mutations (tracked by ``mutation_epoch``), so a
        re-save after appends writes ONE new chunk covering the fresh
        rows and leaves every existing chunk file untouched. Deletes /
        column adds change the epoch and force a full rewrite."""
        n = st._all.n if st._all is not None else 0
        prev = prev_entry.get("chunks") if prev_entry else None
        incremental = (
            prev is not None
            and prev_entry.get("epoch") == st.mutation_epoch
            and prev_entry.get("rows", -1) <= n
            and all(os.path.exists(os.path.join(path, f)) for f in prev)
        )
        if not incremental:
            chunks, lo = [], 0
        else:
            chunks, lo = list(prev), int(prev_entry["rows"])
        cdir_rel = f"{name}_chunks"
        os.makedirs(os.path.join(path, cdir_rel), exist_ok=True)
        if n > lo:
            # uuid-suffixed chunk name: a full rewrite NEVER overwrites a
            # chunk the previous (still-live) manifest references — every
            # old file stays untouched until the new manifest is durably
            # published, so a crash at any point mid-save leaves the old
            # checkpoint + its files fully consistent (and the journal
            # still holds everything past it). save() sweeps the
            # unreferenced files after the manifest replace; legacy v1
            # ``{name}.npz`` files sweep the same way.
            fname = (f"{cdir_rel}/chunk-{len(chunks):05d}-{lo}-{n}"
                     f"-{uuid.uuid4().hex[:8]}.npz")
            resilience.fault_point("fs.save.chunk", schema=name, file=fname)
            cols = {
                k: (v[lo:n].astype("U") if v.dtype.kind == "O"
                    else v[lo:n])
                for k, v in st._all.columns.items()
            }
            with open(os.path.join(path, fname), "wb") as fh:
                np.savez_compressed(fh, **cols)
                fh.flush()
                os.fsync(fh.fileno())
            resilience.fsync_dir(os.path.join(path, cdir_rel))
            chunks.append(fname)
        return {"chunks": chunks, "rows": n, "epoch": st.mutation_epoch}

    # -- aggregate-cache persistence (docs/CACHE.md, docs/LAKE.md) ---------
    def persist_cache(self, path: str) -> Dict[str, Any]:
        """Write the aggregate cache's warm entries (flat cells,
        hierarchy nodes, curve chunks, whole results) to one lake-tier
        file, so a restarted process can :meth:`restore_cache` them and
        answer warm zoom-outs with zero device dispatches. Entries are
        only persisted while their epoch matches the store (a snapshot in
        time); returns a per-schema entry-count summary."""
        from geomesa_tpu.lake import persist as lake_persist

        return lake_persist.save_cache(self, path)

    def restore_cache(self, path: str) -> Dict[str, Any]:
        """Re-admit persisted cache entries for every schema whose data
        still matches the persisted guard (row count + spec) — typically
        right after :meth:`load` of the checkpoint the cache was warmed
        against. Imports ride the normal LRU budget and the store's
        CURRENT epoch, so later mutations invalidate as usual."""
        from geomesa_tpu.lake import persist as lake_persist

        return lake_persist.restore_cache(self, path)

    def save(self, path: str, names: Optional[Sequence[str]] = None):
        """Checkpoint to ``path``. ``names`` restricts the save to those
        schemas — other schemas' manifest entries (and files) carry over
        VERBATIM from the existing checkpoint, so a fleet write commit
        (docs/RESILIENCE.md §7) costs the mutated schema, not the whole
        dataset. A named schema that no longer exists locally is REMOVED
        from the manifest (the delete path).

        With the journal attached (docs/RESILIENCE.md §8), save is the
        CHECKPOINT, not the commit: each saved schema's entry is stamped
        with the journal position it captures (``journal_seq``), the
        manifest publishes durably (tmp + fsync + rename + dir fsync),
        and journal segments every schema has checkpointed past are
        truncated. Attachment stays explicit (attach_journal / load) —
        saving to a scratch path must not bind this dataset's
        durability to it."""
        from geomesa_tpu.index.partitioned import PartitionedFeatureStore

        os.makedirs(path, exist_ok=True)
        prev_manifest = {}
        mpath = os.path.join(path, "manifest.json")
        if os.path.exists(mpath):
            with open(mpath) as fh:
                prev_manifest = json.load(fh).get("schemas", {})
        j = self._journal
        if j is not None and os.path.abspath(j.root) != os.path.abspath(path):
            j = None  # saving elsewhere must not stamp/truncate OUR journal
        jpos = j.last_seq() if j is not None else None
        manifest = {"version": 2, "schemas": {}}
        if names is not None:
            keep = set(names)
            manifest["schemas"] = {
                k: v for k, v in prev_manifest.items() if k not in keep
            }
        for name, st in self._stores.items():
            if names is not None and name not in names:
                continue
            st.flush()
            entry = {
                "spec": st.ft.spec(),
                "n_shards": st.n_shards,
                "dicts": {k: d.to_list() for k, d in st.dicts.items()},
                "stats": {k: v.to_json() for k, v in st.stats.items()},
            }
            if jpos is not None:
                entry["journal_seq"] = jpos
            if self.standing is not None:
                # standing subscriptions checkpoint WITH the schema: the
                # save truncates their journal records, so the manifest
                # must carry them for load to re-register
                # (docs/STANDING.md §7)
                standing = self.standing.subscriptions(name)
                if standing:
                    entry["standing"] = standing
            if isinstance(st, PartitionedFeatureStore):
                # incremental: only dirty partitions rewrite their snapshot
                parts = st.checkpoint_into(os.path.join(path, f"{name}_parts"))
                entry["partitions"] = {
                    str(b): os.path.relpath(d, path) for b, d in parts.items()
                }
            else:
                entry.update(self._save_flat_chunks(
                    path, name, st, prev_manifest.get(name)))
            manifest["schemas"][name] = entry
            # our own checkpoint moved the entry; record it so the next
            # refresh_schema against this root stays incremental
            self._ckpt_fp[name] = self._entry_fp(entry)
        resilience.fault_point("fs.save.manifest", path=mpath)
        resilience.durable_write_json(mpath, manifest, indent=2)
        self._sweep_orphan_chunks(path, manifest["schemas"], names)
        if j is not None:
            # truncate segments every schema in the (new) manifest has
            # checkpointed past; a carried-over entry without a stamp pins
            # the whole journal (safe: replay is idempotent-ordered)
            resilience.fault_point("journal.checkpoint", root=path)
            upto = min((int(e.get("journal_seq", 0))
                        for e in manifest["schemas"].values()),
                       default=jpos)
            j.checkpoint(min(upto, jpos))
            for name in list(self._applied_seq):
                if names is None or name in set(names):
                    self._applied_seq[name] = max(
                        self._applied_seq.get(name, 0), jpos)

    def _sweep_orphan_chunks(self, path: str, schemas: Dict[str, Any],
                             names: Optional[Sequence[str]]) -> None:
        """Remove chunk dirs / legacy npz no longer referenced by the
        just-published manifest — the deferred half of the crash-consistent
        save (old files outlive the save until the new manifest is durable;
        only then do they become sweepable orphans). Restricted to the
        schemas this save touched."""
        ref_dirs = set()
        ref_files = set()
        for entry in schemas.values():
            for rel in entry.get("chunks") or []:
                ref_files.add(rel)
                d = os.path.dirname(rel)
                if d:
                    ref_dirs.add(d)
        swept = set(self._stores) if names is None else set(names)
        try:
            listing = os.listdir(path)
        except OSError:
            return
        for name in swept:
            for fn in listing:
                full = os.path.join(path, fn)
                if fn.startswith(f"{name}_chunks") and os.path.isdir(full):
                    if fn not in ref_dirs:
                        shutil.rmtree(full, ignore_errors=True)
                        continue
                    # referenced dir: sweep the chunk FILES a full rewrite
                    # orphaned (uuid-named, so the live ones were never
                    # overwritten)
                    for cf in os.listdir(full):
                        if f"{fn}/{cf}" not in ref_files:
                            try:
                                os.remove(os.path.join(full, cf))
                            except OSError:
                                pass
                elif fn == f"{name}.npz" and fn not in ref_files:
                    entry = schemas.get(name)
                    # a carried-over v1 entry without "chunks" still loads
                    # through the npz fallback — never sweep that
                    if entry is not None and not entry.get("chunks"):
                        continue
                    try:
                        os.remove(full)
                    except OSError:
                        pass

    @staticmethod
    def load(path: str, mesh=None, prefer_device: bool = True) -> "GeoDataset":
        from geomesa_tpu.fs import journal as _jr

        mpath = os.path.join(path, "manifest.json")
        has_journal = (config.JOURNAL_ENABLED.to_bool()
                       and _jr.journal_exists(path))
        manifest: Dict[str, Any] = {"schemas": {}}
        if os.path.exists(mpath):
            with open(mpath) as fh:
                manifest = json.load(fh)
        elif not has_journal:
            # keep the pre-journal contract: loading a root with neither a
            # manifest nor a journal is an error
            with open(mpath) as fh:  # raises FileNotFoundError
                manifest = json.load(fh)
        ds = GeoDataset(mesh=mesh, prefer_device=prefer_device)
        ckpt: Dict[str, int] = {}
        for name, meta in manifest["schemas"].items():
            ds._attach_schema_entry(path, name, meta)
            ckpt[name] = int(meta.get("journal_seq", 0))
            ds._applied_seq[name] = ckpt[name]
        ds.n_shards = None
        if config.JOURNAL_ENABLED.to_bool():
            ds.attach_journal(path)
            if has_journal:
                # recovery: re-apply records past each schema's checkpointed
                # position, in order; torn tails truncate cleanly here
                if ds._journal_replay(ckpt, truncate=True):
                    ds.flush()
        return ds

    @staticmethod
    def _entry_fp(meta: Dict) -> int:
        """Fingerprint of a manifest schema entry, stable across the JSON
        round trip — what :meth:`refresh_schema` compares to decide whether
        the root's checkpoint moved underneath the journal."""
        return zlib.crc32(json.dumps(
            meta, sort_keys=True, separators=(",", ":"),
            default=str).encode()) & 0xFFFFFFFF

    def _attach_schema_entry(self, path: str, name: str, meta: Dict) -> None:
        """Create + populate ONE schema's store from a checkpoint manifest
        entry (the per-schema half of :meth:`load`; also the fleet epoch
        refresh path — docs/RESILIENCE.md §7)."""
        self._ckpt_fp[name] = self._entry_fp(meta)
        prev_shards = self.n_shards
        ft = FeatureType.from_spec(name, meta["spec"])
        self.n_shards = meta["n_shards"]
        try:
            # attaching FROM a checkpoint is not a new mutation: it must
            # not journal a schema-create record
            with self._replay_scope():
                self.create_schema(ft)
        finally:
            self.n_shards = prev_shards
        st = self._store(name)
        st.dicts = {
            k: DictionaryEncoder(v) for k, v in meta["dicts"].items()
        }
        st.stats = {k: sk.Stat.from_json(v) for k, v in meta["stats"].items()}
        if "partitions" in meta:
            st.attach_snapshots({
                int(b): os.path.join(path, rel)
                for b, rel in meta["partitions"].items()
            })
            self._standing_restore(name, meta)
            return
        # v2 chunked layout, with the v1 single-npz fallback
        chunk_files = meta.get("chunks")
        if chunk_files is None:
            npz_path = os.path.join(path, f"{name}.npz")
            chunk_files = ([os.path.relpath(npz_path, path)]
                           if os.path.exists(npz_path) else [])
        parts = []
        for rel in chunk_files:
            with np.load(os.path.join(path, rel),
                         allow_pickle=False) as z:
                cols = {}
                for k in z.files:
                    v = z[k]
                    cols[k] = (v.astype(object) if v.dtype.kind == "U"
                               else v)
                if cols:
                    parts.append(ColumnBatch(
                        cols, len(next(iter(cols.values())))))
        if parts:
            from geomesa_tpu.schema.columns import schema_null_fills

            # schema-derived fills: mixed-vintage chunks (e.g. saved
            # before a column existed) null-fill per the layout's
            # convention, not a dtype guess
            st._all = (parts[0] if len(parts) == 1
                       else ColumnBatch.concat(
                           parts, fills=schema_null_fills(ft)))
            if "epoch" in meta:
                st.mutation_epoch = meta["epoch"]
            key_cols = dict(st._all.columns)
            for ks in st.keyspaces:
                key_cols.update(ks.index_keys(ft, st._all))
                st.tables[ks.name].rebuild(key_cols, st.dicts)
            # seed the key cache so the next flush appends incrementally
            st._key_cols = {
                k: v for k, v in key_cols.items()
                if k not in st._all.columns
            }
        self._standing_reattach(name)
        self._standing_restore(name, meta)

    def _standing_restore(self, name: str, meta: Dict) -> None:
        """Re-register the checkpoint's standing subscriptions (manifest
        ``entry["standing"]``, written by :meth:`save`) under their
        ORIGINAL ids — each snapshot anchor re-evaluates against the
        freshly attached store (docs/STANDING.md §7). A spec that no
        longer validates (schema drift since the checkpoint) degrades
        through the skip trail instead of failing the load."""
        recs = meta.get("standing") or []
        if not recs:
            return
        from geomesa_tpu.subscribe.spec import StandingSpec

        for rec in recs:
            try:
                self._standing_engine().register(
                    StandingSpec.from_dict(rec["spec"]),
                    sub_id=rec["sub_id"])
            except Exception as e:
                resilience.record_skip(
                    "standing.restore", f"{name}:{rec.get('sub_id')}", e,
                    phase="load")

    def _standing_reattach(self, name: str) -> None:
        if self.standing is not None and self.standing.active(name):
            # the store object was swapped under the standing groups:
            # recompile viewports against the fresh dicts and re-scan
            self.standing.reattach(name)

    def refresh_schema(self, name: str, path: str) -> bool:
        """Replace schema ``name``'s in-memory state with what the shared
        checkpoint at ``path`` holds — the replica-side half of fleet
        epoch propagation (docs/RESILIENCE.md §7): a replica whose known
        fleet epoch trails an incoming request's re-reads the schema from
        the shared root BEFORE serving, so a restarted or failed-over
        replica can never answer from a pre-mutation store or cache
        (the replaced store's covers drop with its uid, exactly like a
        local mutation epoch bump). Handles remote creates (schema in the
        manifest but not here), remote deletes (here but gone from the
        manifest), and plain data changes. Returns True when anything
        changed."""
        mpath = os.path.join(path, "manifest.json")
        schemas: Dict[str, Any] = {}
        if os.path.exists(mpath):
            with open(mpath) as fh:
                schemas = json.load(fh).get("schemas", {})
        meta = schemas.get(name)
        old = self._stores.get(name)
        j = self._journal
        use_journal = (
            j is not None
            and os.path.abspath(j.root) == os.path.abspath(path)
        )
        ckpt = int(meta.get("journal_seq", 0)) if meta is not None else 0
        if use_journal:
            have = self._applied_seq.get(name)
            # the incremental shortcut is valid only while the root's
            # manifest entry is the one we attached (journal-only growth):
            # an entry rewritten out-of-band — a writer checkpointing
            # without the journal — must force the full re-attach below
            # or the rewrite is never observed
            unmoved = (meta is None
                       or self._ckpt_fp.get(name) == self._entry_fp(meta))
            if old is not None and have is not None and have >= ckpt \
                    and unmoved:
                # incremental catch-up (docs/RESILIENCE.md §8): this replica
                # already holds the schema at journal position ``have`` —
                # re-apply only the shared journal's records past it. A
                # one-row fleet insert costs one record here, never a full
                # schema re-attach; version bumps invalidate covers exactly
                # like a local mutation.
                applied = self._journal_replay({name: have}, schema=name)
                if applied and name in self._stores:
                    self.flush(name)
                return applied > 0
            if meta is None:
                # schema not checkpointed yet: it exists (if at all) only in
                # the journal — rebuild it from records alone
                if old is not None:
                    with self._replay_scope():
                        self.delete_schema(name)
                    self._plan_cache_clear(name)
                    self._drop_executors(name)
                applied = self._journal_replay({name: 0}, schema=name)
                if applied and name in self._stores:
                    self.flush(name)
                return applied > 0 or old is not None
        if meta is None:
            if old is None:
                return False
            with self._replay_scope():
                self.delete_schema(name)  # invalidates the old uid's covers
            self._plan_cache_clear(name)
            self._drop_executors(name)
            return True
        if old is not None:
            self.cache.store.invalidate(old.uid)
            del self._stores[name]
            self.metadata.pop(name, None)
            self._plan_cache_clear(name)
            self._drop_executors(name)
        self._attach_schema_entry(path, name, meta)
        self._applied_seq[name] = ckpt
        if use_journal:
            # replay the journal's records past the checkpoint this entry
            # captured (the trailing-replica recovery half of §8)
            if self._journal_replay({name: ckpt}, schema=name):
                if name in self._stores:
                    self.flush(name)
        return True
