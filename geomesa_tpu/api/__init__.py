from geomesa_tpu.api.dataset import GeoDataset, Query  # noqa: F401
