"""Visibility-label security (geomesa-security analog).

Role parity (SURVEY.md §2.8): per-feature visibility expressions — boolean
combinations of labels like ``admin&(user|system)`` — parsed by a
``VisibilityEvaluator`` (reference
geomesa-security/.../VisibilityEvaluator.scala:22,156) and checked against a
user's authorization set (``AuthorizationsProvider``).

TPU translation: visibility strings are dictionary-encoded at ingest into an
int32 ``__vis__`` code column. At plan time the *distinct expressions* (the
dictionary) are evaluated once against the query's auths, producing a boolean
lookup table per code; the query-time check is then a single device gather
``lut[vis_code]`` fused into the predicate mask — row-level enforcement in
the scan kernel, the analog of Accumulo cell-level security.

Grammar (Accumulo-compatible): labels are ``[A-Za-z0-9_.:/-]+`` or quoted
``"..."``; operators ``&`` (and) and ``|`` (or) with parentheses; ``&`` binds
tighter than ``|``. The empty expression means "visible to everyone".
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import FrozenSet, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np

from geomesa_tpu import config

VIS_COLUMN = "__vis__"

_LABEL_RE = re.compile(r"[A-Za-z0-9_.:/\-]+")


# -- expression AST ----------------------------------------------------------

@dataclass(frozen=True)
class VisLabel:
    name: str

    def evaluate(self, auths: FrozenSet[str]) -> bool:
        return self.name in auths


@dataclass(frozen=True)
class VisAnd:
    parts: Tuple["VisExpr", ...]

    def evaluate(self, auths: FrozenSet[str]) -> bool:
        return all(p.evaluate(auths) for p in self.parts)


@dataclass(frozen=True)
class VisOr:
    parts: Tuple["VisExpr", ...]

    def evaluate(self, auths: FrozenSet[str]) -> bool:
        return any(p.evaluate(auths) for p in self.parts)


VisExpr = Union[VisLabel, VisAnd, VisOr]


class VisibilityError(ValueError):
    pass


def parse_visibility(expr: str) -> Optional[VisExpr]:
    """Parse a visibility expression; ``None`` for the empty (public) one."""
    s = expr.strip()
    if not s:
        return None
    tokens = _tokenize(s)
    node, pos = _parse_or(tokens, 0)
    if pos != len(tokens):
        raise VisibilityError(f"trailing tokens in visibility {expr!r}")
    return node


def _tokenize(s: str) -> List[str]:
    out, i = [], 0
    while i < len(s):
        c = s[i]
        if c.isspace():
            i += 1
        elif c in "&|()":
            out.append(c)
            i += 1
        elif c == '"':
            j = s.find('"', i + 1)
            if j < 0:
                raise VisibilityError(f"unterminated quote in {s!r}")
            out.append("L" + s[i + 1 : j])
            i = j + 1
        else:
            m = _LABEL_RE.match(s, i)
            if not m:
                raise VisibilityError(f"bad character {c!r} in visibility {s!r}")
            out.append("L" + m.group(0))
            i = m.end()
    return out


def _parse_or(tokens: List[str], pos: int) -> Tuple[VisExpr, int]:
    parts = []
    node, pos = _parse_and(tokens, pos)
    parts.append(node)
    while pos < len(tokens) and tokens[pos] == "|":
        node, pos = _parse_and(tokens, pos + 1)
        parts.append(node)
    return (parts[0] if len(parts) == 1 else VisOr(tuple(parts))), pos


def _parse_and(tokens: List[str], pos: int) -> Tuple[VisExpr, int]:
    parts = []
    node, pos = _parse_atom(tokens, pos)
    parts.append(node)
    while pos < len(tokens) and tokens[pos] == "&":
        node, pos = _parse_atom(tokens, pos + 1)
        parts.append(node)
    return (parts[0] if len(parts) == 1 else VisAnd(tuple(parts))), pos


def _parse_atom(tokens: List[str], pos: int) -> Tuple[VisExpr, int]:
    if pos >= len(tokens):
        raise VisibilityError("unexpected end of visibility expression")
    t = tokens[pos]
    if t == "(":
        node, pos = _parse_or(tokens, pos + 1)
        if pos >= len(tokens) or tokens[pos] != ")":
            raise VisibilityError("unbalanced parentheses in visibility")
        return node, pos + 1
    if t.startswith("L"):
        return VisLabel(t[1:]), pos + 1
    raise VisibilityError(f"unexpected token {t!r} in visibility")


# -- evaluation --------------------------------------------------------------

class VisibilityEvaluator:
    """Caches parsed expressions (the reference caches via parse-once
    VisibilityExpression objects)."""

    def __init__(self):
        self._cache: dict = {}

    def parse(self, expr: str) -> Optional[VisExpr]:
        node = self._cache.get(expr, False)
        if node is False:
            node = parse_visibility(expr)
            self._cache[expr] = node
        return node

    def can_see(self, expr: str, auths: Iterable[str]) -> bool:
        node = self.parse(expr)
        if node is None:
            return True
        return node.evaluate(frozenset(auths))


_EVALUATOR = VisibilityEvaluator()


def can_see(expr: str, auths: Iterable[str]) -> bool:
    return _EVALUATOR.can_see(expr, auths)


def allowed_lut(vis_values: Sequence[str], auths: Iterable[str]) -> np.ndarray:
    """Boolean lookup table over the visibility dictionary: lut[code] = the
    auths satisfy expression ``vis_values[code]``. The device-side check is
    ``lut[vis_code_column]``."""
    a = frozenset(auths)
    lut = np.empty(max(len(vis_values), 1), dtype=bool)
    lut[:] = True
    for i, expr in enumerate(vis_values):
        lut[i] = _EVALUATOR.can_see(expr, a)
    return lut


# -- auth providers ----------------------------------------------------------

class AuthorizationsProvider:
    """Supplies the effective auth set for a query (reference
    geomesa-security AuthorizationsProvider SPI)."""

    def auths(self) -> Optional[List[str]]:
        raise NotImplementedError


class DefaultAuthorizationsProvider(AuthorizationsProvider):
    """Reads ``geomesa.security.auths`` (comma-separated). Returns None
    (= unrestricted) when the property is unset."""

    def auths(self) -> Optional[List[str]]:
        raw = config.SECURITY_AUTHS.get()
        if raw is None or raw == "":
            return None
        return [a.strip() for a in raw.split(",") if a.strip()]
