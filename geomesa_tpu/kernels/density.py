"""Density (heatmap) kernel.

Parity with the reference's DensityScan (index/iterators/DensityScan.scala:
29-136: per-row RenderingGrid scatter in tablet servers, sparse grids merged
client-side): here ONE scatter-add over the full sharded column set — XLA
partitions the scatter per device and all-reduces the grid (the
``StatsCombiner``/reducer role, played by the XLA collective).
"""

from __future__ import annotations

import numpy as np


def density_grid(x, y, mask, bbox, width: int, height: int, weight=None, xp=None):
    """Masked 2D histogram: points -> (height, width) float32 grid.

    ``x``/``y``/``mask`` may be [S, L] or flat; backend-generic (np or jnp).
    Cells follow the reference's RenderingGrid convention: row 0 = ymin edge.
    """
    xmin, ymin, xmax, ymax = bbox
    # spans computed HERE (host f64 for baked bboxes) so the query-axis
    # batched kernel can pass the f32 images of the SAME span values as
    # traced scalars and reproduce the pixel mapping bit-for-bit — an
    # f32 (xmax - xmin) recomputed in-kernel could differ by an ulp
    return density_grid_at(
        x, y, mask, xmin, ymin, xmax - xmin, ymax - ymin,
        width, height, weight, xp,
    )


def grid_params(bbox) -> np.ndarray:
    """The traced-parameter form of a density bbox for the batched kernel:
    ``[x0, y0, dx, dy]`` as the f32 images of the host-f64 origin/span —
    exactly the scalar values the baked :func:`density_grid` closes over
    after jax's weak-type f32 conversion."""
    xmin, ymin, xmax, ymax = (float(v) for v in bbox)
    return np.asarray(
        [xmin, ymin, xmax - xmin, ymax - ymin], np.float32
    )


def density_grid_at(x, y, mask, x0, y0, dx, dy, width: int, height: int,
                    weight=None, xp=None):
    """:func:`density_grid` against an origin/span parameterization.
    ``x0``/``y0``/``dx``/``dy`` may be python floats (baked — the classic
    path) or traced f32 scalars (the query-axis batched path, one compiled
    kernel serving every viewport)."""
    if xp is None:
        xp = np
    fx = x.reshape(-1)
    fy = y.reshape(-1)
    fm = mask.reshape(-1)
    px = xp.clip(((fx - x0) / dx * width).astype(xp.int32), 0, width - 1)
    py = xp.clip(((fy - y0) / dy * height).astype(xp.int32), 0, height - 1)
    w = fm.astype(xp.float32) if weight is None else xp.where(
        fm, weight.reshape(-1).astype(xp.float32), xp.float32(0)
    )
    flat_idx = py * width + px
    if xp is np:
        grid = np.zeros(height * width, np.float32)
        np.add.at(grid, flat_idx, w)
        return grid.reshape(height, width)
    # Split the scatter into independent pieces accumulating separate
    # grids. Measured on v5e with pre-staged inputs, 8 independent pow2
    # scatters + grid adds ran ~10x faster than one scatter; re-measured
    # r4 FUSED behind a mask compute in one jit, the split shows no gain
    # (~7 ns/update either way — XLA serializes the pieces after the
    # shared producer). Kept because it never hurts and the pre-staged
    # shape still benefits; the real fix is the pallas kernel
    # (density_pallas.py), which replaces this path on z-indexed tables.
    # Pieces must divide evenly — callers keep row counts a multiple of 8
    # (see executor chunk buckets).
    from geomesa_tpu import config

    P = config.SCATTER_SPLIT.to_int() or 0
    n = flat_idx.shape[0]
    if P <= 1 or n % P or n < (1 << 14):
        return (
            xp.zeros(height * width, xp.float32).at[flat_idx].add(w)
        ).reshape(height, width)
    fi = flat_idx.reshape(P, -1)
    fw = w.reshape(P, -1)
    grid = None
    for p in range(P):
        s = xp.zeros(height * width, xp.float32).at[fi[p]].add(fw[p])
        grid = s if grid is None else grid + s
    return grid.reshape(height, width)
