"""Density (heatmap) as a Pallas grouped one-hot matmul — the fast device path.

All prior device formulations of the DensityScan analog
(index/iterators/DensityScan.scala:29-136) hit hardware walls on v5e:

- XLA scatter-add costs ~7 ns per touched row regardless of batching (the
  per-update serialization is architectural): 2M admitted rows = ~15 ms.
- The XLA einsum pair kernel (kernels/density_mxu.py) materializes its
  [PB, B, TX] one-hot operands in HBM between the VPU compare that builds
  them and the MXU contraction that consumes them — ~7x off roofline.
- Any per-query re-ordering of row data into pair order is itself the
  bottleneck: per-element XLA gathers run ~7.5 ns/element and B-row slab
  gathers are DMA-descriptor-bound (~0.5 us per small slab).

This kernel therefore never reorders row data. The mask/weight and cell
coordinates stay in the dense compact [C, B] layout; a per-chunk
(chunk, tile) pair list sorted by tile drives the pallas GRID. Each step
fetches the [SG, B] superchunk block CONTAINING its pair's chunk via a
scalar-prefetched index map (``BlockSpec`` index_map reading ``sc[p]`` =
chunk // SG; a single-chunk block would violate the (8, 128) minimum
block shape) and selects the chunk's sublane row with a second prefetched
scalar (``row[p]`` = chunk % SG). The stable tile sort keeps chunk ids
ascending within a tile run, so consecutive steps usually reuse the
already-fetched block. Per step, the row's one-hots are built in VMEM
with rows in LANES and grid cells in SUBLANES (natural layouts, no
relayout):

    ohx[T, B] = onehot(sublane_iota == px - tile_x0)   # VPU, VMEM-only
    A[T, B]   = w * onehot(sublane_iota == py - tile_y0)
    tile[T, T] += A @ ohx^T                            # MXU, contract lanes

Rows outside the pair's tile produce all-zero one-hot columns
(clip(1-|dx|, 0, 1) with out-of-range dx), so multi-tile chunks need no
masking; consecutive steps of one tile accumulate in VMEM and write back
on tile change (grouped-matmul revisiting). Measured at the bench shape
(2.2M compact rows, 36k pairs, 512x512 grid): ~9.5 ms vs 15.5 ms scatter
and ~22 ms einsum.

Unweighted counts use bfloat16 one-hots (0/1 exact, f32 accumulation);
weighted densities use f32 operands end-to-end.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from geomesa_tpu.kernels.density_mxu import pair_candidates

#: fixed tile = the MXU native shape
TILE = 128

#: chunks per superchunk (the fetch granularity; 8 = the minimum legal
#: sublane block)
SG = 8

#: pad-pair tile origin: far enough off-grid that every one-hot misses,
#: small enough that int32 cell arithmetic cannot overflow
_OFFGRID = np.int32(1 << 20)


def build_grouped(
    compact: Dict, table, keyspace, bbox, width: int, height: int,
    box_cache: Optional[Dict] = None, version=None,
) -> Optional[Dict]:
    """Host-side pair schedule for the grouped kernel: (superchunk, tile)
    pairs sorted by tile id, one pallas grid step per pair. Returns None
    when the index has no morton key (scatter fallback) or the pair
    expansion would duplicate rows beyond the configured budget."""
    from geomesa_tpu import config

    cand = pair_candidates(
        compact, table, keyspace, bbox, width, height, TILE, TILE,
        box_cache, version,
    )
    if cand is None:
        return None
    B = compact["B"]
    # budget against the REAL chunk count: len(valid) is the ladder8-padded
    # count, which would loosen the configured budget by up to ~25%
    C = int((compact["valid"] > 0).sum())
    P = cand["P"]
    md = config.DENSITY_PALLAS_MAX_DUP.to_float()
    max_dup = 4.0 if md is None else md
    if C == 0 or P > max_dup * C:
        return None  # coarse keys made chunk boxes span too many tiles
    ntx, nty = cand["ntx"], cand["nty"]
    ntiles = ntx * nty
    chunk_of, tx, ty = cand["chunk_of"], cand["tx"], cand["ty"]
    tile = (ty * ntx + tx).astype(np.int32)
    # stable sort by tile keeps chunk ids ascending within each tile run,
    # so consecutive steps usually land in the same superchunk block and
    # pallas skips the re-fetch
    order = np.argsort(tile, kind="stable")
    chunk = chunk_of[order]
    tile = tile[order]
    ox = (tx[order] * TILE).astype(np.int32)
    oy = (ty[order] * TILE).astype(np.int32)
    seen = np.zeros(ntiles, bool)
    seen[np.unique(tile)] = True
    # bucket the pair count (shared ladder with the compact chunk count) so
    # similar queries reuse one compiled kernel shape instead of tracing a
    # fresh pallas program per distinct P. Pad pairs aim at the LAST tile
    # with an off-grid origin: their one-hots are all-zero, so they
    # accumulate nothing (and keep the tile-sorted invariant).
    from geomesa_tpu.kernels.density_mxu import ladder8

    Pp = ladder8(P)
    if Pp != P:
        pad = Pp - P

        def _pad(a, fill):
            return np.concatenate([a, np.full(pad, fill, a.dtype)])

        chunk = _pad(chunk, 0)
        tile = _pad(tile, ntiles - 1)
        ox = _pad(ox, _OFFGRID)
        oy = _pad(oy, _OFFGRID)
    return {
        "sc": (chunk // SG).astype(np.int32),
        "row": (chunk % SG).astype(np.int32),
        "tile": tile,
        "ox": ox,
        "oy": oy,
        "seen": seen,
        "B": B,
        "ntx": ntx,
        "nty": nty,
        "n_pairs": Pp,
    }


def density_grid_grouped(x, y, mask, bbox, width: int, height: int, weight,
                         sc, row, tile, ox, oy, seen,
                         B: int, ntx: int, nty: int, n_pairs: int):
    """Device kernel: dense compact [C, B] columns + pair schedule -> grid.

    ``x``/``y``/``mask`` stay in compact order; the pallas index maps pull
    each pair's superchunk block on demand — no reordering pass."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from geomesa_tpu.kernels import pallas_kernels as pk

    xmin, ymin, xmax, ymax = bbox
    px = jnp.clip(
        ((x - xmin) / (xmax - xmin) * width).astype(jnp.int32), 0, width - 1
    )
    py = jnp.clip(
        ((y - ymin) / (ymax - ymin) * height).astype(jnp.int32), 0, height - 1
    )
    w = (
        mask.astype(jnp.float32)
        if weight is None
        else jnp.where(mask, weight.astype(jnp.float32), jnp.float32(0))
    )
    # pad the chunk axis to a whole number of superchunks (ladder8 makes
    # this a no-op in practice)
    C = px.shape[0]
    pad = (-C) % SG
    if pad:
        px = jnp.pad(px, ((0, pad), (0, 0)))
        py = jnp.pad(py, ((0, pad), (0, 0)))
        w = jnp.pad(w, ((0, pad), (0, 0)))
    dt = jnp.bfloat16 if weight is None else jnp.float32
    ntiles = ntx * nty
    T = TILE

    def kernel(sc_ref, r_ref, t_ref, ox_ref, oy_ref,
               px_ref, py_ref, w_ref, acc_ref):
        p = pl.program_id(0)
        first = (p == 0) | (t_ref[p] != t_ref[jnp.maximum(p - 1, 0)])
        iot = jax.lax.broadcasted_iota(jnp.int32, (T, B), 0)
        r = r_ref[p]
        pxr = px_ref[pl.ds(r, 1), :] - ox_ref[p]   # [1, B]
        pyr = py_ref[pl.ds(r, 1), :] - oy_ref[p]
        wr = w_ref[pl.ds(r, 1), :]
        dx = jnp.broadcast_to(pxr, (T, B)) - iot
        dy = jnp.broadcast_to(pyr, (T, B)) - iot
        # arithmetic one-hots: (dx == 0) compiles to an i1 relayout mosaic
        # rejects ("non-singleton dimension replicated"), so clip(1 - |d|)
        ohx = jnp.clip(1 - jnp.abs(dx), 0, 1).astype(dt)
        A = (jnp.broadcast_to(wr, (T, B)).astype(dt)
             * jnp.clip(1 - jnp.abs(dy), 0, 1).astype(dt))
        t = jax.lax.dot_general(
            A, ohx, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )[None]

        @pl.when(first)
        def _():
            acc_ref[...] = t

        @pl.when(~first)
        def _():
            acc_ref[...] += t

    acc = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=5,
            grid=(n_pairs,),
            in_specs=[
                pl.BlockSpec(
                    (SG, B), lambda p, sc, r, t, ox, oy: (sc[p], 0)
                ),
                pl.BlockSpec(
                    (SG, B), lambda p, sc, r, t, ox, oy: (sc[p], 0)
                ),
                pl.BlockSpec(
                    (SG, B), lambda p, sc, r, t, ox, oy: (sc[p], 0)
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, T, T), lambda p, sc, r, t, ox, oy: (t[p], 0, 0)
            ),
        ),
        out_shape=jax.ShapeDtypeStruct((ntiles, T, T), jnp.float32),
        interpret=pk.interpret_mode(),
    )(sc, row, tile, ox, oy, px, py, w)
    # blocks never visited hold uninitialized VMEM — zero them via the mask
    acc = jnp.where(seen[:, None, None], acc, jnp.float32(0))
    grid = acc.reshape(nty, ntx, T, T).transpose(0, 2, 1, 3)
    return grid.reshape(nty * T, ntx * T)[:height, :width]
