"""Pallas TPU kernels for the compute-bound hot ops.

The bandwidth-bound ops (density scatter, masked reductions) are already at
the HBM roofline under plain XLA — measured on v5e, the 512x512 density
scatter over 8M points runs in ~0.1 ms, i.e. memory-bound — so they stay as
jnp. What benefits from a hand kernel is the **point-in-polygon fine filter**
(the reference's per-row geometry predicate inside AggregatingScan,
index/iterators/AggregatingScan.scala:82-116): N points x E edges of
crossing-parity work with an [N, E] broadcast intermediate. The Pallas
version pins the edge table in VMEM and streams point blocks through the VPU,
so the [block, E] intermediate never touches HBM.

CPU tests run the same kernel in interpret mode (tests/test_pallas.py);
production dispatch gates on the TPU backend (``use_pallas()``).
"""

from __future__ import annotations

import contextlib
import os
import threading

import numpy as np

_BLOCK = 1024  # points per program (sublane-aligned: f32 tiles are (8, 128))
# Edge cap is sized by the kernel's [_BLOCK, Ep] VMEM intermediates (~4 live
# f32/i32 arrays): 1024 x 1024 x 4 B x 4 = 16 MB, the VMEM budget — not by
# the 4 x Ep edge table, which is comparatively free.
_MAX_EDGES = 1024

_tls = threading.local()


@contextlib.contextmanager
def sharded_execution(on: bool):
    """Mark that subsequent kernel traces run under a sharded mesh.

    pallas_call has no GSPMD partitioning rule, so under NamedSharding'd
    inputs it would replicate (or fail -> permanent host fallback); the
    executor flips this flag so dispatch sticks to the XLA broadcast path."""
    prev = getattr(_tls, "sharded", False)
    _tls.sharded = on
    try:
        yield
    finally:
        _tls.sharded = prev


def use_pallas() -> bool:
    """Pallas dispatch gate: real TPU backend, unsharded, not env-disabled."""
    if os.environ.get("GEOMESA_PALLAS", "1") == "0":
        return False
    if getattr(_tls, "sharded", False):
        return False
    try:
        import jax

        return jax.default_backend() == "tpu"
    except Exception:
        return False


def polygon_edge_tables(poly):
    """Shared edge-table builder for one Polygon (shell + holes).

    Returns ``(f64_tuple, packed_f32)`` where ``f64_tuple`` is
    ``(x1, y1, x2, y2, slope)`` for the host/broadcast paths and
    ``packed_f32`` is the lane-padded [4, Ep] table for the Pallas kernel.
    Horizontal edges get slope denominator 1.0 — the crossing condition is
    false for them so the value is never used."""
    from geomesa_tpu.utils import geometry as geo

    rings = [np.asarray(geo._close_ring(poly.shell), np.float64)] + [
        np.asarray(geo._close_ring(h), np.float64) for h in poly.holes
    ]
    x1 = np.concatenate([r[:-1, 0] for r in rings])
    y1 = np.concatenate([r[:-1, 1] for r in rings])
    x2 = np.concatenate([r[1:, 0] for r in rings])
    y2 = np.concatenate([r[1:, 1] for r in rings])
    dy = np.where(y2 - y1 == 0.0, 1.0, y2 - y1)
    slope = (x2 - x1) / dy
    return (x1, y1, x2, y2, slope), pack_edges(x1, y1, y2, slope)


def pack_edges(x1, y1, y2, slope) -> np.ndarray:
    """Edge table -> [4, Ep] f32, lane-padded to a multiple of 128.

    Padding rows have y1 == y2 == 0 so the crossing condition
    ``(y1 > y) != (y2 > y)`` is identically false — padded edges never
    contribute a crossing."""
    e = len(x1)
    ep = max(128, ((e + 127) // 128) * 128)
    out = np.zeros((4, ep), np.float32)
    out[0, :e] = x1
    out[1, :e] = y1
    out[2, :e] = y2
    out[3, :e] = slope
    return out


def _pip_kernel(x_ref, y_ref, e_ref, out_ref):
    """One block of points vs the full edge table (even-odd crossing parity).

    x/y blocks are [B, 1] (column layout so the [B, E] broadcast puts E on
    the 128-lane axis); the edge table [4, Ep] lives whole in VMEM."""
    import jax.numpy as jnp

    x = x_ref[:]          # [B, 1]
    y = y_ref[:]          # [B, 1]
    x1 = e_ref[0:1, :]    # [1, Ep]
    y1 = e_ref[1:2, :]
    y2 = e_ref[2:3, :]
    slope = e_ref[3:4, :]
    cond = (y1 > y) != (y2 > y)                      # [B, Ep]
    xint = x1 + (y - y1) * slope
    crossings = jnp.sum(
        (cond & (x < xint)).astype(jnp.int32), axis=1, keepdims=True
    )
    out_ref[:] = (crossings % 2).astype(jnp.float32)


def _pip_call(xf, yf, edges, interpret: bool = False):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = xf.shape[0]
    nb = pl.cdiv(n, _BLOCK)
    col = lambda i: (i, 0)  # noqa: E731
    return pl.pallas_call(
        _pip_kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((_BLOCK, 1), col, memory_space=pltpu.VMEM),
            pl.BlockSpec((_BLOCK, 1), col, memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (4, edges.shape[1]), lambda i: (0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec((_BLOCK, 1), col, memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((nb * _BLOCK, 1), jnp.float32),
        interpret=interpret,
    )(xf.reshape(-1, 1), yf.reshape(-1, 1), edges)


def pip_mask(x, y, edges: np.ndarray, interpret: bool = False):
    """Even-odd point-in-polygon mask for one polygon's packed edge table.

    ``x``/``y``: jnp arrays of any shape; returns a bool mask of that shape.
    Points are zero-padded up to the block size — padding results are sliced
    off before reshaping back."""
    import jax.numpy as jnp

    shape = x.shape
    xf = jnp.ravel(x).astype(jnp.float32)
    yf = jnp.ravel(y).astype(jnp.float32)
    n = xf.shape[0]
    pad = (-n) % _BLOCK
    if pad:
        xf = jnp.pad(xf, (0, pad))
        yf = jnp.pad(yf, (0, pad))
    out = _pip_call(xf, yf, jnp.asarray(edges), interpret=interpret)
    return out[:n, 0].astype(bool).reshape(shape)


def edges_fit(n_edges: int) -> bool:
    return n_edges <= _MAX_EDGES
