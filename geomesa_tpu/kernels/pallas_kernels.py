"""Pallas TPU kernels for the compute-bound hot ops.

The bandwidth-bound ops (density scatter, masked reductions) are already at
the HBM roofline under plain XLA — measured on v5e, the 512x512 density
scatter over 8M points runs in ~0.1 ms, i.e. memory-bound — so they stay as
jnp. What benefits from a hand kernel is the **point-in-polygon fine filter**
(the reference's per-row geometry predicate inside AggregatingScan,
index/iterators/AggregatingScan.scala:82-116): N points x E edges of
crossing-parity work with an [N, E] broadcast intermediate. The Pallas
version pins the edge table in VMEM and streams point blocks through the VPU,
so the [block, E] intermediate never touches HBM.

CPU tests run the same kernel in interpret mode (tests/test_pallas.py);
production dispatch gates on the TPU backend (``use_pallas()``).
"""

from __future__ import annotations

import contextlib
import os
import threading

import numpy as np

_BLOCK = 1024  # points per program (sublane-aligned: f32 tiles are (8, 128))
# Edge cap is sized by the kernel's [_BLOCK, Ep] VMEM intermediates (~4 live
# f32/i32 arrays): 1024 x 1024 x 4 B x 4 = 16 MB, the VMEM budget — not by
# the 4 x Ep edge table, which is comparatively free.
_MAX_EDGES = 1024

_tls = threading.local()


@contextlib.contextmanager
def sharded_execution(mesh_or_flag):
    """Mark that subsequent kernel traces run under a sharded mesh.

    pallas_call has no GSPMD partitioning rule, so under NamedSharding'd
    inputs a bare call would replicate (or fail -> permanent host fallback).
    When the executor passes its actual ``Mesh``, polygon fine-filters keep
    the hand kernel by wrapping it in an inner ``shard_map`` (per-device
    pallas over the local block); a bare truthy flag (mesh unknown) keeps
    the old behavior of falling back to the XLA broadcast path."""
    prev = getattr(_tls, "sharded", False)
    _tls.sharded = mesh_or_flag
    try:
        yield
    finally:
        _tls.sharded = prev


def current_mesh():
    """The active mesh under :func:`sharded_execution`, if one was given."""
    m = getattr(_tls, "sharded", False)
    return m if m is not False and m is not True and m is not None else None


def interpret_mode() -> bool:
    """Force interpret-mode pallas on any backend (CPU-mesh tests)."""
    return os.environ.get("GEOMESA_PALLAS_INTERPRET") == "1"


def _backend_ok() -> bool:
    if os.environ.get("GEOMESA_PALLAS", "1") == "0":
        return False
    if interpret_mode():
        return True
    try:
        import jax

        return jax.default_backend() == "tpu"
    except Exception:
        return False


def use_pallas() -> bool:
    """Plain (unsharded) pallas dispatch gate."""
    if getattr(_tls, "sharded", False):
        return False
    return _backend_ok()


def use_pallas_sharded(mesh, lead_dim: int, kernel: str = None) -> bool:
    """Sharded dispatch gate: backend ok, mesh has a 'shard' axis that
    evenly divides the leading (shard) dimension — shard_map requires
    exact divisibility, unlike GSPMD. Pass ``kernel`` to record an
    uneven-mesh refusal as that kernel's dispatch (bare capability
    probes record nothing)."""
    if mesh is None or not _backend_ok():
        return False
    size = dict(zip(mesh.axis_names, mesh.devices.shape)).get("shard")
    if not size:
        return False
    if lead_dim % size != 0:
        if kernel is not None:
            # the fallback to the XLA broadcast path used to be silent —
            # the dispatch record makes it visible in explain/audit
            record_dispatch(kernel,
                            f"xla-fallback(uneven mesh: {lead_dim} rows"
                            f" % {size} shards != 0)")
        return False
    return True


def record_dispatch(kernel: str, choice: str) -> None:
    """Note a kernel-dispatch decision. Decisions happen at TRACE time,
    so a record exists only for the execution that compiled the kernel;
    cached-kernel reuse produces none (exec_path's ``kernel:*`` entries
    are compile-time attribution). The executor drains these into
    ``plan.exec_path`` once per run."""
    if getattr(_tls, "dispatch", None) is None:
        _tls.dispatch = {}
    # a query may trace several predicates of the same kernel kind with
    # different outcomes (e.g. one pallas, one fallback): keep them all
    seen = _tls.dispatch.setdefault(kernel, [])
    if choice not in seen:
        seen.append(choice)


def take_dispatch() -> dict:
    """Drain the per-thread dispatch records (kernel -> choice, with
    multiple distinct outcomes joined)."""
    out = getattr(_tls, "dispatch", None) or {}
    _tls.dispatch = {}
    return {k: v[0] if len(v) == 1 else " + ".join(v)
            for k, v in out.items()}


def polygon_edge_tables(poly):
    """Shared edge-table builder for one Polygon (shell + holes).

    Returns ``(f64_tuple, packed_f32)`` where ``f64_tuple`` is
    ``(x1, y1, x2, y2, slope)`` for the host/broadcast paths and
    ``packed_f32`` is the lane-padded [4, Ep] table for the Pallas kernel.
    Horizontal edges get slope denominator 1.0 — the crossing condition is
    false for them so the value is never used."""
    from geomesa_tpu.utils import geometry as geo

    rings = [np.asarray(geo._close_ring(poly.shell), np.float64)] + [
        np.asarray(geo._close_ring(h), np.float64) for h in poly.holes
    ]
    x1 = np.concatenate([r[:-1, 0] for r in rings])
    y1 = np.concatenate([r[:-1, 1] for r in rings])
    x2 = np.concatenate([r[1:, 0] for r in rings])
    y2 = np.concatenate([r[1:, 1] for r in rings])
    dy = np.where(y2 - y1 == 0.0, 1.0, y2 - y1)
    slope = (x2 - x1) / dy
    return (x1, y1, x2, y2, slope), pack_edges(x1, y1, y2, slope)


def pack_edges(x1, y1, y2, slope) -> np.ndarray:
    """Edge table -> [4, Ep] f32, lane-padded to a multiple of 128.

    Padding rows have y1 == y2 == 0 so the crossing condition
    ``(y1 > y) != (y2 > y)`` is identically false — padded edges never
    contribute a crossing."""
    e = len(x1)
    ep = max(128, ((e + 127) // 128) * 128)
    out = np.zeros((4, ep), np.float32)
    out[0, :e] = x1
    out[1, :e] = y1
    out[2, :e] = y2
    out[3, :e] = slope
    return out


def _pip_kernel(x_ref, y_ref, e_ref, out_ref):
    """One block of points vs the full edge table (even-odd crossing parity).

    x/y blocks are [B, 1] (column layout so the [B, E] broadcast puts E on
    the 128-lane axis); the edge table [4, Ep] lives whole in VMEM."""
    import jax.numpy as jnp

    x = x_ref[:]          # [B, 1]
    y = y_ref[:]          # [B, 1]
    x1 = e_ref[0:1, :]    # [1, Ep]
    y1 = e_ref[1:2, :]
    y2 = e_ref[2:3, :]
    slope = e_ref[3:4, :]
    cond = (y1 > y) != (y2 > y)                      # [B, Ep]
    xint = x1 + (y - y1) * slope
    crossings = jnp.sum(
        (cond & (x < xint)).astype(jnp.int32), axis=1, keepdims=True
    )
    out_ref[:] = (crossings % 2).astype(jnp.float32)


def _pip_call(xf, yf, edges, interpret: bool = False):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = xf.shape[0]
    nb = pl.cdiv(n, _BLOCK)
    col = lambda i: (i, 0)  # noqa: E731
    return pl.pallas_call(
        _pip_kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((_BLOCK, 1), col, memory_space=pltpu.VMEM),
            pl.BlockSpec((_BLOCK, 1), col, memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (4, edges.shape[1]), lambda i: (0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec((_BLOCK, 1), col, memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((nb * _BLOCK, 1), jnp.float32),
        interpret=interpret,
    )(xf.reshape(-1, 1), yf.reshape(-1, 1), edges)


def pip_mask(x, y, edges: np.ndarray, interpret: bool = False):
    """Even-odd point-in-polygon mask for one polygon's packed edge table.

    ``x``/``y``: jnp arrays of any shape; returns a bool mask of that shape.
    Points are zero-padded up to the block size — padding results are sliced
    off before reshaping back."""
    import jax.numpy as jnp

    shape = x.shape
    xf = jnp.ravel(x).astype(jnp.float32)
    yf = jnp.ravel(y).astype(jnp.float32)
    n = xf.shape[0]
    pad = (-n) % _BLOCK
    if pad:
        xf = jnp.pad(xf, (0, pad))
        yf = jnp.pad(yf, (0, pad))
    out = _pip_call(xf, yf, jnp.asarray(edges), interpret=interpret)
    return out[:n, 0].astype(bool).reshape(shape)


def pip_mask_sharded(x, y, edges: np.ndarray, mesh, interpret: bool = False):
    """:func:`pip_mask` under a NamedSharding'd [S, L] layout: an inner
    ``shard_map`` over the mesh's 'shard' axis runs the pallas kernel
    per-device on the LOCAL shard block (edge table replicated), so polygon
    fine-filtering keeps the hand kernel at pod scale instead of dropping
    to the [N, E] broadcast path. Axes other than 'shard' (e.g. the
    binspace 'bin' axis) see replicated inputs and outputs."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    spec = P("shard", None)

    def local(xl, yl, el):
        return pip_mask(xl, yl, el, interpret=interpret)

    shard_map = getattr(jax, "shard_map", None)
    if shard_map is None:  # pre-0.4.35 jax: experimental module
        from jax.experimental.shard_map import shard_map
    kw = dict(mesh=mesh, in_specs=(spec, spec, P(None, None)), out_specs=spec)
    try:
        sm = shard_map(local, check_vma=False, **kw)
    except TypeError:  # older jax spells it check_rep
        sm = shard_map(local, check_rep=False, **kw)
    return sm(x, y, jnp.asarray(edges))


def edges_fit(n_edges: int) -> bool:
    return n_edges <= _MAX_EDGES
