"""k-nearest-neighbor kernel (KNearestNeighborSearchProcess analog,
reference geomesa-process/.../query/KNearestNeighborSearchProcess.scala —
there an iterative expanding-radius search; here one masked distance +
``lax.top_k`` pass, which is the TPU-shaped formulation)."""

from __future__ import annotations

import numpy as np

from geomesa_tpu.utils.geometry import EARTH_RADIUS_M


def knn_indices(x, y, mask, qx, qy, k: int, xp=None):
    """Indices (into the flattened [S*L] layout) and distances (meters) of
    the k nearest masked points to (qx, qy). Backend-generic; ``qx``/``qy``
    may be traced scalars — one compiled kernel serves every query point.

    Device path: k iterations of argmin + mask-out. Measured on v5e this
    is ~20x faster steady-state AND ~15x faster to compile than
    ``lax.top_k`` at multi-million-row inputs (top_k: 20s compile,
    1.4s/run at 5M; argmin iteration: 1.3s, 65ms)."""
    if xp is None:
        xp = np
    fx = x.reshape(-1)
    fy = y.reshape(-1)
    fm = mask.reshape(-1)
    rx1, ry1 = xp.radians(fx), xp.radians(fy)
    rx2, ry2 = xp.radians(qx), xp.radians(qy)
    a = (
        xp.sin((ry2 - ry1) / 2) ** 2
        + xp.cos(ry1) * xp.cos(ry2) * xp.sin((rx2 - rx1) / 2) ** 2
    )
    d = 2 * EARTH_RADIUS_M * xp.arcsin(xp.sqrt(xp.clip(a, 0, 1)))
    d = xp.where(fm, d, xp.inf)
    if xp is np:
        idx = np.argsort(d)[:k]
        return idx, d[idx]
    import jax.lax
    import jax.numpy as jnp

    if k > 32:
        # the argmin iteration scales linearly in k (runtime AND unrolled
        # HLO size); big-k requests are better served by the single-pass
        # top_k despite its heavier compile
        neg, idx = jax.lax.top_k(-d, k)
        return idx, -neg
    idxs, vals = [], []
    for _ in range(k):
        i = jnp.argmin(d)
        idxs.append(i)
        vals.append(d[i])
        d = d.at[i].set(jnp.inf)
    return jnp.stack(idxs), jnp.stack(vals)
