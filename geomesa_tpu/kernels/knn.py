"""k-nearest-neighbor kernel (KNearestNeighborSearchProcess analog,
reference geomesa-process/.../query/KNearestNeighborSearchProcess.scala —
there an iterative expanding-radius search; here one masked distance +
``lax.top_k`` pass, which is the TPU-shaped formulation)."""

from __future__ import annotations

import numpy as np

from geomesa_tpu.utils.geometry import EARTH_RADIUS_M


def knn_indices(x, y, mask, qx: float, qy: float, k: int, xp=None):
    """Indices (into the flattened [S*L] layout) and distances (meters) of the
    k nearest masked points to (qx, qy). Backend-generic."""
    if xp is None:
        xp = np
    fx = x.reshape(-1)
    fy = y.reshape(-1)
    fm = mask.reshape(-1)
    rx1, ry1 = xp.radians(fx), xp.radians(fy)
    rx2, ry2 = np.radians(qx), np.radians(qy)
    a = (
        xp.sin((ry2 - ry1) / 2) ** 2
        + xp.cos(ry1) * np.cos(ry2) * xp.sin((rx2 - rx1) / 2) ** 2
    )
    d = 2 * EARTH_RADIUS_M * xp.arcsin(xp.sqrt(xp.clip(a, 0, 1)))
    d = xp.where(fm, d, xp.inf)
    if xp is np:
        idx = np.argsort(d)[:k]
        return idx, d[idx]
    import jax.lax

    neg, idx = jax.lax.top_k(-d, k)
    return idx, -neg
