"""Device scan/aggregation kernels (the reference's server-side iterators,
SURVEY.md §2.4 'Aggregating scans' — reborn as jit kernels over sharded
columns)."""
