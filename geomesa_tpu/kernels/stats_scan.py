"""Exact stats over a scan (StatsScan analog, reference
index/iterators/StatsScan.scala:29-85).

Device-supported sketches run as masked reductions inside the scan jit (their
states are the same fixed-shape arrays the host sketches hold, so per-shard
partials merge by tree-map just like the reference's StatsCombiner). Sketches
without a device formulation yet fall back to host observation over the
gathered matches.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from geomesa_tpu.stats import sketches as sk

#: sketch kinds with a device reduction
DEVICE_KINDS = {"count", "minmax", "histogram", "descriptive", "enumeration", "topk"}


def _leaf_stats(stat: sk.Stat) -> List[sk.Stat]:
    return stat.stats if isinstance(stat, sk.SeqStat) else [stat]


def device_supported(stat: sk.Stat, host_only_cols) -> bool:
    for leaf in _leaf_stats(stat):
        if leaf.kind not in DEVICE_KINDS:
            return False
        if isinstance(leaf, sk.DescriptiveStats):
            attrs = leaf.attributes
        elif getattr(leaf, "attribute", None) is not None:
            attrs = [leaf.attribute]
        else:
            attrs = []
        if any(a in host_only_cols for a in attrs):
            return False
    return True


def batch_supported(stat: sk.Stat) -> bool:
    """May this stat tree ride the query-axis batched kernel
    (docs/SERVING.md "Query-axis batching")? Everything the device
    supports EXCEPT descriptive stats: count/minmax/histogram/enumeration/
    topk reduce in exact integer (or order-independent min/max)
    arithmetic, so a batched member's partial is bit-identical to its
    serial scan regardless of layout; descriptive s1/s2 are f32 sums whose
    bits depend on the reduction layout (the serial path may compact),
    so they keep query-at-a-time execution."""
    return all(
        leaf.kind in (DEVICE_KINDS - {"descriptive"})
        for leaf in _leaf_stats(stat)
    )


def device_update(stat: sk.Stat, cols: Dict, mask, xp, vocab_sizes: Dict[str, int]):
    """Compute the masked partial state arrays for every leaf sketch.

    Returns a list of pytrees (one per leaf) — safe to produce inside jit.
    """
    out = []
    fm = mask.reshape(-1)
    n = fm.sum()
    for leaf in _leaf_stats(stat):
        if leaf.kind == "count":
            out.append({"count": n})
        elif leaf.kind == "minmax":
            if leaf.attribute + "__x" in cols:
                vx = cols[leaf.attribute + "__x"].reshape(-1)
                vy = cols[leaf.attribute + "__y"].reshape(-1)
                out.append({
                    "count": n,
                    "lo": xp.stack([
                        xp.where(fm, vx, xp.inf).min(), xp.where(fm, vy, xp.inf).min()
                    ]),
                    "hi": xp.stack([
                        xp.where(fm, vx, -xp.inf).max(), xp.where(fm, vy, -xp.inf).max()
                    ]),
                })
            else:
                v = cols[leaf.attribute].reshape(-1)
                out.append({
                    "count": n,
                    "lo": xp.where(fm, v, xp.inf).min(),
                    "hi": xp.where(fm, v, -xp.inf).max(),
                })
        elif leaf.kind == "histogram":
            v = cols[leaf.attribute].reshape(-1)
            scaled = (v - leaf.lo) / (leaf.hi - leaf.lo) * leaf.bins
            idx = xp.clip(xp.floor(scaled), 0, leaf.bins - 1).astype(xp.int32)
            if xp is np:
                counts = np.bincount(idx[fm], minlength=leaf.bins)
            else:
                counts = xp.zeros(leaf.bins, xp.int32).at[idx].add(fm.astype(xp.int32))
            out.append({"counts": counts})
        elif leaf.kind == "descriptive":
            mat = xp.stack([cols[a].reshape(-1) for a in leaf.attributes], axis=1)
            w = fm.astype(mat.dtype)[:, None]
            mw = mat * w
            out.append({
                "count": n,
                "s1": mw.sum(axis=0),
                "s2": mw.T @ mat,
            })
        elif leaf.kind in ("enumeration", "topk"):
            v = cols[leaf.attribute].reshape(-1).astype(xp.int32)
            size = vocab_sizes[leaf.attribute]
            idx = xp.clip(v, 0, size - 1)
            valid = fm & (v >= 0)
            if xp is np:
                counts = np.bincount(idx[valid], minlength=size)
            else:
                counts = xp.zeros(size, xp.int32).at[idx].add(valid.astype(xp.int32))
            out.append({"counts": counts})
        else:  # pragma: no cover - guarded by device_supported
            raise ValueError(f"no device kernel for stat {leaf.kind!r}")
    return out


def decode_enum_keys(stat: sk.Stat, dicts) -> sk.Stat:
    """Map enumeration/topk count keys from dictionary codes to their string
    values (the host-observe path counts raw code columns; the device path
    decodes in absorb_partials — results must agree)."""
    for leaf in _leaf_stats(stat):
        if leaf.kind in ("enumeration", "topk"):
            d = dicts.get(leaf.attribute)
            if d is None:
                continue
            enum = leaf if leaf.kind == "enumeration" else leaf._enum
            new = {}
            for k, c in enum.counts.items():
                if isinstance(k, (int, np.integer)):
                    if k < 0:
                        continue  # null codes: dropped (device path parity)
                    key = d.values[k] if k < len(d.values) else int(k)
                else:
                    key = k
                new[key] = new.get(key, 0) + c
            enum.counts = new
    return stat


def absorb_partials(stat: sk.Stat, partials, dicts) -> sk.Stat:
    """Fold device partial states back into host Stat objects."""
    for leaf, p in zip(_leaf_stats(stat), partials):
        p = {k: np.asarray(v) for k, v in p.items()}
        if leaf.kind == "count":
            leaf.count += int(p["count"])
        elif leaf.kind == "minmax":
            cnt = int(p["count"])
            if cnt == 0:
                continue
            lo, hi = p["lo"], p["hi"]
            other = sk.MinMax(
                leaf.attribute,
                lo.tolist() if lo.ndim else float(lo),
                hi.tolist() if hi.ndim else float(hi),
                cnt,
            )
            leaf.merge(other)
        elif leaf.kind == "histogram":
            leaf.counts += p["counts"].astype(np.int64)
        elif leaf.kind == "descriptive":
            leaf.count += int(p["count"])
            leaf.s1 += p["s1"].astype(np.float64)
            leaf.s2 += p["s2"].astype(np.float64)
        elif leaf.kind in ("enumeration", "topk"):
            counts = p["counts"].astype(np.int64)
            d = dicts.get(leaf.attribute)
            enum = leaf if leaf.kind == "enumeration" else leaf._enum
            for code, c in enumerate(counts.tolist()):
                if c:
                    key = d.values[code] if d is not None else code
                    enum.counts[key] = enum.counts.get(key, 0) + c
    return stat
