"""Scan-mask construction on device.

The coarse key filter (reference Z3Filter/Z2Filter on raw key bytes,
index/filters/Z3Filter.scala:18-62) becomes: per-shard row windows (resolved
host-side by searchsorted against the sorted key columns) turned into a
boolean mask via a +1/-1 scatter and cumsum — O(S*L), no N×K blowup — ANDed
with the compiled fine predicate and the padding-validity mask.
"""

from __future__ import annotations

import numpy as np


# Below this many windows per shard, an unrolled broadcast-compare mask is
# pure fused elementwise work; above it, the scatter+cumsum form wins (its
# cost is O(L) regardless of K).
_COMPARE_MASK_MAX_K = 16


def window_mask(starts, ends, counts, L: int):
    """[S,K] local-row windows + [S] shard row counts -> [S,L] bool mask.

    Windows within a shard must be non-overlapping (planner merges them).
    Padded windows are (0, 0) and contribute nothing.
    """
    import jax
    import jax.numpy as jnp

    iota = jnp.arange(L, dtype=jnp.int32)
    K = starts.shape[1]
    if K == 0:
        return jnp.zeros((starts.shape[0], L), bool)
    if K <= _COMPARE_MASK_MAX_K:
        # K unrolled [S,L] compares fuse into the consuming kernel — no
        # [S,L+1] scatter/cumsum materialization riding HBM
        wm = None
        for k in range(K):
            m = (iota[None, :] >= starts[:, k, None]) & (
                iota[None, :] < ends[:, k, None]
            )
            wm = m if wm is None else (wm | m)
    else:
        def one(s, e):
            d = jnp.zeros(L + 1, jnp.int32)
            d = d.at[s].add(1)
            d = d.at[e].add(-1)
            return jnp.cumsum(d)[:L] > 0

        wm = jax.vmap(one)(starts, ends)
    return wm & (iota[None, :] < counts[:, None])


def window_mask_batch(starts, ends, counts, L: int, member: int):
    """One member's [S, L] mask out of query-axis-stacked [M, S, K] window
    arrays (docs/SERVING.md "Query-axis batching"). ``member`` is a trace-
    time python int — the batched kernel unrolls its member loop so each
    member's mask is op-for-op the serial :func:`window_mask`, which is
    what makes the de-interleaved results bit-identical to serial
    execution. Members padded to the batch bucket carry all-(0, 0)
    windows and mask to False everywhere."""
    return window_mask(starts[member], ends[member], counts, L)


def window_mask_np(starts, ends, counts, L: int) -> np.ndarray:
    """Host twin of :func:`window_mask` (numpy)."""
    S = starts.shape[0]
    out = np.zeros((S, L), dtype=bool)
    for s in range(S):
        for a, b in zip(starts[s], ends[s]):
            if b > a:
                out[s, a:b] = True
        out[s, counts[s]:] = False
    return out


def sampling_mask_by_key(mask: np.ndarray, n: int, key_codes: np.ndarray) -> np.ndarray:
    """Keep every nth matched row *per key value* (SamplingIterator's
    per-key mode): deterministic counter per key, host-side (numpy).

    ``key_codes``: int codes aligned with ``mask`` (same shape)."""
    flat = mask.reshape(-1)
    keys = np.asarray(key_codes).reshape(-1)
    out = np.zeros_like(flat)
    idx = np.nonzero(flat)[0]
    if idx.size == 0:
        return out.reshape(mask.shape)
    k = keys[idx]
    # running index within key: stable sort by key, position - first-position
    order = np.argsort(k, kind="stable")
    ks = k[order]
    first = np.concatenate(([True], ks[1:] != ks[:-1]))
    group_start = np.maximum.accumulate(np.where(first, np.arange(ks.size), 0))
    within = np.arange(ks.size) - group_start
    keep_sorted = (within % n) == 0
    keep = np.zeros(ks.size, bool)
    keep[order] = keep_sorted
    out[idx[keep]] = True
    return out.reshape(mask.shape)


def sampling_mask_by_key_device(mask, n: int, codes, vocab_size: int, xp):
    """Device twin of :func:`sampling_mask_by_key` for dictionary-coded
    int32 key columns with a known (small) vocabulary: same deterministic
    per-key 1-in-n counter. Sort-free by design — device sort compiles
    pathologically on this TPU toolchain — instead one cumsum per code
    value gives each row its rank within its key (vocab_size cumsums, each
    bandwidth-bound; vocabularies here are query sample keys, typically
    tens of values)."""
    flat = mask.reshape(-1)
    codes = codes.reshape(-1)
    keep = xp.zeros(flat.shape[0], dtype=bool)
    for v in range(-1, vocab_size):  # -1 = null key, its own group (host parity)
        mv = flat & (codes == v)
        rank = xp.cumsum(mv.astype(xp.int32)) - 1
        keep = keep | (mv & ((rank % n) == 0))
    return keep.reshape(mask.shape)


def sampling_mask(mask, n: int, xp):
    """Keep ~1-in-n of the masked rows (SamplingIterator analog): deterministic
    modulo on the running match index so the sample is stable."""
    flat = mask.reshape(-1)
    seq = xp.cumsum(flat.astype(xp.int32)) - 1
    keep = (seq % n) == 0
    return (flat & keep).reshape(mask.shape)


def bucket_of(keys, n_buckets: int, xp):
    """Deterministic hash bucket for int keys (both backends produce the
    same buckets): a 32-bit splitmix-style mixer, masked to n_buckets
    (power of two). Null codes (-1) map to their own stable bucket."""
    h = xp.asarray(keys).astype(xp.uint32)
    h = (h ^ (h >> 16)) * xp.uint32(0x7FEB352D)
    h = (h ^ (h >> 15)) * xp.uint32(0x846CA68B)
    h = h ^ (h >> 16)
    return (h & xp.uint32(n_buckets - 1)).astype(xp.int32)


def sampling_mask_by_key_hash(mask, n: int, keys, n_buckets: int, xp):
    """Per-key sampling for UNBOUNDED key spaces (int attributes,
    dictionary vocabularies > the per-code kernel's gate): keys hash into
    ``n_buckets`` groups, each group keeps a deterministic 1-in-n of its
    matches in row order. Keys sharing a bucket share a counter — an
    approximation of the reference SamplingIterator's exact per-key
    counter, traded for a device kernel that is ``n_buckets`` cumsum
    passes instead of one pass per distinct key. Identical results on
    both backends (the host twin runs the same code with xp=numpy)."""
    flat = mask.reshape(-1)
    b = bucket_of(xp.asarray(keys).reshape(-1), n_buckets, xp)
    keep = xp.zeros(flat.shape[0], dtype=bool)
    for v in range(n_buckets):
        mv = flat & (b == v)
        rank = xp.cumsum(mv.astype(xp.int32)) - 1
        keep = keep | (mv & ((rank % n) == 0))
    return keep.reshape(mask.shape)
