"""Point-in-polygon join kernel (spatial join pushdown).

The device analog of the reference's Spark spatial join
(GeoMesaJoinRelation + grid partitioning, geomesa-spark-sql/.../SQLRules.scala
and RelationUtils; BASELINE config #4): every (point, polygon-edge) crossing
is computed in one vectorized pass, parity is reduced per polygon with a
segment-sum, and each point is assigned the first containing polygon.

Edge buffers come from ``geomesa_tpu.utils.geometry.polygon_edge_buffers``:
padded degenerate edges (at 1e30) produce no crossings, so static shapes hold
across polygon sets — the ragged-polygon strategy from SURVEY.md §7 "hard
parts" (a).
"""

from __future__ import annotations

import numpy as np


def crossing_matrix(px, py, ex1, ey1, ex2, ey2, xp):
    """[N, E] even-odd ray-crossing indicators for points against edges.

    Standard upward ray: edge (p1, p2) crosses the horizontal ray from
    (x, y) iff (y1 > y) != (y2 > y) and x < x-intersect at y.
    """
    px = px[:, None]
    py = py[:, None]
    y1, y2 = ey1[None, :], ey2[None, :]
    x1, x2 = ex1[None, :], ex2[None, :]
    straddle = (y1 > py) != (y2 > py)
    denom = y2 - y1
    # guard padded/degenerate edges (denom == 0 never straddles anyway)
    denom = xp.where(denom == 0, 1.0, denom)
    xint = x1 + (py - y1) * (x2 - x1) / denom
    return straddle & (px < xint)


def pip_assign(px, py, mask, edges, xp):
    """Assign each masked point its first containing polygon id, else -1.

    ``edges``: dict with float32 arrays x1/y1/x2/y2 [E], int32 poly_id [E],
    and n_polys (static python int). Returns int32 [N].
    """
    P = int(edges["n_polys"])
    cross = crossing_matrix(
        px.reshape(-1), py.reshape(-1),
        edges["x1"], edges["y1"], edges["x2"], edges["y2"], xp,
    ).astype(xp.int32)
    if xp is np:
        counts = np.zeros((P, cross.shape[0]), np.int32)
        np.add.at(counts, edges["poly_id"], cross.T)
    else:
        import jax

        counts = jax.ops.segment_sum(
            cross.T, edges["poly_id"], num_segments=P
        )  # [P, N]
    inside = (counts % 2) == 1  # [P, N]
    first = xp.argmax(inside, axis=0).astype(xp.int32)
    any_hit = inside.any(axis=0)
    assign = xp.where(any_hit, first, -1)
    return xp.where(mask.reshape(-1), assign, -1)


#: classify_cells codes — a cell wholly outside the polygon, wholly inside
#: it (with margin to spare), or touching its boundary
CELL_OUTSIDE, CELL_INTERIOR, CELL_BOUNDARY = 0, 1, 2


def _poly_edges(g) -> "list[np.ndarray]":
    """Per-polygon [E, 4] f64 ring segments (shell + holes) of a
    (multi)polygon literal — the edge tables the crossing test runs on."""
    from geomesa_tpu.utils import geometry as geo

    polys = g.polygons if isinstance(g, geo.MultiPolygon) else (g,)
    out = []
    for p in polys:
        segs = []
        for r in p.rings():
            segs.append(np.concatenate([r[:-1], r[1:]], axis=1))
        out.append(np.concatenate(segs, axis=0).astype(np.float64))
    return out


def classify_cells(boxes: np.ndarray, g, margin: float) -> np.ndarray:
    """Classify axis-aligned cells against a (multi)polygon literal:
    int8 [C] of CELL_OUTSIDE / CELL_INTERIOR / CELL_BOUNDARY for ``boxes``
    [C, 4] = (xmin, ymin, xmax, ymax), f64.

    Every box is inflated by ``margin`` before testing, so INTERIOR and
    OUTSIDE verdicts hold for every point the scan kernel could place in
    the cell even under its f32 edge arithmetic (the ~1e-4-deg near-edge
    uncertainty documented at filter/compile._pip_fn) — near-edge rows
    always land in BOUNDARY cells, which the caller scans through the
    *same* polygon kernel as an undecomposed query, so the decomposed
    total is bit-identical by construction (docs/CACHE.md).

    The segment-vs-box test is an exact SAT (box axes + the segment's
    normal); insidedness of edge-free cells reuses :func:`crossing_matrix`
    on the cell centers, per polygon part, matching the scan kernel's
    per-polygon even-odd OR semantics for multipolygons."""
    boxes = np.asarray(boxes, np.float64)
    C = len(boxes)
    x0 = boxes[:, 0] - margin
    y0 = boxes[:, 1] - margin
    x1 = boxes[:, 2] + margin
    y1 = boxes[:, 3] + margin
    codes = np.zeros(C, np.int8)
    inside = np.zeros(C, bool)
    on_boundary = np.zeros(C, bool)
    cx = (x0 + x1) * 0.5
    cy = (y0 + y1) * 0.5
    for E in _poly_edges(g):
        ex1, ey1, ex2, ey2 = E[:, 0], E[:, 1], E[:, 2], E[:, 3]
        # SAT axis 1+2 (the box normals): segment bbox vs inflated box
        overlap = (
            (np.minimum(ex1, ex2)[None, :] <= x1[:, None])
            & (np.maximum(ex1, ex2)[None, :] >= x0[:, None])
            & (np.minimum(ey1, ey2)[None, :] <= y1[:, None])
            & (np.maximum(ey1, ey2)[None, :] >= y0[:, None])
        )
        # SAT axis 3 (the segment normal): all four box corners strictly
        # on one side of the segment's line => separated
        dx = (ex2 - ex1)[None, :]
        dy = (ey2 - ey1)[None, :]
        cross = [
            dx * (by[:, None] - ey1[None, :]) - dy * (bx[:, None] - ex1[None, :])
            for bx, by in ((x0, y0), (x1, y0), (x0, y1), (x1, y1))
        ]
        straddle = ~(
            np.all([c > 0 for c in cross], axis=0)
            | np.all([c < 0 for c in cross], axis=0)
        )
        on_boundary |= (overlap & straddle).any(axis=1)
        # even-odd insidedness of the cell center for THIS polygon part;
        # only meaningful for edge-free cells (the caller's margin makes
        # the whole cell share the center's verdict)
        crossings = crossing_matrix(cx, cy, ex1, ey1, ex2, ey2, np)
        inside |= (crossings.sum(axis=1) % 2) == 1
    codes[inside] = CELL_INTERIOR
    codes[on_boundary] = CELL_BOUNDARY
    return codes


# ---------------------------------------------------------------------------
# Pairwise point-point join predicates (docs/JOIN.md): the exact test the
# co-partitioned build/probe runs on same-cell (+ boundary-strip) candidate
# pairs. One function serves BOTH the device kernel (xp = jax.numpy) and
# the numpy brute-force reference, in the SAME f32 arithmetic and op
# order, so the co-partitioned join is bit-identical to the N*M reference
# by construction — the cells only decide WHICH pairs are tested, never
# how a tested pair decides.
# ---------------------------------------------------------------------------

#: pairwise predicate kinds
JOIN_BBOX, JOIN_DWITHIN = "bbox", "dwithin"
JOIN_DWITHIN_METERS = "dwithin_meters"

#: mean earth radius (meters) — the haversine sphere every
#: ``dwithin_meters`` computation shares (IUGG mean radius R1)
EARTH_RADIUS_M = 6371008.8


def unit_vectors(lon, lat):
    """Points as f32 unit-sphere 3-vectors ``(ux, uy, uz)``. The trig
    runs ONCE, on the host, in f64 (then rounds to f32) — both the
    device kernel and the numpy brute-force reference consume these SAME
    f32 arrays, so the ``dwithin_meters`` predicate stays bit-identical
    by construction even though libm/XLA trig differ in the last ulp:
    the pairwise test itself (:func:`pair_mask`) is pure exactly-rounded
    arithmetic (subtract/multiply/add/compare) on these vectors."""
    lam = np.deg2rad(np.asarray(lon, np.float64))
    phi = np.deg2rad(np.asarray(lat, np.float64))
    cphi = np.cos(phi)
    return (
        (cphi * np.cos(lam)).astype(np.float32),
        (cphi * np.sin(lam)).astype(np.float32),
        np.sin(phi).astype(np.float32),
    )


def pair_params(predicate: str, distance=None, dx=None, dy=None):
    """Canonical f32 parameter pair ``(p0, p1)`` for one predicate:
    ``bbox`` -> (dx, dy) half-widths; ``dwithin`` -> (d^2, 0) with the
    square computed in f32 on the host, so device and reference compare
    against the identical value; ``dwithin_meters`` -> (c^2, 0) where
    ``c = 2 sin(d / 2R)`` is the unit-sphere CHORD length of great-circle
    distance ``d`` meters — ``|u_l - u_r|^2 <= c^2`` is exactly the
    haversine ``<= d`` verdict, with the one trig evaluation on the host
    in f64 (rounded to f32 once, shared by kernel and reference)."""
    if predicate == JOIN_BBOX:
        if dx is None or dy is None:
            raise ValueError("bbox join needs dx and dy half-widths")
        return np.float32(dx), np.float32(dy)
    if predicate == JOIN_DWITHIN:
        if distance is None:
            raise ValueError("dwithin join needs a distance")
        d = np.float32(distance)
        return np.float32(d * d), np.float32(0.0)
    if predicate == JOIN_DWITHIN_METERS:
        if distance is None:
            raise ValueError("dwithin_meters join needs a distance "
                             "(meters)")
        half = min(float(distance) / (2.0 * EARTH_RADIUS_M), np.pi / 2)
        c = np.float32(2.0 * np.sin(half))  # chord of the antipode = 2
        return np.float32(c * c), np.float32(0.0)
    raise ValueError(f"unknown join predicate {predicate!r} "
                     f"(have: {JOIN_BBOX}, {JOIN_DWITHIN}, "
                     f"{JOIN_DWITHIN_METERS})")


def pair_mask(lx, ly, rx, ry, predicate: str, p0, p1, xp,
              lz=None, rz=None):
    """Pairwise predicate verdicts under broadcasting (f32, inclusive
    edges). ``bbox``: the two points' (p0, p1)-half-width envelopes
    intersect, i.e. |lx-rx| <= p0 and |ly-ry| <= p1. ``dwithin``: planar
    degree distance with p0 = d^2 (the sum-of-squares form keeps one
    compare and no sqrt — exact for the <= verdict in f32 given both
    sides compute it identically, which they do: this function IS both
    sides). ``dwithin_meters``: haversine meters via the unit-sphere
    chord — operands are :func:`unit_vectors` components (x, y, z per
    side), p0 = chord^2 from :func:`pair_params`; wholly trig-free here,
    so it wraps the antimeridian and the poles for free and stays
    bit-identical between numpy and the device kernel."""
    ddx = lx.astype(xp.float32) - rx.astype(xp.float32)
    ddy = ly.astype(xp.float32) - ry.astype(xp.float32)
    if predicate == JOIN_BBOX:
        return (xp.abs(ddx) <= p0) & (xp.abs(ddy) <= p1)
    if predicate == JOIN_DWITHIN:
        return ddx * ddx + ddy * ddy <= p0
    if predicate == JOIN_DWITHIN_METERS:
        if lz is None or rz is None:
            raise ValueError("dwithin_meters needs unit-vector z "
                             "operands (lz, rz)")
        ddz = lz.astype(xp.float32) - rz.astype(xp.float32)
        return ddx * ddx + ddy * ddy + ddz * ddz <= p0
    raise ValueError(f"unknown join predicate {predicate!r}")


def brute_force_pairs(lx, ly, rx, ry, predicate: str, p0, p1,
                      chunk: int = 4096, lz=None, rz=None):
    """The naive N*M reference (numpy, chunked): matched (left, right)
    row-index pairs in row-major order — int64 [K, 2]. The bench/CI
    bit-identity gates compare the co-partitioned device join against
    exactly this. For ``dwithin_meters``, pass the sides'
    :func:`unit_vectors` components as (lx, ly, lz) / (rx, ry, rz)."""
    lx = np.asarray(lx, np.float32)
    ly = np.asarray(ly, np.float32)
    rx = np.asarray(rx, np.float32)
    ry = np.asarray(ry, np.float32)
    lz = None if lz is None else np.asarray(lz, np.float32)
    rz = None if rz is None else np.asarray(rz, np.float32)
    out = []
    for lo in range(0, len(lx), chunk):
        hi = min(lo + chunk, len(lx))
        m = pair_mask(
            lx[lo:hi, None], ly[lo:hi, None], rx[None, :], ry[None, :],
            predicate, p0, p1, np,
            lz=None if lz is None else lz[lo:hi, None],
            rz=None if rz is None else rz[None, :],
        )
        li, rj = np.nonzero(m)
        if len(li):
            out.append(np.stack([li.astype(np.int64) + lo,
                                 rj.astype(np.int64)], axis=1))
    if not out:
        return np.zeros((0, 2), np.int64)
    return np.concatenate(out, axis=0)


# ---------------------------------------------------------------------------
# Polygon-dataset join predicates (docs/JOIN.md §7): one side of the join is
# a POLYGON schema. Same contract as pair_mask: ONE function serves the
# device kernel (xp = jax.numpy) and the numpy N*M reference, in the same
# f32 arithmetic and op order, so the cell-classified polygon join is
# bit-identical to the reference by construction — classify_cells only
# decides WHICH (point, row) pairs reach the kernel (boundary cells) or
# match wholesale (interior cells, with CLASSIFY_MARGIN to spare), never
# how a tested pair decides.
# ---------------------------------------------------------------------------

#: polygon-side predicate kinds: ``pip`` — the point's even-odd crossing
#: parity against the row's (multi)polygon (holes ride their polygon's
#: parity; multipolygon parts OR) — and ``poly_bbox`` — the point lies in
#: the row's bounds (inclusive edges)
JOIN_PIP, JOIN_POLY_BBOX = "pip", "poly_bbox"
POLYGON_PREDICATES = (JOIN_PIP, JOIN_POLY_BBOX)


def polygon_tables(geoms, pad_edges=None, pad_parts=None, pad_rows=None):
    """Flattened f32 tables for a polygon join side (one (multi)polygon
    per right row): ``x1/y1/x2/y2`` [E] ring segments (shells AND holes —
    parity per part handles holes), int32 ``part_id`` [E] (flat part per
    edge; a part is one Polygon with its holes), int32 ``part_row`` [Pf]
    (right row per flat part), f32 ``boxes`` [R, 4] per-row bounds, plus
    the static counts. Optional pow2 padding for the bucketed device
    kernel: padded edges are degenerate (1e30 — never straddle), padded
    parts map to row 0 with no edges (parity never true), padded rows
    carry impossible boxes (min > max)."""
    from geomesa_tpu.utils import geometry as geo

    x1s, y1s, x2s, y2s, pids = [], [], [], [], []
    part_rows: "list[int]" = []
    boxes = []
    for j, g in enumerate(geoms):
        boxes.append(g.bounds())
        polys = g.polygons if isinstance(g, geo.MultiPolygon) else (g,)
        for p in polys:
            pid = len(part_rows)
            part_rows.append(j)
            for r in p.rings():
                x1s.append(r[:-1, 0]); y1s.append(r[:-1, 1])
                x2s.append(r[1:, 0]); y2s.append(r[1:, 1])
                pids.append(np.full(len(r) - 1, pid, np.int32))
    t = {
        "x1": np.concatenate(x1s).astype(np.float32),
        "y1": np.concatenate(y1s).astype(np.float32),
        "x2": np.concatenate(x2s).astype(np.float32),
        "y2": np.concatenate(y2s).astype(np.float32),
        "part_id": np.concatenate(pids),
        "part_row": np.asarray(part_rows, np.int32),
        "boxes": np.asarray(boxes, np.float32),
        "n_edges": len(np.concatenate(pids)),
        "n_parts": len(part_rows),
        "n_rows": len(geoms),
    }
    e, pf, r = t["n_edges"], t["n_parts"], t["n_rows"]
    ep = max(pad_edges or e, e)
    pp = max(pad_parts or pf, pf)
    rp = max(pad_rows or r, r)
    if ep > e:
        for k in ("x1", "y1", "x2", "y2"):
            t[k] = np.concatenate([t[k], np.full(ep - e, 1e30, np.float32)])
        t["part_id"] = np.concatenate(
            [t["part_id"], np.zeros(ep - e, np.int32)])
    if pp > pf:
        t["part_row"] = np.concatenate(
            [t["part_row"], np.zeros(pp - pf, np.int32)])
    if rp > r:
        dead = np.empty((rp - r, 4), np.float32)
        dead[:, :2], dead[:, 2:] = 1e30, -1e30
        t["boxes"] = np.concatenate([t["boxes"], dead])
    t["n_parts_padded"], t["n_rows_padded"] = pp, rp
    return t


def polygon_mask(px, py, t, predicate: str, xp):
    """[N, R] polygon-join verdict matrix (f32). ``pip``: per-part
    even-odd crossing parity via :func:`crossing_matrix`, OR over each
    row's parts (the multipolygon semantic :func:`classify_cells`
    matches; a polygon's holes share its part, so parity subtracts them).
    ``poly_bbox``: inclusive-edge containment in the row's f32 bounds.
    Pure exactly-rounded f32 arithmetic on the shared tables — the same
    function IS the brute-force reference."""
    px = px.astype(xp.float32)
    py = py.astype(xp.float32)
    if predicate == JOIN_POLY_BBOX:
        b = t["boxes"]
        return (
            (px[:, None] >= b[None, :, 0]) & (py[:, None] >= b[None, :, 1])
            & (px[:, None] <= b[None, :, 2]) & (py[:, None] <= b[None, :, 3])
        )
    if predicate != JOIN_PIP:
        raise ValueError(f"unknown polygon join predicate {predicate!r}")
    cross = crossing_matrix(
        px, py, t["x1"], t["y1"], t["x2"], t["y2"], xp
    ).astype(xp.int32)  # [N, E]
    P = int(t["n_parts_padded"])
    R = int(t["n_rows_padded"])
    if xp is np:
        counts = np.zeros((P, cross.shape[0]), np.int32)
        np.add.at(counts, t["part_id"], cross.T)
        inside = (counts % 2) == 1  # [P, N]
        hits = np.zeros((R, cross.shape[0]), np.int32)
        np.add.at(hits, t["part_row"], inside.astype(np.int32))
    else:
        import jax

        counts = jax.ops.segment_sum(cross.T, t["part_id"], num_segments=P)
        inside = (counts % 2) == 1
        hits = jax.ops.segment_sum(
            inside.astype(xp.int32), t["part_row"], num_segments=R
        )
    return (hits > 0).T  # [N, R]


def polygon_brute_force(px, py, geoms, predicate: str, chunk: int = 2048):
    """The naive N*M polygon-join reference (numpy, chunked): matched
    (point, right-row) pairs in row-major order — int64 [K, 2]. The
    bench/CI bit-identity gates compare the cell-classified polygon join
    against exactly this (same :func:`polygon_mask`, same tables)."""
    t = polygon_tables(geoms)
    px = np.asarray(px, np.float32)
    py = np.asarray(py, np.float32)
    out = []
    for lo in range(0, len(px), chunk):
        hi = min(lo + chunk, len(px))
        m = polygon_mask(px[lo:hi], py[lo:hi], t, predicate, np)
        li, rj = np.nonzero(m)
        if len(li):
            out.append(np.stack([li.astype(np.int64) + lo,
                                 rj.astype(np.int64)], axis=1))
    if not out:
        return np.zeros((0, 2), np.int64)
    return np.concatenate(out, axis=0)


def pip_counts(px, py, mask, edges, weights, xp):
    """Per-polygon masked point (or weight) totals: float32 [P]."""
    P = int(edges["n_polys"])
    assign = pip_assign(px, py, mask, edges, xp)
    w = (
        weights.reshape(-1).astype(xp.float32)
        if weights is not None
        else xp.ones_like(assign, dtype=xp.float32)
    )
    w = xp.where(assign >= 0, w, 0.0)
    seg = xp.clip(assign, 0, P - 1)
    if xp is np:
        out = np.zeros(P, np.float32)
        np.add.at(out, seg, w)
        return out
    import jax

    return jax.ops.segment_sum(w, seg, num_segments=P)
