"""Point-in-polygon join kernel (spatial join pushdown).

The device analog of the reference's Spark spatial join
(GeoMesaJoinRelation + grid partitioning, geomesa-spark-sql/.../SQLRules.scala
and RelationUtils; BASELINE config #4): every (point, polygon-edge) crossing
is computed in one vectorized pass, parity is reduced per polygon with a
segment-sum, and each point is assigned the first containing polygon.

Edge buffers come from ``geomesa_tpu.utils.geometry.polygon_edge_buffers``:
padded degenerate edges (at 1e30) produce no crossings, so static shapes hold
across polygon sets — the ragged-polygon strategy from SURVEY.md §7 "hard
parts" (a).
"""

from __future__ import annotations

import numpy as np


def crossing_matrix(px, py, ex1, ey1, ex2, ey2, xp):
    """[N, E] even-odd ray-crossing indicators for points against edges.

    Standard upward ray: edge (p1, p2) crosses the horizontal ray from
    (x, y) iff (y1 > y) != (y2 > y) and x < x-intersect at y.
    """
    px = px[:, None]
    py = py[:, None]
    y1, y2 = ey1[None, :], ey2[None, :]
    x1, x2 = ex1[None, :], ex2[None, :]
    straddle = (y1 > py) != (y2 > py)
    denom = y2 - y1
    # guard padded/degenerate edges (denom == 0 never straddles anyway)
    denom = xp.where(denom == 0, 1.0, denom)
    xint = x1 + (py - y1) * (x2 - x1) / denom
    return straddle & (px < xint)


def pip_assign(px, py, mask, edges, xp):
    """Assign each masked point its first containing polygon id, else -1.

    ``edges``: dict with float32 arrays x1/y1/x2/y2 [E], int32 poly_id [E],
    and n_polys (static python int). Returns int32 [N].
    """
    P = int(edges["n_polys"])
    cross = crossing_matrix(
        px.reshape(-1), py.reshape(-1),
        edges["x1"], edges["y1"], edges["x2"], edges["y2"], xp,
    ).astype(xp.int32)
    if xp is np:
        counts = np.zeros((P, cross.shape[0]), np.int32)
        np.add.at(counts, edges["poly_id"], cross.T)
    else:
        import jax

        counts = jax.ops.segment_sum(
            cross.T, edges["poly_id"], num_segments=P
        )  # [P, N]
    inside = (counts % 2) == 1  # [P, N]
    first = xp.argmax(inside, axis=0).astype(xp.int32)
    any_hit = inside.any(axis=0)
    assign = xp.where(any_hit, first, -1)
    return xp.where(mask.reshape(-1), assign, -1)


def pip_counts(px, py, mask, edges, weights, xp):
    """Per-polygon masked point (or weight) totals: float32 [P]."""
    P = int(edges["n_polys"])
    assign = pip_assign(px, py, mask, edges, xp)
    w = (
        weights.reshape(-1).astype(xp.float32)
        if weights is not None
        else xp.ones_like(assign, dtype=xp.float32)
    )
    w = xp.where(assign >= 0, w, 0.0)
    seg = xp.clip(assign, 0, P - 1)
    if xp is np:
        out = np.zeros(P, np.float32)
        np.add.at(out, seg, w)
        return out
    import jax

    return jax.ops.segment_sum(w, seg, num_segments=P)
