"""Density (heatmap) as MXU matmuls — the scatter-free device path.

TPU scatter costs ~6.7 ns per touched row (docs/SCALE.md cost model), so
the DensityScan analog over millions of window rows is scatter-bound. This
kernel reformulates the 2D histogram as batched one-hot matmuls: the grid
splits into (TY, TX) tiles, window rows split into the compacted scan's
B-row chunks, and each (chunk, tile) PAIR contributes

    tile[y, x] += sum_b onehot(py_b == y) * w_b * onehot(px_b == x)
               == (onehot_y * w)^T @ onehot_x        -- one [TY,B]@[B,TX]

which is pure MXU work. The pair list is small because chunks are runs of
the z-sorted order: a B-row run spans a small spatial box (computed on the
host from the chunk's own sorted keys via :func:`_chunk_boxes` — no
device round-trip), so each chunk overlaps a few tiles, not all of them.
Reference parity: DensityScan.scala:29-136 (per-row RenderingGrid scatter
in tablet servers); same sparse-grid result, device-shaped execution.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

def tile_shape():
    """Grid tile shape (TY, TX) in cells: the measured optimum on v5e for
    fine-cover chunk boxes (~30-70 cells) — smaller tiles raise
    pairs-per-chunk, larger tiles raise one-hot operand and tile-tensor
    traffic. Tunable via geomesa.mxu.tile.y/x."""
    from geomesa_tpu import config

    return (config.MXU_TILE_Y.to_int() or 32,
            config.MXU_TILE_X.to_int() or 64)


#: pair-batch row budget: PB pairs x B rows ~ 512Ki rows per matmul batch
_PAIR_ROWS = 512 * 1024


def pair_batch(B: int) -> int:
    return max(8, min(4096, _PAIR_ROWS // max(B, 1)))


def ladder8(n: int) -> int:
    """Geometric (~1.25x) bucket ladder on multiples of 8 — the shared
    shape-bucketing rule for compact chunk counts and MXU pair padding
    (both feed compiled kernel shapes; one rule keeps them aligned)."""
    b = 8
    while b < n:
        b = -(-int(b * 1.25) // 8) * 8
    return b


def _chunk_boxes(compact: Dict, table, col: str, dims: int, shift: int,
                 box_cache: Optional[Dict], version):
    """Exact per-chunk normalized-index boxes from the sorted key column:
    deinterleave every window row's (quantized) key, segment-min/max per
    chunk via ``reduceat``. Exact up to key quantization (each quantized
    cell contributes its full extent), which end-point prefix cubes are
    not: a scan window is a gap-union of cover ranges, so a chunk's
    end-point cube can span the whole union while its rows sit in two
    small clusters. Cached per (windows, store version) — ~ms for millions
    of rows, amortized across grids and repeat queries."""
    ckey = (compact["whash"], compact["B"], col, table.n, version)
    if box_cache is not None:
        hit = box_cache.get(ckey)
        if hit is not None:
            return hit
    from geomesa_tpu.curves.zorder import deinterleave2, deinterleave3

    key = table.key_columns[col]
    L = table.shard_len
    cstart, lo, valid = compact["cstart"], compact["lo"], compact["valid"]
    act = valid > 0
    cs = (cstart + lo).astype(np.int64)
    s_of = cs // L
    g0 = table.shard_bounds[s_of] + (cs % L)
    segs = [
        key[a:a + int(v)]
        for a, v in zip(g0[act], valid[act])
    ]
    if not segs:
        return None
    cat = np.concatenate(segs).astype(np.uint64)
    sh = np.uint64(shift)
    deinter = deinterleave2 if dims == 2 else deinterleave3
    lo_parts = deinter(cat << sh)
    hi_parts = deinter(((cat + np.uint64(1)) << sh) - np.uint64(1))
    starts = np.concatenate(([0], np.cumsum(valid[act].astype(np.int64))[:-1]))
    n_chunk = len(valid)
    out = []
    for d in range(2):  # x, y only (z3's t dimension is irrelevant here)
        lo_d = np.minimum.reduceat(lo_parts[d], starts)
        hi_d = np.maximum.reduceat(hi_parts[d], starts)
        full_lo = np.zeros(n_chunk, np.uint64)
        full_hi = np.zeros(n_chunk, np.uint64)
        full_lo[act] = lo_d
        full_hi[act] = hi_d
        out.append((full_lo, full_hi))
    if box_cache is not None:
        if len(box_cache) >= 64:
            box_cache.clear()
        box_cache[ckey] = out
    return out


def pair_candidates(
    compact: Dict, table, keyspace, bbox, width: int, height: int,
    TY: int, TX: int, box_cache: Optional[Dict] = None, version=None,
) -> Optional[Dict]:
    """Host-side (chunk, tile) candidate list for the compacted scan layout.

    Chunk spatial boxes come from the chunk's own sorted keys
    (:func:`_chunk_boxes`) — conservative supersets (quantized keys widen
    the box by one quantization cell, and the device's f32 px/py rounding
    is covered by a one-cell pad), which is all correctness needs: rows
    outside a pair's tile simply match no one-hot column. Returns None
    when the index has no morton key column (attr/id/xz tables fall back
    to the scatter path). Shared by the XLA-einsum pair kernel below and
    the pallas grouped kernel (kernels/density_pallas.py).
    """
    kind = getattr(keyspace, "kind", None)
    if kind == "z3":
        col, dims = "__z3", 3
        sfc = keyspace.sfc
    elif kind == "z2":
        col, dims = "__z2", 2
        sfc = keyspace.sfc
    else:
        return None
    key = table.key_columns.get(col)
    if key is None:
        return None
    shift = 0
    if table.key_shifts is not None:
        shift = int(table.key_shifts.get(col, 0))
    lon, lat = sfc.lon, sfc.lat
    bits = lon.bits

    valid = compact["valid"]
    act = valid > 0
    boxes = _chunk_boxes(compact, table, col, dims, shift, box_cache, version)
    if boxes is None:
        return None
    (x0, x1), (y0, y1) = boxes

    xmin, ymin, xmax, ymax = (float(v) for v in bbox)
    cellw = (xmax - xmin) / width
    cellh = (ymax - ymin) / height
    scale_x = (lon.hi - lon.lo) / (1 << bits)
    scale_y = (lat.hi - lat.lo) / (1 << bits)
    x0 = x0.astype(np.float64)
    x1 = x1.astype(np.float64)
    y0 = y0.astype(np.float64)
    y1 = y1.astype(np.float64)
    # normalized index -> cell range. The pad must cover (a) the device's
    # f32 px/py rounding and (b) f32 COORDINATE representation error —
    # |x| * 2^-24, which at deep zoom (cell smaller than the coordinate
    # ulp) exceeds one cell, so the pad scales with ulp/cell
    ulp_x = max(abs(lon.lo), abs(lon.hi)) * 2.0 ** -24
    ulp_y = max(abs(lat.lo), abs(lat.hi)) * 2.0 ** -24
    pad_x = 1 + int(np.ceil(ulp_x / max(cellw, 1e-300)))
    pad_y = 1 + int(np.ceil(ulp_y / max(cellh, 1e-300)))
    cx0 = np.floor((lon.lo + x0 * scale_x - xmin) / cellw).astype(np.int64) - pad_x
    cx1 = np.floor((lon.lo + (x1 + 1) * scale_x - xmin) / cellw).astype(np.int64) + pad_x
    cy0 = np.floor((lat.lo + y0 * scale_y - ymin) / cellh).astype(np.int64) - pad_y
    cy1 = np.floor((lat.lo + (y1 + 1) * scale_y - ymin) / cellh).astype(np.int64) + pad_y
    cx0 = np.clip(cx0, 0, width - 1)
    cx1 = np.clip(cx1, 0, width - 1)
    cy0 = np.clip(cy0, 0, height - 1)
    cy1 = np.clip(cy1, 0, height - 1)

    ntx = -(-width // TX)
    nty = -(-height // TY)
    tx0, tx1 = cx0 // TX, cx1 // TX
    ty0, ty1 = cy0 // TY, cy1 // TY
    nx = np.where(act, tx1 - tx0 + 1, 0)
    ny = np.where(act, ty1 - ty0 + 1, 0)
    per = (nx * ny).astype(np.int64)
    P = int(per.sum())
    if P == 0:
        return None
    chunk_of = np.repeat(np.arange(len(per)), per)
    j = np.arange(P) - np.repeat(np.cumsum(per) - per, per)
    tx = tx0[chunk_of] + (j % np.maximum(nx[chunk_of], 1))
    ty = ty0[chunk_of] + (j // np.maximum(nx[chunk_of], 1))
    return {
        "chunk_of": chunk_of, "tx": tx, "ty": ty,
        "ntx": ntx, "nty": nty, "P": P,
    }


def build_pairs(
    compact: Dict, table, keyspace, bbox, width: int, height: int,
    box_cache: Optional[Dict] = None, version=None,
) -> Optional[Dict]:
    """(chunk, tile) pair arrays shaped for the XLA einsum kernel."""
    TY, TX = tile_shape()
    cand = pair_candidates(
        compact, table, keyspace, bbox, width, height, TY, TX,
        box_cache, version,
    )
    if cand is None:
        return None
    chunk_of, tx, ty = cand["chunk_of"], cand["tx"], cand["ty"]
    ntx, nty, P = cand["ntx"], cand["nty"], cand["P"]
    B = compact["B"]
    PB = pair_batch(B)
    Pp = -(-ladder8(P) // PB) * PB
    pad = Pp - P

    def _pad(a, fill=0):
        return np.concatenate([a, np.full(pad, fill, a.dtype)]) if pad else a

    return {
        "chunk": _pad(chunk_of.astype(np.int32)),
        "px0": _pad((tx * TX).astype(np.int32)),
        "py0": _pad((ty * TY).astype(np.int32)),
        "tile": _pad((ty * ntx + tx).astype(np.int32)),
        "pvalid": _pad(np.ones(P, np.float32)),
        "P": Pp,
        "PB": PB,
        "ntx": ntx,
        "nty": nty,
        "TY": TY,
        "TX": TX,
        "n_pairs": P,
    }


def density_grid_pairs(x, y, mask, bbox, width: int, height: int, weight,
                       pair_chunk, px0, py0, ptile, pvalid,
                       PB: int, ntx: int, nty: int, TY: int, TX: int, xp):
    """Device kernel: [C, B] compact columns + [P] pair arrays -> grid.

    Unweighted counts ride the MXU in bfloat16 one-hots (0/1 exact) with
    f32 accumulation; weighted densities use f32 operands."""
    import jax
    import jax.numpy as jnp

    xmin, ymin, xmax, ymax = bbox
    px = jnp.clip(
        ((x - xmin) / (xmax - xmin) * width).astype(jnp.int32), 0, width - 1
    )
    py = jnp.clip(
        ((y - ymin) / (ymax - ymin) * height).astype(jnp.int32), 0, height - 1
    )
    w = (
        mask.astype(jnp.float32)
        if weight is None
        else jnp.where(mask, weight.astype(jnp.float32), jnp.float32(0))
    )
    dt = jnp.bfloat16 if weight is None else jnp.float32
    ntiles = ntx * nty
    P = pair_chunk.shape[0]
    ix = jnp.arange(TX, dtype=jnp.int32)[None, None, :]
    iy = jnp.arange(TY, dtype=jnp.int32)[None, None, :]
    it = jnp.arange(ntiles, dtype=jnp.int32)[None, :]

    def body(i, acc):
        sl = lambda a: jax.lax.dynamic_slice_in_dim(a, i * PB, PB)  # noqa: E731
        pc = sl(pair_chunk)
        gw = w[pc] * sl(pvalid)[:, None]
        lx = px[pc] - sl(px0)[:, None]
        ly = py[pc] - sl(py0)[:, None]
        ohx = (lx[:, :, None] == ix).astype(dt)
        A = jnp.where(ly[:, :, None] == iy, gw[:, :, None], 0).astype(dt)
        tile = jnp.einsum(
            "pby,pbx->pyx", A, ohx, preferred_element_type=jnp.float32
        )
        oht = (sl(ptile)[:, None] == it).astype(jnp.float32)
        return acc + jnp.einsum(
            "pt,pyx->tyx", oht, tile, preferred_element_type=jnp.float32
        )

    acc = jax.lax.fori_loop(
        0, P // PB, body, jnp.zeros((ntiles, TY, TX), jnp.float32)
    )
    grid = acc.reshape(nty, ntx, TY, TX).transpose(0, 2, 1, 3)
    return grid.reshape(nty * TY, ntx * TX)[:height, :width]
