"""Version-stable compiled-kernel registry + shape bucketing (warm path).

GeoMesa's tablet-server iterators are compile-free; the TPU port instead
pays an XLA trace+compile for every *new* jitted scan kernel. This module
is the executor's warm-path substrate (docs/PERF.md):

* :class:`KernelRegistry` — a bounded, thread-safe LRU of jitted kernels,
  shared across time partitions of one store AND across aggregate-cache
  cell queries (one registry per parent store / partitioned executor).
  Entries evict one at a time, least-recently-used first — never the
  clear-on-overflow wipe the per-site dicts used to do, which threw away
  63 hot kernels to admit the 65th. Default capacity 512
  (``geomesa.kernel.cache.size``; raised from 256 when the query-axis
  batch kernels widened the key space with the padded member axis —
  docs/PERF.md records the BENCH_r10 eviction pressure behind the raise).
* **version-stable keys** — kernel cache keys carry NO store version: the
  compiled function is structure-only (shapes + predicate closure), so a
  store mutation must not recompile anything. What CAN invalidate a
  compiled closure is dictionary growth (string predicates bake resolved
  codes at compile time): :func:`dict_fingerprint` captures exactly that.
  Window *data* stays version-keyed in the executor's separate win caches.
* **shape bucketing** — :func:`bucket_count` pads the per-shard window
  count K to a power of two above a floor, so distinct-but-similar
  queries land on one compiled shape (padded windows are empty and the
  ``valid``/``counts`` masks keep results exact).
* **persistent compile cache** — :func:`enable_persistent_cache` wires
  ``jax_compilation_cache_dir`` behind ``geomesa.compile.cache.dir`` so
  restarts start warm.

Metrics (process registry): ``kernel.recompiles`` (fresh traces),
``kernel.bucket_hit`` (registry hits), ``kernel.evict``.
"""

from __future__ import annotations

import threading
import time as _time
from collections import OrderedDict
from typing import Any, Dict, Hashable, Optional, Tuple

from geomesa_tpu import config, metrics, tracing

#: metric names (declared in metrics.py with the exposition contract)
KERNEL_RECOMPILES = metrics.KERNEL_RECOMPILES
KERNEL_HIT = metrics.KERNEL_BUCKET_HIT
KERNEL_EVICT = metrics.KERNEL_EVICT


# ---------------------------------------------------------------------------
# Per-query recompile window + alert (the ROADMAP "surface per-site
# recompile counts as alerts in the metrics exposition" item). Every fresh
# trace bumps a per-site counter (kernel.recompiles.<site>) and a
# thread-local per-QUERY window; a site paying more than
# geomesa.kernel.alert.threshold traces within one query trips the
# kernel.recompile.alert gauge — the warm-path-broken signal (a healthy
# steady state compiles at most once per site per novel shape bucket).
#
# The gauge LATCHES for _ALERT_TTL_S after the last trip instead of being
# zeroed by the next query: windows are thread-local but the gauge is
# process-global, so clear-on-next-query would let concurrent (or merely
# subsequent) queries race a trip away before any scraper could see it.
# ---------------------------------------------------------------------------

_query_window = threading.local()

_MISSING = object()  # OrderedDict.pop sentinel (None is a valid value)

#: how long a trip stays visible on the gauge (covers realistic scrape
#: intervals; the kernel.recompile.alerts counter is the durable record)
_ALERT_TTL_S = 300.0
_alert_lock = threading.Lock()
_alert_state = {"at": 0.0, "over": 0}


def _alert_value() -> float:
    """Callable backing of the kernel.recompile.alert gauge: the number of
    sites over threshold in the most recent tripped window, until the
    latch TTL expires."""
    with _alert_lock:
        if _time.monotonic() - _alert_state["at"] <= _ALERT_TTL_S:
            return float(_alert_state["over"])
    return 0.0


def _ensure_alert_gauge() -> None:
    # same module-level fn every time: registration is idempotent and
    # survives a registry.clear() (re-registered on the next query)
    metrics.registry().gauge(metrics.KERNEL_RECOMPILE_ALERT, _alert_value)


def reset_alert() -> None:
    """Clear the alert latch (tests)."""
    with _alert_lock:
        _alert_state["at"] = 0.0
        _alert_state["over"] = 0


def _site_slug(site) -> str:
    """Metric-name-safe jit-site label."""
    s = str(site)
    return "".join(ch if (ch.isalnum() or ch in "._-") else "_" for ch in s)


def begin_query_window() -> None:
    """Reset this thread's per-query recompile window (called at the top
    of every query plan). The alert gauge is NOT cleared here — it latches
    for _ALERT_TTL_S so a trip survives until a scraper can observe it."""
    _query_window.counts = {}
    _ensure_alert_gauge()


def query_recompiles() -> Dict[str, int]:
    """site -> fresh traces paid by the CURRENT query window (explain's
    Warm path section reports this next to the lifetime totals)."""
    return dict(getattr(_query_window, "counts", {}))


def alert_threshold() -> int:
    """Effective geomesa.kernel.alert.threshold (single source of the
    default — explain and the trip logic must agree)."""
    t = config.KERNEL_ALERT_THRESHOLD.to_int()
    return 3 if t is None else t


def _note_recompile(site) -> None:
    slug = _site_slug(site)
    metrics.inc(KERNEL_RECOMPILES)
    metrics.inc(f"{KERNEL_RECOMPILES}.{slug}")
    # visible INSIDE the query that paid for it (span-tree event)
    tracing.event("kernel.recompile", site=slug)
    counts = getattr(_query_window, "counts", None)
    if counts is None:
        return
    counts[slug] = counts.get(slug, 0) + 1
    threshold = alert_threshold()
    if counts[slug] > threshold:
        over = sum(1 for v in counts.values() if v > threshold)
        with _alert_lock:
            _alert_state["at"] = _time.monotonic()
            _alert_state["over"] = over
        _ensure_alert_gauge()
        if counts[slug] == threshold + 1:  # first trip for this site
            metrics.inc(metrics.KERNEL_RECOMPILE_ALERTS)
            tracing.event("kernel.recompile.alert", site=slug,
                          recompiles=counts[slug])


class KernelRegistry:
    """Bounded LRU of compiled kernels, keyed by version-stable tuples.

    The mapping protocol mirrors the plain dicts it replaces (``get`` /
    ``put``) plus per-site trace accounting: ``key[0]`` (or, for tagged
    keys, ``key[0][0]``) names the jit site, and :meth:`traces` reports
    how many fresh compiles each site has paid — the recompile-regression
    tests assert directly on it.
    """

    def __init__(self, capacity: Optional[int] = None):
        self._entries: "OrderedDict[Hashable, Any]" = OrderedDict()
        self._capacity = capacity
        self._lock = threading.Lock()
        #: site label -> fresh-trace count (puts, not hits)
        self._traces: Dict[Any, int] = {}
        #: site label -> entries evicted (kernel.evict.<site> twin, kept
        #: here so explain/tests can read per-registry pressure directly)
        self._evicts: Dict[Any, int] = {}
        #: keys evicted and not since re-admitted (bounded FIFO set): a
        #: put() whose key is in here is an EVICTION-CAUSED recompile —
        #: the LRU was too small for the live working set, the thrash
        #: signal docs/PERF.md's registry-pressure check watches
        #: (kernel.recompiles.evicted + the bench eviction_recompiles key)
        self._evicted_keys: "OrderedDict[Hashable, None]" = OrderedDict()
        self._evicted_recompiles = 0

    _EVICTED_KEYS_MAX = 4096

    def _cap(self) -> int:
        if self._capacity is not None:
            return self._capacity
        return config.KERNEL_CACHE_SIZE.to_int() or 512

    @staticmethod
    def _site(key: Hashable) -> Any:
        site = key[0] if isinstance(key, tuple) and key else key
        if isinstance(site, tuple) and site:
            site = site[0]
        return site

    def get(self, key: Hashable, default=None):
        if key is None:
            return default
        with self._lock:
            fn = self._entries.get(key)
            if fn is None:
                return default
            self._entries.move_to_end(key)
        metrics.inc(KERNEL_HIT)
        return fn

    def put(self, key: Hashable, fn) -> None:
        """Admit one freshly-traced kernel, evicting LRU entries over
        capacity (one at a time — the clear-on-overflow this replaces
        wiped every hot kernel to admit one). Evictions account per SITE
        (``kernel.evict.<site>``), and re-tracing a previously-evicted
        key counts as an eviction-caused recompile
        (``kernel.recompiles.evicted``) — the LRU-pressure signals the
        docs/PERF.md registry check reads."""
        with self._lock:
            self._entries[key] = fn
            self._entries.move_to_end(key)
            site = self._site(key)
            self._traces[site] = self._traces.get(site, 0) + 1
            evicted_from = self._evicted_keys.pop(key, _MISSING)
            if evicted_from is not _MISSING:
                self._evicted_recompiles += 1
            evicted_sites = []
            cap = max(self._cap(), 1)
            while len(self._entries) > cap:
                ekey, _ = self._entries.popitem(last=False)
                esite = self._site(ekey)
                self._evicts[esite] = self._evicts.get(esite, 0) + 1
                evicted_sites.append(esite)
                self._evicted_keys[ekey] = None
                while len(self._evicted_keys) > self._EVICTED_KEYS_MAX:
                    self._evicted_keys.popitem(last=False)
        _note_recompile(site)
        if evicted_from is not _MISSING:
            metrics.inc(metrics.KERNEL_RECOMPILE_EVICTED)
        if evicted_sites:
            metrics.inc(KERNEL_EVICT, len(evicted_sites))
            for esite in evicted_sites:
                metrics.inc(f"{KERNEL_EVICT}.{_site_slug(esite)}")

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def traces(self, site=None):
        """Fresh-compile count per jit site (or one site's count)."""
        with self._lock:
            if site is not None:
                return self._traces.get(site, 0)
            return dict(self._traces)

    def evicts(self, site=None):
        """LRU evictions per jit site (or one site's count) — the
        per-registry twin of the kernel.evict.<site> metrics."""
        with self._lock:
            if site is not None:
                return self._evicts.get(site, 0)
            return dict(self._evicts)

    def evicted_recompiles(self) -> int:
        """Fresh traces paid for keys the LRU had previously evicted —
        nonzero means the working set exceeds the capacity
        (geomesa.kernel.cache.size; docs/PERF.md)."""
        with self._lock:
            return self._evicted_recompiles

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


def dict_fingerprint(dicts: Dict[str, Any]) -> Tuple:
    """Compiled-predicate validity fingerprint: string predicates resolve
    dictionary codes at compile time, and dictionaries are append-only, so
    per-encoder vocabulary *length* captures every growth that could change
    a compiled closure. Mutations that don't grow a vocabulary (inserts of
    known strings, numeric updates, deletes) leave it unchanged — the
    warm-path guarantee that a store mutation never forces a recompile."""
    return tuple(sorted((k, len(d.values)) for k, d in dicts.items()))


def bucket_batch(n: int) -> int:
    """Pad a fused micro-batch's member count to the next power of two, so
    one batched-parameter kernel (its registry key carries the padded
    member axis next to the usual version-stable token — see
    ``Executor.density_curve_batch``) serves every batch size in the
    bucket instead of tracing per size (docs/SERVING.md). Padded members
    carry zero-length parameter spans and are dropped at de-interleave."""
    if n <= 1:
        return 1
    return 1 << (n - 1).bit_length()


def bucket_count(n: int) -> int:
    """Pad a per-shard window count to its shape bucket: the next power of
    two, floored at ``geomesa.compact.bucket.floor``. Identity when
    ``geomesa.compact.bucketing`` is off (old behavior: exact pow2)."""
    if n <= 1:
        n = 1
    else:
        n = 1 << (n - 1).bit_length()
    if not config.COMPACT_BUCKETING.to_bool():
        return n
    floor = config.COMPACT_BUCKET_FLOOR.to_int()
    floor = 8 if floor is None else max(floor, 1)
    return max(n, floor)


_persistent_cache_done = [False]
_persistent_cache_lock = threading.Lock()


def enable_persistent_cache() -> Optional[str]:
    """Point JAX's persistent compilation cache at
    ``geomesa.compile.cache.dir`` (idempotent; no-op when unset). With it
    set, process restarts reuse compiled XLA executables from disk — the
    cold-start twin of the in-process registry above. Returns the dir in
    effect (None = disabled)."""
    d = config.COMPILE_CACHE_DIR.get()
    if not d:
        return None
    with _persistent_cache_lock:
        if _persistent_cache_done[0]:
            return d
        import jax

        try:
            jax.config.update("jax_compilation_cache_dir", d)
            # persist everything: scan kernels compile fast but re-trace
            # often; the default min-compile-time gate would skip them
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        except AttributeError:
            # older jax without these knobs: directory option alone still
            # enables the cache where supported
            pass
        _persistent_cache_done[0] = True
    return d
