"""Version-stable compiled-kernel registry + shape bucketing (warm path).

GeoMesa's tablet-server iterators are compile-free; the TPU port instead
pays an XLA trace+compile for every *new* jitted scan kernel. This module
is the executor's warm-path substrate (docs/PERF.md):

* :class:`KernelRegistry` — a bounded, thread-safe LRU of jitted kernels,
  shared across time partitions of one store AND across aggregate-cache
  cell queries (one registry per parent store / partitioned executor).
  Entries evict one at a time, least-recently-used first — never the
  clear-on-overflow wipe the per-site dicts used to do, which threw away
  63 hot kernels to admit the 65th.
* **version-stable keys** — kernel cache keys carry NO store version: the
  compiled function is structure-only (shapes + predicate closure), so a
  store mutation must not recompile anything. What CAN invalidate a
  compiled closure is dictionary growth (string predicates bake resolved
  codes at compile time): :func:`dict_fingerprint` captures exactly that.
  Window *data* stays version-keyed in the executor's separate win caches.
* **shape bucketing** — :func:`bucket_count` pads the per-shard window
  count K to a power of two above a floor, so distinct-but-similar
  queries land on one compiled shape (padded windows are empty and the
  ``valid``/``counts`` masks keep results exact).
* **persistent compile cache** — :func:`enable_persistent_cache` wires
  ``jax_compilation_cache_dir`` behind ``geomesa.compile.cache.dir`` so
  restarts start warm.

Metrics (process registry): ``kernel.recompiles`` (fresh traces),
``kernel.bucket_hit`` (registry hits), ``kernel.evict``.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, Hashable, Optional, Tuple

from geomesa_tpu import config, metrics

#: metric names (declared in metrics.py with the exposition contract)
KERNEL_RECOMPILES = metrics.KERNEL_RECOMPILES
KERNEL_HIT = metrics.KERNEL_BUCKET_HIT
KERNEL_EVICT = metrics.KERNEL_EVICT


class KernelRegistry:
    """Bounded LRU of compiled kernels, keyed by version-stable tuples.

    The mapping protocol mirrors the plain dicts it replaces (``get`` /
    ``put``) plus per-site trace accounting: ``key[0]`` (or, for tagged
    keys, ``key[0][0]``) names the jit site, and :meth:`traces` reports
    how many fresh compiles each site has paid — the recompile-regression
    tests assert directly on it.
    """

    def __init__(self, capacity: Optional[int] = None):
        self._entries: "OrderedDict[Hashable, Any]" = OrderedDict()
        self._capacity = capacity
        self._lock = threading.Lock()
        #: site label -> fresh-trace count (puts, not hits)
        self._traces: Dict[Any, int] = {}

    def _cap(self) -> int:
        if self._capacity is not None:
            return self._capacity
        return config.KERNEL_CACHE_SIZE.to_int() or 256

    @staticmethod
    def _site(key: Hashable) -> Any:
        site = key[0] if isinstance(key, tuple) and key else key
        if isinstance(site, tuple) and site:
            site = site[0]
        return site

    def get(self, key: Hashable, default=None):
        if key is None:
            return default
        with self._lock:
            fn = self._entries.get(key)
            if fn is None:
                return default
            self._entries.move_to_end(key)
        metrics.inc(KERNEL_HIT)
        return fn

    def put(self, key: Hashable, fn) -> None:
        """Admit one freshly-traced kernel, evicting LRU entries over
        capacity (one at a time — the clear-on-overflow this replaces
        wiped every hot kernel to admit one)."""
        with self._lock:
            self._entries[key] = fn
            self._entries.move_to_end(key)
            site = self._site(key)
            self._traces[site] = self._traces.get(site, 0) + 1
            evicted = 0
            cap = max(self._cap(), 1)
            while len(self._entries) > cap:
                self._entries.popitem(last=False)
                evicted += 1
        metrics.inc(KERNEL_RECOMPILES)
        if evicted:
            metrics.inc(KERNEL_EVICT, evicted)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def traces(self, site=None):
        """Fresh-compile count per jit site (or one site's count)."""
        with self._lock:
            if site is not None:
                return self._traces.get(site, 0)
            return dict(self._traces)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


def dict_fingerprint(dicts: Dict[str, Any]) -> Tuple:
    """Compiled-predicate validity fingerprint: string predicates resolve
    dictionary codes at compile time, and dictionaries are append-only, so
    per-encoder vocabulary *length* captures every growth that could change
    a compiled closure. Mutations that don't grow a vocabulary (inserts of
    known strings, numeric updates, deletes) leave it unchanged — the
    warm-path guarantee that a store mutation never forces a recompile."""
    return tuple(sorted((k, len(d.values)) for k, d in dicts.items()))


def bucket_count(n: int) -> int:
    """Pad a per-shard window count to its shape bucket: the next power of
    two, floored at ``geomesa.compact.bucket.floor``. Identity when
    ``geomesa.compact.bucketing`` is off (old behavior: exact pow2)."""
    if n <= 1:
        n = 1
    else:
        n = 1 << (n - 1).bit_length()
    if not config.COMPACT_BUCKETING.to_bool():
        return n
    floor = config.COMPACT_BUCKET_FLOOR.to_int()
    floor = 8 if floor is None else max(floor, 1)
    return max(n, floor)


_persistent_cache_done = [False]
_persistent_cache_lock = threading.Lock()


def enable_persistent_cache() -> Optional[str]:
    """Point JAX's persistent compilation cache at
    ``geomesa.compile.cache.dir`` (idempotent; no-op when unset). With it
    set, process restarts reuse compiled XLA executables from disk — the
    cold-start twin of the in-process registry above. Returns the dir in
    effect (None = disabled)."""
    d = config.COMPILE_CACHE_DIR.get()
    if not d:
        return None
    with _persistent_cache_lock:
        if _persistent_cache_done[0]:
            return d
        import jax

        try:
            jax.config.update("jax_compilation_cache_dir", d)
            # persist everything: scan kernels compile fast but re-trace
            # often; the default min-compile-time gate would skip them
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        except AttributeError:
            # older jax without these knobs: directory option alone still
            # enables the cache where supported
            pass
        _persistent_cache_done[0] = True
    return d
