"""Query audit log (reference index/audit/QueryEvent.scala:14,
utils/audit/AuditWriter; wired in GeoMesaFeatureReader.scala:56-71).

Each completed query produces a structured ``QueryEvent`` — store, type name,
user, filter, hints, planTime, scanTime, hits — appended to an in-memory ring
and (when ``geomesa.audit.path`` is set) to a JSONL file, the analog of
Accumulo's ``_queries`` audit table.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional

from geomesa_tpu import config


@dataclass
class QueryEvent:
    """One audited query (QueryEvent.scala:14 field parity)."""

    store: str
    type_name: str
    user: str
    filter: str
    hints: Dict[str, Any] = field(default_factory=dict)
    date: float = 0.0          # epoch seconds
    plan_time_ms: float = 0.0
    scan_time_ms: float = 0.0
    hits: int = 0
    #: coarse-window candidate rows (scanned) and table size — selectivity
    #: of the index pushdown; hits/scanned ratios near 1 mean tight windows
    scanned: int = 0
    table_rows: int = 0

    def to_json(self) -> str:
        return json.dumps(asdict(self), default=str)


class AuditWriter:
    """Collects QueryEvents; optionally appends JSONL to a file."""

    def __init__(self, store_name: str = "geomesa-tpu", max_events: int = 10_000):
        self.store_name = store_name
        self.events: deque = deque(maxlen=max_events)
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return config.AUDIT_ENABLED.to_bool()

    def write(self, event: QueryEvent):
        if not self.enabled:
            return
        event.store = event.store or self.store_name
        if not event.date:
            event.date = time.time()
        with self._lock:
            self.events.append(event)
            path = config.AUDIT_PATH.get()
            if path:
                with open(path, "a") as fh:
                    fh.write(event.to_json() + "\n")

    def record(self, type_name: str, filter_text: str, hints: Dict[str, Any],
               plan_time_ms: float, scan_time_ms: float, hits: int,
               user: str = "", scanned: int = 0, table_rows: int = 0):
        self.write(
            QueryEvent(
                store=self.store_name, type_name=type_name, user=user,
                filter=filter_text, hints=hints, plan_time_ms=plan_time_ms,
                scan_time_ms=scan_time_ms, hits=hits, scanned=scanned,
                table_rows=table_rows,
            )
        )

    def recent(self, n: int = 100) -> List[QueryEvent]:
        with self._lock:
            return list(self.events)[-n:]


# ---------------------------------------------------------------------------
# Degradation trail (resilience layer; docs/RESILIENCE.md). Every skipped
# partition / quarantined message / corrupt file records a DegradationEvent
# here — the operational answer to "what did my degraded aggregate drop?".
# ---------------------------------------------------------------------------


@dataclass
class DegradationEvent:
    """One unit of work dropped by the resilience layer."""

    source: str        # fault-point site, e.g. "fs.read_partition"
    part: str          # partition name / file path / message id
    error: str         # repr of the failure
    phase: str = ""
    date: float = 0.0  # epoch seconds

    def to_json(self) -> str:
        return json.dumps(asdict(self), default=str)


class DegradationLog:
    """In-memory ring of DegradationEvents (JSONL-appended alongside the
    query audit when ``geomesa.audit.path`` is set)."""

    def __init__(self, max_events: int = 10_000):
        self.events: deque = deque(maxlen=max_events)
        self._lock = threading.Lock()

    def write(self, event: DegradationEvent):
        if not config.AUDIT_ENABLED.to_bool():
            return  # same gate AuditWriter honors: disabled means disabled
        if not event.date:
            event.date = time.time()
        with self._lock:
            self.events.append(event)
            path = config.AUDIT_PATH.get()
            if path:
                with open(path, "a") as fh:
                    fh.write(event.to_json() + "\n")

    def recent(self, n: int = 100) -> List[DegradationEvent]:
        with self._lock:
            return list(self.events)[-n:]

    def clear(self):
        with self._lock:
            self.events.clear()


#: process-wide degradation trail
degradations = DegradationLog()


def record_degradation(rec) -> None:
    """Record a resilience-layer skip (``rec`` is a ``resilience.Skipped``
    or anything with source/part/error/phase attributes)."""
    degradations.write(
        DegradationEvent(
            source=getattr(rec, "source", ""),
            part=getattr(rec, "part", ""),
            error=getattr(rec, "error", ""),
            phase=getattr(rec, "phase", ""),
        )
    )
