"""Query audit log (reference index/audit/QueryEvent.scala:14,
utils/audit/AuditWriter; wired in GeoMesaFeatureReader.scala:56-71).

Each completed query produces a structured ``QueryEvent`` — store, type name,
user, filter, hints, planTime, scanTime, hits — appended to an in-memory ring
and (when ``geomesa.audit.path`` is set) to a JSONL file, the analog of
Accumulo's ``_queries`` audit table.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional

from geomesa_tpu import config


class _JsonlAppender:
    """One held append handle for the audit JSONL file (satellite fix: the
    old code reopened the file under the registry lock on EVERY event).
    The handle reopens only when ``geomesa.audit.path`` changes; every
    record kind — query events, degradations, slow traces — flushes
    through this single writer, so file ordering matches event ordering."""

    def __init__(self):
        self._lock = threading.Lock()
        self._path: "str | None" = None
        self._fh = None

    def write(self, line: str) -> None:
        import os

        with self._lock:
            path = config.AUDIT_PATH.get()
            reopen = path != self._path
            if not reopen and self._fh is not None:
                # rotation check: logrotate renames/removes the file while
                # the path string stays the same — one stat per event (far
                # cheaper than the open+close this appender replaced)
                # detects it and reopens, so records land in the NEW file
                try:
                    st = os.stat(path)
                    fst = os.fstat(self._fh.fileno())
                    reopen = (st.st_ino, st.st_dev) != (fst.st_ino, fst.st_dev)
                except OSError:
                    reopen = True  # target missing: recreate it
            if reopen:
                if self._fh is not None:
                    try:
                        self._fh.close()
                    except OSError:
                        pass
                self._fh = open(path, "a") if path else None
                self._path = path
            if self._fh is not None:
                self._fh.write(line + "\n")
                self._fh.flush()

    def reset(self) -> None:
        """Close the held handle (tests; a removed-but-same-path file)."""
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
            self._fh = None
            self._path = None


#: process-wide JSONL appender shared by every audit record kind
_appender = _JsonlAppender()


def append_record(obj: Dict[str, Any]) -> None:
    """Append one structured record (e.g. a slow-trace tree from
    tracing.py) through the shared audit appender. Honors the same
    enabled/path gates as query events."""
    if not config.AUDIT_ENABLED.to_bool():
        return
    _appender.write(json.dumps(obj, default=str))


@dataclass
class QueryEvent:
    """One audited query (QueryEvent.scala:14 field parity)."""

    store: str
    type_name: str
    user: str
    filter: str
    hints: Dict[str, Any] = field(default_factory=dict)
    date: float = 0.0          # epoch seconds
    plan_time_ms: float = 0.0
    scan_time_ms: float = 0.0
    hits: int = 0
    #: coarse-window candidate rows (scanned) and table size — selectivity
    #: of the index pushdown; hits/scanned ratios near 1 mean tight windows
    scanned: int = 0
    table_rows: int = 0

    def to_json(self) -> str:
        return json.dumps(asdict(self), default=str)


class AuditWriter:
    """Collects QueryEvents; optionally appends JSONL to a file."""

    def __init__(self, store_name: str = "geomesa-tpu", max_events: int = 10_000):
        self.store_name = store_name
        self.events: deque = deque(maxlen=max_events)
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return config.AUDIT_ENABLED.to_bool()

    def write(self, event: QueryEvent):
        if not self.enabled:
            return
        event.store = event.store or self.store_name
        if not event.date:
            event.date = time.time()
        with self._lock:
            # file append INSIDE the registry lock (via the held appender
            # handle): ring order and file order stay identical even under
            # concurrent writers
            self.events.append(event)
            _appender.write(event.to_json())

    def record(self, type_name: str, filter_text: str, hints: Dict[str, Any],
               plan_time_ms: float, scan_time_ms: float, hits: int,
               user: str = "", scanned: int = 0, table_rows: int = 0):
        self.write(
            QueryEvent(
                store=self.store_name, type_name=type_name, user=user,
                filter=filter_text, hints=hints, plan_time_ms=plan_time_ms,
                scan_time_ms=scan_time_ms, hits=hits, scanned=scanned,
                table_rows=table_rows,
            )
        )

    def recent(self, n: int = 100) -> List[QueryEvent]:
        with self._lock:
            return list(self.events)[-n:]


# ---------------------------------------------------------------------------
# Degradation trail (resilience layer; docs/RESILIENCE.md). Every skipped
# partition / quarantined message / corrupt file records a DegradationEvent
# here — the operational answer to "what did my degraded aggregate drop?".
# ---------------------------------------------------------------------------


@dataclass
class DegradationEvent:
    """One unit of work dropped by the resilience layer."""

    source: str        # fault-point site, e.g. "fs.read_partition"
    part: str          # partition name / file path / message id
    error: str         # repr of the failure
    phase: str = ""
    date: float = 0.0  # epoch seconds

    def to_json(self) -> str:
        return json.dumps(asdict(self), default=str)


class DegradationLog:
    """In-memory ring of DegradationEvents (JSONL-appended alongside the
    query audit when ``geomesa.audit.path`` is set)."""

    def __init__(self, max_events: int = 10_000):
        self.events: deque = deque(maxlen=max_events)
        self._lock = threading.Lock()

    def write(self, event: DegradationEvent):
        if not config.AUDIT_ENABLED.to_bool():
            return  # same gate AuditWriter honors: disabled means disabled
        if not event.date:
            event.date = time.time()
        with self._lock:
            self.events.append(event)
            _appender.write(event.to_json())

    def recent(self, n: int = 100) -> List[DegradationEvent]:
        with self._lock:
            return list(self.events)[-n:]

    def clear(self):
        with self._lock:
            self.events.clear()


#: process-wide degradation trail
degradations = DegradationLog()


def record_degradation(rec) -> None:
    """Record a resilience-layer skip (``rec`` is a ``resilience.Skipped``
    or anything with source/part/error/phase attributes)."""
    degradations.write(
        DegradationEvent(
            source=getattr(rec, "source", ""),
            part=getattr(rec, "part", ""),
            error=getattr(rec, "error", ""),
            phase=getattr(rec, "phase", ""),
        )
    )
