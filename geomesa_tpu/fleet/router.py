"""Cell-affinity fleet router (docs/RESILIENCE.md §7).

A :class:`FleetRouter` fronts N replica sidecars over one shared storage
root with a GeoDataset-shaped remote API. Per query it:

1. derives an **affinity key** from the query's SFC cell cover (the same
   cell family the aggregate cache decomposes to, cache/cells.py): the
   bbox center's cell at ``geomesa.fleet.routing.level`` — so nearby
   viewports land on the same replica and its flat+hierarchy cache stays
   hot for its slice of the world, making fleet cache capacity additive;
2. ranks replicas on the **rendezvous ring** (fleet/ring.py) and serves
   from the first USABLE owner (registry-filtered: cordoned / draining /
   open-breaker replicas are skipped);
3. **fails over** to the next ring owner when a call fails retryably —
   deadline-aware (an expired budget stops the walk typed), with the
   replica's breaker charged for transport/internal failures and its
   latency fed to the outlier detector;
4. when EVERY owner is down, **degrades typed**: under ``allow_partial()``
   additive aggregates return the survivor total with the skip recorded
   (``[GM-FLEET-PARTIAL]`` accounting, resilience §3 generalized from
   partitions to replicas); strict mode raises
   :class:`~geomesa_tpu.resilience.FleetPartialError`;
5. **scatters** decomposable exact counts across owner groups
   (``geomesa.fleet.scatter``): each replica scans only its own cells —
   integer partials add exactly, so the scatter is bit-identical to the
   single-process scan by the cache's cell-partition argument — and a
   dead owner degrades with EXACT survivor totals (the surviving groups'
   sum plus a per-group skip record);
6. stamps **mutation epochs** onto writes and requires them on reads
   (sidecar fleet headers), so a restarted or failed-over replica
   refreshes from the shared root before it can serve a pre-mutation
   aggregate.

Admission/fair-share rides the same ``_UserLedger``-backed scheduler the
serving layer uses (inline mode + the ``geomesa.fleet.max.inflight``
bound), so ``/debug/fleet`` rollups and shed decisions share one
accounting with every other surface.
"""

from __future__ import annotations

import contextlib
import threading
import time
import uuid
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from geomesa_tpu import config, metrics, resilience, tracing
from geomesa_tpu.cache import cells as cellmod
from geomesa_tpu.cache import service as cache_service
from geomesa_tpu.fleet.registry import ReplicaRegistry
from geomesa_tpu.fleet.ring import RendezvousRing
from geomesa_tpu.resilience import (
    AdmissionRejectedError, CircuitOpenError, DeviceDrainError,
    FleetPartialError, QueryTimeoutError, Skipped,
)

#: routers alive in this process (weak — /debug/fleet reads them)
_ROUTERS: "weakref.WeakSet" = weakref.WeakSet()


def debug_fleet() -> Dict[str, Any]:
    """The /debug/fleet payload: one snapshot per live router in this
    process (obs.py mounts it; docs/RESILIENCE.md §7)."""
    routers = [r.snapshot() for r in list(_ROUTERS)]
    return {"routers": routers}


class _Exhausted(Exception):
    """Internal: every candidate replica failed; carries the last error."""

    def __init__(self, last: Optional[BaseException]):
        super().__init__(repr(last))
        self.last = last


class FleetRouter:
    """See the module docstring. Thread-safe; one per front-end process."""

    def __init__(self, replicas: Dict[str, str],
                 retry_seed: Optional[int] = None,
                 name: str = "geomesa-fleet-router"):
        from geomesa_tpu.serving import QueryScheduler

        self.name = name
        self.registry = ReplicaRegistry(replicas)
        self.ring = RendezvousRing(replicas)
        self._retry_seed = retry_seed
        self._clients: Dict[str, Any] = {}
        self._clients_lock = threading.Lock()
        #: authoritative per-schema fleet epochs (router-stamped writes
        #: bump them; probes adopt newer ones learned from replicas)
        self._epochs: Dict[str, int] = {}
        self._epoch_lock = threading.Lock()
        #: per-thread active write stamp ({schema: epoch}) — read by the
        #: clients' header provider while the stamped call is in flight
        self._tls = threading.local()
        #: fleet-level admission + per-user ledger: the same policy/
        #: accounting object the serving scheduler runs (docs/SERVING.md)
        self.serving = QueryScheduler(name)
        self._fts: Dict[str, Any] = {}
        self._ft_lock = threading.Lock()
        self._counters = {"affinity": 0, "failover": 0, "scatter": 0,
                          "partial": 0, "uncordoned": 0, "joined": 0,
                          "left": 0}
        #: per-owner scatter rows for /debug/fleet (groups served /
        #: cells covered / groups+cells skipped — survivor accounting)
        self._scatter_stats: Dict[str, Dict[str, int]] = {}
        self._counter_lock = threading.Lock()
        #: fleet observability plane (fleet/obs.py) — created lazily so
        #: a router that never scatters or scrapes never starts it
        self._obs = None
        self._obs_lock = threading.Lock()
        _ROUTERS.add(self)

    def observability(self):
        """The router's :class:`~geomesa_tpu.fleet.obs.FleetObservability`
        (docs/OBSERVABILITY.md §9), created on first use."""
        obs = self._obs
        if obs is None:
            from geomesa_tpu.fleet.obs import FleetObservability

            with self._obs_lock:
                obs = self._obs
                if obs is None:
                    obs = self._obs = FleetObservability(self)
        return obs

    # -- membership --------------------------------------------------------
    def add_replica(self, rid: str, location: str) -> None:
        """Add (or re-home) a replica. A cached client to the id's OLD
        location is dropped — a restarted replica usually comes back on
        a fresh port."""
        self.registry.add(rid, location)
        self.ring = RendezvousRing(set(self.ring.members) | {rid})
        with self._clients_lock:
            c = self._clients.pop(rid, None)
        if c is not None:
            try:
                c.close()
            except Exception:
                pass

    def remove_replica(self, rid: str) -> None:
        self.registry.remove(rid)
        members = [m for m in self.ring.members if m != rid]
        self.ring = RendezvousRing(members)
        with self._clients_lock:
            c = self._clients.pop(rid, None)
        if c is not None:
            try:
                c.close()
            except Exception:
                pass

    # -- dynamic membership (docs/RESILIENCE.md §7) ------------------------
    def register_replica(self, location: str) -> str:
        """Runtime JOIN: probe ``location``, learn the replica's identity
        from the gossip channel (the ``x-geomesa-replica-id`` response
        header every fleet replica stamps; the replica-status body is the
        fallback), adopt any newer epochs it knows, and admit it to the
        registry + ring — it starts receiving its HRW share of the key
        space on the next routed query, no router restart. Returns the
        learned replica id; raises if the endpoint is not a fleet
        replica (no identity — admitting it would orphan its keys)."""
        from geomesa_tpu.sidecar.client import GeoFlightClient

        c = GeoFlightClient(location, retry_seed=self._retry_seed,
                            header_provider=self._fleet_headers)
        try:
            st = c.replica_status()
            rid = c.last_replica_id or st.get("replica")
            if not rid:
                raise ValueError(
                    f"{location} did not gossip a replica identity "
                    "(geomesa.fleet.replica.id unset?) — not a fleet "
                    "replica"
                )
            rid = str(rid)
            with self._epoch_lock:
                for sname, e in (st.get("epochs") or {}).items():
                    if self._epochs.get(sname, 0) < int(e):
                        self._epochs[sname] = int(e)
        except Exception:
            c.close()
            raise
        self.add_replica(rid, location)
        self.registry.set_draining(rid, bool(st.get("draining")))
        # keep the already-dialed client (add_replica dropped any OLD one)
        with self._clients_lock:
            self._clients[rid] = c
        # JOIN migration (docs/STANDING.md): standing groups whose route
        # key the new member now owns move over before it serves polls
        self._pull_subscriptions(rid)
        self._count("joined")
        metrics.inc(metrics.FLEET_MEMBER_JOIN)
        return rid

    def deregister_replica(self, rid: str, handoff: bool = True) -> Dict:
        """Runtime LEAVE with an optional **warm handoff**: drain the
        replica (new traffic fails over immediately), push its hottest
        per-schema cache entries to each schema's NEW ring owner (the
        post-removal ring — ``cache-export``/``cache-import``, guarded by
        the row-count + spec data check), then remove it from the
        registry + ring. The drained replica's warmest cells keep
        answering from cache on the new owner instead of dying with the
        process. Returns the handoff summary."""
        out: Dict[str, Any] = {"replica": rid, "handoff": {}}
        try:
            self.drain_replica(rid, reason="deregister")
        except Exception as e:
            # already down: nothing to drain OR hand off — just remove
            out["drain_error"] = repr(e)[:200]
            self.remove_replica(rid)
            self._count("left")
            metrics.inc(metrics.FLEET_MEMBER_LEAVE)
            return out
        if handoff:
            out["handoff"] = self._warm_handoff(rid)
        self.remove_replica(rid)
        self._count("left")
        metrics.inc(metrics.FLEET_MEMBER_LEAVE)
        return out

    def _handoff_dest(self, name: str, key, ring_after) -> Optional[str]:
        """The surviving replica a handed-off entry belongs to: the
        post-removal ring owner of the entry's AFFINITY key. Cell entries
        (``("cell", ..., level, prefix)``) and curve chunks (``("curve",
        ..., level, side, kx, ky)``) carry their cell identity — their
        routing-level ancestor keys the ring exactly as scattered /
        affinity queries will look them up. Whole-result entries (opaque
        filter reprs) return None: the caller broadcasts them, since ANY
        survivor may now own that viewport's key."""
        lvl = self._routing_level()
        try:
            if key[0] == "cell":
                level, prefix = int(key[-2]), int(key[-1])
            elif key[0] == "curve":
                level, side = int(key[-4]), int(key[-3])
                bx, by = int(key[-2]) * side, int(key[-1]) * side
                prefix = cellmod.cell_prefix(level, (bx, by))
            else:
                return None
        except (TypeError, ValueError, IndexError):
            return None
        if level >= lvl:
            alvl, aprefix = lvl, prefix >> (2 * (level - lvl))
        else:
            alvl, aprefix = level, prefix
        return ring_after.owner(f"{name}:z{alvl}:{aprefix}")

    def _warm_handoff(self, rid: str) -> Dict[str, Any]:
        """Push the draining replica's hottest cache entries to the NEW
        ring owners (best effort: a failed schema's handoff is reported,
        never fatal — the entries would simply recompute): cell-
        addressable entries go to their own cell's post-removal owner,
        whole-result entries broadcast to every survivor (bounded by
        ``geomesa.fleet.handoff.entries``) — whichever replica now owns
        the drained replica's hottest viewport answers it from cache."""
        import ast

        summary: Dict[str, Any] = {}
        survivors = [m for m in self.ring.members if m != rid]
        if not survivors:
            return {"skipped": "no surviving replica to hand off to"}
        ring_after = self.ring.with_members(survivors)
        limit = config.FLEET_HANDOFF_ENTRIES.to_int() or 256
        src = self._client(rid)
        try:
            schemas = src.replica_status().get("schemas") or []
        except Exception as e:
            return {"error": repr(e)[:200]}
        for sname in schemas:
            try:
                exported = src.cache_export(sname, limit=limit)
                entries = exported.get("entries") or []
                if not entries:
                    summary[sname] = {"entries": 0}
                    continue
                by_dest: Dict[str, list] = {}
                for ent in entries:
                    try:
                        dest = self._handoff_dest(
                            sname, ast.literal_eval(ent[0]), ring_after
                        )
                    except (ValueError, SyntaxError):
                        continue
                    for d in ([dest] if dest is not None else survivors):
                        by_dest.setdefault(d, []).append(ent)
                guard = exported.get("guard") or {}
                restored = 0
                for dest in survivors:  # ring order: deterministic report
                    batch = by_dest.get(dest)
                    if not batch:
                        continue
                    # per-destination isolation: one unreachable/draining
                    # survivor must not void the other destinations'
                    # (possibly already landed) imports
                    try:
                        got = self._client(dest).cache_import(
                            sname, guard, batch
                        )
                    except Exception as e:
                        summary.setdefault(sname, {}).setdefault(
                            "errors", {})[dest] = repr(e)[:200]
                        continue
                    restored += int(got.get("restored", 0))
                    if got.get("skipped"):
                        summary.setdefault(sname, {}).setdefault(
                            "skipped", {})[dest] = got["skipped"]
                row = summary.setdefault(sname, {})
                row.update({
                    "entries": len(entries), "restored": restored,
                    "to": sorted(by_dest),
                })
                if restored:
                    metrics.inc(metrics.FLEET_HANDOFF_ENTRIES, restored)
            except Exception as e:
                summary[sname] = {"error": repr(e)[:200]}
        # standing subscriptions migrate with the cache (docs/STANDING.md)
        summary["subscriptions"] = self._subscription_handoff(
            src, survivors, ring_after
        )
        return summary

    # -- admin -------------------------------------------------------------
    def cordon(self, rid: str, reason: str = "operator") -> None:
        """Router-side cordon: stop ROUTING to the replica (the replica
        itself keeps serving anyone else)."""
        self.registry.cordon(rid, reason)

    def uncordon(self, rid: str) -> bool:
        return self.registry.uncordon(rid)

    def drain_replica(self, rid: str, reason: Optional[str] = None) -> Dict:
        """Replica-side drain via the admin action: the replica answers
        every router's traffic ``[GM-DRAINING]`` until undrained."""
        out = self._client(rid).drain(reason=reason)
        self.registry.set_draining(rid, True)
        return out

    def undrain_replica(self, rid: str) -> Dict:
        out = self._client(rid).undrain()
        self.registry.set_draining(rid, False)
        return out

    def probe(self, rid: str) -> Dict[str, Any]:
        """One health probe (the /healthz analog over Flight): reads the
        replica's status, adopts its drain flag and any NEWER epochs it
        knows (a fresh router learns fleet state from its replicas), and
        feeds the breaker — a failed probe is failure evidence exactly
        like a failed routed call."""
        try:
            st = self._client(rid).replica_status()
        except Exception as e:
            self.registry.record_failure(rid, e)
            self.registry.note_probe(rid, False)
            return {"replica": rid, "ok": False, "error": repr(e)[:300]}
        self.registry.record_success(rid)
        self.registry.set_draining(rid, bool(st.get("draining")))
        # auto-uncordon (docs/RESILIENCE.md §7): K consecutive successful
        # probes clear a router-side cordon (geomesa.fleet.uncordon.probes)
        uncordoned = self.registry.note_probe(rid, True)
        if uncordoned:
            self._count("uncordoned")
        with self._epoch_lock:
            for name, e in (st.get("epochs") or {}).items():
                if self._epochs.get(name, 0) < int(e):
                    self._epochs[name] = int(e)
        out = {"replica": rid, "ok": True, **st}
        if uncordoned:
            out["uncordoned"] = True
        return out

    def probe_all(self) -> Dict[str, Dict[str, Any]]:
        return {rid: self.probe(rid) for rid in self.registry.members()}

    def snapshot(self) -> Dict[str, Any]:
        """The /debug/fleet payload for this router."""
        with self._counter_lock:
            counters = dict(self._counters)
            scatter = {o: dict(row)
                       for o, row in sorted(self._scatter_stats.items())}
        with self._epoch_lock:
            epochs = dict(self._epochs)
        return {
            "name": self.name,
            "ring": list(self.ring.members),
            "replicas": self.registry.snapshot(),
            "summary": self.registry.summary(),
            "epochs": epochs,
            "counters": counters,
            # per-owner-group scatter survivor rows (docs/OBSERVABILITY.md):
            # groups/cells served vs skipped, keyed by owner replica
            "scatter": scatter,
            "serving": self.serving.snapshot(),
            "users": self.serving.user_rollups(),
            # anomaly watchdog advice row (docs/OBSERVABILITY.md §9):
            # {rid: {op: ratio-to-fleet-median}} past the anomaly factor —
            # observation only, the registry never cordons on it
            "anomalies": self.registry.anomaly_report(),
        }

    def close(self) -> None:
        _ROUTERS.discard(self)  # a closed router leaves /debug/fleet
        obs, self._obs = self._obs, None
        if obs is not None:
            try:
                obs.close()
            except Exception:
                pass
        with self._clients_lock:
            clients, self._clients = list(self._clients.values()), {}
        for c in clients:
            try:
                c.close()
            except Exception:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- plumbing ----------------------------------------------------------
    def _client(self, rid: str):
        with self._clients_lock:
            c = self._clients.get(rid)
            if c is None:
                from geomesa_tpu.sidecar.client import GeoFlightClient

                c = self._clients[rid] = GeoFlightClient(
                    self.registry.location(rid),
                    retry_seed=self._retry_seed,
                    header_provider=self._fleet_headers,
                )
        return c

    def _fleet_headers(self) -> List[Tuple[bytes, bytes]]:
        """Per-call fleet headers: the epochs every replica must be AT
        before serving, plus — inside a write's stamp scope — the epoch
        this mutation establishes (the stamped schema's required read
        epoch is E-1: E's data is what the write is creating)."""
        import json as _json

        from geomesa_tpu.sidecar.client import (
            FLEET_EPOCHS_HEADER, FLEET_STAMP_HEADER,
        )

        with self._epoch_lock:
            epochs = dict(self._epochs)
        stamp = getattr(self._tls, "stamp", None)
        out = []
        if stamp:
            for name, e in stamp.items():
                epochs[name] = int(e) - 1
            out.append((FLEET_STAMP_HEADER.encode(),
                        _json.dumps(stamp).encode()))
        epochs = {k: v for k, v in epochs.items() if v > 0}
        if epochs:
            out.append((FLEET_EPOCHS_HEADER.encode(),
                        _json.dumps(epochs).encode()))
        return out

    @contextlib.contextmanager
    def _stamp(self, name: str):
        """Mutation-epoch stamp scope: bumps the schema's fleet epoch and
        exposes the stamp to the header provider for the duration of the
        write. The bump is monotonic and survives a failed write — the
        worst case is one redundant refresh on each replica, never a
        stale serve."""
        with self._epoch_lock:
            e = self._epochs.get(name, 0) + 1
            self._epochs[name] = e
        metrics.inc(metrics.FLEET_EPOCH_BUMP)
        self._tls.stamp = {name: e}
        try:
            yield e
        finally:
            self._tls.stamp = None
        with self._ft_lock:
            self._fts.pop(name, None)  # spec may have changed

    def _count(self, key: str) -> None:
        with self._counter_lock:
            self._counters[key] += 1

    @contextlib.contextmanager
    def _admit(self, op: str, user: Optional[str] = None):
        cap = config.FLEET_MAX_INFLIGHT.to_int()
        with self.serving.admit(f"fleet.{op}", user=user,
                                inflight_cap=256 if cap is None else cap):
            yield

    # -- affinity ----------------------------------------------------------
    def _ft(self, name: str):
        """The schema's FeatureType, fetched once (describe's additive
        ``spec`` field) and cached until a mutation stamp drops it. None
        when no replica can answer — affinity then degrades to the
        filter-hash key, routing still works."""
        with self._ft_lock:
            ft = self._fts.get(name)
        if ft is not None:
            return ft
        from geomesa_tpu.schema.feature_type import FeatureType

        try:
            spec, _rid = self._call(
                name, f"schema:{name}", "describe",
                lambda c: c.schema_spec(name),
            )
            ft = FeatureType.from_spec(name, spec)
        except Exception:
            return None
        with self._ft_lock:
            self._fts[name] = ft
        return ft

    def _parse(self, name: str, ecql: str):
        """(ir filter, FeatureType) for affinity derivation; (None, ft)
        when the text doesn't parse (the replica will raise the typed
        error — affinity just needs a stable key)."""
        ft = self._ft(name)
        try:
            from geomesa_tpu.filter.ecql import parse_ecql

            f = parse_ecql(ecql)
        except Exception:
            f = None
        return f, ft

    @staticmethod
    def _routing_level() -> int:
        lvl = config.FLEET_ROUTING_LEVEL.to_int()
        return 3 if lvl is None else max(1, min(int(lvl), 15))

    def _affinity_key(self, name: str, f, ft) -> str:
        """The query's ring key: the bbox center's SFC cell at the
        routing level (pan/zoom neighbors share it — and share the cell
        prefixes the replica's cache keys on), else a stable hash of the
        canonical filter so exact repeats stay warm on one replica."""
        if f is not None and ft is not None and ft.geom_field is not None:
            split = cellmod.split_bbox_conjunct(f, ft.geom_field)
            if split is not None:
                box = split[0]
                lvl = self._routing_level()
                n = 1 << lvl
                cx = (box.xmin + box.xmax) / 2.0
                cy = (box.ymin + box.ymax) / 2.0
                ix = int(np.clip((cx + 180.0) / 360.0 * n, 0, n - 1))
                iy = int(np.clip((cy + 90.0) / 180.0 * n, 0, n - 1))
                prefix = cellmod.cell_prefix(lvl, (ix, iy))
                return f"{name}:z{lvl}:{prefix}"
        return f"{name}:f:{repr(f)}" if f is not None else f"schema:{name}"

    def _owners(self, key: str) -> List[str]:
        """Ring owner order for ``key``, usable replicas first. The
        unusable tail stays appended: when NOTHING is usable, half-open
        breakers still admit a trial through the client path, which is
        how a recovered fleet heals."""
        ranked = self.ring.owners(key)
        usable = [r for r in ranked if self.registry.usable(r)]
        rest = [r for r in ranked if r not in usable]
        return usable + rest

    # -- routed call core --------------------------------------------------
    def _classify(self, rid: str, e: BaseException, write: bool) -> str:
        """``raise`` (the caller's own error — propagate), ``skip``
        (candidate unusable, no breaker charge), or ``fail`` (replica
        failure evidence: charge + fail over)."""
        from geomesa_tpu.sidecar.client import error_code

        if isinstance(e, QueryTimeoutError):
            # the QUERY's budget (deadline expiry or a shed) — says
            # nothing about replica health, and another replica cannot
            # beat the same expired budget
            return "raise"
        if isinstance(e, CircuitOpenError):
            return "skip"  # already fenced; the breaker said so
        if isinstance(e, DeviceDrainError):
            # a REPLICA-level drain is sticky (the replica asked; probes
            # clear it on undrain); a slot-level [GM-DRAINING] (one
            # dispatcher died and respawned) is transient — skip this
            # attempt without writing the whole replica off
            msg = str(e).lower()
            if "replica" in msg and "draining" in msg:
                self.registry.set_draining(rid, True)
            return "skip"
        code = error_code(e)
        if code == "GM-ARG":
            return "raise"  # the same request fails the same way anywhere
        if code == "GM-OVERLOADED":
            # healthy but saturated: fail over without breaker charge
            return "skip"
        if write:
            import pyarrow.flight as fl

            if code is None and isinstance(e, fl.FlightUnavailableError) \
                    and "connect" in str(e).lower():
                # connection never established: nothing was sent, so a
                # WRITE is safe to fail over (a dead owner must not make
                # ingest unavailable while survivors hold the root)
                self.registry.record_failure(rid, e)
                return "fail"
            # ANY other write failure — uncoded transport (lost ack) or
            # coded GM-INTERNAL (the server may have applied the rows
            # and failed only at persist/ack time) — must never
            # blind-resend on another replica: it would double-apply
            self.registry.record_failure(rid, e)
            return "raise"
        self.registry.record_failure(rid, e)
        return "fail"

    def _call(self, name: Optional[str], key: str, op: str,
              fn: Callable[[Any], Any], write: bool = False,
              owners: Optional[List[str]] = None):
        """One routed call with ring-owner failover. Returns
        ``(value, rid)``; raises :class:`_Exhausted` when every candidate
        failed (callers decide degrade-vs-typed). ``owners`` overrides
        the candidate ORDER (the scatter path pins each group's owner
        first); usability filtering still applies."""
        if owners is None:
            owners = self._owners(key)
        else:
            usable = [r for r in owners if self.registry.usable(r)]
            owners = usable + [r for r in owners if r not in usable]
        last: Optional[BaseException] = None
        failed_over = False
        t_first = time.perf_counter()
        for i, rid in enumerate(owners):
            if resilience.current_deadline().expired:
                raise QueryTimeoutError(
                    "query deadline expired during fleet routing"
                )
            try:
                with tracing.span("fleet.route", replica=rid, attempt=i,
                                  schema=name or "", op=op):
                    t0 = time.perf_counter()
                    out = fn(self._client(rid))
                    dt = time.perf_counter() - t0
            except Exception as e:
                kind = self._classify(rid, e, write)
                if kind == "raise":
                    raise
                last = e
                failed_over = True
                self.registry.note_failed_over(rid)
                continue
            self.registry.record_latency(rid, dt, op)
            self.registry.record_success(rid)
            if failed_over:
                self._count("failover")
                metrics.inc(metrics.FLEET_ROUTE_FAILOVER)
                # the failover COST: everything since the first attempt
                # (failed dials + backoffs + the surviving call)
                metrics.observe("fleet.failover",
                                time.perf_counter() - t_first)
            else:
                self._count("affinity")
                metrics.inc(metrics.FLEET_ROUTE_AFFINITY)
            return out, rid
        raise _Exhausted(last)

    def _route(self, name: str, key: str, op: str,
               fn: Callable[[Any], Any],
               degrade: Optional[Callable[[], Any]] = None,
               user: Optional[str] = None, write: bool = False):
        """Admission + routed call + the typed degradation contract."""
        with self._admit(op, user=user), \
                tracing.start(f"fleet.{op}", schema=name):
            try:
                out, _rid = self._call(name, key, op, fn, write=write)
                return out
            except _Exhausted as ex:
                return self._degrade(name, op, ex.last, degrade)

    def _degrade(self, name: str, op: str, last: Optional[BaseException],
                 degrade: Optional[Callable[[], Any]]):
        err = last if last is not None else RuntimeError(
            "no usable replica in the fleet"
        )
        self._count("partial")
        metrics.inc(metrics.FLEET_ROUTE_PARTIAL)
        if degrade is not None and resilience.partial_allowed():
            resilience.record_skip(
                "fleet.route", part=f"{name}:{op}", error=err
            )
            return degrade()
        raise FleetPartialError(
            f"every ring owner of {op} on {name!r} is down "
            f"(last: {err!r})",
            value=None, ok=0, total=1,
            skipped=[Skipped(source="fleet.route", part=f"{name}:{op}",
                             error=repr(err))],
        ) from last

    # -- scatter-gather for mergeable aggregates ---------------------------
    # (docs/RESILIENCE.md §7 "Scatter-gather for every mergeable
    # aggregate"): counts, unweighted density grids, exact-merge stats
    # sketches, and density-curve block windows split across owner groups;
    # each group scans only its owned cells; the router composes partials
    # with a FIXED-ORDER merge (tree merge in job order for fold kinds,
    # disjoint block slices for curve) so scattered results are
    # bit-identical to the single-replica answer. Eligibility is the
    # cache's partial-merge table (cache/service.merge_bundle) — what the
    # cache may decompose, the fleet may scatter; everything else routes
    # whole on affinity.
    @staticmethod
    def _bbox_ecql(geom: str, boxes: Sequence[Tuple[float, float, float,
                                                    float]]) -> str:
        parts = [
            f"BBOX({geom}, {b[0]!r}, {b[1]!r}, {b[2]!r}, {b[3]!r})"
            for b in boxes
        ]
        return parts[0] if len(parts) == 1 else "(" + " OR ".join(parts) + ")"

    @staticmethod
    def _and_ecql(ecql: str, conjunct: str) -> str:
        if ecql.strip().upper() == "INCLUDE":
            return conjunct
        return f"({ecql}) AND {conjunct}"

    def _scatter_groups(self, name: str, decomp) -> List[Tuple[
            str, List[Tuple[int, int]]]]:
        """Group the decomposition's interior cells by ring owner: each
        cell's ROUTING-level ancestor keys the ring (the same key family
        single-query affinity uses, so a scattered group lands exactly
        where the undecomposed queries for that slice of the world warm
        their caches).

        Owner order is pinned to RING order (``ring.members`` is a sorted
        tuple — identical on every router instance regardless of the
        order replicas registered), never dict-insertion order: the
        partials enter a fixed-order merge, and structure-sensitive
        outputs (survivor group lists, skip records, /debug/fleet rows)
        must be deterministic across router restarts
        (regression-tested)."""
        lvl = self._routing_level()
        groups: Dict[str, List[Tuple[int, int]]] = {}
        for (ix, iy) in decomp.cells:
            if decomp.level >= lvl:
                anc = (ix >> (decomp.level - lvl), iy >> (decomp.level - lvl))
                alvl = lvl
            else:
                anc, alvl = (ix, iy), decomp.level
            key = f"{name}:z{alvl}:{cellmod.cell_prefix(alvl, anc)}"
            groups.setdefault(self.ring.owner(key), []).append((ix, iy))
        return [(o, groups[o]) for o in self.ring.members if o in groups]

    def _usable_count(self) -> int:
        return sum(1 for r in self.registry.members()
                   if self.registry.usable(r))

    def _scatter_eligible(self, name: str, f, ft):
        """The common scatter gate: knob on, >1 usable replica, the
        filter decomposes to >1 interior cells landing on >1 owners.
        Returns ``(decomp, groups)`` or None (route whole)."""
        if f is None or ft is None or not config.FLEET_SCATTER.to_bool():
            return None
        if self._usable_count() <= 1:
            return None
        decomp = cellmod.decompose(f, ft)
        if decomp is None or len(decomp.cells) <= 1:
            return None
        groups = self._scatter_groups(name, decomp)
        if len(groups) <= 1:
            return None
        return decomp, groups

    def _cell_jobs(self, name: str, ecql: str, decomp, groups, ft,
                   call) -> List[Dict[str, Any]]:
        """One job per owner group over ``orig ∧ (its cells)`` plus the
        boundary strips on the schema-affinity owner — disjoint boxes
        covering the query exactly, so partials compose exactly.
        ``call(sub_ecql)`` builds the per-group client call."""
        geom = ft.geom_field
        jobs: List[Dict[str, Any]] = []
        for owner, cells in groups:
            sub = self._and_ecql(
                ecql, self._bbox_ecql(
                    geom, [decomp.cell_boxes[c] for c in cells]
                )
            )
            jobs.append({
                "owner": owner, "phase": sub, "call": call(sub),
                "cells": len(cells),
                "label": f"{owner}:cells[{len(cells)}@z{decomp.level}]",
            })
        if decomp.strips:
            # boundary strips ride the schema-affinity owner
            owner = self.ring.owner(f"schema:{name}")
            sub = self._and_ecql(
                ecql, self._bbox_ecql(geom, decomp.strips)
            )
            jobs.append({
                "owner": owner, "phase": sub, "call": call(sub),
                "cells": 0,
                "label": f"{owner}:strips[{len(decomp.strips)}]",
            })
        return jobs

    def _scatter_dispatch(self, name: str, op: str,
                          jobs: List[Dict[str, Any]]):
        """Fan the owner-group jobs out over a bounded thread pool
        (``geomesa.fleet.scatter.fanout``; 1 = serial) — each job pins
        its group's owner first, then fails over along the schema's ring
        ranking (any replica can serve any cells: shared storage).
        Workers adopt the caller's deadline, config overrides, and span
        context (the partition-prefetch snapshot/adopt discipline), so
        budgets and fault-injection scopes bound every branch. Returns
        ``(results, failed, served)`` — per-job one-tuples (survivors),
        exhaustion errors, and the replica id that actually answered
        each surviving job (the trace stitcher's fetch list); a
        non-retryable error (deadline expiry, GM-ARG) aborts the whole
        scatter and re-raises."""
        results: List[Optional[Tuple[Any]]] = [None] * len(jobs)
        failed: List[Optional[BaseException]] = [None] * len(jobs)
        #: per-job replica that actually answered (failover may move a
        #: job off its pinned owner) — the stitcher's fetch list
        served: List[Optional[str]] = [None] * len(jobs)
        fatal: List[BaseException] = []
        schema_owners = self.ring.owners(f"schema:{name}")

        def run_one(i: int) -> None:
            job = jobs[i]
            order = [job["owner"]] + [
                r for r in schema_owners if r != job["owner"]
            ]
            try:
                out, rid = self._call(
                    name, f"{name}:owner:{job['owner']}", op, job["call"],
                    owners=order,
                )
                results[i] = (out,)
                served[i] = rid
            except _Exhausted as ex:
                failed[i] = ex.last or RuntimeError("no usable replica")

        fanout = config.FLEET_SCATTER_FANOUT.to_int()
        fanout = 8 if fanout is None else int(fanout)  # "0" = serial
        width = max(1, min(len(jobs), fanout))
        if width == 1:
            for i in range(len(jobs)):
                run_one(i)
            return results, failed, served

        it = iter(range(len(jobs)))
        it_lock = threading.Lock()
        ov = config.snapshot_overrides()
        tspan = tracing.snapshot()
        dl = resilience.current_deadline()

        def worker() -> None:
            config.adopt_overrides(ov)
            tracing.adopt(tspan)
            with resilience.adopt_deadline(dl):
                while not fatal:
                    with it_lock:
                        i = next(it, None)
                    if i is None:
                        return
                    try:
                        run_one(i)
                    except BaseException as e:
                        fatal.append(e)
                        return

        threads = [
            threading.Thread(target=worker, daemon=True,
                             name=f"fleet-scatter-{self.name}-{k}")
            for k in range(width)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if fatal:
            raise fatal[0]
        return results, failed, served

    def _scatter_finish(self, name: str, kind: str, op: str,
                        jobs: List[Dict[str, Any]], results, failed):
        """Per-owner-group survivor accounting shared by every scattered
        kind: skip records carry each missing group's sub-query verbatim
        (``Skipped.phase`` — re-runnable once the fleet heals, so the
        degraded answer reconciles to the full one exactly), the
        ``/debug/fleet`` scatter rows update, and partial metrics bump
        per skipped group. Returns the skip records; the caller merges
        survivors and applies the strict-vs-degraded contract."""
        skipped: List[Skipped] = []
        with self._counter_lock:
            for i, job in enumerate(jobs):
                row = self._scatter_stats.setdefault(job["owner"], {
                    "groups": 0, "cells": 0,
                    "skipped_groups": 0, "skipped_cells": 0,
                })
                if failed[i] is None:
                    row["groups"] += 1
                    row["cells"] += job["cells"]
                else:
                    row["skipped_groups"] += 1
                    row["skipped_cells"] += job["cells"]
        for i, job in enumerate(jobs):
            err = failed[i]
            if err is None:
                continue
            rec = Skipped(source="fleet.route",
                          part=f"{name}:{job['label']}", error=repr(err),
                          phase=job["phase"])
            if resilience.partial_allowed():
                resilience.record_skip(
                    "fleet.route", part=f"{name}:{job['label']}",
                    error=err, phase=job["phase"],
                )
            skipped.append(rec)
            self._count("partial")
            metrics.inc(metrics.FLEET_ROUTE_PARTIAL)
        return skipped

    def _note_stitch(self, served: List[Optional[str]]) -> None:
        """Scatter-completion stitch hook (docs/OBSERVABILITY.md §9): one
        bounded enqueue of (trace id, serving replicas) for the async
        stitcher — ZERO added blocking work on the query path. Gated on
        the stitch knob BEFORE touching the observability plane, so a
        stitch-off fleet never even constructs it."""
        if not config.FLEET_STITCH.to_bool():
            return
        tid = tracing.current_trace_id()
        if tid is None:
            return
        owners = [r for r in served if r is not None]
        if owners:
            self.observability().note_scatter(tid, owners)

    #: merge-cost histogram shape (ms): router-side merges are host-light
    _MERGE_BUCKETS_MS = (0.1, 0.5, 1.0, 5.0, 20.0, 100.0, 500.0)

    def _observe_merge(self, seconds: float) -> None:
        metrics.registry().histogram(
            metrics.FLEET_SCATTER_MERGE_MS,
            buckets=self._MERGE_BUCKETS_MS, unit="ms",
        ).observe(seconds * 1e3)

    def _scatter_fold(self, name: str, kind: str, op: str,
                      jobs: List[Dict[str, Any]], zero, merge,
                      user: Optional[str]):
        """The fold-merge scatter body (count / density / stats): admit
        once, dispatch the owner groups, tree-merge survivors in FIXED
        job order (the docs/SCALE.md sharded-scan merge argument lifted
        to replicas — the order depends only on the ring-pinned group
        order, never on completion timing), and apply the §3 degradation
        contract with exact per-owner-group survivor accounting."""
        from geomesa_tpu.parallel.devices import tree_merge

        self._count("scatter")
        metrics.inc(metrics.FLEET_ROUTE_SCATTER)
        metrics.inc(f"{metrics.FLEET_SCATTER_KIND_PREFIX}.{kind}")
        with self._admit(op, user=user), \
                tracing.start(f"fleet.{op}", schema=name, scatter=True,
                              groups=len(jobs)):
            results, failed, served = self._scatter_dispatch(name, op, jobs)
            skipped = self._scatter_finish(
                name, kind, op, jobs, results, failed
            )
            t0 = time.perf_counter()
            merged = tree_merge(
                [r[0] for r in results if r is not None], merge
            )
            self._observe_merge(time.perf_counter() - t0)
            self._note_stitch(served)
        if merged is None:
            merged = zero()
        ok = len(jobs) - len(skipped)
        if skipped and not resilience.partial_allowed():
            raise FleetPartialError(
                f"{len(skipped)} owner group(s) of {kind} on {name!r} are "
                f"down (survivors: {ok}/{len(jobs)} groups: "
                f"{[r.part for r in skipped]} missing)",
                value=merged, ok=ok, total=len(jobs), skipped=skipped,
            )
        return merged

    def _scatter_count(self, name: str, ecql: str, decomp, groups, ft,
                       call_kw: Dict[str, Any],
                       user: Optional[str]) -> int:
        """Exact count scattered by cell ownership: integer partials over
        disjoint boxes add exactly, so the sum is bit-identical to the
        whole-query count."""
        zero, merge = cache_service.merge_bundle("count")
        jobs = self._cell_jobs(
            name, ecql, decomp, groups, ft,
            lambda sub: (lambda c, e=sub: c.count(name, e, **call_kw)),
        )
        total = self._scatter_fold(
            name, "count", "count", jobs, zero, merge, user
        )
        return int(total)

    def _scatter_density(self, name: str, ecql: str, decomp, groups, ft,
                         bbox, width: int, height: int, auths,
                         user: Optional[str]) -> np.ndarray:
        """Unweighted density scattered by cell ownership: every row
        lands in exactly one disjoint sub-query, each +1 is exact in f32
        (integer counts to 2^24), so per-group grid addition reproduces
        the single-replica raster bit-for-bit — the cache's cell-
        partition argument (docs/CACHE.md "Exactness") over replicas.
        The render raster (``bbox`` x ``width`` x ``height``) is FIXED
        across every sub-call; only the filter splits."""
        zero, merge = cache_service.merge_bundle(
            "density", shape=(height, width)
        )
        jobs = self._cell_jobs(
            name, ecql, decomp, groups, ft,
            lambda sub: (lambda c, e=sub: c.density(
                name, e, bbox=bbox, width=width, height=height,
                weight=None, auths=auths,
            )),
        )
        return self._scatter_fold(
            name, "density", "density", jobs, zero, merge, user
        )

    def _scatter_stats_agg(self, name: str, stat_spec: str, ecql: str,
                           decomp, groups, ft, auths,
                           user: Optional[str]):
        """Exact-merge stats scattered by cell ownership: eligibility and
        merge come from the cache's partial-merge table
        (cache/service.merge_bundle — EXACT_MERGE_KINDS only: integer /
        extremum algebra, order-independent), so the fixed-order sketch
        merge equals the single-replica scan exactly."""
        bundle = cache_service.merge_bundle("stats", stat_spec=stat_spec)
        assert bundle is not None  # caller gated on eligibility
        zero, merge = bundle
        jobs = self._cell_jobs(
            name, ecql, decomp, groups, ft,
            lambda sub: (lambda c, e=sub: c.stats(
                name, stat_spec, e, auths=auths,
            )),
        )
        return self._scatter_fold(
            name, "stats", "stats", jobs, zero, merge, user
        )

    def _scatter_curve(self, name: str, ecql: str, ft, level: int, bbox,
                       auths, user: Optional[str]):
        """Density-curve scattered by BLOCK windows (not coordinate
        cells — block membership is an SFC quantization no coordinate
        predicate reproduces at block edges, the reason the cache keeps
        curve whole in coordinate space): the query's snapped block
        window splits into routing-level-aligned sub-windows grouped by
        ring owner; each sub-call asks for EXACTLY its blocks (the bbox
        passed is the sub-window's block-center box, so the replica's
        outward snap lands on precisely those blocks) with the filter
        narrowed to a one-block-widened cover of the sub-window (rows in
        the margin quantize to out-of-window blocks and crop away — a
        row of the window can never be lost). Block counts are window-
        independent (CDF differences over the z2-sorted scan), so the
        disjoint sub-grids COMPOSE BY BLOCK into the full grid
        bit-identically. Returns ``(grid, snapped_bbox)``."""
        import json as _json

        from geomesa_tpu.api.dataset import GeoDataset

        geom = ft.geom_field
        (ix0, iy0, ix1, iy1), snapped = GeoDataset._snap_blocks(
            bbox, level
        )
        lvl = min(self._routing_level(), level)
        # coarsen the grouping level until the job count is bounded: a
        # world-scale window at the routing level would mean one RPC per
        # routing cell — per-call overhead would eat the scatter win
        while lvl > 1:
            sh = level - lvl
            n_jobs = (((ix1 >> sh) - (ix0 >> sh) + 1)
                      * ((iy1 >> sh) - (iy0 >> sh) + 1))
            if n_jobs <= 16:
                break
            lvl -= 1
        shift = level - lvl
        n_side = 1 << level
        bsx, bsy = 360.0 / n_side, 180.0 / n_side
        subs = []  # (owner, (sx0, sy0, sx1, sy1)) block sub-windows
        for ay in range(iy0 >> shift, (iy1 >> shift) + 1):
            for ax in range(ix0 >> shift, (ix1 >> shift) + 1):
                sx0, sx1 = max(ax << shift, ix0), \
                    min(((ax + 1) << shift) - 1, ix1)
                sy0, sy1 = max(ay << shift, iy0), \
                    min(((ay + 1) << shift) - 1, iy1)
                key = f"{name}:z{lvl}:{cellmod.cell_prefix(lvl, (ax, ay))}"
                subs.append((self.ring.owner(key), (sx0, sy0, sx1, sy1)))
        if len(subs) <= 1 or len({o for o, _ in subs}) <= 1:
            return None  # one owner would serve it all: route whole
        # ring-pinned job order (the _scatter_groups determinism rule)
        order_of = {o: i for i, o in enumerate(self.ring.members)}
        subs.sort(key=lambda s: (order_of[s[0]], s[1][1], s[1][0]))

        jobs: List[Dict[str, Any]] = []
        for owner, (sx0, sy0, sx1, sy1) in subs:
            # block-center box: snaps back to exactly [sx0..sx1]x[sy0..sy1]
            sub_bbox = ((sx0 + 0.5) * bsx - 180.0,
                        (sy0 + 0.5) * bsy - 90.0,
                        (sx1 + 0.5) * bsx - 180.0,
                        (sy1 + 0.5) * bsy - 90.0)
            # filter cover widened a full block each side: conservative
            # against float edge error, exact by the crop argument above
            cover = (max(sx0 * bsx - 180.0 - bsx, -180.0),
                     max(sy0 * bsy - 90.0 - bsy, -90.0),
                     min((sx1 + 1) * bsx - 180.0 + bsx, 180.0),
                     min((sy1 + 1) * bsy - 90.0 + bsy, 90.0))
            sub_ecql = self._and_ecql(ecql, self._bbox_ecql(geom, [cover]))
            jobs.append({
                "owner": owner,
                "phase": _json.dumps({"ecql": ecql, "level": int(level),
                                      "bbox": list(sub_bbox)}),
                "call": (lambda c, e=sub_ecql, b=sub_bbox: c.density_curve(
                    name, e, level=level, bbox=b, auths=auths,
                )),
                "cells": (sx1 - sx0 + 1) * (sy1 - sy0 + 1),
                "label": (f"{owner}:blocks[{sx0},{sy0}..{sx1},{sy1}"
                          f"@z{level}]"),
            })
        self._count("scatter")
        metrics.inc(metrics.FLEET_ROUTE_SCATTER)
        metrics.inc(f"{metrics.FLEET_SCATTER_KIND_PREFIX}.curve")
        out = np.zeros((iy1 - iy0 + 1, ix1 - ix0 + 1), np.float64)
        with self._admit("density_curve", user=user), \
                tracing.start("fleet.density_curve", schema=name,
                              scatter=True, groups=len(jobs)):
            results, failed, served = self._scatter_dispatch(
                name, "density_curve", jobs
            )
            skipped = self._scatter_finish(
                name, "curve", "density_curve", jobs, results, failed
            )
            t0 = time.perf_counter()
            for res, (_o, (sx0, sy0, sx1, sy1)) in zip(results, subs):
                if res is None:
                    continue
                grid, _sn = res[0]
                out[sy0 - iy0: sy1 - iy0 + 1,
                    sx0 - ix0: sx1 - ix0 + 1] = grid
            self._observe_merge(time.perf_counter() - t0)
            self._note_stitch(served)
        ok = len(jobs) - len(skipped)
        if skipped and not resilience.partial_allowed():
            raise FleetPartialError(
                f"{len(skipped)} owner group(s) of curve on {name!r} are "
                f"down (survivors: {ok}/{len(jobs)} groups: "
                f"{[r.part for r in skipped]} missing)",
                value=(out, snapped), ok=ok, total=len(jobs),
                skipped=skipped,
            )
        return out, snapped

    # -- public API (GeoDataset-shaped) ------------------------------------
    def count(self, name: str, ecql: str = "INCLUDE", exact: bool = True,
              auths: Optional[Sequence[str]] = None,
              region: Optional[str] = None,
              speculative_ok: bool = False,
              user: Optional[str] = None) -> int:
        call_kw: Dict[str, Any] = {"exact": exact}
        if auths is not None:
            call_kw["auths"] = list(auths)
        if region is not None:
            call_kw["region"] = region
        if speculative_ok:
            call_kw["speculative_ok"] = True
        f, ft = self._parse(name, ecql)
        # speculative_ok never scatters: one overloaded owner group could
        # answer its sub-count with the planner's coarse estimate, and the
        # sum would present an estimate as the exact scattered total
        if exact and region is None and not speculative_ok:
            el = self._scatter_eligible(name, f, ft)
            if el is not None:
                return self._scatter_count(
                    name, ecql, el[0], el[1], ft, call_kw, user
                )
        key = self._affinity_key(name, f, ft)
        return self._route(
            name, key, "count",
            lambda c: c.count(name, ecql, **call_kw),
            degrade=lambda: 0, user=user,
        )

    def density(self, name: str, ecql: str = "INCLUDE", bbox=None,
                width: int = 256, height: int = 256,
                weight: Optional[str] = None,
                auths: Optional[Sequence[str]] = None,
                region: Optional[str] = None,
                user: Optional[str] = None) -> np.ndarray:
        f, ft = self._parse(name, ecql)
        if weight is None and region is None and bbox is not None:
            # unweighted grids add bit-exactly cell-by-cell (weighted
            # f32 rounding is order-dependent: whole-route only)
            el = self._scatter_eligible(name, f, ft)
            if el is not None:
                return self._scatter_density(
                    name, ecql, el[0], el[1], ft, bbox, width, height,
                    auths, user,
                )
        key = self._affinity_key(name, f, ft)
        return self._route(
            name, key, "density",
            lambda c: c.density(name, ecql, bbox=bbox, width=width,
                                height=height, weight=weight, auths=auths,
                                region=region),
            degrade=lambda: np.zeros((height, width), np.float32),
            user=user,
        )

    def density_curve(self, name: str, ecql: str = "INCLUDE",
                      level: int = 9, bbox=None,
                      weight: Optional[str] = None,
                      auths: Optional[Sequence[str]] = None,
                      user: Optional[str] = None):
        f, ft = self._parse(name, ecql)
        if (weight is None and bbox is not None and f is not None
                and ft is not None and ft.geom_field is not None
                and config.FLEET_SCATTER.to_bool()
                and self._usable_count() > 1):
            # block-window scatter: chunks compose by block (exact f64
            # integer counts) — see _scatter_curve for the bbox snapping
            # and filter-cover argument
            out = self._scatter_curve(
                name, ecql, ft, int(level), bbox, auths, user
            )
            if out is not None:
                return out
        key = self._affinity_key(name, f, ft)
        return self._route(
            name, key, "density_curve",
            lambda c: c.density_curve(name, ecql, level=level, bbox=bbox,
                                      weight=weight, auths=auths),
            user=user,
        )

    def stats(self, name: str, stat_spec: str, ecql: str = "INCLUDE",
              auths: Optional[Sequence[str]] = None,
              region: Optional[str] = None,
              user: Optional[str] = None):
        from geomesa_tpu.stats import parse_stat

        f, ft = self._parse(name, ecql)
        if region is None:
            # eligibility IS the cache's partial-merge table: only specs
            # whose every leaf sketch merges exactly may scatter
            try:
                mergeable = cache_service.merge_bundle(
                    "stats", stat_spec=stat_spec
                ) is not None
            except Exception:
                mergeable = False  # unparseable spec: the replica raises
            if mergeable:
                el = self._scatter_eligible(name, f, ft)
                if el is not None:
                    return self._scatter_stats_agg(
                        name, stat_spec, ecql, el[0], el[1], ft, auths,
                        user,
                    )
        key = self._affinity_key(name, f, ft)
        return self._route(
            name, key, "stats",
            lambda c: c.stats(name, stat_spec, ecql, auths=auths,
                              region=region),
            degrade=lambda: parse_stat(stat_spec), user=user,
        )

    def query(self, name: str, ecql: str = "INCLUDE",
              user: Optional[str] = None, **kw):
        f, ft = self._parse(name, ecql)
        key = self._affinity_key(name, f, ft)
        return self._route(
            name, key, "query",
            lambda c: c.query(name, ecql, **kw), user=user,
        )

    def explain(self, name: str, ecql: str = "INCLUDE",
                user: Optional[str] = None) -> str:
        f, ft = self._parse(name, ecql)
        key = self._affinity_key(name, f, ft)
        return self._route(
            name, key, "explain", lambda c: c.explain(name, ecql),
            user=user,
        )

    def list_schemas(self, user: Optional[str] = None) -> List[str]:
        return self._route(
            "", "schemas", "list-schemas", lambda c: c.list_schemas(),
            user=user,
        )

    # -- writes (router-stamped epochs) ------------------------------------
    def create_schema(self, name: str, spec: str,
                      user: Optional[str] = None) -> str:
        with self._stamp(name):
            return self._route(
                name, f"schema:{name}", "create-schema",
                lambda c: c.create_schema(name, spec),
                user=user, write=True,
            )

    def delete_schema(self, name: str, user: Optional[str] = None) -> None:
        with self._stamp(name):
            self._route(
                name, f"schema:{name}", "delete-schema",
                lambda c: c.delete_schema(name), user=user, write=True,
            )

    def insert_arrow(self, name: str, table,
                     user: Optional[str] = None) -> None:
        """Stamped ingest: the receiving replica applies the rows, saves
        the shared root, and advances to the stamped epoch; every other
        replica refreshes before its next serve of this schema."""
        with self._stamp(name):
            self._route(
                name, f"schema:{name}", "insert",
                lambda c: c.insert_arrow(name, table),
                user=user, write=True,
            )

    # -- standing subscriptions (docs/STANDING.md; PROTOCOL §5 v1.6) -------
    def subscribe(self, name: str, aggregate: str, bbox=None,
                  region: Optional[str] = None, width: int = 256,
                  height: int = 256, levels: Optional[int] = None,
                  stat_spec: Optional[str] = None,
                  user: Optional[str] = None) -> str:
        """Register a standing viewport on its RING OWNER: the sub_id is
        minted router-side from the viewport's route key (the center
        cell at the routing level — the same key family the cache and
        scatter paths use), so every later poll/unsubscribe re-derives
        the owner from the id alone, across membership changes."""
        from geomesa_tpu.subscribe import spec as subspec

        sp = subspec.make_spec(
            name, aggregate, bbox=bbox, region=region, width=width,
            height=height, levels=levels, stat_spec=stat_spec,
        )
        key = sp.route_key(self._routing_level())
        sub_id = f"{key}:{uuid.uuid4().hex[:12]}"
        return self._route(
            name, key, "subscribe",
            lambda c: c.subscribe(
                name, aggregate, bbox=list(sp.bbox), region=sp.region,
                width=sp.width, height=sp.height, levels=sp.levels,
                stat_spec=sp.stat_spec, sub_id=sub_id,
            ),
            user=user,
        )

    def _route_subscription(self, sub_id: str, op: str,
                            fn: Callable[[Any], Any],
                            user: Optional[str] = None):
        """Owner-order failover keyed by the sub_id's EMBEDDED route key.
        ``[GM-SUB-UNKNOWN]`` is not failure evidence — the replica is
        healthy, the subscription just lives elsewhere after a
        membership change — so it walks to the next ring owner without
        charging the breaker; real failures classify as usual."""
        from geomesa_tpu.subscribe import route_key_of

        key = route_key_of(sub_id)
        name = sub_id.split(":", 1)[0]
        with self._admit(op, user=user), \
                tracing.start(f"fleet.{op}", schema=name):
            last: Optional[BaseException] = None
            unknown = False
            for rid in self._owners(key):
                try:
                    out = fn(self._client(rid))
                except Exception as e:
                    if "[GM-SUB-UNKNOWN]" in str(e):
                        last, unknown = e, True
                        continue
                    kind = self._classify(rid, e, write=False)
                    if kind == "raise":
                        raise
                    last = e
                    continue
                self.registry.record_success(rid)
                return out
            if unknown:
                raise KeyError(
                    f"[GM-SUB-UNKNOWN] no ring owner holds subscription "
                    f"{sub_id!r}"
                )
            return self._degrade(name, op, last, None)

    def subscription_poll(self, sub_id: str, cursor: int = 0,
                          user: Optional[str] = None) -> Dict:
        """Current standing result + updates past ``cursor`` from
        whichever ring owner holds the subscription."""
        return self._route_subscription(
            sub_id, "subscribe-poll",
            lambda c: c.subscribe_poll(sub_id, cursor=cursor), user=user,
        )

    def unsubscribe(self, sub_id: str,
                    user: Optional[str] = None) -> bool:
        return bool(self._route_subscription(
            sub_id, "unsubscribe", lambda c: c.unsubscribe(sub_id),
            user=user,
        ))

    def _subscription_handoff(self, src, survivors: List[str],
                              ring_after) -> Dict[str, Any]:
        """LEAVE half of standing-query migration: export every standing
        group from the drained replica (subscribe-export is admin — it
        answers mid-drain, like cache-export) and import each group on
        its route key's POST-REMOVAL ring owner. A matching guard adopts
        results + update rings verbatim (zero missed, zero duplicated
        updates); a mismatch re-scans on the new owner (``resync``)."""
        try:
            exported = src.subscribe_export()
        except Exception as e:
            return {"error": repr(e)[:200]}
        groups = exported.get("groups") or []
        if not groups:
            return {"groups": 0}
        guards = exported.get("guards") or {}
        by_dest: Dict[str, list] = {}
        for g in groups:
            by_dest.setdefault(
                ring_after.owner(g["route_key"]), []
            ).append(g)
        out: Dict[str, Any] = {"groups": len(groups),
                               "adopted": 0, "resynced": 0,
                               "to": sorted(by_dest)}
        for dest in sorted(by_dest):
            try:
                got = self._client(dest).subscribe_import(
                    {"groups": by_dest[dest], "guards": guards}
                )
            except Exception as e:
                out.setdefault("errors", {})[dest] = repr(e)[:200]
                continue
            out["adopted"] += int(got.get("adopted", 0))
            out["resynced"] += int(got.get("resynced", 0))
        return out

    def _pull_subscriptions(self, rid: str) -> None:
        """JOIN half of standing-query migration: route keys the NEW
        replica now owns move from their previous owners — export with
        ``remove=True`` (the source drops them atomically under its
        engine lock) then import here. Best effort: a failed move leaves
        the group where it was, and polls still find it because the old
        owner stays on the key's ring walk."""
        for src in list(self.ring.members):
            if src == rid:
                continue
            try:
                c = self._client(src)
                snap = c.subscribe_export()
                keys = sorted({
                    g["route_key"] for g in snap.get("groups") or []
                    if self.ring.owner(g["route_key"]) == rid
                })
                if not keys:
                    continue
                moved = c.subscribe_export(keys=keys, remove=True)
                if moved.get("groups"):
                    self._client(rid).subscribe_import(moved)
            except Exception:
                continue

    # -- fleet-wide views --------------------------------------------------
    def replica_metrics(self) -> Dict[str, Dict]:
        """Per-replica /metrics snapshots (best effort; a down replica
        reports its error instead) — the bench's affinity-hit-ratio
        source."""
        out: Dict[str, Dict] = {}
        for rid in self.registry.members():
            try:
                out[rid] = self._client(rid).metrics()
            except Exception as e:
                out[rid] = {"error": repr(e)[:200]}
        return out
