"""Cell-affinity fleet router (docs/RESILIENCE.md §7).

A :class:`FleetRouter` fronts N replica sidecars over one shared storage
root with a GeoDataset-shaped remote API. Per query it:

1. derives an **affinity key** from the query's SFC cell cover (the same
   cell family the aggregate cache decomposes to, cache/cells.py): the
   bbox center's cell at ``geomesa.fleet.routing.level`` — so nearby
   viewports land on the same replica and its flat+hierarchy cache stays
   hot for its slice of the world, making fleet cache capacity additive;
2. ranks replicas on the **rendezvous ring** (fleet/ring.py) and serves
   from the first USABLE owner (registry-filtered: cordoned / draining /
   open-breaker replicas are skipped);
3. **fails over** to the next ring owner when a call fails retryably —
   deadline-aware (an expired budget stops the walk typed), with the
   replica's breaker charged for transport/internal failures and its
   latency fed to the outlier detector;
4. when EVERY owner is down, **degrades typed**: under ``allow_partial()``
   additive aggregates return the survivor total with the skip recorded
   (``[GM-FLEET-PARTIAL]`` accounting, resilience §3 generalized from
   partitions to replicas); strict mode raises
   :class:`~geomesa_tpu.resilience.FleetPartialError`;
5. **scatters** decomposable exact counts across owner groups
   (``geomesa.fleet.scatter``): each replica scans only its own cells —
   integer partials add exactly, so the scatter is bit-identical to the
   single-process scan by the cache's cell-partition argument — and a
   dead owner degrades with EXACT survivor totals (the surviving groups'
   sum plus a per-group skip record);
6. stamps **mutation epochs** onto writes and requires them on reads
   (sidecar fleet headers), so a restarted or failed-over replica
   refreshes from the shared root before it can serve a pre-mutation
   aggregate.

Admission/fair-share rides the same ``_UserLedger``-backed scheduler the
serving layer uses (inline mode + the ``geomesa.fleet.max.inflight``
bound), so ``/debug/fleet`` rollups and shed decisions share one
accounting with every other surface.
"""

from __future__ import annotations

import contextlib
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from geomesa_tpu import config, metrics, resilience, tracing
from geomesa_tpu.cache import cells as cellmod
from geomesa_tpu.fleet.registry import ReplicaRegistry
from geomesa_tpu.fleet.ring import RendezvousRing
from geomesa_tpu.resilience import (
    AdmissionRejectedError, CircuitOpenError, DeviceDrainError,
    FleetPartialError, QueryTimeoutError, Skipped,
)

#: routers alive in this process (weak — /debug/fleet reads them)
_ROUTERS: "weakref.WeakSet" = weakref.WeakSet()


def debug_fleet() -> Dict[str, Any]:
    """The /debug/fleet payload: one snapshot per live router in this
    process (obs.py mounts it; docs/RESILIENCE.md §7)."""
    routers = [r.snapshot() for r in list(_ROUTERS)]
    return {"routers": routers}


class _Exhausted(Exception):
    """Internal: every candidate replica failed; carries the last error."""

    def __init__(self, last: Optional[BaseException]):
        super().__init__(repr(last))
        self.last = last


class FleetRouter:
    """See the module docstring. Thread-safe; one per front-end process."""

    def __init__(self, replicas: Dict[str, str],
                 retry_seed: Optional[int] = None,
                 name: str = "geomesa-fleet-router"):
        from geomesa_tpu.serving import QueryScheduler

        self.name = name
        self.registry = ReplicaRegistry(replicas)
        self.ring = RendezvousRing(replicas)
        self._retry_seed = retry_seed
        self._clients: Dict[str, Any] = {}
        self._clients_lock = threading.Lock()
        #: authoritative per-schema fleet epochs (router-stamped writes
        #: bump them; probes adopt newer ones learned from replicas)
        self._epochs: Dict[str, int] = {}
        self._epoch_lock = threading.Lock()
        #: per-thread active write stamp ({schema: epoch}) — read by the
        #: clients' header provider while the stamped call is in flight
        self._tls = threading.local()
        #: fleet-level admission + per-user ledger: the same policy/
        #: accounting object the serving scheduler runs (docs/SERVING.md)
        self.serving = QueryScheduler(name)
        self._fts: Dict[str, Any] = {}
        self._ft_lock = threading.Lock()
        self._counters = {"affinity": 0, "failover": 0, "scatter": 0,
                          "partial": 0}
        self._counter_lock = threading.Lock()
        _ROUTERS.add(self)

    # -- membership --------------------------------------------------------
    def add_replica(self, rid: str, location: str) -> None:
        """Add (or re-home) a replica. A cached client to the id's OLD
        location is dropped — a restarted replica usually comes back on
        a fresh port."""
        self.registry.add(rid, location)
        self.ring = RendezvousRing(set(self.ring.members) | {rid})
        with self._clients_lock:
            c = self._clients.pop(rid, None)
        if c is not None:
            try:
                c.close()
            except Exception:
                pass

    def remove_replica(self, rid: str) -> None:
        self.registry.remove(rid)
        members = [m for m in self.ring.members if m != rid]
        self.ring = RendezvousRing(members)
        with self._clients_lock:
            c = self._clients.pop(rid, None)
        if c is not None:
            try:
                c.close()
            except Exception:
                pass

    # -- admin -------------------------------------------------------------
    def cordon(self, rid: str, reason: str = "operator") -> None:
        """Router-side cordon: stop ROUTING to the replica (the replica
        itself keeps serving anyone else)."""
        self.registry.cordon(rid, reason)

    def uncordon(self, rid: str) -> bool:
        return self.registry.uncordon(rid)

    def drain_replica(self, rid: str, reason: Optional[str] = None) -> Dict:
        """Replica-side drain via the admin action: the replica answers
        every router's traffic ``[GM-DRAINING]`` until undrained."""
        out = self._client(rid).drain(reason=reason)
        self.registry.set_draining(rid, True)
        return out

    def undrain_replica(self, rid: str) -> Dict:
        out = self._client(rid).undrain()
        self.registry.set_draining(rid, False)
        return out

    def probe(self, rid: str) -> Dict[str, Any]:
        """One health probe (the /healthz analog over Flight): reads the
        replica's status, adopts its drain flag and any NEWER epochs it
        knows (a fresh router learns fleet state from its replicas), and
        feeds the breaker — a failed probe is failure evidence exactly
        like a failed routed call."""
        try:
            st = self._client(rid).replica_status()
        except Exception as e:
            self.registry.record_failure(rid, e)
            return {"replica": rid, "ok": False, "error": repr(e)[:300]}
        self.registry.record_success(rid)
        self.registry.set_draining(rid, bool(st.get("draining")))
        with self._epoch_lock:
            for name, e in (st.get("epochs") or {}).items():
                if self._epochs.get(name, 0) < int(e):
                    self._epochs[name] = int(e)
        return {"replica": rid, "ok": True, **st}

    def probe_all(self) -> Dict[str, Dict[str, Any]]:
        return {rid: self.probe(rid) for rid in self.registry.members()}

    def snapshot(self) -> Dict[str, Any]:
        """The /debug/fleet payload for this router."""
        with self._counter_lock:
            counters = dict(self._counters)
        with self._epoch_lock:
            epochs = dict(self._epochs)
        return {
            "name": self.name,
            "ring": list(self.ring.members),
            "replicas": self.registry.snapshot(),
            "summary": self.registry.summary(),
            "epochs": epochs,
            "counters": counters,
            "serving": self.serving.snapshot(),
            "users": self.serving.user_rollups(),
        }

    def close(self) -> None:
        _ROUTERS.discard(self)  # a closed router leaves /debug/fleet
        with self._clients_lock:
            clients, self._clients = list(self._clients.values()), {}
        for c in clients:
            try:
                c.close()
            except Exception:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- plumbing ----------------------------------------------------------
    def _client(self, rid: str):
        with self._clients_lock:
            c = self._clients.get(rid)
            if c is None:
                from geomesa_tpu.sidecar.client import GeoFlightClient

                c = self._clients[rid] = GeoFlightClient(
                    self.registry.location(rid),
                    retry_seed=self._retry_seed,
                    header_provider=self._fleet_headers,
                )
        return c

    def _fleet_headers(self) -> List[Tuple[bytes, bytes]]:
        """Per-call fleet headers: the epochs every replica must be AT
        before serving, plus — inside a write's stamp scope — the epoch
        this mutation establishes (the stamped schema's required read
        epoch is E-1: E's data is what the write is creating)."""
        import json as _json

        from geomesa_tpu.sidecar.client import (
            FLEET_EPOCHS_HEADER, FLEET_STAMP_HEADER,
        )

        with self._epoch_lock:
            epochs = dict(self._epochs)
        stamp = getattr(self._tls, "stamp", None)
        out = []
        if stamp:
            for name, e in stamp.items():
                epochs[name] = int(e) - 1
            out.append((FLEET_STAMP_HEADER.encode(),
                        _json.dumps(stamp).encode()))
        epochs = {k: v for k, v in epochs.items() if v > 0}
        if epochs:
            out.append((FLEET_EPOCHS_HEADER.encode(),
                        _json.dumps(epochs).encode()))
        return out

    @contextlib.contextmanager
    def _stamp(self, name: str):
        """Mutation-epoch stamp scope: bumps the schema's fleet epoch and
        exposes the stamp to the header provider for the duration of the
        write. The bump is monotonic and survives a failed write — the
        worst case is one redundant refresh on each replica, never a
        stale serve."""
        with self._epoch_lock:
            e = self._epochs.get(name, 0) + 1
            self._epochs[name] = e
        metrics.inc(metrics.FLEET_EPOCH_BUMP)
        self._tls.stamp = {name: e}
        try:
            yield e
        finally:
            self._tls.stamp = None
        with self._ft_lock:
            self._fts.pop(name, None)  # spec may have changed

    def _count(self, key: str) -> None:
        with self._counter_lock:
            self._counters[key] += 1

    @contextlib.contextmanager
    def _admit(self, op: str, user: Optional[str] = None):
        cap = config.FLEET_MAX_INFLIGHT.to_int()
        with self.serving.admit(f"fleet.{op}", user=user,
                                inflight_cap=256 if cap is None else cap):
            yield

    # -- affinity ----------------------------------------------------------
    def _ft(self, name: str):
        """The schema's FeatureType, fetched once (describe's additive
        ``spec`` field) and cached until a mutation stamp drops it. None
        when no replica can answer — affinity then degrades to the
        filter-hash key, routing still works."""
        with self._ft_lock:
            ft = self._fts.get(name)
        if ft is not None:
            return ft
        from geomesa_tpu.schema.feature_type import FeatureType

        try:
            spec, _rid = self._call(
                name, f"schema:{name}", "describe",
                lambda c: c.schema_spec(name),
            )
            ft = FeatureType.from_spec(name, spec)
        except Exception:
            return None
        with self._ft_lock:
            self._fts[name] = ft
        return ft

    def _parse(self, name: str, ecql: str):
        """(ir filter, FeatureType) for affinity derivation; (None, ft)
        when the text doesn't parse (the replica will raise the typed
        error — affinity just needs a stable key)."""
        ft = self._ft(name)
        try:
            from geomesa_tpu.filter.ecql import parse_ecql

            f = parse_ecql(ecql)
        except Exception:
            f = None
        return f, ft

    @staticmethod
    def _routing_level() -> int:
        lvl = config.FLEET_ROUTING_LEVEL.to_int()
        return 3 if lvl is None else max(1, min(int(lvl), 15))

    def _affinity_key(self, name: str, f, ft) -> str:
        """The query's ring key: the bbox center's SFC cell at the
        routing level (pan/zoom neighbors share it — and share the cell
        prefixes the replica's cache keys on), else a stable hash of the
        canonical filter so exact repeats stay warm on one replica."""
        if f is not None and ft is not None and ft.geom_field is not None:
            split = cellmod.split_bbox_conjunct(f, ft.geom_field)
            if split is not None:
                box = split[0]
                lvl = self._routing_level()
                n = 1 << lvl
                cx = (box.xmin + box.xmax) / 2.0
                cy = (box.ymin + box.ymax) / 2.0
                ix = int(np.clip((cx + 180.0) / 360.0 * n, 0, n - 1))
                iy = int(np.clip((cy + 90.0) / 180.0 * n, 0, n - 1))
                prefix = cellmod.cell_prefix(lvl, (ix, iy))
                return f"{name}:z{lvl}:{prefix}"
        return f"{name}:f:{repr(f)}" if f is not None else f"schema:{name}"

    def _owners(self, key: str) -> List[str]:
        """Ring owner order for ``key``, usable replicas first. The
        unusable tail stays appended: when NOTHING is usable, half-open
        breakers still admit a trial through the client path, which is
        how a recovered fleet heals."""
        ranked = self.ring.owners(key)
        usable = [r for r in ranked if self.registry.usable(r)]
        rest = [r for r in ranked if r not in usable]
        return usable + rest

    # -- routed call core --------------------------------------------------
    def _classify(self, rid: str, e: BaseException, write: bool) -> str:
        """``raise`` (the caller's own error — propagate), ``skip``
        (candidate unusable, no breaker charge), or ``fail`` (replica
        failure evidence: charge + fail over)."""
        from geomesa_tpu.sidecar.client import error_code

        if isinstance(e, QueryTimeoutError):
            # the QUERY's budget (deadline expiry or a shed) — says
            # nothing about replica health, and another replica cannot
            # beat the same expired budget
            return "raise"
        if isinstance(e, CircuitOpenError):
            return "skip"  # already fenced; the breaker said so
        if isinstance(e, DeviceDrainError):
            # a REPLICA-level drain is sticky (the replica asked; probes
            # clear it on undrain); a slot-level [GM-DRAINING] (one
            # dispatcher died and respawned) is transient — skip this
            # attempt without writing the whole replica off
            msg = str(e).lower()
            if "replica" in msg and "draining" in msg:
                self.registry.set_draining(rid, True)
            return "skip"
        code = error_code(e)
        if code == "GM-ARG":
            return "raise"  # the same request fails the same way anywhere
        if code == "GM-OVERLOADED":
            # healthy but saturated: fail over without breaker charge
            return "skip"
        if write:
            import pyarrow.flight as fl

            if code is None and isinstance(e, fl.FlightUnavailableError) \
                    and "connect" in str(e).lower():
                # connection never established: nothing was sent, so a
                # WRITE is safe to fail over (a dead owner must not make
                # ingest unavailable while survivors hold the root)
                self.registry.record_failure(rid, e)
                return "fail"
            # ANY other write failure — uncoded transport (lost ack) or
            # coded GM-INTERNAL (the server may have applied the rows
            # and failed only at persist/ack time) — must never
            # blind-resend on another replica: it would double-apply
            self.registry.record_failure(rid, e)
            return "raise"
        self.registry.record_failure(rid, e)
        return "fail"

    def _call(self, name: Optional[str], key: str, op: str,
              fn: Callable[[Any], Any], write: bool = False,
              owners: Optional[List[str]] = None):
        """One routed call with ring-owner failover. Returns
        ``(value, rid)``; raises :class:`_Exhausted` when every candidate
        failed (callers decide degrade-vs-typed). ``owners`` overrides
        the candidate ORDER (the scatter path pins each group's owner
        first); usability filtering still applies."""
        if owners is None:
            owners = self._owners(key)
        else:
            usable = [r for r in owners if self.registry.usable(r)]
            owners = usable + [r for r in owners if r not in usable]
        last: Optional[BaseException] = None
        failed_over = False
        t_first = time.perf_counter()
        for i, rid in enumerate(owners):
            if resilience.current_deadline().expired:
                raise QueryTimeoutError(
                    "query deadline expired during fleet routing"
                )
            try:
                with tracing.span("fleet.route", replica=rid, attempt=i,
                                  schema=name or "", op=op):
                    t0 = time.perf_counter()
                    out = fn(self._client(rid))
                    dt = time.perf_counter() - t0
            except Exception as e:
                kind = self._classify(rid, e, write)
                if kind == "raise":
                    raise
                last = e
                failed_over = True
                self.registry.note_failed_over(rid)
                continue
            self.registry.record_latency(rid, dt, op)
            self.registry.record_success(rid)
            if failed_over:
                self._count("failover")
                metrics.inc(metrics.FLEET_ROUTE_FAILOVER)
                # the failover COST: everything since the first attempt
                # (failed dials + backoffs + the surviving call)
                metrics.observe("fleet.failover",
                                time.perf_counter() - t_first)
            else:
                self._count("affinity")
                metrics.inc(metrics.FLEET_ROUTE_AFFINITY)
            return out, rid
        raise _Exhausted(last)

    def _route(self, name: str, key: str, op: str,
               fn: Callable[[Any], Any],
               degrade: Optional[Callable[[], Any]] = None,
               user: Optional[str] = None, write: bool = False):
        """Admission + routed call + the typed degradation contract."""
        with self._admit(op, user=user), \
                tracing.start(f"fleet.{op}", schema=name):
            try:
                out, _rid = self._call(name, key, op, fn, write=write)
                return out
            except _Exhausted as ex:
                return self._degrade(name, op, ex.last, degrade)

    def _degrade(self, name: str, op: str, last: Optional[BaseException],
                 degrade: Optional[Callable[[], Any]]):
        err = last if last is not None else RuntimeError(
            "no usable replica in the fleet"
        )
        self._count("partial")
        metrics.inc(metrics.FLEET_ROUTE_PARTIAL)
        if degrade is not None and resilience.partial_allowed():
            resilience.record_skip(
                "fleet.route", part=f"{name}:{op}", error=err
            )
            return degrade()
        raise FleetPartialError(
            f"every ring owner of {op} on {name!r} is down "
            f"(last: {err!r})",
            value=None, ok=0, total=1,
            skipped=[Skipped(source="fleet.route", part=f"{name}:{op}",
                             error=repr(err))],
        ) from last

    # -- scatter counts ----------------------------------------------------
    @staticmethod
    def _bbox_ecql(geom: str, boxes: Sequence[Tuple[float, float, float,
                                                    float]]) -> str:
        parts = [
            f"BBOX({geom}, {b[0]!r}, {b[1]!r}, {b[2]!r}, {b[3]!r})"
            for b in boxes
        ]
        return parts[0] if len(parts) == 1 else "(" + " OR ".join(parts) + ")"

    @staticmethod
    def _and_ecql(ecql: str, conjunct: str) -> str:
        if ecql.strip().upper() == "INCLUDE":
            return conjunct
        return f"({ecql}) AND {conjunct}"

    def _scatter_groups(self, name: str, decomp) -> Dict[str, List[Tuple[
            int, int]]]:
        """Group the decomposition's interior cells by ring owner: each
        cell's ROUTING-level ancestor keys the ring (the same key family
        single-query affinity uses, so a scattered group lands exactly
        where the undecomposed queries for that slice of the world warm
        their caches)."""
        lvl = self._routing_level()
        groups: Dict[str, List[Tuple[int, int]]] = {}
        for (ix, iy) in decomp.cells:
            if decomp.level >= lvl:
                anc = (ix >> (decomp.level - lvl), iy >> (decomp.level - lvl))
                alvl = lvl
            else:
                anc, alvl = (ix, iy), decomp.level
            key = f"{name}:z{alvl}:{cellmod.cell_prefix(alvl, anc)}"
            groups.setdefault(self.ring.owner(key), []).append((ix, iy))
        return groups

    def _scatter_count(self, name: str, ecql: str, decomp, ft,
                       call_kw: Dict[str, Any],
                       user: Optional[str]) -> int:
        """Exact count scattered by cell ownership: one sub-count per
        owner group over ``orig ∧ (its cells)`` plus the boundary strips
        on the affinity owner — disjoint boxes, integer partials, so the
        sum is bit-identical to the whole-query count. A group whose
        every candidate fails degrades with EXACT survivor totals under
        ``allow_partial()`` and raises typed otherwise."""
        geom = ft.geom_field
        groups = self._scatter_groups(name, decomp)
        jobs: List[Tuple[str, str, str]] = []  # (owner, sub_ecql, label)
        for owner, cells in sorted(groups.items()):
            boxes = [decomp.cell_boxes[c] for c in cells]
            jobs.append((
                owner,
                self._and_ecql(ecql, self._bbox_ecql(geom, boxes)),
                f"cells[{len(cells)}@z{decomp.level}]",
            ))
        if decomp.strips:
            # boundary strips ride the schema-affinity owner
            jobs.append((
                self.ring.owner(f"schema:{name}"),
                self._and_ecql(ecql, self._bbox_ecql(geom, decomp.strips)),
                f"strips[{len(decomp.strips)}]",
            ))
        self._count("scatter")
        metrics.inc(metrics.FLEET_ROUTE_SCATTER)
        total = 0
        ok = 0
        skipped: List[Skipped] = []
        with self._admit("count", user=user), \
                tracing.start("fleet.count", schema=name, scatter=True):
            for owner, sub_ecql, label in jobs:
                # owner-first order, then the ring's ranking for failover
                # (any replica can serve any cells — shared storage)
                order = [owner] + [
                    r for r in self.ring.owners(f"schema:{name}")
                    if r != owner
                ]
                try:
                    n, _rid = self._call(
                        name, f"{name}:owner:{owner}", "count",
                        lambda c, e=sub_ecql: c.count(name, e, **call_kw),
                        owners=order,
                    )
                except _Exhausted as ex:
                    err = ex.last or RuntimeError("no usable replica")
                    # phase carries the group's sub-query verbatim: the
                    # EXACT rows the degraded total is missing — a
                    # consumer (or test) can re-run it once the fleet
                    # heals and reconcile to the full answer. Surviving
                    # groups keep executing in BOTH modes, so the
                    # accounting is always complete: strict mode raises
                    # at the end with the full survivor total.
                    rec = Skipped(source="fleet.route",
                                  part=f"{name}:{label}", error=repr(err),
                                  phase=sub_ecql)
                    if resilience.partial_allowed():
                        resilience.record_skip(
                            "fleet.route", part=f"{name}:{label}",
                            error=err, phase=sub_ecql,
                        )
                    skipped.append(rec)
                    self._count("partial")
                    metrics.inc(metrics.FLEET_ROUTE_PARTIAL)
                    continue
                total += int(n)
                ok += 1
        if skipped and not resilience.partial_allowed():
            raise FleetPartialError(
                f"{len(skipped)} owner group(s) of count on {name!r} are "
                f"down (survivors: {ok}/{len(jobs)} groups, "
                f"count {total})",
                value=total, ok=ok, total=len(jobs), skipped=skipped,
            )
        return total

    # -- public API (GeoDataset-shaped) ------------------------------------
    def count(self, name: str, ecql: str = "INCLUDE", exact: bool = True,
              auths: Optional[Sequence[str]] = None,
              region: Optional[str] = None,
              speculative_ok: bool = False,
              user: Optional[str] = None) -> int:
        call_kw: Dict[str, Any] = {"exact": exact}
        if auths is not None:
            call_kw["auths"] = list(auths)
        if region is not None:
            call_kw["region"] = region
        if speculative_ok:
            call_kw["speculative_ok"] = True
        f, ft = self._parse(name, ecql)
        if (exact and region is None and f is not None and ft is not None
                and config.FLEET_SCATTER.to_bool()
                and sum(1 for r in self.registry.members()
                        if self.registry.usable(r)) > 1):
            decomp = cellmod.decompose(f, ft)
            if decomp is not None and len(decomp.cells) > 1:
                groups = self._scatter_groups(name, decomp)
                if len(groups) > 1:
                    return self._scatter_count(
                        name, ecql, decomp, ft, call_kw, user
                    )
        key = self._affinity_key(name, f, ft)
        return self._route(
            name, key, "count",
            lambda c: c.count(name, ecql, **call_kw),
            degrade=lambda: 0, user=user,
        )

    def density(self, name: str, ecql: str = "INCLUDE", bbox=None,
                width: int = 256, height: int = 256,
                weight: Optional[str] = None,
                auths: Optional[Sequence[str]] = None,
                region: Optional[str] = None,
                user: Optional[str] = None) -> np.ndarray:
        f, ft = self._parse(name, ecql)
        key = self._affinity_key(name, f, ft)
        return self._route(
            name, key, "density",
            lambda c: c.density(name, ecql, bbox=bbox, width=width,
                                height=height, weight=weight, auths=auths,
                                region=region),
            degrade=lambda: np.zeros((height, width), np.float32),
            user=user,
        )

    def density_curve(self, name: str, ecql: str = "INCLUDE",
                      level: int = 9, bbox=None,
                      weight: Optional[str] = None,
                      auths: Optional[Sequence[str]] = None,
                      user: Optional[str] = None):
        f, ft = self._parse(name, ecql)
        key = self._affinity_key(name, f, ft)
        return self._route(
            name, key, "density_curve",
            lambda c: c.density_curve(name, ecql, level=level, bbox=bbox,
                                      weight=weight, auths=auths),
            user=user,
        )

    def stats(self, name: str, stat_spec: str, ecql: str = "INCLUDE",
              auths: Optional[Sequence[str]] = None,
              region: Optional[str] = None,
              user: Optional[str] = None):
        from geomesa_tpu.stats import parse_stat

        f, ft = self._parse(name, ecql)
        key = self._affinity_key(name, f, ft)
        return self._route(
            name, key, "stats",
            lambda c: c.stats(name, stat_spec, ecql, auths=auths,
                              region=region),
            degrade=lambda: parse_stat(stat_spec), user=user,
        )

    def query(self, name: str, ecql: str = "INCLUDE",
              user: Optional[str] = None, **kw):
        f, ft = self._parse(name, ecql)
        key = self._affinity_key(name, f, ft)
        return self._route(
            name, key, "query",
            lambda c: c.query(name, ecql, **kw), user=user,
        )

    def explain(self, name: str, ecql: str = "INCLUDE",
                user: Optional[str] = None) -> str:
        f, ft = self._parse(name, ecql)
        key = self._affinity_key(name, f, ft)
        return self._route(
            name, key, "explain", lambda c: c.explain(name, ecql),
            user=user,
        )

    def list_schemas(self, user: Optional[str] = None) -> List[str]:
        return self._route(
            "", "schemas", "list-schemas", lambda c: c.list_schemas(),
            user=user,
        )

    # -- writes (router-stamped epochs) ------------------------------------
    def create_schema(self, name: str, spec: str,
                      user: Optional[str] = None) -> str:
        with self._stamp(name):
            return self._route(
                name, f"schema:{name}", "create-schema",
                lambda c: c.create_schema(name, spec),
                user=user, write=True,
            )

    def delete_schema(self, name: str, user: Optional[str] = None) -> None:
        with self._stamp(name):
            self._route(
                name, f"schema:{name}", "delete-schema",
                lambda c: c.delete_schema(name), user=user, write=True,
            )

    def insert_arrow(self, name: str, table,
                     user: Optional[str] = None) -> None:
        """Stamped ingest: the receiving replica applies the rows, saves
        the shared root, and advances to the stamped epoch; every other
        replica refreshes before its next serve of this schema."""
        with self._stamp(name):
            self._route(
                name, f"schema:{name}", "insert",
                lambda c: c.insert_arrow(name, table),
                user=user, write=True,
            )

    # -- fleet-wide views --------------------------------------------------
    def replica_metrics(self) -> Dict[str, Dict]:
        """Per-replica /metrics snapshots (best effort; a down replica
        reports its error instead) — the bench's affinity-hit-ratio
        source."""
        out: Dict[str, Dict] = {}
        for rid in self.registry.members():
            try:
                out[rid] = self._client(rid).metrics()
            except Exception as e:
                out[rid] = {"error": repr(e)[:200]}
        return out
