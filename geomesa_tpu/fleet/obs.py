"""Fleet observability plane (docs/OBSERVABILITY.md §9).

One :class:`FleetObservability` hangs off each :class:`FleetRouter` and
gives operators a single pane over the whole fleet, built from four
pieces — all of them PULL or ASYNC, so the query path never pays for any
of it:

* **Metrics federation** — each replica's ``metrics-export`` sidecar
  action returns its STRUCTURED registry snapshot (counters, gauges,
  histogram buckets — not rendered text); the router merges them
  (counters add, histograms merge bucket-wise on identical ladders,
  gauges keep per-replica labels) and renders one fleet-level
  ``/metrics/fleet`` exposition (classic + OpenMetrics, ``replica``
  label on per-replica series). Snapshots are TTL-cached
  (``geomesa.fleet.obs.ttl.ms``) and pulled only when a scrape or debug
  read asks.
* **Fleet SLO burn** — a second :class:`~geomesa_tpu.slo.SloMonitor`
  runs the exact same dual-window differencing over the MERGED
  ``trace.<op>`` histograms, publishing ``slo.burn.fleet.<op>`` gauges:
  "density is burning budget fleet-wide" even when no single replica
  crosses the threshold alone.
* **Cross-replica trace stitching** — scatter completions enqueue their
  trace id; a daemon stitcher waits ``geomesa.fleet.stitch.delay.ms``,
  pulls each surviving replica's subtrees over ``trace-fetch``, and
  grafts them under the router span whose ``span_token`` matches each
  subtree root's ``parent_span`` (the header handshake in
  sidecar/client.py + service.py). The result is ONE stitched span tree
  per scattered query — exported through the existing OTLP/JSONL sinks
  (``tracing_export.export_stitched``) and visible at
  ``/debug/queries?trace=<id>``.
* **Replica anomaly watchdog** — the registry's per-(replica, op)
  latency samples vs the fleet median (fleet/registry.py
  ``anomaly_report``), surfaced as ``fleet.anomaly.<id>`` gauges and a
  ``/debug/fleet`` advice row. Observation only: it never cordons.

``fleet_health`` composes the fleet ``/healthz/fleet``: HARD degradation
(503) only when NO capacity remains (zero usable replicas) or the fleet
SLO burns; everything else that is wrong-but-survivable — cordoned or
draining members, a replica's own hard-degraded local health, open
replica breakers, journal lag on some member, anomaly flags — degrades
SOFT (200, ``soft: true``), because the registry says capacity remains.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from geomesa_tpu import config, heat, metrics, slo, tracing, tracing_export

#: stitched records kept for /debug/queries?trace=<id> lookups
_STITCHED_KEEP = 64


class FleetObservability:
    """See the module docstring. Created lazily by
    :meth:`FleetRouter.observability`; thread-safe."""

    def __init__(self, router):
        #: weak: the plane must not keep a closed router alive (the
        #: /debug/fleet WeakSet is the liveness authority)
        self._router = weakref.ref(router)
        self._lock = threading.Lock()
        #: federation TTL cache: (monotonic stamp, payload)
        self._fed_at = 0.0
        self._fed: Optional[Dict[str, Any]] = None
        #: newest merged export (the fleet SLO monitor's source)
        self._merged: Optional[Dict[str, Any]] = None
        #: fleet-level SLO burn over the MERGED trace.<op> histograms —
        #: same dual-window differencing, distinct gauge namespace
        self.slo = slo.SloMonitor(
            source=self._merged_trace_hist,
            gauge_prefix=f"{metrics.SLO_BURN_PREFIX}.fleet",
        )
        # -- stitcher (async half) ----------------------------------------
        self._queue: "deque" = deque()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stitched: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout=2.0)
        self._thread = None

    def _alive_router(self):
        r = self._router()
        if r is None:
            raise RuntimeError("fleet router is gone")
        return r

    # -- metrics federation ------------------------------------------------
    def federate(self, force: bool = False) -> Dict[str, Any]:
        """Pull one ``metrics-export`` per registry member (best effort:
        a down replica contributes an error row, never a failure) and
        merge. TTL-cached — scrape-driven polling shares one fleet pull
        per ``geomesa.fleet.obs.ttl.ms`` window. Never called from the
        query path."""
        ttl_ms = config.FLEET_OBS_TTL_MS.to_int()
        ttl_s = (2000 if ttl_ms is None else int(ttl_ms)) / 1e3
        with self._lock:
            if not force and self._fed is not None \
                    and time.monotonic() - self._fed_at < ttl_s:
                return self._fed
        router = self._alive_router()
        metrics.inc(metrics.FLEET_FEDERATION_SCRAPES)
        exports: Dict[str, Dict] = {}
        heats: Dict[str, Dict] = {}
        healths: Dict[str, Dict] = {}
        errors: Dict[str, str] = {}
        for rid in router.registry.members():
            try:
                payload = router._client(rid).metrics_export()
            except Exception as e:
                errors[rid] = repr(e)[:200]
                metrics.inc(metrics.FLEET_FEDERATION_ERRORS)
                continue
            exports[rid] = payload.get("metrics") or {}
            heats[rid] = payload.get("heat") or {}
            healths[rid] = payload.get("health") or {}
        merged = metrics.merge_exports(exports)
        out = {
            "replicas": sorted(exports),
            "errors": errors,
            "merged": merged,
            "heat": heats,
            "health": healths,
        }
        with self._lock:
            self._fed = out
            self._fed_at = time.monotonic()
            self._merged = merged
        return out

    def _merged_trace_hist(self, op: str) -> Optional[Dict[str, Any]]:
        """Fleet SLO source: the merged ``trace.<op>`` histogram snapshot
        from the newest federation pull (None before the first pull —
        the monitor just skips the op)."""
        with self._lock:
            merged = self._merged
        if merged is None:
            return None
        return (merged.get("histograms") or {}).get(f"trace.{op}")

    def fleet_metrics_text(self, openmetrics: bool = False) -> str:
        """The ``/metrics/fleet`` exposition: merged counters/histograms
        plain, gauges with a ``replica`` label per member. Refreshes the
        federation cache and ticks the fleet SLO monitor (its
        ``slo.burn.fleet.<op>`` gauges live in the ROUTER's registry, on
        the router's own ``/metrics``)."""
        fed = self.federate()
        self.slo.evaluate()
        return metrics.render_fleet(fed["merged"], openmetrics=openmetrics)

    # -- fleet health ------------------------------------------------------
    def fleet_health(self) -> Dict[str, Any]:
        """The ``/healthz/fleet`` payload. HARD (503) only when no
        usable replica remains or the fleet SLO burns past threshold;
        every survivable defect — cordoned/draining/broken members with
        capacity left, a member's own degraded local health, journal lag
        on some member, anomaly flags — is SOFT (200, ``soft: true``)."""
        router = self._alive_router()
        fed = self.federate()
        summary = router.registry.summary()
        anomalies = router.registry.anomaly_report()
        slo_status = self.slo.status()
        slo_hot = {op: s for op, s in slo_status.items() if s["hot"]}
        reasons: List[str] = []
        if summary["usable"] <= 0 and summary["total"] > 0:
            reasons.append("hard: no usable replica")
        for op in sorted(slo_hot):
            reasons.append(f"hard: fleet SLO burning on {op}")
        hard = bool(reasons)
        if summary["cordoned"]:
            reasons.append(f"soft: {summary['cordoned']} cordoned")
        if summary["draining"]:
            reasons.append(f"soft: {summary['draining']} draining")
        if summary["broken"]:
            reasons.append(f"soft: {summary['broken']} breaker-open")
        for rid in sorted(fed["errors"]):
            reasons.append(f"soft: {rid} unreachable for federation")
        for rid in sorted(fed["health"]):
            h = fed["health"][rid] or {}
            if h.get("status") not in (None, "ok"):
                kind = "soft" if h.get("soft") else "replica-hard"
                # a member's own HARD degradation is still fleet-SOFT
                # while other replicas carry its keys
                reasons.append(f"soft: {rid} local health {kind}")
            lag = h.get("journal") or {}
            if any(int(v) > 0 for v in lag.values()):
                reasons.append(f"soft: {rid} journal lag")
        for rid in sorted(anomalies):
            reasons.append(f"soft: {rid} latency anomaly")
        degraded = hard or any(r.startswith("soft:") for r in reasons)
        return {
            "status": "degraded" if degraded else "ok",
            "soft": bool(degraded and not hard),
            "reasons": reasons,
            "summary": summary,
            "replicas": router.registry.snapshot(),
            "health": fed["health"],
            "federation_errors": fed["errors"],
            "anomalies": anomalies,
            "slo": slo_status,
        }

    # -- cell heat ---------------------------------------------------------
    def fleet_heat(self, top: Optional[int] = None) -> Dict[str, Any]:
        """The fleet heat table (``/debug/heat``, ``geomesa-tpu fleet
        heat``): per-replica ``metrics-export`` heat rows merged by
        (schema, cell), each merged row carrying its per-replica touch
        split — the placement signal the autoscaling arc consumes."""
        fed = self.federate()
        return {
            "schemas": heat.merge_snapshots(fed["heat"], top=top),
            "replicas": sorted(fed["heat"]),
            "errors": fed["errors"],
        }

    # -- anomaly watchdog --------------------------------------------------
    def anomalies(self) -> Dict[str, Dict[str, float]]:
        """Per-replica per-op latency ratios vs the fleet median that
        cross ``geomesa.fleet.anomaly.factor`` (observation only — the
        outlier-streak breaker in the registry stays the enforcement
        path). Publishes the ``fleet.anomaly.<id>`` gauges."""
        return self._alive_router().registry.anomaly_report()

    # -- trace stitching (async half) --------------------------------------
    def note_scatter(self, trace_id: Optional[str],
                     owners: Sequence[str]) -> None:
        """Scatter-completion hook (called by the router WITH the query
        still on the caller's thread): one bounded deque append + event
        set — never blocks, never RPCs. The stitcher thread does the
        pulls after ``geomesa.fleet.stitch.delay.ms``."""
        if trace_id is None or not owners:
            return
        if not config.FLEET_STITCH.to_bool():
            return
        cap = config.FLEET_STITCH_QUEUE.to_int()
        cap = 256 if cap is None else int(cap)
        with self._lock:
            if len(self._queue) >= cap:
                self._queue.popleft()  # oldest out: stitching is advisory
            self._queue.append(
                (trace_id, tuple(dict.fromkeys(owners)), time.monotonic())
            )
        self._wake.set()
        self._ensure_thread()

    def stitched(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """A stitched record by trace id (``/debug/queries?trace=``), or
        None when the id never stitched here (or aged out)."""
        with self._lock:
            return self._stitched.get(trace_id)

    def _ensure_thread(self) -> None:
        t = self._thread
        if t is not None and t.is_alive():
            return
        with self._lock:
            t = self._thread
            if t is not None and t.is_alive():
                return
            self._stop.clear()
            t = threading.Thread(target=self._loop, daemon=True,
                                 name="geomesa-fleet-stitch")
            self._thread = t
            t.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=0.5)
            self._wake.clear()
            while not self._stop.is_set():
                delay_ms = config.FLEET_STITCH_DELAY_MS.to_int()
                delay_s = (100 if delay_ms is None else int(delay_ms)) / 1e3
                with self._lock:
                    item = self._queue[0] if self._queue else None
                if item is None:
                    break
                # wait out the settle delay so every replica has closed
                # (and retained) its root spans before the pulls
                remain = item[2] + delay_s - time.monotonic()
                if remain > 0:
                    if self._stop.wait(timeout=remain):
                        return
                with self._lock:
                    if not self._queue or self._queue[0] is not item:
                        continue
                    self._queue.popleft()
                try:
                    self._stitch(item[0], item[1])
                except Exception:  # pragma: no cover — defensive
                    metrics.inc(metrics.FLEET_TRACE_STITCH_FAILED)

    def _stitch(self, trace_id: str,
                owners: Tuple[str, ...]) -> Optional[Dict[str, Any]]:
        """Assemble ONE stitched span tree: the router's local finished
        trace plus each surviving replica's ``trace-fetch``ed subtrees,
        grafted under the router span whose ``span_token`` matches each
        subtree root's ``parent_span`` attribute. Exports the result
        through the configured sinks and retains it for
        ``/debug/queries?trace=``."""
        router = self._router()
        if router is None:
            return None
        local = tracing.finished_trace(trace_id)
        if local is None:
            metrics.inc(metrics.FLEET_TRACE_STITCH_FAILED)
            return None
        tree = local["tree"]
        # span_token -> grafting point (the sidecar.call span that made
        # the RPC; to_dict() is a fresh dict tree, so grafting into it
        # never mutates the retained trace)
        points: Dict[str, Dict[str, Any]] = {}

        def index(node: Dict[str, Any]) -> None:
            token = (node.get("attrs") or {}).get("span_token")
            if token:
                points[str(token)] = node
            for c in node.get("children") or ():
                index(c)

        index(tree)
        grafted = 0
        seen_tokens: set = set()
        replicas: set = set()
        for rid in owners:
            try:
                fetched = router._client(rid).trace_fetch(trace_id)
            except Exception:
                metrics.inc(metrics.FLEET_TRACE_STITCH_FAILED)
                continue
            for rec in fetched.get("traces") or ():
                sub = (rec or {}).get("tree")
                if not sub:
                    continue
                attrs = sub.setdefault("attrs", {})
                token = attrs.get("parent_span")
                if not token or str(token) in seen_tokens:
                    # no span token: not a child of this scatter (e.g.
                    # the router's own retained root when replicas share
                    # a process). Seen token: another member's fetch
                    # already delivered this subtree — grafting is
                    # idempotent, one subtree per sidecar call.
                    continue
                seen_tokens.add(str(token))
                attrs.setdefault("replica", rid)
                target = points.get(str(token))
                if target is None:
                    # no matching router span (dropped past the span
                    # budget): keep the subtree under the root rather
                    # than losing it
                    target = tree
                    attrs["stitch_orphan"] = True
                target.setdefault("children", []).append(sub)
                grafted += 1
                replicas.add(str(attrs.get("replica") or rid))
        record = {
            "trace_id": trace_id,
            "total_ms": local["total_ms"],
            "stitched": True,
            "replicas": sorted(replicas),
            "subtrees": grafted,
            "tree": tree,
        }
        with self._lock:
            self._stitched[trace_id] = record
            self._stitched.move_to_end(trace_id)
            while len(self._stitched) > _STITCHED_KEEP:
                self._stitched.popitem(last=False)
        tracing_export.export_stitched(trace_id, tree)
        metrics.inc(metrics.FLEET_TRACE_STITCHED)
        return record

    def stitch_now(self, trace_id: str,
                   owners: Sequence[str]) -> Optional[Dict[str, Any]]:
        """Synchronous stitch (tests, CLI): same assembly, caller's
        thread, no settle delay."""
        return self._stitch(trace_id, tuple(dict.fromkeys(owners)))
