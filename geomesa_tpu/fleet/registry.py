"""Per-replica health for the fleet router (docs/RESILIENCE.md §7).

The device-health registry (parallel/health.py, §6) generalized from
local jax devices to remote replica processes: every replica carries

* a **circuit breaker** (``resilience.breaker("replica:<id>")``) fed by
  routed-call connect/dispatch failures, failed health probes, and
  latency-outlier streaks (``geomesa.fleet.breaker.{threshold,reset.ms}``);
* a **latency-outlier detector** — a routed call slower than
  ``geomesa.fleet.latency.outlier`` x the trailing fleet-wide median FOR
  ITS OP (over ``geomesa.fleet.latency.floor.ms``) counts one outlier; a
  threshold-long consecutive streak trips the breaker, fencing the
  slow-but-not-failing replica like a failing one;
* an explicit **cordon** state (router API / ``geomesa.fleet.cordon``)
  and a **draining** state learned from the replica itself (its ``drain``
  admin action answers ``[GM-DRAINING]``; probes read it back) — either
  removes the replica from routing without touching its breaker.

States surface as ``fleet.replica.health.<id>`` gauges and the
``/debug/fleet`` payload; the router's failover walks ring owners
filtered through :meth:`usable`.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, List, Optional, Set

from geomesa_tpu import config, metrics, resilience

OK, CORDONED, DRAINING, BROKEN = "ok", "cordoned", "draining", "broken"
_GAUGE_VALUE = {OK: 1.0, CORDONED: 0.0, DRAINING: 0.0, BROKEN: -1.0}


def _cordon_config_ids() -> Set[str]:
    raw = (config.FLEET_CORDON.get() or "").strip()
    if not raw:
        return set()
    return {tok.strip() for tok in raw.split(",") if tok.strip()}


class ReplicaRegistry:
    """Fleet-membership + health state for one router. Thread-safe;
    replica ids are operator-chosen short tokens (bounded cardinality —
    one breaker, one gauge per replica)."""

    #: distinct per-op latency baselines retained (least recently seen
    #: op's samples drop beyond this — bounded state)
    _MAX_OPS = 64

    def __init__(self, replicas: Dict[str, str]):
        self._lock = threading.Lock()
        #: id -> Flight location ("grpc+tcp://host:port")
        self._members: Dict[str, str] = dict(replicas)
        self._cordoned: Dict[str, str] = {}
        self._draining: Set[str] = set()
        self._failures: Dict[str, int] = {}
        self._last_failure: Dict[str, str] = {}
        #: queries re-routed OFF this replica onto a later ring owner
        self._failed_over: Dict[str, int] = {}
        self._lat_recent: Dict[str, "deque"] = {}
        #: (rid, op) -> recent samples — the anomaly watchdog's per-replica
        #: baseline (the fleet-wide deque above cannot say WHICH replica
        #: drags the median; docs/OBSERVABILITY.md §9)
        self._lat_replica: Dict[tuple, "deque"] = {}
        self._outlier_streak: Dict[str, int] = {}
        #: consecutive successful probes per replica (auto-uncordon)
        self._probe_streak: Dict[str, int] = {}
        self._gauged: Set[str] = set()

    # -- membership --------------------------------------------------------
    def members(self) -> List[str]:
        with self._lock:
            return sorted(self._members)

    def location(self, rid: str) -> str:
        with self._lock:
            loc = self._members.get(rid)
        if loc is None:
            raise KeyError(f"unknown replica {rid!r}")
        return loc

    def add(self, rid: str, location: str) -> None:
        with self._lock:
            self._members[rid] = location

    def remove(self, rid: str) -> None:
        with self._lock:
            self._members.pop(rid, None)
            self._draining.discard(rid)
            self._cordoned.pop(rid, None)
            self._probe_streak.pop(rid, None)

    # -- breaker plumbing --------------------------------------------------
    def breaker(self, rid: str) -> resilience.CircuitBreaker:
        return resilience.breaker(
            f"replica:{rid}",
            threshold=config.FLEET_BREAKER_THRESHOLD.to_int() or 3,
            reset_ms=config.FLEET_BREAKER_RESET_MS.to_float() or 30_000.0,
        )

    def _ensure_gauge(self, rid: str) -> None:
        if rid in self._gauged:
            return
        with self._lock:
            if rid in self._gauged:
                return
            self._gauged.add(rid)
        metrics.registry().gauge(
            f"{metrics.FLEET_REPLICA_HEALTH_PREFIX}.{rid}",
            lambda r=rid: _GAUGE_VALUE[self.state(r)],
            replace=True,
        )

    # -- state -------------------------------------------------------------
    def cordon_reason(self, rid: str) -> Optional[str]:
        with self._lock:
            reason = self._cordoned.get(rid)
        if reason is not None:
            return reason
        if rid in _cordon_config_ids():
            return "geomesa.fleet.cordon"
        return None

    def state(self, rid: str) -> str:
        if self.cordon_reason(rid) is not None:
            return CORDONED
        with self._lock:
            draining = rid in self._draining
        if draining:
            return DRAINING
        if self.breaker(rid).state != resilience.CircuitBreaker.CLOSED:
            return BROKEN
        return OK

    def usable(self, rid: str) -> bool:
        """May the router place a query on this replica? Cordoned /
        draining: no. Open breaker: no. Half-open: yes — the next routed
        call IS the trial (a pure state read, never ``allow()``, so a
        status poll can never consume the trial slot)."""
        self._ensure_gauge(rid)
        if self.cordon_reason(rid) is not None:
            return False
        with self._lock:
            if rid in self._draining:
                return False
        return self.breaker(rid).state != resilience.CircuitBreaker.OPEN

    # -- operator surface --------------------------------------------------
    def cordon(self, rid: str, reason: str = "operator") -> None:
        self._ensure_gauge(rid)
        with self._lock:
            self._cordoned[str(rid)] = str(reason)
            # auto-uncordon counts only probes AFTER the cordon
            self._probe_streak.pop(str(rid), None)

    def uncordon(self, rid: str) -> bool:
        with self._lock:
            return self._cordoned.pop(str(rid), None) is not None

    def note_probe(self, rid: str, ok: bool) -> bool:
        """Probe-result bookkeeping for **auto-uncordon** (docs/
        RESILIENCE.md §7): ``geomesa.fleet.uncordon.probes`` (default 3)
        consecutive SUCCESSFUL probes *while cordoned* clear a
        ROUTER-SIDE cordon — returns True when this probe un-cordoned
        the replica. The streak only accumulates on a cordoned replica
        (successes before the cordon must not pre-pay the exit, so
        :meth:`cordon` always starts from zero). Config-list cordons
        (``geomesa.fleet.cordon``) stay operator-owned: the streak never
        touches them, so a deliberately fenced replica can never probe
        its way back in. A failed probe zeroes the streak."""
        with self._lock:
            if not ok or rid not in self._cordoned:
                self._probe_streak.pop(rid, None)
                return False
            streak = self._probe_streak.get(rid, 0) + 1
            self._probe_streak[rid] = streak
            k = config.FLEET_UNCORDON_PROBES.to_int()
            k = 3 if k is None else int(k)
            if k <= 0 or streak < k:
                return False  # k <= 0: auto-uncordon disabled
            self._cordoned.pop(rid, None)
            self._probe_streak.pop(rid, None)
        metrics.inc(metrics.FLEET_UNCORDON)
        return True

    def set_draining(self, rid: str, draining: bool) -> None:
        """Record the replica's OWN drain state (learned from a
        ``[GM-DRAINING]`` answer or a probe) — distinct from cordon: the
        replica asked to be drained, the router just obeys."""
        self._ensure_gauge(rid)
        with self._lock:
            if draining:
                self._draining.add(rid)
            else:
                self._draining.discard(rid)

    # -- fault bookkeeping -------------------------------------------------
    def record_failure(self, rid: str, error: BaseException) -> None:
        self._ensure_gauge(rid)
        self.breaker(rid).record_failure()
        with self._lock:
            self._failures[rid] = self._failures.get(rid, 0) + 1
            self._last_failure[rid] = repr(error)[:300]

    def record_success(self, rid: str) -> None:
        # NOT the place to reset the outlier streak: a latency outlier
        # is still a successful call (record_success always follows
        # record_latency on that path), so clearing here would cap the
        # streak at 1 and the straggler detector could never trip —
        # record_latency itself zeroes the streak on non-outlier samples
        self.breaker(rid).record_success()

    def note_failed_over(self, rid: str) -> None:
        with self._lock:
            self._failed_over[rid] = self._failed_over.get(rid, 0) + 1

    def record_latency(self, rid: str, seconds: float, op: str) -> None:
        """One routed-call latency sample for ``op``. Consecutive outliers
        vs the trailing fleet-wide median OF THE SAME OP (over the floor)
        trip the replica's breaker — the §6 straggler-lane rule, with the
        op standing in for the kernel shape (what actually determines a
        call's expected cost on the wire)."""
        try:
            factor = config.FLEET_LATENCY_OUTLIER.to_float() or 0.0
        except (TypeError, ValueError):
            factor = 0.0
        if factor <= 0:
            return
        floor_s = (config.FLEET_LATENCY_FLOOR_MS.to_float() or 250.0) / 1e3
        with self._lock:
            dq = self._lat_recent.pop(op, None)
            if dq is None:
                dq = deque(maxlen=256)
            self._lat_recent[op] = dq  # re-insert = most recently seen
            while len(self._lat_recent) > self._MAX_OPS:
                self._lat_recent.pop(next(iter(self._lat_recent)))
            rdq = self._lat_replica.pop((rid, op), None)
            if rdq is None:
                rdq = deque(maxlen=64)
            self._lat_replica[(rid, op)] = rdq
            rdq.append(seconds)
            while len(self._lat_replica) > self._MAX_OPS * 4:
                self._lat_replica.pop(next(iter(self._lat_replica)))
            samples = sorted(dq)
            dq.append(seconds)
            median = samples[len(samples) // 2] if len(samples) >= 8 else None
            if median is not None \
                    and seconds >= max(floor_s, factor * median):
                streak = self._outlier_streak.get(rid, 0) + 1
                self._outlier_streak[rid] = streak
                threshold = config.FLEET_BREAKER_THRESHOLD.to_int() or 3
                if streak < threshold:
                    return
                self._outlier_streak[rid] = 0
                self._last_failure[rid] = (
                    f"latency outlier: {seconds * 1e3:.1f} ms >= "
                    f"{factor:g} x median {median * 1e3:.1f} ms for "
                    f"op {op!r} ({streak} consecutive)"
                )
            else:
                self._outlier_streak[rid] = 0
                return
        # trip outside the registry lock (breaker has its own)
        self.breaker(rid).trip()

    # -- anomaly watchdog (docs/OBSERVABILITY.md §9) -----------------------
    def anomaly_report(self) -> Dict[str, Dict[str, float]]:
        """Per-replica latency anomalies vs the fleet: for every (replica,
        op) with >= 8 recent samples whose fleet-wide op baseline also has
        >= 8, the ratio of the replica's recent median to the fleet
        median. Replicas with any op at or past
        ``geomesa.fleet.anomaly.factor`` are flagged — surfaced as
        ``fleet.anomaly.<id>`` gauges (worst ratio) and a /debug/fleet
        advice row. OBSERVATION ONLY: nothing here cordons or trips a
        breaker (the outlier-streak machinery above owns fencing).
        Returns ``{rid: {op: ratio, ...}}`` for flagged replicas."""
        try:
            factor = config.FLEET_ANOMALY_FACTOR.to_float() or 0.0
        except (TypeError, ValueError):
            factor = 0.0
        with self._lock:
            fleet = {op: sorted(dq) for op, dq in self._lat_recent.items()
                     if len(dq) >= 8}
            per = {k: sorted(dq) for k, dq in self._lat_replica.items()
                   if len(dq) >= 8}
        worst: Dict[str, float] = {}
        flagged: Dict[str, Dict[str, float]] = {}
        for (rid, op), samples in per.items():
            base = fleet.get(op)
            if base is None:
                continue
            fleet_med = base[len(base) // 2]
            if fleet_med <= 0:
                continue
            ratio = samples[len(samples) // 2] / fleet_med
            worst[rid] = max(worst.get(rid, 0.0), ratio)
            if factor > 0 and ratio >= factor:
                flagged.setdefault(rid, {})[op] = round(ratio, 2)
        reg = metrics.registry()
        for rid, ratio in worst.items():
            reg.gauge(f"{metrics.FLEET_ANOMALY_PREFIX}.{rid}").set(
                round(ratio, 3))
        return flagged

    # -- operator payloads -------------------------------------------------
    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-replica health payload (/debug/fleet, the CLI ``fleet
        status`` command)."""
        with self._lock:
            members = dict(self._members)
            cordons = dict(self._cordoned)
            failures = dict(self._failures)
            failed_over = dict(self._failed_over)
            last = dict(self._last_failure)
        out: Dict[str, Dict[str, Any]] = {}
        for rid in sorted(members):
            entry: Dict[str, Any] = {
                "location": members[rid],
                "state": self.state(rid),
                "breaker": self.breaker(rid).state,
                "failures": failures.get(rid, 0),
                "failed_over": failed_over.get(rid, 0),
            }
            reason = cordons.get(rid) or (
                "geomesa.fleet.cordon" if rid in _cordon_config_ids()
                else None
            )
            if reason is not None:
                entry["cordon_reason"] = reason
            if rid in last:
                entry["last_failure"] = last[rid]
            out[rid] = entry
        return out

    def summary(self) -> Dict[str, Any]:
        members = self.members()
        states = {rid: self.state(rid) for rid in members}
        return {
            "total": len(members),
            "usable": sum(1 for rid in members if self.usable(rid)),
            "cordoned": sorted(r for r, s in states.items()
                               if s == CORDONED),
            "draining": sorted(r for r, s in states.items()
                               if s == DRAINING),
            "broken": sorted(r for r, s in states.items() if s == BROKEN),
        }
