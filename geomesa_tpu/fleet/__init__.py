"""Replica fleet (docs/RESILIENCE.md §7): a front-end router plus N
replica sidecar processes over one shared storage root.

Routing is consistent-hash **cell affinity**: a query's SFC cell cover
(the same cell family the aggregate cache decomposes to, cache/cells.py)
picks the replica whose flat+hierarchy cache owns that slice of the
world, through a rendezvous-hash ring that rebalances minimally when
membership changes. Robustness generalizes RESILIENCE.md §6 from devices
to replicas: per-replica circuit breakers, probe- and latency-fed health,
cordon/drain, deadline-aware failover to the next ring owner, typed
``[GM-FLEET-PARTIAL]`` degradation with exact survivor accounting, and
mutation-epoch propagation so a restarted or failed-over replica never
serves a pre-mutation aggregate.
"""

from geomesa_tpu.fleet.registry import ReplicaRegistry
from geomesa_tpu.fleet.ring import RendezvousRing
from geomesa_tpu.fleet.router import FleetRouter, debug_fleet

__all__ = ["FleetRouter", "RendezvousRing", "ReplicaRegistry",
           "debug_fleet"]
