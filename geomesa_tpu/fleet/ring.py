"""Rendezvous (highest-random-weight) hash ring for replica membership.

Chosen over a vnode consistent-hash ring because HRW gives the two
properties the fleet cares about with no tuning surface:

* **minimal movement** — removing a member re-homes ONLY the keys that
  member owned (each key's other candidates keep their relative order),
  and adding one steals exactly the keys it now wins; a replica bounce
  never reshuffles the rest of the fleet's warm caches;
* **an ordered owner list per key** — the failover path IS the ranking:
  the first live member in ``owners(key)`` serves, the next one is the
  natural fallback, identical on every router instance (the hash is
  keyed only by member id and key bytes, never process state).
"""

from __future__ import annotations

import hashlib
from typing import Iterable, List, Optional, Tuple


def _score(member: str, key: bytes) -> int:
    """HRW weight of ``member`` for ``key`` — a keyed blake2b digest, so
    scores are stable across processes and python hash randomization."""
    h = hashlib.blake2b(key, digest_size=8, key=member.encode()[:64])
    return int.from_bytes(h.digest(), "big")


class RendezvousRing:
    """Immutable-membership rendezvous ring. Rebuild on membership change
    (:meth:`with_members`) — construction is O(members)."""

    def __init__(self, members: Iterable[str]):
        self.members: Tuple[str, ...] = tuple(sorted(set(members)))
        if not self.members:
            raise ValueError("ring needs at least one member")

    def with_members(self, members: Iterable[str]) -> "RendezvousRing":
        return RendezvousRing(members)

    @staticmethod
    def _key_bytes(key) -> bytes:
        if isinstance(key, bytes):
            return key
        return str(key).encode()

    def owners(self, key, n: Optional[int] = None) -> List[str]:
        """Members ranked by HRW weight for ``key`` (highest first): the
        affinity owner, then the failover order. ``n`` truncates."""
        kb = self._key_bytes(key)
        ranked = sorted(
            self.members, key=lambda m: _score(m, kb), reverse=True
        )
        return ranked if n is None else ranked[:n]

    def owner(self, key) -> str:
        kb = self._key_bytes(key)
        return max(self.members, key=lambda m: _score(m, kb))
