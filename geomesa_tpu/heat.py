"""Cell-heat telemetry (docs/OBSERVABILITY.md §9).

A process-wide table of per-(schema, SFC cell) access heat fed by the
aggregate cache's decomposition loop (cache/service.py): every cell-level
lookup records a hit or a miss, and a miss carries the scan's wall-clock
milliseconds — the cost-ledger attribution for that cell. The fleet
router federates per-replica snapshots into one fleet heat table
(`/debug/heat`, ``geomesa-tpu fleet heat``) — the placement signal the
autoscaling/rebalancing arc consumes (ROADMAP: "the hottest cells from
the cache heat table and cost ledger"; GeoBlocks, PAPERS.md 1908.07753).

Bounded and lock-cheap: the table holds at most ``geomesa.heat.cells``
rows (coldest-by-touches evict first, counted in ``heat.evicted``), and a
snapshot ships only the ``geomesa.heat.top`` hottest rows per schema.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from geomesa_tpu import config, metrics

#: (schema, "z<level>:<prefix>") -> [hits, misses, device_ms, touches]
_Key = Tuple[str, str]


class HeatTable:
    def __init__(self, max_cells: Optional[int] = None):
        self._rows: Dict[_Key, List[float]] = {}
        self._lock = threading.Lock()
        self._max = max_cells

    def _cap(self) -> int:
        if self._max is not None:
            return self._max
        v = config.HEAT_CELLS_MAX.to_int()
        return 4096 if v is None else int(v)

    def record(self, schema: str, level: int, prefix: str,
               hit: int = 0, miss: int = 0,
               device_ms: float = 0.0) -> None:
        cap = self._cap()
        if cap <= 0:
            return
        key = (schema, f"z{int(level)}:{prefix}")
        with self._lock:
            row = self._rows.get(key)
            if row is None:
                if len(self._rows) >= cap:
                    # evict the coldest row by touches — one scan, only on
                    # the (rare) insert past the bound
                    coldest = min(self._rows, key=lambda k: self._rows[k][3])
                    del self._rows[coldest]
                    metrics.inc(metrics.HEAT_EVICTED)
                row = self._rows[key] = [0, 0, 0.0, 0]
            row[0] += hit
            row[1] += miss
            row[2] += device_ms
            row[3] += 1
            metrics.registry().gauge(metrics.HEAT_CELLS).set(len(self._rows))

    def snapshot(self, top: Optional[int] = None) -> Dict[str, List[dict]]:
        """Per-schema hottest rows, heat-descending. Heat orders by
        touches (hits + misses): a cell everyone reads is hot whether or
        not the cache absorbs it; ``device_ms`` carries the cost weight."""
        if top is None:
            t = config.HEAT_TOP.to_int()
            top = 256 if t is None else int(t)
        with self._lock:
            items = [(k, list(v)) for k, v in self._rows.items()]
        out: Dict[str, List[dict]] = {}
        for (schema, cell), (hits, misses, dev_ms, touches) in items:
            out.setdefault(schema, []).append({
                "cell": cell, "hits": int(hits), "misses": int(misses),
                "device_ms": round(float(dev_ms), 3),
                "touches": int(touches),
            })
        for schema in out:
            out[schema].sort(key=lambda r: (-r["touches"], r["cell"]))
            if top > 0:
                del out[schema][top:]
        return out

    def reset(self) -> None:
        with self._lock:
            self._rows.clear()


def merge_snapshots(snaps: Dict[str, Dict[str, List[dict]]],
                    top: Optional[int] = None) -> Dict[str, List[dict]]:
    """Merge per-replica :meth:`HeatTable.snapshot` payloads by
    (schema, cell): counters add, and each merged row carries the per-
    replica touch split (``replicas: {rid: touches}``) so an operator can
    see WHERE a hot cell's load lands — the rebalancer's input shape."""
    if top is None:
        t = config.HEAT_TOP.to_int()
        top = 256 if t is None else int(t)
    acc: Dict[Tuple[str, str], dict] = {}
    for rid in sorted(snaps):
        for schema, rows in (snaps[rid] or {}).items():
            for r in rows:
                key = (schema, r["cell"])
                m = acc.get(key)
                if m is None:
                    m = acc[key] = {"cell": r["cell"], "hits": 0,
                                    "misses": 0, "device_ms": 0.0,
                                    "touches": 0, "replicas": {}}
                m["hits"] += int(r["hits"])
                m["misses"] += int(r["misses"])
                m["device_ms"] = round(
                    m["device_ms"] + float(r["device_ms"]), 3)
                m["touches"] += int(r["touches"])
                m["replicas"][rid] = (m["replicas"].get(rid, 0)
                                      + int(r["touches"]))
    out: Dict[str, List[dict]] = {}
    for (schema, _cell), row in acc.items():
        out.setdefault(schema, []).append(row)
    for schema in out:
        out[schema].sort(key=lambda r: (-r["touches"], r["cell"]))
        if top > 0:
            del out[schema][top:]
    return out


_TABLE = HeatTable()


def table() -> HeatTable:
    return _TABLE


def record(schema: str, level: int, prefix: str, hit: int = 0,
           miss: int = 0, device_ms: float = 0.0) -> None:
    _TABLE.record(schema, level, prefix, hit=hit, miss=miss,
                  device_ms=device_ms)


def snapshot(top: Optional[int] = None) -> Dict[str, Any]:
    return _TABLE.snapshot(top)


def reset() -> None:
    _TABLE.reset()
