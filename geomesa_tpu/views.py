"""Merged and routed dataset views — federation over multiple datasets.

Parity with the reference's view stores (index/view/MergedDataStoreView.
scala:33, MergedQueryRunner.scala:41 for merged sort/dedupe;
RoutedDataStoreView + RouteSelectorByAttribute for routing): a *merged* view
fans a query out to every underlying dataset and combines results (concat +
merged sort + dedupe + limit; additive grids/sketches merge by ``+``); a
*routed* view picks exactly one dataset per query. The canonical use is
hot(HBM)/cold(Parquet) tiering routed/merged by time predicate.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from geomesa_tpu.api.dataset import FeatureCollection, GeoDataset, Query
from geomesa_tpu.filter import ir, parse_ecql
from geomesa_tpu.schema.columns import ColumnBatch
from geomesa_tpu.stats import sketches as sk


def _as_query(query) -> Query:
    return query if isinstance(query, Query) else Query(ecql=query)


class MergedDatasetView:
    """Query N datasets holding the same schema as one (MergedDataStoreView).

    Reads fan out to every member; writes are not supported through the view
    (write to a member directly — same contract as the reference).
    """

    def __init__(self, datasets: Sequence[GeoDataset]):
        if not datasets:
            raise ValueError("merged view needs at least one dataset")
        self.datasets = list(datasets)

    def get_schema(self, name: str):
        return self.datasets[0].get_schema(name)

    def list_schemas(self) -> List[str]:
        names: List[str] = []
        for ds in self.datasets:
            for n in ds.list_schemas():
                if n not in names:
                    names.append(n)
        return names

    def _members_with(self, name: str) -> List[GeoDataset]:
        return [ds for ds in self.datasets if name in ds.list_schemas()]

    def query(self, name: str, query: "str | Query" = "INCLUDE") -> FeatureCollection:
        """Concatenated results with merged sort, de-dupe by fid, limit
        (MergedQueryRunner semantics)."""
        q = _as_query(query)
        members = self._members_with(name)
        if not members:
            raise KeyError(f"no member dataset has schema {name!r}")
        ft = members[0].get_schema(name)
        # fan out WITHOUT per-member limit/sort/projection; merge client-side
        # (per-member sorts would be discarded by the merged re-sort anyway)
        sub = Query(ecql=q.ecql, auths=q.auths)
        batches = []
        for ds in members:
            fc = ds.query(name, sub)
            st = ds._store(name)
            # decode per-member dictionary codes to values so codes from
            # different members never collide
            cols = dict(fc.batch.columns)
            for a in ft.attributes:
                if a.type == "string" and a.name in cols:
                    d = st.dicts.get(a.name)
                    if d is not None:
                        codes = cols[a.name]
                        vocab = np.array(list(d.values) + [None], dtype=object)
                        cols[a.name] = vocab[
                            np.where(codes >= 0, codes, len(vocab) - 1)
                        ]
            batches.append(ColumnBatch(cols, fc.batch.n))
        merged = ColumnBatch.concat(batches) if batches else ColumnBatch({}, 0)
        # de-dupe by feature id, first member wins (reference dedupes merged
        # stores by id)
        if "__fid__" in merged.columns and merged.n:
            _, first = np.unique(merged.columns["__fid__"], return_index=True)
            keep = np.zeros(merged.n, bool)
            keep[first] = True
            merged = merged.select(keep)
        # merged sort + limit — while strings are still decoded values, so
        # the order is lexicographic, not dictionary-code order
        if q.sort_by and merged.n:
            order = np.arange(merged.n)
            for attr, desc in reversed(list(q.sort_by)):
                col = merged.columns.get(attr)
                if col is None:
                    continue
                col = np.asarray(col)
                if col.dtype.kind == "O":  # decoded strings; nulls sort first
                    col = np.array(
                        ["" if v is None else str(v) for v in col.tolist()]
                    )
                col = col[order]
                if desc:  # stable descending (keeps prior-key tie order)
                    idx = (len(col) - 1) - np.argsort(col[::-1], kind="stable")[::-1]
                else:
                    idx = np.argsort(col, kind="stable")
                order = order[idx]
            merged = ColumnBatch(
                {k: v[order] for k, v in merged.columns.items()}, merged.n
            )
        # re-encode decoded strings against a fresh view-local dictionary so
        # the FeatureCollection contract (codes + dicts) holds
        from geomesa_tpu.schema.columns import DictionaryEncoder

        dicts: Dict[str, DictionaryEncoder] = {}
        for a in ft.attributes:
            if a.type == "string" and a.name in merged.columns:
                enc = DictionaryEncoder()
                vals = [
                    None if v is None else str(v)
                    for v in merged.columns[a.name].tolist()
                ]
                merged.columns[a.name] = enc.encode(vals)
                dicts[a.name] = enc
        if q.max_features is not None and merged.n > q.max_features:
            merged = ColumnBatch(
                {k: v[: q.max_features] for k, v in merged.columns.items()},
                q.max_features,
            )
        if q.properties:
            keep = set(q.properties) | {"__fid__"}
            pref = tuple(p + "__" for p in q.properties)
            merged = ColumnBatch(
                {
                    k: v for k, v in merged.columns.items()
                    if k in keep or k.startswith(pref)
                },
                merged.n,
            )
        return FeatureCollection(ft, merged, dicts or {})

    def count(self, name: str, query: "str | Query" = "INCLUDE",
              exact: bool = True) -> int:
        return sum(
            ds.count(name, query, exact=exact)
            for ds in self._members_with(name)
        )

    def bounds(self, name: str) -> Optional[Tuple[float, float, float, float]]:
        bs = [b for b in (
            ds.bounds(name) for ds in self._members_with(name)
        ) if b is not None]
        if not bs:
            return None
        a = np.asarray(bs)
        return (
            float(a[:, 0].min()), float(a[:, 1].min()),
            float(a[:, 2].max()), float(a[:, 3].max()),
        )

    def density(self, name: str, query: "str | Query" = "INCLUDE",
                bbox=None, width: int = 256, height: int = 256,
                weight: Optional[str] = None) -> np.ndarray:
        if bbox is None:
            bbox = self.bounds(name) or (-180, -90, 180, 90)
        grid = np.zeros((height, width), np.float32)
        for ds in self._members_with(name):
            grid = grid + ds.density(name, query, bbox=bbox, width=width,
                                     height=height, weight=weight)
        return grid

    def stats(self, name: str, stat_spec: str,
              query: "str | Query" = "INCLUDE") -> sk.Stat:
        """Cross-member sketch merge (the LambdaStats/StatsCombiner role)."""
        out: Optional[sk.Stat] = None
        for ds in self._members_with(name):
            s = ds.stats(name, stat_spec, query)
            if out is None:
                out = s
            else:
                out.merge(s)
        if out is None:
            raise KeyError(f"no member dataset has schema {name!r}")
        return out

    def unique(self, name: str, attribute: str,
               query: "str | Query" = "INCLUDE") -> List:
        vals = set()
        for ds in self._members_with(name):
            vals.update(ds.unique(name, attribute, query))
        return sorted(vals, key=lambda v: (v is None, v))


class RoutedDatasetView:
    """Route each query to exactly ONE member dataset (RoutedDataStoreView).

    ``routes``: ordered list of ``(selector, dataset)``. A selector is either
    a set of attribute names — the route matches when the query filter
    references a subset of them (RouteSelectorByAttribute) — or a callable
    ``(ir.Filter) -> bool``. First match wins; an empty attribute set is the
    default route.
    """

    def __init__(self, routes: Sequence[Tuple[object, GeoDataset]]):
        if not routes:
            raise ValueError("routed view needs at least one route")
        self.routes = list(routes)

    def route(self, name: str, query: "str | Query" = "INCLUDE") -> GeoDataset:
        q = _as_query(query)
        f = parse_ecql(q.ecql or "INCLUDE")
        props = set(ir.props_referenced(f))
        default = None
        for selector, ds in self.routes:
            if callable(selector):
                if selector(f):
                    return ds
            else:
                attrs = set(selector)
                if not attrs:
                    default = default or ds
                elif props and props <= attrs:
                    return ds
        if default is not None:
            return default
        raise ValueError(
            f"no route matches query attributes {sorted(props)}"
        )

    def query(self, name: str, query: "str | Query" = "INCLUDE"):
        return self.route(name, query).query(name, query)

    def count(self, name: str, query: "str | Query" = "INCLUDE",
              exact: bool = True) -> int:
        return self.route(name, query).count(name, query, exact=exact)

    def density(self, name: str, query: "str | Query" = "INCLUDE", **kw):
        return self.route(name, query).density(name, query, **kw)

    def stats(self, name: str, stat_spec: str,
              query: "str | Query" = "INCLUDE"):
        return self.route(name, query).stats(name, stat_spec, query)
