"""Vectorized geo-function library — the ``st_*`` UDF surface.

Role parity with the reference's Spark JTS UDFs
(geomesa-spark/geomesa-spark-jts/.../udf/*FunctionFactory-style modules:
GeometricConstructorFunctions, GeometricAccessorFunctions,
GeometricOutputFunctions, GeometricProcessingFunctions,
SpatialRelationFunctions, GeometricCastFunctions — ~80 ``st_*`` functions):
the same names and semantics, but implemented over this framework's pure
numpy geometry substrate, with array fast paths where the operation is a
per-point kernel (relations against a literal geometry, distance, geohash
encode) so the hot forms vectorize instead of iterating JTS objects.

Scalar forms take/return :mod:`geomesa_tpu.utils.geometry` objects (or WKT
strings — every geometry argument may be WKT). Array forms accept numpy
arrays and broadcast. Object-array forms (`arr=` object ndarray of
geometries) map the scalar op.

Precision notes: planar ops (area/length/distance/intersection) are in
degree space like the JTS defaults; *Sphere variants use the haversine great
circle on WGS84's mean radius.
"""

from __future__ import annotations

import json
import math
import struct
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

from geomesa_tpu.utils import geometry as geo
from geomesa_tpu.utils.geometry import (
    EARTH_RADIUS_M, METERS_PER_DEGREE, Geometry, LineString, MultiLineString,
    MultiPoint, MultiPolygon, Point, Polygon, bbox_polygon, haversine_m,
    parse_wkt,
)

GeomLike = Union[Geometry, str]


def _geom(g: GeomLike) -> Geometry:
    return parse_wkt(g) if isinstance(g, str) else g


def _map(fn, arr):
    """Map a scalar op over an object array of geometries (None-safe)."""
    out = np.empty(len(arr), dtype=object)
    for i, g in enumerate(arr):
        out[i] = None if g is None else fn(_geom(g))
    return out


# ===========================================================================
# Constructors (GeometricConstructorFunctions)
# ===========================================================================

def st_makePoint(x, y):
    """Scalar -> Point; arrays -> object array of Points (use raw (x, y)
    columns for device work — this is the object-level constructor)."""
    if np.ndim(x) == 0:
        return Point(float(x), float(y))
    x = np.asarray(x, np.float64)
    y = np.asarray(y, np.float64)
    out = np.empty(len(x), dtype=object)
    for i in range(len(x)):
        out[i] = Point(float(x[i]), float(y[i]))
    return out


st_point = st_makePoint


def st_makePointM(x, y, m):  # measure is carried nowhere; parity signature
    return st_makePoint(x, y)


def st_makeLine(points: Sequence[GeomLike]) -> LineString:
    pts = [_geom(p) for p in points]
    return LineString(tuple((p.x, p.y) for p in pts))


def st_makePolygon(shell: GeomLike) -> Polygon:
    s = _geom(shell)
    if not isinstance(s, LineString):
        raise ValueError("st_makePolygon takes a closed LineString shell")
    return Polygon(tuple(s.coords))


def st_makeBBOX(xmin: float, ymin: float, xmax: float, ymax: float) -> Polygon:
    return bbox_polygon(float(xmin), float(ymin), float(xmax), float(ymax))


st_makeBox2D_doc = "corner points -> bbox polygon"


def st_makeBox2D(ll: GeomLike, ur: GeomLike) -> Polygon:
    a, b = _geom(ll), _geom(ur)
    return bbox_polygon(a.x, a.y, b.x, b.y)


def st_geomFromWKT(wkt) -> Geometry:
    if isinstance(wkt, np.ndarray):
        return _map(lambda g: g, wkt)
    return parse_wkt(wkt)


st_geomFromText = st_geomFromWKT
st_geometryFromText = st_geomFromWKT


def _typed_from_text(wkt, cls, name):
    g = parse_wkt(wkt) if isinstance(wkt, str) else wkt
    if not isinstance(g, cls):
        raise ValueError(f"{name}: WKT is a {type(g).__name__}")
    return g


def st_pointFromText(wkt) -> Point:
    return _typed_from_text(wkt, Point, "st_pointFromText")


def st_lineFromText(wkt) -> LineString:
    return _typed_from_text(wkt, LineString, "st_lineFromText")


def st_polygonFromText(wkt) -> Polygon:
    return _typed_from_text(wkt, Polygon, "st_polygonFromText")


st_polygon = st_polygonFromText


def st_mPointFromText(wkt) -> MultiPoint:
    return _typed_from_text(wkt, MultiPoint, "st_mPointFromText")


def st_mLineFromText(wkt) -> MultiLineString:
    return _typed_from_text(wkt, MultiLineString, "st_mLineFromText")


def st_mPolyFromText(wkt) -> MultiPolygon:
    return _typed_from_text(wkt, MultiPolygon, "st_mPolyFromText")


def st_geomFromGeoJSON(doc) -> Geometry:
    d = json.loads(doc) if isinstance(doc, str) else doc
    t = d["type"]
    c = d.get("coordinates")
    if t == "Point":
        return Point(float(c[0]), float(c[1]))
    if t == "MultiPoint":
        return MultiPoint(tuple(Point(float(p[0]), float(p[1])) for p in c))
    if t == "LineString":
        return LineString(tuple((float(p[0]), float(p[1])) for p in c))
    if t == "MultiLineString":
        return MultiLineString(
            tuple(LineString(tuple((float(p[0]), float(p[1])) for p in ls)) for ls in c)
        )
    if t == "Polygon":
        rings = [tuple((float(p[0]), float(p[1])) for p in r) for r in c]
        return Polygon(rings[0], tuple(rings[1:]))
    if t == "MultiPolygon":
        polys = []
        for pc in c:
            rings = [tuple((float(p[0]), float(p[1])) for p in r) for r in pc]
            polys.append(Polygon(rings[0], tuple(rings[1:])))
        return MultiPolygon(tuple(polys))
    raise ValueError(f"unsupported GeoJSON geometry type {t!r}")


# ===========================================================================
# WKB (GeometricOutputFunctions st_asBinary / constructors st_geomFromWKB)
# ===========================================================================

_WKB_TYPES = {
    "point": 1, "linestring": 2, "polygon": 3,
    "multipoint": 4, "multilinestring": 5, "multipolygon": 6,
}


def _wkb_encode(g: Geometry) -> bytes:
    """Little-endian ISO WKB."""
    def header(t):
        return struct.pack("<BI", 1, t)

    def pts(seq):
        return struct.pack("<I", len(seq)) + b"".join(
            struct.pack("<dd", float(x), float(y)) for x, y in seq
        )

    if isinstance(g, Point):
        return header(1) + struct.pack("<dd", g.x, g.y)
    if isinstance(g, LineString):
        return header(2) + pts(g.coords)
    if isinstance(g, Polygon):
        rings = [geo._close_ring(g.shell)] + [geo._close_ring(h) for h in g.holes]
        return header(3) + struct.pack("<I", len(rings)) + b"".join(pts(r) for r in rings)
    if isinstance(g, MultiPoint):
        return header(4) + struct.pack("<I", len(g.points)) + b"".join(
            _wkb_encode(p) for p in g.points
        )
    if isinstance(g, MultiLineString):
        return header(5) + struct.pack("<I", len(g.lines)) + b"".join(
            _wkb_encode(ls) for ls in g.lines
        )
    if isinstance(g, MultiPolygon):
        return header(6) + struct.pack("<I", len(g.polygons)) + b"".join(
            _wkb_encode(p) for p in g.polygons
        )
    raise ValueError(f"cannot WKB-encode {type(g).__name__}")


def _wkb_decode(buf: bytes, off: int = 0) -> Tuple[Geometry, int]:
    bo = "<" if buf[off] == 1 else ">"
    (t,) = struct.unpack_from(bo + "I", buf, off + 1)
    off += 5
    t &= 0xFF  # mask any SRID/dimension flags

    def pts(off):
        (n,) = struct.unpack_from(bo + "I", buf, off)
        off += 4
        out = []
        for _ in range(n):
            x, y = struct.unpack_from(bo + "dd", buf, off)
            out.append((x, y))
            off += 16
        return tuple(out), off

    if t == 1:
        x, y = struct.unpack_from(bo + "dd", buf, off)
        return Point(x, y), off + 16
    if t == 2:
        c, off = pts(off)
        return LineString(c), off
    if t == 3:
        (nr,) = struct.unpack_from(bo + "I", buf, off)
        off += 4
        rings = []
        for _ in range(nr):
            r, off = pts(off)
            rings.append(r)
        return Polygon(rings[0], tuple(rings[1:])), off
    if t in (4, 5, 6):
        (n,) = struct.unpack_from(bo + "I", buf, off)
        off += 4
        parts = []
        for _ in range(n):
            g, off = _wkb_decode(buf, off)
            parts.append(g)
        if t == 4:
            return MultiPoint(tuple(parts)), off
        if t == 5:
            return MultiLineString(tuple(parts)), off
        return MultiPolygon(tuple(parts)), off
    raise ValueError(f"unsupported WKB type {t}")


def st_asBinary(g: GeomLike) -> bytes:
    return _wkb_encode(_geom(g))


def st_byteArray(s: str) -> bytes:
    return s.encode("utf-8")


def st_geomFromWKB(buf: bytes) -> Geometry:
    return _wkb_decode(bytes(buf))[0]


def st_pointFromWKB(buf: bytes) -> Point:
    g = st_geomFromWKB(buf)
    if not isinstance(g, Point):
        raise ValueError("st_pointFromWKB: WKB is not a point")
    return g


# ===========================================================================
# Outputs (GeometricOutputFunctions)
# ===========================================================================

def st_asText(g):
    if isinstance(g, np.ndarray):
        return _map(lambda x: x.wkt(), g)
    return _geom(g).wkt()


def st_asGeoJSON(g: GeomLike) -> str:
    from geomesa_tpu.io.geojson import _shape_to_json

    return json.dumps(_shape_to_json(_geom(g)))


def st_asLatLonText(g: GeomLike) -> str:
    p = _geom(g)
    if not isinstance(p, Point):
        raise ValueError("st_asLatLonText takes a point")

    def dms(v, pos, neg):
        h = pos if v >= 0 else neg
        v = abs(v)
        d = int(v)
        m = int((v - d) * 60)
        s = (v - d - m / 60) * 3600
        return f"{d}°{m:02d}'{s:06.3f}\"{h}"

    return f"{dms(p.y, 'N', 'S')} {dms(p.x, 'E', 'W')}"


# ===========================================================================
# GeoHash (st_geoHash family; reference utils/geohash/)
# ===========================================================================

_GH32 = "0123456789bcdefghjkmnpqrstuvwxyz"
_GH32_INV = {c: i for i, c in enumerate(_GH32)}


def geohash_encode(x, y, precision_bits: int) -> np.ndarray:
    """Vectorized geohash of (lon, lat) arrays at ``precision_bits``
    (multiple of 5 -> precision_bits/5 base-32 chars). Bit interleave starts
    with longitude, matching the standard."""
    x = np.atleast_1d(np.asarray(x, np.float64))
    y = np.atleast_1d(np.asarray(y, np.float64))
    nlon = (precision_bits + 1) // 2
    nlat = precision_bits // 2
    ix = np.clip(((x + 180.0) / 360.0 * (1 << nlon)).astype(np.uint64), 0, (1 << nlon) - 1)
    iy = np.clip(((y + 90.0) / 180.0 * (1 << nlat)).astype(np.uint64), 0, (1 << nlat) - 1)
    # interleave: bit k of result (from MSB, k=0 is lon MSB)
    bits = np.zeros(x.shape, np.uint64)
    for k in range(precision_bits):
        if k % 2 == 0:  # longitude bit
            src = (ix >> np.uint64(nlon - 1 - k // 2)) & np.uint64(1)
        else:
            src = (iy >> np.uint64(nlat - 1 - k // 2)) & np.uint64(1)
        bits = (bits << np.uint64(1)) | src
    nchars = precision_bits // 5
    out = np.empty(x.shape, dtype=object)
    for i in range(len(out)):
        v = int(bits[i])
        s = ""
        for c in range(nchars):
            s = _GH32[v & 31] + s
            v >>= 5
        out[i] = s
    return out


def geohash_decode_bbox(h: str) -> Tuple[float, float, float, float]:
    xmin, xmax, ymin, ymax = -180.0, 180.0, -90.0, 90.0
    lon_turn = True
    for ch in h:
        v = _GH32_INV[ch]
        for b in (16, 8, 4, 2, 1):
            if lon_turn:
                mid = (xmin + xmax) / 2
                if v & b:
                    xmin = mid
                else:
                    xmax = mid
            else:
                mid = (ymin + ymax) / 2
                if v & b:
                    ymin = mid
                else:
                    ymax = mid
            lon_turn = not lon_turn
    return xmin, ymin, xmax, ymax


def st_geoHash(g, precision_bits: int = 25):
    """Geometry (or x/y arrays via st_geoHash((x, y), bits)) -> geohash."""
    if isinstance(g, tuple) and len(g) == 2 and np.ndim(g[0]) > 0:
        return geohash_encode(g[0], g[1], precision_bits)
    p = _geom(g)
    if not isinstance(p, Point):
        xmin, ymin, xmax, ymax = p.bounds()
        p = Point((xmin + xmax) / 2, (ymin + ymax) / 2)
    return geohash_encode([p.x], [p.y], precision_bits)[0]


def st_geomFromGeoHash(h: str, prec: Optional[int] = None) -> Polygon:
    s = h if prec is None else h[: max(1, prec // 5)]
    return bbox_polygon(*geohash_decode_bbox(s))


st_box2DFromGeoHash = st_geomFromGeoHash


def st_pointFromGeoHash(h: str, prec: Optional[int] = None) -> Point:
    xmin, ymin, xmax, ymax = st_geomFromGeoHash(h, prec).bounds()
    return Point((xmin + xmax) / 2, (ymin + ymax) / 2)


# ===========================================================================
# Accessors (GeometricAccessorFunctions)
# ===========================================================================

def st_x(g):
    if isinstance(g, np.ndarray) and g.dtype == object:
        return np.array([_geom(p).x if p is not None else np.nan for p in g])
    p = _geom(g)
    return p.x if isinstance(p, Point) else None


def st_y(g):
    if isinstance(g, np.ndarray) and g.dtype == object:
        return np.array([_geom(p).y if p is not None else np.nan for p in g])
    p = _geom(g)
    return p.y if isinstance(p, Point) else None


def st_envelope(g: GeomLike) -> Geometry:
    gm = _geom(g)
    xmin, ymin, xmax, ymax = gm.bounds()
    if xmin == xmax and ymin == ymax:
        return Point(xmin, ymin)
    return bbox_polygon(xmin, ymin, xmax, ymax)


def st_exteriorRing(g: GeomLike) -> Optional[LineString]:
    gm = _geom(g)
    if not isinstance(gm, Polygon):
        return None
    return LineString(tuple(map(tuple, geo._close_ring(gm.shell))))


def st_interiorRingN(g: GeomLike, n: int) -> Optional[LineString]:
    gm = _geom(g)
    if not isinstance(gm, Polygon) or n >= len(gm.holes):
        return None
    return LineString(tuple(map(tuple, geo._close_ring(gm.holes[n]))))


def _parts(g: Geometry) -> List[Geometry]:
    if isinstance(g, MultiPoint):
        return list(g.points)
    if isinstance(g, MultiLineString):
        return list(g.lines)
    if isinstance(g, MultiPolygon):
        return list(g.polygons)
    return [g]


def st_geometryN(g: GeomLike, n: int) -> Optional[Geometry]:
    parts = _parts(_geom(g))
    return parts[n] if 0 <= n < len(parts) else None


def st_numGeometries(g: GeomLike) -> int:
    return len(_parts(_geom(g)))


def _coords_of(g: Geometry) -> np.ndarray:
    if isinstance(g, Point):
        return np.array([[g.x, g.y]])
    if isinstance(g, MultiPoint):
        return np.array([[p.x, p.y] for p in g.points])
    if isinstance(g, LineString):
        return np.asarray(g.coords, np.float64)
    if isinstance(g, MultiLineString):
        return np.concatenate([np.asarray(ls.coords, np.float64) for ls in g.lines])
    if isinstance(g, Polygon):
        return np.concatenate([r for r in g.rings()])
    if isinstance(g, MultiPolygon):
        return np.concatenate([_coords_of(p) for p in g.polygons])
    raise ValueError(type(g).__name__)


def st_numPoints(g: GeomLike) -> int:
    return len(_coords_of(_geom(g)))


def st_pointN(g: GeomLike, n: int) -> Optional[Point]:
    gm = _geom(g)
    if not isinstance(gm, LineString):
        return None
    if n < 0:
        n += len(gm.coords)
    if not (0 <= n < len(gm.coords)):
        return None
    return Point(*gm.coords[n])


def st_coordDim(g: GeomLike) -> int:
    return 2


def st_dimension(g: GeomLike) -> int:
    gm = _geom(g)
    if isinstance(gm, (Point, MultiPoint)):
        return 0
    if isinstance(gm, (LineString, MultiLineString)):
        return 1
    return 2


def st_geometryType(g: GeomLike) -> str:
    return {
        "point": "Point", "multipoint": "MultiPoint",
        "linestring": "LineString", "multilinestring": "MultiLineString",
        "polygon": "Polygon", "multipolygon": "MultiPolygon",
    }[_geom(g).kind]


def st_isClosed(g: GeomLike) -> bool:
    gm = _geom(g)
    if isinstance(gm, LineString):
        return len(gm.coords) > 2 and gm.coords[0] == gm.coords[-1]
    if isinstance(gm, MultiLineString):
        return all(st_isClosed(ls) for ls in gm.lines)
    return True  # points and polygons are closed by definition


def st_isRing(g: GeomLike) -> bool:
    gm = _geom(g)
    return isinstance(gm, LineString) and st_isClosed(gm) and st_isSimple(gm)


def st_isCollection(g: GeomLike) -> bool:
    return isinstance(_geom(g), (MultiPoint, MultiLineString, MultiPolygon))


def st_isEmpty(g: GeomLike) -> bool:
    gm = _geom(g)
    try:
        return len(_coords_of(gm)) == 0
    except ValueError:
        return True


def st_isSimple(g: GeomLike) -> bool:
    """No self-intersection (lines) / valid ring orientation (polygons)."""
    gm = _geom(g)
    if isinstance(gm, (Point, MultiPoint)):
        return True
    if isinstance(gm, LineString):
        e = _edges(gm)
        return not _segments_self_intersect(e)
    if isinstance(gm, MultiLineString):
        return all(st_isSimple(ls) for ls in gm.lines)
    return st_isValid(gm)


def st_isValid(g: GeomLike) -> bool:
    gm = _geom(g)
    if isinstance(gm, (Point, MultiPoint, LineString, MultiLineString)):
        return not st_isEmpty(gm)
    polys = gm.polygons if isinstance(gm, MultiPolygon) else (gm,)
    for p in polys:
        ring = np.asarray(geo._close_ring(p.shell), np.float64)
        if len(ring) < 4:
            return False
        if _segments_self_intersect(_ring_edges(ring)):
            return False
    return True


def st_boundary(g: GeomLike) -> Geometry:
    gm = _geom(g)
    if isinstance(gm, Polygon):
        rings = [LineString(tuple(map(tuple, r))) for r in gm.rings()]
        return rings[0] if len(rings) == 1 else MultiLineString(tuple(rings))
    if isinstance(gm, MultiPolygon):
        rings = [
            LineString(tuple(map(tuple, r)))
            for p in gm.polygons
            for r in p.rings()
        ]
        return MultiLineString(tuple(rings))
    if isinstance(gm, LineString):
        return MultiPoint((Point(*gm.coords[0]), Point(*gm.coords[-1])))
    if isinstance(gm, MultiLineString):
        pts = []
        for ls in gm.lines:
            pts += [Point(*ls.coords[0]), Point(*ls.coords[-1])]
        return MultiPoint(tuple(pts))
    return MultiPoint(())  # points have empty boundary


# ===========================================================================
# Casts (GeometricCastFunctions)
# ===========================================================================

def st_castToPoint(g: GeomLike) -> Point:
    gm = _geom(g)
    if not isinstance(gm, Point):
        raise ValueError("st_castToPoint: not a point")
    return gm


def st_castToLineString(g: GeomLike) -> LineString:
    gm = _geom(g)
    if not isinstance(gm, LineString):
        raise ValueError("st_castToLineString: not a linestring")
    return gm


def st_castToPolygon(g: GeomLike) -> Polygon:
    gm = _geom(g)
    if not isinstance(gm, Polygon):
        raise ValueError("st_castToPolygon: not a polygon")
    return gm


def st_castToGeometry(g: GeomLike) -> Geometry:
    return _geom(g)


# ===========================================================================
# Segment primitives (shared by relations & processing)
# ===========================================================================

def _edges(g: Geometry) -> np.ndarray:
    """[E, 4] (x1, y1, x2, y2) boundary segments."""
    if isinstance(g, LineString):
        a = np.asarray(g.coords, np.float64)
        return np.concatenate([a[:-1], a[1:]], axis=1)
    if isinstance(g, MultiLineString):
        return np.concatenate([_edges(ls) for ls in g.lines])
    if isinstance(g, Polygon):
        segs = []
        for r in g.rings():
            segs.append(np.concatenate([r[:-1], r[1:]], axis=1))
        return np.concatenate(segs)
    if isinstance(g, MultiPolygon):
        return np.concatenate([_edges(p) for p in g.polygons])
    raise ValueError(f"no edges for {type(g).__name__}")


def _ring_edges(ring: np.ndarray) -> np.ndarray:
    return np.concatenate([ring[:-1], ring[1:]], axis=1)


def _cross(ox, oy, ax, ay, bx, by):
    return (ax - ox) * (by - oy) - (ay - oy) * (bx - ox)


def _seg_intersect_matrix(A: np.ndarray, B: np.ndarray,
                          proper_only: bool = False) -> np.ndarray:
    """[Ea, Eb] pairwise segment intersection tests."""
    ax1, ay1, ax2, ay2 = (A[:, i][:, None] for i in range(4))
    bx1, by1, bx2, by2 = (B[:, i][None, :] for i in range(4))
    d1 = _cross(ax1, ay1, ax2, ay2, bx1, by1)
    d2 = _cross(ax1, ay1, ax2, ay2, bx2, by2)
    d3 = _cross(bx1, by1, bx2, by2, ax1, ay1)
    d4 = _cross(bx1, by1, bx2, by2, ax2, ay2)
    proper = ((d1 > 0) != (d2 > 0)) & ((d3 > 0) != (d4 > 0)) \
        & (d1 != 0) & (d2 != 0) & (d3 != 0) & (d4 != 0)
    if proper_only:
        return proper

    def on(d, px, py, qx, qy, rx, ry):
        return (d == 0) & (np.minimum(px, qx) <= rx) & (rx <= np.maximum(px, qx)) \
            & (np.minimum(py, qy) <= ry) & (ry <= np.maximum(py, qy))

    touch = (
        on(d1, ax1, ay1, ax2, ay2, bx1, by1)
        | on(d2, ax1, ay1, ax2, ay2, bx2, by2)
        | on(d3, bx1, by1, bx2, by2, ax1, ay1)
        | on(d4, bx1, by1, bx2, by2, ax2, ay2)
    )
    return proper | touch


def _segments_self_intersect(E: np.ndarray) -> bool:
    """Any non-adjacent pair of segments intersecting. Segments from a
    closed ring (last endpoint == first start) also treat the wraparound
    pair as adjacent."""
    n = len(E)
    if n < 3:
        return False
    m = _seg_intersect_matrix(E, E)
    adj = np.zeros((n, n), dtype=bool)
    i = np.arange(n)
    adj[i, i] = True
    adj[i[:-1], i[:-1] + 1] = True
    adj[i[:-1] + 1, i[:-1]] = True
    if tuple(E[-1, 2:]) == tuple(E[0, :2]):  # closed-ring wraparound
        adj[0, n - 1] = adj[n - 1, 0] = True
    return bool((m & ~adj).any())


# ===========================================================================
# Spatial relations (SpatialRelationFunctions)
#
# Array fast path: every predicate accepts ``st_contains(g, (x, y))`` with
# coordinate arrays and returns a boolean mask — the form the filter
# compiler fuses into scan kernels. Scalar geometry-pair forms implement the
# standard predicate semantics via point-membership + segment intersection.
# ===========================================================================

def _is_xy(b) -> bool:
    return isinstance(b, tuple) and len(b) == 2 and np.ndim(b[0]) > 0


def _any_vertex_in(a: Geometry, b: Geometry, strict: bool = False) -> bool:
    c = _coords_of(a)
    m = b.contains_points(c[:, 0], c[:, 1])
    if strict and m.any() and st_dimension(b) == 2:
        onb = _on_boundary_of(b, c[:, 0], c[:, 1])
        m = m & ~onb
    return bool(m.any())


def _all_vertices_in(a: Geometry, b: Geometry) -> bool:
    c = _coords_of(a)
    return bool(b.contains_points(c[:, 0], c[:, 1]).all())


def _on_boundary_of(g: Geometry, xs, ys) -> np.ndarray:
    xs, ys = np.asarray(xs, np.float64), np.asarray(ys, np.float64)
    out = np.zeros(xs.shape, dtype=bool)
    if st_dimension(g) == 0:
        return out
    for e in _edges(g):
        out |= geo._on_segment(xs, ys, e[:2], e[2:])
    return out


def _boundaries_cross(a: Geometry, b: Geometry, proper_only=False) -> bool:
    if st_dimension(a) == 0 or st_dimension(b) == 0:
        return False
    return bool(_seg_intersect_matrix(_edges(a), _edges(b), proper_only).any())


def st_intersects(a: GeomLike, b) -> "bool | np.ndarray":
    if _is_xy(b):
        return _geom(a).contains_points(np.asarray(b[0]), np.asarray(b[1]))
    ga, gb = _geom(a), _geom(b)
    if not geo.bounds_intersect(ga.bounds(), gb.bounds()):
        return False
    return (
        _any_vertex_in(ga, gb)
        or _any_vertex_in(gb, ga)
        or _boundaries_cross(ga, gb)
    )


def st_disjoint(a: GeomLike, b) -> "bool | np.ndarray":
    r = st_intersects(a, b)
    return ~r if isinstance(r, np.ndarray) else not r


def st_contains(a: GeomLike, b) -> "bool | np.ndarray":
    """a contains b: b entirely in a's closure, interiors intersecting.
    For the common polygon/point-array case this is exact; polygon-polygon
    uses all-vertices-in + no-boundary-crossing (exact for simple shapes)."""
    if _is_xy(b):
        return _geom(a).contains_points(np.asarray(b[0]), np.asarray(b[1]))
    ga, gb = _geom(a), _geom(b)
    if not geo.bounds_intersect(ga.bounds(), gb.bounds()):
        return False
    if st_dimension(ga) < st_dimension(gb):
        return False
    return _all_vertices_in(gb, ga) and not _boundaries_cross(ga, gb, proper_only=True)


def st_within(a: GeomLike, b: GeomLike) -> bool:
    return st_contains(_geom(b), _geom(a))


def st_covers(a: GeomLike, b) -> "bool | np.ndarray":
    # boundary-inclusive containment; our contains_points is already
    # boundary-inclusive so covers == contains here
    return st_contains(a, b)


def st_crosses(a: GeomLike, b: GeomLike) -> bool:
    """Interiors intersect and the intersection has lower dimension than the
    max operand (line x line at a point, line through polygon, ...)."""
    ga, gb = _geom(a), _geom(b)
    da, db = st_dimension(ga), st_dimension(gb)
    if da == db == 1:
        return _boundaries_cross(ga, gb, proper_only=True)
    if da == 0 or db == 0:
        pt, other = (ga, gb) if da == 0 else (gb, ga)
        c = _coords_of(pt)
        inside = other.contains_points(c[:, 0], c[:, 1])
        return bool(inside.any() and not inside.all())
    if {da, db} == {1, 2}:
        line, poly = (ga, gb) if da == 1 else (gb, ga)
        # a proper crossing of the polygon boundary means the line passes
        # between interior and exterior; else look for interior + exterior
        # vertex evidence
        if _boundaries_cross(line, poly, proper_only=True):
            return True
        c = _coords_of(line)
        inside = poly.contains_points(c[:, 0], c[:, 1])
        onb = _on_boundary_of(poly, c[:, 0], c[:, 1])
        interior = inside & ~onb
        outside = ~inside
        return bool(interior.any() and outside.any())
    return False  # polygon x polygon cannot cross


def st_overlaps(a: GeomLike, b: GeomLike) -> bool:
    """Same dimension, interiors intersect, neither contains the other."""
    ga, gb = _geom(a), _geom(b)
    if st_dimension(ga) != st_dimension(gb):
        return False
    return (
        bool(st_intersects(ga, gb))
        and not st_contains(ga, gb)
        and not st_contains(gb, ga)
    )


def st_touches(a: GeomLike, b: GeomLike) -> bool:
    """Boundaries meet but interiors do not intersect."""
    ga, gb = _geom(a), _geom(b)
    if not st_intersects(ga, gb):
        return False
    if st_dimension(ga) == 2 and st_dimension(gb) == 0:
        c = _coords_of(gb)
        onb = _on_boundary_of(ga, c[:, 0], c[:, 1])
        inside = ga.contains_points(c[:, 0], c[:, 1])
        return bool(onb.any() and not (inside & ~onb).any())
    if st_dimension(gb) == 2 and st_dimension(ga) == 0:
        return st_touches(gb, ga)
    # general: intersect but no interior-interior evidence
    return (
        not _any_vertex_in(ga, gb, strict=True)
        and not _any_vertex_in(gb, ga, strict=True)
        and not _boundaries_cross(ga, gb, proper_only=True)
    )


def st_equals(a: GeomLike, b: GeomLike) -> bool:
    ga, gb = _geom(a), _geom(b)
    if st_dimension(ga) != st_dimension(gb):
        return False
    ba, bb = np.asarray(ga.bounds()), np.asarray(gb.bounds())
    if not np.allclose(ba, bb):
        return False
    if isinstance(ga, Point) and isinstance(gb, Point):
        return ga.x == gb.x and ga.y == gb.y
    if st_dimension(ga) == 2:
        return st_contains(ga, gb) and st_contains(gb, ga)
    ca, cb = _coords_of(ga), _coords_of(gb)
    # same vertex set (tolerates ring rotation / direction)
    sa = {tuple(p) for p in ca.tolist()}
    sb = {tuple(p) for p in cb.tolist()}
    return sa == sb


def st_relate(a: GeomLike, b: GeomLike) -> str:
    """DE-9IM matrix string, derived from the predicate set (dimension
    entries are the best-available approximation: 'T' evidence uses the
    operand dimensions; refer to the individual predicates for exactness)."""
    ga, gb = _geom(a), _geom(b)
    da, db = st_dimension(ga), st_dimension(gb)
    inter = bool(st_intersects(ga, gb))
    if not inter:
        m = ["F", "F", str(da), "F", "F", _bdim(da), str(db), _bdim(db), "2"]
        return "".join(m)
    within = st_contains(gb, ga)
    contains = st_contains(ga, gb)
    ii = str(min(da, db))
    m = [ii, "F", "F", "F", "F", "F", "F", "F", "2"]
    # interior/exterior and boundary entries from the containment facts
    m[1] = _bdim(db) if not contains or db < 2 else "F"       # I(a) ∩ B(b)
    m[2] = "F" if within else str(da)                          # I(a) ∩ E(b)
    m[3] = _bdim(da) if not within or da < 2 else "F"          # B(a) ∩ I(b)
    m[4] = _bdim(min(da, db)) if da and db else "F"            # B ∩ B
    m[5] = "F" if within else _bdim(da)                        # B(a) ∩ E(b)
    m[6] = "F" if contains else str(db)                        # E(a) ∩ I(b)
    m[7] = "F" if contains else _bdim(db)                      # E(a) ∩ B(b)
    return "".join(m)


def _bdim(d: int) -> str:
    return "F" if d == 0 else str(d - 1)


def st_relateBool(a: GeomLike, b: GeomLike, pattern: str) -> bool:
    got = st_relate(a, b)
    for g, p in zip(got, pattern):
        if p == "*":
            continue
        if p == "T":
            if g == "F":
                return False
        elif p != g:
            return False
    return True


# ===========================================================================
# Processing (GeometricProcessingFunctions)
# ===========================================================================

def st_area(g) -> "float | np.ndarray":
    if isinstance(g, np.ndarray):
        return np.array([st_area(x) if x is not None else np.nan for x in g])
    gm = _geom(g)
    if isinstance(gm, MultiPolygon):
        return float(sum(st_area(p) for p in gm.polygons))
    if not isinstance(gm, Polygon):
        return 0.0
    total = _ring_area(np.asarray(geo._close_ring(gm.shell), np.float64))
    for h in gm.holes:
        total -= _ring_area(np.asarray(geo._close_ring(h), np.float64))
    return float(max(total, 0.0))


def _ring_area(r: np.ndarray) -> float:
    x, y = r[:, 0], r[:, 1]
    return abs(float(np.sum(x[:-1] * y[1:] - x[1:] * y[:-1])) / 2.0)


def st_length(g: GeomLike) -> float:
    """Planar length in degrees (lines; polygon -> 0 like JTS's getLength
    convention for the spark UDF, which uses line length only)."""
    gm = _geom(g)
    if isinstance(gm, (LineString, MultiLineString)):
        e = _edges(gm)
        return float(np.hypot(e[:, 2] - e[:, 0], e[:, 3] - e[:, 1]).sum())
    return 0.0


def st_lengthSphere(g: GeomLike) -> float:
    gm = _geom(g)
    if not isinstance(gm, (LineString, MultiLineString)):
        return 0.0
    e = _edges(gm)
    return float(haversine_m(e[:, 0], e[:, 1], e[:, 2], e[:, 3]).sum())


st_lengthSpheroid = st_lengthSphere


def st_perimeter(g: GeomLike) -> float:
    gm = _geom(g)
    if isinstance(gm, (Polygon, MultiPolygon)):
        e = _edges(gm)
        return float(np.hypot(e[:, 2] - e[:, 0], e[:, 3] - e[:, 1]).sum())
    return 0.0


def st_centroid(g: GeomLike) -> Point:
    gm = _geom(g)
    if isinstance(gm, Point):
        return gm
    if isinstance(gm, MultiPoint):
        c = _coords_of(gm)
        return Point(float(c[:, 0].mean()), float(c[:, 1].mean()))
    if isinstance(gm, (LineString, MultiLineString)):
        e = _edges(gm)
        L = np.hypot(e[:, 2] - e[:, 0], e[:, 3] - e[:, 1])
        mx = (e[:, 0] + e[:, 2]) / 2
        my = (e[:, 1] + e[:, 3]) / 2
        w = L.sum() or 1.0
        return Point(float((mx * L).sum() / w), float((my * L).sum() / w))
    polys = gm.polygons if isinstance(gm, MultiPolygon) else (gm,)
    cx = cy = aw = 0.0
    for p in polys:
        for sign, ring in [(1.0, np.asarray(geo._close_ring(p.shell), np.float64))] + [
            (-1.0, np.asarray(geo._close_ring(h), np.float64)) for h in p.holes
        ]:
            x, y = ring[:-1, 0], ring[:-1, 1]
            x1, y1 = ring[1:, 0], ring[1:, 1]
            c = x * y1 - x1 * y
            a = float(c.sum()) / 2.0
            if a == 0:
                continue
            cx += sign * float(((x + x1) * c).sum()) / 6.0
            cy += sign * float(((y + y1) * c).sum()) / 6.0
            aw += sign * a
    if aw == 0:
        c = _coords_of(gm)
        return Point(float(c[:, 0].mean()), float(c[:, 1].mean()))
    return Point(cx / aw, cy / aw)


def st_distance(a: GeomLike, b) -> "float | np.ndarray":
    """Planar (degree-space) minimum distance. Array form: st_distance(g,
    (x, y)) -> per-point distance to g."""
    if _is_xy(b):
        return _dist_to_geom(_geom(a), np.asarray(b[0], np.float64),
                             np.asarray(b[1], np.float64))
    ga, gb = _geom(a), _geom(b)
    ca = _coords_of(ga)
    d1 = _dist_to_geom(gb, ca[:, 0], ca[:, 1]).min()
    cb = _coords_of(gb)
    d2 = _dist_to_geom(ga, cb[:, 0], cb[:, 1]).min()
    return float(min(d1, d2))


def _dist_to_geom(g: Geometry, xs: np.ndarray, ys: np.ndarray) -> np.ndarray:
    """Per-point planar distance to geometry (0 inside polygons)."""
    if isinstance(g, Point):
        return np.hypot(xs - g.x, ys - g.y)
    if isinstance(g, MultiPoint):
        c = _coords_of(g)
        return np.min(
            np.hypot(xs[:, None] - c[None, :, 0], ys[:, None] - c[None, :, 1]),
            axis=1,
        )
    E = _edges(g)
    ax, ay = E[None, :, 0], E[None, :, 1]
    dx, dy = E[None, :, 2] - ax, E[None, :, 3] - ay
    L2 = np.maximum(dx * dx + dy * dy, 1e-300)
    t = np.clip(((xs[:, None] - ax) * dx + (ys[:, None] - ay) * dy) / L2, 0, 1)
    d = np.hypot(xs[:, None] - (ax + t * dx), ys[:, None] - (ay + t * dy)).min(axis=1)
    if st_dimension(g) == 2:
        d = np.where(g.contains_points(xs, ys), 0.0, d)
    return d


def st_distanceSphere(a: GeomLike, b) -> "float | np.ndarray":
    """Great-circle distance in meters (point-point exact; other pairs use
    the planar closest-point pair, then measure it geodesically)."""
    if _is_xy(b):
        ga = _geom(a)
        if isinstance(ga, Point):
            return haversine_m(np.asarray(b[0]), np.asarray(b[1]), ga.x, ga.y)
        xs, ys = np.asarray(b[0], np.float64), np.asarray(b[1], np.float64)
        return _dist_to_geom(ga, xs, ys) * METERS_PER_DEGREE
    ga, gb = _geom(a), _geom(b)
    if isinstance(ga, Point) and isinstance(gb, Point):
        return float(haversine_m(ga.x, ga.y, gb.x, gb.y))
    pa, pb = st_closestPoint(ga, gb), st_closestPoint(gb, ga)
    return float(haversine_m(pa.x, pa.y, pb.x, pb.y))


st_distanceSpheroid = st_distanceSphere


def st_closestPoint(a: GeomLike, b: GeomLike) -> Point:
    """The point on ``a`` closest to ``b``."""
    ga, gb = _geom(a), _geom(b)
    if isinstance(ga, Point):
        return ga
    cb = _coords_of(gb)
    if st_dimension(ga) == 2 and bool(ga.contains_points(cb[:1, 0], cb[:1, 1])[0]):
        return Point(float(cb[0, 0]), float(cb[0, 1]))
    E = _edges(ga) if st_dimension(ga) > 0 else None
    if E is None:
        ca = _coords_of(ga)
        d = np.hypot(ca[:, 0][:, None] - cb[None, :, 0],
                     ca[:, 1][:, None] - cb[None, :, 1])
        i = np.unravel_index(np.argmin(d), d.shape)[0]
        return Point(float(ca[i, 0]), float(ca[i, 1]))
    best, bx, by = np.inf, 0.0, 0.0
    for x, y in cb:
        ax, ay = E[:, 0], E[:, 1]
        dx, dy = E[:, 2] - ax, E[:, 3] - ay
        L2 = np.maximum(dx * dx + dy * dy, 1e-300)
        t = np.clip(((x - ax) * dx + (y - ay) * dy) / L2, 0, 1)
        px, py = ax + t * dx, ay + t * dy
        d = np.hypot(x - px, y - py)
        i = int(np.argmin(d))
        if d[i] < best:
            best, bx, by = float(d[i]), float(px[i]), float(py[i])
    return Point(bx, by)


def st_bufferPoint(g: GeomLike, radius_m: float, segments: int = 32) -> Polygon:
    """Geodesic point buffer (the reference's st_bufferPoint builds a
    GeodeticCalculator circle): a polygon of ``segments`` vertices at
    great-circle distance ``radius_m``."""
    p = _geom(g)
    if not isinstance(p, Point):
        raise ValueError("st_bufferPoint takes a point")
    lat1 = math.radians(p.y)
    lon1 = math.radians(p.x)
    ang = radius_m / EARTH_RADIUS_M
    verts = []
    for i in range(segments):
        brg = 2 * math.pi * i / segments
        lat2 = math.asin(
            math.sin(lat1) * math.cos(ang)
            + math.cos(lat1) * math.sin(ang) * math.cos(brg)
        )
        lon2 = lon1 + math.atan2(
            math.sin(brg) * math.sin(ang) * math.cos(lat1),
            math.cos(ang) - math.sin(lat1) * math.sin(lat2),
        )
        verts.append((math.degrees(lon2), math.degrees(lat2)))
    verts.append(verts[0])
    return Polygon(tuple(verts))


def st_convexhull(g) -> Geometry:
    """Convex hull (monotone chain). Accepts a geometry, WKT, or an object
    array of geometries (the UDAF form: hull of everything)."""
    if isinstance(g, np.ndarray):
        pts = np.concatenate([_coords_of(_geom(x)) for x in g if x is not None])
    else:
        pts = _coords_of(_geom(g))
    pts = np.unique(pts, axis=0)
    if len(pts) == 1:
        return Point(float(pts[0, 0]), float(pts[0, 1]))
    if len(pts) == 2:
        return LineString(tuple(map(tuple, pts)))
    P = pts[np.lexsort((pts[:, 1], pts[:, 0]))]

    def half(points):
        out: List[np.ndarray] = []
        for p in points:
            while len(out) >= 2 and _cross(
                out[-2][0], out[-2][1], out[-1][0], out[-1][1], p[0], p[1]
            ) <= 0:
                out.pop()
            out.append(p)
        return out

    lower = half(P)
    upper = half(P[::-1])
    ring = lower[:-1] + upper[:-1]
    if len(ring) < 3:
        return LineString(tuple(map(tuple, pts)))
    ring.append(ring[0])
    return Polygon(tuple((float(x), float(y)) for x, y in ring))


def st_translate(g: GeomLike, dx: float, dy: float) -> Geometry:
    gm = _geom(g)
    if isinstance(gm, Point):
        return Point(gm.x + dx, gm.y + dy)
    if isinstance(gm, MultiPoint):
        return MultiPoint(tuple(Point(p.x + dx, p.y + dy) for p in gm.points))
    if isinstance(gm, LineString):
        return LineString(tuple((x + dx, y + dy) for x, y in gm.coords))
    if isinstance(gm, MultiLineString):
        return MultiLineString(tuple(st_translate(ls, dx, dy) for ls in gm.lines))
    if isinstance(gm, Polygon):
        return Polygon(
            tuple((x + dx, y + dy) for x, y in gm.shell),
            tuple(tuple((x + dx, y + dy) for x, y in h) for h in gm.holes),
        )
    if isinstance(gm, MultiPolygon):
        return MultiPolygon(tuple(st_translate(p, dx, dy) for p in gm.polygons))
    raise ValueError(type(gm).__name__)


def _clip_convex(subject: Polygon, clip: Polygon) -> Optional[Polygon]:
    """Sutherland–Hodgman: subject clipped by a CONVEX clip polygon."""
    cr = np.asarray(geo._close_ring(clip.shell), np.float64)
    # ensure counter-clockwise orientation
    if float(np.sum((cr[1:, 0] - cr[:-1, 0]) * (cr[1:, 1] + cr[:-1, 1]))) > 0:
        cr = cr[::-1]
    out = [tuple(p) for p in np.asarray(geo._close_ring(subject.shell), np.float64)[:-1]]
    for i in range(len(cr) - 1):
        if not out:
            return None
        ax, ay = cr[i]
        bx, by = cr[i + 1]
        new: List[Tuple[float, float]] = []
        for j in range(len(out)):
            cur = out[j]
            prv = out[j - 1]
            cur_in = _cross(ax, ay, bx, by, cur[0], cur[1]) >= 0
            prv_in = _cross(ax, ay, bx, by, prv[0], prv[1]) >= 0
            if cur_in != prv_in:
                # edge intersection with the clip line
                x1, y1 = prv
                x2, y2 = cur
                den = (bx - ax) * (y2 - y1) - (by - ay) * (x2 - x1)
                if den != 0:
                    t = ((bx - ax) * (ay - y1) - (by - ay) * (ax - x1)) / den
                    new.append((x1 + t * (x2 - x1), y1 + t * (y2 - y1)))
            if cur_in:
                new.append(cur)
        out = new
    if len(out) < 3:
        return None
    out.append(out[0])
    return Polygon(tuple(out))


def _is_convex(p: Polygon) -> bool:
    r = np.asarray(geo._close_ring(p.shell), np.float64)
    v = np.diff(r, axis=0)
    cr = v[:-1, 0] * v[1:, 1] - v[:-1, 1] * v[1:, 0]
    return bool((cr >= 0).all() or (cr <= 0).all())


def st_intersection(a: GeomLike, b: GeomLike) -> Optional[Geometry]:
    """Geometry intersection. Supported: point/multipoint vs anything;
    polygon vs convex polygon (Sutherland–Hodgman); identical geometries.
    Other pairs raise — the reference delegates these to JTS overlay, which
    is out of scope for the columnar hot path."""
    ga, gb = _geom(a), _geom(b)
    if not st_intersects(ga, gb):
        return None
    if st_dimension(ga) == 0:
        c = _coords_of(ga)
        m = gb.contains_points(c[:, 0], c[:, 1])
        kept = c[m]
        if len(kept) == 1:
            return Point(float(kept[0, 0]), float(kept[0, 1]))
        return MultiPoint(tuple(Point(float(x), float(y)) for x, y in kept))
    if st_dimension(gb) == 0:
        return st_intersection(gb, ga)
    if st_equals(ga, gb):
        return ga
    if isinstance(ga, Polygon) and isinstance(gb, Polygon) and not ga.holes and not gb.holes:
        if _is_convex(gb):
            return _clip_convex(ga, gb)
        if _is_convex(ga):
            return _clip_convex(gb, ga)
    raise NotImplementedError(
        "st_intersection supports point/* and polygon/convex-polygon pairs"
    )


def st_difference(a: GeomLike, b: GeomLike) -> Optional[Geometry]:
    """Supported: disjoint (returns a), point sets, and polygon minus a
    fully-contained hole-free polygon (returns a with a hole)."""
    ga, gb = _geom(a), _geom(b)
    if not st_intersects(ga, gb):
        return ga
    if st_dimension(ga) == 0:
        c = _coords_of(ga)
        m = ~gb.contains_points(c[:, 0], c[:, 1])
        kept = c[m]
        if len(kept) == 0:
            return None
        if len(kept) == 1:
            return Point(float(kept[0, 0]), float(kept[0, 1]))
        return MultiPoint(tuple(Point(float(x), float(y)) for x, y in kept))
    if (
        isinstance(ga, Polygon) and isinstance(gb, Polygon)
        and not gb.holes and st_contains(ga, gb)
        and not _boundaries_cross(ga, gb)
    ):
        return Polygon(ga.shell, ga.holes + (gb.shell,))
    raise NotImplementedError(
        "st_difference supports disjoint, point, and contained-polygon pairs"
    )


def st_antimeridianSafeGeom(g: GeomLike) -> Geometry:
    """Split geometries whose longitudes cross the ±180 antimeridian into a
    multipolygon of in-range pieces (reference st_antimeridianSafeGeom /
    st_idlSafeGeom)."""
    gm = _geom(g)
    xmin, ymin, xmax, ymax = gm.bounds()
    if xmin >= -180.0 and xmax <= 180.0:
        return gm
    if not isinstance(gm, Polygon):
        raise NotImplementedError("antimeridian split implemented for polygons")
    parts = []
    west = _clip_convex(gm, bbox_polygon(-540.0, -90.0, 180.0, 90.0))
    east = _clip_convex(gm, bbox_polygon(180.0, -90.0, 540.0, 90.0))
    if west is not None:
        parts.append(west)
    if east is not None:
        parts.append(
            Polygon(tuple((x - 360.0, y) for x, y in east.shell))
        )
    if len(parts) == 1:
        return parts[0]
    return MultiPolygon(tuple(parts))


st_idlSafeGeom = st_antimeridianSafeGeom


def st_aggregateDistanceSphere(points: Sequence[GeomLike]) -> float:
    """Total great-circle path length over a point sequence."""
    pts = [_geom(p) for p in points]
    if len(pts) < 2:
        return 0.0
    x = np.array([p.x for p in pts])
    y = np.array([p.y for p in pts])
    return float(haversine_m(x[:-1], y[:-1], x[1:], y[1:]).sum())
