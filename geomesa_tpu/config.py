"""Three-scope configuration system (system / store / query).

Mirrors GeoMesa's ``SystemProperty`` pattern
(reference: geomesa-utils/.../conf/GeoMesaSystemProperties.scala:19-60 and
geomesa-index-api/.../conf/QueryProperties.scala:15-50): a named, typed tunable
with a default, overridable by environment variable or a thread-local scope.

Resolution order: thread-local override > environment variable > default.
Environment variable name = property name with ``.``/``-`` replaced by ``_``,
upper-cased (e.g. ``geomesa.scan.ranges.target`` -> ``GEOMESA_SCAN_RANGES_TARGET``).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional

_local = threading.local()

_REGISTRY: Dict[str, "SystemProperty"] = {}


def _overrides() -> Dict[str, str]:
    if not hasattr(_local, "overrides"):
        _local.overrides = {}
    return _local.overrides


class SystemProperty:
    """A named tunable with a default and typed accessors."""

    def __init__(self, name: str, default: Optional[str] = None):
        self.name = name
        self.default = default
        self.env_name = name.replace(".", "_").replace("-", "_").upper()
        _REGISTRY[name] = self

    def get(self) -> Optional[str]:
        ov = _overrides()
        if self.name in ov:
            return ov[self.name]
        if self.env_name in os.environ:
            return os.environ[self.env_name]
        return self.default

    def set(self, value: Optional[Any]) -> None:
        """Thread-local override (None clears)."""
        ov = _overrides()
        if value is None:
            ov.pop(self.name, None)
        else:
            ov[self.name] = str(value)

    class _Scope:
        def __init__(self, prop: "SystemProperty", value: Any):
            self.prop, self.value = prop, value

        def __enter__(self):
            ov = _overrides()
            self.prev = ov.get(self.prop.name)
            ov[self.prop.name] = str(self.value)
            return self

        def __exit__(self, *exc):
            ov = _overrides()
            if self.prev is None:
                ov.pop(self.prop.name, None)
            else:
                ov[self.prop.name] = self.prev
            return False

    def scoped(self, value: Any) -> "SystemProperty._Scope":
        """``with prop.scoped(123): ...`` — temporary thread-local override."""
        return SystemProperty._Scope(self, value)

    # typed accessors -----------------------------------------------------
    def to_str(self) -> Optional[str]:
        return self.get()

    def to_int(self) -> Optional[int]:
        v = self.get()
        return None if v is None else int(v)

    def to_float(self) -> Optional[float]:
        v = self.get()
        return None if v is None else float(v)

    def to_bool(self) -> Optional[bool]:
        v = self.get()
        if v is None:
            return None
        return str(v).strip().lower() in ("1", "true", "yes", "on")

    def to_duration_ms(self) -> Optional[int]:
        """Parse '100 ms', '10s', '5 minutes', '1h' etc. to milliseconds."""
        v = self.get()
        if v is None:
            return None
        s = str(v).strip().lower()
        num = ""
        for ch in s:
            if ch.isdigit() or ch == ".":
                num += ch
            else:
                break
        unit = s[len(num):].strip()
        if not num:
            raise ValueError(f"invalid duration: {v!r}")
        x = float(num)
        factors = {
            "": 1, "ms": 1, "millis": 1, "millisecond": 1, "milliseconds": 1,
            "s": 1000, "sec": 1000, "second": 1000, "seconds": 1000,
            "m": 60_000, "min": 60_000, "minute": 60_000, "minutes": 60_000,
            "h": 3_600_000, "hour": 3_600_000, "hours": 3_600_000,
            "d": 86_400_000, "day": 86_400_000, "days": 86_400_000,
        }
        if unit not in factors:
            raise ValueError(f"invalid duration unit: {v!r}")
        return int(x * factors[unit])


def registry() -> Dict[str, SystemProperty]:
    return dict(_REGISTRY)


def snapshot_overrides() -> Dict[str, str]:
    """Copy of the CURRENT thread's override map. Overrides are
    thread-local, so a worker thread spawned mid-scope sees only
    env/defaults; pass this snapshot to :func:`adopt_overrides` on the
    worker so both threads resolve every property identically (the
    partition prefetcher does this — a bucketing knob diverging between
    the staging and consuming threads would silently mismatch shapes)."""
    return dict(_overrides())


def adopt_overrides(snapshot: Dict[str, str]) -> None:
    """Install a :func:`snapshot_overrides` copy as this thread's
    override map (replaces any existing thread-local overrides)."""
    _local.overrides = dict(snapshot)


# ---------------------------------------------------------------------------
# Query/scan tunables (names kept from the reference so operator docs carry
# over; see geomesa-index-api/.../conf/QueryProperties.scala).
# ---------------------------------------------------------------------------

#: Soft budget of z-ranges produced by range cover (reference default 2000,
#: QueryProperties.scala:24).
SCAN_RANGES_TARGET = SystemProperty("geomesa.scan.ranges.target", "2000")

#: Query timeout; None = unlimited.
QUERY_TIMEOUT = SystemProperty("geomesa.query.timeout", None)

#: Refuse full-table scans when set (FullTableScanQueryGuard analog).
BLOCK_FULL_TABLE_SCANS = SystemProperty("geomesa.scan.block-full-table", "false")

#: Force exact counts instead of estimates.
FORCE_COUNT = SystemProperty("geomesa.force.count", "false")

#: Loose BBOX semantics: evaluate BBOX on extent geometries as envelope
#: overlap only, skipping the exact-intersection refinement pass (the
#: reference's loose-bbox query option; default is exact).
LOOSE_BBOX = SystemProperty("geomesa.loose.bbox", "false")

#: Parallel shard-scan width (AbstractBatchScan thread analog).
QUERY_THREADS = SystemProperty("geomesa.query.threads", "8")

#: Default number of logical shards per index (ShardStrategy analog).
DEFAULT_SHARDS = SystemProperty("geomesa.index.shards", "4")

#: Density scan row batch (reference DensityScan.scala:58).
DENSITY_BATCH_SIZE = SystemProperty("geomesa.density.batch.size", "100000")

#: Stats scan row batch (reference StatsScan.scala:47).
STATS_BATCH_SIZE = SystemProperty("geomesa.stats.batch.size", "10000")

#: Enable cost-based strategy selection (StrategyDecider analog).
STRATEGY_DECIDER = SystemProperty("geomesa.strategy.decider", "cost")

#: Max interval (days) accepted by the temporal query guard when configured.
TEMPORAL_GUARD_MAX_DAYS = SystemProperty("geomesa.guard.temporal.max.days", None)

#: Default authorization set, comma-separated (geomesa-security analog).
#: Unset = unrestricted access; set (possibly empty auth list via per-query
#: auths) = visibility enforcement on.
SECURITY_AUTHS = SystemProperty("geomesa.security.auths", None)

#: Audit log destination: a JSONL file path, or unset for in-memory only.
AUDIT_PATH = SystemProperty("geomesa.audit.path", None)

#: Enable query auditing (QueryEvent records; reference index/audit/).
AUDIT_ENABLED = SystemProperty("geomesa.audit.enabled", "true")

# ---------------------------------------------------------------------------
# Time-partitioned / out-of-core store (TimePartition.scala:35 analog).
# ---------------------------------------------------------------------------

#: Spill directory for cold time partitions (unset = a per-store temp dir).
SPILL_DIR = SystemProperty("geomesa.partition.spill.dir", None)

#: Max time partitions kept resident in host RAM per partitioned store;
#: the rest live on disk and stream through partition-at-a-time.
MAX_RESIDENT_PARTITIONS = SystemProperty("geomesa.partition.max.resident", "4")

#: Partitioned tables round their padded shard length up to a multiple of
#: this, so near-equal partitions share one compiled scan kernel shape.
SHARD_LEN_BUCKET = SystemProperty("geomesa.partition.shard.bucket", "65536")

# ---------------------------------------------------------------------------
# Columnar geo-lake tier (docs/LAKE.md): the Spatial-Parquet-style spill
# format with per-row-group statistics and file-level pushdown.
# ---------------------------------------------------------------------------

#: Spill partitions as footer-indexed lake snapshots (off = the legacy
#: np.savez snapshots; either format always LOADS).
LAKE_ENABLED = SystemProperty("geomesa.lake.enabled", "true")

#: Rows per lake row group — the pruning granule. Smaller groups prune
#: tighter but cost more footer entries and per-group decode calls.
LAKE_ROWGROUP_ROWS = SystemProperty("geomesa.lake.rowgroup.rows", "16384")

#: Statistics-pruned partial loads for additive cold scans (count /
#: unweighted density / unweighted density_curve / stats): only the row
#: groups whose bbox/time statistics intersect the query load. Off =
#: every cold scan loads whole partitions (the pre-lake behavior).
LAKE_PUSHDOWN = SystemProperty("geomesa.lake.pushdown", "true")

#: Degrees added around a query bbox before it prunes row groups, so the
#: scan kernel's f32 edge arithmetic can never match a row whose group
#: was pruned away (the same safety family as cache.cells.CLASSIFY_MARGIN).
LAKE_PRUNE_MARGIN = SystemProperty("geomesa.lake.prune.margin", "1e-3")

# ---------------------------------------------------------------------------
# Compacted-scan + MXU density kernel tunables (r4; docs/SCALE.md cost
# model). Env names follow the standard mapping, e.g.
# geomesa.compact.min.rows -> GEOMESA_COMPACT_MIN_ROWS.
# ---------------------------------------------------------------------------

#: Enable the window-compacted scan layout (gather only window rows).
COMPACT_ENABLED = SystemProperty("geomesa.compact.enabled", "true")

#: Minimum table rows before compaction is considered.
COMPACT_MIN_ROWS = SystemProperty("geomesa.compact.min.rows", str(1 << 20))

#: Compaction engages only when padded chunk rows < this fraction of the
#: table (windows admitting most rows can't win).
COMPACT_FRACTION = SystemProperty("geomesa.compact.fraction", "0.5")

#: Chunk slab length override (0 = adaptive: least padding, largest B
#: within 10%).
COMPACT_B = SystemProperty("geomesa.compact.b", "0")

#: Range-cover budget for the compact path's fine (gap-union-free) window
#: resolution; <= geomesa.scan.ranges.target disables the fine pass.
COMPACT_COVER = SystemProperty("geomesa.compact.cover", "32768")

#: Bucket compiled-kernel shapes (padded window count K to a power of two
#: above the floor below; compact chunk counts already follow the
#: geometric ladder in kernels/density_mxu.ladder8) so distinct-but-similar
#: queries trace once per bucket instead of once per shape. Masked tails
#: keep results exact.
COMPACT_BUCKETING = SystemProperty("geomesa.compact.bucketing", "true")

#: Floor for the bucketed window count K: every query's K pads up to at
#: least this, so any plan with <= floor windows per shard shares one
#: kernel shape. Padded windows are empty (start == end == 0).
COMPACT_BUCKET_FLOOR = SystemProperty("geomesa.compact.bucket.floor", "8")

#: Plain (non-partitioned) stores round their padded shard length L up to
#: a multiple of this under bucketing, so a small insert never changes the
#: padded scan kernel's static shape (partitioned children use the larger
#: geomesa.partition.shard.bucket, set explicitly per table).
COMPACT_SHARD_BUCKET = SystemProperty("geomesa.compact.shard.bucket", "8192")

#: Capacity of the shared compiled-kernel LRU registry (entries). Evicts
#: least-recently-used kernels one at a time — never clear-on-overflow.
#: Raised 256 -> 512 with the query-axis batch kernels (their padded
#: member axis multiplies the key space ~5x for batch sites; BENCH_r10
#: measured 615 recompiles / 359 evictions across the full bench at 256
#: — docs/PERF.md "Registry pressure").
KERNEL_CACHE_SIZE = SystemProperty("geomesa.kernel.cache.size", "512")

#: Directory for JAX's persistent compilation cache; when set, compiled
#: XLA executables survive process restarts (warm starts skip compiles).
COMPILE_CACHE_DIR = SystemProperty("geomesa.compile.cache.dir", None)

#: Double-buffered partition pipeline: overlap the NEXT partition's host
#: slab-gather/column assembly with the CURRENT partition's device
#: execution (one prefetch thread, one in-flight partition; compile and
#: dispatch stay on the query thread).
PIPELINE_PREFETCH = SystemProperty("geomesa.pipeline.prefetch", "true")

#: Use the scatter-free MXU density kernel on z-indexed tables.
DENSITY_MXU = SystemProperty("geomesa.density.mxu", "true")

#: Use the Pallas grouped one-hot-matmul density kernel (preferred over
#: the XLA einsum pair kernel when the backend supports pallas; measured
#: ~5x over scatter and ~6x over the einsum at the bench shape).
DENSITY_PALLAS = SystemProperty("geomesa.density.pallas", "true")

#: Pallas density bails out (to the einsum/scatter fallbacks) when the
#: pair expansion would duplicate rows beyond this factor.
DENSITY_PALLAS_MAX_DUP = SystemProperty("geomesa.density.pallas.max.dup", "4.0")

#: Split the padded-path density scatter into this many independent
#: pieces (measured ~10x on v5e); <=1 disables.
SCATTER_SPLIT = SystemProperty("geomesa.scatter.split", "8")

#: MXU density grid tile shape (cells).
MXU_TILE_X = SystemProperty("geomesa.mxu.tile.x", "64")
MXU_TILE_Y = SystemProperty("geomesa.mxu.tile.y", "32")

#: Bin-space (2-D mesh) streaming: lax.scan chunk count per device along
#: the time-bin axis (1 = no streaming; >1 trades HBM for steps).
BIN_STREAM_CHUNKS = SystemProperty("geomesa.bin.stream.chunks", "1")

#: Devices for the sharded partitioned scan (docs/SCALE.md): pruned
#: partitions fan out round-robin over this many local devices, with
#: per-device partial aggregates merged in a fixed deterministic order.
#: Unset/"all" = every local device; an integer caps the count;
#: 0/1/"off" disables (single-device streaming, the pre-sharding path).
#: Ignored when an explicit GSPMD mesh is configured on the dataset (the
#: mesh shards WITHIN a partition instead) and while a serving pool with
#: more than one executor is running (the pool owns the devices — one
#: dispatch thread per device).
MESH_DEVICES = SystemProperty("geomesa.mesh.devices", None)

#: Devices cordoned out of scheduling, comma-separated ids (e.g. "3" or
#: "2,5"): a cordoned device is excluded from the sharded scan's fan-out
#: and from serving-pool slot pinning WITHOUT a restart — the config-knob
#: face of parallel/health.py's explicit cordon()/uncordon() API (the CLI
#: ``devices cordon`` and the sidecar ``cordon-device`` action mutate the
#: in-process registry instead). Unset = nothing cordoned.
MESH_CORDON = SystemProperty("geomesa.mesh.cordon", None)

#: Consecutive dispatch failures that BREAK a device (open its
#: ``device:<id>`` circuit breaker, removing it from scheduling until the
#: reset window's half-open trial succeeds). Fed by sharded-scan dispatch
#: failures and latency-outlier streaks (parallel/health.py).
DEVICE_BREAKER_THRESHOLD = SystemProperty(
    "geomesa.device.breaker.threshold", "3"
)

#: Broken-device reset window (ms): after it, ONE trial dispatch is
#: admitted — success restores the device to scheduling, failure re-opens.
DEVICE_BREAKER_RESET_MS = SystemProperty(
    "geomesa.device.breaker.reset.ms", "30000"
)

#: Latency-outlier factor: a per-device partition sync slower than
#: factor x the trailing mesh-wide median (AND over the floor below)
#: counts one outlier; geomesa.device.breaker.threshold consecutive
#: outliers trip the device's breaker. "0" disables outlier detection.
DEVICE_LATENCY_OUTLIER = SystemProperty(
    "geomesa.device.latency.outlier", "20"
)

#: Absolute floor (ms) below which a sync is never an outlier — keeps
#: microsecond-scale jitter on tiny partitions from breaking a healthy
#: device (outliers are a straggler-lane signal, not a noise detector).
DEVICE_LATENCY_FLOOR_MS = SystemProperty(
    "geomesa.device.latency.floor.ms", "250"
)

#: Extend the partition prefetch pipeline's overlap to the device upload
#: on the SHARDED scan: the prefetch thread device_puts partition i+1's
#: staged host arrays onto its assigned device while device i executes.
#: Safe under the one-jit-thread-per-device discipline because device_put
#: is a pure transfer — it never traces or compiles (the PR 1 wedge was
#: jit compilation on foreign threads) — and results are bit-identical
#: with the overlap off (the upload populates the same device cache, same
#: sharding singleton, the query thread would have populated itself).
PIPELINE_DEVICE_PUT = SystemProperty("geomesa.pipeline.device-put", "true")

#: Bucket count for hash-bucketed per-key sampling (int keys and
#: dictionary vocabularies beyond the exact per-code kernel's gate).
#: Power of two; 0 routes such keys to the host's exact per-key counter.
SAMPLE_HASH_BUCKETS = SystemProperty("geomesa.sample.hash-buckets", "64")

#: Sorted-query top-k pushdown: max Query.max_features eligible for the
#: device threshold-select (binary-searched count reductions, no device
#: sort); larger limits gather the full result and sort on host.
TOPK_MAX = SystemProperty("geomesa.topk.max", "100000")

# ---------------------------------------------------------------------------
# Spatial aggregate cache (cache/; docs/CACHE.md). Memoizes aggregate results
# (density grids, stats sketches, counts) per SFC cell so repeated and
# overlapping queries pay only for the newly exposed residual region.
# ---------------------------------------------------------------------------

#: Master switch for the aggregate result cache (default off).
CACHE_ENABLED = SystemProperty("geomesa.cache.enabled", "false")

#: Memory budget for cached aggregates (bytes), applied PER FEATURE STORE
#: (one budget per schema — a dataset with N schemas can hold up to N x
#: this); size-aware LRU eviction keeps each store under it.
CACHE_BUDGET_BYTES = SystemProperty("geomesa.cache.budget.bytes", str(64 << 20))

#: Partial-cover decomposition targets at most this many grid cells per
#: axis over the query bbox (cell level adapts to the bbox span).
CACHE_CELLS_PER_AXIS = SystemProperty("geomesa.cache.cells-per-axis", "8")

#: Finest SFC cell level the decomposition may choose (cells are the
#: 2^level x 2^level lon/lat grid aligned with the z2 curve blocks).
CACHE_MAX_LEVEL = SystemProperty("geomesa.cache.max.level", "12")

#: Hard cap on interior cells per decomposed query; beyond it the query
#: falls back to whole-result caching only.
CACHE_MAX_CELLS = SystemProperty("geomesa.cache.max.cells", "256")

#: Hierarchical pre-aggregation (cache/hierarchy.py; docs/CACHE.md): a
#: level-k cell assembles from its four level-(k+1) children (counts add,
#: unweighted grids downsample-add, exact sketches merge — all in the
#: fixed SW/SE/NW/NE child order, so assembly is bit-identical to a fresh
#: scan), and completed sibling quads roll up bottom-up on put. Makes a
#: zoom-out over a warm region cost O(visible cells), never O(data).
CACHE_HIERARCHY = SystemProperty("geomesa.cache.hierarchy", "true")

#: How many levels DOWN an on-miss assembly may recurse looking for
#: cached children (1 = direct children only).
CACHE_HIERARCHY_DEPTH = SystemProperty("geomesa.cache.hierarchy.depth", "2")

#: Polygon-region decomposition (cache/cells.py; docs/CACHE.md): a query
#: whose one spatial conjunct is INTERSECTS/WITHIN of a polygon literal
#: splits into interior cells (served from the cache/hierarchy — they
#: share cell keys with bbox queries) plus boundary cells scanned exactly
#: under the polygon predicate. Off = polygon queries are whole-result
#: cached only.
CACHE_POLYGON = SystemProperty("geomesa.cache.polygon", "true")

# ---------------------------------------------------------------------------
# TPU-native spatial joins (planning/join_exec.py; docs/JOIN.md): SFC-cell
# co-partitioned build/probe with a bucketed pairwise kernel.
# ---------------------------------------------------------------------------

#: Pairwise-kernel tile edge: per-cell build/probe blocks chunk into tiles
#: of at most this many rows per side (pow2-bucketed below it), so skewed
#: cells split into more tiles instead of inflating every cell's padding.
JOIN_TILE = SystemProperty("geomesa.join.tile", "64")

#: Finest SFC cell level the join co-partition may choose (cells are the
#: same 2^level x 2^level lon/lat grid the aggregate cache decomposes to).
JOIN_MAX_LEVEL = SystemProperty("geomesa.join.max.level", "12")

#: Matched-pair ColumnBatch chunk size for the streaming join result.
JOIN_BATCH_ROWS = SystemProperty("geomesa.join.batch.rows", "65536")

#: Adaptive per-cell strategy selection (docs/JOIN.md §5): classify each
#: joint cell from its build/probe counts and route it to the cheapest
#: executor — dense balanced cells keep the bucketed pairwise kernel,
#: sparse cells take the flat brute-force path (no tile padding), skewed
#: cells split along the longer side with their own narrow buckets. OFF
#: forces the single-strategy path everywhere (the A/B switch; results
#: are bit-identical either way — only dispatch shapes change).
JOIN_ADAPTIVE = SystemProperty("geomesa.join.adaptive", "true")

#: A joint cell whose n_build * n_probe candidate product is at most this
#: goes to the flat brute-force strategy (gathered 1-D pair list, no
#: [B, P] tile padding).
JOIN_ADAPTIVE_BRUTE_PAIRS = SystemProperty(
    "geomesa.join.adaptive.brute.pairs", "256")

#: A joint cell whose longer side holds at least this many times the
#: shorter side's rows is SKEWED: its tiles dispatch in a separate
#: section whose short-side bucket stays narrow instead of inflating to
#: the dense cells' padding.
JOIN_ADAPTIVE_SKEW_RATIO = SystemProperty(
    "geomesa.join.adaptive.skew.ratio", "8")

#: Window-pushdown join side scans (docs/JOIN.md §8, docs/LAKE.md): for
#: ``join_count`` with the probe side on a partitioned store, stream the
#: probe side per cell group through footer-pruned ranged reads instead
#: of materializing the whole filtered side on the host.
JOIN_PUSHDOWN = SystemProperty("geomesa.join.pushdown", "true")

#: Cell-group size for the pushdown side scan: each probe-side ranged
#: read covers at most this many occupied build cells. Smaller groups
#: bound per-chunk host memory; larger groups amortize the footer pass
#: and avoid re-decoding row groups that straddle chunk boundaries
#: (adjacent chunks' inflated windows overlap by the reach).
JOIN_PUSHDOWN_CELLS = SystemProperty("geomesa.join.pushdown.cells", "256")

# ---------------------------------------------------------------------------
# Resilience layer (resilience.py; docs/RESILIENCE.md). Retry defaults track
# the reference's tablet-server client retry posture; the breaker fences a
# dead sidecar so calls fail fast instead of paying the timeout each time.
# ---------------------------------------------------------------------------

#: Per-call timeout for sidecar Flight RPCs (FlightCallOptions.timeout);
#: a live query deadline tightens it further. None = no per-call timeout.
SIDECAR_TIMEOUT = SystemProperty("geomesa.sidecar.timeout", "30 s")

#: Total tries per retryable remote call (1 disables retry).
RETRY_ATTEMPTS = SystemProperty("geomesa.retry.attempts", "3")

#: Backoff base delay (ms); retry i waits base * 2^(i-1), capped below.
RETRY_BASE_MS = SystemProperty("geomesa.retry.base.ms", "50")

#: Backoff delay cap (ms).
RETRY_MAX_MS = SystemProperty("geomesa.retry.max.ms", "5000")

#: Jitter fraction [0, 1): each delay is scaled by 1 - jitter * U(0, 1)
#: from the policy's seeded RNG (deterministic under a fixed seed).
RETRY_JITTER = SystemProperty("geomesa.retry.jitter", "0.2")

#: Consecutive failures that open a circuit breaker.
BREAKER_THRESHOLD = SystemProperty("geomesa.breaker.threshold", "5")

#: Open -> half-open reset window (ms).
BREAKER_RESET_MS = SystemProperty("geomesa.breaker.reset.ms", "30000")

#: Allow degraded (partial) aggregates: a failing partition is skipped and
#: recorded instead of failing the whole scan. Off = strict (raise); the
#: ``resilience.allow_partial()`` scope enables it per-operation.
SCAN_PARTIAL = SystemProperty("geomesa.scan.partial", "false")

#: Master switch for the deterministic fault-injection registry
#: (resilience.inject_faults refuses to install without it). Fault points
#: are a single no-op check when no injector is installed.
FAULT_INJECTION = SystemProperty("geomesa.fault.injection", "false")

#: Extra gather slots for boundary ties in the device top-k selection;
#: selections whose tie group overflows k + slack fall back to the host.
TOPK_TIE_SLACK = SystemProperty("geomesa.topk.tie-slack", "4096")

# ---------------------------------------------------------------------------
# Observability (tracing.py, obs.py; docs/OBSERVABILITY.md). Tracing is
# off-by-default-cheap: with geomesa.trace.enabled false the span API is a
# no-op (a context-var read returning a shared singleton), asserted by the
# bench smoke trace_overhead_pct gate.
# ---------------------------------------------------------------------------

#: Master switch for query span-tree tracing (default off).
TRACE_ENABLED = SystemProperty("geomesa.trace.enabled", "false")

#: Slow-query threshold: a completed root span slower than this writes its
#: full span tree as a JSONL record through the audit appender (and into
#: the in-memory slow-trace ring served by /debug/queries). Unset = never.
TRACE_SLOW_MS = SystemProperty("geomesa.trace.slow.ms", None)

#: Per-query span budget: spans beyond this are dropped (counted on the
#: root as ``dropped``) so a decomposed 256-cell query cannot balloon its
#: trace unboundedly.
TRACE_MAX_SPANS = SystemProperty("geomesa.trace.max.spans", "512")

#: Mirror spans into jax.profiler.TraceAnnotation scopes so they appear in
#: TensorBoard/Perfetto device profiles alongside XLA ops (default off).
TRACE_JAX_PROFILER = SystemProperty("geomesa.trace.jax.profiler", "false")

#: Per-site recompile alert: a jit site that pays more than this many
#: fresh traces within ONE query trips the ``kernel.recompile.alert``
#: gauge (warm-path regression signal; docs/PERF.md).
KERNEL_ALERT_THRESHOLD = SystemProperty("geomesa.kernel.alert.threshold", "3")

# ---------------------------------------------------------------------------
# Trace export + tail-based sampling (tracing_export.py;
# docs/OBSERVABILITY.md). Export engages when either sink below is
# configured; the sampling decision is made at trace COMPLETION (tail-based):
# slow/errored/degraded/shed/recompile-carrying traces are always kept,
# healthy traces sample at the seeded-deterministic rate.
# ---------------------------------------------------------------------------

#: HTTP OTLP sink: POST finished span batches (OTLP/JSON shape) here.
#: Retried via resilience.RetryPolicy and fenced by the ``trace.otlp``
#: circuit breaker. Unset = no HTTP sink.
TRACE_OTLP_ENDPOINT = SystemProperty("geomesa.trace.otlp.endpoint", None)

#: File sink: append one OTLP-shaped JSON span batch per line (JSONL) —
#: the air-gapped/CI sink. Unset = no file sink.
TRACE_EXPORT_PATH = SystemProperty("geomesa.trace.export.path", None)

#: Tail-sampling keep rate for HEALTHY traces in [0, 1]. Decided
#: deterministically from (seed, trace_id), so a given trace id is kept or
#: dropped identically run to run. Always-keep classes (slow, errored,
#: degraded, shed, recompile-carrying) ignore the rate.
TRACE_SAMPLE_RATE = SystemProperty("geomesa.trace.sample.rate", "1.0")

#: Seed for the deterministic sampling hash above.
TRACE_SAMPLE_SEED = SystemProperty("geomesa.trace.sample.seed", "0")

#: Bounded export queue depth between trace completion and the background
#: flusher. Overflow DROPS the trace (counted in ``trace.export.dropped``)
#: — the query/dispatch threads never block on export.
TRACE_EXPORT_QUEUE = SystemProperty("geomesa.trace.export.queue", "1024")

#: Max traces converted + written per flusher pass (one OTLP batch).
TRACE_EXPORT_BATCH = SystemProperty("geomesa.trace.export.batch", "64")

# ---------------------------------------------------------------------------
# Per-device utilization accounting (utilization.py; docs/OBSERVABILITY.md).
# ---------------------------------------------------------------------------

#: Trailing window (seconds) over which the ``device.busy.<id>`` and
#: ``serving.slot.occupancy.<slot>`` gauges compute their busy fraction.
DEVICE_BUSY_WINDOW = SystemProperty("geomesa.device.busy.window", "60")

# ---------------------------------------------------------------------------
# SLO burn-rate monitor (slo.py; docs/OBSERVABILITY.md). Targets are
# per-op p99 latencies named ``geomesa.slo.<op>.p99.ms`` (thread-local
# override or env, e.g. GEOMESA_SLO_COUNT_P99_MS=50), evaluated over the
# existing ``trace.<op>`` histograms with fast/slow dual-window burn rates.
# ---------------------------------------------------------------------------

#: Fast burn window (seconds): /healthz degrades when this window burns
#: past geomesa.slo.burn.threshold.
SLO_WINDOW_FAST_S = SystemProperty("geomesa.slo.window.fast.s", "300")

#: Slow burn window (seconds): the page-worthy confirmation window.
SLO_WINDOW_SLOW_S = SystemProperty("geomesa.slo.window.slow.s", "3600")

#: Fast-window burn rate past which /healthz reports degraded (the classic
#: 14.4x = "a 99% monthly budget gone in ~2 days at this rate" threshold).
SLO_BURN_THRESHOLD = SystemProperty("geomesa.slo.burn.threshold", "14.4")

#: Per-op SLO target prefix/suffix: ``geomesa.slo.<op>.p99.ms`` (op is a
#: root-span name: count, density, density_curve, ... — underscores, no
#: dots). Resolved via :func:`slo_targets`.
SLO_PREFIX = "geomesa.slo."
SLO_SUFFIX = ".p99.ms"


def slo_targets() -> Dict[str, float]:
    """Effective per-op p99 targets in ms: ``{op: target_ms}``. Thread-local
    overrides first (``geomesa.slo.<op>.p99.ms``), then env
    (``GEOMESA_SLO_<OP>_P99_MS``); an unparseable value is ignored."""
    out: Dict[str, float] = {}
    env_pre, env_suf = "GEOMESA_SLO_", "_P99_MS"
    for k, v in os.environ.items():
        if k.startswith(env_pre) and k.endswith(env_suf) \
                and len(k) > len(env_pre) + len(env_suf):
            try:
                out[k[len(env_pre):-len(env_suf)].lower()] = float(v)
            except ValueError:
                pass
    for k, v in _overrides().items():
        if k.startswith(SLO_PREFIX) and k.endswith(SLO_SUFFIX) \
                and len(k) > len(SLO_PREFIX) + len(SLO_SUFFIX):
            try:
                out[k[len(SLO_PREFIX):-len(SLO_SUFFIX)]] = float(v)
            except ValueError:
                pass
    return out

# ---------------------------------------------------------------------------
# Serving scheduler (serving/scheduler.py; docs/SERVING.md). The sidecar's
# single dispatch thread sits behind a bounded admission queue with
# deadline-aware ordering, per-user fair share, and cross-query fusion of
# compatible aggregates into one device pass.
# ---------------------------------------------------------------------------

#: Bounded admission queue depth: requests beyond it are rejected at
#: submission with a typed [GM-OVERLOADED] error (load shedding before any
#: planning or device work).
SERVING_QUEUE_DEPTH = SystemProperty("geomesa.serving.queue.depth", "256")

#: Cross-query fusion: compatible queued aggregates (same schema, predicate
#: text, auths, and op shape — hence the same version-stable kernel token)
#: coalesce into one micro-batch sharing a single device pass. Only
#: already-queued work fuses; fusion never delays dispatch to grow a batch.
SERVING_FUSION = SystemProperty("geomesa.serving.fusion", "true")

#: Max members per fused micro-batch.
SERVING_FUSION_MAX = SystemProperty("geomesa.serving.fusion.max", "16")

#: Query-axis (distinct-literal) fusion: requests whose ECQL differs ONLY
#: in BBOX / temporal literals share a structural fuse key and execute as
#: one batched device pass with the literals as kernel data
#: (docs/SERVING.md "Query-axis batching"). Off = only identical-key
#: repeats (and density_curve tile crops) fuse, the pre-megakernel rule.
SERVING_FUSION_DISTINCT = SystemProperty(
    "geomesa.serving.fusion.distinct", "true"
)

#: Pool-aware fusion placement: a fuse-bearing query prefers the executor
#: slot whose device most recently scanned its schema's columns (they are
#: still resident there), deferring briefly to that slot when it is idle
#: instead of binding to whichever slot drains the queue first. The
#: decision is surfaced on the fused group's trace span.
SERVING_PLACEMENT = SystemProperty("geomesa.serving.placement", "true")

#: How long (ms) a placement-deferred ticket is reserved for its preferred
#: slot before any slot may take it (starvation backstop).
SERVING_PLACEMENT_GRACE_MS = SystemProperty(
    "geomesa.serving.placement.grace.ms", "50"
)

#: Per-user fair share: the dispatcher serves the pending user with the
#: least attained service time instead of global FIFO, so one user's burst
#: cannot starve another's interactive queries. Off = strict FIFO.
SERVING_FAIR_SHARE = SystemProperty("geomesa.serving.fair-share", "true")

#: Admission-time estimate shedding: reject a request whose deadline budget
#: is smaller than the estimated queue wait (EWMA service time x pending
#: depth) with a typed [GM-SHED] error — before any device work.
SERVING_SHED_ESTIMATE = SystemProperty("geomesa.serving.shed.estimate", "true")

#: Dispatch-thread pool width for the serving scheduler: N executors,
#: one dispatch thread per executor slot (slot i pins jax device
#: i % device_count), each keeping the one-jit-thread-per-device
#: discipline. Admission, deadline shedding, fair share, and fusion stay
#: GLOBAL; a fusion group binds to one executor so batch results stay
#: bit-identical. "all" = one per local device; default 1 = the single
#: dispatch thread (pre-pool behavior, byte-for-byte).
SERVING_EXECUTORS = SystemProperty("geomesa.serving.executors", "1")

#: Identity attached to queries for fair-share accounting and the
#: /debug/queries per-user rollups (the sidecar client forwards it as the
#: x-geomesa-user Flight header; unset = "anonymous").
USER = SystemProperty("geomesa.user", None)

# ---------------------------------------------------------------------------
# Replica fleet (fleet/; docs/RESILIENCE.md §7). A front-end router plus N
# replica sidecars over one shared storage root: consistent-hash CELL
# affinity routing, per-replica breakers + failover, and mutation-epoch
# propagation so no replica ever serves a pre-mutation aggregate.
# ---------------------------------------------------------------------------

#: This process's replica identity in a fleet (stamped into every response
#: as the x-geomesa-replica-id header; "replica:<id>" names its breaker on
#: routers). Unset = not a fleet replica.
FLEET_REPLICA_ID = SystemProperty("geomesa.fleet.replica.id", None)

#: Shared storage root the fleet's replicas load from / persist to
#: (GeoDataset.save/load layout). A replica whose known fleet epoch for a
#: schema trails an incoming request's epoch refreshes that schema from
#: here BEFORE serving; a replica applying a router-stamped write saves
#: here before acknowledging. Unset = no cross-replica refresh.
FLEET_ROOT = SystemProperty("geomesa.fleet.root", None)

#: SFC cell level the router derives affinity keys at: a query's bbox
#: center quantizes to one 2^level x 2^level cell, and the rendezvous
#: ring hashes (schema, cell prefix) to pick the owner replica — nearby
#: viewports land on the same replica, keeping its cell cache hot.
FLEET_ROUTING_LEVEL = SystemProperty("geomesa.fleet.routing.level", "3")

#: Scatter decomposable MERGEABLE aggregates across replicas by cell
#: ownership (each owner group scans only its cells; partials compose
#: exactly — counts add, unweighted grids add, exact-merge sketches
#: merge, curve chunks slot by block). Off = every query routes whole
#: to one replica.
FLEET_SCATTER = SystemProperty("geomesa.fleet.scatter", "true")

# -- standing queries (geomesa_tpu/subscribe/; docs/STANDING.md) -----------

#: Master switch for the subscription subsystem: off, registrations raise
#: and mutation hooks are no-ops (zero ingest-path overhead).
SUBSCRIBE_ENABLED = SystemProperty("geomesa.subscribe.enabled", "true")

#: Hard-assert every incremental (delta-applied) standing result against a
#: from-scratch re-scan at the same epoch after EVERY settle — the
#: bit-identity contract, paid as a full re-scan per update. On in tests
#: and the standing-smoke CI gate; off in production serving.
SUBSCRIBE_VERIFY = SystemProperty("geomesa.subscribe.verify", "false")

#: Maximum DISTINCT standing groups per schema (fused subscribers share a
#: group, so 10k watchers of one hot viewport cost one slot). Registration
#: past the cap answers a typed [GM-SUB-LIMIT] error.
SUBSCRIBE_MAX_GROUPS = SystemProperty("geomesa.subscribe.max.groups", "256")

#: Update-ring depth per group: how many per-batch update records a slow
#: poller may lag before the ring truncates (a truncated poller sees a
#: version gap and should re-anchor on the carried full result).
SUBSCRIBE_UPDATES_RING = SystemProperty("geomesa.subscribe.updates.ring",
                                        "256")

#: Quadtree-rollup pyramid depth: the leaf grid is 2^levels x 2^levels
#: and downsample-adds up to the 1x1 root (cache/hierarchy.downsample,
#: fixed SW/SE/NW/NE order).
SUBSCRIBE_PYRAMID_LEVELS = SystemProperty("geomesa.subscribe.pyramid.levels",
                                          "5")

#: Concurrent owner-group dispatches per scattered query (the router's
#: fan-out thread bound). "1" serializes the groups (still scattered,
#: no parallel wall-clock win).
FLEET_SCATTER_FANOUT = SystemProperty("geomesa.fleet.scatter.fanout", "8")

#: Consecutive SUCCESSFUL probes after which the router automatically
#: un-cordons a replica it cordoned (router-side cordons only — the
#: geomesa.fleet.cordon config list stays operator-owned). "0" disables
#: auto-uncordon (the pre-PR-15 manual-exit behavior).
FLEET_UNCORDON_PROBES = SystemProperty("geomesa.fleet.uncordon.probes", "3")

#: Hottest cache entries a draining replica pushes to the new ring owner
#: during a warm-handoff drain (per schema, LRU-hottest first).
FLEET_HANDOFF_ENTRIES = SystemProperty("geomesa.fleet.handoff.entries",
                                       "256")

#: Fleet-level admission bound on the router: concurrent in-flight routed
#: queries beyond this are rejected typed [GM-OVERLOADED] before any RPC
#: (the same _UserLedger-backed policy the serving scheduler runs).
FLEET_MAX_INFLIGHT = SystemProperty("geomesa.fleet.max.inflight", "256")

#: Consecutive connect/dispatch failures that BREAK a replica (open its
#: ``replica:<id>`` breaker, removing it from routing until the half-open
#: trial succeeds). Fed by routed-call failures, failed /healthz-style
#: probes, and latency-outlier streaks.
FLEET_BREAKER_THRESHOLD = SystemProperty("geomesa.fleet.breaker.threshold", "3")

#: Broken-replica reset window (ms): after it, ONE trial call is admitted.
FLEET_BREAKER_RESET_MS = SystemProperty(
    "geomesa.fleet.breaker.reset.ms", "30000"
)

#: Latency-outlier factor for routed calls: a replica's call slower than
#: factor x the trailing fleet-wide median for the same op (and over the
#: floor below) counts one outlier; a threshold-long consecutive streak
#: trips the replica's breaker. "0" disables.
FLEET_LATENCY_OUTLIER = SystemProperty("geomesa.fleet.latency.outlier", "20")

#: Absolute floor (ms) below which a routed call is never an outlier.
FLEET_LATENCY_FLOOR_MS = SystemProperty(
    "geomesa.fleet.latency.floor.ms", "250"
)

#: Replicas cordoned out of routing, comma-separated ids — the config-knob
#: face of FleetRouter.cordon()/uncordon() (explicit API on the router).
FLEET_CORDON = SystemProperty("geomesa.fleet.cordon", None)

# ---------------------------------------------------------------------------
# Fleet observability plane (fleet/obs.py; docs/OBSERVABILITY.md §9):
# metrics federation, cross-replica trace stitching, cell-heat telemetry,
# and the replica anomaly watchdog. All pull/async: nothing here runs on
# the routed-query path.
# ---------------------------------------------------------------------------

#: Federation snapshot TTL (ms): a fleet /metrics, /healthz, or /debug/heat
#: read within this window of the last sweep reuses the cached merge
#: instead of re-pulling every replica. "0" re-pulls on every read.
FLEET_OBS_TTL_MS = SystemProperty("geomesa.fleet.obs.ttl.ms", "2000")

#: Per-replica metrics-export / trace-fetch pull timeout (seconds).
FLEET_OBS_TIMEOUT_S = SystemProperty("geomesa.fleet.obs.timeout.s", "5")

#: Master switch for the async trace stitcher: with it false, scattered
#: queries export their router-local trace only (pre-PR-19 behavior).
FLEET_STITCH = SystemProperty("geomesa.fleet.stitch", "true")

#: Completed scattered queries the stitcher queues for assembly; overflow
#: drops the oldest pending id (counted fleet.trace.stitch.failed) — the
#: same non-blocking contract as the trace export queue.
FLEET_STITCH_QUEUE = SystemProperty("geomesa.fleet.stitch.queue", "256")

#: Settle delay (ms) between a scattered query finishing and its stitch
#: pull: replica root spans must FINISH (late children re-finish the
#: trace) before trace-fetch can see their subtree.
FLEET_STITCH_DELAY_MS = SystemProperty("geomesa.fleet.stitch.delay.ms",
                                       "100")

#: Anomaly-watchdog flag factor: a replica whose recent per-op latency
#: median is >= factor x the fleet median for that op (both over >= 8
#: samples) is flagged in fleet.anomaly.<id> and the /debug/fleet advice
#: row. Observation only — no cordon. "0" disables the watchdog.
FLEET_ANOMALY_FACTOR = SystemProperty("geomesa.fleet.anomaly.factor", "4")

#: Distinct (schema, cell) rows the process heat table retains (coldest
#: rows evict first). "0" disables heat recording.
HEAT_CELLS_MAX = SystemProperty("geomesa.heat.cells", "4096")

#: Hottest rows a heat snapshot ships per schema (metrics-export payload
#: and /debug/heat bound).
HEAT_TOP = SystemProperty("geomesa.heat.top", "256")

#: Finished traces retained BY ID for /debug/queries?trace= and the
#: trace-fetch action (a bounded ring; the slow-trace ring is separate).
TRACE_RETAIN = SystemProperty("geomesa.trace.retain", "256")

#: Cross-chunk row-group residency budget (MiB) for window-pushdown join
#: side scans (docs/JOIN.md §11): decoded column chunks of row groups
#: straddling adjacent pushdown chunks are kept across chunk scans so the
#: boundary groups stop decoding twice. "0" disables the cache.
JOIN_PUSHDOWN_RESIDENCY_MB = SystemProperty(
    "geomesa.join.pushdown.residency.mb", "64"
)

# ---------------------------------------------------------------------------
# Durable mutation journal (fs/journal.py; docs/RESILIENCE.md §8): per-root
# crc-framed write-ahead log with group commit. With it attached, an acked
# mutation is ON DISK before the call returns; load() replays records past
# each schema's checkpointed position, and save() checkpoints then truncates
# the journal segment-wise.
# ---------------------------------------------------------------------------

#: Master switch: with it false, attach_journal() is a no-op and every root
#: keeps the pre-journal semantics (acked mutations live until the next
#: explicit save()).
JOURNAL_ENABLED = SystemProperty("geomesa.journal.enabled", "true")

#: Group-commit window (ms): after the first pending append wakes the
#: committer, it waits this long for concurrent appenders to join the
#: batch, then writes + fsyncs ONCE for all of them. "0" commits each
#: drain immediately — concurrent writers still batch naturally because
#: appends arriving during an fsync join the next drain (commit
#: pipelining); positive values trade single-writer append latency for
#: wider groups under concurrency.
JOURNAL_GROUP_MS = SystemProperty("geomesa.journal.group.ms", "2")

#: Segment roll threshold (bytes): the active segment closes and a new one
#: starts past this size, bounding both the torn-tail blast radius and the
#: granularity at which checkpoints reclaim space.
JOURNAL_SEGMENT_BYTES = SystemProperty(
    "geomesa.journal.segment.bytes", str(8 << 20)
)

#: Fleet-replica checkpoint cadence: a replica serving stamped writes from
#: a shared root runs a full ``save()`` (checkpoint + journal truncation)
#: every this-many commits — between checkpoints a one-row insert costs one
#: journal append + marker advance, never a schema snapshot rewrite.
JOURNAL_CHECKPOINT_WRITES = SystemProperty(
    "geomesa.journal.checkpoint.writes", "256"
)

#: Per-user fair-share weight prefix: ``geomesa.serving.user.weight.<user>``
#: scales a user's attained-service debt (the dispatcher picks the user
#: minimizing service_s / weight), so weight 4 earns ~4x the service of
#: weight 1 under contention. Resolved on the SUBMITTING thread at each
#: submit/admit and captured into the user's ledger (thread-local
#: override first, then env — non-alphanumeric identity chars map to
#: ``_`` in the env name), default 1.0; values <= 0 are treated as 1.0.
#: Surfaced in the /debug/queries per-user rollups.
USER_WEIGHT_PREFIX = "geomesa.serving.user.weight."


def user_weight(user: str) -> float:
    """Effective fair-share weight for ``user`` (see USER_WEIGHT_PREFIX)."""
    name = USER_WEIGHT_PREFIX + user
    v = _overrides().get(name)
    if v is None:
        env = "".join(
            ch if ch.isalnum() else "_" for ch in name
        ).upper()
        v = os.environ.get(env)
    if v is None:
        return 1.0
    try:
        w = float(v)
    except ValueError:
        return 1.0
    return w if w > 0 else 1.0
