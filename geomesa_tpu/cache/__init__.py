"""Spatial aggregate cache (docs/CACHE.md).

SFC-cell result caching with epoch invalidation, partial-cover reuse, a
hierarchical pre-aggregation quadtree (coarse cells assemble from cached
children — zoom-out costs O(visible cells), not O(data)), and polygon-
region decomposition (interior cells cache-served, boundary cells scanned
exactly): repeated and overlapping pushdown aggregates (density grids,
stats sketches, counts, curve-block grids) are served from memoized
per-cell partials, so repeat latency is independent of dataset size. Off
by default; enable with ``geomesa.cache.enabled=true``
(GEOMESA_CACHE_ENABLED=true).
"""

from geomesa_tpu.cache import hierarchy
from geomesa_tpu.cache.cells import (
    Decomposition, RegionDecomposition, decompose, decompose_region,
    split_bbox_conjunct, split_region_conjunct,
)
from geomesa_tpu.cache.service import EXACT_MERGE_KINDS, AggregateCache
from geomesa_tpu.cache.store import CacheStore

__all__ = [
    "AggregateCache", "CacheStore", "Decomposition", "RegionDecomposition",
    "decompose", "decompose_region", "split_bbox_conjunct",
    "split_region_conjunct", "hierarchy", "EXACT_MERGE_KINDS",
]
