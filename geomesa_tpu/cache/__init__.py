"""Spatial aggregate cache (docs/CACHE.md).

SFC-cell result caching with epoch invalidation and partial-cover reuse:
repeated and overlapping pushdown aggregates (density grids, stats sketches,
counts) are served from memoized per-cell partials, so repeat latency is
independent of dataset size. Off by default; enable with
``geomesa.cache.enabled=true`` (GEOMESA_CACHE_ENABLED=true).
"""

from geomesa_tpu.cache.cells import Decomposition, decompose, split_bbox_conjunct
from geomesa_tpu.cache.service import EXACT_MERGE_KINDS, AggregateCache
from geomesa_tpu.cache.store import CacheStore

__all__ = [
    "AggregateCache", "CacheStore", "Decomposition", "decompose",
    "split_bbox_conjunct", "EXACT_MERGE_KINDS",
]
