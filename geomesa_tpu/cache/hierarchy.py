"""Hierarchical pre-aggregation over the SFC cell quadtree (docs/CACHE.md).

GeoBlocks' core idea (PAPERS.md): every level-k cell is the disjoint union
of its four level-(k+1) children, so a coarse aggregate is the merge of the
children's aggregates — counts add, unweighted f32 density grids add (and
curve-block grids downsample-add) bit-exactly, exact-algebra sketches
``Stat.merge``. The flat cache already stores per-cell partials; this
module turns them into a hierarchy two ways:

* **lazily on miss** (:func:`assemble`): a coarse cell the cache has never
  seen tries its children (recursively, ``geomesa.cache.hierarchy.depth``
  levels down) before falling back to a residual scan. A continent-scale
  zoom-out over a region warmed by fine-level pans/tiles then costs
  O(visible cells) lookups and ZERO device dispatches, never O(data);
* **bottom-up on put** (:func:`rollup`): storing a cell whose three
  siblings are already resident writes the parent too (and recurses
  upward), so the coarse levels are pre-merged by the time the zoom-out
  arrives.

Merge order is FIXED — children always combine in SW, SE, NW, NE order
(x-fastest from the southwest: ``(2ix, 2iy), (2ix+1, 2iy), (2ix, 2iy+1),
(2ix+1, 2iy+1)``) — so every assembly of the same subtree reproduces the
same bytes. For the aggregates admitted to decomposition this is belt and
suspenders (their merges are order-independent exact integer/extremum
algebra), but the fixed order is the documented contract the curve-grid
``downsample`` below and any future merge rely on.

Invalidation rides the existing epoch mechanism: hierarchy entries live in
the same :class:`~geomesa_tpu.cache.store.CacheStore` under the same
(uid, epoch) scope as the flat cells they were merged from, so any
mutation drops every subtree at once — a pre-merged parent can never
outlive the children it summarizes.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from geomesa_tpu import config, metrics

Cell = Tuple[int, int]

#: THE child-merge order: SW, SE, NW, NE (x-fastest from the southwest)
CHILD_ORDER = ((0, 0), (1, 0), (0, 1), (1, 1))


def enabled() -> bool:
    return bool(config.CACHE_HIERARCHY.to_bool())


def depth() -> int:
    d = config.CACHE_HIERARCHY_DEPTH.to_int()
    return 2 if d is None else max(int(d), 0)


def children(cell: Cell) -> List[Cell]:
    """A cell's four children one level finer, in :data:`CHILD_ORDER`."""
    ix, iy = cell
    return [(2 * ix + dx, 2 * iy + dy) for dx, dy in CHILD_ORDER]


def parent(cell: Cell) -> Cell:
    return (cell[0] >> 1, cell[1] >> 1)


def assemble(
    get: Callable[[int, Cell], Optional[Any]],
    put: Callable[[int, Cell, Any], Any],
    merge4: Callable[[List[Any]], Any],
    level: int,
    cell: Cell,
    max_depth: Optional[int] = None,
    max_level: Optional[int] = None,
    stats: Optional[Dict[str, int]] = None,
    count_promotes: bool = True,
) -> Optional[Any]:
    """Assemble ``cell`` at ``level`` from cached children, recursively up
    to ``max_depth`` levels down; promote (``put``) every assembled node
    so the next query hits it directly. Returns the assembled (packed)
    value, or None when any descendant subtree is missing — all-or-
    nothing, so a partially warm quad falls back to one exact residual
    scan instead of a wrong partial merge.

    ``get``/``put`` speak PACKED (storable) values; ``merge4`` receives
    the four packed children in :data:`CHILD_ORDER` and returns the packed
    parent. ``stats`` (optional) accumulates ``assembled`` node counts and
    the ``deepest`` child level consulted, for explain/exec-path notes.
    ``count_promotes=False``: dry-run callers (explain's residency probe
    passes a no-op put) must not inflate ``cache.hierarchy.promote``."""
    if max_depth is None:
        max_depth = depth()
    if max_level is None:
        max_level = config.CACHE_MAX_LEVEL.to_int() or 12
    if max_depth <= 0 or level + 1 > max_level:
        return None
    vals: List[Any] = []
    for ch in children(cell):
        v = get(level + 1, ch)
        if v is None:
            v = assemble(get, put, merge4, level + 1, ch,
                         max_depth - 1, max_level, stats, count_promotes)
            if v is None:
                return None
        elif stats is not None:
            stats["deepest"] = max(stats.get("deepest", 0), level + 1)
        vals.append(v)
    packed = merge4(vals)
    put(level, cell, packed)
    if count_promotes:
        metrics.inc(metrics.CACHE_HIER_PROMOTE)
    if stats is not None:
        stats["assembled"] = stats.get("assembled", 0) + 1
        stats["deepest"] = max(stats.get("deepest", 0), level + 1)
    return packed


def rollup(
    get: Callable[[int, Cell], Optional[Any]],
    put: Callable[[int, Cell, Any], Any],
    merge4: Callable[[List[Any]], Any],
    level: int,
    cell: Cell,
    min_level: int = 1,
) -> int:
    """Bottom-up population: after ``cell`` lands at ``level``, write its
    parent whenever all four siblings are resident (and recurse upward
    while quads keep completing). Idempotent — an already-present parent
    stops the walk (it was merged from the same epoch's children, so
    rewriting it could only produce the same bytes). Returns the number of
    parents written."""
    wrote = 0
    while level > min_level:
        par = parent(cell)
        if get(level - 1, par) is not None:
            break
        vals = []
        for ch in children(par):
            v = get(level, ch)
            if v is None:
                return wrote
            vals.append(v)
        put(level - 1, par, merge4(vals))
        metrics.inc(metrics.CACHE_HIER_PROMOTE)
        wrote += 1
        cell, level = par, level - 1
    return wrote


# -- curve-block grids (density_curve; block space) -------------------------
#
# Chunks in block space nest 1:1 across levels: the chunk (cx, cy) of side
# c at level k covers blocks [cx*c, (cx+1)*c) x [cy*c, (cy+1)*c), which at
# level k+1 is exactly the chunk (cx, cy) of side 2c — so a zoom-out step
# is a single child lookup plus one downsample-add, and a stored chunk
# pre-merges ALL its coarser projections bottom-up for free.

def downsample(grid: np.ndarray) -> np.ndarray:
    """One zoom-out step in block space: 2x2 blocks of a level-(k+1) count
    grid sum into one level-k block. Exact for the unweighted path — the
    grids are f64 integer counts (decode_curve), and a level-k block's
    rows are exactly the union of its four children's rows by the z2
    prefix nesting — in the fixed SW,SE,NW,NE order of the reshape."""
    h, w = grid.shape
    return grid.reshape(h // 2, 2, w // 2, 2).sum(axis=(3, 1))


def assemble_curve(
    get: Callable[[int, int, int, int], Optional[np.ndarray]],
    put: Callable[[int, int, int, int, np.ndarray], Any],
    level: int,
    side: int,
    cx: int,
    cy: int,
    max_depth: Optional[int] = None,
    max_level: int = 15,
    stats: Optional[Dict[str, int]] = None,
) -> Optional[np.ndarray]:
    """Assemble the (cx, cy) chunk of ``side`` at ``level`` by
    downsample-adding its level-(k+1) projection (recursively, up to
    ``max_depth`` levels down), promoting every assembled grid.
    ``get``/``put`` take (level, side, cx, cy)."""
    if max_depth is None:
        max_depth = depth()
    if max_depth <= 0 or level + 1 > max_level:
        return None
    g = get(level + 1, side * 2, cx, cy)
    if g is None:
        g = assemble_curve(get, put, level + 1, side * 2, cx, cy,
                           max_depth - 1, max_level, stats)
        if g is None:
            return None
    elif stats is not None:
        stats["deepest"] = max(stats.get("deepest", 0), level + 1)
    out = downsample(g)
    put(level, side, cx, cy, out)
    metrics.inc(metrics.CACHE_HIER_PROMOTE)
    if stats is not None:
        stats["assembled"] = stats.get("assembled", 0) + 1
        stats["deepest"] = max(stats.get("deepest", 0), level + 1)
    return out


def rollup_curve(
    get: Callable[[int, int, int, int], Optional[np.ndarray]],
    put: Callable[[int, int, int, int, np.ndarray], Any],
    level: int,
    side: int,
    cx: int,
    cy: int,
    grid: np.ndarray,
    min_level: int = 1,
) -> int:
    """Bottom-up population for curve chunks: a freshly stored chunk
    pre-merges its coarser projections (halving the side each step) until
    one already exists, the side reaches one block, or ``min_level``."""
    wrote = 0
    while side >= 2 and level - 1 >= min_level:
        level, side = level - 1, side // 2
        if get(level, side, cx, cy) is not None:
            break
        grid = downsample(grid)
        put(level, side, cx, cy, grid)
        metrics.inc(metrics.CACHE_HIER_PROMOTE)
        wrote += 1
    return wrote
