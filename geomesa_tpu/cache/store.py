"""Size-aware LRU store for cached aggregate results.

One object holds every feature store's entries, partitioned by the store's
process-unique ``uid`` so budgets and invalidation are per schema (the LRU
budget applies to EACH uid — a dataset with N schemas can hold N budgets).
Entries are keyed under a dataset **epoch** (the FeatureStore ``version``,
bumped by every mutation path — flush, delete, schema/index changes): an
access with a newer epoch drops *all* of that dataset's covers at once, the
invalidation contract GeoBlocks-style caches need (PAPERS.md) — a cached
cell must never survive a write it cannot see.

Thread-safe; metrics ride the process registry (metrics.py: cache.*).
"""

from __future__ import annotations

import threading
import weakref
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

from geomesa_tpu import config, metrics

#: every live CacheStore, so the process-wide cache.bytes/cache.entries
#: gauges sum across datasets instead of tracking only the newest store
_STORES: "weakref.WeakSet[CacheStore]" = weakref.WeakSet()


def _gauge_total(attr: str) -> float:
    return float(sum(getattr(s, attr) for s in list(_STORES)))


def value_nbytes(value: Any) -> int:
    """Approximate resident size of a cached value."""
    import numpy as np

    if isinstance(value, np.ndarray):
        return int(value.nbytes)
    if isinstance(value, (bytes, str)):
        return len(value)
    if isinstance(value, tuple):
        return sum(value_nbytes(v) for v in value)
    return 32  # ints / floats / small scalars


class CacheStore:
    """Per-dataset, epoch-keyed, size-aware LRU."""

    def __init__(self, budget_bytes: Optional[int] = None):
        #: uid -> OrderedDict[key, (value, nbytes)] in LRU order
        self._data: Dict[int, "OrderedDict[Tuple, Tuple[Any, int]]"] = {}
        self._bytes: Dict[int, int] = {}
        self._epoch: Dict[int, int] = {}
        self._budget = budget_bytes
        self._lock = threading.Lock()
        _STORES.add(self)
        # (re-)registering is idempotent in effect: the gauge fn sums over
        # _STORES, never over one captured store — but each init builds a
        # fresh lambda, so the swap must be EXPLICIT (replace=True; the
        # registry refuses silent callable replacement)
        reg = metrics.registry()
        reg.gauge(metrics.CACHE_BYTES,
                  lambda: _gauge_total("total_bytes"), replace=True)
        reg.gauge(metrics.CACHE_ENTRIES,
                  lambda: _gauge_total("total_entries"), replace=True)

    # -- budgets -----------------------------------------------------------
    def budget(self) -> int:
        if self._budget is not None:
            return self._budget
        b = config.CACHE_BUDGET_BYTES.to_int()
        return b if b is not None else int(config.CACHE_BUDGET_BYTES.default)

    @property
    def total_bytes(self) -> int:
        with self._lock:
            return sum(self._bytes.values())

    @property
    def total_entries(self) -> int:
        with self._lock:
            return sum(len(d) for d in self._data.values())

    # -- epoch invalidation ------------------------------------------------
    def _sync_epoch(self, uid: int, epoch: int) -> None:
        """Drop every cover of ``uid`` when its epoch advanced (caller holds
        the lock). A regressed epoch (store object reuse after load) also
        invalidates — staleness is *any* mismatch, not just monotone growth."""
        cur = self._epoch.get(uid)
        if cur is None:
            self._epoch[uid] = epoch
            return
        if cur != epoch:
            dropped = len(self._data.get(uid, ()))
            self._data.pop(uid, None)
            self._bytes.pop(uid, None)
            self._epoch[uid] = epoch
            if dropped:
                metrics.inc(metrics.CACHE_INVALIDATE, dropped)

    # -- access ------------------------------------------------------------
    def get(self, uid: int, epoch: int, key: Tuple) -> Optional[Any]:
        with self._lock:
            self._sync_epoch(uid, epoch)
            d = self._data.get(uid)
            if d is None:
                return None
            hit = d.get(key)
            if hit is None:
                return None
            d.move_to_end(key)
            return hit[0]

    def put(self, uid: int, epoch: int, key: Tuple, value: Any) -> bool:
        nbytes = value_nbytes(value)
        budget = self.budget()
        if nbytes > budget:
            return False  # a single over-budget entry would evict everything
        with self._lock:
            self._sync_epoch(uid, epoch)
            d = self._data.setdefault(uid, OrderedDict())
            old = d.pop(key, None)
            if old is not None:
                self._bytes[uid] = self._bytes.get(uid, 0) - old[1]
            d[key] = (value, nbytes)
            self._bytes[uid] = self._bytes.get(uid, 0) + nbytes
            metrics.inc(metrics.CACHE_PUT)
            while self._bytes.get(uid, 0) > budget and d:
                _, (_, sz) = d.popitem(last=False)
                self._bytes[uid] -= sz
                metrics.inc(metrics.CACHE_EVICT)
            return True

    # -- persistence (geomesa_tpu/lake/persist.py; docs/CACHE.md) ----------
    def export_uid(self, uid: int,
                   limit: Optional[int] = None) -> Tuple[Optional[int], list]:
        """Snapshot one dataset's entries for persistence: ``(epoch,
        [(key, value), ...])`` in LRU order (coldest first, so a budget-
        capped restore keeps the hottest). ``limit`` keeps only the
        HOTTEST ``limit`` entries (the warm-handoff drain's per-schema
        cap — docs/RESILIENCE.md §7). Values are shared references —
        callers must treat them read-only."""
        with self._lock:
            d = self._data.get(uid)
            epoch = self._epoch.get(uid)
            if not d:
                return epoch, []
            items = [(k, v[0]) for k, v in d.items()]
        if limit is not None and len(items) > limit:
            items = items[-limit:]  # LRU order: the tail is the hottest
        return epoch, items

    def import_entries(self, uid: int, epoch: int, items) -> int:
        """Restore persisted entries under ``(uid, epoch)`` — the live
        store's CURRENT epoch, so normal invalidation keeps guarding
        later mutations. Budget applies exactly as for fresh puts.
        Returns the number of entries admitted."""
        n = 0
        for key, value in items:
            if self.put(uid, epoch, key, value):
                n += 1
        if n:
            metrics.inc(metrics.CACHE_PERSIST_RESTORED, n)
        return n

    def invalidate(self, uid: Optional[int] = None) -> None:
        """Explicit drop — all datasets, or one."""
        with self._lock:
            if uid is None:
                dropped = sum(len(d) for d in self._data.values())
                self._data.clear()
                self._bytes.clear()
                self._epoch.clear()
            else:
                dropped = len(self._data.get(uid, ()))
                self._data.pop(uid, None)
                self._bytes.pop(uid, None)
                self._epoch.pop(uid, None)
            if dropped:
                metrics.inc(metrics.CACHE_INVALIDATE, dropped)

    def export_wire(self, uid: int,
                    limit: Optional[int] = None) -> Tuple[Optional[int],
                                                          list]:
        """:meth:`export_uid` in the JSON-safe wire shape the fleet's
        warm-handoff drain ships over Flight (sidecar ``cache-export`` /
        ``cache-import`` actions — docs/RESILIENCE.md §7): ``(epoch,
        [[key_repr, encoded_value], ...])`` hottest-last. Entries whose
        key does not survive the repr round trip, or whose value kind
        has no wire encoding, are skipped entry-by-entry (the
        lake-persistence rule)."""
        import ast

        epoch, items = self.export_uid(uid, limit=limit)
        out = []
        for key, value in items:
            kr = repr(key)
            try:
                if ast.literal_eval(kr) != key:
                    continue
            except (ValueError, SyntaxError):
                continue
            enc = encode_wire_value(value)
            if enc is not None:
                out.append([kr, enc])
        return epoch, out

    def import_wire(self, uid: int, epoch: int, entries) -> int:
        """Admit ``cache-export`` wire entries under ``(uid, epoch)`` —
        the receiving store's CURRENT epoch, exactly like
        :meth:`import_entries` (normal invalidation keeps guarding later
        mutations; budget applies as for fresh puts)."""
        items = []
        import ast

        for key_repr, enc in entries:
            try:
                items.append((ast.literal_eval(key_repr),
                              decode_wire_value(enc)))
            except (ValueError, SyntaxError, KeyError, TypeError):
                continue  # one bad entry must not fail the handoff
        return self.import_entries(uid, epoch, items)

    def snapshot(self) -> Dict[str, Any]:
        """Operator-facing stats (sidecar ``cache-stats`` action)."""
        reg = metrics.registry().report()
        with self._lock:
            per_ds = {
                str(uid): {"entries": len(d), "bytes": self._bytes.get(uid, 0),
                           "epoch": self._epoch.get(uid)}
                for uid, d in self._data.items()
            }
        return {
            "enabled": bool(config.CACHE_ENABLED.to_bool()),
            "budget_bytes": self.budget(),
            "datasets": per_ds,
            "counters": {
                k: v for k, v in reg.items() if k.startswith("cache.")
            },
        }


# -- wire value codec (fleet warm handoff; docs/RESILIENCE.md §7) ----------
# The JSON-embeddable sibling of lake/persist.py's container codec: cache
# VALUES are ints / floats / strs (stat JSON) / ndarrays / tuples thereof.
# Arrays ride base64 with dtype+shape — a handoff is a few hundred hot
# entries, not a lake snapshot, so the container's delta encoder would be
# overkill on the action channel.

def encode_wire_value(v: Any):
    import base64

    import numpy as np

    if isinstance(v, bool):
        return {"t": "bool", "v": bool(v)}
    if isinstance(v, (int, np.integer)):
        return {"t": "int", "v": int(v)}
    if isinstance(v, (float, np.floating)):
        return {"t": "float", "v": float(v)}
    if isinstance(v, str):
        return {"t": "str", "v": v}
    if isinstance(v, np.ndarray):
        raw = np.ascontiguousarray(v)
        return {"t": "arr", "dtype": str(raw.dtype),
                "shape": list(raw.shape),
                "b64": base64.b64encode(raw.tobytes()).decode()}
    if isinstance(v, tuple):
        items = [encode_wire_value(i) for i in v]
        if any(i is None for i in items):
            return None
        return {"t": "tuple", "items": items}
    return None  # unencodable kind: the caller skips the entry


def decode_wire_value(d) -> Any:
    import base64

    import numpy as np

    t = d["t"]
    if t in ("bool", "int", "float", "str"):
        return d["v"]
    if t == "arr":
        a = np.frombuffer(base64.b64decode(d["b64"]),
                          dtype=np.dtype(d["dtype"]))
        return a.reshape(d["shape"]).copy()  # frombuffer is read-only
    if t == "tuple":
        return tuple(decode_wire_value(i) for i in d["items"])
    raise ValueError(f"unknown wire value type {t!r}")
