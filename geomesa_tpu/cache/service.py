"""Aggregate-cache orchestration: memoize pushdown aggregates per SFC cell.

One :class:`AggregateCache` hangs off a GeoDataset (so the sidecar's Flight
queries share it — one process, one cache) and fronts the four aggregate
entry points (count / density / density_curve / stats):

1. **whole-result fast path** — an exact repeat of a query (same canonical
   filter, same op parameters, same auths, same dataset epoch) returns the
   stored aggregate without touching the executor;
2. **partial-cover reuse** — a decomposable query (cells.py) looks up each
   interior SFC cell, executes ONLY the missing cells and the boundary
   strips through the ordinary planner/executor, merges cached + fresh
   partials (grids add, counts add, sketches merge), and stores the fresh
   cells for the next overlapping query;
3. **hierarchical pre-aggregation** (hierarchy.py; GeoBlocks, PAPERS.md) —
   a missing cell assembles from its four finer children before falling
   back to a scan, and completed sibling quads roll up bottom-up, so a
   zoom-out over a warm region costs O(visible cells), not O(data);
4. **polygon regions** (cells.decompose_region) — a query whose spatial
   conjunct is INTERSECTS/WITHIN of a polygon literal splits into interior
   cells (cache/hierarchy-served; they share cell keys with bbox queries
   over the same residual) plus boundary cells scanned exactly under the
   polygon predicate through the ordinary planner/executor — which is the
   partitioned/sharded executor on partitioned stores, so residual
   boundary scans fan out over the device mesh like any other scan.

Invalidation is epoch-based (store.py): the FeatureStore ``version`` is the
epoch, so every mutation path (flush / delete / schema or index change)
drops the dataset's covers wholesale.

Bit-identical contract (docs/CACHE.md): decomposition is only attempted for
aggregates whose partial merge is exact —

* counts: integer addition over disjoint cells;
* unweighted density: f32 grids hold integer counts (exact to 2^24), so
  per-cell grid addition reproduces the cold scatter bit-for-bit; weighted
  grids (f32 rounding is order-dependent) use the whole-result path only;
* stats: only sketch kinds whose ``merge`` is exact integer/extremum algebra
  (count, minmax, enumeration, topk, histogram, frequency);
* density_curve: whole-result only (block membership is decided by the SFC
  quantization of row coordinates, which a coordinate-space cell predicate
  cannot reproduce exactly at block edges).

Degraded aggregates (resilience partial-results: ``plan.degraded``) are
**never** cached — a skipped partition must not become a permanent lie.
Sampling hints bypass the cache entirely (the 1-in-n counter is scan-order
dependent and not decomposable).
"""

from __future__ import annotations

import time
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from geomesa_tpu import config, heat, metrics, tracing
from geomesa_tpu.cache import cells as cellmod
from geomesa_tpu.cache import hierarchy
from geomesa_tpu.cache.store import CacheStore
from geomesa_tpu.stats import sketches as sk

#: sketch kinds whose merge is exact (integer / extremum algebra) — the only
#: ones partial-cover decomposition may split
EXACT_MERGE_KINDS = {
    "count", "minmax", "enumeration", "topk", "histogram", "frequency",
}


def stats_exact_merge(stat) -> bool:
    """True when every leaf sketch of ``stat`` merges exactly — THE
    eligibility test shared by cache decomposition here and the fleet
    router's stats scatter (docs/RESILIENCE.md §7): an aggregate may be
    split over disjoint row sets iff its partial merge is exact."""
    from geomesa_tpu.kernels.stats_scan import _leaf_stats

    return all(leaf.kind in EXACT_MERGE_KINDS for leaf in _leaf_stats(stat))


def merge_bundle(kind: str, *, shape=None, stat_spec: Optional[str] = None):
    """The partial-merge table (docs/CACHE.md "Exactness"): ``(zero,
    merge)`` for every aggregate kind whose partial composition over
    DISJOINT row sets is exact, or None for kinds that must stay whole.
    One table, two consumers — the cache's ``_Op`` bundles below and the
    fleet router's scatter-gather (fleet/router.py) — so scatter
    eligibility can never drift from cache-decomposition eligibility.

    * ``count``: integer addition;
    * ``density`` (unweighted; ``shape=(h, w)``): f32 grids hold integer
      counts (exact to 2^24), cell-partition grid addition is bit-exact;
    * ``stats`` (``stat_spec``; only when :func:`stats_exact_merge`):
      sketch merge through :meth:`Stat.merge` — integer/extremum algebra;
    * ``curve``: f64 block-count grids add exactly (integers to 2^53);
      the ROUTER composes curve partials by disjoint block slices, the
      cache by chunk families (_serve_curve) — both exact by blocks.
    """
    if kind == "count":
        return (lambda: 0), (lambda a, b: a + int(b))
    if kind == "density":
        h, w = int(shape[0]), int(shape[1])
        return (lambda: np.zeros((h, w), np.float32)), (
            lambda a, b: a + np.asarray(b, np.float32)
        )
    if kind == "stats":
        from geomesa_tpu.stats import parse_stat

        if not stats_exact_merge(parse_stat(stat_spec)):
            return None

        def merge(acc: sk.Stat, piece: sk.Stat) -> sk.Stat:
            acc.merge(piece)
            return acc

        return (lambda: parse_stat(stat_spec)), merge
    if kind == "curve":
        return (lambda: None), (lambda a, b: b if a is None else a + b)
    return None


class _Op:
    """Per-aggregate behavior bundle for the generic serve loop."""

    def __init__(self, fingerprint: Tuple, run: Callable, zero: Callable,
                 merge: Callable, pack: Callable, unpack: Callable,
                 decomposable: bool, cell_nbytes: int = 0):
        self.fingerprint = fingerprint
        self.run = run          # plan -> raw value (through the executor)
        self.zero = zero        # () -> empty value
        self.merge = merge      # (acc, piece) -> acc
        self.pack = pack        # value -> storable (immutable-ish)
        self.unpack = unpack    # storable -> fresh value safe to hand out
        self.decomposable = decomposable
        #: estimated stored size of ONE cell entry (0 = negligible) — gates
        #: decomposition against the LRU budget
        self.cell_nbytes = cell_nbytes


class AggregateCache:
    """Query-result cache for one GeoDataset (shared across its queries)."""

    def __init__(self, budget_bytes: Optional[int] = None):
        self.store = CacheStore(budget_bytes)

    # -- gates -------------------------------------------------------------
    @staticmethod
    def enabled() -> bool:
        return bool(config.CACHE_ENABLED.to_bool())

    @staticmethod
    def _bypass(q) -> bool:
        # sampling's 1-in-n counter depends on scan order: not cacheable
        return q.sampling is not None or q.sample_by is not None

    # -- plumbing ----------------------------------------------------------
    @staticmethod
    def _note(plan, **kw) -> None:
        plan.__dict__.setdefault("exec_path", {}).update(kw)

    @staticmethod
    def _auth_key(ds, q) -> Optional[Tuple[str, ...]]:
        auths = ds._effective_auths(q)
        return None if auths is None else tuple(auths)

    @staticmethod
    def _sub_plan(ds, st, q, f):
        """Plan + visibility-wrap a residual/cell filter through the
        ordinary pipeline (interceptor guards included).

        Cell filters are canonical per (cell, residual), so the sub-plan
        gets a stable ``cache_token``: its jitted kernels land in the
        store's shared LRU kernel registry and are REUSED whenever any
        later query decomposes over the same cell — even after a mutation
        drops the cached results themselves (kernel keys are
        version-stable; docs/PERF.md). Cold decomposed queries therefore
        share compiled kernels instead of tracing one per cell per query."""
        from geomesa_tpu.planning.planner import QueryHints, QueryPlanner

        plan2 = QueryPlanner(st).plan(f, QueryHints(query_index=q.index))
        auths = ds._effective_auths(q)
        ds._apply_visibility(st, plan2, auths)
        plan2.__dict__["cache_token"] = (
            "cache_cell", repr(plan2.filter),
            None if auths is None else tuple(auths),
        )
        return plan2

    def _run_sub(self, ds, st, q, f, op, plan, scan_acc: List[int]):
        """Execute one cell/strip query; returns (value, cacheable)."""
        plan2 = self._sub_plan(ds, st, q, f)
        value = op.run(plan2)
        scan_acc[0] += plan2.__dict__.get("scanned_rows", 0)
        scan_acc[1] = max(scan_acc[1], plan2.__dict__.get("table_rows", 0))
        degraded = plan2.__dict__.pop("degraded", None)
        if degraded:
            # carry the skipped-partition account into the outer query's
            # audit event; the piece itself must not be cached
            plan.__dict__.setdefault("degraded", []).extend(degraded)
            return value, False
        return value, True

    # -- the generic serve loop --------------------------------------------
    def _serve(self, ds, st, q, plan, op: "_Op"):
        if not self.enabled() or self._bypass(q):
            return op.run(plan)
        uid, epoch = st.uid, st.version
        akey = self._auth_key(ds, q)
        wkey = ("whole",) + op.fingerprint + (repr(plan.filter), akey)
        with tracing.span("cache.lookup", key="whole"):
            hit = self.store.get(uid, epoch, wkey)
        if hit is not None:
            metrics.inc(metrics.CACHE_HIT)
            tracing.add_cost("cache_hits", 1.0)
            self._note(plan, cache="hit")
            plan.__dict__["scanned_rows"] = 0
            plan.__dict__.setdefault("table_rows", 0)
            return op.unpack(hit)

        geom = st.ft.geom_field
        decomp = None
        if op.decomposable and not plan.is_empty:
            decomp = cellmod.decompose(plan.filter, st.ft)
            if decomp is None:
                # polygon-region shape (GeoBlocks decomposition): interior
                # cells share keys with bbox queries over the same residual
                decomp = cellmod.decompose_region(plan.filter, st.ft)
                if decomp is not None:
                    metrics.inc(metrics.CACHE_POLYGON)
        if (
            decomp is not None
            and op.cell_nbytes
            and op.cell_nbytes * (len(decomp.cells) + 1)
                > self.store.budget() // 2
        ):
            # the cell partials alone would blow half the LRU budget (e.g.
            # a large density raster stored once PER cell), evicting
            # everything including this query's own earlier cells — the
            # whole-result entry is the only one worth keeping
            decomp = None
        if decomp is None:
            value = op.run(plan)
            if not plan.__dict__.get("degraded"):
                self.store.put(uid, epoch, wkey, op.pack(value))
            metrics.inc(metrics.CACHE_MISS)
            self._note(plan, cache="miss")
            return value

        # partial-cover path: cached interior cells + executed residual.
        # Cell keys are level-qualified, so the hierarchy can address any
        # level of the quadtree with the same builder.
        def cell_key(level: int, cell) -> Tuple:
            return ("cell",) + op.fingerprint + (
                decomp.residual_key, akey, level,
                cellmod.cell_prefix(level, cell),
            )

        def hier_get(level: int, cell):
            return self.store.get(uid, epoch, cell_key(level, cell))

        def hier_put(level: int, cell, packed):
            return self.store.put(uid, epoch, cell_key(level, cell), packed)

        def merge4(vals):
            acc4 = op.zero()
            for v in vals:
                acc4 = op.merge(acc4, op.unpack(v))
            return op.pack(acc4)

        use_hier = hierarchy.enabled()
        hstats: dict = {}
        acc = op.zero()
        hits = 0
        hier_hits = 0
        scan_acc = [0, 0]  # [scanned_rows, table_rows] over executed pieces
        all_cacheable = True
        with tracing.span("cache.cells", total=len(decomp.cells),
                          level=decomp.level, kind=decomp.kind) as cells_span:
            for cell in decomp.cells:
                ckey = cell_key(decomp.level, cell)
                cprefix = cellmod.cell_prefix(decomp.level, cell)
                with tracing.span("cache.lookup", key="cell"):
                    got = self.store.get(uid, epoch, ckey)
                if got is None and use_hier:
                    # zoom-out path: pre-merge the cell from cached finer
                    # children before paying a scan (docs/CACHE.md)
                    with tracing.span("cache.hierarchy", level=decomp.level):
                        got = hierarchy.assemble(
                            hier_get, hier_put, merge4,
                            decomp.level, cell, stats=hstats,
                        )
                    if got is not None:
                        hier_hits += 1
                        metrics.inc(metrics.CACHE_HIER_HIT)
                    else:
                        metrics.inc(metrics.CACHE_HIER_RESIDUAL)
                if got is not None:
                    hits += 1
                    tracing.add_cost("cache_hits", 1.0)
                    # cell-heat telemetry (docs/OBSERVABILITY.md §9): a
                    # hit is a touch with zero attributed cost
                    heat.record(st.ft.name, decomp.level, cprefix, hit=1)
                    acc = op.merge(acc, op.unpack(got))
                    continue
                t_cell = time.perf_counter()
                with tracing.span("cache.cell.scan"):
                    value, cacheable = self._run_sub(
                        ds, st, q, decomp.cell_filter(cell, geom), op, plan,
                        scan_acc,
                    )
                # a miss carries the scan's wall-clock ms — the cost-
                # ledger attribution for this cell's slice of the world
                heat.record(
                    st.ft.name, decomp.level, cprefix, miss=1,
                    device_ms=(time.perf_counter() - t_cell) * 1e3,
                )
                if cacheable:
                    self.store.put(uid, epoch, ckey, op.pack(value))
                    if use_hier:
                        # bottom-up population: a completed sibling quad
                        # pre-merges its parent for the next zoom-out
                        hierarchy.rollup(hier_get, hier_put, merge4,
                                         decomp.level, cell)
                else:
                    all_cacheable = False
                acc = op.merge(acc, value)
            cells_span.set(hits=hits, assembled=hier_hits)
        strip_f = decomp.residual_scan_filter(geom)
        if strip_f is not None:
            with tracing.span("cache.residual", kind=decomp.kind):
                value, cacheable = self._run_sub(
                    ds, st, q, strip_f, op, plan, scan_acc
                )
            if not cacheable:
                all_cacheable = False
            acc = op.merge(acc, value)
        with tracing.span("cache.merge"):
            if all_cacheable:
                self.store.put(uid, epoch, wkey, op.pack(acc))
        plan.__dict__["scanned_rows"] = scan_acc[0]
        plan.__dict__["table_rows"] = scan_acc[1]
        if hits:
            metrics.inc(metrics.CACHE_PARTIAL)
        else:
            metrics.inc(metrics.CACHE_MISS)
        self._note(
            plan,
            cache=("partial" if hits else "miss"),
            cache_cells=f"{hits}/{len(decomp.cells)}",
            cache_level=decomp.level,
        )
        if decomp.kind == "polygon":
            covered = len(decomp.cells) + len(decomp.boundary)
            self._note(
                plan, cache_region="polygon",
                cache_boundary_cells=len(decomp.boundary),
                cache_residual_fraction=round(
                    len(decomp.boundary) / max(covered, 1), 3
                ),
            )
        if hier_hits:
            self._note(
                plan,
                hierarchy=f"{hier_hits}/{len(decomp.cells)} cells assembled"
                          f" (children to level {hstats.get('deepest', 0)})",
            )
        return acc

    # -- explain support -----------------------------------------------------
    def probe_cover(self, ds, st, q, plan) -> Optional[dict]:
        """Dry-run decomposition + residency probe for explain's
        ``Hierarchy`` section (docs/CACHE.md): which cells the query would
        cover, how many are resident at the query's own level, how many
        the hierarchy could assemble from finer children (probed with the
        ``count`` fingerprint, without promoting anything), and the
        residual fraction a polygon query would scan exactly."""
        if plan.is_empty:
            return None
        decomp = cellmod.decompose(plan.filter, st.ft)
        if decomp is None:
            decomp = cellmod.decompose_region(plan.filter, st.ft)
        if decomp is None:
            return None
        uid, epoch = st.uid, st.version
        akey = self._auth_key(ds, q)
        fp = ("count",)

        def key(level, cell):
            return ("cell",) + fp + (
                decomp.residual_key, akey, level,
                cellmod.cell_prefix(level, cell),
            )

        levels: dict = {}
        missing = 0
        dep = hierarchy.depth() if hierarchy.enabled() else 0
        for cell in decomp.cells:
            if self.store.get(uid, epoch, key(decomp.level, cell)) is not None:
                levels[decomp.level] = levels.get(decomp.level, 0) + 1
                continue
            hstats: dict = {}
            got = hierarchy.assemble(
                lambda lvl, c: self.store.get(uid, epoch, key(lvl, c)),
                lambda lvl, c, v: None,  # probe: never promote
                lambda vals: 0,          # count probe: values irrelevant
                decomp.level, cell, max_depth=dep, stats=hstats,
                count_promotes=False,
            ) if dep else None
            if got is not None:
                lvl = hstats.get("deepest", decomp.level + 1)
                levels[lvl] = levels.get(lvl, 0) + 1
            else:
                missing += 1
        boundary = decomp.residual_count()
        covered = len(decomp.cells) + (
            boundary if decomp.kind == "polygon" else 0
        )
        return {
            "kind": decomp.kind,
            "level": decomp.level,
            "cells": len(decomp.cells),
            "boundary": boundary,
            "levels": levels,
            "missing": missing,
            "residual_fraction": round(
                (missing + (boundary if decomp.kind == "polygon" else 0))
                / max(covered, 1), 3
            ),
        }

    def speculative_cells(self, ds, st, q, plan):
        """Host-only residency READ backing the speculative degraded
        density/stats answers (docs/SERVING.md): the query's decomposed
        cells with their RESIDENT count values — cache hits plus
        hierarchy assembly from cached children, never a scan, never a
        promotion. Returns ``(decomp, resident, missing)`` where
        ``resident`` is ``[(cell, count), ...]`` and ``missing`` the
        unserved cells, or None when the query does not decompose (the
        caller falls back to the planner estimate)."""
        if not self.enabled() or plan.is_empty:
            return None
        decomp = cellmod.decompose(plan.filter, st.ft)
        if decomp is None:
            decomp = cellmod.decompose_region(plan.filter, st.ft)
        if decomp is None:
            return None
        uid, epoch = st.uid, st.version
        akey = self._auth_key(ds, q)
        fp = ("count",)

        def key(level, cell):
            return ("cell",) + fp + (
                decomp.residual_key, akey, level,
                cellmod.cell_prefix(level, cell),
            )

        def merge4(vals):
            return sum(int(v) for v in vals)

        dep = hierarchy.depth() if hierarchy.enabled() else 0
        resident, missing = [], []
        for cell in decomp.cells:
            got = self.store.get(uid, epoch, key(decomp.level, cell))
            if got is None and dep:
                got = hierarchy.assemble(
                    lambda lvl, c: self.store.get(uid, epoch, key(lvl, c)),
                    lambda lvl, c, v: None,  # read-only: never promote
                    merge4, decomp.level, cell, max_depth=dep,
                    count_promotes=False,
                )
            if got is not None:
                resident.append((cell, int(got)))
            else:
                missing.append(cell)
        return decomp, resident, missing

    # -- ops ----------------------------------------------------------------
    def count(self, ds, st, q, plan) -> int:
        ex = ds._executor(st)
        zero, merge = merge_bundle("count")
        op = _Op(
            fingerprint=("count",),
            run=lambda p: int(ex.count(p)),
            zero=zero,
            merge=merge,
            pack=int,
            unpack=int,
            decomposable=True,
        )
        return int(self._serve(ds, st, q, plan, op))

    def density(self, ds, st, q, plan, bbox, width: int, height: int,
                weight: Optional[str]) -> np.ndarray:
        ex = ds._executor(st)
        render = tuple(float(v) for v in bbox)

        def run(p):
            return np.asarray(ex.density(p, bbox, width, height, weight))

        def raster_decoupled() -> bool:
            # cell entries embed the render raster in their fingerprint, so
            # they are only ever reusable while the raster stays FIXED. In
            # the pan/zoom map shape the filter bbox IS the raster — a pan
            # moves both, every cell key changes, and decomposing would
            # burn cold latency and LRU budget for cells nothing can reuse
            # (the whole-result entry already serves exact repeats).
            # Decompose only when the raster is fixed relative to the
            # filter (dashboard / WMS-overview shape).
            split = cellmod.split_bbox_conjunct(plan.filter, st.ft.geom_field)
            if split is None:
                return True  # decompose() re-checks and rejects these
            b = split[0]
            return (b.xmin, b.ymin, b.xmax, b.ymax) != render

        zero, merge = merge_bundle("density", shape=(height, width))
        op = _Op(
            fingerprint=("density", render, int(width), int(height), weight),
            run=run,
            zero=zero,
            merge=merge,
            pack=lambda v: np.asarray(v, np.float32).copy(),
            unpack=lambda v: v.copy(),
            # unweighted grids are integer-valued f32: cell addition is
            # exact; weighted grids would re-order f32 rounding
            decomposable=weight is None and raster_decoupled(),
            # every cell entry holds a FULL render raster
            cell_nbytes=int(width) * int(height) * 4,
        )
        return self._serve(ds, st, q, plan, op)

    def density_curve(self, ds, st, q, plan, level: int, block_window,
                      weight: Optional[str]) -> np.ndarray:
        ex = ds._executor(st)
        zero, merge = merge_bundle("curve")
        op = _Op(
            fingerprint=("density_curve", int(level),
                         tuple(int(v) for v in block_window), weight),
            run=lambda p: np.asarray(
                ex.density_curve(p, level, block_window, weight)
            ),
            zero=zero,
            merge=merge,
            pack=lambda v: v.copy(),
            unpack=lambda v: v.copy(),
            # coordinate-space cells can't reproduce SFC block membership,
            # but BLOCK-SPACE chunks can: the partial-cover path for this
            # op is _serve_curve below, not the generic cell loop
            decomposable=False,
        )
        if (
            self.enabled() and not self._bypass(q) and weight is None
            and not plan.is_empty
        ):
            # unweighted only: a block's count is window-independent (CDF
            # differences over the same z2-sorted scan), so chunk grids
            # concatenate exactly and downsample-add exactly (f64 integer
            # counts); weighted cross-level sums would re-round f32 — the
            # whole-result fallback keeps those bit-identical (CACHE.md)
            return self._serve_curve(
                ds, st, q, plan, int(level), block_window, op, ex
            )
        return self._serve(ds, st, q, plan, op)

    def _serve_curve(self, ds, st, q, plan, level: int, block_window,
                     op: "_Op", ex) -> np.ndarray:
        """Block-space partial-cover for density_curve (docs/CACHE.md):
        the window splits into aligned power-of-two chunks; cached chunk
        grids assemble by slicing, only missing sub-windows execute (one
        fused ``density_curve_batch`` dispatch when the executor has it),
        and the hierarchy serves a zoom-out by downsample-adding the
        chunk's level-(k+1) projection. Tile pyramids over one filter
        share chunks across tiles AND across zoom levels.

        Polygon-region filters additionally split the chunk loop into
        FAMILIES (docs/CACHE.md "Polygon curve chunks"): interior chunks
        key on the residual alone and scan without the polygon predicate
        (shared with non-region pyramids over the same residual), outside
        chunks contribute zeros with no scan, and only boundary chunks
        pay the polygon — warm polygon tile pyramids stop over-scanning
        their boundary."""
        uid, epoch = st.uid, st.version
        akey = self._auth_key(ds, q)
        wkey = ("whole",) + op.fingerprint + (repr(plan.filter), akey)
        with tracing.span("cache.lookup", key="whole"):
            hit = self.store.get(uid, epoch, wkey)
        if hit is not None:
            metrics.inc(metrics.CACHE_HIT)
            tracing.add_cost("cache_hits", 1.0)
            self._note(plan, cache="hit")
            plan.__dict__["scanned_rows"] = 0
            plan.__dict__.setdefault("table_rows", 0)
            return op.unpack(hit)

        ix0, iy0, ix1, iy1 = (int(v) for v in block_window)
        nx, ny = ix1 - ix0 + 1, iy1 - iy0 + 1
        per_axis = config.CACHE_CELLS_PER_AXIS.to_int() or 8
        c = 1
        while max(nx, ny) > per_axis * c:
            c *= 2
        cx0, cx1, cy0, cy1 = ix0 // c, ix1 // c, iy0 // c, iy1 // c
        n_chunks = (cx1 - cx0 + 1) * (cy1 - cy0 + 1)
        if c * c * 8 * (n_chunks + 1) > self.store.budget() // 2:
            # chunk grids alone would blow half the LRU budget: the
            # whole-result entry is the only one worth keeping
            return self._serve(ds, st, q, plan, op)

        base = ("curve",) + (repr(plan.filter), akey)

        # Polygon-region chunk families (docs/CACHE.md "Polygon curve
        # chunks"): when the filter is `polygon ∧ residual` on a point
        # column, classify each chunk's geographic box against the
        # polygon with CLASSIFY_MARGIN room — INTERIOR chunks hold
        # residual-only counts (the polygon conjunct is a tautology over
        # them, by the same margin argument decompose_region makes), so
        # they key on the RESIDUAL alone and share cached grids with
        # non-region queries over the same filter; OUTSIDE chunks
        # contribute zeros without any scan; only BOUNDARY chunks key on
        # (and scan under) the polygon literal.
        region_split = None
        geomf = st.ft.geom_field
        if (config.CACHE_POLYGON.to_bool() and geomf is not None
                and st.ft.attr(geomf).is_point):
            region_split = cellmod.split_region_conjunct(plan.filter, geomf)
        codes = None
        base_plain = base
        if region_split is not None:
            from geomesa_tpu.kernels import join as jk

            spatial, residual = region_split
            base_plain = ("curve",) + (repr(residual), akey)
            n_side = 1 << level
            bsx, bsy = 360.0 / n_side, 180.0 / n_side
            coords = [(kx, ky) for ky in range(cy0, cy1 + 1)
                      for kx in range(cx0, cx1 + 1)]
            cboxes = np.asarray([
                (kx * c * bsx - 180.0, ky * c * bsy - 90.0,
                 (kx + 1) * c * bsx - 180.0, (ky + 1) * c * bsy - 90.0)
                for kx, ky in coords
            ], np.float64)
            kcodes = jk.classify_cells(cboxes, spatial.geom,
                                       cellmod.CLASSIFY_MARGIN)
            codes = dict(zip(coords, (int(v) for v in kcodes)))
            metrics.inc(metrics.CACHE_CURVE_REGION)

        def _get(fam_base, lvl: int, side: int, kx: int, ky: int):
            return self.store.get(uid, epoch,
                                  fam_base + (lvl, side, kx, ky))

        def _put(fam_base, lvl: int, side: int, kx: int, ky: int, g):
            return self.store.put(
                uid, epoch, fam_base + (lvl, side, kx, ky),
                np.ascontiguousarray(g),
            )

        def _family(fam_base):
            return (lambda *a: _get(fam_base, *a),
                    lambda *a: _put(fam_base, *a))

        chunk_get, chunk_put = _family(base)
        plain_get, plain_put = _family(base_plain)

        use_hier = hierarchy.enabled()
        hstats: dict = {}
        out = np.zeros((ny, nx), np.float64)
        hits = hier_hits = n_outside = 0
        #: (sub_window, out-slice, full-chunk coords or None, plain?)
        misses = []
        with tracing.span("cache.cells", total=n_chunks, level=level,
                          kind="curve", chunk=c) as cells_span:
            for ky in range(cy0, cy1 + 1):
                for kx in range(cx0, cx1 + 1):
                    plain = False
                    if codes is not None:
                        from geomesa_tpu.kernels import join as jk

                        code = codes[(kx, ky)]
                        if code == jk.CELL_OUTSIDE:
                            # wholly outside the polygon (with margin):
                            # the output slice stays zero, no scan, no
                            # cache entry — the over-scan this family
                            # split exists to stop
                            n_outside += 1
                            continue
                        plain = code == jk.CELL_INTERIOR
                    get_, put_ = ((plain_get, plain_put) if plain
                                  else (chunk_get, chunk_put))
                    bx0, by0 = kx * c, ky * c
                    bx1, by1 = bx0 + c - 1, by0 + c - 1
                    sx0, sy0 = max(bx0, ix0), max(by0, iy0)
                    sx1, sy1 = min(bx1, ix1), min(by1, iy1)
                    full = (sx0, sy0, sx1, sy1) == (bx0, by0, bx1, by1)
                    with tracing.span("cache.lookup", key="chunk"):
                        g = get_(level, c, kx, ky)
                    if g is None and use_hier:
                        with tracing.span("cache.hierarchy", level=level):
                            g = hierarchy.assemble_curve(
                                get_, put_, level, c, kx, ky,
                                stats=hstats,
                            )
                        if g is not None:
                            hier_hits += 1
                            metrics.inc(metrics.CACHE_HIER_HIT)
                        else:
                            metrics.inc(metrics.CACHE_HIER_RESIDUAL)
                    dst = np.s_[sy0 - iy0: sy1 - iy0 + 1,
                                sx0 - ix0: sx1 - ix0 + 1]
                    if g is not None:
                        hits += 1
                        tracing.add_cost("cache_hits", 1.0)
                        out[dst] = g[sy0 - by0: sy1 - by0 + 1,
                                     sx0 - bx0: sx1 - bx0 + 1]
                    else:
                        misses.append((
                            (sx0, sy0, sx1, sy1), dst,
                            (kx, ky) if full else None, plain,
                        ))
            cells_span.set(hits=hits, assembled=hier_hits,
                           outside=n_outside)

        all_cacheable = True
        if misses:
            scan_acc = [0, 0]  # executed [scanned_rows, table_rows]
            deg0 = len(plan.__dict__.get("degraded") or ())

            def _exec_windows(p, windows):
                """Execute missing sub-windows under plan ``p``, folding
                its per-execution counters (and any degradation) into
                the OUTER plan's accounting."""

                def _fold():
                    scan_acc[0] += p.__dict__.pop("scanned_rows", 0)
                    scan_acc[1] = max(scan_acc[1],
                                      p.__dict__.pop("table_rows", 0))

                with tracing.span("cache.cell.scan", n=len(windows)):
                    if len(windows) > 1 \
                            and hasattr(ex, "density_curve_batch"):
                        grids = ex.density_curve_batch(p, level, windows,
                                                       None)
                        _fold()
                    else:
                        grids = []
                        for w in windows:
                            grids.append(np.asarray(
                                ex.density_curve(p, level, w, None)))
                            _fold()
                if p is not plan:
                    deg = p.__dict__.pop("degraded", None)
                    if deg:
                        plan.__dict__.setdefault(
                            "degraded", []).extend(deg)
                return grids

            poly_misses = [m for m in misses if not m[3]]
            plain_misses = [m for m in misses if m[3]]
            grids_by: dict = {}
            if poly_misses:
                for m, g in zip(poly_misses, _exec_windows(
                        plan, [m[0] for m in poly_misses])):
                    grids_by[id(m)] = g
            if plain_misses:
                # interior chunks scan under the RESIDUAL alone — the
                # polygon predicate is a tautology over them, and the
                # residual-only plan's kernels/grids are the ones plain
                # (non-region) curve queries share
                plan_plain = self._sub_plan(ds, st, q, region_split[1])
                for m, g in zip(plain_misses, _exec_windows(
                        plan_plain, [m[0] for m in plain_misses])):
                    grids_by[id(m)] = g
            plan.__dict__["scanned_rows"] = scan_acc[0]
            plan.__dict__["table_rows"] = scan_acc[1]
            if len(plan.__dict__.get("degraded") or ()) > deg0:
                # a partition was skipped somewhere in the fresh scans:
                # none of them may become a permanently-cached lie
                all_cacheable = False
            for m in misses:
                win, dst, full_at, plain = m
                g = np.asarray(grids_by[id(m)], np.float64)
                out[dst] = g
                if full_at is not None and all_cacheable:
                    kx, ky = full_at
                    get_, put_ = ((plain_get, plain_put) if plain
                                  else (chunk_get, chunk_put))
                    put_(level, c, kx, ky, g)
                    if use_hier:
                        hierarchy.rollup_curve(
                            get_, put_, level, c, kx, ky, g
                        )
        else:
            # fully chunk-warm: nothing executed, the audit must say so
            plan.__dict__["scanned_rows"] = 0
            plan.__dict__.setdefault("table_rows", 0)
        with tracing.span("cache.merge"):
            if all_cacheable:
                self.store.put(uid, epoch, wkey, op.pack(out))
        if hits:
            metrics.inc(metrics.CACHE_PARTIAL)
        else:
            metrics.inc(metrics.CACHE_MISS)
        self._note(
            plan,
            cache=("partial" if hits else "miss"),
            cache_cells=f"{hits}/{n_chunks}",
            cache_level=level,
            cache_chunk=c,
        )
        if codes is not None:
            from geomesa_tpu.kernels import join as jk

            n_int = sum(1 for v in codes.values()
                        if v == jk.CELL_INTERIOR)
            n_bnd = sum(1 for v in codes.values()
                        if v == jk.CELL_BOUNDARY)
            self._note(
                plan, cache_region="polygon-chunks",
                cache_region_chunks=(
                    f"{n_int} interior (residual-keyed) / {n_bnd} "
                    f"boundary / {n_outside} outside (unscanned)"
                ),
            )
        if hier_hits:
            self._note(
                plan,
                hierarchy=f"{hier_hits}/{n_chunks} chunks assembled"
                          f" (children to level {hstats.get('deepest', 0)})",
            )
        return out

    def stats(self, ds, st, q, plan, stat_spec: str) -> sk.Stat:
        from geomesa_tpu.stats import parse_stat

        ex = ds._executor(st)
        bundle = merge_bundle("stats", stat_spec=stat_spec)
        exact_merge = bundle is not None

        def _sketch_merge(acc: sk.Stat, piece: sk.Stat) -> sk.Stat:
            acc.merge(piece)
            return acc

        # non-exact specs keep a working (but inexact) merge for safety;
        # decomposable=False below means _serve never actually calls it
        zero, merge = bundle if exact_merge else (
            (lambda: parse_stat(stat_spec)), _sketch_merge,
        )

        op = _Op(
            fingerprint=("stats", stat_spec),
            run=lambda p: ex.stats(p, parse_stat(stat_spec)),
            zero=zero,
            merge=merge,
            # serialized snapshots: the caller's (mutable) Stat object can
            # never alias a cache entry
            pack=lambda v: v.to_json(),
            unpack=sk.Stat.from_json,
            decomposable=exact_merge,
        )
        return self._serve(ds, st, q, plan, op)
