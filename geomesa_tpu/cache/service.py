"""Aggregate-cache orchestration: memoize pushdown aggregates per SFC cell.

One :class:`AggregateCache` hangs off a GeoDataset (so the sidecar's Flight
queries share it — one process, one cache) and fronts the four aggregate
entry points (count / density / density_curve / stats):

1. **whole-result fast path** — an exact repeat of a query (same canonical
   filter, same op parameters, same auths, same dataset epoch) returns the
   stored aggregate without touching the executor;
2. **partial-cover reuse** — a decomposable query (cells.py) looks up each
   interior SFC cell, executes ONLY the missing cells and the boundary
   strips through the ordinary planner/executor, merges cached + fresh
   partials (grids add, counts add, sketches merge), and stores the fresh
   cells for the next overlapping query.

Invalidation is epoch-based (store.py): the FeatureStore ``version`` is the
epoch, so every mutation path (flush / delete / schema or index change)
drops the dataset's covers wholesale.

Bit-identical contract (docs/CACHE.md): decomposition is only attempted for
aggregates whose partial merge is exact —

* counts: integer addition over disjoint cells;
* unweighted density: f32 grids hold integer counts (exact to 2^24), so
  per-cell grid addition reproduces the cold scatter bit-for-bit; weighted
  grids (f32 rounding is order-dependent) use the whole-result path only;
* stats: only sketch kinds whose ``merge`` is exact integer/extremum algebra
  (count, minmax, enumeration, topk, histogram, frequency);
* density_curve: whole-result only (block membership is decided by the SFC
  quantization of row coordinates, which a coordinate-space cell predicate
  cannot reproduce exactly at block edges).

Degraded aggregates (resilience partial-results: ``plan.degraded``) are
**never** cached — a skipped partition must not become a permanent lie.
Sampling hints bypass the cache entirely (the 1-in-n counter is scan-order
dependent and not decomposable).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from geomesa_tpu import config, metrics, tracing
from geomesa_tpu.cache import cells as cellmod
from geomesa_tpu.cache.store import CacheStore
from geomesa_tpu.stats import sketches as sk

#: sketch kinds whose merge is exact (integer / extremum algebra) — the only
#: ones partial-cover decomposition may split
EXACT_MERGE_KINDS = {
    "count", "minmax", "enumeration", "topk", "histogram", "frequency",
}


class _Op:
    """Per-aggregate behavior bundle for the generic serve loop."""

    def __init__(self, fingerprint: Tuple, run: Callable, zero: Callable,
                 merge: Callable, pack: Callable, unpack: Callable,
                 decomposable: bool, cell_nbytes: int = 0):
        self.fingerprint = fingerprint
        self.run = run          # plan -> raw value (through the executor)
        self.zero = zero        # () -> empty value
        self.merge = merge      # (acc, piece) -> acc
        self.pack = pack        # value -> storable (immutable-ish)
        self.unpack = unpack    # storable -> fresh value safe to hand out
        self.decomposable = decomposable
        #: estimated stored size of ONE cell entry (0 = negligible) — gates
        #: decomposition against the LRU budget
        self.cell_nbytes = cell_nbytes


class AggregateCache:
    """Query-result cache for one GeoDataset (shared across its queries)."""

    def __init__(self, budget_bytes: Optional[int] = None):
        self.store = CacheStore(budget_bytes)

    # -- gates -------------------------------------------------------------
    @staticmethod
    def enabled() -> bool:
        return bool(config.CACHE_ENABLED.to_bool())

    @staticmethod
    def _bypass(q) -> bool:
        # sampling's 1-in-n counter depends on scan order: not cacheable
        return q.sampling is not None or q.sample_by is not None

    # -- plumbing ----------------------------------------------------------
    @staticmethod
    def _note(plan, **kw) -> None:
        plan.__dict__.setdefault("exec_path", {}).update(kw)

    @staticmethod
    def _auth_key(ds, q) -> Optional[Tuple[str, ...]]:
        auths = ds._effective_auths(q)
        return None if auths is None else tuple(auths)

    @staticmethod
    def _sub_plan(ds, st, q, f):
        """Plan + visibility-wrap a residual/cell filter through the
        ordinary pipeline (interceptor guards included).

        Cell filters are canonical per (cell, residual), so the sub-plan
        gets a stable ``cache_token``: its jitted kernels land in the
        store's shared LRU kernel registry and are REUSED whenever any
        later query decomposes over the same cell — even after a mutation
        drops the cached results themselves (kernel keys are
        version-stable; docs/PERF.md). Cold decomposed queries therefore
        share compiled kernels instead of tracing one per cell per query."""
        from geomesa_tpu.planning.planner import QueryHints, QueryPlanner

        plan2 = QueryPlanner(st).plan(f, QueryHints(query_index=q.index))
        auths = ds._effective_auths(q)
        ds._apply_visibility(st, plan2, auths)
        plan2.__dict__["cache_token"] = (
            "cache_cell", repr(plan2.filter),
            None if auths is None else tuple(auths),
        )
        return plan2

    def _run_sub(self, ds, st, q, f, op, plan, scan_acc: List[int]):
        """Execute one cell/strip query; returns (value, cacheable)."""
        plan2 = self._sub_plan(ds, st, q, f)
        value = op.run(plan2)
        scan_acc[0] += plan2.__dict__.get("scanned_rows", 0)
        scan_acc[1] = max(scan_acc[1], plan2.__dict__.get("table_rows", 0))
        degraded = plan2.__dict__.pop("degraded", None)
        if degraded:
            # carry the skipped-partition account into the outer query's
            # audit event; the piece itself must not be cached
            plan.__dict__.setdefault("degraded", []).extend(degraded)
            return value, False
        return value, True

    # -- the generic serve loop --------------------------------------------
    def _serve(self, ds, st, q, plan, op: "_Op"):
        if not self.enabled() or self._bypass(q):
            return op.run(plan)
        uid, epoch = st.uid, st.version
        akey = self._auth_key(ds, q)
        wkey = ("whole",) + op.fingerprint + (repr(plan.filter), akey)
        with tracing.span("cache.lookup", key="whole"):
            hit = self.store.get(uid, epoch, wkey)
        if hit is not None:
            metrics.inc(metrics.CACHE_HIT)
            tracing.add_cost("cache_hits", 1.0)
            self._note(plan, cache="hit")
            plan.__dict__["scanned_rows"] = 0
            plan.__dict__.setdefault("table_rows", 0)
            return op.unpack(hit)

        geom = st.ft.geom_field
        decomp = (
            cellmod.decompose(plan.filter, st.ft)
            if op.decomposable and not plan.is_empty else None
        )
        if (
            decomp is not None
            and op.cell_nbytes
            and op.cell_nbytes * (len(decomp.cells) + 1)
                > self.store.budget() // 2
        ):
            # the cell partials alone would blow half the LRU budget (e.g.
            # a large density raster stored once PER cell), evicting
            # everything including this query's own earlier cells — the
            # whole-result entry is the only one worth keeping
            decomp = None
        if decomp is None:
            value = op.run(plan)
            if not plan.__dict__.get("degraded"):
                self.store.put(uid, epoch, wkey, op.pack(value))
            metrics.inc(metrics.CACHE_MISS)
            self._note(plan, cache="miss")
            return value

        # partial-cover path: cached interior cells + executed residual
        acc = op.zero()
        hits = 0
        scan_acc = [0, 0]  # [scanned_rows, table_rows] over executed pieces
        all_cacheable = True
        with tracing.span("cache.cells", total=len(decomp.cells),
                          level=decomp.level) as cells_span:
            for cell in decomp.cells:
                ckey = ("cell",) + op.fingerprint + (
                    decomp.residual_key, akey, decomp.level,
                    decomp.cell_prefix(cell),
                )
                with tracing.span("cache.lookup", key="cell"):
                    got = self.store.get(uid, epoch, ckey)
                if got is not None:
                    hits += 1
                    tracing.add_cost("cache_hits", 1.0)
                    acc = op.merge(acc, op.unpack(got))
                    continue
                with tracing.span("cache.cell.scan"):
                    value, cacheable = self._run_sub(
                        ds, st, q, decomp.cell_filter(cell, geom), op, plan,
                        scan_acc,
                    )
                if cacheable:
                    self.store.put(uid, epoch, ckey, op.pack(value))
                else:
                    all_cacheable = False
                acc = op.merge(acc, value)
            cells_span.set(hits=hits)
        strip_f = decomp.strip_filter(geom)
        if strip_f is not None:
            with tracing.span("cache.residual"):
                value, cacheable = self._run_sub(
                    ds, st, q, strip_f, op, plan, scan_acc
                )
            if not cacheable:
                all_cacheable = False
            acc = op.merge(acc, value)
        with tracing.span("cache.merge"):
            if all_cacheable:
                self.store.put(uid, epoch, wkey, op.pack(acc))
        plan.__dict__["scanned_rows"] = scan_acc[0]
        plan.__dict__["table_rows"] = scan_acc[1]
        if hits:
            metrics.inc(metrics.CACHE_PARTIAL)
        else:
            metrics.inc(metrics.CACHE_MISS)
        self._note(
            plan,
            cache=("partial" if hits else "miss"),
            cache_cells=f"{hits}/{len(decomp.cells)}",
            cache_level=decomp.level,
        )
        return acc

    # -- ops ----------------------------------------------------------------
    def count(self, ds, st, q, plan) -> int:
        ex = ds._executor(st)
        op = _Op(
            fingerprint=("count",),
            run=lambda p: int(ex.count(p)),
            zero=lambda: 0,
            merge=lambda a, b: a + int(b),
            pack=int,
            unpack=int,
            decomposable=True,
        )
        return int(self._serve(ds, st, q, plan, op))

    def density(self, ds, st, q, plan, bbox, width: int, height: int,
                weight: Optional[str]) -> np.ndarray:
        ex = ds._executor(st)
        render = tuple(float(v) for v in bbox)

        def run(p):
            return np.asarray(ex.density(p, bbox, width, height, weight))

        def raster_decoupled() -> bool:
            # cell entries embed the render raster in their fingerprint, so
            # they are only ever reusable while the raster stays FIXED. In
            # the pan/zoom map shape the filter bbox IS the raster — a pan
            # moves both, every cell key changes, and decomposing would
            # burn cold latency and LRU budget for cells nothing can reuse
            # (the whole-result entry already serves exact repeats).
            # Decompose only when the raster is fixed relative to the
            # filter (dashboard / WMS-overview shape).
            split = cellmod.split_bbox_conjunct(plan.filter, st.ft.geom_field)
            if split is None:
                return True  # decompose() re-checks and rejects these
            b = split[0]
            return (b.xmin, b.ymin, b.xmax, b.ymax) != render

        op = _Op(
            fingerprint=("density", render, int(width), int(height), weight),
            run=run,
            zero=lambda: np.zeros((height, width), np.float32),
            merge=lambda a, b: a + np.asarray(b, np.float32),
            pack=lambda v: np.asarray(v, np.float32).copy(),
            unpack=lambda v: v.copy(),
            # unweighted grids are integer-valued f32: cell addition is
            # exact; weighted grids would re-order f32 rounding
            decomposable=weight is None and raster_decoupled(),
            # every cell entry holds a FULL render raster
            cell_nbytes=int(width) * int(height) * 4,
        )
        return self._serve(ds, st, q, plan, op)

    def density_curve(self, ds, st, q, plan, level: int, block_window,
                      weight: Optional[str]) -> np.ndarray:
        ex = ds._executor(st)
        op = _Op(
            fingerprint=("density_curve", int(level),
                         tuple(int(v) for v in block_window), weight),
            run=lambda p: np.asarray(
                ex.density_curve(p, level, block_window, weight)
            ),
            zero=lambda: None,
            merge=lambda a, b: b if a is None else a + b,
            pack=lambda v: v.copy(),
            unpack=lambda v: v.copy(),
            decomposable=False,  # block membership is SFC-quantized
        )
        return self._serve(ds, st, q, plan, op)

    def stats(self, ds, st, q, plan, stat_spec: str) -> sk.Stat:
        from geomesa_tpu.kernels.stats_scan import _leaf_stats
        from geomesa_tpu.stats import parse_stat

        ex = ds._executor(st)
        probe = parse_stat(stat_spec)
        exact_merge = all(
            leaf.kind in EXACT_MERGE_KINDS for leaf in _leaf_stats(probe)
        )

        def merge(acc: sk.Stat, piece: sk.Stat) -> sk.Stat:
            acc.merge(piece)
            return acc

        op = _Op(
            fingerprint=("stats", stat_spec),
            run=lambda p: ex.stats(p, parse_stat(stat_spec)),
            zero=lambda: parse_stat(stat_spec),
            merge=merge,
            # serialized snapshots: the caller's (mutable) Stat object can
            # never alias a cache entry
            pack=lambda v: v.to_json(),
            unpack=sk.Stat.from_json,
            decomposable=exact_merge,
        )
        return self._serve(ds, st, q, plan, op)
