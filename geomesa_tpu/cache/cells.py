"""Partial-cover decomposition: query bbox -> SFC cells + boundary strips.

The cacheable unit is a **grid cell** of the global 2^level x 2^level lon/lat
partition — the same cell family the z2 curve's prefix blocks quantize to
(``curves/zorder.interleave2(ix, iy)`` is each cell's curve prefix, used as
its identity), so cell keys are absolute: a panned query re-derives the same
cell ids for the overlap and pays only for the newly exposed strip
(GeoBlocks' query/cache decomposition over aggregate cells; PAPERS.md).

Exactness contract (what makes cached + fresh partials merge bit-identically
with a cold scan):

* cells are **half-open** ``[x0, x1) x [y0, y1)`` — realized as closed BBox
  predicates with the open edges pulled one f64 ulp inward — so the cells of
  a level partition the plane and no row is double-counted or dropped;
* the cell edges ``i * (360 / 2^level) - 180`` are exact in f64 (the cell
  span is 45 * 2^(3-level), a dyadic multiple), so every query derives
  byte-identical cell boxes;
* interior cells satisfy ``[x0, x1) x [y0, y1) ⊆ Q`` *by direct f64
  comparison against the query box*, so a cell query (residual ∧ cell box)
  returns exactly the query's rows inside that cell;
* the boundary Q \\ interior is covered by at most four disjoint strips
  (left/right full-height, bottom/top between them).

Decomposition applies when the schema's geometry is a POINT and the filter
constrains it with exactly one BBox conjunct at the top level (the pan/zoom
shape); anything richer — extent (line/polygon) geometry columns, whose
features intersect multiple cells and would be counted once per cell,
spatial predicates under OR/NOT, multiple boxes — falls back to
whole-result caching, which is always safe.

Polygon-region queries (one INTERSECTS/WITHIN polygon-literal conjunct on a
point column) get their own decomposition (:func:`decompose_region`,
GeoBlocks' polygon split; PAPERS.md): the covering cells classify against
the polygon (``kernels/join.classify_cells``, the join kernel's crossing
test) into **interior** cells — wholly inside with margin to spare, served
from the same cell entries bbox queries populate, because a cell fully
inside the polygon makes the polygon conjunct a tautology over it —
**boundary** cells, scanned exactly under the original polygon predicate
(the same kernel an undecomposed query runs, so near-edge rows decide
identically), and **outside** cells, contributing nothing.

Domain-edge closure: the half-open ``[x0, x1)`` partition leaves the
``x = 180`` meridian and ``y = 90`` pole lines uncovered, so the LAST cell
column/row closes at the domain edge (its box realization ends at exactly
180 / 90 instead of one ulp below). The cells of a level then partition the
full [-180, 180] x [-90, 90] domain — a domain-spanning zoom-out
decomposes with NO residual strips, which is what lets a warm zoom-out
answer with zero device dispatches (docs/CACHE.md).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from geomesa_tpu import config
from geomesa_tpu.filter import ir

Box = Tuple[float, float, float, float]


def _has_spatial(node: ir.Filter, geom: str) -> bool:
    """Does this subtree constrain (or even mention) the geometry?"""
    if isinstance(node, (ir.BBox, ir.Spatial, ir.DWithin)):
        return node.prop == geom
    if isinstance(node, (ir.And, ir.Or)):
        return any(_has_spatial(c, geom) for c in node.children)
    if isinstance(node, ir.Not):
        return _has_spatial(node.child, geom)
    if isinstance(node, ir.ExprCompare):
        return geom in node.props()
    prop = getattr(node, "prop", None)
    return prop == geom


def _prev(v: float) -> float:
    return float(np.nextafter(v, -np.inf))


def cell_box(level: int, ix: int, iy: int) -> Box:
    """The closed-BBox realization of the absolute half-open cell
    ``(ix, iy)`` at ``level``: open edges pulled one f64 ulp inward,
    except the domain-edge column/row, which closes at exactly 180 / 90
    (see the module docstring's domain-edge closure)."""
    n = 1 << level
    sx, sy = 360.0 / n, 180.0 / n
    xmax = 180.0 if ix == n - 1 else _prev((ix + 1) * sx - 180.0)
    ymax = 90.0 if iy == n - 1 else _prev((iy + 1) * sy - 90.0)
    return (ix * sx - 180.0, iy * sy - 90.0, xmax, ymax)


def cell_prefix(level: int, cell: Tuple[int, int]) -> int:
    """A cell's z2 curve prefix (its absolute identity on the curve) —
    also the identity the hierarchy keys child/parent lookups on."""
    from geomesa_tpu.curves.zorder import interleave2

    ix, iy = cell
    return int(interleave2(
        np.asarray([ix], np.uint64), np.asarray([iy], np.uint64)
    )[0])


def point_cells(x, y, level: int) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized cell assignment at ``level``: int64 ``(ix, iy)`` per
    point, clipped to the grid — every consumer of the cell family (the
    cache decomposition, the join co-partition, the pushdown side scan)
    derives the SAME cell for the same f64 coordinate, which is what lets
    join cell groups key footer windows and cache statistics."""
    n = 1 << level
    sx, sy = 360.0 / n, 180.0 / n
    x = np.asarray(x, np.float64)
    y = np.asarray(y, np.float64)
    ix = np.clip(np.floor((x + 180.0) / sx), 0, n - 1).astype(np.int64)
    iy = np.clip(np.floor((y + 90.0) / sy), 0, n - 1).astype(np.int64)
    return ix, iy


def cell_boxes(level: int, ix: np.ndarray, iy: np.ndarray) -> np.ndarray:
    """Vectorized :func:`cell_box`: f64 [C, 4] closed boxes (open edges
    one ulp inward, domain-edge column/row closed) for cell index arrays
    — the geometry ``classify_cells`` runs on for polygon joins."""
    n = 1 << level
    sx, sy = 360.0 / n, 180.0 / n
    ix = np.asarray(ix, np.int64)
    iy = np.asarray(iy, np.int64)
    xmax = np.nextafter((ix + 1) * sx - 180.0, -np.inf)
    ymax = np.nextafter((iy + 1) * sy - 90.0, -np.inf)
    xmax = np.where(ix == n - 1, 180.0, xmax)
    ymax = np.where(iy == n - 1, 90.0, ymax)
    return np.stack([ix * sx - 180.0, iy * sy - 90.0, xmax, ymax], axis=1)


@dataclass
class _CellCover:
    """Shared shape of a partial-cover plan: the interior cells (served
    from / stored into the cache, and assemblable by the hierarchy) plus a
    residual filter every cell query ANDs with."""

    level: int
    #: the filter minus the spatial conjunct (what cell queries AND with)
    residual: ir.Filter
    #: canonical text of the residual — part of every cell key
    residual_key: str
    #: interior cell ids, absolute (ix, iy) at ``level``
    cells: List[Tuple[int, int]]
    #: (ix, iy) -> closed BBox realizing the half-open cell
    cell_boxes: Dict[Tuple[int, int], Box]

    def cell_filter(self, cell: Tuple[int, int], geom: str) -> ir.Filter:
        b = self.cell_boxes[cell]
        return _and(self.residual, ir.BBox(geom, *b))

    def cell_prefix(self, cell: Tuple[int, int]) -> int:
        """The cell's z2 curve prefix (its identity on the curve)."""
        return cell_prefix(self.level, cell)


@dataclass
class Decomposition(_CellCover):
    """One bbox query's partial-cover plan."""

    #: boundary strips (closed boxes, disjoint, covering Q minus interior)
    strips: List[Box]
    kind: str = "bbox"

    def strip_filter(self, geom: str) -> Optional[ir.Filter]:
        if not self.strips:
            return None
        boxes = tuple(ir.BBox(geom, *s) for s in self.strips)
        spatial = boxes[0] if len(boxes) == 1 else ir.Or(boxes)
        return _and(self.residual, spatial)

    #: uniform residual-scan surface shared with RegionDecomposition
    residual_scan_filter = strip_filter

    def residual_count(self) -> int:
        return len(self.strips)


@dataclass
class RegionDecomposition(_CellCover):
    """One polygon query's partial-cover plan: interior cells + boundary
    cells scanned exactly under the original polygon predicate."""

    #: the polygon spatial conjunct, verbatim (op + literal)
    spatial: ir.Filter = None  # type: ignore[assignment]
    #: boundary cell ids at ``level``
    boundary: List[Tuple[int, int]] = None  # type: ignore[assignment]
    #: disjoint closed boxes covering exactly the boundary cells (adjacent
    #: cells merged into row runs, so the residual scan's OR stays small)
    boundary_boxes: List[Box] = None  # type: ignore[assignment]
    kind: str = "polygon"

    def residual_scan_filter(self, geom: str) -> Optional[ir.Filter]:
        """residual ∧ polygon ∧ (boundary-cell cover) — the polygon
        predicate evaluates through the same kernel an undecomposed query
        compiles to, so boundary rows decide identically (bit-identity)."""
        if not self.boundary_boxes:
            return None
        boxes = tuple(ir.BBox(geom, *b) for b in self.boundary_boxes)
        cover = boxes[0] if len(boxes) == 1 else ir.Or(boxes)
        return _and(_and(self.residual, self.spatial), cover)

    def residual_count(self) -> int:
        return len(self.boundary)


def _and(residual: ir.Filter, spatial: ir.Filter) -> ir.Filter:
    if isinstance(residual, ir.Include):
        return spatial
    return ir.And((residual, spatial))


def split_bbox_conjunct(
    f: ir.Filter, geom: Optional[str]
) -> Optional[Tuple[ir.BBox, ir.Filter]]:
    """(bbox, residual) when the filter is `BBOX ∧ rest` with exactly one
    spatial constraint, all at top level; None otherwise."""
    if geom is None:
        return None
    conjuncts = list(f.children) if isinstance(f, ir.And) else [f]
    boxes = [c for c in conjuncts if isinstance(c, ir.BBox) and c.prop == geom]
    if len(boxes) != 1:
        return None
    rest = [c for c in conjuncts if c is not boxes[0]]
    if any(_has_spatial(c, geom) for c in rest):
        return None  # a second spatial constraint: not the pan/zoom shape
    if not rest:
        residual: ir.Filter = ir.Include()
    elif len(rest) == 1:
        residual = rest[0]
    else:
        residual = ir.And(tuple(rest))
    return boxes[0], residual


def _pick_level(dx: float, dy: float) -> Optional[int]:
    per_axis = config.CACHE_CELLS_PER_AXIS.to_int() or 8
    max_level = config.CACHE_MAX_LEVEL.to_int() or 12
    if dx <= 0 or dy <= 0:
        return None
    # finest level where the bbox spans at most per_axis cells on each axis
    lx = int(np.floor(np.log2(per_axis * 360.0 / dx)))
    ly = int(np.floor(np.log2(per_axis * 180.0 / dy)))
    level = min(lx, ly, max_level)
    return level if level >= 1 else None


def decompose(f: ir.Filter, ft) -> Optional[Decomposition]:
    """Partial-cover plan for a filter against schema ``ft``, or None when
    not decomposable. Only POINT geometries decompose: an extent feature
    (line/polygon) intersects every cell it straddles — the cells would
    each count it, breaking the disjoint-partition argument."""
    geom = None if ft is None else ft.geom_field
    if geom is None or not ft.attr(geom).is_point:
        return None
    split = split_bbox_conjunct(f, geom)
    if split is None:
        return None
    box, residual = split
    xmin, ymin, xmax, ymax = box.xmin, box.ymin, box.xmax, box.ymax
    if not (
        np.isfinite([xmin, ymin, xmax, ymax]).all()
        and -180.0 <= xmin <= xmax <= 180.0
        and -90.0 <= ymin <= ymax <= 90.0
    ):
        return None
    level = _pick_level(xmax - xmin, ymax - ymin)
    if level is None:
        return None
    n = 1 << level
    sx = 360.0 / n  # 45 * 2^(3-level): exact in f64
    sy = 180.0 / n

    def xedge(i: int) -> float:
        return i * sx - 180.0

    def yedge(i: int) -> float:
        return i * sy - 90.0

    # interior cells: [edge(i), edge(i+1)) ⊆ [min, max] by f64 comparison
    ix_lo = max(0, int(np.floor((xmin + 180.0) / sx)))
    ix_hi = min(n - 1, int(np.ceil((xmax + 180.0) / sx)))
    iy_lo = max(0, int(np.floor((ymin + 90.0) / sy)))
    iy_hi = min(n - 1, int(np.ceil((ymax + 90.0) / sy)))
    xs = [i for i in range(ix_lo, ix_hi + 1)
          if xedge(i) >= xmin and xedge(i + 1) <= xmax]
    ys = [i for i in range(iy_lo, iy_hi + 1)
          if yedge(i) >= ymin and yedge(i + 1) <= ymax]
    if not xs or not ys:
        return None
    max_cells = config.CACHE_MAX_CELLS.to_int() or 256
    if len(xs) * len(ys) > max_cells:
        return None
    # the interior index ranges are contiguous by construction
    X0, X1 = xedge(xs[0]), xedge(xs[-1] + 1)
    Y0, Y1 = yedge(ys[0]), yedge(ys[-1] + 1)

    cells: List[Tuple[int, int]] = []
    cell_boxes: Dict[Tuple[int, int], Box] = {}
    for iy in ys:
        for ix in xs:
            cells.append((ix, iy))
            cell_boxes[(ix, iy)] = cell_box(level, ix, iy)

    # Q \ interior as disjoint closed strips. The right strip is normally
    # present — rows at exactly x == X1 (the interior's open edge) live
    # there even when X1 == xmax — EXCEPT when the interior reaches the
    # domain-edge column, whose cells close at x == 180 (likewise the top
    # strip at y == 90), so a domain-spanning bbox has no strips at all.
    right_closed = xs[-1] == n - 1  # interior owns x == 180 (== xmax)
    top_closed = ys[-1] == n - 1    # interior owns y == 90 (== ymax)
    ix_hi_edge = 180.0 if right_closed else _prev(X1)
    strips: List[Box] = []
    if xmin < X0:
        strips.append((xmin, ymin, _prev(X0), ymax))          # left
    if not right_closed:
        strips.append((X1, ymin, xmax, ymax))                 # right
    if ymin < Y0:
        strips.append((X0, ymin, ix_hi_edge, _prev(Y0)))      # bottom
    if not top_closed:
        strips.append((X0, Y1, ix_hi_edge, ymax))             # top
    strips = [s for s in strips if s[0] <= s[2] and s[1] <= s[3]]

    return Decomposition(
        level=level, residual=residual, residual_key=repr(residual),
        cells=cells, cell_boxes=cell_boxes, strips=strips,
    )


#: cell-vs-polygon classification margin (degrees): a cell is INTERIOR or
#: OUTSIDE only when the verdict holds with this much room, so the scan
#: kernel's f32 near-edge uncertainty (~1e-4 deg worst case at
#: filter/compile._pip_fn) plus f32 coordinate rounding (~1e-5 deg) can
#: never flip a row the classification already committed. Near-edge rows
#: land in BOUNDARY cells and decide through the same kernel as an
#: undecomposed query — the bit-identity contract (docs/CACHE.md).
CLASSIFY_MARGIN = 1e-3

#: polygon ops decomposable for POINT columns: the predicate is constant
#: over any cell that clears the margin (for a point, INTERSECTS == WITHIN
#: off the boundary — and boundary-adjacent cells always scan exactly)
_REGION_OPS = ("intersects", "within")


def split_region_conjunct(
    f: ir.Filter, geom: Optional[str]
) -> Optional[Tuple[ir.Spatial, ir.Filter]]:
    """(polygon conjunct, residual) when the filter is ``SPATIAL ∧ rest``
    with exactly one spatial constraint — an INTERSECTS/WITHIN of a
    (multi)polygon literal — at top level; None otherwise."""
    from geomesa_tpu.utils import geometry as geo

    if geom is None:
        return None
    conjuncts = list(f.children) if isinstance(f, ir.And) else [f]
    polys = [
        c for c in conjuncts
        if isinstance(c, ir.Spatial) and c.prop == geom
        and c.op in _REGION_OPS
        and isinstance(c.geom, (geo.Polygon, geo.MultiPolygon))
    ]
    if len(polys) != 1:
        return None
    rest = [c for c in conjuncts if c is not polys[0]]
    if any(_has_spatial(c, geom) for c in rest):
        return None  # a second spatial constraint: not the region shape
    if not rest:
        residual: ir.Filter = ir.Include()
    elif len(rest) == 1:
        residual = rest[0]
    else:
        residual = ir.And(tuple(rest))
    return polys[0], residual


def _merge_runs(
    level: int, boundary: List[Tuple[int, int]]
) -> List[Box]:
    """Disjoint closed boxes covering exactly the boundary cells: per-row
    consecutive runs merge into one rectangle, so the residual scan's OR
    stays small."""
    by_row: Dict[int, List[int]] = {}
    for ix, iy in boundary:
        by_row.setdefault(iy, []).append(ix)
    out: List[Box] = []
    for iy in sorted(by_row):
        xs = sorted(by_row[iy])
        lo = prev = xs[0]
        for ix in xs[1:] + [None]:  # type: ignore[list-item]
            if ix is not None and ix == prev + 1:
                prev = ix
                continue
            b0 = cell_box(level, lo, iy)
            b1 = cell_box(level, prev, iy)
            out.append((b0[0], b0[1], b1[2], b1[3]))
            if ix is not None:
                lo = prev = ix
    return out


def decompose_region(f: ir.Filter, ft) -> Optional[RegionDecomposition]:
    """Polygon partial-cover plan: interior cells (cache-served — they
    share cell keys with bbox decompositions of the same residual) plus
    boundary cells (exact residual scan under the polygon predicate), or
    None when not decomposable. POINT geometries only, like
    :func:`decompose` (an extent feature straddles cells)."""
    if not config.CACHE_POLYGON.to_bool():
        return None
    geom = None if ft is None else ft.geom_field
    if geom is None or not ft.attr(geom).is_point:
        return None
    split = split_region_conjunct(f, geom)
    if split is None:
        return None
    spatial, residual = split
    xmin, ymin, xmax, ymax = spatial.geom.bounds()
    if not (
        np.isfinite([xmin, ymin, xmax, ymax]).all()
        and -180.0 <= xmin <= xmax <= 180.0
        and -90.0 <= ymin <= ymax <= 90.0
    ):
        return None
    level = _pick_level(xmax - xmin, ymax - ymin)
    if level is None:
        return None
    n = 1 << level
    sx, sy = 360.0 / n, 180.0 / n
    ix_lo = max(0, int(np.floor((xmin + 180.0) / sx)))
    ix_hi = min(n - 1, int(np.floor((xmax + 180.0) / sx)))
    iy_lo = max(0, int(np.floor((ymin + 90.0) / sy)))
    iy_hi = min(n - 1, int(np.floor((ymax + 90.0) / sy)))
    max_cells = config.CACHE_MAX_CELLS.to_int() or 256
    if (ix_hi - ix_lo + 1) * (iy_hi - iy_lo + 1) > max_cells:
        return None

    from geomesa_tpu.kernels import join as jk

    candidates = [
        (ix, iy)
        for iy in range(iy_lo, iy_hi + 1)
        for ix in range(ix_lo, ix_hi + 1)
    ]
    boxes = np.asarray(
        [cell_box(level, ix, iy) for ix, iy in candidates], np.float64
    )
    codes = jk.classify_cells(boxes, spatial.geom, CLASSIFY_MARGIN)
    cells = [c for c, k in zip(candidates, codes) if k == jk.CELL_INTERIOR]
    boundary = [c for c, k in zip(candidates, codes) if k == jk.CELL_BOUNDARY]
    if not cells:
        return None  # nothing reusable: whole-result caching is cheaper
    cell_boxes = {c: cell_box(level, *c) for c in cells}
    return RegionDecomposition(
        level=level, residual=residual, residual_key=repr(residual),
        cells=cells, cell_boxes=cell_boxes, spatial=spatial,
        boundary=boundary, boundary_boxes=_merge_runs(level, boundary),
    )
