"""Partial-cover decomposition: query bbox -> SFC cells + boundary strips.

The cacheable unit is a **grid cell** of the global 2^level x 2^level lon/lat
partition — the same cell family the z2 curve's prefix blocks quantize to
(``curves/zorder.interleave2(ix, iy)`` is each cell's curve prefix, used as
its identity), so cell keys are absolute: a panned query re-derives the same
cell ids for the overlap and pays only for the newly exposed strip
(GeoBlocks' query/cache decomposition over aggregate cells; PAPERS.md).

Exactness contract (what makes cached + fresh partials merge bit-identically
with a cold scan):

* cells are **half-open** ``[x0, x1) x [y0, y1)`` — realized as closed BBox
  predicates with the open edges pulled one f64 ulp inward — so the cells of
  a level partition the plane and no row is double-counted or dropped;
* the cell edges ``i * (360 / 2^level) - 180`` are exact in f64 (the cell
  span is 45 * 2^(3-level), a dyadic multiple), so every query derives
  byte-identical cell boxes;
* interior cells satisfy ``[x0, x1) x [y0, y1) ⊆ Q`` *by direct f64
  comparison against the query box*, so a cell query (residual ∧ cell box)
  returns exactly the query's rows inside that cell;
* the boundary Q \\ interior is covered by at most four disjoint strips
  (left/right full-height, bottom/top between them).

Decomposition applies when the schema's geometry is a POINT and the filter
constrains it with exactly one BBox conjunct at the top level (the pan/zoom
shape); anything richer — extent (line/polygon) geometry columns, whose
features intersect multiple cells and would be counted once per cell,
polygon query literals, spatial predicates under OR/NOT, multiple boxes —
falls back to whole-result caching, which is always safe.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from geomesa_tpu import config
from geomesa_tpu.filter import ir

Box = Tuple[float, float, float, float]


def _has_spatial(node: ir.Filter, geom: str) -> bool:
    """Does this subtree constrain (or even mention) the geometry?"""
    if isinstance(node, (ir.BBox, ir.Spatial, ir.DWithin)):
        return node.prop == geom
    if isinstance(node, (ir.And, ir.Or)):
        return any(_has_spatial(c, geom) for c in node.children)
    if isinstance(node, ir.Not):
        return _has_spatial(node.child, geom)
    if isinstance(node, ir.ExprCompare):
        return geom in node.props()
    prop = getattr(node, "prop", None)
    return prop == geom


def _prev(v: float) -> float:
    return float(np.nextafter(v, -np.inf))


@dataclass
class Decomposition:
    """One query's partial-cover plan."""

    level: int
    #: the filter minus the spatial conjunct (what cell queries AND with)
    residual: ir.Filter
    #: canonical text of the residual — part of every cell key
    residual_key: str
    #: interior cell ids, absolute (ix, iy) at ``level``
    cells: List[Tuple[int, int]]
    #: (ix, iy) -> closed BBox realizing the half-open cell
    cell_boxes: Dict[Tuple[int, int], Box]
    #: boundary strips (closed boxes, disjoint, covering Q minus interior)
    strips: List[Box]

    def cell_filter(self, cell: Tuple[int, int], geom: str) -> ir.Filter:
        b = self.cell_boxes[cell]
        return _and(self.residual, ir.BBox(geom, *b))

    def strip_filter(self, geom: str) -> Optional[ir.Filter]:
        if not self.strips:
            return None
        boxes = tuple(ir.BBox(geom, *s) for s in self.strips)
        spatial = boxes[0] if len(boxes) == 1 else ir.Or(boxes)
        return _and(self.residual, spatial)

    def cell_prefix(self, cell: Tuple[int, int]) -> int:
        """The cell's z2 curve prefix (its identity on the curve)."""
        from geomesa_tpu.curves.zorder import interleave2

        ix, iy = cell
        return int(interleave2(
            np.asarray([ix], np.uint64), np.asarray([iy], np.uint64)
        )[0])


def _and(residual: ir.Filter, spatial: ir.Filter) -> ir.Filter:
    if isinstance(residual, ir.Include):
        return spatial
    return ir.And((residual, spatial))


def split_bbox_conjunct(
    f: ir.Filter, geom: Optional[str]
) -> Optional[Tuple[ir.BBox, ir.Filter]]:
    """(bbox, residual) when the filter is `BBOX ∧ rest` with exactly one
    spatial constraint, all at top level; None otherwise."""
    if geom is None:
        return None
    conjuncts = list(f.children) if isinstance(f, ir.And) else [f]
    boxes = [c for c in conjuncts if isinstance(c, ir.BBox) and c.prop == geom]
    if len(boxes) != 1:
        return None
    rest = [c for c in conjuncts if c is not boxes[0]]
    if any(_has_spatial(c, geom) for c in rest):
        return None  # a second spatial constraint: not the pan/zoom shape
    if not rest:
        residual: ir.Filter = ir.Include()
    elif len(rest) == 1:
        residual = rest[0]
    else:
        residual = ir.And(tuple(rest))
    return boxes[0], residual


def _pick_level(dx: float, dy: float) -> Optional[int]:
    per_axis = config.CACHE_CELLS_PER_AXIS.to_int() or 8
    max_level = config.CACHE_MAX_LEVEL.to_int() or 12
    if dx <= 0 or dy <= 0:
        return None
    # finest level where the bbox spans at most per_axis cells on each axis
    lx = int(np.floor(np.log2(per_axis * 360.0 / dx)))
    ly = int(np.floor(np.log2(per_axis * 180.0 / dy)))
    level = min(lx, ly, max_level)
    return level if level >= 1 else None


def decompose(f: ir.Filter, ft) -> Optional[Decomposition]:
    """Partial-cover plan for a filter against schema ``ft``, or None when
    not decomposable. Only POINT geometries decompose: an extent feature
    (line/polygon) intersects every cell it straddles — the cells would
    each count it, breaking the disjoint-partition argument."""
    geom = None if ft is None else ft.geom_field
    if geom is None or not ft.attr(geom).is_point:
        return None
    split = split_bbox_conjunct(f, geom)
    if split is None:
        return None
    box, residual = split
    xmin, ymin, xmax, ymax = box.xmin, box.ymin, box.xmax, box.ymax
    if not (
        np.isfinite([xmin, ymin, xmax, ymax]).all()
        and -180.0 <= xmin <= xmax <= 180.0
        and -90.0 <= ymin <= ymax <= 90.0
    ):
        return None
    level = _pick_level(xmax - xmin, ymax - ymin)
    if level is None:
        return None
    n = 1 << level
    sx = 360.0 / n  # 45 * 2^(3-level): exact in f64
    sy = 180.0 / n

    def xedge(i: int) -> float:
        return i * sx - 180.0

    def yedge(i: int) -> float:
        return i * sy - 90.0

    # interior cells: [edge(i), edge(i+1)) ⊆ [min, max] by f64 comparison
    ix_lo = max(0, int(np.floor((xmin + 180.0) / sx)))
    ix_hi = min(n - 1, int(np.ceil((xmax + 180.0) / sx)))
    iy_lo = max(0, int(np.floor((ymin + 90.0) / sy)))
    iy_hi = min(n - 1, int(np.ceil((ymax + 90.0) / sy)))
    xs = [i for i in range(ix_lo, ix_hi + 1)
          if xedge(i) >= xmin and xedge(i + 1) <= xmax]
    ys = [i for i in range(iy_lo, iy_hi + 1)
          if yedge(i) >= ymin and yedge(i + 1) <= ymax]
    if not xs or not ys:
        return None
    max_cells = config.CACHE_MAX_CELLS.to_int() or 256
    if len(xs) * len(ys) > max_cells:
        return None
    # the interior index ranges are contiguous by construction
    X0, X1 = xedge(xs[0]), xedge(xs[-1] + 1)
    Y0, Y1 = yedge(ys[0]), yedge(ys[-1] + 1)

    cells: List[Tuple[int, int]] = []
    cell_boxes: Dict[Tuple[int, int], Box] = {}
    for iy in ys:
        for ix in xs:
            cells.append((ix, iy))
            cell_boxes[(ix, iy)] = (
                xedge(ix), yedge(iy), _prev(xedge(ix + 1)), _prev(yedge(iy + 1))
            )

    # Q \ interior as disjoint closed strips. The right strip is always
    # present: rows at exactly x == X1 (the interior's open edge) live there
    # even when X1 == xmax.
    strips: List[Box] = []
    if xmin < X0:
        strips.append((xmin, ymin, _prev(X0), ymax))          # left
    strips.append((X1, ymin, xmax, ymax))                     # right
    if ymin < Y0:
        strips.append((X0, ymin, _prev(X1), _prev(Y0)))       # bottom
    strips.append((X0, Y1, _prev(X1), ymax))                  # top
    strips = [s for s in strips if s[0] <= s[2] and s[1] <= s[3]]

    return Decomposition(
        level=level, residual=residual, residual_key=repr(residual),
        cells=cells, cell_boxes=cell_boxes, strips=strips,
    )
