"""S2 cell curve: Hilbert curve on the 6 faces of a cube projected onto the
sphere (reference S2SFC at geomesa-z3/.../S2SFC.scala:17, which wraps Google
S2's S2CellId/S2RegionCoverer; here the cell math is implemented directly as
vectorized numpy so point encoding is a batch kernel).

Cell id layout (Google S2-compatible): 3 face bits, 60 Hilbert position
bits, one trailing marker bit — a level-L cell's id has its marker at bit
2*(30-L); leaf cells (level 30) are odd. Tokens are the id's hex with
trailing zeros stripped.

The quadratic ST projection and the canonical Hilbert orientation tables
follow the published S2 geometry definition, so ids/tokens interoperate with
other S2 implementations.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from geomesa_tpu import config
from geomesa_tpu.curves.cover import ZRange, _merge

MAX_LEVEL = 30
POS_BITS = 2 * MAX_LEVEL + 1  # 61

# canonical Hilbert tables: traversal order per orientation
# orientation bits: 1 = swap i/j, 2 = invert
_POS_TO_IJ = np.array(
    [[0, 1, 3, 2], [0, 2, 3, 1], [3, 2, 0, 1], [3, 1, 0, 2]], np.int64
)
_IJ_TO_POS = np.array(
    [[0, 1, 3, 2], [0, 3, 1, 2], [2, 3, 1, 0], [2, 1, 3, 0]], np.int64
)
_POS_TO_ORI = np.array([1, 0, 0, 3], np.int64)  # swap, 0, 0, invert|swap


# -- projections ------------------------------------------------------------

def _lnglat_to_xyz(x, y):
    lam = np.radians(np.asarray(x, np.float64))
    phi = np.radians(np.asarray(y, np.float64))
    cphi = np.cos(phi)
    return cphi * np.cos(lam), cphi * np.sin(lam), np.sin(phi)


def _xyz_to_face_uv(px, py, pz):
    comps = np.stack([px, py, pz])
    f = np.argmax(np.abs(comps), axis=0)
    major = np.take_along_axis(comps, f[None], axis=0)[0]
    face = f + np.where(major < 0, 3, 0)
    # per-face (u, v) = ratios of the two minor axes over the major axis
    # (np.select evaluates all branches; zero divisors only occur in the
    # branches that are not selected)
    with np.errstate(divide="ignore", invalid="ignore"):
        u = np.select(
            [face == 0, face == 1, face == 2, face == 3, face == 4, face == 5],
            [py / px, -px / py, -px / pz, pz / px, pz / py, -py / pz],
        )
        v = np.select(
            [face == 0, face == 1, face == 2, face == 3, face == 4, face == 5],
            [pz / px, pz / py, -py / pz, py / px, -px / py, -px / pz],
        )
    return face.astype(np.int64), u, v


def _face_uv_to_xyz(face: int, u, v):
    if face == 0:
        return np.ones_like(u), u, v
    if face == 1:
        return -u, np.ones_like(u), v
    if face == 2:
        return -u, -v, np.ones_like(u)
    if face == 3:
        return -np.ones_like(u), -v, -u
    if face == 4:
        return v, -np.ones_like(u), -u
    return v, u, -np.ones_like(u)


def _uv_to_st(u):
    with np.errstate(invalid="ignore"):
        return np.where(
            u >= 0, 0.5 * np.sqrt(1 + 3 * u), 1 - 0.5 * np.sqrt(1 - 3 * u)
        )


def _st_to_uv(s):
    s = np.asarray(s, np.float64)
    return np.where(
        s >= 0.5, (1.0 / 3.0) * (4 * s * s - 1), (1.0 / 3.0) * (1 - 4 * (1 - s) ** 2)
    )


def _st_to_ij(s):
    return np.clip(
        (np.asarray(s, np.float64) * (1 << MAX_LEVEL)).astype(np.int64),
        0, (1 << MAX_LEVEL) - 1,
    )


# -- Hilbert encode/decode ---------------------------------------------------

def face_ij_to_id(face, i, j) -> np.ndarray:
    """(face, i, j) at leaf level -> uint64 cell id, vectorized."""
    face = np.asarray(face, np.int64)
    i = np.asarray(i, np.int64)
    j = np.asarray(j, np.int64)
    pos = np.zeros(face.shape, np.uint64)
    ori = face & 1  # initial orientation carries the face's swap bit
    for k in range(MAX_LEVEL - 1, -1, -1):
        ij = 2 * ((i >> k) & 1) + ((j >> k) & 1)
        p = _IJ_TO_POS[ori, ij]
        pos = (pos << np.uint64(2)) | p.astype(np.uint64)
        ori = ori ^ _POS_TO_ORI[p]
    return (
        (face.astype(np.uint64) << np.uint64(POS_BITS))
        | (pos << np.uint64(1))
        | np.uint64(1)
    )


def id_to_face_ij(ids) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """uint64 leaf cell ids -> (face, i, j), vectorized."""
    ids = np.asarray(ids, np.uint64)
    face = (ids >> np.uint64(POS_BITS)).astype(np.int64)
    pos = (ids >> np.uint64(1)) & np.uint64((1 << (2 * MAX_LEVEL)) - 1)
    i = np.zeros(ids.shape, np.int64)
    j = np.zeros(ids.shape, np.int64)
    ori = face & 1
    for k in range(MAX_LEVEL - 1, -1, -1):
        p = ((pos >> np.uint64(2 * k)) & np.uint64(3)).astype(np.int64)
        ij = _POS_TO_IJ[ori, p]
        i = (i << 1) | (ij >> 1)
        j = (j << 1) | (ij & 1)
        ori = ori ^ _POS_TO_ORI[p]
    return face, i, j


def lnglat_to_id(x, y) -> np.ndarray:
    """(lon, lat) degrees -> uint64 leaf cell ids (level 30), vectorized."""
    px, py, pz = _lnglat_to_xyz(np.atleast_1d(x), np.atleast_1d(y))
    face, u, v = _xyz_to_face_uv(px, py, pz)
    return face_ij_to_id(face, _st_to_ij(_uv_to_st(u)), _st_to_ij(_uv_to_st(v)))


def id_to_lnglat(ids) -> Tuple[np.ndarray, np.ndarray]:
    """Leaf cell ids -> (lon, lat) of the cell center."""
    face, i, j = id_to_face_ij(ids)
    s = (np.asarray(i, np.float64) + 0.5) / (1 << MAX_LEVEL)
    t = (np.asarray(j, np.float64) + 0.5) / (1 << MAX_LEVEL)
    u, v = _st_to_uv(s), _st_to_uv(t)
    out_x = np.empty(face.shape, np.float64)
    out_y = np.empty(face.shape, np.float64)
    for f in range(6):
        m = face == f
        if not m.any():
            continue
        px, py, pz = _face_uv_to_xyz(f, u[m], v[m])
        out_x[m] = np.degrees(np.arctan2(py, px))
        out_y[m] = np.degrees(np.arctan2(pz, np.hypot(px, py)))
    return out_x, out_y


# -- level / hierarchy ops ---------------------------------------------------

def lsb(ids) -> np.ndarray:
    ids = np.asarray(ids, np.uint64)
    return ids & (~ids + np.uint64(1))


def level_of(ids) -> np.ndarray:
    """Cell level (0..30)."""
    low = lsb(ids).astype(np.float64)
    return (MAX_LEVEL - (np.log2(low).astype(np.int64) >> 1)).astype(np.int64)


def parent(ids, level: int) -> np.ndarray:
    ids = np.asarray(ids, np.uint64)
    new_lsb = np.uint64(1 << (2 * (MAX_LEVEL - level)))
    return (ids & (~new_lsb + np.uint64(1))) | new_lsb


def range_min(ids) -> np.ndarray:
    ids = np.asarray(ids, np.uint64)
    return ids - (lsb(ids) - np.uint64(1))


def range_max(ids) -> np.ndarray:
    ids = np.asarray(ids, np.uint64)
    return ids + (lsb(ids) - np.uint64(1))


def contains(parent_ids, child_ids) -> np.ndarray:
    return (range_min(parent_ids) <= np.asarray(child_ids, np.uint64)) & (
        np.asarray(child_ids, np.uint64) <= range_max(parent_ids)
    )


def children(cid: int) -> List[int]:
    cid = int(cid)
    step = int(lsb(cid)) >> 2  # child cells' lsb
    if step == 0:
        return []
    return [cid + m * step for m in (-3, -1, 1, 3)]


def token(cid: int) -> str:
    s = f"{int(cid):016x}".rstrip("0")
    return s or "X"


def from_token(tok: str) -> int:
    return int(tok.ljust(16, "0"), 16)


def cell_corners(cid: int) -> np.ndarray:
    """[4, 2] (lon, lat) corners of a cell."""
    lo = int(range_min(cid))
    level = int(level_of(cid))
    face, i0, j0 = (int(a[0]) for a in id_to_face_ij([lo]))
    size = 1 << (MAX_LEVEL - level)
    # the first leaf in Hilbert order is *a* corner of the cell, not
    # necessarily the (min i, min j) one — mask down to the ij base corner
    i0 &= ~(size - 1)
    j0 &= ~(size - 1)
    corners = []
    for di, dj in ((0, 0), (size, 0), (size, size), (0, size)):
        s = (i0 + di) / (1 << MAX_LEVEL)
        t = (j0 + dj) / (1 << MAX_LEVEL)
        u, v = float(_st_to_uv(s)), float(_st_to_uv(t))
        px, py, pz = _face_uv_to_xyz(face, np.float64(u), np.float64(v))
        corners.append(
            (
                float(np.degrees(np.arctan2(py, px))),
                float(np.degrees(np.arctan2(pz, np.hypot(px, py)))),
            )
        )
    return np.asarray(corners)


class S2SFC:
    """Point -> S2 leaf id; bbox -> leaf-id range cover (S2RegionCoverer
    analog: BFS subdivision of intersecting cells under a cell budget)."""

    def __init__(self, min_level: int = 0, max_level: int = 30,
                 level_mod: int = 1, max_cells: int = 8):
        self.min_level = min_level
        self.max_level = max_level
        self.level_mod = max(1, level_mod)
        self.max_cells = max_cells

    def index(self, x, y) -> np.ndarray:
        x = np.asarray(x, np.float64)
        y = np.asarray(y, np.float64)
        if ((y < -90) | (y > 90)).any():
            raise ValueError("latitude out of range [-90, 90]")
        return lnglat_to_id(x, y)

    # -- covering ---------------------------------------------------------
    def _cell_latlng_bounds(self, cid: int) -> Tuple[float, float, float, float]:
        """Conservative (slightly padded) lon/lat bbox of a cell."""
        c = cell_corners(cid)
        level = int(level_of(cid))
        xs, ys = c[:, 0], c[:, 1]
        xmin, xmax = float(xs.min()), float(xs.max())
        ymin, ymax = float(ys.min()), float(ys.max())
        if xmax - xmin > 180.0:  # face wraps the antimeridian
            xmin, xmax = -180.0, 180.0
        # pole-adjacent cells: corners miss the pole; faces 2 (+z) and 5 (-z)
        # own the poles
        face = cid >> POS_BITS
        if level <= 1 and face == 2:
            ymax = 90.0
        if level <= 1 and face == 5:
            ymin = -90.0
        # curvature padding: cell edges bow outward in lat/lng by up to
        # ~11% of the edge span on low levels
        pad_x = (xmax - xmin) * 0.15
        pad_y = (ymax - ymin) * 0.15
        return (
            max(xmin - pad_x, -180.0), max(ymin - pad_y, -90.0),
            min(xmax + pad_x, 180.0), min(ymax + pad_y, 90.0),
        )

    def _tight_bounds(self, cid: int) -> Tuple[float, float, float, float]:
        """Under-approximated bbox (for the fully-inside test)."""
        c = cell_corners(cid)
        xs, ys = c[:, 0], c[:, 1]
        if float(xs.max() - xs.min()) > 180.0:
            return (0.0, 0.0, 0.0, 0.0)  # never 'fully inside'
        grow_x = (xs.max() - xs.min()) * 0.15
        grow_y = (ys.max() - ys.min()) * 0.15
        # the 'fully inside' box must OVER-estimate the cell so the test
        # never claims containment for a cell that sticks out
        return (
            float(xs.min() - grow_x), float(ys.min() - grow_y),
            float(xs.max() + grow_x), float(ys.max() + grow_y),
        )

    def ranges(self, xmin: float, ymin: float, xmax: float, ymax: float,
               max_cells: int = 0) -> List[ZRange]:
        """Leaf-id ranges covering a lon/lat bbox (never under-covers)."""
        budget = max_cells or self.max_cells or config.SCAN_RANGES_TARGET.to_int()
        query = (xmin, ymin, xmax, ymax)

        def intersects(b):
            return b[0] <= query[2] and b[2] >= query[0] and b[1] <= query[3] and b[3] >= query[1]

        def covered(b):
            return (
                query[0] <= b[0] and b[2] <= query[2]
                and query[1] <= b[1] and b[3] <= query[3]
            )

        out: List[int] = []
        frontier: List[int] = []
        for f in range(6):
            face_cell = (f << POS_BITS) | (1 << (POS_BITS - 1))
            if intersects(self._cell_latlng_bounds(face_cell)):
                frontier.append(face_cell)
        while frontier:
            cid = frontier.pop(0)
            level = int(level_of(cid))
            if (
                level >= self.max_level
                or (level >= self.min_level and covered(self._tight_bounds(cid)))
                or len(out) + len(frontier) >= budget
            ):
                out.append(cid)
                continue
            for ch in children(cid):
                # descend level_mod levels at a time where possible
                if intersects(self._cell_latlng_bounds(ch)):
                    frontier.append(ch)
        rngs = [ZRange(int(range_min(c)), int(range_max(c))) for c in out]
        return _merge(rngs)
