"""XZ-ordering curves for geometries with extent (polygons, lines).

Capability parity with the reference's XZ2SFC (geomesa-z3/.../XZ2SFC.scala:25)
and XZ3SFC (XZ3SFC.scala:26), which implement Böhm's XZ-ordering: an element is
stored at the quadtree/octree node whose cell contains the element's min corner
and whose *enlarged* (doubled-extent) cell contains the whole element. Node ids
are a preorder (DFS) numbering, so a subtree is one contiguous id range.

Everything here is host-side: `index()` is vectorized numpy over ingest
batches; `ranges()` is per-query plan-time traversal.
"""

from __future__ import annotations

from collections import deque
from typing import List, Tuple

import numpy as np

from geomesa_tpu.curves.binned_time import BinnedTime, TimePeriod
from geomesa_tpu.curves.cover import ZRange, _merge


class _XZBase:
    """Shared machinery for d-dimensional XZ ordering with resolution g."""

    def __init__(self, dims: int, g: int, los, his):
        self.d = dims
        self.g = g
        self.los = np.asarray(los, dtype=np.float64)
        self.his = np.asarray(his, dtype=np.float64)
        self.fan = 1 << dims  # children per node
        # subtree_size[depth] = node count of a subtree rooted at that depth
        # (inclusive), depth 0 = root. s(g) = 1; s(k) = 1 + fan*s(k+1).
        sizes = [0] * (g + 2)
        sizes[g] = 1
        for k in range(g - 1, -1, -1):
            sizes[k] = 1 + self.fan * sizes[k + 1]
        sizes[g + 1] = 0
        self.subtree_size = sizes

    # -- normalization ----------------------------------------------------
    def _norm(self, vals, k: int) -> np.ndarray:
        """Dim k float -> integer grid coordinate at resolution 2^g."""
        v = np.asarray(vals, dtype=np.float64)
        scaled = (v - self.los[k]) / (self.his[k] - self.los[k]) * (1 << self.g)
        return np.clip(np.floor(scaled), 0, (1 << self.g) - 1).astype(np.int64)

    def _norm_f(self, vals, k: int) -> np.ndarray:
        """Dim k float -> continuous [0, 2^g] grid coordinate (for fit tests)."""
        v = np.asarray(vals, dtype=np.float64)
        scaled = (v - self.los[k]) / (self.his[k] - self.los[k]) * (1 << self.g)
        return np.clip(scaled, 0.0, float(1 << self.g))

    # -- encode -----------------------------------------------------------
    def index_boxes(self, mins: List[np.ndarray], maxs: List[np.ndarray]) -> np.ndarray:
        """Vectorized: per-element bounding boxes -> XZ sequence codes (int64).

        ``mins[k]``/``maxs[k]`` are arrays of the k-th dim's bounds.
        """
        n = np.asarray(mins[0]).shape[0]
        fmins = [self._norm_f(mins[k], k) for k in range(self.d)]
        fmaxs = [self._norm_f(maxs[k], k) for k in range(self.d)]
        # Element's grid extent (in cells of size 1 at finest resolution 2^g).
        w = np.zeros(n, dtype=np.float64)
        for k in range(self.d):
            w = np.maximum(w, fmaxs[k] - fmins[k])
        # Deepest level whose cell side (2^(g-l) at finest units) >= ... an
        # element of extent w fits an enlarged cell at level l iff the doubled
        # cell (side 2*2^(g-l)) can contain it given the min corner lies in the
        # cell: sufficient & necessary check below mirrors XZ2SFC.scala:25ff.
        with np.errstate(divide="ignore"):
            l_guess = np.floor(-np.log2(np.maximum(w, 1e-300) / (1 << self.g))).astype(np.int64)
        l_guess = np.clip(l_guess, 0, self.g)
        # Verify fit at l_guess: the min corner's cell at level l must, when
        # doubled, contain the max corner; else back off one level.
        lvl = l_guess
        for _ in range(2):  # at most one back-off needed; loop twice for safety
            side = (1 << self.g) / (2.0 ** lvl)  # cell side in finest units
            fits = np.ones(n, dtype=bool)
            for k in range(self.d):
                cell_lo = np.floor(fmins[k] / side) * side
                fits &= fmaxs[k] <= cell_lo + 2 * side
            lvl = np.where(fits, lvl, np.maximum(lvl - 1, 0))
        # Sequence code: walk the tree to depth lvl following the min corner.
        imins = [np.minimum(np.floor(fmins[k]).astype(np.int64), (1 << self.g) - 1)
                 for k in range(self.d)]
        code = np.zeros(n, dtype=np.int64)
        for level in range(self.g):
            active = level < lvl
            bit_pos = self.g - 1 - level
            child = np.zeros(n, dtype=np.int64)
            for k in range(self.d):
                child = (child << 1) | ((imins[k] >> bit_pos) & 1)
            step = 1 + child * self.subtree_size[level + 1]
            code = np.where(active, code + step, code)
        return code

    # -- query ------------------------------------------------------------
    def ranges_box(self, qlo, qhi, max_ranges: int = 2000) -> List[ZRange]:
        """Sequence-code ranges of nodes whose elements may intersect [qlo,qhi].

        Emits whole-subtree ranges where every element in the subtree is
        guaranteed to intersect the query, and singleton ranges for boundary
        nodes (resolved by the downstream fine filter) — the same contract as
        XZ2SFC.ranges in the reference.
        """
        qlo = [self._norm_f([qlo[k]], k)[0] for k in range(self.d)]
        qhi = [self._norm_f([qhi[k]], k)[0] for k in range(self.d)]
        out: List[ZRange] = []
        # node: (code, depth, cell mins in finest units)
        frontier = deque([(0, 0, tuple([0.0] * self.d))])
        while frontier:
            code, depth, mins = frontier.popleft()
            side = (1 << self.g) / (2.0 ** depth)
            # Enlarged cell = doubled extent.
            if any(mins[k] > qhi[k] or mins[k] + 2 * side < qlo[k] for k in range(self.d)):
                continue  # no element in this subtree can touch the query
            if all(qlo[k] <= mins[k] and mins[k] + 2 * side <= qhi[k] for k in range(self.d)):
                # Every element in the subtree lies inside the query.
                out.append(ZRange(code, code + self.subtree_size[depth] - 1))
                continue
            out.append(ZRange(code, code))  # elements AT this node: maybe
            if depth == self.g:
                continue
            if len(out) + len(frontier) + self.fan > max_ranges:
                # Budget: over-cover remaining subtrees whole.
                out.append(ZRange(code, code + self.subtree_size[depth] - 1))
                while frontier:
                    c2, d2, m2 = frontier.popleft()
                    s2 = (1 << self.g) / (2.0 ** d2)
                    if any(m2[k] > qhi[k] or m2[k] + 2 * s2 < qlo[k] for k in range(self.d)):
                        continue
                    out.append(ZRange(c2, c2 + self.subtree_size[d2] - 1))
                break
            half = side / 2.0
            for combo in range(self.fan):
                c_mins = []
                for k in range(self.d):
                    bit = (combo >> (self.d - 1 - k)) & 1
                    c_mins.append(mins[k] + bit * half)
                frontier.append(
                    (code + 1 + combo * self.subtree_size[depth + 1], depth + 1, tuple(c_mins))
                )
        return _merge(out)


class XZ2SFC(_XZBase):
    """XZ ordering over (lon, lat) bounding boxes. Reference: XZ2SFC.scala:25."""

    def __init__(self, g: int = 12):
        super().__init__(dims=2, g=g, los=[-180.0, -90.0], his=[180.0, 90.0])

    def index(self, xmin, ymin, xmax, ymax) -> np.ndarray:
        return self.index_boxes([xmin, ymin], [xmax, ymax])

    def ranges(self, xmin: float, ymin: float, xmax: float, ymax: float,
               max_ranges: int = 2000) -> List[ZRange]:
        return self.ranges_box([xmin, ymin], [xmax, ymax], max_ranges)


class XZ3SFC(_XZBase):
    """XZ ordering over (lon, lat, binned-time-offset). Reference: XZ3SFC.scala:26.

    Like Z3, keys are per time-bin: the offset dimension spans one period.
    """

    def __init__(self, period: "str | TimePeriod" = TimePeriod.WEEK, g: int = 12):
        self.binned = BinnedTime(period)
        super().__init__(
            dims=3, g=g,
            los=[-180.0, -90.0, 0.0],
            his=[180.0, 90.0, float(self.binned.max_offset_ms)],
        )

    def index(self, xmin, ymin, tmin_off, xmax, ymax, tmax_off) -> np.ndarray:
        return self.index_boxes([xmin, ymin, tmin_off], [xmax, ymax, tmax_off])

    def ranges(self, xbounds, ybounds, tbounds_off, max_ranges: int = 2000) -> List[ZRange]:
        return self.ranges_box(
            [xbounds[0], ybounds[0], tbounds_off[0]],
            [xbounds[1], ybounds[1], tbounds_off[1]],
            max_ranges,
        )
