"""Space-filling-curve substrate (L0).

Capability parity with the reference's geomesa-z3 module (Z2SFC/Z3SFC/XZ2SFC/
XZ3SFC + BinnedTime, see SURVEY.md §2.1) but implemented TPU-first: encoding is
a vectorized numpy kernel on the host (ingest path) and an equivalent jnp kernel
on device; range cover runs on the host at plan time (small, per-query).
"""

from geomesa_tpu.curves.binned_time import BinnedTime, TimePeriod  # noqa: F401
from geomesa_tpu.curves.zorder import Z2SFC, Z3SFC, NormalizedDimension  # noqa: F401
from geomesa_tpu.curves.xz import XZ2SFC, XZ3SFC  # noqa: F401
