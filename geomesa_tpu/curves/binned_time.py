"""Epoch time -> (bin, offset) decomposition.

Capability parity with the reference's ``BinnedTime``
(geomesa-z3/.../curve/BinnedTime.scala:48-283): timestamps are split into a
coarse period bin (day/week/month/year since epoch) and a millisecond offset
within the bin. The bin becomes the leading component of the Z3 sort key; the
offset is the (normalized) time dimension of the Z3 curve.

All conversions are vectorized numpy — they run over whole ingest batches.
"""

from __future__ import annotations

import enum
from typing import Tuple

import numpy as np

DAY_MS = 86_400_000
WEEK_MS = 7 * DAY_MS
# Fixed maxima so the curve's time dimension has a static extent (the reference
# uses the same trick: max month = 31 days, max year = 366 days).
MONTH_MS = 31 * DAY_MS
YEAR_MS = 366 * DAY_MS


class TimePeriod(str, enum.Enum):
    DAY = "day"
    WEEK = "week"
    MONTH = "month"
    YEAR = "year"

    @staticmethod
    def parse(s: "str | TimePeriod") -> "TimePeriod":
        if isinstance(s, TimePeriod):
            return s
        return TimePeriod(str(s).strip().lower())


class BinnedTime:
    """Vectorized epoch-ms <-> (bin, offset-ms) codec for a time period."""

    def __init__(self, period: "str | TimePeriod" = TimePeriod.WEEK):
        self.period = TimePeriod.parse(period)

    @property
    def max_offset_ms(self) -> int:
        return {
            TimePeriod.DAY: DAY_MS,
            TimePeriod.WEEK: WEEK_MS,
            TimePeriod.MONTH: MONTH_MS,
            TimePeriod.YEAR: YEAR_MS,
        }[self.period]

    @property
    def off_scale(self) -> int:
        """Offset quantization (ms per unit) so a scaled offset fits int32 —
        the device time representation (no 64-bit ints on the TPU fast path).
        Day/week are exact (1 ms); month/year quantize to 4/16 ms."""
        return {
            TimePeriod.DAY: 1,
            TimePeriod.WEEK: 1,
            TimePeriod.MONTH: 4,
            TimePeriod.YEAR: 16,
        }[self.period]

    def to_scaled(self, epoch_ms: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """epoch_ms -> (bin int32, scaled-offset int32) device columns."""
        if self.period in (TimePeriod.DAY, TimePeriod.WEEK):
            from geomesa_tpu import native

            P = DAY_MS if self.period == TimePeriod.DAY else WEEK_MS
            out = native.time_split(
                np.asarray(epoch_ms, np.int64), P, self.off_scale,
                want_off_ms=False, want_scaled=True,
            )
            if out is not None:
                return out[0], out[2]
        b, off = self.to_bin_and_offset(epoch_ms)
        return b, (off // self.off_scale).astype(np.int32)

    def to_bin_and_offset(self, epoch_ms: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """epoch_ms (int64) -> (bin int32, offset_ms int64). Vectorized
        (one native pass for the fixed-width periods)."""
        t = np.asarray(epoch_ms, dtype=np.int64)
        if self.period in (TimePeriod.DAY, TimePeriod.WEEK):
            from geomesa_tpu import native

            P = DAY_MS if self.period == TimePeriod.DAY else WEEK_MS
            out = native.time_split(t, P, 1, want_off_ms=True)
            if out is not None:
                return out[0], out[1]
        if self.period == TimePeriod.DAY:
            b = np.floor_divide(t, DAY_MS)
            off = t - b * DAY_MS
        elif self.period == TimePeriod.WEEK:
            b = np.floor_divide(t, WEEK_MS)
            off = t - b * WEEK_MS
        elif self.period == TimePeriod.MONTH:
            dt = t.view(np.int64).astype("datetime64[ms]")
            months = dt.astype("datetime64[M]")
            b = months.astype(np.int64)  # months since 1970-01
            off = (dt - months).astype("timedelta64[ms]").astype(np.int64)
        else:  # YEAR
            dt = t.view(np.int64).astype("datetime64[ms]")
            years = dt.astype("datetime64[Y]")
            b = years.astype(np.int64)  # years since 1970
            off = (dt - years).astype("timedelta64[ms]").astype(np.int64)
        return b.astype(np.int32), off.astype(np.int64)

    def offset_from_bin(self, epoch_ms: np.ndarray, bins: np.ndarray) -> np.ndarray:
        """offset_ms given ALREADY-computed bins — one multiply/subtract pass
        instead of re-dividing (the ingest path computes bins once in
        encode_batch and reuses them for every key space)."""
        t = np.asarray(epoch_ms, dtype=np.int64)
        if self.period in (TimePeriod.DAY, TimePeriod.WEEK):
            from geomesa_tpu import native

            P = DAY_MS if self.period == TimePeriod.DAY else WEEK_MS
            out = native.off_from_bin(t, bins, P)
            if out is not None:
                return out
            # widen during the multiply (skips a separate astype copy)
            return t - np.multiply(bins, P, dtype=np.int64)
        return t - self.bin_start_ms(bins)

    def bin_start_ms(self, b: np.ndarray) -> np.ndarray:
        """bin -> epoch ms of the bin's start. Vectorized."""
        b = np.asarray(b)
        if self.period == TimePeriod.DAY:
            return b.astype(np.int64) * DAY_MS
        if self.period == TimePeriod.WEEK:
            return b.astype(np.int64) * WEEK_MS
        if self.period == TimePeriod.MONTH:
            return b.astype("datetime64[M]").astype("datetime64[ms]").astype(np.int64)
        return b.astype("datetime64[Y]").astype("datetime64[ms]").astype(np.int64)

    def bin_of(self, epoch_ms: int) -> int:
        b, _ = self.to_bin_and_offset(np.asarray([epoch_ms], dtype=np.int64))
        return int(b[0])

    def bins_between(self, lo_ms: int, hi_ms: int) -> np.ndarray:
        """All bins touched by [lo_ms, hi_ms] inclusive."""
        lo_b = self.bin_of(int(lo_ms))
        hi_b = self.bin_of(int(hi_ms))
        return np.arange(lo_b, hi_b + 1, dtype=np.int32)
