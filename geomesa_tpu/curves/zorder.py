"""Z-order (Morton) space-filling curves.

Capability parity with the reference's Z2SFC (geomesa-z3/.../curve/Z2SFC.scala:22,
31 bits/dim) and Z3SFC (Z3SFC.scala:22, 21 bits/dim + binned time), including the
bit-interleave kernels that the reference pulls from the external ``sfcurve``
library (declared at geomesa-z3/pom.xml:21) — implemented here from scratch.

Two implementations of the encode kernel:

* **Host (numpy, uint64)** — the ingest path. Encoding a batch of points is a
  handful of vectorized bit ops; this is where sort keys are computed before
  device upload.
* **Device (jnp, uint32 pair)** — JAX has no 64-bit ints without global x64 mode
  (and TPU prefers 32-bit lanes), so on device a z-value is an ``(hi, lo)``
  pair of uint32 arrays. Comparisons are lexicographic on the pair. The encode
  is a statically-unrolled bit-spread, fully vectorized over points.

Bit layout convention (matches the cover algorithm in ``cover.py``): for d
dimensions, bit ``i`` of dimension ``k`` (k=0 most significant) lands at
position ``d*i + (d-1-k)`` — i.e. within each group of d bits, dimension 0 is
the highest bit.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence, Tuple

import numpy as np

from geomesa_tpu import config
from geomesa_tpu.curves.binned_time import BinnedTime, TimePeriod
from geomesa_tpu.curves.cover import zcover_fast, ZRange


# ---------------------------------------------------------------------------
# Dimension normalization (reference: sfcurve NormalizedDimension; lossy
# fixed-point mapping of a float extent onto [0, 2^bits - 1]).
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class NormalizedDimension:
    lo: float
    hi: float
    bits: int

    @property
    def max_index(self) -> int:
        return (1 << self.bits) - 1

    def normalize(self, x: np.ndarray) -> np.ndarray:
        """float -> fixed-point index (clipped to the extent). Vectorized."""
        x = np.asarray(x, dtype=np.float64)
        scaled = (x - self.lo) / (self.hi - self.lo) * (1 << self.bits)
        return np.clip(np.floor(scaled), 0, self.max_index).astype(np.uint64)

    def denormalize(self, i: np.ndarray) -> np.ndarray:
        """fixed-point index -> cell-center float. Vectorized."""
        i = np.asarray(i, dtype=np.float64)
        return self.lo + (i + 0.5) * (self.hi - self.lo) / (1 << self.bits)


# ---------------------------------------------------------------------------
# Host bit-interleave kernels (numpy uint64, vectorized)
# ---------------------------------------------------------------------------

def _split2(x: np.ndarray) -> np.ndarray:
    """Spread the low 31 bits of x so bit i lands at position 2i (uint64)."""
    x = np.asarray(x, dtype=np.uint64) & np.uint64(0x7FFFFFFF)
    x = (x | (x << np.uint64(16))) & np.uint64(0x0000FFFF0000FFFF)
    x = (x | (x << np.uint64(8))) & np.uint64(0x00FF00FF00FF00FF)
    x = (x | (x << np.uint64(4))) & np.uint64(0x0F0F0F0F0F0F0F0F)
    x = (x | (x << np.uint64(2))) & np.uint64(0x3333333333333333)
    x = (x | (x << np.uint64(1))) & np.uint64(0x5555555555555555)
    return x


def _combine2(z: np.ndarray) -> np.ndarray:
    """Inverse of _split2: gather every 2nd bit (starting at 0) down."""
    z = np.asarray(z, dtype=np.uint64) & np.uint64(0x5555555555555555)
    z = (z | (z >> np.uint64(1))) & np.uint64(0x3333333333333333)
    z = (z | (z >> np.uint64(2))) & np.uint64(0x0F0F0F0F0F0F0F0F)
    z = (z | (z >> np.uint64(4))) & np.uint64(0x00FF00FF00FF00FF)
    z = (z | (z >> np.uint64(8))) & np.uint64(0x0000FFFF0000FFFF)
    z = (z | (z >> np.uint64(16))) & np.uint64(0x00000000FFFFFFFF)
    return z


def _split3(x: np.ndarray) -> np.ndarray:
    """Spread the low 21 bits of x so bit i lands at position 3i (uint64)."""
    x = np.asarray(x, dtype=np.uint64) & np.uint64(0x1FFFFF)
    x = (x | (x << np.uint64(32))) & np.uint64(0x1F00000000FFFF)
    x = (x | (x << np.uint64(16))) & np.uint64(0x1F0000FF0000FF)
    x = (x | (x << np.uint64(8))) & np.uint64(0x100F00F00F00F00F)
    x = (x | (x << np.uint64(4))) & np.uint64(0x10C30C30C30C30C3)
    x = (x | (x << np.uint64(2))) & np.uint64(0x1249249249249249)
    return x


def _combine3(z: np.ndarray) -> np.ndarray:
    """Inverse of _split3: gather every 3rd bit (starting at 0) down."""
    z = np.asarray(z, dtype=np.uint64) & np.uint64(0x1249249249249249)
    z = (z | (z >> np.uint64(2))) & np.uint64(0x10C30C30C30C30C3)
    z = (z | (z >> np.uint64(4))) & np.uint64(0x100F00F00F00F00F)
    z = (z | (z >> np.uint64(8))) & np.uint64(0x1F0000FF0000FF)
    z = (z | (z >> np.uint64(16))) & np.uint64(0x1F00000000FFFF)
    z = (z | (z >> np.uint64(32))) & np.uint64(0x1FFFFF)
    return z


def _interleave2_np(x: np.ndarray, y: np.ndarray) -> np.ndarray:
    return (_split2(x) << np.uint64(1)) | _split2(y)


def _deinterleave2_np(z: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    return _combine2(np.asarray(z, np.uint64) >> np.uint64(1)), _combine2(z)


def _interleave3_np(x: np.ndarray, y: np.ndarray, t: np.ndarray) -> np.ndarray:
    return (_split3(x) << np.uint64(2)) | (_split3(y) << np.uint64(1)) | _split3(t)


def _deinterleave3_np(z: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    z = np.asarray(z, np.uint64)
    return (
        _combine3(z >> np.uint64(2)),
        _combine3(z >> np.uint64(1)),
        _combine3(z),
    )


# Native-dispatch threshold: below this the ctypes call overhead dominates.
_NATIVE_MIN = 8192


def _use_native(n: int) -> bool:
    if n < _NATIVE_MIN:
        return False
    from geomesa_tpu import native

    return native.available()


def interleave2(x: np.ndarray, y: np.ndarray) -> np.ndarray:
    """Morton-interleave two 31-bit indices; x occupies the higher bit of
    each pair. Bulk batches go through the native runtime (ingest hot path:
    the numpy spread is 6 full passes with temporaries; C++ does one)."""
    x = np.asarray(x, np.uint64)
    if _use_native(len(x)):
        from geomesa_tpu import native

        return native.interleave2(x, y)
    return _interleave2_np(x, np.asarray(y, np.uint64))


def deinterleave2(z: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    z = np.asarray(z, np.uint64)
    if _use_native(len(z)):
        from geomesa_tpu import native

        return native.deinterleave2(z)
    return _deinterleave2_np(z)


def interleave3(x: np.ndarray, y: np.ndarray, t: np.ndarray) -> np.ndarray:
    """Morton-interleave three 21-bit indices; x highest within each triple."""
    x = np.asarray(x, np.uint64)
    if _use_native(len(x)):
        from geomesa_tpu import native

        return native.interleave3(x, y, t)
    return _interleave3_np(x, np.asarray(y, np.uint64), np.asarray(t, np.uint64))


def deinterleave3(z: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    z = np.asarray(z, np.uint64)
    if _use_native(len(z)):
        from geomesa_tpu import native

        return native.deinterleave3(z)
    return _deinterleave3_np(z)


# ---------------------------------------------------------------------------
# Device encode kernels (jnp; z as (hi, lo) uint32 pair)
# ---------------------------------------------------------------------------

def device_interleave(dims, bits: int):
    """jnp Morton interleave of ``d`` int32 arrays (each < 2**bits) into a
    (hi, lo) uint32 pair. Statically unrolled — ~3*bits vector ops, fused by XLA.

    ``dims[0]`` is the most-significant dimension within each bit group
    (matches :func:`interleave2` / :func:`interleave3`).
    """
    import jax.numpy as jnp

    d = len(dims)
    dims = [jnp.asarray(v).astype(jnp.uint32) for v in dims]
    lo = jnp.zeros_like(dims[0])
    hi = jnp.zeros_like(dims[0])
    one = jnp.uint32(1)
    for i in range(bits):
        for k in range(d):
            pos = d * i + (d - 1 - k)
            bit = (dims[k] >> jnp.uint32(i)) & one
            if pos < 32:
                lo = lo | (bit << jnp.uint32(pos))
            else:
                hi = hi | (bit << jnp.uint32(pos - 32))
    return hi, lo


def pair_lex_lte(a_hi, a_lo, b_hi, b_lo):
    """Lexicographic (a <= b) on uint32 pairs — the device z-compare."""
    return (a_hi < b_hi) | ((a_hi == b_hi) & (a_lo <= b_lo))


def pair_lex_gte(a_hi, a_lo, b_hi, b_lo):
    return (a_hi > b_hi) | ((a_hi == b_hi) & (a_lo >= b_lo))


def split_u64(z: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Host uint64 z -> (hi, lo) uint32 columns for device upload."""
    z = np.asarray(z, dtype=np.uint64)
    return (z >> np.uint64(32)).astype(np.uint32), (z & np.uint64(0xFFFFFFFF)).astype(np.uint32)


def join_u64(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    return (np.asarray(hi, np.uint64) << np.uint64(32)) | np.asarray(lo, np.uint64)


# ---------------------------------------------------------------------------
# Curves
# ---------------------------------------------------------------------------

class Z2SFC:
    """2D Z-order curve over (lon, lat), 31 bits per dimension.

    Reference: geomesa-z3/.../curve/Z2SFC.scala:15-22.
    """

    BITS = 31

    def __init__(self):
        self.lon = NormalizedDimension(-180.0, 180.0, self.BITS)
        self.lat = NormalizedDimension(-90.0, 90.0, self.BITS)

    def index(self, x: np.ndarray, y: np.ndarray) -> np.ndarray:
        """(lon, lat) -> z (uint64). Vectorized (fused native single pass
        when the library is built; numpy normalize+interleave otherwise)."""
        from geomesa_tpu import native

        out = native.z2_encode(np.asarray(x, np.float64), np.asarray(y, np.float64))
        if out is not None:
            return out
        return interleave2(self.lon.normalize(x), self.lat.normalize(y))

    def invert(self, z: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        xi, yi = deinterleave2(z)
        return self.lon.denormalize(xi), self.lat.denormalize(yi)

    def ranges(
        self,
        xmin: float,
        ymin: float,
        xmax: float,
        ymax: float,
        max_ranges: int = None,
    ) -> List[ZRange]:
        """Cover the bbox with z-ranges (host-side, plan time)."""
        if max_ranges is None:
            max_ranges = config.SCAN_RANGES_TARGET.to_int()
        lo = (int(self.lon.normalize(xmin)), int(self.lat.normalize(ymin)))
        hi = (int(self.lon.normalize(xmax)), int(self.lat.normalize(ymax)))
        return zcover_fast(lo, hi, bits=self.BITS, dims=2, max_ranges=max_ranges)


class Z3SFC:
    """3D Z-order curve over (lon, lat, time-offset-in-bin), 21 bits per dim.

    Reference: geomesa-z3/.../curve/Z3SFC.scala:22-54 (time extent depends on
    the schema's time period; offsets are normalized into 21 bits).
    """

    BITS = 21

    def __init__(self, period: "str | TimePeriod" = TimePeriod.WEEK):
        self.binned = BinnedTime(period)
        self.lon = NormalizedDimension(-180.0, 180.0, self.BITS)
        self.lat = NormalizedDimension(-90.0, 90.0, self.BITS)
        self.time = NormalizedDimension(0.0, float(self.binned.max_offset_ms), self.BITS)

    def index(self, x: np.ndarray, y: np.ndarray, t_offset_ms: np.ndarray) -> np.ndarray:
        """(lon, lat, offset-ms-within-bin) -> z (uint64). Vectorized (fused
        native single pass when available)."""
        from geomesa_tpu import native

        t = np.asarray(t_offset_ms)
        if t.dtype.kind in "iu":
            out = native.z3_encode(
                np.asarray(x, np.float64), np.asarray(y, np.float64),
                t.astype(np.int64, copy=False), float(self.binned.max_offset_ms),
            )
            if out is not None:
                return out
        return interleave3(
            self.lon.normalize(x), self.lat.normalize(y), self.time.normalize(t_offset_ms)
        )

    def invert(self, z: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        xi, yi, ti = deinterleave3(z)
        return (
            self.lon.denormalize(xi),
            self.lat.denormalize(yi),
            self.time.denormalize(ti),
        )

    def ranges(
        self,
        xbounds: Tuple[float, float],
        ybounds: Tuple[float, float],
        tbounds_ms: Tuple[float, float],
        max_ranges: int = None,
    ) -> List[ZRange]:
        """Cover (bbox × time-offset-window) with z-ranges (host, plan time)."""
        if max_ranges is None:
            max_ranges = config.SCAN_RANGES_TARGET.to_int()
        lo = (
            int(self.lon.normalize(xbounds[0])),
            int(self.lat.normalize(ybounds[0])),
            int(self.time.normalize(tbounds_ms[0])),
        )
        hi = (
            int(self.lon.normalize(xbounds[1])),
            int(self.lat.normalize(ybounds[1])),
            int(self.time.normalize(tbounds_ms[1])),
        )
        return zcover_fast(lo, hi, bits=self.BITS, dims=3, max_ranges=max_ranges)
