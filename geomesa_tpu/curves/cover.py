"""Z-range cover: decompose an axis-aligned query box into Morton-order ranges.

Host-side, per-query planning code — the analog of ``sfcurve``'s ``zranges``
used by the reference's key spaces (e.g. geomesa-z3/.../Z3SFC.scala:54 ->
Z3IndexKeySpace.getRanges, geomesa-index-api/.../z3/Z3IndexKeySpace.scala:162).

Algorithm: BFS over z-prefix cells. A cell at level L fixes the top L bits of
every dimension; its z-values form the contiguous block
``[prefix·0…0, prefix·1…1]``. Cells fully inside the query box emit their whole
block; intersecting cells are subdivided until ``max_ranges`` would be
exceeded, at which point remaining frontier cells are emitted whole
(over-covering — correctness comes from the downstream fine filter, exactly as
in the reference). Adjacent/overlapping ranges are merged.

Bit layout matches ``zorder.py``: for d dims, bit i of dim k sits at
``d*i + (d-1-k)``.
"""

from __future__ import annotations

from collections import deque
from typing import List, NamedTuple, Sequence, Tuple


class ZRange(NamedTuple):
    lo: int  # inclusive
    hi: int  # inclusive


def _merge(ranges: List[ZRange]) -> List[ZRange]:
    if not ranges:
        return []
    ranges.sort()
    out = [ranges[0]]
    for r in ranges[1:]:
        last = out[-1]
        if r.lo <= last.hi + 1:
            if r.hi > last.hi:
                out[-1] = ZRange(last.lo, r.hi)
        else:
            out.append(r)
    return out


def zcover_fast(
    lo: Sequence[int],
    hi: Sequence[int],
    bits: int,
    dims: int,
    max_ranges: int = 2000,
) -> List[ZRange]:
    """Cover via the native runtime when built, else the Python BFS below.

    Semantics are identical (parity enforced by tests/test_native.py); the
    native path exists because cover is the one per-query host loop whose cost
    grows with range budget (SURVEY.md §3.1 'pathological polygons')."""
    from geomesa_tpu import native

    return native.zcover(lo, hi, bits, dims, max_ranges)


def zcover(
    lo: Sequence[int],
    hi: Sequence[int],
    bits: int,
    dims: int,
    max_ranges: int = 2000,
) -> List[ZRange]:
    """Cover the integer box [lo, hi] (inclusive, per-dim) with z-ranges.

    ``lo``/``hi`` are normalized fixed-point coordinates (0 .. 2^bits-1).
    Returns merged, sorted, inclusive [lo, hi] z-value ranges (ints; values fit
    in ``dims*bits`` <= 63 bits).
    """
    d = dims
    total_bits = d * bits
    qlo = [int(v) for v in lo]
    qhi = [int(v) for v in hi]
    for k in range(d):
        if qlo[k] > qhi[k]:
            raise ValueError(f"inverted query box on dim {k}: {qlo[k]} > {qhi[k]}")

    # Frontier entries: (zmin, level, mins, maxs) where mins/maxs are the
    # cell's per-dim coordinate bounds and zmin its smallest z-value.
    full = (1 << bits) - 1
    frontier = deque([(0, 0, tuple([0] * d), tuple([full] * d))])
    out: List[ZRange] = []

    def cell_span(level: int) -> int:
        return (1 << (d * (bits - level))) - 1  # number of z values in cell - 1

    while frontier:
        zmin, level, mins, maxs = frontier.popleft()
        # Disjoint?
        if any(maxs[k] < qlo[k] or mins[k] > qhi[k] for k in range(d)):
            continue
        # Fully contained?
        if all(qlo[k] <= mins[k] and maxs[k] <= qhi[k] for k in range(d)):
            out.append(ZRange(zmin, zmin + cell_span(level)))
            continue
        # At max depth: emit (single z value).
        if level == bits:
            out.append(ZRange(zmin, zmin))
            continue
        # Budget check: if splitting would exceed the budget, emit frontier whole.
        if len(out) + len(frontier) + (1 << d) > max_ranges:
            out.append(ZRange(zmin, zmin + cell_span(level)))
            while frontier:
                zm, lv, mn, mx = frontier.popleft()
                if any(mx[k] < qlo[k] or mn[k] > qhi[k] for k in range(d)):
                    continue
                out.append(ZRange(zm, zm + cell_span(lv)))
            break
        # Subdivide: fix the next bit (bit index b = bits-1-level) of each dim.
        b = bits - 1 - level
        half = 1 << b
        group_shift = d * b  # position of this level's d-bit group in z
        for combo in range(1 << d):
            c_mins, c_maxs = [], []
            zadd = 0
            for k in range(d):
                # dim k's bit within the group is at offset (d-1-k)
                bit = (combo >> (d - 1 - k)) & 1
                if bit:
                    c_mins.append(mins[k] + half)
                    c_maxs.append(maxs[k])
                    zadd |= 1 << (group_shift + (d - 1 - k))
                else:
                    c_mins.append(mins[k])
                    c_maxs.append(maxs[k] - half)
            frontier.append((zmin + zadd, level + 1, tuple(c_mins), tuple(c_maxs)))

    return _merge(out)
