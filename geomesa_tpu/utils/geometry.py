"""Pure-numpy geometry substrate (no JTS/GEOS/shapely dependency).

Role parity with the reference's JTS usage (SURVEY.md §2.1 "Geometry utils"):
WKT parse/format, bounds, rectangularity, point-in-polygon, and distance — the
operations the filter compiler and processes need. Plan-time ops are scalar
Python/numpy; predicate evaluation is exposed as **padded vertex/edge buffers**
so the same test runs vectorized on device (N points × E edges).

Coordinates are (x=lon, y=lat) degrees, matching the reference's default CRS
handling (EPSG:4326).
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

EARTH_RADIUS_M = 6_371_008.8
# meters per degree of latitude (used for degree<->meter conversions in
# DWITHIN, mirroring GeoTools' approximate geodesic handling for 4326)
METERS_PER_DEGREE = 111_319.49079327358


class Geometry:
    kind: str = "geometry"

    def bounds(self) -> Tuple[float, float, float, float]:
        """(xmin, ymin, xmax, ymax)"""
        raise NotImplementedError

    def wkt(self) -> str:
        raise NotImplementedError

    def contains_points(self, xs: np.ndarray, ys: np.ndarray) -> np.ndarray:
        """Vectorized point-membership test (boundary-inclusive)."""
        raise NotImplementedError


@dataclass(frozen=True)
class Point(Geometry):
    x: float
    y: float
    kind = "point"

    def bounds(self):
        return (self.x, self.y, self.x, self.y)

    def wkt(self):
        return f"POINT ({_fmt(self.x)} {_fmt(self.y)})"

    def contains_points(self, xs, ys):
        return (np.asarray(xs) == self.x) & (np.asarray(ys) == self.y)


@dataclass(frozen=True)
class MultiPoint(Geometry):
    points: Tuple[Point, ...]
    kind = "multipoint"

    def bounds(self):
        xs = [p.x for p in self.points]
        ys = [p.y for p in self.points]
        return (min(xs), min(ys), max(xs), max(ys))

    def wkt(self):
        inner = ", ".join(f"({_fmt(p.x)} {_fmt(p.y)})" for p in self.points)
        return f"MULTIPOINT ({inner})"

    def contains_points(self, xs, ys):
        m = np.zeros(len(np.asarray(xs)), dtype=bool)
        for p in self.points:
            m |= p.contains_points(xs, ys)
        return m


@dataclass(frozen=True)
class LineString(Geometry):
    coords: Tuple[Tuple[float, float], ...]  # ((x, y), ...)
    kind = "linestring"

    def bounds(self):
        a = np.asarray(self.coords)
        return (a[:, 0].min(), a[:, 1].min(), a[:, 0].max(), a[:, 1].max())

    def wkt(self):
        inner = ", ".join(f"{_fmt(x)} {_fmt(y)}" for x, y in self.coords)
        return f"LINESTRING ({inner})"

    def contains_points(self, xs, ys):
        # Points exactly on a segment; rarely used as a predicate — epsilon test.
        xs, ys = np.asarray(xs, np.float64), np.asarray(ys, np.float64)
        m = np.zeros(xs.shape, dtype=bool)
        a = np.asarray(self.coords)
        for i in range(len(a) - 1):
            m |= _on_segment(xs, ys, a[i], a[i + 1])
        return m


@dataclass(frozen=True)
class MultiLineString(Geometry):
    lines: Tuple["LineString", ...]
    kind = "multilinestring"

    def bounds(self):
        bs = np.asarray([ls.bounds() for ls in self.lines])
        return (float(bs[:, 0].min()), float(bs[:, 1].min()),
                float(bs[:, 2].max()), float(bs[:, 3].max()))

    def wkt(self):
        def seg(ls: "LineString"):
            return "(" + ", ".join(f"{_fmt(x)} {_fmt(y)}" for x, y in ls.coords) + ")"

        return "MULTILINESTRING (" + ", ".join(seg(ls) for ls in self.lines) + ")"

    def contains_points(self, xs, ys):
        m = np.zeros(np.asarray(xs).shape, dtype=bool)
        for ls in self.lines:
            m |= ls.contains_points(xs, ys)
        return m


@dataclass(frozen=True)
class Polygon(Geometry):
    shell: Tuple[Tuple[float, float], ...]  # closed or open ring
    holes: Tuple[Tuple[Tuple[float, float], ...], ...] = ()
    kind = "polygon"

    def bounds(self):
        a = np.asarray(self.shell)
        return (float(a[:, 0].min()), float(a[:, 1].min()),
                float(a[:, 0].max()), float(a[:, 1].max()))

    def wkt(self):
        def ring(r):
            r = _close_ring(r)
            return "(" + ", ".join(f"{_fmt(x)} {_fmt(y)}" for x, y in r) + ")"

        inner = ", ".join([ring(self.shell)] + [ring(h) for h in self.holes])
        return f"POLYGON ({inner})"

    def rings(self) -> List[np.ndarray]:
        return [np.asarray(_close_ring(self.shell), np.float64)] + [
            np.asarray(_close_ring(h), np.float64) for h in self.holes
        ]

    def is_rectangle(self) -> bool:
        """Axis-aligned rectangle test — enables the reference's loose-bbox
        fast path (Z3IndexKeySpace.useFullFilter:235)."""
        if self.holes:
            return False
        r = np.asarray(_close_ring(self.shell), np.float64)
        if len(r) != 5:
            return False
        xmin, ymin, xmax, ymax = self.bounds()
        corners = {(xmin, ymin), (xmin, ymax), (xmax, ymin), (xmax, ymax)}
        return {(float(x), float(y)) for x, y in r[:4]} == corners

    def contains_points(self, xs, ys):
        xs, ys = np.asarray(xs, np.float64), np.asarray(ys, np.float64)
        inside = _ring_contains(np.asarray(_close_ring(self.shell), np.float64), xs, ys)
        for h in self.holes:
            hr = np.asarray(_close_ring(h), np.float64)
            inside &= ~_ring_contains_open(hr, xs, ys)
        return inside


@dataclass(frozen=True)
class MultiPolygon(Geometry):
    polygons: Tuple[Polygon, ...]
    kind = "multipolygon"

    def bounds(self):
        bs = np.asarray([p.bounds() for p in self.polygons])
        return (float(bs[:, 0].min()), float(bs[:, 1].min()),
                float(bs[:, 2].max()), float(bs[:, 3].max()))

    def wkt(self):
        def poly(p: Polygon):
            return p.wkt()[len("POLYGON "):]

        return "MULTIPOLYGON (" + ", ".join(poly(p) for p in self.polygons) + ")"

    def contains_points(self, xs, ys):
        m = np.zeros(np.asarray(xs).shape, dtype=bool)
        for p in self.polygons:
            m |= p.contains_points(xs, ys)
        return m


# ---------------------------------------------------------------------------
# Ring membership (crossing number + boundary inclusion), vectorized
# ---------------------------------------------------------------------------

def _close_ring(r: Sequence[Tuple[float, float]]):
    r = list(r)
    if r[0] != r[-1]:
        r = r + [r[0]]
    return tuple(tuple(p) for p in r)


def _ring_crossings(ring: np.ndarray, xs: np.ndarray, ys: np.ndarray) -> np.ndarray:
    """Crossing-number parity: True where (x, y) is strictly inside the ring."""
    x1, y1 = ring[:-1, 0], ring[:-1, 1]
    x2, y2 = ring[1:, 0], ring[1:, 1]
    xs = xs[:, None]
    ys = ys[:, None]
    cond = (y1[None, :] > ys) != (y2[None, :] > ys)
    with np.errstate(divide="ignore", invalid="ignore"):
        xint = x1[None, :] + (ys - y1[None, :]) * (x2 - x1)[None, :] / np.where(
            (y2 - y1)[None, :] == 0, 1.0, (y2 - y1)[None, :]
        )
    crossings = (cond & (xs < xint)).sum(axis=1)
    return (crossings % 2) == 1


def _on_boundary(ring: np.ndarray, xs: np.ndarray, ys: np.ndarray) -> np.ndarray:
    m = np.zeros(xs.shape, dtype=bool)
    for i in range(len(ring) - 1):
        m |= _on_segment(xs, ys, ring[i], ring[i + 1])
    return m


def _on_segment(xs, ys, a, b, eps: float = 1e-12) -> np.ndarray:
    ax, ay = float(a[0]), float(a[1])
    bx, by = float(b[0]), float(b[1])
    cross = (bx - ax) * (ys - ay) - (by - ay) * (xs - ax)
    within = (
        (xs >= min(ax, bx) - eps) & (xs <= max(ax, bx) + eps)
        & (ys >= min(ay, by) - eps) & (ys <= max(ay, by) + eps)
    )
    scale = max(abs(bx - ax), abs(by - ay), 1.0)
    return within & (np.abs(cross) <= eps * scale)


def _ring_contains(ring: np.ndarray, xs, ys) -> np.ndarray:
    """Boundary-inclusive containment (ECQL CONTAINS/INTERSECTS semantics)."""
    return _ring_crossings(ring, xs, ys) | _on_boundary(ring, xs, ys)


def _ring_contains_open(ring: np.ndarray, xs, ys) -> np.ndarray:
    """Strict interior (points on a hole's boundary remain in the polygon)."""
    return _ring_crossings(ring, xs, ys) & ~_on_boundary(ring, xs, ys)


# ---------------------------------------------------------------------------
# Padded edge buffers: the device representation of polygon predicates
# ---------------------------------------------------------------------------

def polygon_edge_buffers(geom: Geometry, pad_to: Optional[int] = None):
    """Flatten a (Multi)Polygon into padded edge arrays for the device PIP
    kernel: returns dict of float32 arrays ``x1,y1,x2,y2`` (shape [E]),
    ``ring_sign`` (+1 shell, -1 hole), and int32 ``poly_id`` per edge.

    The device kernel computes, per polygon, crossing parity over shell edges
    minus hole edges; padding edges are degenerate (zero-length at NaN-safe
    coords) and contribute no crossings.
    """
    polys = geom.polygons if isinstance(geom, MultiPolygon) else (geom,)
    x1s, y1s, x2s, y2s, signs, pids = [], [], [], [], [], []
    for pid, p in enumerate(polys):
        rings = [(np.asarray(_close_ring(p.shell), np.float64), 1)] + [
            (np.asarray(_close_ring(h), np.float64), -1) for h in p.holes
        ]
        for ring, sign in rings:
            x1s.append(ring[:-1, 0]); y1s.append(ring[:-1, 1])
            x2s.append(ring[1:, 0]); y2s.append(ring[1:, 1])
            signs.append(np.full(len(ring) - 1, sign, np.int32))
            pids.append(np.full(len(ring) - 1, pid, np.int32))
    out = {
        "x1": np.concatenate(x1s), "y1": np.concatenate(y1s),
        "x2": np.concatenate(x2s), "y2": np.concatenate(y2s),
        "sign": np.concatenate(signs), "poly_id": np.concatenate(pids),
        "n_polys": len(polys),
    }
    e = len(out["x1"])
    target = pad_to or e
    if target > e:
        padn = target - e
        for k in ("x1", "y1", "x2", "y2"):
            out[k] = np.concatenate([out[k], np.full(padn, 1e30)])
        out["sign"] = np.concatenate([out["sign"], np.zeros(padn, np.int32)])
        out["poly_id"] = np.concatenate([out["poly_id"], np.zeros(padn, np.int32)])
    return out


# ---------------------------------------------------------------------------
# Distance
# ---------------------------------------------------------------------------

def haversine_m(x1, y1, x2, y2):
    """Great-circle distance in meters, vectorized (degrees in)."""
    rx1, ry1, rx2, ry2 = (np.radians(np.asarray(v, np.float64)) for v in (x1, y1, x2, y2))
    dlat = ry2 - ry1
    dlon = rx2 - rx1
    a = np.sin(dlat / 2) ** 2 + np.cos(ry1) * np.cos(ry2) * np.sin(dlon / 2) ** 2
    return 2 * EARTH_RADIUS_M * np.arcsin(np.sqrt(np.clip(a, 0, 1)))


# ---------------------------------------------------------------------------
# WKT
# ---------------------------------------------------------------------------

def _fmt(v: float) -> str:
    # shortest round-trip representation: WKT is the master store for
    # extent geometries, so formatting must never lose f64 precision
    # (exact-predicate refinement parses it back)
    return repr(float(v))


_NUM = r"[-+]?\d+(?:\.\d+)?(?:[eE][-+]?\d+)?"


def parse_wkt(text: str) -> Geometry:
    """Parse POINT / MULTIPOINT / LINESTRING / POLYGON / MULTIPOLYGON WKT."""
    s = text.strip()
    m = re.match(r"^\s*([A-Za-z]+)\s*(.*)$", s, re.S)
    if not m:
        raise ValueError(f"invalid WKT: {text!r}")
    tag = m.group(1).upper()
    body = m.group(2).strip()

    def coords(chunk: str):
        pts = []
        for pair in chunk.split(","):
            nums = re.findall(_NUM, pair)
            if len(nums) < 2:
                raise ValueError(f"invalid WKT coordinates: {pair!r}")
            pts.append((float(nums[0]), float(nums[1])))
        return tuple(pts)

    def rings(chunk: str):
        out = []
        for rm in re.finditer(r"\(([^()]*)\)", chunk):
            out.append(coords(rm.group(1)))
        return out

    if tag == "POINT":
        nums = re.findall(_NUM, body)
        return Point(float(nums[0]), float(nums[1]))
    if tag == "MULTIPOINT":
        pts = coords(body.replace("(", " ").replace(")", " "))
        return MultiPoint(tuple(Point(x, y) for x, y in pts))
    if tag == "LINESTRING":
        return LineString(coords(body.strip("() ")))
    if tag == "MULTILINESTRING":
        return MultiLineString(tuple(LineString(r) for r in rings(body)))
    if tag == "POLYGON":
        rs = rings(body)
        if not rs:
            raise ValueError(f"invalid POLYGON WKT: {text!r}")
        return Polygon(rs[0], tuple(rs[1:]))
    if tag == "MULTIPOLYGON":
        # strip the outer wrapper paren, then split polygon groups by
        # balanced parens at depth 0
        first, last = body.find("("), body.rfind(")")
        if first < 0 or last <= first:
            raise ValueError(f"invalid MULTIPOLYGON WKT: {text!r}")
        body = body[first + 1 : last]
        polys = []
        depth = 0
        start = None
        for i, ch in enumerate(body):
            if ch == "(":
                if depth == 0:
                    start = i
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    rs = rings(body[start + 1 : i])
                    polys.append(Polygon(rs[0], tuple(rs[1:])))
        if not polys:
            raise ValueError(f"invalid MULTIPOLYGON WKT: {text!r}")
        return MultiPolygon(tuple(polys))
    if tag == "ENVELOPE":  # ECQL extension: ENVELOPE(xmin, xmax, ymin, ymax)
        nums = [float(v) for v in re.findall(_NUM, body)]
        xmin, xmax, ymin, ymax = nums[:4]
        return bbox_polygon(xmin, ymin, xmax, ymax)
    raise ValueError(f"unsupported WKT type: {tag}")


def bbox_polygon(xmin: float, ymin: float, xmax: float, ymax: float) -> Polygon:
    return Polygon(((xmin, ymin), (xmax, ymin), (xmax, ymax), (xmin, ymax), (xmin, ymin)))


def bounds_intersect(a, b) -> bool:
    return a[0] <= b[2] and a[2] >= b[0] and a[1] <= b[3] and a[3] >= b[1]
