"""Result reprojection (the CRS half of GeoTools Query semantics).

Reference parity: the reference reprojects query results as the LAST
post-processing step (QueryPlanner.runQuery's reduce -> sort -> limit ->
reproject chain, geomesa-index-api/.../planning/QueryPlanner.scala:68-90),
delegating the math to GeoTools' referencing module. Storage stays
EPSG:4326 (like the reference's indices, which normalize to lon/lat for
the space-filling curves); a query may ask for results in another CRS.

This module ships closed-form transforms for the CRS pair that covers
web mapping (EPSG:4326 <-> EPSG:3857 spherical mercator) behind a small
registry, so additional projections plug in without touching the query
path. Transforms are vectorized numpy (and jit-able: pure ufunc math)."""

from __future__ import annotations

import math
from typing import Callable, Dict, Tuple

import numpy as np

#: spherical-mercator earth radius (EPSG:3857 definition)
R = 6378137.0

#: 3857's valid latitude band; beyond it the projection diverges
MAX_LAT = 85.051128779806604


def to_mercator(x, y, xp=np):
    """EPSG:4326 lon/lat degrees -> EPSG:3857 meters."""
    mx = x * (math.pi / 180.0) * R
    yc = xp.clip(y, -MAX_LAT, MAX_LAT)
    my = xp.log(xp.tan((90.0 + yc) * (math.pi / 360.0))) * R
    return mx, my


def from_mercator(mx, my, xp=np):
    """EPSG:3857 meters -> EPSG:4326 lon/lat degrees."""
    x = mx / R * (180.0 / math.pi)
    y = (2.0 * xp.arctan(xp.exp(my / R)) - math.pi / 2.0) * (180.0 / math.pi)
    return x, y


_TRANSFORMS: Dict[Tuple[int, int], Callable] = {
    (4326, 3857): to_mercator,
    (3857, 4326): from_mercator,
}


def register(src: int, dst: int, fn: Callable) -> None:
    """Plug in a transform ``fn(x, y, xp) -> (x', y')``."""
    _TRANSFORMS[(src, dst)] = fn


def transformer(src: int, dst: int) -> Callable:
    """The (x, y, xp) -> (x', y') transform, or raise for unknown pairs."""
    if src == dst:
        return lambda x, y, xp=np: (x, y)
    fn = _TRANSFORMS.get((src, dst))
    if fn is None:
        known = sorted({c for pair in _TRANSFORMS for c in pair})
        raise ValueError(
            f"no transform EPSG:{src} -> EPSG:{dst} (built-in codes: "
            f"{known}; register one via utils.reproject.register)"
        )
    return fn


def reproject_wkt(wkt: str, fn: Callable) -> str:
    """Transform every vertex of a WKT geometry (slow path for extent
    geometry columns; point columns transform vectorized)."""
    from geomesa_tpu.utils.geometry import parse_wkt

    g = parse_wkt(wkt)
    return _rebuild(g, fn).wkt()


def _rebuild(g, fn):
    from geomesa_tpu.utils import geometry as geo

    def pt(x, y):
        nx, ny = fn(np.asarray([x]), np.asarray([y]))
        return float(nx[0]), float(ny[0])

    def ring(r):
        a = np.asarray(r, np.float64)
        xs, ys = fn(a[:, 0], a[:, 1])
        return tuple((float(x), float(y)) for x, y in zip(xs, ys))

    if isinstance(g, geo.Point):
        return geo.Point(*pt(g.x, g.y))
    if isinstance(g, geo.MultiPoint):
        return geo.MultiPoint(
            tuple(geo.Point(*pt(p.x, p.y)) for p in g.points)
        )
    if isinstance(g, geo.LineString):
        return geo.LineString(ring(g.coords))
    if isinstance(g, geo.MultiLineString):
        return geo.MultiLineString(
            tuple(geo.LineString(ring(ls.coords)) for ls in g.lines)
        )
    if isinstance(g, geo.Polygon):
        return geo.Polygon(
            ring(g.shell), tuple(ring(h) for h in g.holes)
        )
    if isinstance(g, geo.MultiPolygon):
        return geo.MultiPolygon(tuple(
            geo.Polygon(ring(p.shell), tuple(ring(h) for h in p.holes))
            for p in g.polygons
        ))
    raise ValueError(f"cannot reproject geometry type {type(g).__name__}")
