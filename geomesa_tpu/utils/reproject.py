"""Result reprojection (the CRS half of GeoTools Query semantics).

Reference parity: the reference reprojects query results as the LAST
post-processing step (QueryPlanner.runQuery's reduce -> sort -> limit ->
reproject chain, geomesa-index-api/.../planning/QueryPlanner.scala:68-90),
delegating the math to GeoTools' referencing module. Storage stays
EPSG:4326 (like the reference's indices, which normalize to lon/lat for
the space-filling curves); a query may ask for results in another CRS.

CRS coverage, in resolution order:

1. explicitly registered pairs (``register``),
2. ``pyproj`` when importable (any EPSG code, both directions),
3. built-in closed-form ellipsoidal projections — vectorized numpy,
   accurate to sub-mm against the published formulas:
   - EPSG:3857 spherical web mercator,
   - EPSG:3395 world mercator (ellipsoidal),
   - EPSG:32601-32660 / 32701-32760 UTM north/south (transverse
     mercator via the order-6 Krueger series, GeographicLib's method),
   - EPSG:5070 CONUS Albers equal-area conic,
   - EPSG:3035 ETRS89-extended LAEA Europe.

Any (src, dst) pair between covered codes composes through EPSG:4326
(inverse of src, then forward of dst), so ``Query.srid`` works both for
output reprojection and for ingesting foreign-CRS coordinates.
"""

from __future__ import annotations

import math
import warnings
from typing import Callable, Dict, Optional, Tuple

import numpy as np

#: spherical-mercator earth radius (EPSG:3857 definition)
R = 6378137.0

#: 3857's valid latitude band; beyond it the projection diverges
MAX_LAT = 85.051128779806604

# WGS84 / GRS80 ellipsoids (GRS80 flattening differs in the 11th digit;
# NAD83/ETRS89 vs WGS84 datum shift is sub-meter and ignored, as is
# standard for web-scale work)
_A_WGS84 = 6378137.0
_F_WGS84 = 1.0 / 298.257223563
_F_GRS80 = 1.0 / 298.257222101


def to_mercator(x, y, xp=np):
    """EPSG:4326 lon/lat degrees -> EPSG:3857 meters.

    Latitudes beyond the projection's +/-85.051 degree band are clamped to
    the edge (the projection diverges at the poles); a RuntimeWarning is
    emitted when that happens so callers can detect the lossy relocation
    (the GeoTools referencing path the reference delegates to does not
    silently move coordinates)."""
    if xp is np and np.any(np.abs(np.asarray(y)) > MAX_LAT):
        warnings.warn(
            "EPSG:3857 is undefined beyond +/-85.051 degrees latitude; "
            "poleward coordinates were clamped to the projection edge",
            RuntimeWarning, stacklevel=2,
        )
    mx = x * (math.pi / 180.0) * R
    yc = xp.clip(y, -MAX_LAT, MAX_LAT)
    my = xp.log(xp.tan((90.0 + yc) * (math.pi / 360.0))) * R
    return mx, my


def from_mercator(mx, my, xp=np):
    """EPSG:3857 meters -> EPSG:4326 lon/lat degrees."""
    x = mx / R * (180.0 / math.pi)
    y = (2.0 * xp.arctan(xp.exp(my / R)) - math.pi / 2.0) * (180.0 / math.pi)
    return x, y


# -- ellipsoidal projection machinery ------------------------------------
# Formulas: Snyder, "Map Projections: A Working Manual" (USGS PP 1395)
# for Mercator/Albers/LAEA; Karney, "Transverse Mercator with an accuracy
# of a few nanometers" (J. Geod 2011) for the Krueger series TM.


def _asf(v, xp):
    """Float array in the backend's widest float: f64 on numpy, the
    default float under jax (f32 unless x64 is enabled — requesting f64
    there would just warn and truncate)."""
    return xp.asarray(v, np.float64) if xp is np else xp.asarray(
        v, dtype=float)


class _Ellipsoid:
    def __init__(self, a: float, f: float):
        self.a = a
        self.f = f
        self.e2 = f * (2.0 - f)
        self.e = math.sqrt(self.e2)
        self.n = f / (2.0 - f)


_WGS84 = _Ellipsoid(_A_WGS84, _F_WGS84)
_GRS80 = _Ellipsoid(_A_WGS84, _F_GRS80)


def _merc_ell(ell: _Ellipsoid):
    """Ellipsoidal Mercator (EPSG:9804/3395): closed-form forward,
    fixed-point conformal-latitude inverse."""
    a, e = ell.a, ell.e

    def fwd(lon, lat, xp=np):
        lam = xp.radians(_asf(lon, xp))
        phi = xp.radians(xp.clip(_asf(lat, xp),
                                 -89.999999, 89.999999))
        s = xp.sin(phi)
        x = a * lam
        y = a * xp.log(xp.tan(math.pi / 4 + phi / 2)
                       * ((1 - e * s) / (1 + e * s)) ** (e / 2))
        return x, y

    def inv(x, y, xp=np):
        lam = _asf(x, xp) / a
        t = xp.exp(-_asf(y, xp) / a)
        phi = math.pi / 2 - 2 * xp.arctan(t)
        for _ in range(8):
            s = xp.sin(phi)
            phi = math.pi / 2 - 2 * xp.arctan(
                t * ((1 - e * s) / (1 + e * s)) ** (e / 2)
            )
        return xp.degrees(lam), xp.degrees(phi)

    return fwd, inv


def _authalic_q(ell: _Ellipsoid, phi, xp=np):
    """Snyder's q (3-12): 2x the authalic-latitude sine scale factor."""
    e, e2 = ell.e, ell.e2
    s = xp.sin(phi)
    return (1 - e2) * (s / (1 - e2 * s * s)
                       - (1 / (2 * e)) * xp.log((1 - e * s) / (1 + e * s)))


def _phi_from_authalic_q(ell: _Ellipsoid, q, xp=np):
    """Invert Snyder's q by Newton iteration (3-16); shared by the
    equal-area projections (Albers, LAEA)."""
    e, e2 = ell.e, ell.e2
    phi = xp.arcsin(xp.clip(q / 2, -1, 1))
    for _ in range(6):
        s = xp.sin(phi)
        phi = phi + ((1 - e2 * s * s) ** 2 / (2 * xp.cos(phi))) * (
            q / (1 - e2) - s / (1 - e2 * s * s)
            + (1 / (2 * e)) * xp.log((1 - e * s) / (1 + e * s))
        )
    return phi


def _tm_krueger(ell: _Ellipsoid, lon0: float, k0: float,
                fe: float, fn_: float):
    """Transverse Mercator via the order-6 Krueger series in the
    conformal-latitude / Gauss-Schreiber plane (Karney 2011, eq. 35-36;
    the method GeographicLib uses — good to nanometers within the UTM
    band, far beyond the f32->f64 needs here)."""
    n = ell.n
    n2, n3, n4, n5, n6 = n * n, n ** 3, n ** 4, n ** 5, n ** 6
    A = ell.a / (1 + n) * (1 + n2 / 4 + n4 / 64 + n6 / 256)
    alpha = (
        n / 2 - 2 * n2 / 3 + 5 * n3 / 16 + 41 * n4 / 180
        - 127 * n5 / 288 + 7891 * n6 / 37800,
        13 * n2 / 48 - 3 * n3 / 5 + 557 * n4 / 1440 + 281 * n5 / 630
        - 1983433 * n6 / 1935360,
        61 * n3 / 240 - 103 * n4 / 140 + 15061 * n5 / 26880
        + 167603 * n6 / 181440,
        49561 * n4 / 161280 - 179 * n5 / 168 + 6601661 * n6 / 7257600,
        34729 * n5 / 80640 - 3418889 * n6 / 1995840,
        212378941 * n6 / 319334400,
    )
    beta = (
        n / 2 - 2 * n2 / 3 + 37 * n3 / 96 - n4 / 360 - 81 * n5 / 512
        + 96199 * n6 / 604800,
        n2 / 48 + n3 / 15 - 437 * n4 / 1440 + 46 * n5 / 105
        - 1118711 * n6 / 3870720,
        17 * n3 / 480 - 37 * n4 / 840 - 209 * n5 / 4480
        + 5569 * n6 / 90720,
        4397 * n4 / 161280 - 11 * n5 / 504 - 830251 * n6 / 7257600,
        4583 * n5 / 161280 - 108847 * n6 / 3991680,
        20648693 * n6 / 638668800,
    )
    e = ell.e
    lam0 = math.radians(lon0)

    def fwd(lon, lat, xp=np):
        lam = xp.radians(_asf(lon, xp)) - lam0
        phi = xp.radians(xp.clip(_asf(lat, xp),
                                 -89.999999, 89.999999))
        s = xp.sin(phi)
        # conformal latitude: tau' = sinh(asinh(tan) - e atanh(e sin))
        tau = xp.tan(phi)
        taup = xp.sinh(xp.arcsinh(tau) - e * xp.arctanh(e * s))
        cl = xp.cos(lam)
        xi_p = xp.arctan2(taup, cl)
        eta_p = xp.arcsinh(xp.sin(lam) / xp.sqrt(taup * taup + cl * cl))
        xi, eta = xi_p, eta_p
        for j, aj in enumerate(alpha, start=1):
            xi = xi + aj * xp.sin(2 * j * xi_p) * xp.cosh(2 * j * eta_p)
            eta = eta + aj * xp.cos(2 * j * xi_p) * xp.sinh(2 * j * eta_p)
        return fe + k0 * A * eta, fn_ + k0 * A * xi

    def inv(x, y, xp=np):
        eta = (_asf(x, xp) - fe) / (k0 * A)
        xi = (_asf(y, xp) - fn_) / (k0 * A)
        xi_p, eta_p = xi, eta
        for j, bj in enumerate(beta, start=1):
            xi_p = xi_p - bj * xp.sin(2 * j * xi) * xp.cosh(2 * j * eta)
            eta_p = eta_p - bj * xp.cos(2 * j * xi) * xp.sinh(2 * j * eta)
        sh, cx = xp.sinh(eta_p), xp.cos(xi_p)
        taup = xp.sin(xi_p) / xp.sqrt(sh * sh + cx * cx)
        # invert the conformal latitude by Newton on tau'(tau)
        tau = taup
        for _ in range(6):
            s = tau / xp.sqrt(1 + tau * tau)
            f_val = xp.sinh(xp.arcsinh(tau) - e * xp.arctanh(e * s)) - taup
            # d tau'/d tau
            df = (xp.cosh(xp.arcsinh(tau) - e * xp.arctanh(e * s))
                  * (1 - ell.e2) / ((1 - ell.e2 * s * s)
                                    * xp.sqrt(1 + tau * tau)))
            tau = tau - f_val / df
        phi = xp.arctan(tau)
        lam = xp.arctan2(sh, cx)
        return xp.degrees(lam + lam0), xp.degrees(phi)

    return fwd, inv


def _albers(ell: _Ellipsoid, lat1: float, lat2: float, lat0: float,
            lon0: float, fe: float, fn_: float):
    """Albers equal-area conic (Snyder 14-1..14-21), ellipsoidal."""
    a, e2 = ell.a, ell.e2

    def m_of(phi):
        s = np.sin(phi)
        return np.cos(phi) / np.sqrt(1 - e2 * s * s)

    p1, p2, p0 = (math.radians(v) for v in (lat1, lat2, lat0))
    lam0 = math.radians(lon0)
    m1, m2 = m_of(np.float64(p1)), m_of(np.float64(p2))
    q1 = _authalic_q(ell, np.float64(p1))
    q2 = _authalic_q(ell, np.float64(p2))
    q0 = _authalic_q(ell, np.float64(p0))
    nc = (m1 * m1 - m2 * m2) / (q2 - q1)
    C = m1 * m1 + nc * q1
    rho0 = a * np.sqrt(C - nc * q0) / nc

    def fwd(lon, lat, xp=np):
        lam = xp.radians(_asf(lon, xp)) - lam0
        phi = xp.radians(_asf(lat, xp))
        q = _authalic_q(ell, phi, xp)
        rho = a * xp.sqrt(xp.maximum(C - nc * q, 0.0)) / nc
        th = nc * lam
        return fe + rho * xp.sin(th), fn_ + rho0 - rho * xp.cos(th)

    def inv(x, y, xp=np):
        xr = _asf(x, xp) - fe
        yr = rho0 - (_asf(y, xp) - fn_)
        rho = xp.sqrt(xr * xr + yr * yr)
        th = xp.arctan2(np.sign(nc) * xr, np.sign(nc) * yr)
        q = (C - (rho * nc / a) ** 2) / nc
        phi = _phi_from_authalic_q(ell, q, xp)
        return xp.degrees(lam0 + th / nc), xp.degrees(phi)

    return fwd, inv


def _laea(ell: _Ellipsoid, lat0: float, lon0: float, fe: float, fn_: float):
    """Lambert azimuthal equal-area, oblique ellipsoidal (Snyder 24-2..26)."""
    a, e2 = ell.a, ell.e2

    p0 = math.radians(lat0)
    lam0 = math.radians(lon0)
    qp = float(_authalic_q(ell, np.float64(math.pi / 2)))
    q0 = float(_authalic_q(ell, np.float64(p0)))
    beta0 = math.asin(q0 / qp)
    Rq = a * math.sqrt(qp / 2)
    s0 = math.sin(p0)
    m0 = math.cos(p0) / math.sqrt(1 - e2 * s0 * s0)
    D = a * m0 / (Rq * math.cos(beta0))
    sb0, cb0 = math.sin(beta0), math.cos(beta0)

    def fwd(lon, lat, xp=np):
        lam = xp.radians(_asf(lon, xp)) - lam0
        phi = xp.radians(_asf(lat, xp))
        beta = xp.arcsin(xp.clip(_authalic_q(ell, phi, xp) / qp, -1, 1))
        sb, cb = xp.sin(beta), xp.cos(beta)
        denom = 1 + sb0 * sb + cb0 * cb * xp.cos(lam)
        B = Rq * xp.sqrt(2 / denom)
        x = fe + B * D * cb * xp.sin(lam)
        y = fn_ + (B / D) * (cb0 * sb - sb0 * cb * xp.cos(lam))
        return x, y

    def inv(x, y, xp=np):
        xr = (_asf(x, xp) - fe) / D
        yr = (_asf(y, xp) - fn_) * D
        rho = xp.sqrt(xr * xr + yr * yr)
        ce = 2 * xp.arcsin(xp.clip(rho / (2 * Rq), -1, 1))
        sc, cc = xp.sin(ce), xp.cos(ce)
        # guard the rho=0 center point (0/0); xp.where keeps it jit-safe
        safe_rho = xp.where(rho > 0, rho, 1.0)
        q = qp * (cc * sb0 + xp.where(rho > 0,
                                      yr * sc * cb0 / safe_rho, 0.0))
        lam = xp.arctan2(xr * sc, rho * cb0 * cc - yr * sb0 * sc)
        phi = _phi_from_authalic_q(ell, q, xp)
        phi = xp.where(rho > 0, phi, p0)
        lam = xp.where(rho > 0, lam, 0.0)
        return xp.degrees(lam0 + lam), xp.degrees(phi)

    return fwd, inv


def _builtin_projection(code: int):
    """(forward, inverse) 4326<->code for built-in closed forms, else None."""
    if code == 3857:
        return (lambda x, y, xp=np: to_mercator(x, y, xp),
                lambda x, y, xp=np: from_mercator(x, y, xp))
    if code == 3395:
        return _merc_ell(_WGS84)
    if 32601 <= code <= 32660:  # UTM north, WGS84
        zone = code - 32600
        return _tm_krueger(_WGS84, -183.0 + 6.0 * zone, 0.9996, 500000.0, 0.0)
    if 32701 <= code <= 32760:  # UTM south, WGS84
        zone = code - 32700
        return _tm_krueger(_WGS84, -183.0 + 6.0 * zone, 0.9996, 500000.0,
                           10000000.0)
    if code == 5070:  # NAD83 / Conus Albers
        return _albers(_GRS80, 29.5, 45.5, 23.0, -96.0, 0.0, 0.0)
    if code == 3035:  # ETRS89-extended / LAEA Europe
        return _laea(_GRS80, 52.0, 10.0, 4321000.0, 3210000.0)
    return None


def _pyproj_transform(src: int, dst: int) -> Optional[Callable]:
    try:
        from pyproj import Transformer
    except ImportError:
        return None
    try:
        tr = Transformer.from_crs(f"EPSG:{src}", f"EPSG:{dst}",
                                  always_xy=True)
    except Exception:
        return None

    def fn(x, y, xp=np):
        return tr.transform(np.asarray(x, np.float64),
                            np.asarray(y, np.float64))

    return fn


_TRANSFORMS: Dict[Tuple[int, int], Callable] = {
    (4326, 3857): to_mercator,
    (3857, 4326): from_mercator,
}


def register(src: int, dst: int, fn: Callable) -> None:
    """Plug in a transform ``fn(x, y, xp) -> (x', y')``."""
    _TRANSFORMS[(src, dst)] = fn


#: EPSG codes outside the UTM ranges with built-in closed-form support
_BUILTIN_CODES = (4326, 3857, 3395, 5070, 3035)


def supported_codes() -> Tuple[int, ...]:
    """EPSG codes with built-in closed-form support (plus anything
    pyproj can resolve when installed)."""
    return _BUILTIN_CODES + tuple(range(32601, 32661)) + tuple(
        range(32701, 32761))


def transformer(src: int, dst: int) -> Callable:
    """The (x, y, xp) -> (x', y') transform, or raise for unknown pairs.

    Resolution order: registered pairs, built-in closed forms (composed
    through 4326 when neither side is 4326), then pyproj (if installed)
    for codes with no closed form. Built-ins win over pyproj so the
    vectorized, jit-able (x, y, xp) contract holds regardless of what is
    installed."""
    if src == dst:
        return lambda x, y, xp=np: (x, y)
    fn = _TRANSFORMS.get((src, dst))
    if fn is not None:
        return fn
    to_geo = None if src == 4326 else _builtin_projection(src)
    from_geo = None if dst == 4326 else _builtin_projection(dst)
    if (src == 4326 or to_geo is not None) and (
            dst == 4326 or from_geo is not None):
        def composed(x, y, xp=np, _inv=to_geo, _fwd=from_geo):
            if _inv is not None:
                x, y = _inv[1](x, y, xp)
            if _fwd is not None:
                x, y = _fwd[0](x, y, xp)
            return x, y

        _TRANSFORMS[(src, dst)] = composed
        return composed
    fn = _pyproj_transform(src, dst)
    if fn is not None:
        _TRANSFORMS[(src, dst)] = fn
        return fn
    known = sorted({c for pair in _TRANSFORMS for c in pair}
                   | set(_BUILTIN_CODES))
    raise ValueError(
        f"no transform EPSG:{src} -> EPSG:{dst} (built-in codes: "
        f"{known} + UTM 326xx/327xx; install pyproj for arbitrary codes "
        f"or register one via utils.reproject.register)"
    )


# -- WKT reprojection ----------------------------------------------------

def reproject_wkt(wkt: str, fn: Callable) -> str:
    """Transform every vertex of one WKT geometry. Prefer
    ``reproject_wkt_array`` for columns — it batches all vertices of all
    geometries into a single transform call."""
    out = reproject_wkt_array(np.array([wkt], dtype=object), fn)
    return out[0]


def _collect_arrays(g, out: list) -> None:
    """Append every coordinate array of geometry ``g`` to ``out`` in the
    same deterministic order ``_rebuild_from`` consumes them."""
    from geomesa_tpu.utils import geometry as geo

    if isinstance(g, geo.Point):
        out.append(np.array([[g.x, g.y]], np.float64))
    elif isinstance(g, geo.MultiPoint):
        out.append(np.array([[p.x, p.y] for p in g.points], np.float64))
    elif isinstance(g, geo.LineString):
        out.append(np.asarray(g.coords, np.float64).reshape(-1, 2))
    elif isinstance(g, geo.MultiLineString):
        for ls in g.lines:
            out.append(np.asarray(ls.coords, np.float64).reshape(-1, 2))
    elif isinstance(g, geo.Polygon):
        out.append(np.asarray(g.shell, np.float64).reshape(-1, 2))
        for h in g.holes:
            out.append(np.asarray(h, np.float64).reshape(-1, 2))
    elif isinstance(g, geo.MultiPolygon):
        for p in g.polygons:
            out.append(np.asarray(p.shell, np.float64).reshape(-1, 2))
            for h in p.holes:
                out.append(np.asarray(h, np.float64).reshape(-1, 2))
    else:
        raise ValueError(f"cannot reproject geometry type {type(g).__name__}")


def _rebuild_from(g, chunks) -> object:
    """Rebuild ``g`` consuming transformed (k, 2) arrays from ``chunks``
    (an iterator) in ``_collect_arrays`` order."""
    from geomesa_tpu.utils import geometry as geo

    def tup(a):
        return tuple((float(x), float(y)) for x, y in a)

    if isinstance(g, geo.Point):
        a = next(chunks)
        return geo.Point(float(a[0, 0]), float(a[0, 1]))
    if isinstance(g, geo.MultiPoint):
        a = next(chunks)
        return geo.MultiPoint(tuple(
            geo.Point(float(x), float(y)) for x, y in a))
    if isinstance(g, geo.LineString):
        return geo.LineString(tup(next(chunks)))
    if isinstance(g, geo.MultiLineString):
        return geo.MultiLineString(tuple(
            geo.LineString(tup(next(chunks))) for _ in g.lines))
    if isinstance(g, geo.Polygon):
        shell = tup(next(chunks))
        holes = tuple(tup(next(chunks)) for _ in g.holes)
        return geo.Polygon(shell, holes)
    if isinstance(g, geo.MultiPolygon):
        polys = []
        for p in g.polygons:
            shell = tup(next(chunks))
            holes = tuple(tup(next(chunks)) for _ in p.holes)
            polys.append(geo.Polygon(shell, holes))
        return geo.MultiPolygon(tuple(polys))
    raise ValueError(f"cannot reproject geometry type {type(g).__name__}")


def reproject_wkt_array(wkts, fn: Callable) -> np.ndarray:
    """Transform a whole object-array of WKT strings with ONE vectorized
    transform call over the concatenation of every vertex (replaces the
    per-geometry Python loop the round-4 advisor flagged). Null / empty
    entries pass through unchanged."""
    from geomesa_tpu.utils.geometry import parse_wkt

    wkts = np.asarray(wkts, dtype=object)
    geoms: list = [None] * len(wkts)
    arrays: list = []
    spans: list = [None] * len(wkts)
    for i, w in enumerate(wkts):
        if w is None or (isinstance(w, float) and math.isnan(w)) or str(w) == "":
            continue
        g = parse_wkt(str(w))
        geoms[i] = g
        start = len(arrays)
        _collect_arrays(g, arrays)
        spans[i] = (start, len(arrays))
    if not arrays:
        return wkts.copy()
    lens = [a.shape[0] for a in arrays]
    flat = np.concatenate(arrays, axis=0)
    tx, ty = fn(flat[:, 0], flat[:, 1])
    flat = np.stack([np.asarray(tx, np.float64),
                     np.asarray(ty, np.float64)], axis=1)
    split = np.split(flat, np.cumsum(lens)[:-1]) if len(lens) > 1 else [flat]
    out = np.empty(len(wkts), dtype=object)
    for i, w in enumerate(wkts):
        if spans[i] is None:
            out[i] = w
        else:
            lo, hi = spans[i]
            out[i] = _rebuild_from(geoms[i], iter(split[lo:hi])).wkt()
    return out
