"""SLO burn-rate monitor over the trace stage histograms
(docs/OBSERVABILITY.md).

An operator declares per-op p99 latency targets — ``geomesa.slo.<op>.p99.ms``
(thread-local override or ``GEOMESA_SLO_<OP>_P99_MS``), where ``<op>`` is a
root-span name the tracing layer already histograms (``count``,
``density``, ``density_curve``, ``query``, ...). This module turns those
targets plus the existing ``trace.<op>`` histograms into the standard
multi-window burn-rate signal:

* **bad fraction** over a window = observations above the target bucket /
  total observations in that window (windowed by differencing timestamped
  histogram snapshots — the histograms themselves are cumulative);
* **burn rate** = bad fraction / error budget, where a p99 target implies
  a 1% budget — burn 1.0 means "exactly on budget", 14.4 means "a month's
  budget gone in ~2 days";
* **dual windows**: the fast window (``geomesa.slo.window.fast.s``, 5 min)
  pages — /healthz reports ``degraded`` while it burns past
  ``geomesa.slo.burn.threshold`` — and the slow window
  (``geomesa.slo.window.slow.s``, 1 h) confirms a sustained burn vs a
  blip. Both ride the ``slo.burn.<op>`` gauges and /debug/devices.

Observations land in the histograms at the *bucket* granularity the
exposition already commits to, so "above target" snaps the target to the
smallest bucket bound >= target — the same answer a PromQL burn query
over the exported buckets would compute.
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import deque
from typing import Any, Dict, Optional

from geomesa_tpu import config, metrics

#: error budget implied by a p99 target: 1% of requests may exceed it
P99_BUDGET = 0.01

#: breaker state -> slo.breaker.<name> gauge value
_BREAKER_GAUGE = {"open": 1.0, "half-open": 0.5, "closed": 0.0}

_breaker_gauged: set = set()
_breaker_lock = threading.Lock()


def sync_breaker_gauges() -> Dict[str, str]:
    """Mirror every named circuit breaker onto the SLO alert surface as a
    ``slo.breaker.<name>`` gauge (1 open, 0.5 half-open, 0 closed), so a
    breaker-open transition pages through the SAME scrape the burn gauges
    ride — an operator watching ``slo.*`` sees "the sidecar breaker is
    open" next to "density is burning budget" instead of in a separate
    surface (RESILIENCE.md follow-up). Returns the current state map.
    Gauges are live callables: registration happens once per breaker
    name, every scrape reads the breaker's state at scrape time."""
    from geomesa_tpu import resilience

    states = resilience.breaker_states()
    for name in states:
        gname = f"{metrics.SLO_BREAKER_PREFIX}.{name}"
        if gname in _breaker_gauged:
            continue
        with _breaker_lock:
            if gname in _breaker_gauged:
                continue
            metrics.registry().gauge(
                gname,
                lambda n=name: _BREAKER_GAUGE.get(
                    resilience.breaker_states().get(n, "closed"), 0.0
                ),
                replace=True,
            )
            _breaker_gauged.add(gname)
    return states

#: injectable clock (tests drive window arithmetic deterministically)
_clock = time.monotonic


def _over_count(snap: Dict[str, Any], target_ms: float) -> "tuple":
    """(total, over-target) observation counts from one histogram
    SNAPSHOT (``Histogram.snapshot()`` shape — the fleet monitor feeds
    bucket-wise-merged snapshots through the same arithmetic), with the
    target snapped UP to a bucket bound (bucket granularity is all the
    fixed-bucket histogram can answer; observations in the target's own
    bucket count as within-SLO, matching the cumulative le= semantics).
    Accepts a live ``Histogram`` too and snapshots it."""
    if not isinstance(snap, dict):
        snap = snap.snapshot()
    total = snap["count"]
    target_s = target_ms / 1e3
    buckets = snap["buckets"]
    i = bisect.bisect_left(buckets, target_s)
    good = sum(snap["counts"][: i + 1])  # le= the snapped bound (+Inf ok)
    return total, max(total - good, 0)


class SloMonitor:
    """Timestamped snapshot ring per op; burn rates by differencing the
    newest snapshot against the oldest one inside each window.

    ``source`` generalizes WHERE the cumulative histograms come from: a
    callable ``op -> Histogram.snapshot()-shaped dict (or None)``. The
    default reads the process registry's ``trace.<op>`` histograms; the
    fleet observability plane (fleet/obs.py) passes a source over the
    MERGED per-replica histograms, so fleet burn runs the exact same
    dual-window differencing. ``gauge_prefix`` keeps the two monitors'
    gauges distinct in one process (``slo.burn.<op>`` vs
    ``slo.burn.fleet.<op>``)."""

    def __init__(self, source=None, gauge_prefix: Optional[str] = None):
        self._lock = threading.Lock()
        #: op -> deque[(t, total, over)]
        self._snaps: Dict[str, "deque"] = {}
        self._last_eval = 0.0
        self._source = source or (
            lambda op: metrics.registry().histogram(f"trace.{op}").snapshot()
        )
        self._prefix = gauge_prefix or metrics.SLO_BURN_PREFIX

    # -- sampling ----------------------------------------------------------
    def evaluate(self, force: bool = False) -> None:
        """Take one snapshot per targeted op (rate-limited to 1/s unless
        forced — gauges and /healthz may poll much faster)."""
        now = _clock()
        sync_breaker_gauges()  # breaker transitions ride the same surface
        targets = config.slo_targets()
        with self._lock:
            # a target with no snapshot yet (just declared) bypasses the
            # rate limit: its first poll must see a burn, not a blank
            fresh = any(op not in self._snaps for op in targets)
            if not force and not fresh and now - self._last_eval < 1.0:
                return
            self._last_eval = now
        slow_s = config.SLO_WINDOW_SLOW_S.to_float() or 3600.0
        for op, target_ms in targets.items():
            snap = self._source(op)
            if snap is None:
                continue
            total, over = _over_count(snap, target_ms)
            with self._lock:
                dq = self._snaps.setdefault(op, deque())
                dq.append((now, total, over))
                # retain one snapshot beyond the slow window so the oldest
                # in-window diff always has a baseline
                while len(dq) > 2 and dq[1][0] < now - slow_s:
                    dq.popleft()
            self._ensure_gauge(op)

    _gauged: set = set()

    def _ensure_gauge(self, op: str) -> None:
        name = f"{self._prefix}.{op}"
        if name in self._gauged:
            return
        with self._lock:
            if name in self._gauged:
                return
            fast_s = config.SLO_WINDOW_FAST_S.to_float() or 300.0
            metrics.registry().gauge(
                name, lambda op=op, w=fast_s: self.burn(op, w),
                replace=True,
            )
            self._gauged.add(name)

    # -- burn arithmetic ---------------------------------------------------
    def burn(self, op: str, window_s: float) -> float:
        """Burn rate for ``op`` over the trailing ``window_s``: bad
        fraction of the window's observations over the 1% p99 budget.
        0.0 with no observations (an idle service burns nothing)."""
        now = _clock()
        with self._lock:
            dq = self._snaps.get(op)
            if not dq:
                return 0.0
            newest = dq[-1]
            base = None
            for t, total, over in dq:
                if t >= now - window_s:
                    break
                base = (t, total, over)
            if base is None:
                # whole history inside the window: diff from zero
                base = (0.0, 0, 0)
        d_total = newest[1] - base[1]
        d_over = newest[2] - base[2]
        if d_total <= 0:
            return 0.0
        return (d_over / d_total) / P99_BUDGET

    def status(self) -> Dict[str, Any]:
        """Per-op burn summary for /healthz and /debug/devices:
        ``{op: {target_ms, fast_burn, slow_burn, hot}}``. ``hot`` = the
        fast window burns past geomesa.slo.burn.threshold (the /healthz
        degradation trigger)."""
        self.evaluate()
        fast_s = config.SLO_WINDOW_FAST_S.to_float() or 300.0
        slow_s = config.SLO_WINDOW_SLOW_S.to_float() or 3600.0
        thresh = config.SLO_BURN_THRESHOLD.to_float() or 14.4
        out: Dict[str, Any] = {}
        for op, target_ms in config.slo_targets().items():
            fast = self.burn(op, fast_s)
            slow = self.burn(op, slow_s)
            out[op] = {
                "target_ms": target_ms,
                "fast_burn": round(fast, 3),
                "slow_burn": round(slow, 3),
                "hot": fast > thresh,
            }
        return out

    def hot_ops(self) -> Dict[str, Any]:
        return {op: s for op, s in self.status().items() if s["hot"]}


_monitor: Optional[SloMonitor] = None
_lock = threading.Lock()


def monitor() -> SloMonitor:
    global _monitor
    m = _monitor
    if m is None:
        with _lock:
            m = _monitor
            if m is None:
                m = _monitor = SloMonitor()
    return m


def reset() -> None:
    """Drop monitor state (test isolation)."""
    global _monitor
    with _lock:
        _monitor = None
    SloMonitor._gauged = set()
    with _breaker_lock:
        _breaker_gauged.clear()
